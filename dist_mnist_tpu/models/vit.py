"""ViT-Tiny for CIFAR-10 (BASELINE.md config 5 — the attention-path stretch
config for pod slices).

Standard ViT-Ti geometry (dim 192, depth 12, heads 3), 4x4 patches so a
32x32 image is a 64-token sequence, learned position embeddings, CLS token,
pre-LN blocks. The attention inner loop is swappable: the default XLA
einsum path (ops/nn.dot_product_attention), the Pallas flash kernel
(ops/pallas/flash_attention.py), ring attention over the `seq` mesh axis
(parallel/ring_attention.py, "ring_flash" = flash local blocks), or
Ulysses all-to-all sequence parallelism (parallel/ulysses.py; needs
heads % seq == 0; "ulysses_flash" = flash local full-S attention) —
selected by `attention_impl`; `attention_block_k` streams K/V tiles
within the kernel paths.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from dist_mnist_tpu.ops import nn


def convert_block_layout(params: dict) -> dict:
    """Convert a ViT param tree between the unrolled layout
    (``block0..blockN-1``) and the scanned layout (stacked ``blocks``) —
    whichever it has, you get the other. The layouts are numerically
    interchangeable, so a checkpoint written before flipping
    ``scan_blocks`` restores after a pass through this converter."""
    import re

    if "blocks" in params:
        out = {k: v for k, v in params.items() if k != "blocks"}
        stacked = params["blocks"]
        depth = jax.tree.leaves(stacked)[0].shape[0]
        for i in range(depth):
            out[f"block{i}"] = jax.tree.map(lambda a, i=i: a[i], stacked)
        return out
    block_keys = sorted(
        (k for k in params if re.fullmatch(r"block\d+", k)),
        key=lambda k: int(k[5:]),
    )
    if not block_keys:
        raise ValueError("no block0.. or 'blocks' entry to convert")
    from dist_mnist_tpu.parallel.pipeline import stack_stage_params

    out = {k: v for k, v in params.items() if k not in block_keys}
    out["blocks"] = stack_stage_params([params[k] for k in block_keys])
    return out


@dataclasses.dataclass(frozen=True)
class ViTTiny:
    num_classes: int = 10
    patch: int = 4
    dim: int = 192
    depth: int = 12
    heads: int = 3
    mlp_ratio: int = 4
    dropout_rate: float = 0.1
    compute_dtype: jnp.dtype = jnp.bfloat16
    # "xla" | "flash" | "ring" | "ring_flash" | "ulysses" | "ulysses_flash"
    attention_impl: str = "xla"
    attention_block_k: int | None = None  # kernel impls (flash,
    # ring_flash, ulysses_flash): stream
    # K/V through VMEM in tiles of this many keys (online softmax,
    # ops/pallas/flash_attention block_k) instead of holding the full
    # (local) key axis resident. None = full-K (proven small-S path).
    pool: str = "cls"  # "cls" | "mean" (mean keeps token count a power of
    # two — required when the sequence dim is sharded, e.g. ring attention)
    mlp_impl: str = "dense"  # "dense" | "moe" (switch-routed expert FFN,
    # expert-parallel over the `model` axis when it matches n_experts —
    # parallel/moe.py)
    n_experts: int = 4
    moe_capacity_factor: float = 1.25
    moe_top_k: int = 1  # 1 = Switch routing; >=2 = GShard-style top-k
    moe_aux_weight: float = 1e-2  # load-balance loss weight (Switch form);
    # the train step adds state["moe_aux"] to the loss
    scan_blocks: bool = False  # compile ONE block and lax.scan over stacked
    # per-layer params instead of unrolling `depth` copies of the program —
    # ~depth x less HLO to build/compile, identical numerics. The required
    # idiom for deep stacks under XLA; off by default only so per-block
    # param paths (block0/...) stay addressable by older sharding rules.
    block_pipeline: int = 0  # N>0: shard the block stack into N GPipe
    # stages over the `pipe` mesh axis (parallel/pipeline.py). Needs
    # scan_blocks (stacked layout), depth % N == 0, dense MLP; dropout is
    # fine (per-(microbatch, stage) keys). Engages only when the ambient
    # mesh's pipe axis equals N; on any other mesh the same model falls
    # back to the plain scan — one model, any topology.
    pipeline_microbatches: int = 8  # GPipe M; bubble = (N-1)/(M+N-1)
    pipeline_skip_bubble: bool = False  # lax.cond the stage fn so
    # fill/drain ticks skip its compute entirely (identical outputs;
    # parallel/pipeline.py skip_bubble). Off until measured on multi-chip.
    pipeline_circular: int = 0  # v>1: circular/interleaved schedule — each
    # pipe rank holds v non-adjacent chunks of depth/(N*v) blocks; the
    # fill/drain bubble shrinks from (N-1) stage-times to (N-1) chunk-times
    # (parallel/pipeline.py). Needs depth % (N*v) == 0 and M % N == 0.

    def flops_per_example(self, sample_shape) -> float:
        """Analytic FORWARD FLOPs per example (matmul MACs x2; LN/softmax/
        elementwise ignored). ESSENTIAL here: with `scan_blocks=True` the
        depth-layer stack runs under `lax.scan`, and XLA's cost analysis
        counts a scan body ONCE — the compiled-program FLOPs figure
        understates the transformer stack by ~depth x (measured: 13.8G
        reported vs ~46G actual fwd for the vit_tiny_cifar ladder point).
        MFU must therefore use this analytic count (utils/flops.py)."""
        h, w, c = (int(d) for d in sample_shape[1:])
        s = (h // self.patch) * (w // self.patch)
        if self.pool == "cls":
            s += 1
        d = self.dim
        patch_embed = (s - (1 if self.pool == "cls" else 0)) * d \
            * (self.patch * self.patch * c) * 2
        per_block = (
            s * 3 * d * d * 2          # qkv projection
            + 2 * s * s * d * 2        # scores (QK^T) + apply (A*V)
            + s * d * d * 2            # output projection
            + 2 * s * d * (d * self.mlp_ratio) * 2  # mlp in + out
        )
        head = d * self.num_classes * 2
        return float(patch_embed + self.depth * per_block + head)

    def init(self, rng, sample_input):
        h, w, c = (int(d) for d in sample_input.shape[1:])
        n_tokens = (h // self.patch) * (w // self.patch)
        if self.pool == "cls":
            n_tokens += 1
        keys = jax.random.split(rng, 4 + self.depth)
        d = self.dim
        params: dict = {
            "patch": nn.init_conv(keys[0], self.patch, self.patch,
                                  c, d, init=nn.xavier_uniform),
            "pos": 0.02 * jax.random.normal(keys[1], (1, n_tokens, d)),
            "head": nn.init_dense(keys[2], d, self.num_classes,
                                  init=nn.xavier_uniform),
            "final_ln": nn.init_layer_norm(d),
        }
        if self.pool == "cls":
            params["cls"] = jnp.zeros((1, 1, d))
        blocks = []
        for i in range(self.depth):
            k1, k2, k3 = jax.random.split(keys[3 + i], 3)
            block = {
                "ln1": nn.init_layer_norm(d),
                "attn": nn.init_attention(k1, d, self.heads),
                "ln2": nn.init_layer_norm(d),
            }
            if self.mlp_impl == "moe":
                from dist_mnist_tpu.parallel.moe import init_moe

                block["moe"] = init_moe(k2, d, d * self.mlp_ratio,
                                        self.n_experts)
            else:
                block["mlp_in"] = nn.init_dense(k2, d, d * self.mlp_ratio,
                                                init=nn.xavier_uniform)
                block["mlp_out"] = nn.init_dense(k3, d * self.mlp_ratio, d,
                                                 init=nn.xavier_uniform)
            blocks.append(block)
        if self.scan_blocks:
            # one stacked pytree ([depth, ...] leaves) scanned by apply;
            # per-block init is identical to the unrolled layout, so the
            # two layouts are numerically interchangeable
            # (convert_block_layout moves checkpoints between them)
            from dist_mnist_tpu.parallel.pipeline import stack_stage_params

            params["blocks"] = stack_stage_params(blocks)
        else:
            for i, block in enumerate(blocks):
                params[f"block{i}"] = block
        # state carries the load-balance aux loss so the train step can add
        # it to the objective, plus routing-health stats surfaced as step
        # metrics via the `_metric` contract (structure must match apply's
        # output)
        state = (
            {"moe_aux": jnp.zeros(()),
             "moe_drop_fraction_metric": jnp.zeros(()),
             "moe_expert_load_metric": jnp.zeros((self.n_experts,)),
             "moe_ep_engaged_metric": jnp.zeros(())}
            if self.mlp_impl == "moe" else {}
        )
        return params, state

    def _attention(self, p, x, mask=None):
        if self.attention_impl == "xla":
            return nn.multi_head_attention(p, x, self.heads, mask=mask)
        if mask is not None and self.attention_impl != "flash":
            # the ring/ulysses kernel impls take no mask argument;
            # serve/zoo.py degrades them to the native-length-only bucket
            raise ValueError(
                f"attention_impl {self.attention_impl!r} does not support a "
                "token mask; serve at native length or use 'xla'/'flash'"
            )
        b, s, d = x.shape
        h = self.heads
        qkv = nn.dense(p["qkv"], x).reshape(b, s, 3, h, d // h)
        q, k, v = jnp.moveaxis(qkv, 2, 0)
        if self.attention_impl == "flash":
            # mesh-adaptive: per-device local heads under a model axis
            # (a bare pallas_call would replicate — parallel/flash.py)
            from dist_mnist_tpu.parallel.flash import (
                flash_attention_sharded,
                masked_flash_attention_sharded,
            )

            if mask is not None:
                # zoo masks are key prefixes (real tokens first, then
                # padding), so the variable-length kernel takes per-row
                # LENGTHS and its grid skips fully-padded key blocks —
                # sub-native buckets stop paying full-bucket math
                lengths = jnp.sum(mask.astype(jnp.int32), axis=-1)
                out = masked_flash_attention_sharded(
                    q, k, v, lengths, block_k=self.attention_block_k)
            else:
                out = flash_attention_sharded(q, k, v,
                                              block_k=self.attention_block_k)
        elif self.attention_impl in ("ring", "ring_flash"):
            from dist_mnist_tpu.parallel.ring_attention import ring_attention

            # ring_flash = sequence-sharded ring whose LOCAL block runs the
            # Pallas kernel (VMEM score tiles) instead of an HBM einsum —
            # the long-context composition (flash_attention.py docstring)
            out = ring_attention(
                q, k, v,
                impl="flash" if self.attention_impl == "ring_flash"
                else "xla",
                block_k=self.attention_block_k)
        elif self.attention_impl in ("ulysses", "ulysses_flash"):
            from dist_mnist_tpu.parallel.ulysses import ulysses_attention

            # ulysses_flash = all-to-all head reshard whose full-S LOCAL
            # attention runs the Pallas kernel — the XLA path would
            # materialize [B, H/n, S, S] in HBM (parallel/ulysses.py)
            out = ulysses_attention(
                q, k, v,
                impl="flash" if self.attention_impl == "ulysses_flash"
                else "xla",
                block_k=self.attention_block_k)
        else:
            raise ValueError(
                f"unknown attention_impl {self.attention_impl!r}; "
                "use 'xla' | 'flash' | 'ring' | 'ring_flash' | 'ulysses' "
                "| 'ulysses_flash'"
            )
        if self.attention_impl == "flash":
            # same save_attn remat tag the other impls get inside
            # ops/nn.dot_product_attention (ring/ulysses route through it;
            # tagging them here too would double the per-block save)
            from jax.ad_checkpoint import checkpoint_name

            out = checkpoint_name(out, "attn_out")
        return nn.dense(p["out"], out.reshape(b, s, d))

    def _moe_zero_stats(self):
        return {"drop_fraction": jnp.zeros(()),
                "expert_load": jnp.zeros((self.n_experts,)),
                "ep_engaged": jnp.zeros(())}

    def _block(self, p, x, layer_rng, use_dropout, mask=None):
        """One pre-LN transformer block; returns (x, moe_aux, moe_stats)."""
        y = nn.layer_norm(p["ln1"], x)
        x = x + self._attention(p["attn"], y, mask=mask)
        y = nn.layer_norm(p["ln2"], x)
        aux = jnp.zeros((), jnp.float32)
        stats = self._moe_zero_stats() if self.mlp_impl == "moe" else None
        if self.mlp_impl == "moe":
            from dist_mnist_tpu.parallel.moe import moe_ffn_adaptive

            bb, ss, dd = y.shape
            y, aux, stats = moe_ffn_adaptive(
                p["moe"], y.reshape(bb * ss, dd),
                capacity_factor=self.moe_capacity_factor,
                top_k=self.moe_top_k,
            )
            y = y.reshape(bb, ss, dd)
        else:
            y = nn.gelu(nn.dense(p["mlp_in"], y))
        if use_dropout:
            y = nn.dropout(layer_rng, y, self.dropout_rate, train=True)
        x = x + (y if self.mlp_impl == "moe" else nn.dense(p["mlp_out"], y))
        return x, aux, stats

    def _pipe_axis_matches(self) -> bool:
        """True only when the ambient mesh's pipe axis equals the
        configured stage count; a >1-but-mismatched axis falls back to the
        plain scan (one model, any topology), loudly."""
        import logging

        from dist_mnist_tpu.cluster.mesh import PIPE_AXIS, ambient_mesh

        mesh = ambient_mesh()
        shape = getattr(mesh, "shape", {}) if mesh is not None else {}
        axis = shape.get(PIPE_AXIS, 1)
        # axis > 1 required: a singleton/absent pipe axis always means the
        # plain scan, even for block_pipeline=1 (there is nothing to pipe)
        if axis > 1 and axis == self.block_pipeline:
            return True
        if axis > 1:
            logging.getLogger(__name__).warning(
                "block_pipeline=%d != pipe axis %d — running the plain "
                "scanned stack (no pipeline); size the pipe axis to the "
                "stage count for pipeline parallelism",
                self.block_pipeline, axis,
            )
        return False

    def _pipelined_blocks(self, params, x, use_dropout, rng=None):
        """GPipe the block stack over the `pipe` mesh axis: stage s runs
        blocks [s*depth/N, (s+1)*depth/N) as an inner scan; activations
        flow stage->stage via ppermute (parallel/pipeline.py).

        Dropout: the schedule derives a key per (data shard, microbatch,
        global stage) (pipeline_apply's rng threading), and each block
        folds its local index in — masks are i.i.d. per (shard,
        microbatch, layer), so training is statistically equivalent to
        the scanned path's per-layer keys (the exact mask STREAM differs:
        the scanned path draws one full-batch mask per layer)."""
        from dist_mnist_tpu.cluster.mesh import PIPE_AXIS, ambient_mesh
        from dist_mnist_tpu.parallel.pipeline import pipeline_apply

        mesh = ambient_mesh()
        n = mesh.shape[PIPE_AXIS]
        v = max(1, self.pipeline_circular)
        if not self.scan_blocks or self.depth % (n * v):
            raise ValueError(
                "block_pipeline needs scan_blocks=True and depth % "
                "(stages * circular_chunks) == 0"
            )
        if self.mlp_impl == "moe":
            raise ValueError("block_pipeline supports dense MLP blocks only")
        per_stage = self.depth // (n * v)
        stage_params = jax.tree.map(
            lambda a: a.reshape((n * v, per_stage) + a.shape[1:]),
            params["blocks"],
        )

        if use_dropout:
            def stage_fn(p, xx, key):
                def body(carry, xs):
                    pp, i = xs
                    out, _, _ = self._block(
                        pp, carry, jax.random.fold_in(key, i), True)
                    return out, None

                out, _ = jax.lax.scan(
                    body, xx, (p, jnp.arange(per_stage)))
                return out
        else:
            def stage_fn(p, xx):
                def body(carry, pp):
                    out, _, _ = self._block(pp, carry, None, False)
                    return out, None

                out, _ = jax.lax.scan(body, xx, p)
                return out

        # Pipeline output is independent of M, so adapt M down to the
        # largest count this batch supports (B % M == 0, per-microbatch rows
        # divisible by the data axis, and — circular — M % stages == 0) —
        # e.g. eval batches differ from the train batch and must not have
        # to know the model's M
        from dist_mnist_tpu.cluster.mesh import DATA_AXIS

        b = x.shape[0]
        data_axis = mesh.shape.get(DATA_AXIS, 1)
        m = min(self.pipeline_microbatches, b)
        while m > 1 and (b % m or (b // m) % data_axis
                         or (v > 1 and m % n)):
            m -= 1
        if v > 1 and m % n:
            raise ValueError(
                f"pipeline_circular={v} needs a microbatch count divisible "
                f"by the {n}-way pipe axis; none fits batch {b}"
            )
        return pipeline_apply(stage_fn, stage_params, x, m, mesh,
                              circular_chunks=v,
                              rng=rng if use_dropout else None,
                              skip_bubble=self.pipeline_skip_bubble)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        """`mask` [B, patch_tokens] marks real patch tokens for inputs whose
        HEIGHT was right-padded below the init-time native shape (variable-
        length serving, serve/zoo.py): padded keys are masked out of every
        attention softmax and out of the pool, and `pos` is sliced to the
        actual token count — so a short input's logits equal running it at
        its own native bucket. `mask=None` (every training/eval call)
        compiles the exact historical program. Requires attention_impl
        "xla" (the -1e30 pre-softmax einsum) or "flash" (the
        variable-length Pallas kernel — padded key BLOCKS are skipped by
        the grid, so attention FLOPs scale with real length; see
        ops/pallas/flash_attention.masked_flash_attention) and no block
        pipeline; MoE note: padded tokens still occupy
        router capacity slots (shape-stable executables), which shows up in
        `moe_drop_fraction_metric` rather than corrupting real tokens."""
        x = x.astype(self.compute_dtype)
        x = nn.conv2d(params["patch"], x, stride=self.patch, padding="VALID")
        b, ph, pw, d = x.shape
        x = x.reshape(b, ph * pw, d)
        tok_mask = None
        if mask is not None:
            if mask.shape != (b, ph * pw):
                raise ValueError(
                    f"mask shape {mask.shape} != (batch, patch_tokens) "
                    f"{(b, ph * pw)}"
                )
            tok_mask = mask.astype(bool)
        if self.pool == "cls":
            cls = jnp.broadcast_to(params["cls"].astype(x.dtype), (b, 1, d))
            x = jnp.concatenate([cls, x], axis=1)
            if tok_mask is not None:  # the CLS token is always real
                tok_mask = jnp.concatenate(
                    [jnp.ones((b, 1), bool), tok_mask], axis=1)
        # slice, not broadcast: a sub-native token count (masked serving)
        # uses the leading rows of the learned table — row-major patch
        # order means the first k*pw entries ARE the top k patch-rows'
        # positions. At native length the slice is the whole table.
        x = x + params["pos"][:, : x.shape[1]].astype(x.dtype)
        if tok_mask is not None and self.block_pipeline:
            raise ValueError("mask is not supported with block_pipeline")
        use_dropout = train and rng is not None and self.dropout_rate > 0
        rngs = (jax.random.split(rng, self.depth) if use_dropout
                else jnp.zeros((self.depth,)))  # scannable dummy
        is_moe = self.mlp_impl == "moe"
        zero_aux = jnp.zeros((), jnp.float32)
        zero_stats = self._moe_zero_stats() if is_moe else None
        if self.block_pipeline and self._pipe_axis_matches():
            x = self._pipelined_blocks(params, x, use_dropout, rng)
            aux_total, stats_total = zero_aux, zero_stats
        elif self.scan_blocks:
            def body(carry, xs):
                x, aux_total, stats_total = carry
                p, layer_rng = xs
                x, aux, stats = self._block(p, x, layer_rng, use_dropout,
                                            mask=tok_mask)
                if is_moe:
                    stats_total = jax.tree.map(jnp.add, stats_total, stats)
                return (x, aux_total + aux, stats_total), None

            (x, aux_total, stats_total), _ = jax.lax.scan(
                body, (x, zero_aux, zero_stats),
                (params["blocks"], rngs),
            )
        else:
            aux_total, stats_total = zero_aux, zero_stats
            for i in range(self.depth):
                x, aux, stats = self._block(params[f"block{i}"], x, rngs[i],
                                            use_dropout, mask=tok_mask)
                aux_total = aux_total + aux
                if is_moe:
                    stats_total = jax.tree.map(jnp.add, stats_total, stats)
        x = nn.layer_norm(params["final_ln"], x)
        if self.pool == "cls":
            pooled = x[:, 0]
        elif tok_mask is None:
            pooled = jnp.mean(x, axis=1)
        else:  # masked mean: padded rows carry garbage, weight them 0
            m = tok_mask.astype(x.dtype)[..., None]
            pooled = jnp.sum(x * m, axis=1) / jnp.sum(m, axis=1)
        logits = nn.dense(params["head"], pooled)
        if is_moe:
            # stats are depth-means; `_metric` keys surface as step outputs
            # (train/step.py) and flow into SummaryHook histograms
            state = {
                "moe_aux": self.moe_aux_weight * aux_total / self.depth,
                "moe_drop_fraction_metric": stats_total["drop_fraction"]
                / self.depth,
                "moe_expert_load_metric": stats_total["expert_load"]
                / self.depth,
                # 1.0 = every block dispatched over the expert axis; 0.0 =
                # dense fallback (mesh's model axis != n_experts) — makes a
                # not-actually-expert-parallel run visible in step outputs,
                # not just a once-per-trace Python warning
                "moe_ep_engaged_metric": stats_total["ep_engaged"]
                / self.depth,
            }
        return logits.astype(jnp.float32), state
