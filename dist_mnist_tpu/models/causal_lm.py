"""Tiny causal autoregressive transformer — the decode-serving workload.

Everything else in `models/` classifies a whole input in one forward;
this model emits one token at a time, which is what the decode serving
subsystem (serve/decode.py) exists to schedule. Geometry mirrors
`models/vit.py` (pre-LN blocks, learned positions, `ops/nn` attention
params) with two differences forced by autoregression:

- **Causal attention is implemented here**, not via
  `nn.dot_product_attention`: that kernel's mask is key-only ``[B, S_k]``
  (variable-length serving) and cannot express a per-query causal
  frontier. The math keeps the same accumulation contract (f32 scores
  and softmax, -1e30 masking) so numerics match the rest of the repo.
- **Two forward surfaces over one set of weights**: `apply`/`prefill`
  run the whole sequence with a triangular mask (and prefill writes
  every position's K/V into a cache), while `decode_step` runs ONE new
  token per slot against the cache, updating it in place with
  `lax.dynamic_update_slice`. Both routes share `_attend`, so an
  incremental decode reproduces the full-sequence forward bit-for-bit
  at every position (tests/test_serve_decode.py holds this).

Tensor parallelism follows `parallel/flash.py`: when the ambient mesh
has a model axis >1 and it divides `heads`, the attention kernel — cache
write included — runs under `compat_shard_map` with heads sharded, so
each device owns its head slice of the KV cache and updates it locally
(no collectives: attention is head-parallel, the out-projection happens
on the gathered activations outside the shard_map).

Cache layouts (``cache_layout``, PR 20): ``"dense"`` is the original
``[depth, slot, max_seq, heads, head_dim]`` stripe-per-slot buffer and
stays byte-for-byte the pre-paging code path. ``"paged"`` replaces it
with a page POOL ``[depth, pages, page_tokens, heads, head_dim]`` plus a
caller-owned page table ``[rows, n]`` (int32 pool indices; row r's
tokens ``[j*T, (j+1)*T)`` live in page ``table[r, j]``): a slot pins
only the pages its live prefix needs, and the decode step may be traced
at any TRUNCATED table width n <= max_seq/T — attention math then runs
over ``n*T`` keys instead of max_seq.

Two parity regimes, deliberately split:

- **Float pages at the FULL table width are bitwise-equal to dense**:
  the gather reconstructs the exact dense ``[rows, max_seq, H, D]``
  stripe, then the same `_attend`/mask runs on it — identical logits,
  bit for bit (tests/test_serve_paged.py). Truncation is NOT bitwise
  for float: masked tails contribute exact +0.0 to every softmax sum,
  but a shorter key axis re-tiles XLA's reduction of the NONZERO terms
  (~1 ulp, same reassociation effect the `_attend` docstring documents
  for the M dim) — so the serve engine decodes float pages at full
  width, keeping the memory win and the bitwise twin.
- **int8 pages (``kv_quant="int8"``) decode at truncated page buckets**
  and carry the compute win: pools are `ops/quant.QuantizedArray` nodes
  (int8 + per-token-per-head f32 scales, `quantize_kv`), quantized at
  write inside the step, dequantized at read — fused in-kernel on TPU
  (ops/pallas/paged_attention.py), einsum-tiled `_attend_fast` via XLA
  elsewhere. No bitwise contract to preserve means no broadcast-sum
  tax either; correctness is the >=0.99 token-agreement gate
  bench.py --serve --decode holds.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from dist_mnist_tpu.cluster.mesh import (
    MODEL_AXIS,
    ambient_mesh,
    compat_shard_map,
)
from dist_mnist_tpu.ops import nn
from dist_mnist_tpu.ops.quant import QuantizedArray, quantize_kv


def _attend(q, k, v, mask):
    """Masked multi-head attention: q ``[B,Sq,H,D]`` against k/v
    ``[B,Sk,H,D]`` with a boolean mask ``[B,Sq,Sk]`` (True = attend).
    f32 scores and softmax regardless of the activation dtype — the same
    accumulation contract as `nn.dot_product_attention`.

    Both contractions are broadcast-multiply + ``jnp.sum`` rather than
    einsums ON PURPOSE: XLA lowers a dot_general's accumulation order
    per gemm tiling, which varies with the query-length (M) dimension —
    measured on CPU, ``weights @ v`` at Sq=1 rounds differently from
    Sq=S by ~1 ulp. A single-axis reduce is per-output-element and
    independent of the other dims, which is what lets an incremental
    decode (Sq=1) bit-match the full-sequence forward at every position
    — the correctness contract tests/test_serve_decode.py pins. The
    O(Sq*Sk*H*D) broadcast is fine at this model's serving scale."""
    dh = q.shape[-1]
    # [B,Sq,Sk,H] <- sum_d q[B,Sq,1,H,D] * k[B,1,Sk,H,D]
    scores = jnp.sum(
        q.astype(jnp.float32)[:, :, None] * k.astype(jnp.float32)[:, None],
        axis=-1)
    scores = scores.transpose(0, 3, 1, 2)  # [B,H,Sq,Sk]
    scores = scores * (1.0 / jnp.sqrt(jnp.float32(dh)))
    scores = jnp.where(mask[:, None, :, :], scores, -1e30)
    weights = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    # [B,H,Sq,D] <- sum_k w[B,H,Sq,Sk,1] * v[B,H,1,Sk,D]
    out = jnp.sum(
        weights[..., None] * jnp.moveaxis(v, 1, 2)[:, :, None], axis=3)
    return out.transpose(0, 2, 1, 3)  # [B,Sq,H,D]


def _write_step(cache, new, pos):
    """Write one token's K (or V) per slot: ``cache`` [R,S,H,D], ``new``
    [R,1,H,D], ``pos`` [R] — row r gets its token at ``pos[r]``."""
    return jax.vmap(
        lambda c, n, p: lax.dynamic_update_slice(c, n, (p, 0, 0))
    )(cache, new, pos)


def _decode_attn_update(q, k_new, v_new, k_cache, v_cache, pos):
    """One cached-attention step (runs per head-shard under shard_map):
    write the new K/V at each slot's position, then attend the single
    query against keys ``<= pos`` — write-before-attend is what lets a
    freshly admitted slot overwrite stale prefill padding before any
    mask ever admits it."""
    k_cache = _write_step(k_cache, k_new, pos)
    v_cache = _write_step(v_cache, v_new, pos)
    max_seq = k_cache.shape[1]
    mask = jnp.arange(max_seq)[None, None, :] <= pos[:, None, None]
    return _attend(q, k_cache, v_cache, mask), k_cache, v_cache


def _decode_attn_update_flash(q, k_new, v_new, k_cache, v_cache, pos):
    """`_decode_attn_update` with the attention itself on the
    variable-length Pallas flash kernel: the decode mask (``arange <=
    pos``) is EXACTLY a key-prefix, so it becomes per-slot lengths
    ``pos + 1`` and the kernel's grid skips cache blocks past each
    slot's frontier — short sequences in a long `max_seq` cache stop
    paying full-cache attention math. Opt-in (`attention_impl="flash"`):
    the kernel's dot_general accumulation differs from `_attend`'s
    broadcast-sum by ~1 ulp, so it relaxes the bit-exact decode==forward
    contract to a tolerance (see tests/test_kernels.py)."""
    from dist_mnist_tpu.ops.pallas.flash_attention import (
        masked_flash_attention,
    )

    k_cache = _write_step(k_cache, k_new, pos)
    v_cache = _write_step(v_cache, v_new, pos)
    out = masked_flash_attention(q, k_cache, v_cache,
                                 (pos + 1).astype(jnp.int32))
    return out, k_cache, v_cache


# ---- paged KV layout (PR 20) ------------------------------------------
#
# A "pool" below is one layer's page store: [pages, page_tokens, heads,
# head_dim] — either a plain float array or a QuantizedArray (int8 q +
# [..., heads, 1] f32 scales, mode "kv_head"). Page tables are int32
# pool indices; entries past a slot's allocation point at the engine's
# scratch pages, whose garbage is never read (same write-before-attend
# masking argument as the dense scratch row).


def _layer_pool(pool, i):
    """Layer i's slice of a stacked [depth, ...] pool (either dtype)."""
    if isinstance(pool, QuantizedArray):
        return QuantizedArray(pool.q[i], pool.scale[i], pool.mode)
    return pool[i]


def _stack_pools(pools):
    """Inverse of `_layer_pool`: restack per-layer pools along depth."""
    if isinstance(pools[0], QuantizedArray):
        return QuantizedArray(jnp.stack([p.q for p in pools]),
                              jnp.stack([p.scale for p in pools]),
                              pools[0].mode)
    return jnp.stack(pools)


def _paged_chunk_write(pool, chunk, page_id):
    """Prefill write: land ``chunk`` [c<=T, H, D] (float) at the head of
    page ``page_id``, quantizing on the way in when the pool is int8. A
    partial chunk (prompt bucket smaller than the page) leaves the tail
    of the page stale — unread by the masking contract."""
    at = (page_id, jnp.int32(0), jnp.int32(0), jnp.int32(0))
    if isinstance(pool, QuantizedArray):
        q, s = quantize_kv(chunk)
        return QuantizedArray(
            lax.dynamic_update_slice(pool.q, q[None], at),
            lax.dynamic_update_slice(pool.scale, s[None], at),
            pool.mode)
    return lax.dynamic_update_slice(pool, chunk[None], at)


def _paged_token_write(pool, new, page_ids, offs):
    """Decode write: row r's single token ``new`` [R, 1, H, D] lands at
    ``(page_ids[r], offs[r])``. Sequential per-row updates (R is a
    static row count): last-write-wins keeps rows aliased onto shared
    scratch pages harmless, exactly like the dense scratch row."""
    r = new.shape[0]
    if isinstance(pool, QuantizedArray):
        q, s = quantize_kv(new)
        pq, ps = pool.q, pool.scale
        for j in range(r):
            at = (page_ids[j], offs[j], jnp.int32(0), jnp.int32(0))
            pq = lax.dynamic_update_slice(pq, q[j][None], at)
            ps = lax.dynamic_update_slice(ps, s[j][None], at)
        return QuantizedArray(pq, ps, pool.mode)
    for j in range(r):
        at = (page_ids[j], offs[j], jnp.int32(0), jnp.int32(0))
        pool = lax.dynamic_update_slice(pool, new[j][None], at)
    return pool


def _paged_read(pool, page_table):
    """Gather a table's pages into the dense view ``[R, n*T, H, D]``
    attention consumes. Float pools pass through at their stored dtype
    (the bitwise-twin path); int8 pools dequantize to f32 — `_attend`
    computes scores/softmax in f32 regardless, so this adds no cast the
    dense path doesn't already perform."""
    if isinstance(pool, QuantizedArray):
        kq = jnp.take(pool.q, page_table, axis=0)
        ks = jnp.take(pool.scale, page_table, axis=0)
        r, n, t, h, d = kq.shape
        return (kq.astype(jnp.float32)
                * ks.astype(jnp.float32)).reshape(r, n * t, h, d)
    k = jnp.take(pool, page_table, axis=0)
    r, n, t, h, d = k.shape
    return k.reshape(r, n * t, h, d)


def _attend_fast(q, k, v, pos):
    """Key-prefix attention on einsum/dot_general tilings — the fast
    path for the agreement-gated int8 decode, where no bitwise contract
    forbids GEMM reassociation (so none of `_attend`'s broadcast-sum
    tax, and no [B, Sq, Sk, H, D] broadcast intermediate either). f32
    scores and softmax, -1e30 masking: same accumulation contract,
    different (tolerance-level) rounding."""
    dh = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32))
    scores = scores * (1.0 / jnp.sqrt(jnp.float32(dh)))
    mask = jnp.arange(k.shape[1])[None, None, None, :] \
        <= pos[:, None, None, None]
    scores = jnp.where(mask, scores, -1e30)
    weights = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


def _paged_decode_attn_update(q, k_new, v_new, k_pool, v_pool, pos,
                              page_table):
    """Paged twin of `_decode_attn_update`: write the token through the
    page table, then attend over the table's ``n*T`` gathered positions.
    Float pools run the bitwise `_attend` contract (full-width tables —
    module docstring); int8 pools take the fused Pallas kernel on TPU
    (`ops/pallas/paged_attention.use_paged_kernel`) and the einsum
    `_attend_fast` via XLA elsewhere."""
    t = (k_pool.q if isinstance(k_pool, QuantizedArray) else k_pool).shape[1]
    r = q.shape[0]
    page_ids = page_table[jnp.arange(r), pos // t]
    offs = pos % t
    k_pool = _paged_token_write(k_pool, k_new, page_ids, offs)
    v_pool = _paged_token_write(v_pool, v_new, page_ids, offs)
    if isinstance(k_pool, QuantizedArray):
        from dist_mnist_tpu.ops.pallas.paged_attention import (
            paged_attention,
            use_paged_kernel,
        )

        if use_paged_kernel():
            out = paged_attention(q, k_pool, v_pool, page_table,
                                  (pos + 1).astype(jnp.int32))
            return out, k_pool, v_pool
        k = _paged_read(k_pool, page_table)
        v = _paged_read(v_pool, page_table)
        return _attend_fast(q, k, v, pos), k_pool, v_pool
    k = _paged_read(k_pool, page_table)
    v = _paged_read(v_pool, page_table)
    mask = jnp.arange(k.shape[1])[None, None, :] <= pos[:, None, None]
    return _attend(q, k, v, mask), k_pool, v_pool


def _paged_decode_attn_update_gather(q, k_new, v_new, k_pool, v_pool, pos,
                                     page_table):
    """Shard-mapped paged decode body: pools stay head-sharded (the
    [P, T, H, D] heads axis rides the model axis — for int8 pools the
    rank-4 spec prefixes BOTH q and scale leaves), the attention output
    gathers like the dense TP path."""
    o, ck, cv = _paged_decode_attn_update(q, k_new, v_new, k_pool, v_pool,
                                          pos, page_table)
    return lax.all_gather(o, MODEL_AXIS, axis=2, tiled=True), ck, cv


def _attend_gather(q, k, v, mask):
    """Shard-mapped body for the full-sequence forward: per-device local
    heads, then a tiled all_gather back to the full head axis so the
    OUTPUT leaves the shard_map replicated. Gathering here (instead of
    letting GSPMD psum a heads-sharded out-projection) trades one small
    activation gather for bitwise parity with the unsharded path — the
    partial-sum reduction order of a sharded contraction is not the
    unsharded order, and this model's contract is bit-stable logits."""
    o = _attend(q, k, v, mask)
    return lax.all_gather(o, MODEL_AXIS, axis=2, tiled=True)


def _decode_attn_update_gather(q, k_new, v_new, k_cache, v_cache, pos):
    """Shard-mapped decode body: caches stay head-sharded (device-local
    in-place update), the attention output gathers (see above)."""
    o, ck, cv = _decode_attn_update(q, k_new, v_new, k_cache, v_cache, pos)
    return lax.all_gather(o, MODEL_AXIS, axis=2, tiled=True), ck, cv


def _heads_spec(mesh, heads):
    """PartitionSpec sharding the heads axis of [B,S,H,D] over the model
    axis, or None when the mesh can't (absent/singleton axis). Raising on
    an indivisible head count mirrors parallel/flash.py: silently
    replicating a "TP" cache would defeat the memory story."""
    shape = getattr(mesh, "shape", {}) if mesh is not None else {}
    m = shape.get(MODEL_AXIS, 1)
    if m <= 1:
        return None
    if heads % m:
        raise ValueError(
            f"heads={heads} not divisible by model axis {m}; "
            "the TP-sharded KV cache needs heads % model == 0"
        )
    return P(None, None, MODEL_AXIS, None)


@dataclasses.dataclass(frozen=True)
class CausalLMTiny:
    """Small decoder-only LM over a synthetic token alphabet.

    `init`/`apply` satisfy the `models/base.py` Model protocol
    (sample_input is a ``[B, S]`` int token batch or None — only the
    vocab/geometry fields size the params). `prefill`/`decode_step`/
    `init_cache` are the serving surface consumed by serve/decode.py.
    """

    vocab_size: int = 256
    dim: int = 64
    depth: int = 2
    heads: int = 4
    mlp_ratio: int = 4
    max_seq: int = 64
    compute_dtype: jnp.dtype = jnp.float32
    # "xla" (default): broadcast-sum attention everywhere — decode
    # bit-matches the full forward (tests/test_serve_decode.py contract).
    # "flash": decode_step's cached attention runs the variable-length
    # Pallas kernel (lengths = pos + 1, padded cache blocks skipped);
    # prefill/apply keep the xla path (their causal mask is per-query,
    # not key-only). Tolerance-parity, not bit-parity, vs "xla".
    attention_impl: str = "xla"
    # "dense": the original [slot, max_seq] stripe cache. "paged": page
    # pool + caller-owned page table (module docstring) — float pages
    # stay BITWISE equal to dense; kv_quant="int8" (paged only) stores
    # pages as QuantizedArray under the >=0.99 agreement gate.
    cache_layout: str = "dense"
    kv_page_tokens: int = 16
    kv_quant: str = "none"

    @property
    def head_dim(self) -> int:
        return self.dim // self.heads

    @property
    def pages_per_slot(self) -> int:
        """Pages covering one slot's full max_seq stripe (paged layout)."""
        return self.max_seq // self.kv_page_tokens

    def init(self, rng, sample_input=None):
        if self.dim % self.heads:
            raise ValueError(f"dim {self.dim} % heads {self.heads} != 0")
        if self.attention_impl not in ("xla", "flash"):
            raise ValueError(
                f"unknown attention_impl {self.attention_impl!r}; "
                "use 'xla' (bit-exact decode) or 'flash' (variable-length "
                "Pallas decode attention)")
        if self.cache_layout not in ("dense", "paged"):
            raise ValueError(
                f"unknown cache_layout {self.cache_layout!r}; "
                "use 'dense' | 'paged'")
        if self.kv_quant not in ("none", "int8"):
            raise ValueError(
                f"unknown kv_quant {self.kv_quant!r}; use 'none' | 'int8'")
        if self.kv_quant == "int8" and self.cache_layout != "paged":
            raise ValueError(
                "kv_quant='int8' is a paged-layout feature; set "
                "cache_layout='paged'")
        if self.cache_layout == "paged":
            if self.kv_page_tokens < 1 or self.max_seq % self.kv_page_tokens:
                raise ValueError(
                    f"kv_page_tokens={self.kv_page_tokens} must divide "
                    f"max_seq={self.max_seq} — whole pages are what keeps "
                    "the paged float path bitwise-equal to dense")
        keys = jax.random.split(rng, 3 + self.depth)
        d = self.dim
        params: dict = {
            "tok_emb": 0.02 * jax.random.normal(keys[0],
                                                (self.vocab_size, d)),
            "pos": 0.02 * jax.random.normal(keys[1], (1, self.max_seq, d)),
            "final_ln": nn.init_layer_norm(d),
            "lm_head": nn.init_dense(keys[2], d, self.vocab_size,
                                     init=nn.xavier_uniform),
        }
        for i in range(self.depth):
            k1, k2, k3 = jax.random.split(keys[3 + i], 3)
            params[f"block{i}"] = {
                "ln1": nn.init_layer_norm(d),
                "attn": nn.init_attention(k1, d, self.heads),
                "ln2": nn.init_layer_norm(d),
                "mlp_in": nn.init_dense(k2, d, d * self.mlp_ratio,
                                        init=nn.xavier_uniform),
                "mlp_out": nn.init_dense(k3, d * self.mlp_ratio, d,
                                         init=nn.xavier_uniform),
            }
        return params, {}

    def _qkv(self, p, x):
        b, s, d = x.shape
        qkv = nn.dense(p["qkv"], x).reshape(b, s, 3, self.heads,
                                            self.head_dim)
        return jnp.moveaxis(qkv, 2, 0)

    def _mlp(self, p, x):
        y = nn.layer_norm(p["ln2"], x)
        return x + nn.dense(p["mlp_out"], nn.gelu(nn.dense(p["mlp_in"], y)))

    def _forward(self, params, tokens):
        """Full-sequence causal forward: tokens ``[B,S]`` ->
        (logits ``[B,S,V]`` f32, per-layer (k, v) list). Positions past a
        prompt's real length produce garbage logits but — causality —
        never influence earlier positions, so callers simply index the
        rows they care about."""
        b, s = tokens.shape
        if s > self.max_seq:
            raise ValueError(f"sequence {s} > max_seq {self.max_seq}")
        x = params["tok_emb"][tokens].astype(self.compute_dtype)
        x = x + params["pos"][:, :s].astype(x.dtype)
        causal = jnp.broadcast_to(
            jnp.tril(jnp.ones((s, s), bool))[None], (b, s, s))
        mesh = ambient_mesh()
        spec = _heads_spec(mesh, self.heads)
        if spec is None:
            attend = _attend
        else:
            attend = compat_shard_map(
                _attend_gather, mesh=mesh,
                in_specs=(spec, spec, spec, P(None, None, None)),
                out_specs=P(None, None, None, None))
        kv = []
        for i in range(self.depth):
            p = params[f"block{i}"]
            y = nn.layer_norm(p["ln1"], x)
            q, k, v = self._qkv(p["attn"], y)
            o = attend(q, k, v, causal)
            x = x + nn.dense(p["attn"]["out"], o.reshape(b, s, self.dim))
            x = self._mlp(p, x)
            kv.append((k, v))
        x = nn.layer_norm(params["final_ln"], x)
        logits = nn.dense(params["lm_head"], x)
        return logits.astype(jnp.float32), kv

    def apply(self, params, state, x, *, train=False, rng=None):
        """Model-protocol forward: next-token logits at every position."""
        del train, rng
        logits, _ = self._forward(params, x)
        return logits, state

    def flops_per_example(self, sample_shape) -> float:
        """Analytic forward FLOPs (matmul MACs x2), mirroring vit.py."""
        s = int(sample_shape[1])
        d = self.dim
        per_block = (
            s * 3 * d * d * 2
            + 2 * s * s * d * 2
            + s * d * d * 2
            + 2 * s * d * (d * self.mlp_ratio) * 2
        )
        head = s * d * self.vocab_size * 2
        # lint: ok[host-sync] pure python-int arithmetic, no device values
        return float(self.depth * per_block + head)

    # ---- serving surface (serve/decode.py) ----------------------------

    def init_cache(self, slots: int, *, num_pages: int | None = None) -> dict:
        """Preallocated KV cache, layout per ``cache_layout``.

        dense: ``[depth, slot, max_seq, heads, head_dim]`` per tensor,
        zero-filled. paged: page pools ``[depth, num_pages, page_tokens,
        heads, head_dim]`` (default ``slots * pages_per_slot`` pages —
        enough to back every row fully, so the default pool never defers
        an admission); int8 pools are QuantizedArray nodes. Either way
        the serve engine device_puts the result with the heads axis (3)
        sharded over the model mesh axis — the int8 scale leaf is rank-5
        with heads at the same axis, so one spec covers all layouts."""
        if self.cache_layout == "dense":
            shape = (self.depth, slots, self.max_seq, self.heads,
                     self.head_dim)
            return {"k": jnp.zeros(shape, self.compute_dtype),
                    "v": jnp.zeros(shape, self.compute_dtype)}
        if num_pages is None:
            num_pages = slots * self.pages_per_slot
        shape = (self.depth, num_pages, self.kv_page_tokens, self.heads,
                 self.head_dim)
        if self.kv_quant == "int8":
            def pool():
                return QuantizedArray(
                    jnp.zeros(shape, jnp.int8),
                    jnp.zeros(shape[:-1] + (1,), jnp.float32), "kv_head")
            return {"k": pool(), "v": pool()}
        return {"k": jnp.zeros(shape, self.compute_dtype),
                "v": jnp.zeros(shape, self.compute_dtype)}

    def prefill(self, params, cache, tokens, slot_ids, lengths,
                page_table=None):
        """Run whole prompts and land their K/V in the cache.

        tokens ``[n, S_b]`` (right-padded to the prompt bucket), slot_ids
        ``[n]`` (cache rows; padding rows point at the engine's scratch
        slot), lengths ``[n]``. Returns (logits-at-last-real-position
        ``[n, V]``, updated cache). Padding positions >= length DO write
        garbage K/V — harmless, because decode's write-before-attend
        masking overwrites position p before any query can see it.

        Paged layout additionally takes ``page_table`` [rows,
        pages_per_slot] and writes each row's bucket page-chunk by
        page-chunk through its table row; chunks past a slot's
        allocation land in scratch pages (stale-never-read)."""
        paged = self.cache_layout == "paged"
        if paged and page_table is None:
            raise ValueError("paged cache_layout needs a page_table")
        if not paged and page_table is not None:
            raise ValueError("page_table is a paged-layout argument")
        logits, kv = self._forward(params, tokens)
        n, s_b = tokens.shape
        new_k, new_v = [], []
        if paged:
            t = self.kv_page_tokens
            n_chunks = -(-s_b // t)
            table_rows = page_table[slot_ids]  # [n, pages_per_slot]
            for i, (k, v) in enumerate(kv):
                pk = _layer_pool(cache["k"], i)
                pv = _layer_pool(cache["v"], i)
                for j in range(n):
                    for c in range(n_chunks):
                        pid = table_rows[j, c]
                        pk = _paged_chunk_write(pk, k[j, c * t:(c + 1) * t],
                                                pid)
                        pv = _paged_chunk_write(pv, v[j, c * t:(c + 1) * t],
                                                pid)
                new_k.append(pk)
                new_v.append(pv)
        else:
            for i, (k, v) in enumerate(kv):
                ck, cv = cache["k"][i], cache["v"][i]
                # sequential per-row writes (n is a static bucket size):
                # last-write-wins keeps duplicate scratch-slot rows harmless
                for j in range(n):
                    at = (slot_ids[j], jnp.int32(0), jnp.int32(0),
                          jnp.int32(0))
                    ck = lax.dynamic_update_slice(ck, k[j][None], at)
                    cv = lax.dynamic_update_slice(cv, v[j][None], at)
                new_k.append(ck)
                new_v.append(cv)
        cache = {"k": _stack_pools(new_k), "v": _stack_pools(new_v)}
        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
        return last, cache

    def decode_step(self, params, cache, tokens, positions,
                    page_table=None):
        """One token per slot: tokens ``[R]`` are each slot's most recent
        token, positions ``[R]`` where it goes in that slot's sequence.
        Returns (next-token logits ``[R, V]`` f32, updated cache). Each
        slot row only ever reads its own cache rows, so per-request
        streams are independent of batch composition — the invariant that
        makes continuous and static scheduling bit-identical.

        Paged layout takes ``page_table`` [rows, n] — n may be any page
        bucket covering every live prefix (``n*T > max(positions)``);
        attention then costs O(n*T). Float pools keep the bitwise twin
        contract only at FULL table width; int8 pools are built for
        truncation (module docstring)."""
        paged = self.cache_layout == "paged"
        if paged and page_table is None:
            raise ValueError("paged cache_layout needs a page_table")
        if not paged and page_table is not None:
            raise ValueError("page_table is a paged-layout argument")
        r = tokens.shape[0]
        x = params["tok_emb"][tokens].astype(self.compute_dtype)
        x = (x + params["pos"][0][positions].astype(x.dtype))[:, None, :]
        mesh = ambient_mesh()
        spec = _heads_spec(mesh, self.heads)
        extra = ()
        if paged:
            if spec is None:
                step = _paged_decode_attn_update
            else:
                step = compat_shard_map(
                    _paged_decode_attn_update_gather, mesh=mesh,
                    in_specs=(spec,) * 5 + (P(None), P(None, None)),
                    out_specs=(P(None, None, None, None), spec, spec))
            extra = (page_table,)
        elif spec is None:
            # the TP shard_map path stays on _attend regardless of
            # attention_impl: its contract is the gathered bit-stable
            # output, and heads are already device-local there
            step = (_decode_attn_update_flash
                    if self.attention_impl == "flash"
                    else _decode_attn_update)
        else:
            step = compat_shard_map(
                _decode_attn_update_gather, mesh=mesh,
                in_specs=(spec,) * 5 + (P(None),),
                out_specs=(P(None, None, None, None), spec, spec))
        new_k, new_v = [], []
        for i in range(self.depth):
            p = params[f"block{i}"]
            y = nn.layer_norm(p["ln1"], x)
            q, k, v = self._qkv(p["attn"], y)
            o, ck, cv = step(q, k, v, _layer_pool(cache["k"], i),
                             _layer_pool(cache["v"], i), positions, *extra)
            new_k.append(ck)
            new_v.append(cv)
            x = x + nn.dense(p["attn"]["out"], o.reshape(r, 1, self.dim))
            x = self._mlp(p, x)
        cache = {"k": _stack_pools(new_k), "v": _stack_pools(new_v)}
        x = nn.layer_norm(params["final_ln"], x)
        logits = nn.dense(params["lm_head"], x[:, 0])
        return logits.astype(jnp.float32), cache
