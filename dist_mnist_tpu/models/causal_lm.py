"""Tiny causal autoregressive transformer — the decode-serving workload.

Everything else in `models/` classifies a whole input in one forward;
this model emits one token at a time, which is what the decode serving
subsystem (serve/decode.py) exists to schedule. Geometry mirrors
`models/vit.py` (pre-LN blocks, learned positions, `ops/nn` attention
params) with two differences forced by autoregression:

- **Causal attention is implemented here**, not via
  `nn.dot_product_attention`: that kernel's mask is key-only ``[B, S_k]``
  (variable-length serving) and cannot express a per-query causal
  frontier. The math keeps the same accumulation contract (f32 scores
  and softmax, -1e30 masking) so numerics match the rest of the repo.
- **Two forward surfaces over one set of weights**: `apply`/`prefill`
  run the whole sequence with a triangular mask (and prefill writes
  every position's K/V into a cache), while `decode_step` runs ONE new
  token per slot against the cache, updating it in place with
  `lax.dynamic_update_slice`. Both routes share `_attend`, so an
  incremental decode reproduces the full-sequence forward bit-for-bit
  at every position (tests/test_serve_decode.py holds this).

Tensor parallelism follows `parallel/flash.py`: when the ambient mesh
has a model axis >1 and it divides `heads`, the attention kernel — cache
write included — runs under `compat_shard_map` with heads sharded, so
each device owns its head slice of the KV cache and updates it locally
(no collectives: attention is head-parallel, the out-projection happens
on the gathered activations outside the shard_map).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from dist_mnist_tpu.cluster.mesh import (
    MODEL_AXIS,
    ambient_mesh,
    compat_shard_map,
)
from dist_mnist_tpu.ops import nn


def _attend(q, k, v, mask):
    """Masked multi-head attention: q ``[B,Sq,H,D]`` against k/v
    ``[B,Sk,H,D]`` with a boolean mask ``[B,Sq,Sk]`` (True = attend).
    f32 scores and softmax regardless of the activation dtype — the same
    accumulation contract as `nn.dot_product_attention`.

    Both contractions are broadcast-multiply + ``jnp.sum`` rather than
    einsums ON PURPOSE: XLA lowers a dot_general's accumulation order
    per gemm tiling, which varies with the query-length (M) dimension —
    measured on CPU, ``weights @ v`` at Sq=1 rounds differently from
    Sq=S by ~1 ulp. A single-axis reduce is per-output-element and
    independent of the other dims, which is what lets an incremental
    decode (Sq=1) bit-match the full-sequence forward at every position
    — the correctness contract tests/test_serve_decode.py pins. The
    O(Sq*Sk*H*D) broadcast is fine at this model's serving scale."""
    dh = q.shape[-1]
    # [B,Sq,Sk,H] <- sum_d q[B,Sq,1,H,D] * k[B,1,Sk,H,D]
    scores = jnp.sum(
        q.astype(jnp.float32)[:, :, None] * k.astype(jnp.float32)[:, None],
        axis=-1)
    scores = scores.transpose(0, 3, 1, 2)  # [B,H,Sq,Sk]
    scores = scores * (1.0 / jnp.sqrt(jnp.float32(dh)))
    scores = jnp.where(mask[:, None, :, :], scores, -1e30)
    weights = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    # [B,H,Sq,D] <- sum_k w[B,H,Sq,Sk,1] * v[B,H,1,Sk,D]
    out = jnp.sum(
        weights[..., None] * jnp.moveaxis(v, 1, 2)[:, :, None], axis=3)
    return out.transpose(0, 2, 1, 3)  # [B,Sq,H,D]


def _write_step(cache, new, pos):
    """Write one token's K (or V) per slot: ``cache`` [R,S,H,D], ``new``
    [R,1,H,D], ``pos`` [R] — row r gets its token at ``pos[r]``."""
    return jax.vmap(
        lambda c, n, p: lax.dynamic_update_slice(c, n, (p, 0, 0))
    )(cache, new, pos)


def _decode_attn_update(q, k_new, v_new, k_cache, v_cache, pos):
    """One cached-attention step (runs per head-shard under shard_map):
    write the new K/V at each slot's position, then attend the single
    query against keys ``<= pos`` — write-before-attend is what lets a
    freshly admitted slot overwrite stale prefill padding before any
    mask ever admits it."""
    k_cache = _write_step(k_cache, k_new, pos)
    v_cache = _write_step(v_cache, v_new, pos)
    max_seq = k_cache.shape[1]
    mask = jnp.arange(max_seq)[None, None, :] <= pos[:, None, None]
    return _attend(q, k_cache, v_cache, mask), k_cache, v_cache


def _decode_attn_update_flash(q, k_new, v_new, k_cache, v_cache, pos):
    """`_decode_attn_update` with the attention itself on the
    variable-length Pallas flash kernel: the decode mask (``arange <=
    pos``) is EXACTLY a key-prefix, so it becomes per-slot lengths
    ``pos + 1`` and the kernel's grid skips cache blocks past each
    slot's frontier — short sequences in a long `max_seq` cache stop
    paying full-cache attention math. Opt-in (`attention_impl="flash"`):
    the kernel's dot_general accumulation differs from `_attend`'s
    broadcast-sum by ~1 ulp, so it relaxes the bit-exact decode==forward
    contract to a tolerance (see tests/test_kernels.py)."""
    from dist_mnist_tpu.ops.pallas.flash_attention import (
        masked_flash_attention,
    )

    k_cache = _write_step(k_cache, k_new, pos)
    v_cache = _write_step(v_cache, v_new, pos)
    out = masked_flash_attention(q, k_cache, v_cache,
                                 (pos + 1).astype(jnp.int32))
    return out, k_cache, v_cache


def _attend_gather(q, k, v, mask):
    """Shard-mapped body for the full-sequence forward: per-device local
    heads, then a tiled all_gather back to the full head axis so the
    OUTPUT leaves the shard_map replicated. Gathering here (instead of
    letting GSPMD psum a heads-sharded out-projection) trades one small
    activation gather for bitwise parity with the unsharded path — the
    partial-sum reduction order of a sharded contraction is not the
    unsharded order, and this model's contract is bit-stable logits."""
    o = _attend(q, k, v, mask)
    return lax.all_gather(o, MODEL_AXIS, axis=2, tiled=True)


def _decode_attn_update_gather(q, k_new, v_new, k_cache, v_cache, pos):
    """Shard-mapped decode body: caches stay head-sharded (device-local
    in-place update), the attention output gathers (see above)."""
    o, ck, cv = _decode_attn_update(q, k_new, v_new, k_cache, v_cache, pos)
    return lax.all_gather(o, MODEL_AXIS, axis=2, tiled=True), ck, cv


def _heads_spec(mesh, heads):
    """PartitionSpec sharding the heads axis of [B,S,H,D] over the model
    axis, or None when the mesh can't (absent/singleton axis). Raising on
    an indivisible head count mirrors parallel/flash.py: silently
    replicating a "TP" cache would defeat the memory story."""
    shape = getattr(mesh, "shape", {}) if mesh is not None else {}
    m = shape.get(MODEL_AXIS, 1)
    if m <= 1:
        return None
    if heads % m:
        raise ValueError(
            f"heads={heads} not divisible by model axis {m}; "
            "the TP-sharded KV cache needs heads % model == 0"
        )
    return P(None, None, MODEL_AXIS, None)


@dataclasses.dataclass(frozen=True)
class CausalLMTiny:
    """Small decoder-only LM over a synthetic token alphabet.

    `init`/`apply` satisfy the `models/base.py` Model protocol
    (sample_input is a ``[B, S]`` int token batch or None — only the
    vocab/geometry fields size the params). `prefill`/`decode_step`/
    `init_cache` are the serving surface consumed by serve/decode.py.
    """

    vocab_size: int = 256
    dim: int = 64
    depth: int = 2
    heads: int = 4
    mlp_ratio: int = 4
    max_seq: int = 64
    compute_dtype: jnp.dtype = jnp.float32
    # "xla" (default): broadcast-sum attention everywhere — decode
    # bit-matches the full forward (tests/test_serve_decode.py contract).
    # "flash": decode_step's cached attention runs the variable-length
    # Pallas kernel (lengths = pos + 1, padded cache blocks skipped);
    # prefill/apply keep the xla path (their causal mask is per-query,
    # not key-only). Tolerance-parity, not bit-parity, vs "xla".
    attention_impl: str = "xla"

    @property
    def head_dim(self) -> int:
        return self.dim // self.heads

    def init(self, rng, sample_input=None):
        if self.dim % self.heads:
            raise ValueError(f"dim {self.dim} % heads {self.heads} != 0")
        if self.attention_impl not in ("xla", "flash"):
            raise ValueError(
                f"unknown attention_impl {self.attention_impl!r}; "
                "use 'xla' (bit-exact decode) or 'flash' (variable-length "
                "Pallas decode attention)")
        keys = jax.random.split(rng, 3 + self.depth)
        d = self.dim
        params: dict = {
            "tok_emb": 0.02 * jax.random.normal(keys[0],
                                                (self.vocab_size, d)),
            "pos": 0.02 * jax.random.normal(keys[1], (1, self.max_seq, d)),
            "final_ln": nn.init_layer_norm(d),
            "lm_head": nn.init_dense(keys[2], d, self.vocab_size,
                                     init=nn.xavier_uniform),
        }
        for i in range(self.depth):
            k1, k2, k3 = jax.random.split(keys[3 + i], 3)
            params[f"block{i}"] = {
                "ln1": nn.init_layer_norm(d),
                "attn": nn.init_attention(k1, d, self.heads),
                "ln2": nn.init_layer_norm(d),
                "mlp_in": nn.init_dense(k2, d, d * self.mlp_ratio,
                                        init=nn.xavier_uniform),
                "mlp_out": nn.init_dense(k3, d * self.mlp_ratio, d,
                                         init=nn.xavier_uniform),
            }
        return params, {}

    def _qkv(self, p, x):
        b, s, d = x.shape
        qkv = nn.dense(p["qkv"], x).reshape(b, s, 3, self.heads,
                                            self.head_dim)
        return jnp.moveaxis(qkv, 2, 0)

    def _mlp(self, p, x):
        y = nn.layer_norm(p["ln2"], x)
        return x + nn.dense(p["mlp_out"], nn.gelu(nn.dense(p["mlp_in"], y)))

    def _forward(self, params, tokens):
        """Full-sequence causal forward: tokens ``[B,S]`` ->
        (logits ``[B,S,V]`` f32, per-layer (k, v) list). Positions past a
        prompt's real length produce garbage logits but — causality —
        never influence earlier positions, so callers simply index the
        rows they care about."""
        b, s = tokens.shape
        if s > self.max_seq:
            raise ValueError(f"sequence {s} > max_seq {self.max_seq}")
        x = params["tok_emb"][tokens].astype(self.compute_dtype)
        x = x + params["pos"][:, :s].astype(x.dtype)
        causal = jnp.broadcast_to(
            jnp.tril(jnp.ones((s, s), bool))[None], (b, s, s))
        mesh = ambient_mesh()
        spec = _heads_spec(mesh, self.heads)
        if spec is None:
            attend = _attend
        else:
            attend = compat_shard_map(
                _attend_gather, mesh=mesh,
                in_specs=(spec, spec, spec, P(None, None, None)),
                out_specs=P(None, None, None, None))
        kv = []
        for i in range(self.depth):
            p = params[f"block{i}"]
            y = nn.layer_norm(p["ln1"], x)
            q, k, v = self._qkv(p["attn"], y)
            o = attend(q, k, v, causal)
            x = x + nn.dense(p["attn"]["out"], o.reshape(b, s, self.dim))
            x = self._mlp(p, x)
            kv.append((k, v))
        x = nn.layer_norm(params["final_ln"], x)
        logits = nn.dense(params["lm_head"], x)
        return logits.astype(jnp.float32), kv

    def apply(self, params, state, x, *, train=False, rng=None):
        """Model-protocol forward: next-token logits at every position."""
        del train, rng
        logits, _ = self._forward(params, x)
        return logits, state

    def flops_per_example(self, sample_shape) -> float:
        """Analytic forward FLOPs (matmul MACs x2), mirroring vit.py."""
        s = int(sample_shape[1])
        d = self.dim
        per_block = (
            s * 3 * d * d * 2
            + 2 * s * s * d * 2
            + s * d * d * 2
            + 2 * s * d * (d * self.mlp_ratio) * 2
        )
        head = s * d * self.vocab_size * 2
        # lint: ok[host-sync] pure python-int arithmetic, no device values
        return float(self.depth * per_block + head)

    # ---- serving surface (serve/decode.py) ----------------------------

    def init_cache(self, slots: int) -> dict:
        """Preallocated KV cache: ``[depth, slot, max_seq, heads,
        head_dim]`` per tensor, zero-filled. The serve engine device_puts
        this with the heads axis sharded over the model mesh axis."""
        shape = (self.depth, slots, self.max_seq, self.heads, self.head_dim)
        return {"k": jnp.zeros(shape, self.compute_dtype),
                "v": jnp.zeros(shape, self.compute_dtype)}

    def prefill(self, params, cache, tokens, slot_ids, lengths):
        """Run whole prompts and land their K/V in the cache.

        tokens ``[n, S_b]`` (right-padded to the prompt bucket), slot_ids
        ``[n]`` (cache rows; padding rows point at the engine's scratch
        slot), lengths ``[n]``. Returns (logits-at-last-real-position
        ``[n, V]``, updated cache). Padding positions >= length DO write
        garbage K/V — harmless, because decode's write-before-attend
        masking overwrites position p before any query can see it."""
        logits, kv = self._forward(params, tokens)
        n = tokens.shape[0]
        new_k, new_v = [], []
        for i, (k, v) in enumerate(kv):
            ck, cv = cache["k"][i], cache["v"][i]
            # sequential per-row writes (n is a static bucket size):
            # last-write-wins keeps duplicate scratch-slot rows harmless
            for j in range(n):
                at = (slot_ids[j], jnp.int32(0), jnp.int32(0), jnp.int32(0))
                ck = lax.dynamic_update_slice(ck, k[j][None], at)
                cv = lax.dynamic_update_slice(cv, v[j][None], at)
            new_k.append(ck)
            new_v.append(cv)
        cache = {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}
        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
        return last, cache

    def decode_step(self, params, cache, tokens, positions):
        """One token per slot: tokens ``[R]`` are each slot's most recent
        token, positions ``[R]`` where it goes in that slot's sequence.
        Returns (next-token logits ``[R, V]`` f32, updated cache). Each
        slot row only ever reads its own cache rows, so per-request
        streams are independent of batch composition — the invariant that
        makes continuous and static scheduling bit-identical."""
        r = tokens.shape[0]
        x = params["tok_emb"][tokens].astype(self.compute_dtype)
        x = (x + params["pos"][0][positions].astype(x.dtype))[:, None, :]
        mesh = ambient_mesh()
        spec = _heads_spec(mesh, self.heads)
        if spec is None:
            # the TP shard_map path stays on _attend regardless of
            # attention_impl: its contract is the gathered bit-stable
            # output, and heads are already device-local there
            step = (_decode_attn_update_flash
                    if self.attention_impl == "flash"
                    else _decode_attn_update)
        else:
            step = compat_shard_map(
                _decode_attn_update_gather, mesh=mesh,
                in_specs=(spec,) * 5 + (P(None),),
                out_specs=(P(None, None, None, None), spec, spec))
        new_k, new_v = [], []
        for i in range(self.depth):
            p = params[f"block{i}"]
            y = nn.layer_norm(p["ln1"], x)
            q, k, v = self._qkv(p["attn"], y)
            o, ck, cv = step(q, k, v, cache["k"][i], cache["v"][i],
                             positions)
            new_k.append(ck)
            new_v.append(cv)
            x = x + nn.dense(p["attn"]["out"], o.reshape(r, 1, self.dim))
            x = self._mlp(p, x)
        cache = {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}
        x = nn.layer_norm(params["final_ln"], x)
        logits = nn.dense(params["lm_head"], x[:, 0])
        return logits.astype(jnp.float32), cache
