"""ResNet-20 for CIFAR-10 (BASELINE.md config 4: 8-way DP stress of
conv + all-reduce).

Classic CIFAR ResNet (He et al. 2016): 3 stages × 3 basic blocks, widths
16/32/64, stride-2 at stage entry, identity shortcuts with 1x1 projection on
downsample, batch norm + ReLU, global average pool, fc10. Batch norm runs
synchronized across the `data` mesh axis for free: the batch dim is sharded,
so XLA turns the batch-mean into an ICI all-reduce (see ops/nn.batch_norm).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from dist_mnist_tpu.ops import nn


def _init_block(key, cin, cout, stride):
    k1, k2, k3 = jax.random.split(key, 3)
    conv1 = nn.init_conv(k1, 3, 3, cin, cout, init=nn.he_normal)
    conv2 = nn.init_conv(k2, 3, 3, cout, cout, init=nn.he_normal)
    bn1_p, bn1_s = nn.init_batch_norm(cout)
    bn2_p, bn2_s = nn.init_batch_norm(cout)
    params = {"conv1": conv1, "conv2": conv2, "bn1": bn1_p, "bn2": bn2_p}
    state = {"bn1": bn1_s, "bn2": bn2_s}
    if stride != 1 or cin != cout:
        params["proj"] = nn.init_conv(k3, 1, 1, cin, cout, init=nn.he_normal)
    return params, state


def _apply_block(p, s, x, stride, train):
    y = nn.conv2d(p["conv1"], x, stride=stride)
    y, s1 = nn.batch_norm(p["bn1"], s["bn1"], y, train=train)
    y = nn.relu(y)
    y = nn.conv2d(p["conv2"], y)
    y, s2 = nn.batch_norm(p["bn2"], s["bn2"], y, train=train)
    shortcut = nn.conv2d(p["proj"], x, stride=stride) if "proj" in p else x
    return nn.relu(y + shortcut), {"bn1": s1, "bn2": s2}


@dataclasses.dataclass(frozen=True)
class ResNet20:
    num_classes: int = 10
    widths: tuple[int, ...] = (16, 32, 64)
    blocks_per_stage: int = 3
    compute_dtype: jnp.dtype = jnp.bfloat16

    def init(self, rng, sample_input):
        c = int(sample_input.shape[-1])
        keys = jax.random.split(rng, 2 + len(self.widths) * self.blocks_per_stage)
        params: dict = {"stem": nn.init_conv(keys[0], 3, 3, c, self.widths[0],
                                             init=nn.he_normal)}
        bn_p, bn_s = nn.init_batch_norm(self.widths[0])
        params["stem_bn"] = bn_p
        state: dict = {"stem_bn": bn_s}
        cin = self.widths[0]
        ki = 1
        for si, w in enumerate(self.widths):
            for bi in range(self.blocks_per_stage):
                stride = 2 if (si > 0 and bi == 0) else 1
                bp, bs = _init_block(keys[ki], cin, w, stride)
                params[f"s{si}b{bi}"] = bp
                state[f"s{si}b{bi}"] = bs
                cin = w
                ki += 1
        params["head"] = nn.init_dense(keys[ki], cin, self.num_classes,
                                       init=nn.xavier_uniform)
        return params, state

    def flops_per_example(self, sample_shape) -> float:
        """Analytic FORWARD FLOPs per example (conv/matmul MACs x2; BN and
        elementwise ignored); see MLP.flops_per_example for why."""
        h, w, c = (int(d) for d in sample_shape[1:])
        total = h * w * self.widths[0] * (3 * 3 * c) * 2  # stem
        cin = self.widths[0]
        for si, cout in enumerate(self.widths):
            for bi in range(self.blocks_per_stage):
                stride = 2 if (si > 0 and bi == 0) else 1
                if stride == 2:
                    h, w = h // 2, w // 2
                total += h * w * cout * (3 * 3 * cin) * 2   # conv1
                total += h * w * cout * (3 * 3 * cout) * 2  # conv2
                if stride == 2 or cin != cout:
                    total += h * w * cout * cin * 2         # 1x1 projection
                cin = cout
        total += cin * self.num_classes * 2  # head after global avg pool
        return float(total)

    def apply(self, params, state, x, *, train=False, rng=None):
        x = x.astype(self.compute_dtype)
        x = nn.conv2d(params["stem"], x)
        x, stem_s = nn.batch_norm(params["stem_bn"], state["stem_bn"], x, train=train)
        x = nn.relu(x)
        new_state = {"stem_bn": stem_s}
        for si in range(len(self.widths)):
            for bi in range(self.blocks_per_stage):
                stride = 2 if (si > 0 and bi == 0) else 1
                name = f"s{si}b{bi}"
                x, new_state[name] = _apply_block(
                    params[name], state[name], x, stride, train
                )
        x = nn.global_avg_pool(x)
        logits = nn.dense(params["head"], x)
        return logits.astype(jnp.float32), new_state
