"""Model registry: name -> constructor, mirroring the config ladder."""

from __future__ import annotations

from dist_mnist_tpu.models.causal_lm import CausalLMTiny
from dist_mnist_tpu.models.lenet import LeNet5
from dist_mnist_tpu.models.mlp import MLP
from dist_mnist_tpu.models.resnet import ResNet20
from dist_mnist_tpu.models.vit import ViTTiny

MODELS = {
    "mlp": MLP,
    "lenet5": LeNet5,
    "resnet20": ResNet20,
    "vit_tiny": ViTTiny,
    "causal_tiny": CausalLMTiny,
}


def get_model(name: str, **overrides):
    if name not in MODELS:
        raise KeyError(f"unknown model {name!r}; have {sorted(MODELS)}")
    return MODELS[name](**overrides)
