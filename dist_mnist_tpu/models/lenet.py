"""LeNet-5 CNN — the "original dist config" tower (SURVEY.md §0.1 step 5):
conv5x5x32 → maxpool → conv5x5x64 → maxpool → fc512 → dropout → fc10.

This is the flagship benchmark model (BASELINE.md north-star metric is
"MNIST CNN steps/sec/chip"). Compute defaults to bfloat16: both convs and
the fc512 GEMM hit the MXU at double rate while params/logits stay f32.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from dist_mnist_tpu.ops import nn


@dataclasses.dataclass(frozen=True)
class LeNet5:
    num_classes: int = 10
    dropout_rate: float = 0.5
    compute_dtype: jnp.dtype = jnp.bfloat16

    def init(self, rng, sample_input):
        h, w, c = (int(d) for d in sample_input.shape[1:])
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        fc_in = (h // 4) * (w // 4) * 64  # two SAME convs + two 2x2 pools
        params = {
            "conv1": nn.init_conv(k1, 5, 5, c, 32),
            "conv2": nn.init_conv(k2, 5, 5, 32, 64),
            "fc1": nn.init_dense(k3, fc_in, 512),
            "fc2": nn.init_dense(k4, 512, self.num_classes),
        }
        return params, {}

    def flops_per_example(self, sample_shape) -> float:
        """Analytic FORWARD FLOPs per example (conv/matmul MACs x2); see
        MLP.flops_per_example for why every model publishes this."""
        h, w, c = (int(d) for d in sample_shape[1:])
        conv1 = h * w * 32 * (5 * 5 * c) * 2
        conv2 = (h // 2) * (w // 2) * 64 * (5 * 5 * 32) * 2
        fc1 = ((h // 4) * (w // 4) * 64) * 512 * 2
        fc2 = 512 * self.num_classes * 2
        return float(conv1 + conv2 + fc1 + fc2)

    def apply(self, params, state, x, *, train=False, rng=None):
        x = x.astype(self.compute_dtype)
        x = nn.relu(nn.conv2d(params["conv1"], x))
        x = nn.max_pool(x, 2)
        x = nn.relu(nn.conv2d(params["conv2"], x))
        x = nn.max_pool(x, 2)
        x = nn.flatten(x)
        x = nn.relu(nn.dense(params["fc1"], x))
        if train and rng is not None:
            x = nn.dropout(rng, x, self.dropout_rate, train=True)
        logits = nn.dense(params["fc2"], x)
        return logits.astype(jnp.float32), state
