"""The model contract.

The reference built models as side-effectful graph construction against
implicit collections (`tf.Variable` placed by replica_device_setter —
SURVEY.md §0.1 step 5, §2.2 row 5). Here a model is two pure functions over
explicit pytrees; placement is a separate concern (parallel/sharding.py
assigns PartitionSpecs to the returned params by path).
"""

from __future__ import annotations

from typing import Protocol

import jax

Params = dict
State = dict  # mutable model state (BN running stats); {} for stateless models


class Model(Protocol):
    """Functional model: `init` builds pytrees, `apply` is pure.

    - ``init(rng, sample_input) -> (params, state)``; sample_input is a
      host/abstract batch used only for shapes.
    - ``apply(params, state, x, *, train, rng) -> (logits, new_state)``;
      ``rng`` may be None when the model has no stochastic layers or
      ``train=False``.
    - ``compute_dtype`` — activations dtype (bfloat16 on TPU by default);
      params stay float32 (master weights).
    """

    compute_dtype: jax.numpy.dtype

    def init(self, rng: jax.Array, sample_input) -> tuple[Params, State]: ...

    def apply(
        self,
        params: Params,
        state: State,
        x: jax.Array,
        *,
        train: bool = False,
        rng: jax.Array | None = None,
    ) -> tuple[jax.Array, State]: ...
