"""Model zoo for the benchmark ladder (BASELINE.md configs 1-5):

- `mlp.MLP` — the reference driver's exact 2-layer MLP geometry
  (SURVEY.md §0.1 step 5).
- `lenet.LeNet5` — the "original dist config" CNN tower.
- `resnet.ResNet20` — CIFAR-10 residual net (8-way DP config).
- `vit.ViTTiny` — attention-path stretch config (pod slice).

All models follow the functional contract in `base.Model`: f32 params,
optional bfloat16 compute, mutable state (e.g. BN running stats) threaded
explicitly.
"""

from dist_mnist_tpu.models.base import Model
from dist_mnist_tpu.models.registry import get_model, MODELS

__all__ = ["Model", "get_model", "MODELS"]
