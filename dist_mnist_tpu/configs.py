"""The benchmark-ladder configs (BASELINE.md / SURVEY.md §0.1).

Each reference config named a cluster shape (ps/worker counts); here the
same ladder is expressed as a mesh shape — the "1 ps + 2 workers" topology
is meaningless under SPMD, so configs 2-5 state their data-parallel width
directly. `batch_size` is GLOBAL (the reference's was per-worker; its
original dist config = 2 workers × 100 = global 200, preserved here).
"""

from __future__ import annotations

import dataclasses
from typing import Any

from dist_mnist_tpu.cluster.mesh import MeshSpec


@dataclasses.dataclass(frozen=True)
class Config:
    name: str
    model: str
    dataset: str
    batch_size: int  # global
    train_steps: int
    learning_rate: float
    optimizer: str = "adam"  # adam | sgd | momentum
    model_kwargs: dict[str, Any] = dataclasses.field(default_factory=dict)
    mesh: MeshSpec = MeshSpec()  # data = all devices by default
    ladder_devices: int = 1  # chip count the BASELINE ladder sizes this
    # config's GLOBAL batch for; on a smaller box, bench preserves the
    # per-chip batch (batch_size/ladder_devices per chip) instead of
    # cramming the whole pod-slice batch into one chip's HBM
    loss: str = "stable"  # "clipped" = reference parity loss
    lr_schedule: str = "constant"  # constant | cosine
    warmup_steps: int = 0
    replicas_to_aggregate: int = 1  # >1 => gradient accumulation (optim/sync.py)
    sharding_rules: str = "dp"  # "dp" (params replicated) | "tp" (Megatron
    # column/row TP_RULES over the `model` axis) | "fsdp" (ZeRO-style:
    # params + optimizer slots sharded over `data`, 1/data-th per device)
    # | "fsdp_tp" (both composed) — parallel/sharding.py
    overlap: bool = False  # fsdp comm/compute overlap: bucketed param
    # all-gather prefetch + reduce-scatter flushed while the backward is
    # still running (parallel/overlap.py). Requires an fsdp axis; value-
    # identical to the serial path (bit-exact on the CPU mesh).
    overlap_bucket_mb: float = 4.0  # bucket granularity: smaller buckets =
    # more chunks in flight (better overlap, more launches); larger = fewer,
    # bigger transfers. Registered tunable (tune/spec.py): --tuned=auto
    # applies the per-geometry stored winner unless this is set explicitly
    overlap_chunk: str = "all_gather"  # "all_gather" (one collective per
    # leaf) | "ring" (ppermute double-buffering, collective_matmul-style)
    grad_clip_norm: float | None = None
    weight_decay: float = 0.0
    prng_impl: str = "threefry2x32"  # | "rbg": hardware-friendly PRNG —
    # threefry's bit-mixing is a known TPU cost for per-layer dropout
    # masks; rbg trades cross-backend bit-reproducibility for speed
    # (determinism WITHIN a backend is preserved)
    remat: bool = False  # jax.checkpoint the forward (HBM <-> FLOPs trade)
    remat_policy: str = "dots_no_batch"  # what remat saves vs recomputes:
    # dots_no_batch | save_attn (keep per-block attention outputs — stops
    # the O(S^2) backward recompute) | dots | nothing
    # (train/step.py REMAT_POLICIES)
    augment: bool = False  # on-device pad-crop-flip (data/augment.py)
    eval_every: int = 1000
    log_every: int = 100
    checkpoint_every_secs: float = 600.0  # CheckpointSaverHook default cadence
    # Global-batch policy when an elastic resize changes the device count
    # (cli/launch.py --elastic; see apply_elastic_policy):
    #   keep_global — batch_size stays the GLOBAL batch; each surviving
    #                 device's share grows, optimizer trajectory unchanged
    #   scale_lr    — additionally scale learning_rate by
    #                 current/baseline devices (linear-scaling rule run in
    #                 reverse, for models whose per-device batch must not
    #                 grow)
    elastic_batch_policy: str = "keep_global"
    seed: int = 42


CONFIGS = {
    # 1) the reference driver's own defaults (§0.1 flag table), single chip
    "mlp_mnist": Config(
        name="mlp_mnist",
        model="mlp",
        dataset="mnist",
        batch_size=64,
        train_steps=2000,
        learning_rate=0.01,
        loss="clipped",  # bit-comparable with the reference loss
        model_kwargs={"hidden_units": 100},
        eval_every=500,
    ),
    # 2) "original dist config": LeNet-5, 2 workers x batch 100
    "lenet5_mnist": Config(
        name="lenet5_mnist",
        model="lenet5",
        dataset="mnist",
        batch_size=200,
        train_steps=2000,
        learning_rate=1e-3,
        eval_every=500,
    ),
    # 3) LeNet-5 / Fashion-MNIST / 4-way DP
    "lenet5_fashion": Config(
        name="lenet5_fashion",
        model="lenet5",
        dataset="fashion_mnist",
        batch_size=512,
        train_steps=3000,
        learning_rate=1e-3,
        mesh=MeshSpec(data=4),
        ladder_devices=4,
    ),
    # 4) ResNet-20 / CIFAR-10 / 8-way DP
    "resnet20_cifar": Config(
        name="resnet20_cifar",
        model="resnet20",
        dataset="cifar10",
        batch_size=1024,
        train_steps=5000,
        learning_rate=2e-3,
        lr_schedule="cosine",
        warmup_steps=200,
        grad_clip_norm=1.0,
        augment=True,  # pad-crop-flip: standard CIFAR recipe, on device
        mesh=MeshSpec(data=8),
        ladder_devices=8,
    ),
    # 4b) config 4 under ZeRO/FSDP: same model, data, and trajectory as
    # resnet20_cifar (the sharding is numerics-neutral), but params + Adam
    # slots live 1/8th per chip — the bench-ladder rung that measures the
    # HBM claim (`bench.py --memory` dp vs fsdp).
    "resnet20_cifar_fsdp": Config(
        name="resnet20_cifar_fsdp",
        model="resnet20",
        dataset="cifar10",
        batch_size=1024,
        train_steps=5000,
        learning_rate=2e-3,
        lr_schedule="cosine",
        warmup_steps=200,
        grad_clip_norm=1.0,
        augment=True,
        sharding_rules="fsdp",
        mesh=MeshSpec(data=8),
        ladder_devices=8,
    ),
    # 5) ViT-Tiny / CIFAR-10 / pod slice (stretch; attention path)
    "vit_tiny_cifar": Config(
        name="vit_tiny_cifar",
        model="vit_tiny",
        dataset="cifar10",
        batch_size=1024,
        train_steps=5000,
        learning_rate=1e-3,
        lr_schedule="cosine",
        warmup_steps=500,
        grad_clip_norm=1.0,
        weight_decay=0.05,
        remat=True,  # depth-12 attention stack: recompute, don't hold
        augment=True,
        model_kwargs={"scan_blocks": True},  # one compiled block, not 12
        mesh=MeshSpec(data=-1),  # whole slice
        ladder_devices=16,  # "v4-32" = 32 TensorCores = 16 chips
    ),
    # 5b) config 5 with Ulysses sequence parallelism (SURVEY.md §5.7): the
    # all-to-all SP alternative to ring attention, selectable like any
    # other config. heads=4 (not ViT-Ti's 3) so heads % seq == 0, and mean
    # pooling keeps the token count divisible by the seq axis.
    "vit_tiny_cifar_ulysses": Config(
        name="vit_tiny_cifar_ulysses",
        model="vit_tiny",
        dataset="cifar10",
        batch_size=1024,
        train_steps=5000,
        learning_rate=1e-3,
        lr_schedule="cosine",
        warmup_steps=500,
        grad_clip_norm=1.0,
        weight_decay=0.05,
        remat=True,
        augment=True,
        model_kwargs={"attention_impl": "ulysses", "pool": "mean",
                      "heads": 4, "scan_blocks": True},
        mesh=MeshSpec(data=-1, seq=2),
        ladder_devices=16,
    ),
    # 5g) Ulysses with the flash LOCAL engine: after the head reshard each
    # device attends over the FULL sequence — the configuration where the
    # kernel's VMEM score tiles matter most (parallel/ulysses.py).
    "vit_tiny_cifar_ulysses_flash": Config(
        name="vit_tiny_cifar_ulysses_flash",
        model="vit_tiny",
        dataset="cifar10",
        batch_size=1024,
        train_steps=5000,
        learning_rate=1e-3,
        lr_schedule="cosine",
        warmup_steps=500,
        grad_clip_norm=1.0,
        weight_decay=0.05,
        remat=True,
        augment=True,
        model_kwargs={"attention_impl": "ulysses_flash", "pool": "mean",
                      "heads": 4, "scan_blocks": True},
        mesh=MeshSpec(data=-1, seq=2),
        ladder_devices=16,
    ),
    # 5c) config 5 with switch-MoE FFN blocks, expert-parallel over a
    # 4-way `model` axis (one expert per rank — parallel/moe.py); the
    # load-balance aux loss joins the objective via model_state.
    "vit_tiny_cifar_moe": Config(
        name="vit_tiny_cifar_moe",
        model="vit_tiny",
        dataset="cifar10",
        batch_size=1024,
        train_steps=5000,
        learning_rate=1e-3,
        lr_schedule="cosine",
        warmup_steps=500,
        grad_clip_norm=1.0,
        weight_decay=0.05,
        remat=True,
        augment=True,
        model_kwargs={"mlp_impl": "moe", "n_experts": 4, "pool": "mean",
                      "scan_blocks": True},
        mesh=MeshSpec(data=-1, model=4),
        ladder_devices=16,
    ),
    # 5e) config 5 tensor-parallel: qkv/mlp matmuls Megatron-sharded over a
    # 2-way `model` axis (TP_RULES column/row pattern); grads for the
    # sharded params stay sharded — XLA inserts the TP reduce in-step.
    "vit_tiny_cifar_tp": Config(
        name="vit_tiny_cifar_tp",
        model="vit_tiny",
        dataset="cifar10",
        batch_size=1024,
        train_steps=5000,
        learning_rate=1e-3,
        lr_schedule="cosine",
        warmup_steps=500,
        grad_clip_norm=1.0,
        weight_decay=0.05,
        remat=True,
        augment=True,
        model_kwargs={"scan_blocks": True},
        sharding_rules="tp",
        mesh=MeshSpec(data=-1, model=2),
        ladder_devices=16,
    ),
    # 5e') config 5e with FSDP composed on top of TP: the `model` axis
    # takes the Megatron column/row split first, the FSDP shape rule then
    # shards each leaf's largest remaining free dim over `data` — params +
    # slots are 1/(data*model)-th per chip where both apply.
    "vit_tiny_cifar_fsdp_tp": Config(
        name="vit_tiny_cifar_fsdp_tp",
        model="vit_tiny",
        dataset="cifar10",
        batch_size=1024,
        train_steps=5000,
        learning_rate=1e-3,
        lr_schedule="cosine",
        warmup_steps=500,
        grad_clip_norm=1.0,
        weight_decay=0.05,
        remat=True,
        augment=True,
        model_kwargs={"scan_blocks": True},
        sharding_rules="fsdp_tp",
        mesh=MeshSpec(data=-1, model=2),
        ladder_devices=16,
    ),
    # 5f) config 5 with ring attention over a 2-way `seq` axis (blockwise
    # K/V rotation around the ICI ring — parallel/ring_attention.py).
    "vit_tiny_cifar_ring": Config(
        name="vit_tiny_cifar_ring",
        model="vit_tiny",
        dataset="cifar10",
        batch_size=1024,
        train_steps=5000,
        learning_rate=1e-3,
        lr_schedule="cosine",
        warmup_steps=500,
        grad_clip_norm=1.0,
        weight_decay=0.05,
        remat=True,
        augment=True,
        model_kwargs={"attention_impl": "ring", "pool": "mean",
                      "scan_blocks": True},
        mesh=MeshSpec(data=-1, seq=2),
        ladder_devices=16,
    ),
    # 5f') config 5f with the Pallas kernel as the ring's LOCAL block
    # engine (flash_attention_lse's merge-ready (out, lse) pair feeding the
    # blockwise-LSE accumulator): the composed long-context configuration —
    # O(S_local) HBM from the ring AND VMEM score tiles from the kernel.
    "vit_tiny_cifar_ring_flash": Config(
        name="vit_tiny_cifar_ring_flash",
        model="vit_tiny",
        dataset="cifar10",
        batch_size=1024,
        train_steps=5000,
        learning_rate=1e-3,
        lr_schedule="cosine",
        warmup_steps=500,
        grad_clip_norm=1.0,
        weight_decay=0.05,
        remat=True,
        augment=True,
        model_kwargs={"attention_impl": "ring_flash", "pool": "mean",
                      "scan_blocks": True},
        mesh=MeshSpec(data=-1, seq=2),
        ladder_devices=16,
    ),
    # 5g) config 5 with the Pallas flash-attention kernel (fused VMEM
    # softmax-attention, fwd + custom-VJP bwd — ops/pallas/flash_attention):
    # the single-chip kernel leg of SURVEY §5.7's blockwise-attention row
    # (ring/ulysses cover the sharded legs).
    "vit_tiny_cifar_flash": Config(
        name="vit_tiny_cifar_flash",
        model="vit_tiny",
        dataset="cifar10",
        batch_size=1024,
        train_steps=5000,
        learning_rate=1e-3,
        lr_schedule="cosine",
        warmup_steps=500,
        grad_clip_norm=1.0,
        weight_decay=0.05,
        remat=True,
        augment=True,
        model_kwargs={"attention_impl": "flash", "scan_blocks": True},
        mesh=MeshSpec(data=-1),
        ladder_devices=16,
    ),
    # 5d) config 5 with the block stack GPipe'd over a 4-stage `pipe` axis
    # (3 blocks per stage, microbatched activations around the ICI ring —
    # parallel/pipeline.py). Trains with the default dropout 0.1 like its
    # siblings: the schedule threads a per-(shard, microbatch, stage) key.
    "vit_tiny_cifar_pp": Config(
        name="vit_tiny_cifar_pp",
        model="vit_tiny",
        dataset="cifar10",
        batch_size=1024,
        train_steps=5000,
        learning_rate=1e-3,
        lr_schedule="cosine",
        warmup_steps=500,
        grad_clip_norm=1.0,
        weight_decay=0.05,
        remat=True,
        augment=True,
        model_kwargs={"scan_blocks": True, "block_pipeline": 4},
        mesh=MeshSpec(data=-1, pipe=4),
        ladder_devices=16,
    ),
}


def get_config(name: str, **overrides) -> Config:
    if name not in CONFIGS:
        raise KeyError(f"unknown config {name!r}; have {sorted(CONFIGS)}")
    cfg = CONFIGS[name]
    return dataclasses.replace(cfg, **overrides) if overrides else cfg


ELASTIC_BATCH_POLICIES = ("keep_global", "scale_lr")


def apply_elastic_policy(
    cfg: Config, baseline_devices: int, current_devices: int
) -> Config:
    """Resolve the global-batch policy for an elastically resized mesh.

    `batch_size` is GLOBAL everywhere in this repo, so under keep_global
    (the default) a shrink needs no config change at all — `data/` slices
    the same global batch across fewer devices and the optimizer sees an
    identical gradient estimate; that invariance is what makes the
    post-resize trajectory comparable to the unshrunken run's
    hyperparameters. scale_lr is for models where the per-device batch
    growth itself is the problem (activation memory): the returned config
    records learning_rate scaled by current/baseline, so the decision is
    IN the config object the run logs, not an untracked runtime side
    effect.
    """
    if cfg.elastic_batch_policy not in ELASTIC_BATCH_POLICIES:
        raise ValueError(
            f"unknown elastic_batch_policy {cfg.elastic_batch_policy!r}; "
            f"one of {ELASTIC_BATCH_POLICIES}"
        )
    if baseline_devices <= 0 or current_devices == baseline_devices:
        return cfg
    if cfg.elastic_batch_policy == "keep_global":
        return cfg
    return dataclasses.replace(
        cfg,
        learning_rate=cfg.learning_rate * current_devices / baseline_devices,
    )
