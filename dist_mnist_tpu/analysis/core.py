"""graftlint core: one parse, many visitors.

The engine owns everything rule-independent so each rule stays a small
AST (or cross-artifact) check:

- **SourceFile** — a file parsed ONCE (`ast` tree + comment-derived
  suppression table); every rule sees the same parse, so a full-tree run
  is one `ast.parse` per file no matter how many rules are active.
- **Suppressions** — ``# lint: ok[rule-id] reason`` blesses its own line
  and the line below (marker-above style for statements that would
  overflow the line). Several ids may share one marker
  (``ok[rule-a,rule-b]``). The legacy ``# host-sync-ok: reason`` marker
  from scripts/check_host_sync.py is honored as ``ok[host-sync]`` so the
  shim CLI keeps its contract. A marker with NO reason is itself a
  finding (`suppression-hygiene`): the reason is the reviewable artifact.
- **Baseline** — see `baseline.py`: grandfathered findings, each entry
  carrying a reason, matched by (rule, path, message substring) so line
  drift doesn't invalidate entries.

Rules implement the tiny `Rule` protocol below and register in
`rules/__init__.py`. Nothing in this package may import jax: the suite
must run (and finish in seconds) on a machine with no accelerator stack
warmed up.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import Callable, Iterable

SUPPRESS_RE = re.compile(r"lint:\s*ok\[([a-z0-9_,\- ]+)\]\s*(.*)")
LEGACY_HOST_SYNC_RE = re.compile(r"host-sync-ok:?\s*(.*)")
#: tag/event hygiene shared by the drift rules and the obs test suite
TAG_RE = re.compile(r"^[a-z0-9_/.]+$")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Suppression:
    line: int  # the comment's own line; blesses `line` and `line + 1`
    rules: frozenset[str]
    reason: str
    legacy: bool = False


class SourceFile:
    """One file, parsed once: `tree` (None on syntax error) + the
    suppression table mined from its comment tokens."""

    def __init__(self, path: Path, rel: str):
        self.path = path
        self.rel = rel
        self.text = path.read_text()
        self.lines = self.text.splitlines(keepends=True)
        try:
            self.tree: ast.Module | None = ast.parse(self.text)
            self.parse_error: str | None = None
        except SyntaxError as err:
            self.tree = None
            self.parse_error = f"unparseable: {err}"
        self.suppressions: list[Suppression] = []
        self._blessed: dict[int, set[str]] = {}
        self._scan_comments()

    def _scan_comments(self) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.text).readline)
            comments = [t for t in tokens if t.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return
        for tok in comments:
            m = SUPPRESS_RE.search(tok.string)
            if m:
                rules = frozenset(
                    r.strip() for r in m.group(1).split(",") if r.strip())
                sup = Suppression(tok.start[0], rules, m.group(2).strip())
            else:
                m = LEGACY_HOST_SYNC_RE.search(tok.string)
                if not m:
                    continue
                sup = Suppression(tok.start[0], frozenset({"host-sync"}),
                                  m.group(1).strip(), legacy=True)
            self.suppressions.append(sup)
            for line in (sup.line, sup.line + 1):
                self._blessed.setdefault(line, set()).update(sup.rules)

    def is_suppressed(self, rule: str, line: int) -> bool:
        return rule in self._blessed.get(line, ())

    def finding(self, rule: str, node_or_line, message: str) -> Finding:
        line = getattr(node_or_line, "lineno", node_or_line)
        return Finding(rule, self.rel, int(line), message)


class Context:
    """Everything a rule may look at: the repo tree, the shared parse
    cache, and the package file list. Cross-artifact rules read docs and
    shell scripts through `read_text` so even non-Python artifacts go
    through one access point (and one place to handle absence)."""

    def __init__(self, repo_root: Path, package: str = "dist_mnist_tpu"):
        self.repo_root = Path(repo_root)
        self.package = package
        self._cache: dict[str, SourceFile] = {}

    # -- files ---------------------------------------------------------------
    def source(self, rel: str | Path) -> SourceFile | None:
        rel = str(Path(rel).as_posix())
        if rel not in self._cache:
            path = self.repo_root / rel
            if not path.is_file():
                return None
            self._cache[rel] = SourceFile(path, rel)
        return self._cache[rel]

    def package_files(self) -> list[str]:
        pkg = self.repo_root / self.package
        out = []
        for p in sorted(pkg.rglob("*.py")):
            rel = p.relative_to(self.repo_root).as_posix()
            if "analysis/" in rel:
                continue  # the linter doesn't lint itself for hot-path rules
            out.append(rel)
        return out

    def package_sources(self) -> Iterable[SourceFile]:
        for rel in self.package_files():
            sf = self.source(rel)
            if sf is not None:
                yield sf

    def read_text(self, rel: str) -> str | None:
        path = self.repo_root / rel
        return path.read_text() if path.is_file() else None


class Rule:
    """Protocol-by-convention: subclasses set `rule_id`/`doc` and
    implement `check`. Kept as a base class (not typing.Protocol) so the
    registry can assert isinstance at import time."""

    rule_id: str = ""
    doc: str = ""

    def check(self, ctx: Context) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError


def suppression_hygiene(ctx: Context,
                        files: Iterable[SourceFile]) -> list[Finding]:
    """Reasonless markers are findings themselves: a suppression without
    a why is just a louder way to disable the lint."""
    out = []
    for sf in files:
        for sup in sf.suppressions:
            if not sup.reason:
                marker = ("# host-sync-ok:" if sup.legacy
                          else "# lint: ok[...]")
                out.append(sf.finding(
                    "suppression-hygiene", sup.line,
                    f"suppression `{marker}` carries no reason; write "
                    f"`# lint: ok[rule-id] <why>`"))
    return out


def run(ctx: Context, rules: list[Rule], *,
        changed_only: Callable[[str], bool] | None = None) -> dict:
    """Run `rules`, apply suppressions, and return the raw result dict
    (baseline partitioning happens in cli.py, where the baseline file is
    resolved). `changed_only` filters findings by path AFTER the rules
    ran — cross-artifact rules need the whole tree to compute drift even
    when only one artifact changed."""
    findings: list[Finding] = []
    suppressed = 0
    for rule in rules:
        findings.extend(rule.check(ctx))
    findings.extend(
        suppression_hygiene(ctx, list(ctx._cache.values())))
    kept = []
    for f in findings:
        sf = ctx.source(f.path)
        if sf is not None and sf.is_suppressed(f.rule, f.line):
            suppressed += 1
            continue
        kept.append(f)
    if changed_only is not None:
        kept = [f for f in kept if changed_only(f.path)]
    kept.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return {"findings": kept, "suppressed": suppressed,
            "rules": [r.rule_id for r in rules]}


# -- small AST helpers shared by rules ----------------------------------------

def call_name(node: ast.Call) -> tuple[str | None, bool]:
    """(name, is_method) for a call: `f(...)` -> ("f", False),
    `x.f(...)` -> ("f", True), anything else -> (None, False)."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id, False
    if isinstance(fn, ast.Attribute):
        return fn.attr, True
    return None, False


def const_str(node: ast.AST | None) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def str_prefix(node: ast.AST | None) -> tuple[str | None, bool]:
    """(prefix, exact): a literal string gives (value, True); an f-string
    with a leading constant gives (that constant, False); else (None, _).
    Rules use the inexact prefix for namespace checks on tags like
    f"memory/{k}"."""
    s = const_str(node)
    if s is not None:
        return s, True
    if (isinstance(node, ast.JoinedStr) and node.values
            and isinstance(node.values[0], ast.Constant)
            and isinstance(node.values[0].value, str)
            and node.values[0].value):
        return node.values[0].value, False
    return None, False


def node_source(sf: SourceFile, node: ast.AST) -> str:
    """ast.get_source_segment, but against the file's cached line list —
    the stock helper re-splits the whole file per call, which made the
    spmd rule (one call per `if` in the package) the runtime hot spot."""
    try:
        sl, sc = node.lineno - 1, node.col_offset
        el, ec = node.end_lineno - 1, node.end_col_offset
    except AttributeError:
        return ""
    lines = sf.lines
    if sl == el:
        return lines[sl][sc:ec]
    return lines[sl][sc:] + "".join(lines[sl + 1:el]) + lines[el][:ec]
