"""bench-stages: bench.py modes stay wired into both measurement scripts.

Every measurement-day battery script must know every bench mode, or a
subsystem silently stops being measured: `measure_all.sh` runs the full
battery and `retry_missed_stages.sh` re-runs the catch-up pass, and each
new `bench.py --<stage>` flag historically had to be added to BOTH by
hand (PR 9's regression gate reads whichever artifacts they produce).

The rule parses bench.py's argparse calls for `store_true` mode flags
and checks each appears (as a ``--flag`` occurrence) in both scripts.
A flag that is deliberately NOT a battery stage (a parameterization of
another stage) belongs in the committed baseline with its reason — that
is the allowlist for this rule.

The reverse direction catches typos: every ``bench.py ... --x`` flag the
scripts pass must be one bench.py actually defines.
"""

from __future__ import annotations

import ast
import re

from dist_mnist_tpu.analysis.core import Context, Finding, Rule, const_str

BENCH_PATH = "bench.py"
SCRIPTS = ("scripts/measure_all.sh", "scripts/retry_missed_stages.sh")
_SH_BENCH_LINE = re.compile(r"python bench\.py([^\n]*)")
_SH_FLAG = re.compile(r"--([a-z][a-z0-9-]*)")


def bench_store_true_flags(ctx: Context) -> dict[str, int]:
    """{--flag: lineno} for bench.py's `store_true` arguments."""
    sf = ctx.source(BENCH_PATH)
    if sf is None or sf.tree is None:
        return {}
    out: dict[str, int] = {}
    for node in ast.walk(sf.tree):
        if (not isinstance(node, ast.Call)
                or not isinstance(node.func, ast.Attribute)
                or node.func.attr != "add_argument"):
            continue
        action = next((const_str(kw.value) for kw in node.keywords
                       if kw.arg == "action"), None)
        if action != "store_true":
            continue
        for arg in node.args:
            s = const_str(arg)
            if s and s.startswith("--"):
                out[s] = node.lineno
    return out


def bench_all_flags(ctx: Context) -> set[str]:
    sf = ctx.source(BENCH_PATH)
    if sf is None or sf.tree is None:
        return set()
    out: set[str] = set()
    for node in ast.walk(sf.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"):
            for arg in node.args:
                s = const_str(arg)
                if s and s.startswith("--"):
                    out.add(s)
    return out


def script_bench_flags(text: str) -> set[str]:
    flags: set[str] = set()
    for m in _SH_BENCH_LINE.finditer(text):
        flags.update(f"--{f}" for f in _SH_FLAG.findall(m.group(1)))
    return flags


class BenchStagesRule(Rule):
    rule_id = "bench-stages"
    doc = ("every bench.py store_true mode flag appears in measure_all.sh "
           "AND retry_missed_stages.sh (baseline = intentional "
           "parameterizations)")

    def check(self, ctx: Context) -> list[Finding]:
        modes = bench_store_true_flags(ctx)
        if not modes:
            return [Finding(self.rule_id, BENCH_PATH, 1,
                            "found no store_true flags in bench.py — "
                            "parser moved?")]
        all_flags = bench_all_flags(ctx)
        out: list[Finding] = []
        script_flags: dict[str, set[str]] = {}
        for rel in SCRIPTS:
            text = ctx.read_text(rel)
            if text is None:
                out.append(Finding(self.rule_id, rel, 1, "script missing"))
                continue
            script_flags[rel] = script_bench_flags(text)
        for flag, lineno in sorted(modes.items()):
            missing = [rel for rel, flags in script_flags.items()
                       if flag not in flags]
            if missing:
                out.append(Finding(
                    self.rule_id, BENCH_PATH, lineno,
                    f"bench mode {flag} is not exercised by "
                    f"{', '.join(missing)} — add a stage (or baseline it "
                    f"with the reason it is a parameterization, not a "
                    f"stage)"))
        # reverse: scripts must not pass flags bench.py doesn't define
        for rel, flags in script_flags.items():
            for flag in sorted(flags - all_flags):
                out.append(Finding(
                    self.rule_id, rel, 1,
                    f"{rel} passes {flag} to bench.py, which defines no "
                    f"such flag — typo'd or removed stage"))
        return out


RULE = BenchStagesRule()
