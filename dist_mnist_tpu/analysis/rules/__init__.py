"""graftlint rule registry.

A rule module exposes one or more `Rule` instances; list them here to
activate. `python -m dist_mnist_tpu.analysis --rules a,b` subsets by
`rule_id`. Adding a rule = new module + one registry line + a fixture
pair in tests/test_analysis.py (docs/ANALYSIS.md "Adding a rule").
"""

from __future__ import annotations

from dist_mnist_tpu.analysis.core import Rule
from dist_mnist_tpu.analysis.rules import (
    bench_stages,
    cache_key,
    host_sync,
    registry_drift,
    spmd_divergence,
    thread_lifecycle,
)

ALL_RULES: list[Rule] = [
    host_sync.RULE,
    spmd_divergence.RULE,
    cache_key.RULE,
    thread_lifecycle.RULE,
    registry_drift.RULE,
    registry_drift.METRIC_RULE,
    bench_stages.RULE,
]

RULE_IDS = [r.rule_id for r in ALL_RULES]

assert len(set(RULE_IDS)) == len(RULE_IDS), "duplicate rule ids"


def select(ids: list[str] | None) -> list[Rule]:
    if not ids:
        return list(ALL_RULES)
    unknown = set(ids) - set(RULE_IDS)
    if unknown:
        raise KeyError(
            f"unknown rule id(s) {sorted(unknown)}; have {RULE_IDS}")
    return [r for r in ALL_RULES if r.rule_id in ids]
