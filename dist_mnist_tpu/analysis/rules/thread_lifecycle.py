"""thread-lifecycle: every background thread is named, registered, and
has a shutdown path.

The conftest leak-check (tests/conftest.py `_no_leaked_prefetch_workers`)
is prefix-based: it can only catch a leaked thread whose name starts with
a registered prefix. A `threading.Thread(...)` created with no name (or
an unregistered one) is invisible to it — the exact blind spot every new
subsystem re-creates. Three checks per instantiation in the package:

1. **named** — the constructor passes ``name=`` with a resolvable
   literal prefix (a plain string, an f-string's leading constant, a
   module-level ``THREAD_NAME_PREFIX``, or a parameter's string
   default).
2. **registered** — that prefix matches one of the ``startswith(...)``
   prefixes the conftest leak-check polls for.
3. **joinable** — the enclosing class has a shutdown-shaped method
   (close/stop/shutdown/drain/wait/join/__exit__), or, for threads built
   outside a class, the enclosing function joins a thread itself.

The registry is parsed FROM tests/conftest.py, so adding a prefix there
is the single source of truth — this rule can never drift from what the
leak-check actually polices.
"""

from __future__ import annotations

import ast

from dist_mnist_tpu.analysis.core import (
    Context, Finding, Rule, SourceFile, const_str)

CONFTEST_PATH = "tests/conftest.py"
SHUTDOWN_METHODS = frozenset({
    "close", "stop", "shutdown", "drain", "wait", "join", "__exit__",
})
#: data/prefetch.py exports the prefix conftest imports; resolve both ends
PREFIX_VAR = "THREAD_NAME_PREFIX"


def conftest_prefixes(ctx: Context) -> set[str]:
    """Every literal prefix the leak-check polls via `startswith`, plus
    the resolved THREAD_NAME_PREFIX constants it imports."""
    prefixes: set[str] = set()
    sf = ctx.source(CONFTEST_PATH)
    if sf is None or sf.tree is None:
        return prefixes
    for node in ast.walk(sf.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "startswith"):
            for arg in node.args:
                s = const_str(arg)
                if s:
                    prefixes.add(s)
    # conftest imports data.prefetch's THREAD_NAME_PREFIX; the snapshot
    # writer defines its own — both are registered via their values
    for rel in ("dist_mnist_tpu/data/prefetch.py",
                "dist_mnist_tpu/checkpoint/snapshot.py"):
        val = _module_prefix_value(ctx.source(rel))
        if val:
            prefixes.add(val)
    return prefixes


def _module_prefix_value(sf: SourceFile | None) -> str | None:
    if sf is None or sf.tree is None:
        return None
    for node in sf.tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == PREFIX_VAR:
                    return const_str(node.value)
    return None


def _resolve_name(sf: SourceFile, call: ast.Call,
                  enclosing: list[ast.AST]) -> str | None:
    """Best-effort literal prefix of the `name=` kwarg."""
    name_kw = next((kw.value for kw in call.keywords if kw.arg == "name"),
                   None)
    if name_kw is None:
        return None
    s = const_str(name_kw)
    if s is not None:
        return s
    if isinstance(name_kw, ast.JoinedStr):
        parts = []
        for v in name_kw.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
                continue
            if (isinstance(v, ast.FormattedValue)
                    and isinstance(v.value, ast.Name)):
                resolved = _resolve_variable(sf, v.value.id, enclosing)
                if resolved is not None:
                    parts.append(resolved)
                    continue
            break  # first unresolvable piece ends the literal prefix
        return "".join(parts) or None
    if isinstance(name_kw, ast.Name):
        return _resolve_variable(sf, name_kw.id, enclosing)
    return None


def _resolve_variable(sf: SourceFile, var: str,
                      enclosing: list[ast.AST]) -> str | None:
    """Resolve `var` to a string: module-level assign, or the string
    default of a parameter of the enclosing function."""
    if var == PREFIX_VAR:
        return _module_prefix_value(sf)
    for node in reversed(enclosing):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = node.args
            pos = args.posonlyargs + args.args
            defaults = args.defaults
            for a, d in zip(pos[len(pos) - len(defaults):], defaults):
                if a.arg == var:
                    return const_str(d)
            for a, d in zip(args.kwonlyargs, args.kw_defaults):
                if a.arg == var and d is not None:
                    return const_str(d)
    if sf.tree is not None:
        for node in sf.tree.body:
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == var:
                        return const_str(node.value)
    return None


def _has_shutdown_path(enclosing: list[ast.AST]) -> bool:
    # a thread built inside a method belongs to the CLASS's lifecycle:
    # prefer the nearest enclosing ClassDef over the method itself
    for node in reversed(enclosing):
        if isinstance(node, ast.ClassDef):
            return any(
                isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
                and m.name in SHUTDOWN_METHODS
                for m in node.body)
    for node in reversed(enclosing):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # function-local thread: require a .join( somewhere in it
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "join"):
                    return True
            return False
    return False


def _thread_calls(sf: SourceFile):
    """Yield (call, enclosing_stack) for threading.Thread(...) /
    Thread(...) instantiations."""
    if sf.tree is None:
        return

    stack: list[ast.AST] = []

    def walk(node: ast.AST):
        is_scope = isinstance(
            node, (ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef))
        if is_scope:
            stack.append(node)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.Call):
                fn = child.func
                if ((isinstance(fn, ast.Attribute) and fn.attr == "Thread")
                        or (isinstance(fn, ast.Name)
                            and fn.id == "Thread")):
                    yield child, list(stack)
            yield from walk(child)
        if is_scope:
            stack.pop()

    yield from walk(sf.tree)


def _thread_subclasses(sf: SourceFile):
    if sf.tree is None:
        return
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ClassDef):
            for base in node.bases:
                base_name = (base.attr if isinstance(base, ast.Attribute)
                             else base.id if isinstance(base, ast.Name)
                             else None)
                if base_name == "Thread":
                    yield node


def scan_source(sf: SourceFile, prefixes: set[str]) -> list[Finding]:
    out = []
    for cls in _thread_subclasses(sf):
        if not any(isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
                   and m.name in SHUTDOWN_METHODS for m in cls.body):
            out.append(sf.finding(
                "thread-lifecycle", cls,
                f"threading.Thread subclass {cls.name} defines no "
                f"close/stop/shutdown/drain/wait/join method"))
    for call, enclosing in _thread_calls(sf):
        name = _resolve_name(sf, call, enclosing)
        if name is None:
            out.append(sf.finding(
                "thread-lifecycle", call,
                "thread has no resolvable literal `name=` — the conftest "
                "leak-check is prefix-based and cannot see unnamed "
                "threads; name it with a registered prefix"))
        elif not any(name.startswith(p) for p in prefixes):
            out.append(sf.finding(
                "thread-lifecycle", call,
                f"thread name {name!r} matches no prefix polled by "
                f"{CONFTEST_PATH}'s leak-check — a leak here is "
                f"invisible to tier-1; register the prefix there"))
        if not _has_shutdown_path(enclosing):
            out.append(sf.finding(
                "thread-lifecycle", call,
                "no shutdown path: the enclosing class has no "
                "close/stop/shutdown/drain/wait/join method and the "
                "enclosing function never joins a thread"))
    return out


class ThreadLifecycleRule(Rule):
    rule_id = "thread-lifecycle"
    doc = ("background threads must carry a conftest-registered name "
           "prefix and a close/join path")

    def check(self, ctx: Context) -> list[Finding]:
        prefixes = conftest_prefixes(ctx)
        if not prefixes:
            return [Finding(self.rule_id, CONFTEST_PATH, 1,
                            "could not parse any leak-check prefixes")]
        out: list[Finding] = []
        for sf in ctx.package_sources():
            out.extend(scan_source(sf, prefixes))
        return out


RULE = ThreadLifecycleRule()
