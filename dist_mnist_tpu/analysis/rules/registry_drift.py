"""journal-drift / metric-drift: code and docs/OBSERVABILITY.md agree.

docs/OBSERVABILITY.md carries two contracts as markdown tables: the
journal **event vocabulary** ("Event vocabulary and emitters") and the
**metric namespace** table. Both drifted after PRs 10-13 added events
and series faster than the tables grew rows. Two rule ids, one module:

- ``journal-drift`` — every literal event name passed to
  ``events.emit("...")`` / ``journal.emit("...")`` must appear in the
  event table, and every documented event must still exist in code
  (documented-but-dead names rot the doc's authority). Names must obey
  the tag hygiene charset ``^[a-z0-9_/.]+$``.
- ``metric-drift`` — every literal metric tag fed to a writer/registry
  sink (``scalar``/``histogram``/``attach_histogram``/``gauge``/
  ``counter`` first args, and literal dict keys passed straight to
  ``scalars``/``set_scalars``) must match a documented name or a
  documented ``ns/*`` wildcard. f-string tags check their leading
  constant prefix. The reverse check is deliberately lenient: a
  documented name/namespace is "live" if ANY string literal in the
  package equals it or starts with the wildcard's prefix — most hook
  tags are built in dicts the forward scan can't see.

Doc parsing keys on backtick spans inside table rows, so prose around
the names can change freely; only the `code`-quoted vocabulary binds.
"""

from __future__ import annotations

import ast
import re

from dist_mnist_tpu.analysis.core import (
    TAG_RE, Context, Finding, Rule, str_prefix)

DOC_PATH = "docs/OBSERVABILITY.md"
EVENT_TABLE_HEADER = "| event | emitter |"
METRIC_TABLE_HEADER = "| namespace | source |"
SINKS_FIRST_ARG = frozenset({
    "scalar", "histogram", "attach_histogram", "gauge", "counter"})
SINKS_DICT_ARG = frozenset({"scalars", "set_scalars"})
_BACKTICK_RE = re.compile(r"`([^`]+)`")
#: names render with glob stars in the doc (`fleet/*`); events never do
_NAME_OK = re.compile(r"^[a-z0-9_/.*]+$")


def _table_rows(text: str, header: str) -> list[tuple[int, str]]:
    """(lineno, first_cell) per data row of the table whose header row
    starts with `header`."""
    rows = []
    in_table = False
    for i, line in enumerate(text.splitlines(), 1):
        if line.startswith(header):
            in_table = True
            continue
        if in_table:
            if not line.startswith("|"):
                break
            cells = line.split("|")
            if len(cells) > 1 and not set(cells[1].strip()) <= {"-"}:
                rows.append((i, cells[1]))
    return rows


def _doc_names(text: str, header: str) -> dict[str, int]:
    """{backticked-name: lineno} from a table's first column."""
    out: dict[str, int] = {}
    for lineno, cell in _table_rows(text, header):
        for name in _BACKTICK_RE.findall(cell):
            name = name.strip()
            if _NAME_OK.match(name):
                out.setdefault(name, lineno)
    return out


# -- code-side collection -----------------------------------------------------

def _emit_event_names(ctx: Context) -> list[tuple[str, str, int, bool]]:
    """(name_or_prefix, path, line, exact) for literal first args of
    `emit(...)` calls (module fn or any `.emit(` method)."""
    out = []
    for sf in ctx.package_sources():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            name = (fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute) else None)
            if name != "emit":
                continue
            s, exact = str_prefix(node.args[0])
            if s is not None:
                out.append((s, sf.rel, node.lineno, exact))
    return out


def _metric_tags(ctx: Context) -> list[tuple[str, str, int, bool]]:
    out = []
    for sf in ctx.package_sources():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            name = (fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute) else None)
            if name in SINKS_FIRST_ARG:
                s, exact = str_prefix(node.args[0])
                if s is not None:
                    out.append((s, sf.rel, node.lineno, exact))
            elif name in SINKS_DICT_ARG and isinstance(node.args[0], ast.Dict):
                for key in node.args[0].keys:
                    s, exact = str_prefix(key)
                    if s is not None:
                        out.append((s, sf.rel, node.lineno, exact))
    return out


def _all_string_literals(ctx: Context) -> set[str]:
    """Every string constant + f-string leading constant in the package
    (the lenient liveness oracle for documented metric names)."""
    out: set[str] = set()
    for sf in ctx.package_sources():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                out.add(node.value)
            elif isinstance(node, ast.JoinedStr) and node.values:
                first = node.values[0]
                if (isinstance(first, ast.Constant)
                        and isinstance(first.value, str)):
                    out.add(first.value + "\x00prefix")
    return out


def _matches_doc(tag: str, exact: bool, doc: dict[str, int]) -> bool:
    for name in doc:
        if name.endswith("*"):
            if tag.startswith(name[:-1]):
                return True
        elif exact and tag == name:
            return True
        elif not exact and name.startswith(tag):
            # an f-string prefix like "fleet/latency_ms_" may sit under a
            # longer documented pattern; accept when either contains the
            # other up to the wildcard
            return True
    return False


class JournalDriftRule(Rule):
    rule_id = "journal-drift"
    doc = ("journal event names in code vs docs/OBSERVABILITY.md's event "
           "table (both directions + charset hygiene)")

    def check(self, ctx: Context) -> list[Finding]:
        text = ctx.read_text(DOC_PATH)
        if text is None:
            return [Finding(self.rule_id, DOC_PATH, 1, "doc missing")]
        documented = _doc_names(text, EVENT_TABLE_HEADER)
        if not documented:
            return [Finding(self.rule_id, DOC_PATH, 1,
                            "could not parse the event table")]
        out: list[Finding] = []
        emitted: set[str] = set()
        for name, path, line, exact in _emit_event_names(ctx):
            if not exact:
                continue  # dynamic event names: nothing checkable
            emitted.add(name)
            if not TAG_RE.match(name):
                out.append(Finding(
                    self.rule_id, path, line,
                    f"event name {name!r} violates the hygiene charset "
                    f"^[a-z0-9_/.]+$"))
            elif name not in documented:
                out.append(Finding(
                    self.rule_id, path, line,
                    f"event {name!r} is emitted here but missing from "
                    f"{DOC_PATH}'s event table — add a row (event, "
                    f"emitter, payload)"))
        for name, lineno in sorted(documented.items()):
            if name not in emitted:
                out.append(Finding(
                    self.rule_id, DOC_PATH, lineno,
                    f"documented event {name!r} is emitted nowhere in "
                    f"the package — dead row, or the emitter renamed it"))
        return out


class MetricDriftRule(Rule):
    rule_id = "metric-drift"
    doc = ("literal metric tags in code vs docs/OBSERVABILITY.md's "
           "namespace table (forward: strict; reverse: liveness)")

    def check(self, ctx: Context) -> list[Finding]:
        text = ctx.read_text(DOC_PATH)
        if text is None:
            return [Finding(self.rule_id, DOC_PATH, 1, "doc missing")]
        documented = _doc_names(text, METRIC_TABLE_HEADER)
        if not documented:
            return [Finding(self.rule_id, DOC_PATH, 1,
                            "could not parse the metric namespace table")]
        out: list[Finding] = []
        for tag, path, line, exact in _metric_tags(ctx):
            if exact and not TAG_RE.match(tag):
                out.append(Finding(
                    self.rule_id, path, line,
                    f"metric tag {tag!r} violates the hygiene charset "
                    f"^[a-z0-9_/.]+$"))
            elif not _matches_doc(tag, exact, documented):
                out.append(Finding(
                    self.rule_id, path, line,
                    f"metric tag {tag!r} matches no namespace in "
                    f"{DOC_PATH}'s table — add it (or its `ns/*` row)"))
        literals = _all_string_literals(ctx)
        prefixes = {s[:-len("\x00prefix")] for s in literals
                    if s.endswith("\x00prefix")}
        plain = {s for s in literals if not s.endswith("\x00prefix")}
        for name, lineno in sorted(documented.items()):
            if name.endswith("*"):
                stem = name[:-1]
                live = (any(s.startswith(stem) for s in plain)
                        or any(p.startswith(stem) or stem.startswith(p)
                               for p in prefixes if p))
            else:
                live = name in plain or any(
                    name.startswith(p) for p in prefixes if p)
            if not live:
                out.append(Finding(
                    self.rule_id, DOC_PATH, lineno,
                    f"documented metric {name!r} has no trace in the "
                    f"package's string literals — dead row?"))
        return out


RULE = JournalDriftRule()
METRIC_RULE = MetricDriftRule()
