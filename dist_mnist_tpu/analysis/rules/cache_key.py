"""cache-key: every compile-relevant Config knob folds into the key.

PR 7 retrofitted the overlap knobs into `compile_cache_key_fields` by
hand after a stale serial executable could have served an overlapped
run. This rule makes the invariant structural: diff the fields of the
`Config` dataclass (configs.py) against the ``cfg.<field>`` reads inside
`compilecache/key_fields.py compile_cache_key_fields` (moved out of
cli/train.py so serve/tune processes can import it without re-running
the train CLI's flag definitions). A field that is neither read by
the key builder nor on the explicit runtime-only allowlist is a finding
— new knobs default to "invalidates the cache" until someone argues
otherwise IN the allowlist, with a reason.

Why the default is compile-relevant: most Config scalars are closed over
by the jitted step (learning-rate schedules bake their constants,
grad-clip/weight-decay change the optimizer chain's structure), so a
cache hit across a changed value silently runs the OLD program with the
old constant — the numbers drift, nothing crashes.

A second, narrower check pins the serve path: `serve/engine.py` must
mention "quant" in both its in-memory and disk key builders (PR 13's
invariant — an int8 program can never satisfy a float key).

A third check closes the same loop over the autotuner's knob catalog
(`tune/spec.py`): a `TunableSpec` declared ``compile_relevant=True``
must have every stored knob name appear in `compile_cache_key_fields`
(as a ``cfg.<name>`` read OR a dict-literal key — `scan_chunk` is keyed
as a builder parameter, not a Config field), so a tuner-applied value
always forces an executable-store miss; one declared
``compile_relevant=False`` must be allowlisted in TUNER_RUNTIME_ONLY
with a reason. Tuner knobs are NOT all Config fields (prefetch_depth
and the serve grid are CLI-flag surfaces), hence the separate allowlist:
folding them into RUNTIME_ONLY would trip its staleness check.
"""

from __future__ import annotations

import ast

from dist_mnist_tpu.analysis.core import Context, Finding, Rule

CONFIGS_PATH = "dist_mnist_tpu/configs.py"
KEY_BUILDER_PATH = "dist_mnist_tpu/compilecache/key_fields.py"
KEY_BUILDER_FN = "compile_cache_key_fields"
ENGINE_PATH = "dist_mnist_tpu/serve/engine.py"

#: runtime-only knobs: change the run, not the compiled program.
#: Every entry carries its why — this allowlist is the reviewable
#: artifact, exactly like a suppression reason.
RUNTIME_ONLY: dict[str, str] = {
    "name": "already folded as the key's `config` field",
    "eval_every": "hook cadence; never traced",
    "log_every": "hook cadence; never traced",
    "checkpoint_every_secs": "saver cadence; never traced",
    "elastic_batch_policy": "resolved pre-run into batch_size/learning_rate,"
                            " which ARE keyed",
    "seed": "changes initial weights (data), not the traced program",
    "ladder_devices": "bench-ladder sizing metadata; never traced",
    "mesh": "the LIVE mesh shape is keyed from the constructed Mesh "
            "argument instead (a MeshSpec of -1s is unresolved)",
}

TUNE_SPEC_PATH = "dist_mnist_tpu/tune/spec.py"

#: runtime-only TUNER knobs (tune/spec.py compile_relevant=False):
#: applied by --tuned=auto without invalidating any compiled step.
#: Same contract as RUNTIME_ONLY — every entry argues its why — but a
#: separate dict because these are knob names, not Config fields, and
#: RUNTIME_ONLY's staleness check diffs against the Config dataclass.
TUNER_RUNTIME_ONLY: dict[str, str] = {
    "prefetch_depth": "host-side prefetch ring depth (data/prefetch.py);"
                      " the traced program is identical at every depth",
    "serve_max_batch": "shapes the serve zoo's (batch, seq) grid; every"
                       " grid cell compiles under its own zoo executable"
                       " key (serve/zoo.py), never the train-step key",
    "serve_seq_buckets": "same grid: per-bucket zoo keys absorb it",
    "snapshot_window": "host-side write-behind ring depth"
                       " (checkpoint/snapshot.py); the traced step never"
                       " sees the snapshot queue",
    "moe_capacity_factor": "serve-only knob: the zoo engine folds the"
                           " live factor into every per-cell executable"
                           " key (serve/engine.py _key/_store_key), so"
                           " it never touches the train-step key",
    "kv_page_tokens": "decode-serving only: the decode engine folds the"
                      " live page size into every per-cell executable key"
                      " (serve/decode.py _layout_key -> _key/_store_key),"
                      " never the train-step key",
    "decode_admit_buckets": "decode-serving only: each admit bucket IS a"
                            " ('prefill', n, s) cell in the decode grid,"
                            " compiled under its own executable key"
                            " (serve/decode.py _key); the train-step key"
                            " is never involved",
}


def _config_fields(ctx: Context) -> dict[str, int]:
    """{field: lineno} of the Config dataclass's annotated fields."""
    sf = ctx.source(CONFIGS_PATH)
    if sf is None or sf.tree is None:
        return {}
    for node in sf.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            return {
                stmt.target.id: stmt.lineno
                for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            }
    return {}


def _keyed_fields(ctx: Context) -> set[str] | None:
    """Config attributes the key builder reads (`cfg.X` anywhere in it)."""
    sf = ctx.source(KEY_BUILDER_PATH)
    if sf is None or sf.tree is None:
        return None
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.FunctionDef) and node.name == KEY_BUILDER_FN:
            reads = set()
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Attribute)
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id == "cfg"):
                    reads.add(sub.attr)
            return reads
    return None


def _key_literal_keys(ctx: Context) -> set[str]:
    """Dict-literal string keys inside the key builder — the payload
    entries that are builder parameters rather than ``cfg.`` reads
    (scan_chunk, input_pipeline, dtype...). Kept separate from
    `_keyed_fields` so the Config-field check's semantics are untouched:
    a Config field must be READ, not merely share a name with a key."""
    sf = ctx.source(KEY_BUILDER_PATH)
    if sf is None or sf.tree is None:
        return set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.FunctionDef) and node.name == KEY_BUILDER_FN:
            return {
                k.value
                for sub in ast.walk(node) if isinstance(sub, ast.Dict)
                for k in sub.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)
            }
    return set()


def _tunable_specs(ctx: Context) -> list[tuple[int, str, tuple, bool]]:
    """(lineno, spec name, stored knob names, compile_relevant) for every
    `TunableSpec(...)` registration in the tuner's knob catalog."""
    sf = ctx.source(TUNE_SPEC_PATH)
    if sf is None or sf.tree is None:
        return []
    out = []
    for node in ast.walk(sf.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "TunableSpec"):
            continue
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        name = kw.get("name")
        if not (isinstance(name, ast.Constant)
                and isinstance(name.value, str)):
            continue
        fields = kw.get("fields")
        knob_names = tuple(
            e.value for e in fields.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        ) if isinstance(fields, ast.Tuple) else (name.value,)
        relevant = kw.get("compile_relevant")
        out.append((node.lineno, name.value, knob_names,
                    bool(isinstance(relevant, ast.Constant)
                         and relevant.value)))
    return out


class CacheKeyRule(Rule):
    rule_id = "cache-key"
    doc = ("Config dataclass fields missing from compile_cache_key_fields "
           "and not allowlisted as runtime-only")

    def check(self, ctx: Context) -> list[Finding]:
        out: list[Finding] = []
        fields = _config_fields(ctx)
        keyed = _keyed_fields(ctx)
        if not fields:
            return [Finding(self.rule_id, CONFIGS_PATH, 1,
                            "could not locate the Config dataclass")]
        if keyed is None:
            return [Finding(self.rule_id, KEY_BUILDER_PATH, 1,
                            f"could not locate {KEY_BUILDER_FN}()")]
        for field, lineno in sorted(fields.items()):
            if field in keyed or field in RUNTIME_ONLY:
                continue
            out.append(Finding(
                self.rule_id, CONFIGS_PATH, lineno,
                f"Config.{field} is read by neither "
                f"{KEY_BUILDER_FN}() nor the RUNTIME_ONLY allowlist — a "
                f"cached executable compiled under a different "
                f"{field} would be served silently; fold it into the key "
                f"or allowlist it with a reason "
                f"(analysis/rules/cache_key.py)"))
        # stale allowlist entries: a field that vanished from Config
        for field in sorted(RUNTIME_ONLY):
            if field not in fields:
                out.append(Finding(
                    self.rule_id, CONFIGS_PATH, 1,
                    f"RUNTIME_ONLY allowlists {field!r}, which is no "
                    f"longer a Config field — drop the entry"))
        # tuner knob catalog: compile_relevant knobs must be keyed, the
        # rest must carry a reason in TUNER_RUNTIME_ONLY
        specs = _tunable_specs(ctx)
        literal_keys = _key_literal_keys(ctx)
        declared: set[str] = set()
        for lineno, spec_name, knob_names, relevant in specs:
            declared.update(knob_names)
            for knob in knob_names:
                if relevant and not (knob in keyed or knob in literal_keys):
                    out.append(Finding(
                        self.rule_id, TUNE_SPEC_PATH, lineno,
                        f"tunable {spec_name!r} declares {knob!r} "
                        f"compile-relevant but {KEY_BUILDER_FN}() neither "
                        f"reads cfg.{knob} nor keys a {knob!r} payload "
                        f"entry — a --tuned=auto run would reuse an "
                        f"executable compiled under the default"))
                elif not relevant and knob not in TUNER_RUNTIME_ONLY:
                    out.append(Finding(
                        self.rule_id, TUNE_SPEC_PATH, lineno,
                        f"tunable {spec_name!r} declares {knob!r} "
                        f"runtime-only but TUNER_RUNTIME_ONLY has no "
                        f"entry arguing why — add one "
                        f"(analysis/rules/cache_key.py)"))
        for knob in sorted(TUNER_RUNTIME_ONLY):
            if specs and knob not in declared:
                out.append(Finding(
                    self.rule_id, TUNE_SPEC_PATH, 1,
                    f"TUNER_RUNTIME_ONLY allowlists {knob!r}, which no "
                    f"TunableSpec declares any more — drop the entry"))
        # serve path: quant must stay folded into both engine key tiers
        engine = ctx.read_text(ENGINE_PATH)
        if engine is not None and engine.count("quant") < 2:
            out.append(Finding(
                self.rule_id, ENGINE_PATH, 1,
                "serve engine no longer folds `quant` into its cache "
                "keys — an int8 program could satisfy a float key"))
        return out


RULE = CacheKeyRule()
