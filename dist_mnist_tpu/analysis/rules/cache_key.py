"""cache-key: every compile-relevant Config knob folds into the key.

PR 7 retrofitted the overlap knobs into `compile_cache_key_fields` by
hand after a stale serial executable could have served an overlapped
run. This rule makes the invariant structural: diff the fields of the
`Config` dataclass (configs.py) against the ``cfg.<field>`` reads inside
`cli/train.py compile_cache_key_fields`. A field that is neither read by
the key builder nor on the explicit runtime-only allowlist is a finding
— new knobs default to "invalidates the cache" until someone argues
otherwise IN the allowlist, with a reason.

Why the default is compile-relevant: most Config scalars are closed over
by the jitted step (learning-rate schedules bake their constants,
grad-clip/weight-decay change the optimizer chain's structure), so a
cache hit across a changed value silently runs the OLD program with the
old constant — the numbers drift, nothing crashes.

A second, narrower check pins the serve path: `serve/engine.py` must
mention "quant" in both its in-memory and disk key builders (PR 13's
invariant — an int8 program can never satisfy a float key).
"""

from __future__ import annotations

import ast

from dist_mnist_tpu.analysis.core import Context, Finding, Rule

CONFIGS_PATH = "dist_mnist_tpu/configs.py"
KEY_BUILDER_PATH = "dist_mnist_tpu/cli/train.py"
KEY_BUILDER_FN = "compile_cache_key_fields"
ENGINE_PATH = "dist_mnist_tpu/serve/engine.py"

#: runtime-only knobs: change the run, not the compiled program.
#: Every entry carries its why — this allowlist is the reviewable
#: artifact, exactly like a suppression reason.
RUNTIME_ONLY: dict[str, str] = {
    "name": "already folded as the key's `config` field",
    "eval_every": "hook cadence; never traced",
    "log_every": "hook cadence; never traced",
    "checkpoint_every_secs": "saver cadence; never traced",
    "elastic_batch_policy": "resolved pre-run into batch_size/learning_rate,"
                            " which ARE keyed",
    "seed": "changes initial weights (data), not the traced program",
    "ladder_devices": "bench-ladder sizing metadata; never traced",
    "mesh": "the LIVE mesh shape is keyed from the constructed Mesh "
            "argument instead (a MeshSpec of -1s is unresolved)",
}


def _config_fields(ctx: Context) -> dict[str, int]:
    """{field: lineno} of the Config dataclass's annotated fields."""
    sf = ctx.source(CONFIGS_PATH)
    if sf is None or sf.tree is None:
        return {}
    for node in sf.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            return {
                stmt.target.id: stmt.lineno
                for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            }
    return {}


def _keyed_fields(ctx: Context) -> set[str] | None:
    """Config attributes the key builder reads (`cfg.X` anywhere in it)."""
    sf = ctx.source(KEY_BUILDER_PATH)
    if sf is None or sf.tree is None:
        return None
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.FunctionDef) and node.name == KEY_BUILDER_FN:
            reads = set()
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Attribute)
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id == "cfg"):
                    reads.add(sub.attr)
            return reads
    return None


class CacheKeyRule(Rule):
    rule_id = "cache-key"
    doc = ("Config dataclass fields missing from compile_cache_key_fields "
           "and not allowlisted as runtime-only")

    def check(self, ctx: Context) -> list[Finding]:
        out: list[Finding] = []
        fields = _config_fields(ctx)
        keyed = _keyed_fields(ctx)
        if not fields:
            return [Finding(self.rule_id, CONFIGS_PATH, 1,
                            "could not locate the Config dataclass")]
        if keyed is None:
            return [Finding(self.rule_id, KEY_BUILDER_PATH, 1,
                            f"could not locate {KEY_BUILDER_FN}()")]
        for field, lineno in sorted(fields.items()):
            if field in keyed or field in RUNTIME_ONLY:
                continue
            out.append(Finding(
                self.rule_id, CONFIGS_PATH, lineno,
                f"Config.{field} is read by neither "
                f"{KEY_BUILDER_FN}() nor the RUNTIME_ONLY allowlist — a "
                f"cached executable compiled under a different "
                f"{field} would be served silently; fold it into the key "
                f"or allowlist it with a reason "
                f"(analysis/rules/cache_key.py)"))
        # stale allowlist entries: a field that vanished from Config
        for field in sorted(RUNTIME_ONLY):
            if field not in fields:
                out.append(Finding(
                    self.rule_id, CONFIGS_PATH, 1,
                    f"RUNTIME_ONLY allowlists {field!r}, which is no "
                    f"longer a Config field — drop the entry"))
        # serve path: quant must stay folded into both engine key tiers
        engine = ctx.read_text(ENGINE_PATH)
        if engine is not None and engine.count("quant") < 2:
            out.append(Finding(
                self.rule_id, ENGINE_PATH, 1,
                "serve engine no longer folds `quant` into its cache "
                "keys — an int8 program could satisfy a float key"))
        return out


RULE = CacheKeyRule()
