"""spmd-divergence: collectives under per-process branches deadlock.

The gloo deadlock class PR 8 and PR 10 each hit once: under SPMD every
process must issue the same collective sequence, so a collective (or a
multihost orbax save/restore, which runs its own barrier collectives)
lexically nested under an ``if jax.process_index() == 0:`` /
``is_chief()`` / host-id / rank conditional hangs every OTHER process in
the collective until the heartbeat timeout. The classic shape:

    if jax.process_index() == 0:
        state = broadcast_one_to_all(state)   # only rank 0 arrives

The check is lexical on purpose: an early-``return`` guard
(``if process_index() != 0: return``) puts later collectives OUTSIDE the
``if`` body and is therefore fine, while both the body and the ``else``
arm of a rank conditional are flagged (one arm issuing a collective the
other doesn't is the same deadlock).

Checkpoint-manager ``.save``/``.restore`` attribute calls count only
when the receiver's source mentions a checkpoint-ish name — plain
``writer.save(...)`` on a rank guard is the chief-writes-summaries
pattern and is legal.
"""

from __future__ import annotations

import ast

from dist_mnist_tpu.analysis.core import (
    Context, Finding, Rule, SourceFile, call_name, node_source)

COLLECTIVES = frozenset({
    "psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "psum_scatter", "shard_map",
    "broadcast_one_to_all", "process_allgather", "sync_global_devices",
    "assert_equal",
})
#: attribute calls that are collective-bearing only on checkpoint-ish
#: receivers (orbax managers run barrier collectives internally)
CKPT_METHODS = frozenset({"save", "restore", "wait_until_finished"})
CKPT_RECEIVER_HINTS = ("ckpt", "checkpoint", "manager", "mngr", "orbax",
                       "snapshot")
RANK_MARKERS = ("process_index", "process_id", "host_id", "is_chief",
                "task_index", "rank")


def _is_rank_conditional(sf: SourceFile, test: ast.AST) -> bool:
    src = node_source(sf, test)
    return any(m in src for m in RANK_MARKERS)


def _collective_desc(sf: SourceFile, call: ast.Call) -> str | None:
    name, is_method = call_name(call)
    if name in COLLECTIVES:
        return f"{name}()"
    if name in CKPT_METHODS and is_method:
        recv = node_source(sf, call.func.value).lower()
        if any(h in recv for h in CKPT_RECEIVER_HINTS):
            return f"checkpoint {name}() (internal barrier collectives)"
    return None


class _Visitor(ast.NodeVisitor):
    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.stack: list[ast.If] = []
        self.findings: list[Finding] = []

    def visit_If(self, node: ast.If) -> None:
        ranked = _is_rank_conditional(self.sf, node.test)
        if ranked:
            self.stack.append(node)
        self.generic_visit(node)
        if ranked:
            self.stack.pop()

    def visit_Call(self, node: ast.Call) -> None:
        if self.stack:
            desc = _collective_desc(self.sf, node)
            if desc is not None:
                guard = node_source(self.sf, self.stack[-1].test)
                guard = " ".join(guard.split())[:60]
                self.findings.append(self.sf.finding(
                    "spmd-divergence", node,
                    f"{desc} under per-process branch `if {guard}:` — "
                    f"ranks that skip the branch never join the "
                    f"collective (deadlock); hoist it or annotate "
                    f"`# lint: ok[spmd-divergence] <why>`"))
        self.generic_visit(node)


def scan_source(sf: SourceFile) -> list[Finding]:
    if sf.tree is None:
        return []
    v = _Visitor(sf)
    v.visit(sf.tree)
    return v.findings


class SpmdDivergenceRule(Rule):
    rule_id = "spmd-divergence"
    doc = ("collectives / multihost checkpoint IO lexically nested under "
           "process_index()/host-id/rank conditionals")

    def check(self, ctx: Context) -> list[Finding]:
        out: list[Finding] = []
        for sf in ctx.package_sources():
            out.extend(scan_source(sf))
        return out


RULE = SpmdDivergenceRule()
