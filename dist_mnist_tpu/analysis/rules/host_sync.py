"""host-sync: no blocking device->host transfers in hot-path functions.

AST re-implementation of scripts/check_host_sync.py (which is now a thin
shim over this rule). Same flagged constructs — bare ``float(``,
``.item()`` methods, bare or qualified ``device_get(`` — but with real
scoping instead of whole-file token scanning:

- Only code INSIDE function/lambda bodies counts. Module-level calls run
  once at import, not per step; the tokenize version flagged them too,
  which is why its scope had to stay narrow. (A nested function inherits
  the hot-path verdict of its enclosing module either way.)
- Comments and docstrings can't trigger it by construction.

The scanned module set is the same curated hot-path list the tokenize
lint grew PR over PR (train/, faults/, the prefetch worker, hook cadence
paths, the overlap schedule, and the serve dispatch/load paths); it
lives here now as `HOT_PATH_TARGETS`.

Suppress with ``# lint: ok[host-sync] <why>`` (the legacy
``# host-sync-ok: <why>`` marker is still honored for the shim CLI).
"""

from __future__ import annotations

import ast
from pathlib import Path

from dist_mnist_tpu.analysis.core import (
    Context, Finding, Rule, SourceFile, call_name)

ANY_NAMES = ("device_get",)     # bare or attribute-qualified
BARE_NAMES = ("float",)         # builtin only; `t.float()` is torch-style
METHOD_NAMES = ("item",)        # method only; bare `item(` is unrelated

#: the hot-path module set, repo-relative (glob entries end with /*.py)
HOT_PATH_TARGETS = (
    "dist_mnist_tpu/train/*.py",
    "dist_mnist_tpu/faults/*.py",
    "dist_mnist_tpu/data/prefetch.py",
    "dist_mnist_tpu/hooks/builtin.py",
    "dist_mnist_tpu/parallel/overlap.py",
    "dist_mnist_tpu/serve/zoo.py",
    "dist_mnist_tpu/serve/autoscale.py",
    "dist_mnist_tpu/ops/quant.py",
    "dist_mnist_tpu/serve/engine.py",
    "dist_mnist_tpu/serve/loader.py",
    "dist_mnist_tpu/serve/decode.py",
    "dist_mnist_tpu/models/causal_lm.py",
    # the Pallas kernel dispatch wrappers run per serve request / train
    # step — a host sync there stalls the whole pipeline
    "dist_mnist_tpu/ops/pallas/*.py",
    # the tuner's objectives run bench legs in a scoring loop: an
    # unsuppressed sync there multiplies across every trial of every
    # halving round (the score handoff itself is suppressed, reasoned)
    "dist_mnist_tpu/tune/*.py",
)


def hot_path_files(repo_root: Path) -> list[Path]:
    out: list[Path] = []
    for pat in HOT_PATH_TARGETS:
        if pat.endswith("*.py"):
            out.extend(sorted((repo_root / pat[:-len("*.py")]).glob("*.py")))
        else:
            p = repo_root / pat
            if p.exists():
                out.append(p)
    return out


def _sync_calls(tree: ast.Module):
    """Yield (node, name, is_method) for every flagged call that sits
    inside a function or lambda body."""
    # collect the line spans of every function body; a call is hot-path
    # only if some def encloses it
    spans = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            spans.append((node.lineno, getattr(node, "end_lineno",
                                               node.lineno)))

    def in_function(call: ast.Call) -> bool:
        return any(a <= call.lineno <= b for a, b in spans)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name, is_method = call_name(node)
        if name is None:
            continue
        if (name in ANY_NAMES
                or (name in BARE_NAMES and not is_method)
                or (name in METHOD_NAMES and is_method)):
            if in_function(node):
                yield node, name, is_method


def scan_source(sf: SourceFile) -> list[Finding]:
    """Unsuppressed-yet findings for one file (suppressions are applied
    by the engine; the shim applies them itself for standalone files)."""
    if sf.tree is None:
        return [sf.finding("host-sync", 1, sf.parse_error or "unparseable")]
    out = []
    for node, name, is_method in _sync_calls(sf.tree):
        what = f".{name}()" if is_method else f"{name}("
        out.append(sf.finding(
            "host-sync", node,
            f"{what} in a hot-path module is a blocking device->host "
            f"sync; batch it or annotate with `# lint: ok[host-sync] "
            f"<why>` (legacy `# host-sync-ok: <why>` honored)"))
    return out


class HostSyncRule(Rule):
    rule_id = "host-sync"
    doc = ("blocking device->host syncs (float()/.item()/device_get) in "
           "hot-path functions")

    def check(self, ctx: Context) -> list[Finding]:
        out: list[Finding] = []
        for path in hot_path_files(ctx.repo_root):
            rel = path.relative_to(ctx.repo_root).as_posix()
            sf = ctx.source(rel)
            if sf is not None:
                out.extend(scan_source(sf))
        return out


RULE = HostSyncRule()
