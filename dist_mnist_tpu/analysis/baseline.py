"""Committed baseline for grandfathered graftlint findings.

The baseline keeps the suite green while carrying known, *reasoned*
debt: each entry names a rule, a path, a message substring to match, and
the one-line reason it is allowed to stand. Matching ignores line
numbers (they drift under every edit); an entry matches any finding with
the same rule + path whose message contains `match`.

The file lives at `<repo>/.graftlint_baseline.json` so it reads as repo
state, not package code:

    {"entries": [
      {"rule": "bench-stages", "path": "bench.py",
       "match": "--async-save",
       "reason": "parameterization of the --ckpt leg, not a stage"}
    ]}

Stale entries (matching nothing) are reported as warnings — delete them
when the debt is paid. Entries without a reason are hard errors: the
reason IS the point.
"""

from __future__ import annotations

import json
from pathlib import Path

from dist_mnist_tpu.analysis.core import Finding

DEFAULT_NAME = ".graftlint_baseline.json"


class BaselineError(ValueError):
    pass


class Baseline:
    def __init__(self, entries: list[dict]):
        for i, e in enumerate(entries):
            missing = {"rule", "path", "match", "reason"} - set(e)
            if missing:
                raise BaselineError(
                    f"baseline entry {i} missing {sorted(missing)}")
            if not str(e["reason"]).strip():
                raise BaselineError(
                    f"baseline entry {i} ({e['rule']} {e['path']}) has an "
                    f"empty reason — every grandfathered finding carries "
                    f"its why")
        self.entries = entries
        self._hits = [0] * len(entries)

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        if not path.is_file():
            return cls([])
        data = json.loads(path.read_text())
        return cls(list(data.get("entries", [])))

    def matches(self, f: Finding) -> bool:
        hit = False
        for i, e in enumerate(self.entries):
            if (e["rule"] == f.rule and e["path"] == f.path
                    and e["match"] in f.message):
                self._hits[i] += 1
                hit = True
        return hit

    def partition(self, findings: list[Finding]
                  ) -> tuple[list[Finding], list[Finding]]:
        """(new, baselined)"""
        new, old = [], []
        for f in findings:
            (old if self.matches(f) else new).append(f)
        return new, old

    def stale_entries(self) -> list[dict]:
        return [e for e, hits in zip(self.entries, self._hits) if not hits]
