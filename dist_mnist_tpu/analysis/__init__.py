"""graftlint: the repo's AST-based static-analysis suite.

Run it: ``python -m dist_mnist_tpu.analysis`` (see cli.py / the rule
catalog in docs/ANALYSIS.md). Import surface for tests and the
scripts/check_host_sync.py shim:

    from dist_mnist_tpu.analysis import core, baseline, rules

Stdlib-only by design — importing this package must never pull jax (the
root package's PEP 562 lazy exports keep `import dist_mnist_tpu` free of
it too), so the lint runs in seconds anywhere.
"""

from dist_mnist_tpu.analysis import baseline, cli, core, rules  # noqa: F401
from dist_mnist_tpu.analysis.core import Context, Finding, Rule  # noqa: F401
