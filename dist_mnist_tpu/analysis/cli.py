"""graftlint CLI: `python -m dist_mnist_tpu.analysis`.

Exit 0 when every finding is suppressed or baselined, 1 otherwise (2 on
usage errors). Default output is `path:line: rule-id message`, one per
line; `--json` emits one machine-readable object (schema below). Keeps
to stdlib imports only — a full-tree run must finish in seconds with no
accelerator stack.

    python -m dist_mnist_tpu.analysis                 # whole tree
    python -m dist_mnist_tpu.analysis --json
    python -m dist_mnist_tpu.analysis --rules host-sync,bench-stages
    python -m dist_mnist_tpu.analysis --changed-only  # git-diff scoped

JSON schema (stable; tests pin it):

    {"version": 1, "rules": [...], "findings": [
        {"rule", "path", "line", "message"}],
     "baselined": N, "suppressed": N, "stale_baseline": [entries]}
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path

from dist_mnist_tpu.analysis import baseline as baseline_mod
from dist_mnist_tpu.analysis import rules as rules_mod
from dist_mnist_tpu.analysis.core import Context, run


def repo_root_from(package_dir: Path | None = None) -> Path:
    here = package_dir or Path(__file__).resolve().parent
    return here.parent.parent


def _changed_paths(repo_root: Path) -> set[str] | None:
    """Repo-relative changed files (staged + unstaged + untracked); None
    when git is unavailable — callers fall back to a full run."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            cwd=repo_root, capture_output=True, text=True, timeout=30)
        status = subprocess.run(
            ["git", "status", "--porcelain"],
            cwd=repo_root, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if diff.returncode or status.returncode:
        return None
    paths = set(diff.stdout.split())
    for line in status.stdout.splitlines():
        if line[:2].strip() and len(line) > 3:
            paths.add(line[3:].strip())
    return paths


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dist_mnist_tpu.analysis",
        description="graftlint: AST static analysis for this repo's "
                    "trace-safety / SPMD / lifecycle / drift invariants")
    ap.add_argument("--json", action="store_true", dest="json_out",
                    help="machine-readable output (one JSON object)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids (default: all)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: "
                         f"<repo>/{baseline_mod.DEFAULT_NAME})")
    ap.add_argument("--changed-only", action="store_true",
                    help="report only findings on files changed vs git "
                         "HEAD (rules still see the whole tree)")
    ap.add_argument("--repo-root", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in rules_mod.ALL_RULES:
            print(f"{rule.rule_id:18s} {rule.doc}")
        return 0

    repo_root = (Path(args.repo_root).resolve() if args.repo_root
                 else repo_root_from())
    try:
        selected = rules_mod.select(
            [r.strip() for r in args.rules.split(",") if r.strip()]
            if args.rules else None)
    except KeyError as err:
        print(err.args[0], file=sys.stderr)
        return 2

    bl_path = (Path(args.baseline) if args.baseline
               else repo_root / baseline_mod.DEFAULT_NAME)
    try:
        bl = baseline_mod.Baseline.load(bl_path)
    except (baseline_mod.BaselineError, json.JSONDecodeError) as err:
        print(f"bad baseline {bl_path}: {err}", file=sys.stderr)
        return 2

    changed = None
    if args.changed_only:
        paths = _changed_paths(repo_root)
        if paths is not None:
            changed = lambda rel: rel in paths  # noqa: E731

    ctx = Context(repo_root)
    result = run(ctx, selected, changed_only=changed)
    new, baselined = bl.partition(result["findings"])
    stale = bl.stale_entries() if changed is None else []

    if args.json_out:
        print(json.dumps({
            "version": 1,
            "rules": result["rules"],
            "findings": [f.to_json() for f in new],
            "baselined": len(baselined),
            "suppressed": result["suppressed"],
            "stale_baseline": stale,
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        for e in stale:
            print(f"warning: stale baseline entry {e['rule']} {e['path']} "
                  f"({e['match']!r} matched nothing) — debt paid, delete "
                  f"it", file=sys.stderr)
        if new:
            print(f"\n{len(new)} finding(s) "
                  f"({len(baselined)} baselined, "
                  f"{result['suppressed']} suppressed).", file=sys.stderr)
    return 1 if new else 0
