"""Chrome-trace export + op-time triage.

Replaces the reference's Timeline path (SURVEY.md §5.1): there,
`ProfilerHook` requested FULL_TRACE RunMetadata and
`client/timeline.py:410` (`generate_chrome_trace_format:825`) converted the
returned step_stats into `timeline-<step>.json` for chrome://tracing.

`jax.profiler` already captures a superset (XLA ops, ICI collectives, host
runtime) but buries it as TensorBoard plugin data
(`<logdir>/plugins/profile/<run>/*.trace.json.gz`). The two functions here
close the gap to the reference's UX:

- `export_chrome_trace(logdir, out)` -> the literal `timeline-*.json` file
  the reference emitted, loadable in chrome://tracing / perfetto.
- `summarize_trace(path, top)` -> top-N ops by self device time, for triage
  on machines with no TensorBoard reachable (this box: zero egress).

On a shared logdir, multiple hosts profile into the same
`plugins/profile` tree; exports are therefore stamped with the host id
(`timeline-<host>-<run>.json`, host from `DIST_MNIST_TPU_HOST_ID`) so
one host's export can never shadow another's, and
scripts/fleet_trace.py can merge them back into one per-host-track
fleet trace.
"""

from __future__ import annotations

import gzip
import json
import os
from collections import defaultdict
from pathlib import Path

__all__ = ["latest_trace", "export_chrome_trace", "summarize_trace"]

_ENV_HOST_ID = "DIST_MNIST_TPU_HOST_ID"  # == obs/events.ENV_HOST_ID


def latest_trace(logdir: str | Path) -> Path | None:
    """Newest .trace.json.gz under a jax.profiler logdir (None if absent)."""
    candidates = sorted(
        Path(logdir).glob("plugins/profile/*/*.trace.json.gz"),
        key=lambda p: p.stat().st_mtime,
    )
    return candidates[-1] if candidates else None


def export_chrome_trace(
    logdir: str | Path, out_path: str | Path | None = None,
    host_id: int | str | None = None,
) -> Path | None:
    """Decompress the latest profiler trace to
    `timeline-<host>-<run>.json` (`timeline-<run>.json` when no host
    identity is known — single-process runs).

    Returns the written path, or None when no trace exists yet. Naming
    mirrors the reference's `timeline-<step>.json` files; the host stamp
    keeps concurrent hosts on a shared logdir from shadowing each
    other's export."""
    src = latest_trace(logdir)
    if src is None:
        return None
    if host_id is None:
        host_id = os.environ.get(_ENV_HOST_ID)
    if out_path is None:
        stem = (f"timeline-h{host_id}-{src.parent.name}"
                if host_id is not None else f"timeline-{src.parent.name}")
        out_path = Path(logdir) / f"{stem}.json"
    out_path = Path(out_path)
    out_path.write_bytes(gzip.decompress(src.read_bytes()))
    return out_path


def summarize_trace(
    trace_path: str | Path, top: int = 15
) -> list[dict[str, float | str | int]]:
    """Aggregate complete events by name: total duration, count.

    Works on either the raw `.trace.json.gz` or an exported timeline JSON.
    Returns rows sorted by total time, descending:
    `{"name", "total_us", "count", "avg_us"}`.

    Tolerant of sparse producers: events missing `pid`/`tid`/`name` or
    carrying a non-numeric `dur` (hand-built traces, fleet_trace merges,
    other profilers) are aggregated under defaults or skipped rather
    than raising.
    """
    raw = Path(trace_path).read_bytes()
    if str(trace_path).endswith(".gz"):
        raw = gzip.decompress(raw)
    events = json.loads(raw).get("traceEvents", [])
    total = defaultdict(float)
    count = defaultdict(int)
    tracks = defaultdict(set)
    for ev in events:
        if not isinstance(ev, dict) or ev.get("ph") != "X":
            continue
        dur = ev.get("dur")
        if not isinstance(dur, (int, float)):
            continue
        name = ev.get("name", "?")
        total[name] += dur
        count[name] += 1
        # pid/tid are optional per the trace-format spec: default, never
        # index, so partial producers summarize instead of crash
        tracks[name].add((ev.get("pid", 0), ev.get("tid", 0)))
    rows = sorted(total, key=total.__getitem__, reverse=True)[:top]
    return [
        {
            "name": n,
            "total_us": round(total[n], 1),
            "count": count[n],
            "avg_us": round(total[n] / count[n], 2),
            "tracks": len(tracks[n]),
        }
        for n in rows
    ]
