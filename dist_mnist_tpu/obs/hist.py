"""Streaming histograms: fixed log-bucket, thread-safe, mergeable.

The serve reservoirs (`serve/metrics.py`) and any per-step timing signal
share the same problem: percentiles over an unbounded stream without
unbounded memory. A fixed geometric bucket ladder solves it — O(1) per
observation, O(n_buckets) memory, and two histograms with the same
ladder merge by adding counts (so per-process histograms can roll up
across a fleet). Quantile estimates carry the ladder's relative error
(`growth - 1`, ~10% at the default), while count/sum/min/max are exact.

Stdlib-only on purpose: the journal (`obs/events.py`) and supervisor
import freely without pulling numpy/jax.
"""

from __future__ import annotations

import math
import threading

__all__ = ["StreamingHistogram"]

# Default ladder: (1e-3, growth=1.1, 254 buckets) spans ~1e-3 .. ~3e7
# with <=10% relative error — microseconds to hours when the unit is ms.
_DEF_MIN = 1e-3
_DEF_GROWTH = 1.1
_DEF_BUCKETS = 254


class StreamingHistogram:
    """Fixed log-spaced bucket histogram over non-negative values.

    Bucket 0 holds everything <= ``min_value`` (including zeros and any
    stray negatives); the last bucket is the overflow. Interior bucket
    ``i`` covers ``(min_value * growth**(i-1), min_value * growth**i]``.
    """

    def __init__(self, *, min_value: float = _DEF_MIN,
                 growth: float = _DEF_GROWTH, n_buckets: int = _DEF_BUCKETS):
        if not (min_value > 0 and growth > 1 and n_buckets >= 2):
            raise ValueError(
                f"bad ladder: min_value={min_value} growth={growth} "
                f"n_buckets={n_buckets}")
        self.min_value = float(min_value)
        self.growth = float(growth)
        self.n_buckets = int(n_buckets)
        self._log_growth = math.log(self.growth)
        self._counts = [0] * self.n_buckets
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._lock = threading.Lock()

    # -- recording ------------------------------------------------------------

    def _index(self, value: float) -> int:
        if value <= self.min_value:
            return 0
        idx = 1 + int(math.floor(
            math.log(value / self.min_value) / self._log_growth))
        # floating-point edge: value exactly on an edge may round either way
        return min(max(idx, 1), self.n_buckets - 1)

    def observe(self, value: float) -> None:
        v = float(value)
        if math.isnan(v):
            return
        with self._lock:
            self._counts[self._index(v)] += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    def observe_many(self, values) -> None:
        for v in values:
            self.observe(float(v))

    def merge(self, other: "StreamingHistogram") -> None:
        """Fold ``other`` into self. Ladders must match exactly."""
        if (self.min_value, self.growth, self.n_buckets) != (
                other.min_value, other.growth, other.n_buckets):
            raise ValueError("cannot merge histograms with different ladders")
        with other._lock:
            counts = list(other._counts)
            count, total = other._count, other._sum
            lo, hi = other._min, other._max
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._count += count
            self._sum += total
            if lo < self._min:
                self._min = lo
            if hi > self._max:
                self._max = hi

    # -- reading --------------------------------------------------------------

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else math.nan

    def bucket_upper_edge(self, i: int) -> float:
        """Upper edge of bucket i (inf for the overflow bucket)."""
        if i >= self.n_buckets - 1:
            return math.inf
        return self.min_value * self.growth ** i

    def buckets(self) -> list[tuple[float, int]]:
        """(upper_edge, count) per bucket — the raw exposition surface."""
        with self._lock:
            counts = list(self._counts)
        return [(self.bucket_upper_edge(i), c) for i, c in enumerate(counts)]

    def quantile(self, q: float) -> float:
        """Approximate q-quantile; NaN when empty. Monotonic in q."""
        with self._lock:
            return self._quantile_locked(q)

    def _quantile_locked(self, q: float) -> float:
        if not self._count:
            return math.nan
        rank = max(1.0, math.ceil(q * self._count))
        cum = 0
        for i, c in enumerate(self._counts):
            cum += c
            if cum >= rank:
                # clamp the edge estimate to the exact observed range
                est = self.bucket_upper_edge(i)
                return min(max(est, self._min), self._max)
        return self._max

    def percentiles(self) -> dict:
        with self._lock:
            return {"p50": self._quantile_locked(0.50),
                    "p95": self._quantile_locked(0.95),
                    "p99": self._quantile_locked(0.99)}

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "count": self._count,
                "sum": self._sum,
                "mean": self._sum / self._count if self._count else math.nan,
                "min": self._min if self._count else math.nan,
                "max": self._max if self._count else math.nan,
                "p50": self._quantile_locked(0.50),
                "p95": self._quantile_locked(0.95),
                "p99": self._quantile_locked(0.99),
            }

    def representative_values(self, cap: int = 2048) -> list[float]:
        """Reconstruct a bounded sample that approximates the distribution
        (bucket midpoints repeated by count, thinned above ``cap``) so the
        raw-array ``MetricWriter.histogram`` protocol keeps working after
        the reservoirs it used to read are gone."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
            lo = self._min
            hi = self._max
        if not total:
            return []
        scale = min(1.0, cap / total)
        out: list[float] = []
        prev_edge = 0.0
        for i, c in enumerate(counts):
            edge = self.bucket_upper_edge(i)
            if c:
                mid = prev_edge + (min(edge, hi) - prev_edge) / 2 \
                    if math.isfinite(edge) else hi
                mid = min(max(mid, lo), hi)
                out.extend([mid] * max(1, int(round(c * scale))))
            prev_edge = edge if math.isfinite(edge) else prev_edge
        if len(out) > cap:
            # per-bucket rounding can overshoot; out is bucket-ordered, so
            # an even stride is a quantile-preserving thinning
            stride = len(out) / cap
            out = [out[int(i * stride)] for i in range(cap)]
        return out

    def __repr__(self):
        s = self.snapshot()
        return (f"StreamingHistogram(count={s['count']}, mean={s['mean']:.4g},"
                f" p50={s['p50']:.4g}, p99={s['p99']:.4g})")
