"""Scalar metric writers.

Replaces the reference's summary path (SURVEY.md §5.5: merged summary op ->
SummarySaverHook -> SummaryWriterCache -> event files). Writers here are
plain host-side objects fed by hooks; TensorBoard output goes through
`clu.metric_writers` when available. Only the chief process writes
(mirroring chief-only summary hooks, monitored_session.py:517-532).
"""

from __future__ import annotations

import csv
import logging
from pathlib import Path
from typing import Protocol

import numpy as np

log = logging.getLogger(__name__)


class MetricWriter(Protocol):
    def scalar(self, tag: str, value: float, step: int) -> None: ...

    def scalars(self, values: dict, step: int) -> None: ...

    def histogram(self, tag: str, values, step: int) -> None: ...

    def flush(self) -> None: ...


def _summary_stats(values) -> dict[str, float]:
    v = np.asarray(values, dtype=np.float64).ravel()
    if v.size == 0:
        return {"count": 0.0}
    return {
        "count": float(v.size),
        "mean": float(v.mean()),
        "std": float(v.std()),
        "min": float(v.min()),
        "max": float(v.max()),
    }


class StdoutWriter:
    def scalar(self, tag, value, step):
        log.info("[metric] step=%d %s=%.6g", step, tag, value)

    def scalars(self, values, step):
        # one line per batch, not per tag — batched writes exist so a
        # multi-metric cadence costs one writer call (hooks/builtin.py)
        log.info("[metric] step=%d %s", step,
                 " ".join(f"{k}={v:.6g}" for k, v in values.items()))

    def histogram(self, tag, values, step):
        s = _summary_stats(values)
        log.info("[hist] step=%d %s: %s", step, tag,
                 " ".join(f"{k}={v:.6g}" for k, v in s.items()))

    def flush(self):
        pass


class CsvWriter:
    """One CSV per run: step,tag,value — trivially parseable by benches.
    A CSV is a scalar sink, so histograms land as summary-stat rows
    (`tag/mean`, `tag/std`, ...)."""

    # rows buffered past this count are flushed to disk: the window lost
    # at abnormal exit is bounded, which is exactly when post-mortem
    # metrics matter (docs/RESILIENCE.md)
    FLUSH_EVERY = 32

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._fh = open(self.path, "a", newline="")
        self._writer = csv.writer(self._fh)
        self._unflushed = 0
        if self._fh.tell() == 0:
            self._writer.writerow(["step", "tag", "value"])

    def _wrote(self, n: int) -> None:
        self._unflushed += n
        if self._unflushed >= self.FLUSH_EVERY:
            self.flush()

    def scalar(self, tag, value, step):
        self._writer.writerow([step, tag, value])
        self._wrote(1)

    def scalars(self, values, step):
        self._writer.writerows([step, k, v] for k, v in values.items())
        self._wrote(len(values))

    def histogram(self, tag, values, step):
        stats = _summary_stats(values)
        for k, v in stats.items():
            self._writer.writerow([step, f"{tag}/{k}", v])
        self._wrote(len(stats))

    def flush(self):
        if not self._fh.closed:
            self._fh.flush()
        self._unflushed = 0

    def close(self):
        if not self._fh.closed:
            self._fh.flush()
            self._fh.close()


class TensorBoardWriter:
    """clu-backed TensorBoard event files; degrades to a warning if clu is
    unavailable (nothing in the framework hard-depends on it)."""

    def __init__(self, logdir: str | Path):
        try:
            from clu import metric_writers

            self._w = metric_writers.SummaryWriter(str(logdir))
        except Exception:
            log.warning("clu/tensorboard unavailable; TensorBoardWriter is a no-op")
            self._w = None

    def scalar(self, tag, value, step):
        if self._w is not None:
            self._w.write_scalars(step, {tag: value})

    def scalars(self, values, step):
        # clu's native API IS batched; one event-file record for the set
        if self._w is not None:
            self._w.write_scalars(step, dict(values))

    def histogram(self, tag, values, step):
        # full-distribution summaries — the reference's arbitrary-proto
        # summary path ($TF basic_session_run_hooks.py:793) beyond scalars
        if self._w is not None:
            self._w.write_histograms(step, {tag: np.asarray(values).ravel()})

    def flush(self):
        if self._w is not None:
            self._w.flush()


class MultiWriter:
    def __init__(self, *writers: MetricWriter):
        self.writers = writers

    def scalar(self, tag, value, step):
        for w in self.writers:
            w.scalar(tag, value, step)

    def scalars(self, values, step):
        for w in self.writers:
            # pre-batch custom writers (scalar/flush only) degrade to a
            # per-tag loop instead of crashing
            batch_write = getattr(w, "scalars", None)
            if callable(batch_write):
                batch_write(values, step)
            else:
                for k, v in values.items():
                    w.scalar(k, v, step)

    def histogram(self, tag, values, step):
        for w in self.writers:
            # scalar-only writers degrade to summary-stat rows instead of
            # crashing the whole fan-out (same contract as scalars above)
            hist_write = getattr(w, "histogram", None)
            if callable(hist_write):
                hist_write(tag, values, step)
            else:
                for k, v in _summary_stats(values).items():
                    w.scalar(f"{tag}/{k}", v, step)

    def flush(self):
        for w in self.writers:
            w.flush()

    def close(self):
        for w in self.writers:
            close = getattr(w, "close", None)
            if callable(close):
                close()
            else:
                w.flush()


def make_default_writer(logdir: str | Path | None, *, chief: bool = True,
                        registry=None):
    """Stdout always (chief only); CSV + TensorBoard when a logdir is given.
    When a ``MetricRegistry`` is passed, a ``RegistryWriter`` joins the
    fan-out on EVERY process (chief or not) so the local ``/metrics``
    endpoint stays live even where the disk sinks are chief-gated."""
    live: list[MetricWriter] = []
    if registry is not None:
        from dist_mnist_tpu.obs.registry import RegistryWriter

        live.append(RegistryWriter(registry))
    if not chief:
        return MultiWriter(*live)
    writers: list[MetricWriter] = live + [StdoutWriter()]
    if logdir is not None:
        writers.append(CsvWriter(Path(logdir) / "metrics.csv"))
        writers.append(TensorBoardWriter(logdir))
    return MultiWriter(*writers)
