"""In-process metric registry: the live, scrapeable view of everything
the hooks publish.

Writers (`obs/writers.py`) are write-only sinks — CSV rows and TB event
files are post-hoc. The registry is the read side: a ``RegistryWriter``
slots into ``make_default_writer`` next to the disk sinks, so every
``goodput/*``, ``startup/*``, ``memory/*``, ``input/*``, ``serve/*``
scalar a hook emits is also held in memory where the exporter
(`obs/exporter.py`) can serve it over ``/metrics`` while the run is
still going.

Histograms come in two flavors:
  * raw-array writes through the ``MetricWriter.histogram`` protocol are
    folded into a registry-owned ``StreamingHistogram`` per tag;
  * live histograms owned elsewhere (the train loop's step-time ladder,
    the serve metrics reservoir replacements) are *attached* by
    reference, so the exporter reads them with zero copying.
"""

from __future__ import annotations

import threading
import time

from dist_mnist_tpu.obs.hist import StreamingHistogram

__all__ = ["MetricRegistry", "RegistryWriter"]


class MetricRegistry:
    """Thread-safe map of tag -> latest scalar and tag -> histogram."""

    def __init__(self):
        self._lock = threading.Lock()
        self._scalars: dict[str, tuple[float, int, float]] = {}
        self._hists: dict[str, StreamingHistogram] = {}

    # -- scalars --------------------------------------------------------------

    def set_scalar(self, tag: str, value, step: int) -> None:
        with self._lock:
            self._scalars[str(tag)] = (float(value), int(step), time.time())

    def set_scalars(self, values: dict, step: int) -> None:
        now = time.time()
        with self._lock:
            for tag, value in values.items():
                self._scalars[str(tag)] = (float(value), int(step), now)

    def scalars(self) -> dict[str, tuple[float, int, float]]:
        """tag -> (value, step, wall_time) snapshot."""
        with self._lock:
            return dict(self._scalars)

    # -- histograms -----------------------------------------------------------

    def attach_histogram(self, tag: str, hist: StreamingHistogram) -> None:
        """Register a live, externally-owned histogram under ``tag``."""
        with self._lock:
            self._hists[str(tag)] = hist

    def observe(self, tag: str, value: float) -> None:
        self._hist_for(tag).observe(value)

    def record_values(self, tag: str, values) -> None:
        self._hist_for(tag).observe_many(values)

    def _hist_for(self, tag: str) -> StreamingHistogram:
        with self._lock:
            h = self._hists.get(str(tag))
            if h is None:
                h = self._hists[str(tag)] = StreamingHistogram()
        return h

    def histograms(self) -> dict[str, StreamingHistogram]:
        with self._lock:
            return dict(self._hists)

    # -- combined view --------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-friendly snapshot: scalars as values, hists as summaries."""
        with self._lock:
            scalars = {t: v for t, (v, _s, _w) in self._scalars.items()}
            hists = dict(self._hists)
        return {"scalars": scalars,
                "histograms": {t: h.snapshot() for t, h in hists.items()}}

    def tags(self) -> list[str]:
        with self._lock:
            return sorted(set(self._scalars) | set(self._hists))


class RegistryWriter:
    """MetricWriter facade over a MetricRegistry — the hook side of the
    live-metrics path. Matches the protocol in obs/writers.py."""

    def __init__(self, registry: MetricRegistry):
        self.registry = registry

    def scalar(self, tag, value, step):
        self.registry.set_scalar(tag, value, step)

    def scalars(self, values, step):
        self.registry.set_scalars(values, step)

    def histogram(self, tag, values, step):
        self.registry.record_values(tag, values)

    def flush(self):
        pass

    def close(self):
        pass
