"""Observability: metric writers (tf.summary / SummaryWriterCache analogue,
SURVEY.md §5.5) and chrome-trace export (client/timeline.py analogue, §5.1)."""

from dist_mnist_tpu.obs.writers import (
    MetricWriter,
    StdoutWriter,
    CsvWriter,
    TensorBoardWriter,
    MultiWriter,
    make_default_writer,
)
from dist_mnist_tpu.obs.timeline import (
    latest_trace,
    export_chrome_trace,
    summarize_trace,
)

__all__ = [
    "MetricWriter",
    "StdoutWriter",
    "CsvWriter",
    "TensorBoardWriter",
    "MultiWriter",
    "make_default_writer",
    "latest_trace",
    "export_chrome_trace",
    "summarize_trace",
]
