"""Observability: metric writers (tf.summary / SummaryWriterCache analogue,
SURVEY.md §5.5)."""

from dist_mnist_tpu.obs.writers import (
    MetricWriter,
    StdoutWriter,
    CsvWriter,
    TensorBoardWriter,
    MultiWriter,
    make_default_writer,
)

__all__ = [
    "MetricWriter",
    "StdoutWriter",
    "CsvWriter",
    "TensorBoardWriter",
    "MultiWriter",
    "make_default_writer",
]
