"""Observability: metric writers (tf.summary / SummaryWriterCache analogue,
SURVEY.md §5.5), chrome-trace export (client/timeline.py analogue, §5.1),
and the live telemetry spine — streaming histograms, in-process metric
registry, /metrics + /healthz exposition, and the structured run journal
(docs/OBSERVABILITY.md)."""

from dist_mnist_tpu.obs.writers import (
    MetricWriter,
    StdoutWriter,
    CsvWriter,
    TensorBoardWriter,
    MultiWriter,
    make_default_writer,
)
from dist_mnist_tpu.obs.timeline import (
    latest_trace,
    export_chrome_trace,
    summarize_trace,
)
from dist_mnist_tpu.obs.hist import StreamingHistogram
from dist_mnist_tpu.obs.registry import MetricRegistry, RegistryWriter
from dist_mnist_tpu.obs.exporter import HealthState, MetricsExporter
from dist_mnist_tpu.obs.events import RunJournal
from dist_mnist_tpu.obs.fleet import FleetScraper, parse_prometheus
from dist_mnist_tpu.obs.anomaly import AnomalyHook, RobustDetector

__all__ = [
    "MetricWriter",
    "StdoutWriter",
    "CsvWriter",
    "TensorBoardWriter",
    "MultiWriter",
    "make_default_writer",
    "latest_trace",
    "export_chrome_trace",
    "summarize_trace",
    "StreamingHistogram",
    "MetricRegistry",
    "RegistryWriter",
    "HealthState",
    "MetricsExporter",
    "RunJournal",
    "FleetScraper",
    "parse_prometheus",
    "AnomalyHook",
    "RobustDetector",
]
