"""Fleet observability: scrape every worker's exposition endpoints and
serve one merged view from the supervisor.

The PR 6 telemetry spine made each *process* observable; an elastic
fleet (cli/launch.py --elastic) needs the cross-host questions answered
in one place: which host is the straggler dragging the synchronous
step, how the step-time distribution looks *fleet-wide*, and which
hosts are alive/degraded right now. A single MonitoredTrainingSession
chief got this for free in the reference architecture; a multi-host
SPMD world has to rebuild it explicitly — that is this module.

``FleetScraper`` polls every live child's ``/metrics`` + ``/healthz``
(+ ``/events``) over localhost HTTP, parses the Prometheus text *back*
into values and ``StreamingHistogram``s (the ladder is fixed precisely
so per-process histograms merge by adding counts), and exposes:

- merged fleet-wide histograms + per-host attribution series, appended
  to the supervisor exporter's ``/metrics`` (obs/exporter.py hands the
  scraper the request via ``MetricsExporter.fleet``);
- a ``/fleet`` JSON snapshot (per-host state, straggler verdict);
- ``fleet/*`` gauges in its own ``MetricRegistry``;
- a ``straggler_detected`` journal event naming the host when one
  host's step time stays skewed above the fleet median.

Straggler math: per scrape, each host's step-time signal is the mean of
the *new* ``step_time_ms`` samples since the previous scrape (delta of
the histogram's ``_sum``/``_count``; falls back to the cumulative mean
when a host produced no new samples). The fleet reference is the lower
median of those means — robust for small fleets, where an upper median
would let a single straggler drag the reference toward itself. A host
whose ``mean / median`` ratio stays >= ``straggler_ratio`` for
``straggler_window`` consecutive scrapes is declared a straggler once,
and the detector re-arms after the ratio clears.

Stdlib-only on purpose: this runs inside the supervisor, which must
stay importable before (and without) jax.
"""

from __future__ import annotations

import json
import logging
import math
import threading
import time
import urllib.error
import urllib.request

from dist_mnist_tpu.obs.exporter import render_histogram_lines
from dist_mnist_tpu.obs.hist import StreamingHistogram
from dist_mnist_tpu.obs.registry import MetricRegistry

log = logging.getLogger(__name__)

__all__ = ["parse_prometheus", "FleetScraper"]


# -- Prometheus text -> values ------------------------------------------------

def _parse_labels(raw: str) -> dict:
    """``k1="v1",k2="v2"`` -> dict. Values in our exposition never
    contain escaped quotes, so a simple split is exact."""
    out: dict = {}
    for part in raw.split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        k, v = part.split("=", 1)
        out[k.strip()] = v.strip().strip('"')
    return out


def _split_sample(line: str) -> tuple[str, dict, float] | None:
    """One sample line -> (name, labels, value); None when unparseable."""
    try:
        metric, value_s = line.rsplit(None, 1)
    except ValueError:
        return None
    labels: dict = {}
    if "{" in metric:
        name, rest = metric.split("{", 1)
        labels = _parse_labels(rest.rsplit("}", 1)[0])
    else:
        name = metric
    name = name.strip()
    value_s = value_s.strip()
    try:
        if value_s == "+Inf":
            value = math.inf
        elif value_s == "-Inf":
            value = -math.inf
        else:
            value = float(value_s)
    except ValueError:
        return None
    return name, labels, value


def _rebuild_histogram(cum_buckets: list[tuple[float, float]],
                       total: float, hsum: float,
                       ladder: StreamingHistogram) -> StreamingHistogram:
    """Cumulative ``_bucket`` samples -> a StreamingHistogram on the
    given ladder. Bucket indices recover exactly from edges because the
    exposition prints ``repr(float(edge))`` of ``min_value*growth**i``;
    min/max are approximated by the occupied bucket edges (count/sum
    stay exact, which is all merging needs)."""
    h = StreamingHistogram(min_value=ladder.min_value, growth=ladder.growth,
                           n_buckets=ladder.n_buckets)
    log_growth = math.log(h.growth)
    prev_cum = 0.0
    finite_total = 0.0
    for edge, cum in sorted(cum_buckets):
        if not math.isfinite(edge):
            continue
        count = int(round(cum - prev_cum))
        prev_cum = cum
        if count <= 0:
            continue
        idx = int(round(math.log(edge / h.min_value) / log_growth))
        idx = min(max(idx, 0), h.n_buckets - 1)
        h._counts[idx] += count
        finite_total += count
    overflow = int(round(total - finite_total))
    if overflow > 0:
        h._counts[h.n_buckets - 1] += overflow
    h._count = int(round(total))
    h._sum = float(hsum)
    occupied = [i for i, c in enumerate(h._counts) if c]
    if occupied:
        lo_i, hi_i = occupied[0], occupied[-1]
        h._min = 0.0 if lo_i == 0 else h.bucket_upper_edge(lo_i - 1)
        h._max = h.bucket_upper_edge(hi_i)  # inf when overflow occupied
    return h


def parse_prometheus(text: str, *,
                     ladder: StreamingHistogram | None = None):
    """Parse exporter.render_prometheus output back into
    ``(scalars, histograms, info)``.

    - ``scalars``: plain (label-free) gauge samples by exposition name.
    - ``histograms``: StreamingHistogram per ``# TYPE ... histogram``
      family, rebuilt on the repo-default ladder (or ``ladder``'s) so it
      merges with live histograms.
    - ``info``: labels of the ``process_info`` gauge (host_id,
      generation, role), plus ``state`` from ``process_state``.
    """
    if ladder is None:
        ladder = StreamingHistogram()
    scalars: dict[str, float] = {}
    info: dict[str, str] = {}
    # family -> {"buckets": [(edge, cum)], "sum": float, "count": float}
    fams: dict[str, dict] = {}
    hist_names: set[str] = set()
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE" and \
                    parts[3] == "histogram":
                hist_names.add(parts[2])
            continue
        sample = _split_sample(line)
        if sample is None:
            continue
        name, labels, value = sample
        if name == "process_info":
            info.update(labels)
            continue
        if name == "process_state":
            if value == 1 and "state" in labels:
                info["state"] = labels["state"]
            continue
        base = None
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in hist_names:
                base = name[: -len(suffix)]
                fam = fams.setdefault(
                    base, {"buckets": [], "sum": 0.0, "count": 0.0})
                if suffix == "_bucket":
                    edge_s = labels.get("le", "+Inf")
                    edge = math.inf if edge_s == "+Inf" else float(edge_s)
                    fam["buckets"].append((edge, value))
                elif suffix == "_sum":
                    fam["sum"] = value
                else:
                    fam["count"] = value
                break
        if base is None and not labels:
            scalars[name] = value
    hists = {
        name: _rebuild_histogram(fam["buckets"], fam["count"], fam["sum"],
                                 ladder)
        for name, fam in fams.items()
    }
    return scalars, hists, info


# -- the scraper --------------------------------------------------------------

class _HostView:
    """Everything the scraper knows about one host, plus the straggler
    detector's per-host delta state."""

    def __init__(self, host_id: int):
        self.host_id = host_id
        self.url: str | None = None
        self.reachable = False
        self.healthy = False
        self.state = "unknown"
        self.info: dict = {}
        self.scalars: dict = {}
        self.hists: dict = {}
        self.last_events: list = []
        self.last_scrape_ts: float | None = None
        self.error: str | None = None
        # step-time delta tracking (cumulative sum/count at last scrape)
        self._prev_sum = 0.0
        self._prev_count = 0
        self.step_time_mean_ms: float | None = None
        self.skew_streak = 0

    def update_step_time(self, hist: StreamingHistogram | None) -> None:
        if hist is None or not hist.count:
            return
        d_count = hist.count - self._prev_count
        d_sum = hist.sum - self._prev_sum
        if d_count > 0:
            self.step_time_mean_ms = d_sum / d_count
        else:
            # no new samples since last scrape (or a generation restart
            # reset the counters): fall back to the cumulative mean
            self.step_time_mean_ms = hist.mean
        self._prev_count = hist.count
        self._prev_sum = hist.sum

    def snapshot(self) -> dict:
        return {
            "host": self.host_id,
            "url": self.url,
            "reachable": self.reachable,
            "healthy": self.healthy,
            "state": self.state,
            "info": self.info,
            "step_time_mean_ms": self.step_time_mean_ms,
            "last_scrape_ts": self.last_scrape_ts,
            "error": self.error,
        }


class FleetScraper:
    """Supervisor-side poller merging every worker's exposition.

    Lifecycle: construct once per supervised run, ``set_targets`` at
    every generation start (host id -> base URL), ``start()`` the
    background loop (thread named ``ObsExporter-fleet`` so the conftest
    leak-check covers it), attach to the supervisor's exporter via
    ``MetricsExporter(fleet=scraper)``, ``close()`` in the finally.

    A host vanishing mid-scrape (elastic shrink, preemption) is the
    normal case, not an error path: every request has a short timeout
    and a per-target exception net, so one dead socket can never wedge
    the loop.
    """

    def __init__(self, *, journal=None, interval_s: float = 1.0,
                 timeout_s: float = 0.5,
                 step_time_metric: str = "train_step_time_ms",
                 straggler_ratio: float = 2.0, straggler_window: int = 3,
                 events_tail: int = 5):
        self.registry = MetricRegistry()
        self._journal = journal
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.step_time_metric = step_time_metric
        self.straggler_ratio = float(straggler_ratio)
        self.straggler_window = int(straggler_window)
        self.events_tail = int(events_tail)
        self._lock = threading.Lock()
        self._hosts: dict[int, _HostView] = {}
        self._targets: dict[int, str] = {}
        self._scrapes = 0
        self._scrape_errors = 0
        self._stragglers_detected = 0
        self._current_ratio = math.nan
        self._current_straggler: int | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- targets ---------------------------------------------------------------

    def set_targets(self, targets: dict) -> None:
        """Replace the scrape target set: ``{host_id: base_url}``.
        Called by the supervisor at every generation start; hosts keep
        their delta state across generations (host ids are stable)."""
        with self._lock:
            self._targets = {int(h): str(u).rstrip("/")
                             for h, u in targets.items()}
            for h, u in self._targets.items():
                view = self._hosts.setdefault(h, _HostView(h))
                view.url = u
            for h in list(self._hosts):
                if h not in self._targets:
                    self._hosts[h].reachable = False
                    self._hosts[h].state = "gone"
                    self._hosts[h].healthy = False

    # -- scraping --------------------------------------------------------------

    def _get(self, url: str) -> str:
        with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
            return resp.read().decode("utf-8")

    def _scrape_host(self, view: _HostView) -> None:
        base = view.url
        try:
            scalars, hists, info = parse_prometheus(self._get(base +
                                                              "/metrics"))
            view.scalars, view.hists = scalars, hists
            view.info = {k: v for k, v in info.items() if k != "state"}
            view.update_step_time(hists.get(self.step_time_metric))
            try:
                snap = json.loads(self._get(base + "/healthz"))
                view.state = snap.get("state", "unknown")
                view.healthy = bool(snap.get("healthy", False))
            except urllib.error.HTTPError as e:  # 503 carries the body too
                try:
                    snap = json.loads(e.read().decode("utf-8"))
                    view.state = snap.get("state", "unknown")
                except Exception:  # noqa: BLE001
                    view.state = "unknown"
                view.healthy = False
            if self.events_tail > 0:
                try:
                    body = self._get(
                        f"{base}/events?n={self.events_tail}")
                    view.last_events = [
                        json.loads(ln) for ln in body.splitlines() if ln]
                except Exception:  # noqa: BLE001 - /events is optional
                    view.last_events = []
            view.reachable = True
            view.error = None
            view.last_scrape_ts = time.time()
        except Exception as e:  # noqa: BLE001 - dead hosts are normal
            view.reachable = False
            view.healthy = False
            view.error = f"{type(e).__name__}: {e}"
            with self._lock:
                self._scrape_errors += 1

    def _detect_straggler(self, views: list) -> None:
        means = {v.host_id: v.step_time_mean_ms for v in views
                 if v.reachable and v.step_time_mean_ms is not None
                 and v.step_time_mean_ms > 0}
        if len(means) < 2:
            self._current_ratio = math.nan
            self._current_straggler = None
            return
        ordered = sorted(means.values())
        median = ordered[(len(ordered) - 1) // 2]  # lower median
        slowest_host, slowest = max(means.items(), key=lambda kv: kv[1])
        ratio = slowest / median if median > 0 else math.nan
        self._current_ratio = ratio
        self._current_straggler = slowest_host
        for v in views:
            if v.host_id == slowest_host and ratio >= self.straggler_ratio:
                v.skew_streak += 1
                if v.skew_streak == self.straggler_window:
                    self._stragglers_detected += 1
                    log.warning(
                        "straggler detected: host %d step-time %.3fms is "
                        "%.2fx the fleet median %.3fms",
                        v.host_id, slowest, ratio, median)
                    if self._journal is not None:
                        try:
                            self._journal.emit(
                                "straggler_detected", host=v.host_id,
                                ratio=round(ratio, 3),
                                step_time_mean_ms=round(slowest, 3),
                                fleet_median_ms=round(median, 3),
                                window=self.straggler_window)
                        except Exception:  # noqa: BLE001
                            log.warning("straggler journal emit failed",
                                        exc_info=True)
            else:
                v.skew_streak = 0

    def scrape_once(self) -> dict:
        """One full pass over the current targets; returns snapshot()."""
        with self._lock:
            views = [self._hosts[h] for h in sorted(self._targets)]
        for view in views:
            self._scrape_host(view)
        with self._lock:
            self._scrapes += 1
            self._detect_straggler(views)
            n_reach = sum(v.reachable for v in views)
            n_healthy = sum(v.healthy for v in views)
            self.registry.set_scalars({
                "fleet/hosts": len(views),
                "fleet/reachable_hosts": n_reach,
                "fleet/healthy_hosts": n_healthy,
                "fleet/scrapes": self._scrapes,
                "fleet/scrape_errors": self._scrape_errors,
                "fleet/straggler_ratio": (
                    self._current_ratio
                    if math.isfinite(self._current_ratio) else 0.0),
                "fleet/straggler_host": (
                    self._current_straggler
                    if self._current_straggler is not None else -1),
                "fleet/stragglers_detected": self._stragglers_detected,
            }, step=self._scrapes)
        return self.snapshot()

    # -- exposition ------------------------------------------------------------

    def merged_histograms(self) -> dict:
        """Fleet-wide histograms: same-name per-host histograms folded
        together (the ladder is identical by construction)."""
        merged: dict[str, StreamingHistogram] = {}
        with self._lock:
            views = list(self._hosts.values())
        for view in views:
            for name, h in view.hists.items():
                if name not in merged:
                    merged[name] = StreamingHistogram(
                        min_value=h.min_value, growth=h.growth,
                        n_buckets=h.n_buckets)
                try:
                    merged[name].merge(h)
                except ValueError:
                    log.warning("fleet merge skipped %s: ladder mismatch",
                                name)
        return merged

    def render_prometheus(self) -> str:
        """Fleet-only exposition block, appended by the supervisor's
        exporter after its own registry: merged ``fleet_<hist>`` series
        plus per-host attribution gauges."""
        lines: list[str] = []
        for name, h in sorted(self.merged_histograms().items()):
            lines.extend(render_histogram_lines(f"fleet_{name}", h))
        with self._lock:
            views = [v for _, v in sorted(self._hosts.items())]
        lines.append("# TYPE fleet_host_up gauge")
        for v in views:
            lines.append(f'fleet_host_up{{host="{v.host_id}"}} '
                         f"{int(v.reachable)}")
        lines.append("# TYPE fleet_host_healthy gauge")
        for v in views:
            lines.append(f'fleet_host_healthy{{host="{v.host_id}"}} '
                         f"{int(v.healthy)}")
        lines.append("# TYPE fleet_host_step_time_mean_ms gauge")
        for v in views:
            if v.step_time_mean_ms is not None:
                lines.append(
                    f'fleet_host_step_time_mean_ms{{host="{v.host_id}"}} '
                    f"{repr(float(v.step_time_mean_ms))}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """JSON-able fleet state for the ``/fleet`` endpoint."""
        with self._lock:
            views = [v for _, v in sorted(self._hosts.items())]
            return {
                "targets": dict(self._targets),
                "hosts": [v.snapshot() for v in views],
                "scrapes": self._scrapes,
                "scrape_errors": self._scrape_errors,
                "straggler": {
                    "ratio": (self._current_ratio
                              if math.isfinite(self._current_ratio)
                              else None),
                    "host": self._current_straggler,
                    "threshold": self.straggler_ratio,
                    "window": self.straggler_window,
                    "detected": self._stragglers_detected,
                },
            }

    # -- background loop -------------------------------------------------------

    def start(self) -> "FleetScraper":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="ObsExporter-fleet", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 - the loop must survive
                log.warning("fleet scrape pass failed", exc_info=True)
            self._stop.wait(self.interval_s)

    def close(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
