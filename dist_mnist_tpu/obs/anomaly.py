"""In-loop anomaly detection: robust detectors over loss and step time.

The telemetry spine records what happened; this module notices *when it
goes wrong*, while the run is still going, without touching the run. An
``AnomalyHook`` rides the normal hook cadence, feeds two
``RobustDetector``s (loss value, per-cadence mean step time), and on a
detection:

- journals an ``anomaly`` event (kind, step, value, robust z-score,
  window median) so ``tail_run.py`` and the fleet scraper surface it;
- flips the process ``/healthz`` state to ``degraded`` — a
  200-but-flagged state (obs/exporter.py): the process is still doing
  useful work, routers keep sending traffic, but the flag is visible in
  the body, in ``process_state{state="degraded"}``, and in the
  supervisor's ``/fleet`` view. After ``recovery_cadences`` consecutive
  clean checks the hook restores ``training``.

Detection is deliberately robust rather than parametric: a sliding
window's median/MAD give a z-score that one spike cannot poison (mean/
stddev would chase the outlier it is trying to flag), with an EWMA kept
alongside purely as smoothed context for the journal record. The robust
z is ``|x - median| / (1.4826 * MAD)`` — the 1.4826 factor scales MAD
to a stddev equivalent under normality, so thresholds read in sigmas.

The bit-identical invariant (docs/RESILIENCE.md) extends to this hook:
it only *reads* — one cadence-gated ``device_get`` of the loss (the
NaNGuardHook sync budget) and host-side histogram counters for step
time — and never mutates state, outputs, or control flow. bench.py
--faults runs it enabled and asserts the trajectory stays bit-identical
to the obs-disabled run.

jax is imported lazily inside the hook so the module (and detector)
stay importable from jax-free processes.
"""

from __future__ import annotations

import collections
import logging
import math

from dist_mnist_tpu.obs import events as events_mod

log = logging.getLogger(__name__)

__all__ = ["RobustDetector", "AnomalyHook"]

# MAD -> stddev-equivalent scale under a normal distribution
_MAD_SCALE = 1.4826


class RobustDetector:
    """Sliding-window median/MAD outlier detector with an EWMA sidecar.

    ``check(x)`` scores x against the *current* window, then admits it;
    outliers enter the window too — the median/MAD absorb them, which
    is the point of using robust statistics. Returns a dict verdict
    (anomaly flag, z, median, mad, ewma) or None during warmup.
    """

    def __init__(self, *, window: int = 64, threshold: float = 6.0,
                 warmup: int = 8, ewma_alpha: float = 0.1):
        if window < 4 or warmup < 2:
            raise ValueError(f"window={window} warmup={warmup} too small")
        self.threshold = float(threshold)
        self.warmup = int(warmup)
        self._values: collections.deque = collections.deque(maxlen=window)
        self._ewma: float | None = None
        self._alpha = float(ewma_alpha)

    def _median(self, xs: list) -> float:
        xs = sorted(xs)
        n = len(xs)
        mid = xs[n // 2]
        return mid if n % 2 else (xs[n // 2 - 1] + mid) / 2.0

    def check(self, x: float) -> dict | None:
        x = float(x)
        if math.isnan(x):
            return None
        self._ewma = x if self._ewma is None else \
            self._alpha * x + (1 - self._alpha) * self._ewma
        verdict = None
        if len(self._values) >= self.warmup:
            med = self._median(list(self._values))
            mad = self._median([abs(v - med) for v in self._values])
            scale = _MAD_SCALE * mad
            if scale <= 0:
                # a flat window: fall back to a relative-change guard so
                # a constant signal jumping still registers
                scale = max(abs(med) * 1e-3, 1e-12)
            z = abs(x - med) / scale
            verdict = {
                "anomaly": z >= self.threshold,
                "z": z,
                "median": med,
                "mad": mad,
                "ewma": self._ewma,
            }
        self._values.append(x)
        return verdict


class AnomalyHook:
    """Train-loop hook: robust anomaly watch over loss and step time.

    Matches the hooks/base.Hook protocol structurally (no import, so
    this stays usable from obs without the hooks package). Cadence and
    sync budget follow NaNGuardHook: one ``device_get`` of the loss
    scalar per ``every_steps``; step time comes free from the loop's
    ``step_time_hist`` sum/count deltas (no device sync at all).
    """

    def __init__(self, *, key: str = "loss", every_steps: int = 25,
                 health=None, threshold: float = 6.0, window: int = 64,
                 warmup: int = 8, recovery_cadences: int = 3):
        self._key = key
        self._every = max(1, int(every_steps))
        self._health = health
        self._loss_det = RobustDetector(window=window, threshold=threshold,
                                        warmup=warmup)
        self._step_det = RobustDetector(window=window, threshold=threshold,
                                        warmup=warmup)
        self._recovery = max(1, int(recovery_cadences))
        self._next_check: int | None = None
        self._prev_count = 0
        self._prev_sum = 0.0
        self._degraded = False
        self._clean_streak = 0
        self.anomalies: list[dict] = []  # for bench harnesses / tests
        self.last: dict = {}

    # -- hook protocol ---------------------------------------------------------

    def begin(self, loop):
        self._loop = loop
        self._next_check = loop.initial_step + self._every
        hist = getattr(loop, "step_time_hist", None)
        if hist is not None:
            self._prev_count, self._prev_sum = hist.count, hist.sum

    def before_step(self, step):
        pass

    def after_step(self, step, state, outputs):
        if self._next_check is None or step < self._next_check:
            return
        self._next_check = step + self._every
        found = []
        if self._key in outputs:
            import jax  # lazy: keep obs.anomaly importable without jax

            # the NaNGuardHook budget: ONE scalar fetch per cadence
            val = float(jax.device_get(outputs[self._key]))  # lint: ok[host-sync] one scalar per cadence, the detector NEEDS the value
            v = self._loss_det.check(val)
            self.last["loss"] = val
            if v is not None and v["anomaly"]:
                found.append(("loss", val, v))
        hist = getattr(self._loop, "step_time_hist", None)
        if hist is not None:
            d_count = hist.count - self._prev_count
            d_sum = hist.sum - self._prev_sum
            self._prev_count, self._prev_sum = hist.count, hist.sum
            if d_count > 0:
                mean_ms = d_sum / d_count
                v = self._step_det.check(mean_ms)
                self.last["step_time_ms"] = mean_ms
                if v is not None and v["anomaly"]:
                    found.append(("step_time", mean_ms, v))
        if found:
            self._clean_streak = 0
            for kind, value, v in found:
                rec = {"kind": kind, "step": int(step),
                       "value": round(float(value), 6),
                       "zscore": round(v["z"], 3),
                       "median": round(v["median"], 6),
                       "ewma": round(v["ewma"], 6)}
                self.anomalies.append(rec)
                log.warning("anomaly: %s=%g at step %d (z=%.1f, median=%g)",
                            kind, value, step, v["z"], v["median"])
                events_mod.emit("anomaly", **rec)
            self._set_degraded(found)
        else:
            self._maybe_recover(step)

    def end(self, state):
        # leave /healthz to the loop's terminal transition; a run ending
        # while degraded still reports stopped/failed from the loop
        pass

    # -- health plumbing -------------------------------------------------------

    def _set_degraded(self, found) -> None:
        if self._health is None or self._degraded:
            self._degraded = True
            return
        # only shade *training*: draining/preempted/etc. outrank us
        if self._health.state == "training":
            kinds = ",".join(sorted({k for k, _, _ in found}))
            self._health.set("degraded", f"anomaly: {kinds}")
        self._degraded = True

    def _maybe_recover(self, step) -> None:
        if not self._degraded:
            return
        self._clean_streak += 1
        if self._clean_streak < self._recovery:
            return
        self._degraded = False
        self._clean_streak = 0
        if self._health is not None and self._health.state == "degraded":
            self._health.set("training", f"recovered at step {step}")
        events_mod.emit("anomaly_cleared", step=int(step))
