"""HTTP exposition: ``/metrics`` (Prometheus text), ``/healthz``
(process state machine), ``/events`` (journal tail).

One stdlib ``ThreadingHTTPServer`` on a daemon thread per process,
enabled by ``--metrics_port`` on the train and serve CLIs. The server
reads the live ``MetricRegistry`` / ``HealthState`` / journal file on
each GET — no background sampling loop, nothing to fall behind.

Health is a tiny explicit state machine rather than a boolean:

    starting -> training | serving -> draining | preempted -> stopped
                       `-> degraded  -> resizing               | failed

``/healthz`` returns 200 while the process is doing useful work
(starting/training/serving/degraded) and 503 otherwise, so a fleet
router can stop sending traffic to a draining replica before it
disappears (ROADMAP "replica health/drain integration with the
supervisor"). ``resizing`` is the elastic supervisor's mesh
re-formation window (cli/launch.py --elastic, docs/RESILIENCE.md
"Elastic generations"): a membership change was decided and the next
generation has not started yet — deliberately NOT healthy, so routers
hold traffic exactly like a drain. ``degraded`` is the anomaly
detector's 200-but-flagged state (obs/anomaly.py): the process is
still making progress — killing or rerouting it would cost more than
the anomaly — but operators and the fleet scraper can see the flag in
the /healthz body and in ``process_state{state="degraded"}``.

Threads are named ``ObsExporter*`` and live exporters are tracked in
``_LIVE_EXPORTERS`` so the conftest leak-check can prove every test
closed its server.
"""

from __future__ import annotations

import json
import logging
import math
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

log = logging.getLogger(__name__)

__all__ = ["HealthState", "MetricsExporter"]

# conftest leak registry: every started-but-unclosed exporter is a leak.
_LIVE_EXPORTERS: list = []

_HEALTHY = frozenset({"starting", "training", "serving", "degraded"})
_STATES = frozenset(
    {"starting", "training", "serving", "degraded", "draining", "resizing",
     "preempted", "stopped", "failed"})


class HealthState:
    """Thread-safe process state with a transition timestamp."""

    def __init__(self, state: str = "starting", *, generation: int = 0):
        self._lock = threading.Lock()
        self._state = "starting"
        self._detail = None
        self._since = time.time()
        self.generation = int(generation)
        if state != "starting":
            self.set(state)

    def set(self, state: str, detail: str | None = None) -> None:
        if state not in _STATES:
            raise ValueError(f"unknown health state {state!r}")
        with self._lock:
            if state != self._state:
                self._since = time.time()
            self._state = state
            self._detail = detail

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def healthy(self) -> bool:
        with self._lock:
            return self._state in _HEALTHY

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "healthy": self._state in _HEALTHY,
                "detail": self._detail,
                "since_s": round(time.time() - self._since, 3),
                "generation": self.generation,
            }


# -- Prometheus text exposition -----------------------------------------------

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(tag: str) -> str:
    """Total mangling: any tag becomes a valid Prometheus metric name."""
    name = _PROM_BAD.sub("_", str(tag))
    if not name or not (name[0].isalpha() or name[0] in "_:"):
        name = "_" + name
    return name


def _prom_value(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


def render_histogram_lines(name: str, hist) -> list[str]:
    """Prometheus text lines for one StreamingHistogram (cumulative
    buckets, ``_sum``/``_count``). Shared by the per-process exporter
    and the fleet scraper's merged view (obs/fleet.py)."""
    lines = [f"# TYPE {name} histogram"]
    cum = 0
    for edge, count in hist.buckets():
        # the overflow bucket IS le="+Inf"; the explicit total
        # line below covers it (emitting both would duplicate
        # the series)
        if count == 0 or math.isinf(edge):
            continue
        cum += count
        lines.append(f'{name}_bucket{{le="{repr(float(edge))}"}} {cum}')
    lines.append(f'{name}_bucket{{le="+Inf"}} {hist.count}')
    lines.append(f"{name}_sum {_prom_value(hist.sum)}")
    lines.append(f"{name}_count {hist.count}")
    return lines


def render_prometheus(registry, health: HealthState | None = None,
                      info: dict | None = None) -> str:
    """Render the registry (and health, as ``up``-style gauges) in
    Prometheus text exposition format.

    ``info`` is an optional identity-label dict (host_id / generation /
    role) rendered as a constant ``process_info{...} 1`` info-gauge so
    merged fleet series stay attributable to their source process.
    """
    lines: list[str] = []
    if registry is not None:
        for tag, (value, step, _wall) in sorted(registry.scalars().items()):
            name = _prom_name(tag)
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {_prom_value(value)}")
        for tag, hist in sorted(registry.histograms().items()):
            lines.extend(render_histogram_lines(_prom_name(tag), hist))
    if info:
        labels = ",".join(
            f'{_prom_name(k)}="{v}"' for k, v in sorted(info.items()))
        lines.append("# TYPE process_info gauge")
        lines.append(f"process_info{{{labels}}} 1")
    if health is not None:
        snap = health.snapshot()
        lines.append("# TYPE process_healthy gauge")
        lines.append(f"process_healthy {int(snap['healthy'])}")
        for s in sorted(_STATES):
            lines.append(
                f'process_state{{state="{s}"}} {int(snap["state"] == s)}')
    return "\n".join(lines) + "\n"


# -- HTTP server --------------------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    exporter: "MetricsExporter"  # set on the subclass per server

    def log_message(self, fmt, *args):  # quiet: absl logging owns stderr
        log.debug("exporter: " + fmt, *args)

    def _send(self, code: int, body: str, content_type: str) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 - http.server API
        try:
            url = urlparse(self.path)
            exp = self.exporter
            if url.path == "/metrics":
                body = render_prometheus(exp.registry, exp.health,
                                         info=exp.info)
                if exp.fleet is not None:
                    body += exp.fleet.render_prometheus()
                self._send(200, body, "text/plain; version=0.0.4")
            elif url.path == "/fleet":
                if exp.fleet is None:
                    self._send(404, "no fleet scraper attached\n",
                               "text/plain")
                    return
                self._send(200,
                           json.dumps(exp.fleet.snapshot(), sort_keys=True),
                           "application/json")
            elif url.path == "/healthz":
                if exp.health is None:
                    self._send(200, json.dumps({"state": "unknown"}),
                               "application/json")
                    return
                snap = exp.health.snapshot()
                code = 200 if snap["healthy"] else 503
                self._send(code, json.dumps(snap, sort_keys=True),
                           "application/json")
            elif url.path == "/events":
                from dist_mnist_tpu.obs import events as events_mod

                n = int(parse_qs(url.query).get("n", ["50"])[0])
                if exp.journal_path is None:
                    self._send(404, "no journal configured\n", "text/plain")
                    return
                recs = events_mod.tail_journal(exp.journal_path, n)
                body = "\n".join(
                    json.dumps(r, separators=(",", ":")) for r in recs)
                self._send(200, body + ("\n" if body else ""),
                           "application/x-ndjson")
            else:
                self._send(404, "not found\n", "text/plain")
        except Exception:  # noqa: BLE001 - never kill the serve thread
            log.warning("exporter request failed", exc_info=True)
            try:
                self._send(500, "internal error\n", "text/plain")
            except Exception:  # client already gone
                pass

    # -- serve data plane (POST) ----------------------------------------------
    # Wired only when the owning process is a serve replica
    # (cli/serve.py --serve_forever): /predict executes one inference via
    # exporter.predict_fn, /swap rolls the replica's weights via
    # exporter.swap_fn. Errors map to the typed statuses serve/router.py's
    # HttpReplica reconstructs (429 queue full, 503 shutting down, 504
    # deadline, 500 + error type otherwise), so a remote replica fails
    # EXACTLY like an in-process one under classify_failure.

    def _send_serve_error(self, err: BaseException) -> None:
        from dist_mnist_tpu.serve.admission import (
            DeadlineExceededError,
            QueueFullError,
            ShuttingDownError,
        )

        code = 500
        if isinstance(err, DeadlineExceededError):
            code = 504  # before QueueFull/Shutdown: it's the narrow type
        elif isinstance(err, QueueFullError):
            code = 429
        elif isinstance(err, ShuttingDownError):
            code = 503
        self._send(code, json.dumps(
            {"error": type(err).__name__, "message": str(err)}),
            "application/json")

    def do_POST(self):  # noqa: N802 - http.server API
        try:
            url = urlparse(self.path)
            exp = self.exporter
            length = int(self.headers.get("Content-Length", 0) or 0)
            body = self.rfile.read(length) if length else b""
            if url.path == "/predict":
                if exp.predict_fn is None:
                    self._send(404, "not a serve replica\n", "text/plain")
                    return
                import io as _io

                import numpy as _np

                q = parse_qs(url.query).get("deadline_ms", [None])[0]
                deadline_ms = float(q) if q not in (None, "", "None") else None
                image = _np.load(_io.BytesIO(body), allow_pickle=False)
                try:
                    res = exp.predict_fn(image, deadline_ms)
                except Exception as err:  # noqa: BLE001 - typed status below
                    self._send_serve_error(err)
                    return
                self._send(200, json.dumps({
                    "logits": _np.asarray(res.logits, dtype=float).tolist(),
                    "label": int(res.label),
                    "latency_ms": float(res.latency_ms),
                }), "application/json")
            elif url.path == "/swap":
                if exp.swap_fn is None:
                    self._send(404, "not a serve replica\n", "text/plain")
                    return
                step = int(parse_qs(url.query).get("step", ["-1"])[0])
                try:
                    out = exp.swap_fn(step)
                except Exception as err:  # noqa: BLE001 - typed status below
                    self._send_serve_error(err)
                    return
                self._send(200, json.dumps(
                    {"step": step, "result": out}, default=str),
                    "application/json")
            else:
                self._send(404, "not found\n", "text/plain")
        except Exception:  # noqa: BLE001 - never kill the serve thread
            log.warning("exporter POST failed", exc_info=True)
            try:
                self._send(500, "internal error\n", "text/plain")
            except Exception:  # client already gone
                pass


class MetricsExporter:
    """Background /metrics + /healthz + /events server for one process.

    With ``predict_fn``/``swap_fn`` wired it is also a serve replica's
    data plane: POST /predict and /swap next to the observability
    endpoints, one port per replica (see _Handler.do_POST)."""

    def __init__(self, registry=None, *, health: HealthState | None = None,
                 journal_path=None, port: int = 0, host: str = "127.0.0.1",
                 info: dict | None = None, fleet=None,
                 predict_fn=None, swap_fn=None):
        self.registry = registry
        self.health = health
        self.journal_path = journal_path
        # identity labels (host_id/generation/role) -> process_info gauge
        self.info = dict(info) if info else None
        # optional obs/fleet.FleetScraper: merged fleet series on /metrics
        # plus the /fleet JSON endpoint
        self.fleet = fleet
        # serve data plane: (image, deadline_ms) -> InferenceResult, and
        # step -> swap result; both None on pure-observability processes
        self.predict_fn = predict_fn
        self.swap_fn = swap_fn
        self.host = host
        self.port = int(port)
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def start(self) -> "MetricsExporter":
        if self._server is not None:
            return self
        handler = type("_BoundHandler", (_Handler,), {"exporter": self})
        server = ThreadingHTTPServer((self.host, self.port), handler)
        server.daemon_threads = True
        self._server = server
        self.port = server.server_address[1]
        self._thread = threading.Thread(
            target=server.serve_forever, kwargs={"poll_interval": 0.1},
            name=f"ObsExporter-{self.port}", daemon=True)
        _LIVE_EXPORTERS.append(self)
        self._thread.start()
        log.info("metrics exporter listening on http://%s:%d/metrics",
                 self.host, self.port)
        return self

    def close(self) -> None:
        server, thread = self._server, self._thread
        self._server = self._thread = None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(timeout=5)
        if self in _LIVE_EXPORTERS:
            _LIVE_EXPORTERS.remove(self)

    def url(self, path: str = "/metrics") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
