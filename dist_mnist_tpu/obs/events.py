"""Structured run journal: append-only JSONL lifecycle record.

One journal file records a whole run — across processes and across
supervisor restart generations. Every record is a single JSON line

    {"seq": n, "ts": <unix>, "pid": <pid>, "gen": <generation>,
     "host": <host_id, when known>, "event": "<name>", ...fields}

``host`` is the stable elastic-membership host id (set by the
supervisor via ``DIST_MNIST_TPU_HOST_ID``): unlike ``pid`` it survives
generation rollover, which is what lets scripts/fleet_trace.py keep one
timeline track per host across a resize.

``seq`` is monotonic per (pid, generation); ``(pid, gen, seq)`` is a
total order key within one process's lifetime. Writes go through an
``O_APPEND`` fd with one ``os.write`` per record: on POSIX, appends
under ``PIPE_BUF`` bytes are atomic, so the supervisor and its child
processes share one file without interleaving torn lines.

The module-level *current journal* lets deep subsystems (checkpoint
manager, fault injectors, compile cache, the autotuner's ``tuning/*``
family — search trials, winners, applied knobs, stale keys) emit events
without threading a journal handle through every constructor:
``events.emit(...)`` is a no-op unless someone installed a journal via
``set_journal``.

Stdlib-only on purpose: importable from the supervisor and from any
process before jax/numpy are up.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

log = logging.getLogger(__name__)

__all__ = [
    "RunJournal", "set_journal", "get_journal", "emit",
    "read_journal", "tail_journal",
    "ENV_JOURNAL", "ENV_GENERATION", "ENV_HOST_ID",
]

# Env vars the supervisor sets so every child generation lands in the
# supervisor-owned journal (mirrors the --compile_cache_dir injection).
ENV_JOURNAL = "DIST_MNIST_TPU_JOURNAL"
ENV_GENERATION = "DIST_MNIST_TPU_GENERATION"
# Stable host identity across generations. Defined (with the same
# value) in cluster/membership.py; duplicated here so the journal
# stays importable without pulling the cluster package.
ENV_HOST_ID = "DIST_MNIST_TPU_HOST_ID"


class RunJournal:
    """Append-only JSONL event sink. Thread-safe; multi-process-safe on
    POSIX for records under PIPE_BUF (ours are tiny)."""

    def __init__(self, path, *, generation: int = 0,
                 host_id: int | None = None):
        self.path = os.fspath(path)
        self.generation = int(generation)
        if host_id is None:
            env_host = os.environ.get(ENV_HOST_ID)
            host_id = int(env_host) if env_host is not None else None
        # stable host id (survives generation rollover); None for
        # single-process runs and the supervisor itself
        self.host_id = host_id
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._fd = os.open(self.path,
                           os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        self._seq = 0
        self._lock = threading.Lock()
        self._closed = False

    def emit(self, event: str, **fields) -> dict:
        rec = {"seq": 0, "ts": time.time(), "pid": os.getpid(),
               "gen": self.generation, "event": str(event)}
        if self.host_id is not None:
            rec["host"] = self.host_id
        rec.update(fields)
        with self._lock:
            if self._closed:
                return rec
            rec["seq"] = self._seq
            self._seq += 1
            line = json.dumps(rec, sort_keys=False,
                              separators=(",", ":"), default=str) + "\n"
            os.write(self._fd, line.encode("utf-8"))
        return rec

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            os.close(self._fd)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __repr__(self):
        return f"RunJournal({self.path!r}, gen={self.generation})"


# -- module-level current journal ---------------------------------------------

_CURRENT: RunJournal | None = None


def set_journal(journal: RunJournal | None) -> RunJournal | None:
    """Install the process-wide journal; returns the previous one so
    callers can restore it (``prev = set_journal(j) ... set_journal(prev)``)."""
    global _CURRENT
    prev = _CURRENT
    _CURRENT = journal
    return prev


def get_journal() -> RunJournal | None:
    return _CURRENT


def emit(event: str, **fields) -> None:
    """Emit to the current journal; silently no-op when none is installed.
    Never raises: telemetry must not take down the run it is recording."""
    j = _CURRENT
    if j is None:
        return
    try:
        j.emit(event, **fields)
    except Exception:  # noqa: BLE001 - observability is best-effort
        log.warning("journal emit failed for event %r", event, exc_info=True)


# -- reading ------------------------------------------------------------------

def read_journal(path) -> list[dict]:
    """Parse a journal file; skips torn/invalid trailing lines."""
    out: list[dict] = []
    try:
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    log.warning("skipping malformed journal line: %.80s", line)
    except FileNotFoundError:
        return []
    return out


def tail_journal(path, n: int = 50) -> list[dict]:
    recs = read_journal(path)
    return recs[-n:] if n >= 0 else recs
