"""Collective matmul: ICI communication overlapped behind chunk matmuls.

The scaling-book TP recipe: a Megatron layer needs `all_gather(x) @ W_col`
before the column-parallel matmul and a reduce(-scatter) after the
row-parallel one. Done naively, the collective and the matmul serialize —
the MXU idles for a full ICI round-trip per layer. The classic fix is to
decompose the collective into its ring steps (one `ring_shift` hop per
step) and interleave: matmul the chunk that is already resident while the
next hop is in flight, so the ICI time hides behind MXU time whenever
`chunk_matmul_time >= hop_time`.

XLA's GSPMD already performs this fusion in common cases (it is the
DEFAULT path everywhere else in this framework — see parallel/sharding.py);
these explicit shard_map variants exist for when manual control is wanted
(custom schedules, odd shapes GSPMD won't overlap) and as the executable
documentation of what the compiler does on the `model` axis. Reference
counterpart: none — the PS design (SURVEY.md §3.3) serialized ALL
communication by construction; overlap is a TPU-native capability.

Both primitives use the single counter-clockwise ring from
collectives.ring_shift; `axis` is any live mesh axis name.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from dist_mnist_tpu.cluster.mesh import compat_shard_map
from dist_mnist_tpu.parallel.collectives import ring_shift


def allgather_matmul(x, w, mesh: Mesh, axis: str = "model"):
    """`all_gather(x, axis) @ w` with the gather's ring hops overlapped.

    x: [M, D] sharded over `axis` on dim 0 (M = n * m rows globally).
    w: [D, F] sharded over `axis` on dim 1 (each device holds [D, F/n]).
    Returns [M, F] sharded over `axis` on dim 1 — every device computes
    the FULL row range against its own weight columns, chunk by chunk,
    rotating the x shards around the ring between chunk matmuls.
    """
    n = mesh.shape[axis]
    assert x.shape[0] % n == 0, \
        f"x rows {x.shape[0]} not divisible by {axis}={n}"
    assert w.shape[1] % n == 0, \
        f"w cols {w.shape[1]} not divisible by {axis}={n}"

    def body(x_local, w_local):
        m = x_local.shape[0]
        i = jax.lax.axis_index(axis)
        out = jnp.zeros((n * m, w_local.shape[1]), x_local.dtype)
        buf = x_local
        for k in range(n):
            # buf currently holds shard (i + k) % n; matmul it into its
            # row block while the NEXT rotation's hop overlaps (XLA
            # schedules the independent ring_shift alongside the dot)
            block = (i + k) % n
            out = jax.lax.dynamic_update_slice(
                out, buf @ w_local, (block * m, 0)
            )
            if k < n - 1:
                buf = ring_shift(buf, axis, reverse=True)
        return out

    return compat_shard_map(
        body, mesh=mesh,
        in_specs=(P(axis, None), P(None, axis)),
        out_specs=P(None, axis),
    )(x, w)


def matmul_reducescatter(x, w, mesh: Mesh, axis: str = "model"):
    """`reduce_scatter(x @ w, axis)` with the reduction ring overlapped.

    x: [M, D] with D sharded over `axis` (each device holds [M, D/n]).
    w: [D, F] with D sharded over `axis` (each device holds [D/n, F]).
    The full local partial `x_local @ w_local` is NEVER materialized:
    each ring step matmuls ONE row chunk of x_local against w_local and
    adds it to the in-flight accumulator — the chunk dot is independent
    of the hop it rides alongside, so the ICI time hides behind the MXU
    (the same schedule allgather_matmul uses, reversed). Each device ends
    with its [M/n, F] row block of the true product — the Megatron
    row-parallel epilogue without a serialized all-reduce.
    """
    n = mesh.shape[axis]

    def body(x_local, w_local):
        M = x_local.shape[0]
        assert M % n == 0, f"rows {M} not divisible by {axis}={n}"
        m = M // n
        i = jax.lax.axis_index(axis)

        def chunk_dot(idx):
            rows = jax.lax.dynamic_slice(
                x_local, (idx * m, 0), (m, x_local.shape[1])
            )
            return rows @ w_local  # [m, F] partial sum over local D

        # ring reduce-scatter: at step s the accumulator on device i holds
        # the growing partial sum for row block (i + 1 + s) mod n; after
        # n-1 hops each block lands on its home device fully reduced. The
        # step-s chunk_dot has no dependence on the in-flight hop.
        acc = chunk_dot((i + 1) % n)
        for s in range(1, n):
            acc = ring_shift(acc, axis, reverse=True)
            acc = acc + chunk_dot((i + 1 + s) % n)
        return acc

    return compat_shard_map(
        body, mesh=mesh,
        in_specs=(P(None, axis), P(axis, None)),
        out_specs=P(axis, None),
    )(x, w)
