"""Parallelism: sharding rules, explicit collectives, sequence parallelism.

This package is the TPU-native replacement for the reference's entire
distributed fabric (SURVEY.md §2.5 rows 21-27 — GrpcServer, Master/Worker
services, graph partitioning, rendezvous, RecvTensor RPC): placement is a
PartitionSpec per array instead of replica_device_setter's round-robin
(§2.2 row 5), and every byte that crossed gRPC per step becomes an XLA
collective over ICI compiled into the step program.

- `sharding.py` — param/batch PartitionSpec rules per mesh axis
  (DP/TP/FSDP — ZeRO-style param+opt-state sharding over `data`).
- `collectives.py` — thin named wrappers over lax collectives + shard_map
  helpers for the explicit-SPMD path.
- `ring_attention.py` — sequence-parallel ring attention (ppermute K/V).
- `ulysses.py` — all-to-all head<->sequence reshard alternative.
- `pipeline.py` — GPipe pipeline parallelism over the `pipe` axis.
- `moe.py` — expert-parallel switch MoE (all_to_all dispatch).
- `collective_matmul.py` — explicit overlapped AG->matmul / matmul->RS
  rings (the scaling-book TP idiom; GSPMD's automatic fusion is default).
- `overlap.py` — fsdp comm/compute overlap: bucketed param all-gather
  prefetch + reduce-scatter flushed during the backward, same latency-
  hiding idiom applied to the ZeRO axis instead of the TP axis.
- `ps_demo/` — native C++ demo of the reference's async-PS protocol.
"""

from dist_mnist_tpu.parallel.sharding import (
    ShardingRules,
    DP_RULES,
    TP_RULES,
    FSDP_RULES,
    FSDP_TP_RULES,
    derive_state_specs,
    reshard_state,
    shard_train_state,
    params_sharding,
    tree_sharding,
)
from dist_mnist_tpu.parallel.overlap import (
    OverlapConfig,
    build_param_gather,
    plan_stats,
    prefetched_layer_matmul,
)

__all__ = [
    "ShardingRules",
    "DP_RULES",
    "TP_RULES",
    "FSDP_RULES",
    "FSDP_TP_RULES",
    "derive_state_specs",
    "reshard_state",
    "shard_train_state",
    "params_sharding",
    "tree_sharding",
    "OverlapConfig",
    "build_param_gather",
    "plan_stats",
    "prefetched_layer_matmul",
]
