"""Pipeline parallelism — GPipe microbatch schedule over the `pipe` axis.

Absent from the reference (SURVEY.md §2.6 lists PP as "not needed for
parity"), but first-class here: a stack of identically-shaped layer stages
is sharded over the `pipe` mesh axis (one stage per pipe rank), a global
batch is split into M microbatches, and activations flow stage→stage around
the ICI ring via `ppermute`. The schedule is the classic GPipe ladder: at
tick t, stage s computes microbatch t-s; the pipe drains after
M + S - 1 ticks. Bubble fraction = (S-1)/(M+S-1) — pick M >= 4*S to keep
the MXU busy.

Everything is inside one SPMD program, so `jax.grad` differentiates through
the schedule (`ppermute` transposes to the reverse rotation), giving the
1F1B-equivalent backward sweep for free — no hand-written send/recv of
gradients, which is what a CUDA/NCCL pipeline implementation spends most of
its code on.

Composability: the batch dimension stays sharded over `data` (each pipe
group runs the same schedule on its slice of the batch), so PP x DP works
out of one spec. Requires all stages to share one activation shape — true
for the repeated encoder blocks this targets (ViT depth, MLP towers).

Schedules: the classic GPipe ladder (default), and the CIRCULAR
(interleaved/Megatron-style) schedule via `circular_chunks=v`: each rank
holds v non-adjacent chunks of the stage stack (global stage g = c*S + s
lives on rank s as chunk c), so a microbatch laps the ring v times. The
bubble then costs (S-1) CHUNK-times instead of (S-1) stage-times: wall
drops from (M+S-1)*v to M*v + S - 1 chunk-times — at M=8, S=4, v=3 that is
27 vs 33, ~18% less. The schedule stays uniform-SPMD: every rank runs the
same local program delayed by its rank index (local time q = t - s selects
microbatch (q//(S*v))*S + q%S and chunk (q//S) mod v), and every transfer
is the same +1 ring hop — including the wrap S-1 -> 0 between chunk laps,
where rank 0 swaps a finished microbatch's output for the next group's
fresh input. See scripts/pp_probe.py for the measured overhead.

Entry points:
- `pipeline_apply_inner(fn, stage_params, x_mb, rng=None, axis_name=...)`
  — inside shard_map; x_mb is [M, mb, ...] microbatched activations.
- `pipeline_apply(fn, stacked_params, x, num_microbatches, mesh,
  circular_chunks=v, rng=None)` — jits a shard_map over `mesh`'s pipe
  (and data) axes; v>1 selects the circular schedule (stacked leading dim
  S*v); rng threads a per-(data shard, microbatch, global stage) key into
  fn for stochastic stages (dropout).
- `stack_stage_params(params_list)` — stack S per-stage pytrees along a new
  leading axis for sharding over `pipe`.
"""

from __future__ import annotations

from functools import partial

import jax

from dist_mnist_tpu.cluster.mesh import compat_axis_size
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dist_mnist_tpu.cluster.mesh import DATA_AXIS, PIPE_AXIS
from dist_mnist_tpu.parallel.collectives import ring_shift


def stack_stage_params(params_list):
    """Stack S per-stage param pytrees into one pytree with leading dim S
    (the dim sharded over `pipe`). All stages must be isomorphic."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *params_list)


def pipeline_apply_inner(fn, stage_params, x_mb, rng=None,
                         axis_name: str = PIPE_AXIS,
                         fold_data_axis: bool = False,
                         skip_bubble: bool = False):
    """Run the GPipe schedule; call inside shard_map.

    fn: (params, x) -> y with y.shape == x.shape (one stage); with `rng`
      given, (params, x, key) -> y, where key is derived per
      (microbatch, stage) — see below.
    stage_params: THIS stage's params, leading stage axis of size 1
      (as delivered by shard_map with spec P(pipe)); squeezed here.
    x_mb: [M, mb, ...] microbatches (replicated over `pipe`).
    rng: optional base PRNG key (replicated). Stage s working microbatch m
      receives fold_in(fold_in(rng, m), s) — a pure function of the
      schedule position, so stage fns stay pure and the schedule stays
      uniform-SPMD (VERDICT r4 weak #5: this is what lets pipelined models
      keep dropout).
    fold_data_axis: fold this shard's data-axis index into rng first —
      REQUIRED whenever the batch is data-sharded, else every data rank
      derives the same key and draws the same shard-shaped mask (bit-equal
      dropout across DP shards — correlated noise, caught in code review;
      pipeline_apply sets this automatically).
    skip_bubble: wrap the stage in `lax.cond(valid, fn, identity)` so
      fill/drain ticks skip the stage compute instead of computing masked
      garbage (every rank otherwise runs fn on every tick — VERDICT r4
      weak #4). Outputs are identical either way: garbage ticks only ever
      feed garbage ticks (rank s+1's first valid tick consumes rank s's
      first valid output). Off by default until measured on multi-chip
      hardware — a cond can also inhibit XLA's compute/ppermute overlap.
      Requires fn to preserve dtype as well as shape (the identity branch
      must match).
    Returns [M, mb, ...] outputs (identical on every pipe rank).
    """
    params = jax.tree.map(lambda p: jnp.squeeze(p, axis=0), stage_params)
    if rng is not None and fold_data_axis:
        rng = jax.random.fold_in(rng, lax.axis_index(DATA_AXIS))
    s = lax.axis_index(axis_name)
    n_stages = compat_axis_size(axis_name)
    n_mb = x_mb.shape[0]
    first = jnp.equal(s, 0)
    last = jnp.equal(s, n_stages - 1)

    def tick(t, carry):
        act, out_buf = carry
        # stage 0 ingests microbatch t (clip keeps the index static-safe
        # during the drain ticks; the value is masked by `first` anyway)
        inp = lax.dynamic_index_in_dim(
            x_mb, jnp.clip(t, 0, n_mb - 1), axis=0, keepdims=False
        )
        act = jnp.where(first, inp, act)

        def run_stage(a):
            if rng is not None:
                # microbatch this stage works on at tick t (fill/drain
                # ticks compute on masked garbage; key choice irrelevant)
                m_cur = jnp.clip(t - s, 0, n_mb - 1)
                key = jax.random.fold_in(jax.random.fold_in(rng, m_cur),
                                         s)
                return fn(params, a, key)
            return fn(params, a)

        if skip_bubble:
            valid = jnp.logical_and(t >= s, t - s < n_mb)
            y = lax.cond(valid, run_stage, lambda a: a, act)
        else:
            y = run_stage(act)
        # last stage retires microbatch t-(S-1); writes during fill ticks
        # (t < S-1) land on index 0 masked off by `ready`
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_mb - 1)
        ready = jnp.logical_and(last, t >= n_stages - 1)
        slot = lax.dynamic_index_in_dim(out_buf, out_idx, axis=0,
                                        keepdims=False)
        out_buf = lax.dynamic_update_index_in_dim(
            out_buf, jnp.where(ready, y, slot), out_idx, axis=0
        )
        # rotate activations one stage forward (neighbour ICI hop); XLA
        # overlaps the ppermute with the next tick's compute
        act = ring_shift(y, axis_name)
        return act, out_buf

    act0 = jnp.zeros_like(x_mb[0])
    out0 = jnp.zeros_like(x_mb)
    _, out_buf = lax.fori_loop(0, n_mb + n_stages - 1, tick, (act0, out0),
                               unroll=False)
    # out_buf only ever receives last-rank writes (`ready` implies `last`;
    # every other rank's buffer stays zero), so the psum IS the
    # rank-(S-1)-sourced broadcast — with zeros elsewhere there is nothing
    # to mask, and no cheaper jax primitive exists for one-to-all (a
    # ppermute chain would serialize S-1 hops of the same bytes)
    return lax.psum(out_buf, axis_name)


def pipeline_apply_circular_inner(fn, chunk_params, x_mb, rng=None,
                                  axis_name: str = PIPE_AXIS,
                                  n_chunks: int = 1,
                                  fold_data_axis: bool = False,
                                  skip_bubble: bool = False):
    """The circular (interleaved) schedule; call inside shard_map.

    chunk_params: THIS rank's v chunks, shape [1, v, ...] (P(pipe) on dim
      0); chunk c holds global stage c*S + s. x_mb: [M, mb, ...], M % S == 0.
    rng: optional base key; fn then takes (params, x, key) with key =
      fold_in(fold_in(rng, m), c*S + s) — per (microbatch, GLOBAL stage),
      so the same key schedule as the GPipe path at v=1. fold_data_axis:
      see pipeline_apply_inner (de-correlates DP shards' masks).

    Every rank runs the same local program delayed by its rank index: at
    local time q = t - s it applies chunk c = (q//S) mod v to microbatch
    m = (q//(S*v))*S + q%S, then ring-shifts the result one rank forward.
    The wrap hop S-1 -> 0 between laps doubles as retire/ingest: when a
    microbatch finishes its last chunk on the last rank, rank 0 replaces
    the arriving (finished) activation with the next group's fresh input.
    Wall = M*v + S - 1 ticks of ONE chunk each, vs GPipe's (M+S-1) ticks
    of v chunks each — the fill/drain bubble shrinks by v.
    """
    params = jax.tree.map(lambda p: jnp.squeeze(p, axis=0), chunk_params)
    if rng is not None and fold_data_axis:
        rng = jax.random.fold_in(rng, lax.axis_index(DATA_AXIS))
    s = lax.axis_index(axis_name)
    n_stages = compat_axis_size(axis_name)
    v = n_chunks
    n_mb = x_mb.shape[0]
    first = jnp.equal(s, 0)
    last = jnp.equal(s, n_stages - 1)

    def tick(t, carry):
        act, out_buf = carry
        q = jnp.maximum(t - s, 0)  # local time; fill ticks masked below
        valid = t >= s
        j = q % n_stages
        c = (q // n_stages) % v
        m = jnp.clip((q // (n_stages * v)) * n_stages + j, 0, n_mb - 1)
        # rank 0 on a chunk-0 tick ingests microbatch m (replacing the
        # finished activation that just wrapped around from the last rank)
        inp = lax.dynamic_index_in_dim(x_mb, m, axis=0, keepdims=False)
        act = jnp.where(jnp.logical_and(first, jnp.equal(c, 0)), inp, act)
        def run_stage(a):
            # chunk gather + key derivation stay inside the (possible)
            # cond branch — skipped ticks skip them too
            p_c = jax.tree.map(
                lambda z: lax.dynamic_index_in_dim(z, c, axis=0,
                                                   keepdims=False),
                params,
            )
            if rng is not None:
                g = c * n_stages + s  # global stage this chunk holds
                key = jax.random.fold_in(jax.random.fold_in(rng, m), g)
                return fn(p_c, a, key)
            return fn(p_c, a)

        if skip_bubble:
            # a rank's real work occupies local times q in [0, M*v)
            y = lax.cond(valid & (q < n_mb * v), run_stage,
                         lambda a: a, act)
        else:
            y = run_stage(act)
        # last rank finishing a microbatch's last chunk retires it
        ready = last & jnp.equal(c, v - 1) & valid
        slot = lax.dynamic_index_in_dim(out_buf, m, axis=0, keepdims=False)
        out_buf = lax.dynamic_update_index_in_dim(
            out_buf, jnp.where(ready, y, slot), m, axis=0
        )
        act = ring_shift(y, axis_name)
        return act, out_buf

    act0 = jnp.zeros_like(x_mb[0])
    out0 = jnp.zeros_like(x_mb)
    _, out_buf = lax.fori_loop(0, n_mb * v + n_stages - 1, tick,
                               (act0, out0), unroll=False)
    # last-rank-only buffer; psum = broadcast (see pipeline_apply_inner)
    return lax.psum(out_buf, axis_name)


def pipeline_apply(fn, stacked_params, x, num_microbatches: int,
                   mesh: Mesh, axis_name: str = PIPE_AXIS,
                   circular_chunks: int = 1, rng=None,
                   skip_bubble: bool = False):
    """GPipe (default) or circular (`circular_chunks=v>1`) pipeline over
    `mesh`'s pipe axis, batch sharded over `data`.

    stacked_params: leaves [S, ...] (see stack_stage_params) for GPipe, or
      [S*v, ...] — one entry per GLOBAL stage, in stage order — for the
      circular schedule (stage c*S + s is placed on rank s as chunk c).
    x: [B, ...] global-batch activations; B % num_microbatches == 0.
    rng: optional base PRNG key; fn then takes (params, x, key), key
      derived per (microbatch, global stage) — fold_in(fold_in(rng, m), g)
      — so stochastic stage fns (dropout) run under the schedule with a
      deterministic, schedule-position-pure key stream.
    skip_bubble: lax.cond the stage so fill/drain ticks skip its compute
      (identical outputs; see pipeline_apply_inner — off by default until
      the cond-vs-overlap tradeoff is measured on multi-chip hardware;
      scripts/pp_probe.py measures both).
    Returns [B, ...].
    """
    n_stages = mesh.shape[axis_name]
    v = circular_chunks
    want = n_stages * v
    chex_msg = (
        f"stacked_params leading dim must equal pipe axis size {n_stages}"
        + (f" x circular_chunks {v} = {want}" if v > 1 else "")
    )
    for leaf in jax.tree.leaves(stacked_params):
        if leaf.shape[0] != want:
            raise ValueError(chex_msg + f", got {leaf.shape[0]}")
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(f"batch {b} % microbatches {num_microbatches} != 0")
    if v > 1 and num_microbatches % n_stages:
        raise ValueError(
            f"circular schedule needs microbatches {num_microbatches} % "
            f"pipe axis {n_stages} == 0 (microbatches enter in rank-width "
            "groups)"
        )
    x_mb = x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])

    if v > 1:
        # [S*v, ...] stage-major -> [v, S, ...] (chunk-major) -> [S, v, ...]
        # so P(pipe) on dim 0 hands rank s its v NON-ADJACENT chunks
        stacked_params = jax.tree.map(
            lambda a: jnp.swapaxes(
                a.reshape((v, n_stages) + a.shape[1:]), 0, 1
            ),
            stacked_params,
        )
        inner = partial(pipeline_apply_circular_inner, fn,
                        axis_name=axis_name, n_chunks=v,
                        fold_data_axis=DATA_AXIS in mesh.shape,
                        skip_bubble=skip_bubble)
    else:
        inner = partial(pipeline_apply_inner, fn, axis_name=axis_name,
                        fold_data_axis=DATA_AXIS in mesh.shape,
                        skip_bubble=skip_bubble)

    p_spec = jax.tree.map(lambda _: P(axis_name), stacked_params)
    # microbatch dim unsharded, per-microbatch batch dim over `data`
    x_spec = P(None, DATA_AXIS)
    in_specs = (p_spec, x_spec) + ((P(),) if rng is not None else ())
    from dist_mnist_tpu.cluster.mesh import compat_shard_map

    run = compat_shard_map(
        inner,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=x_spec,
    )
    args = (stacked_params, x_mb) + ((rng,) if rng is not None else ())
    out = run(*args)
    return out.reshape((b,) + out.shape[2:])
