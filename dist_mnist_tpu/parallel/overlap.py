"""Communication/compute overlap for the ZeRO/fsdp hot path.

Under `fsdp` (parallel/sharding.py) every big param leaf lives 1/data-th
per device; each step must all-gather params before use and reduce(-scatter)
grads after the backward. GSPMD inserts those collectives wherever its
scheduler likes — correct, but the SCHEDULE is implicit. This module makes
the schedule an explicit, benchmarkable artifact, the ZeRO-axis sibling of
parallel/collective_matmul.py's TP rings:

- `build_param_gather` returns a value-level IDENTITY transform that
  gathers the fsdp-sharded param leaves in BUCKETS (grouped by cumulative
  bytes, in layer/traversal order) through explicit `compat_shard_map`
  collectives — `lax.all_gather` per bucket, or the `ppermute` ring
  decomposition (`chunk="ring"`) that rotates shards hop by hop exactly
  like collective_matmul's rings. Its `jax.custom_vjp` backward pins each
  bucket's grad cotangent to the fsdp sharding at the bucket boundary, so
  early buckets' gradient reductions are already in flight while later
  layers still run their backward (the bucketed flush).

- `serial=True` builds the ABLATION TWIN: the same buckets chained through
  `lax.optimization_barrier` so every gather strictly precedes compute and
  every grad flush strictly follows the full backward — all communication
  exposed on the critical path. `optimization_barrier` is a bit-exact
  identity, so serial and overlapped trajectories are bit-identical BY
  CONSTRUCTION, and both are bit-identical to plain GSPMD fsdp (all three
  move the same values; only dependency edges differ). The serial twin is
  what `bench.py --overlap` times against the overlapped program to report
  `comm_exposed_ms_per_step` honestly.

- `prefetched_layer_matmul` is the `lax.scan` double-buffering primitive
  in executable-documentation form: a layer-stack matmul whose weights are
  ZeRO-sharded over `data`, gathering layer l+1's shards WHILE layer l's
  matmul runs (one-layer-ahead prefetch). The training models here keep
  params as dicts rather than scanned stacks, so the train step buckets by
  traversal order instead; this primitive is the stacked-layout shape of
  the same schedule.

Reference counterpart: none — the PS design (SURVEY.md §3.3) serialized
all weight-pull/grad-push traffic by construction. Hot-path module: linted
by scripts/check_host_sync.py (no host syncs may ride the prefetch path).
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dist_mnist_tpu.cluster.mesh import (
    DATA_AXIS,
    compat_axis_size,
    compat_shard_map,
)
from dist_mnist_tpu.parallel.collectives import ring_shift
from dist_mnist_tpu.parallel.sharding import ShardingRules, _paths

#: gather decompositions: one `all_gather` op per leaf, or the explicit
#: `ppermute` ring (n-1 `collective-permute` hops per leaf — the
#: collective_matmul.py idiom on the ZeRO axis)
CHUNK_MODES = ("all_gather", "ring")


@dataclasses.dataclass(frozen=True)
class OverlapConfig:
    """Knobs of the explicit fsdp gather/flush schedule.

    `bucket_mb` trades latency for pipelining: a bucket's gather is one
    collective launch, so tiny buckets pay launch overhead per layer while
    one huge bucket degenerates to gather-everything-up-front (no overlap
    left to find). `serial=True` is the barriered ablation twin — never a
    production setting, it exists so the overlap win is measurable as a
    controlled pair. Every field is folded into the compile-cache key
    (cli/train.py) — cached executables never mix schedules."""

    bucket_mb: float = 4.0
    chunk: str = "all_gather"  # | "ring"
    serial: bool = False  # True = barriered ablation twin (comm exposed)

    def __post_init__(self):
        if self.chunk not in CHUNK_MODES:
            raise ValueError(
                f"unknown overlap chunk mode {self.chunk!r}; use one of "
                f"{CHUNK_MODES}"
            )
        if not self.bucket_mb > 0:
            raise ValueError(f"bucket_mb must be > 0, got {self.bucket_mb}")


def _nbytes(leaf) -> int:
    shape = getattr(leaf, "shape", ())
    itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", 4)
    return math.prod(shape) * itemsize


def _plan(params, mesh: Mesh, rules: ShardingRules, cfg: OverlapConfig):
    """(treedef, leaves, specs, dims, buckets) for `params` under `rules`.

    `dims[i]` is the dim of leaf i that the fsdp axis shards (None when the
    leaf is not fsdp-sharded — small biases, counters — and passes through
    untouched). Buckets are index groups of SHARDED leaves in traversal
    order (= layer order for the dict models here), closed when cumulative
    global bytes reach `bucket_mb` — the leaf that crosses the threshold
    closes its bucket."""
    axis = rules.fsdp_axis
    flat, treedef, paths = _paths(params)
    leaves = [v for _, v in flat]
    specs = [rules.leaf_spec(p, v, mesh) for p, v in zip(paths, leaves)]
    dims = []
    for s in specs:
        entries = tuple(s)
        dims.append(entries.index(axis) if axis in entries else None)
    limit = max(1, int(cfg.bucket_mb * 2**20))
    buckets, cur, cur_bytes = [], [], 0
    for i, d in enumerate(dims):
        if d is None:
            continue
        cur.append(i)
        cur_bytes += _nbytes(leaves[i])
        if cur_bytes >= limit:
            buckets.append(tuple(cur))
            cur, cur_bytes = [], 0
    if cur:
        buckets.append(tuple(cur))
    return treedef, leaves, specs, dims, buckets


def plan_stats(params, mesh: Mesh, rules: ShardingRules,
               cfg: OverlapConfig) -> dict:
    """Pure-metadata description of the gather plan for `params` — what
    OverlapHook publishes as `overlap/*` scalars and bench reports. No
    transfer, no trace."""
    _, leaves, _, dims, buckets = _plan(params, mesh, rules, cfg)
    sharded = [i for i, d in enumerate(dims) if d is not None]
    return {
        "buckets": len(buckets),
        "sharded_leaves": len(sharded),
        "total_leaves": len(leaves),
        "gathered_bytes": sum(_nbytes(leaves[i]) for i in sharded),
        "bucket_mb": cfg.bucket_mb,
        "serial": cfg.serial,
        "chunk": cfg.chunk,
    }


def _ring_gather(loc, axis_name: str, d: int):
    """all_gather via explicit ppermute hops (collective_matmul.py's ring,
    gather-only): rotate the local shard around the ring, depositing each
    arriving shard into its block of the full array. Pure copies — bit-exact
    — and each hop is independent of the previous deposit, so the scheduler
    may overlap hops with whatever compute is ready."""
    n = compat_axis_size(axis_name)
    i0 = lax.axis_index(axis_name)
    m = loc.shape[d]
    full_shape = loc.shape[:d] + (n * m,) + loc.shape[d + 1:]
    out = jnp.zeros(full_shape, loc.dtype)
    buf = loc
    for k in range(n):
        # buf holds shard (i0 + k) % n — same rotation bookkeeping as
        # allgather_matmul (parallel/collective_matmul.py)
        block = (i0 + k) % n
        start = (0,) * d + (block * m,) + (0,) * (loc.ndim - d - 1)
        out = lax.dynamic_update_slice(out, buf, start)
        if k < n - 1:
            buf = ring_shift(buf, axis_name, reverse=True)
    return out


def _bucket_gather_fn(mesh: Mesh, axis: str, in_specs, out_specs, dims,
                      chunk: str):
    """One shard_map gathering a whole bucket: local fsdp shards in, full
    (data-replicated) leaves out. One collective launch region per bucket —
    the granularity the scheduler overlaps."""

    def body(*locs):
        outs = []
        for loc, d in zip(locs, dims):
            if chunk == "ring":
                outs.append(_ring_gather(loc, axis, d))
            else:
                outs.append(lax.all_gather(loc, axis, axis=d, tiled=True))
        return tuple(outs)

    return compat_shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                            out_specs=tuple(out_specs))


def build_param_gather(mesh: Mesh, rules: ShardingRules, cfg: OverlapConfig):
    """`gather(params) -> params` — the explicit fsdp gather boundary.

    Value-level identity: fsdp-sharded leaves come back gathered (data axis
    removed from their layout), everything else passes through untouched.
    Apply INSIDE the loss (under `value_and_grad`) so the custom backward
    owns the gradient flush: each bucket's cotangent is pinned to the fsdp
    sharding at the bucket boundary (`with_sharding_constraint`), which is
    where GSPMD materializes the cross-data reduction — reduce-scatter when
    the backend fuses it, all-reduce-then-slice otherwise. `cfg.serial`
    chains both directions through `optimization_barrier` (see module
    docstring)."""
    if rules.fsdp_axis is None:
        raise ValueError(
            "communication/compute overlap needs an fsdp strategy: the "
            f"active sharding rules {rules.rules or '(dp)'} have no "
            "fsdp_axis, so there are no parameter shards to prefetch. "
            "Use sharding_rules='fsdp' or 'fsdp_tp'."
        )
    axis = rules.fsdp_axis

    def gather(params):
        treedef, leaves, specs, dims, buckets = _plan(params, mesh, rules,
                                                      cfg)
        if not buckets:
            return params
        out_specs = [
            P(*(None if e == axis else e for e in tuple(s))) for s in specs
        ]
        bucket_fns = [
            _bucket_gather_fn(
                mesh, axis,
                [specs[i] for i in b], [out_specs[i] for i in b],
                [dims[i] for i in b], cfg.chunk,
            )
            for b in buckets
        ]

        @jax.custom_vjp
        def gathered(*shd):
            ls = list(shd)
            prev = None
            for b, fn in zip(buckets, bucket_fns):
                ins = [ls[i] for i in b]
                if cfg.serial and prev is not None:
                    # serialize: bucket k+1's gather may not issue until
                    # bucket k's has produced a value
                    tied = lax.optimization_barrier(tuple(ins) + (prev,))
                    ins = list(tied[:-1])
                outs = fn(*ins)
                for j, i in enumerate(b):
                    ls[i] = outs[j]
                prev = outs[0]
            if cfg.serial:
                # expose ALL gather time: no compute may start before the
                # last bucket lands (identity — bit-exact)
                ls = list(lax.optimization_barrier(tuple(ls)))
            return tuple(ls)

        def fwd(*shd):
            return gathered(*shd), None

        def bwd(_, cts):
            cts = list(cts)
            prev = None
            order = list(reversed(buckets)) if cfg.serial else buckets
            for b in order:
                grp = tuple(cts[i] for i in b)
                if cfg.serial and prev is not None:
                    # serialize flushes back-to-front, after ALL backward
                    # compute (each ct is only ready once its layer's
                    # backward ran; the chain then orders the reductions)
                    tied = lax.optimization_barrier(grp + (prev,))
                    grp = tied[:-1]
                else:
                    # bucketed flush: the bucket's cotangents leave as one
                    # group, so its reductions launch together while later
                    # (earlier-layer) backward is still computing
                    grp = lax.optimization_barrier(grp)
                for j, i in enumerate(b):
                    cts[i] = lax.with_sharding_constraint(
                        grp[j], NamedSharding(mesh, specs[i])
                    )
                prev = cts[b[-1]]
            return tuple(cts)

        gathered.defvjp(fwd, bwd)
        return jax.tree.unflatten(treedef, list(gathered(*leaves)))

    return gather


def prefetched_layer_matmul(x, ws, mesh: Mesh, axis: str = DATA_AXIS,
                            activation=jnp.tanh):
    """Layer-stack matmul with one-layer-ahead weight prefetch under
    `lax.scan` — the double-buffered form of the train step's bucket
    schedule, for models that keep weights as a scanned stack.

    x:  [B, D] batch-sharded over `axis` (rows).
    ws: [L, D, D] with dim 1 (each layer's input dim) sharded over `axis` —
        the ZeRO resident layout: every device holds [L, D/n, D].
    Applies `h = activation(h @ W_l)` for l = 0..L-1. The scan carry is
    (activations, CURRENT full weight); each iteration all-gathers layer
    l+1's shards — independent of layer l's matmul, so the gather rides
    alongside it — and double-buffers the result into the carry. Returns
    [B, D] sharded over `axis`, bit-identical to the serial gather-then-
    matmul loop (gathers are pure copies)."""
    n = mesh.shape[axis]
    if ws.ndim != 3 or ws.shape[0] < 1:
        raise ValueError(f"ws must be a [L, D, D] layer stack, got {ws.shape}")
    if ws.shape[1] % n or x.shape[0] % n:
        raise ValueError(
            f"D={ws.shape[1]} and B={x.shape[0]} must divide {axis}={n}"
        )

    def body(x_local, ws_local):
        def gather_w(w_shard):  # [D/n, D] -> [D, D]
            return lax.all_gather(w_shard, axis, axis=0, tiled=True)

        def step(carry, w_next_shard):
            h, w_cur = carry
            w_next = gather_w(w_next_shard)  # prefetch: no dep on the dot
            h = activation(h @ w_cur)
            return (h, w_next), None

        (h, w_last), _ = lax.scan(step, (x_local, gather_w(ws_local[0])),
                                  ws_local[1:])
        return activation(h @ w_last)

    return compat_shard_map(
        body, mesh=mesh,
        in_specs=(P(axis, None), P(None, axis, None)),
        out_specs=P(axis, None),
    )(x, ws)
