"""ctypes bindings for the C++ parameter server (no pybind11 in-image).

The library is built on demand with g++ (cached next to the source). All
blocking entry points (token dequeue, chief take_grad) release the GIL —
ctypes foreign calls always do — so Python threads act as genuinely
concurrent PS clients, like the reference's per-worker processes.
"""

from __future__ import annotations

import ctypes
import logging
from pathlib import Path

import numpy as np

from dist_mnist_tpu.utils.native_build import build_shared_lib, load_lib

log = logging.getLogger(__name__)

_SRC = Path(__file__).parent / "ps_server.cc"
_LIB = Path(__file__).parent / "libps_server.so"


def build_library(force: bool = False) -> Path:
    """Compile ps_server.cc -> libps_server.so (cached by mtime)."""
    return build_shared_lib(_SRC, _LIB, force=force)


def _signatures():
    i64, f32p = ctypes.c_int64, ctypes.POINTER(ctypes.c_float)
    return {
        "ps_create": ([ctypes.POINTER(i64), ctypes.c_int, ctypes.c_double,
                       ctypes.c_double, ctypes.c_double, ctypes.c_double,
                       ctypes.c_int, i64], ctypes.c_void_p),
        "ps_destroy": ([ctypes.c_void_p], None),
        "ps_total_size": ([ctypes.c_void_p], i64),
        "ps_init": ([ctypes.c_void_p, f32p], None),
        "ps_pull": ([ctypes.c_void_p, f32p], i64),
        "ps_push_async": ([ctypes.c_void_p, f32p, i64], ctypes.c_int),
        "ps_push_sync": ([ctypes.c_void_p, f32p, i64], ctypes.c_int),
        "ps_chief_sync_once": ([ctypes.c_void_p, ctypes.c_int], i64),
        "ps_dequeue_token": ([ctypes.c_void_p], i64),
        "ps_step": ([ctypes.c_void_p], i64),
        "ps_dropped": ([ctypes.c_void_p], i64),
        "ps_close": ([ctypes.c_void_p], None),
    }


def _get_lib():
    return load_lib(_SRC, _LIB, _signatures())


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class ParameterServer:
    """Python handle over the native PS. Parameters travel as ONE flat f32
    vector (the wire format — like RecvTensor moved whole tensors)."""

    def __init__(self, sizes, *, lr=1e-3, b1=0.9, b2=0.999, eps=1e-8,
                 replicas_to_aggregate=0, staleness_bound=-1):
        lib = _get_lib()
        arr = (ctypes.c_int64 * len(sizes))(*sizes)
        self._h = lib.ps_create(arr, len(sizes), lr, b1, b2, eps,
                                replicas_to_aggregate, staleness_bound)
        self._lib = lib
        self.total = int(lib.ps_total_size(self._h))
        self.sizes = list(sizes)

    def init(self, flat: np.ndarray) -> None:
        flat = np.ascontiguousarray(flat, np.float32)
        assert flat.size == self.total
        self._lib.ps_init(self._h, _fptr(flat))

    def pull(self) -> tuple[np.ndarray, int]:
        out = np.empty(self.total, np.float32)
        step = self._lib.ps_pull(self._h, _fptr(out))
        return out, int(step)

    def push_async(self, grads: np.ndarray, local_step: int) -> bool:
        grads = np.ascontiguousarray(grads, np.float32)
        return bool(self._lib.ps_push_async(self._h, _fptr(grads), local_step))

    def push_sync(self, grads: np.ndarray, local_step: int) -> bool:
        grads = np.ascontiguousarray(grads, np.float32)
        rc = self._lib.ps_push_sync(self._h, _fptr(grads), local_step)
        if rc < 0:
            raise RuntimeError(
                "push_sync on a PS created without replicas_to_aggregate "
                "(async mode has no accumulator)"
            )
        return bool(rc)

    def chief_sync_once(self, tokens_per_step: int) -> int:
        return int(self._lib.ps_chief_sync_once(self._h, tokens_per_step))

    def dequeue_token(self) -> int:
        return int(self._lib.ps_dequeue_token(self._h))

    @property
    def step(self) -> int:
        return int(self._lib.ps_step(self._h))

    @property
    def dropped(self) -> int:
        return int(self._lib.ps_dropped(self._h))

    def close(self) -> None:
        self._lib.ps_close(self._h)

    def __del__(self):
        try:
            self._lib.ps_destroy(self._h)
        except Exception:
            pass
