"""Async/sync parameter-server DEMO — the protocol the TPU path replaced.

The reference's default mode is asynchronous parameter-server data
parallelism (SURVEY.md §2.6 row 1) which is architecturally out-of-model for
a lockstep SPMD program (§7 hard part (b)). Per the survey's build plan
(§7 step 6), this package is the one place native code re-creates the PS
protocol itself: a C++ parameter server (`ps_server.cc`) holding the flat
master weights + Adam slots, with the ConditionalAccumulator staleness/
aggregation state machine and the FIFO token-queue barrier, driven by
Python worker THREADS that compute real gradients with JAX on CPU.

This is an educational/parity artifact: `python -m
dist_mnist_tpu.parallel.ps_demo` trains the reference MLP both ways and
prints the steps/sec + staleness profile, so the README's "what did the
TPU rebuild actually delete?" section has a live exhibit.
"""

from dist_mnist_tpu.parallel.ps_demo.bindings import (
    ParameterServer,
    build_library,
)
from dist_mnist_tpu.parallel.ps_demo.demo import run_demo

__all__ = ["ParameterServer", "build_library", "run_demo"]
