// Host-side async/sync parameter server — a faithful C++ demonstration of
// the reference's PS-side machinery (SURVEY.md §2.3 rows 8-12, §2.5), kept
// OUT of the TPU training path on purpose: on TPU the entire PS role is an
// ICI all-reduce inside the compiled step. This exists to (a) document the
// protocol being replaced, (b) provide executable parity for the
// `--sync_replicas`/async modes of the original `dist_mnist.py` on hosts.
//
// Mirrored semantics, with their reference anchors:
//  * ApplyAdam update rule incl. beta-power bias correction
//    (training_ops.h ApplyAdam; adam.py:216-231): lr_t = lr *
//    sqrt(1-b2^t)/(1-b1^t); p -= lr_t * m / (sqrt(v) + eps)  [eps outside]
//  * ConditionalAccumulator (conditional_accumulator_base.h:30-46):
//    apply_grad DROPS gradients whose local_step < the accumulator's
//    current global step; take_grad(n) BLOCKS until n fresh gradients,
//    returns their average, resets, bumps the internal step.
//  * FIFOQueue sync token barrier (fifo_queue.h:34; sync protocol
//    sync_replicas_optimizer.py:72-97 and 312-322): workers block
//    dequeuing a token; the chief enqueues `tokens_per_step` tokens
//    carrying the new global step after each aggregated apply.
//  * Async mode (the reference default): push applies immediately under
//    the param lock; staleness is tolerated (bounded here for sanity).
//
// All public entry points are `extern "C"` with flat float buffers so the
// Python side binds with ctypes (no pybind11 in this image). Blocking calls
// release the GIL by construction (ctypes releases it around foreign
// calls), so Python worker THREADS get true PS-style concurrency.

#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <vector>

namespace {

struct AdamSlots {
  std::vector<float> m, v;
  explicit AdamSlots(size_t n) : m(n, 0.f), v(n, 0.f) {}
};

struct AdamHyper {
  double lr, b1, b2, eps;
};

// One fused pass over a flat span: the training_ops.h ApplyAdam functor.
void apply_adam(float* p, AdamSlots& s, const float* g, size_t n,
                const AdamHyper& h, int64_t t) {
  const double lr_t =
      h.lr * std::sqrt(1.0 - std::pow(h.b2, (double)t)) /
      (1.0 - std::pow(h.b1, (double)t));
  for (size_t i = 0; i < n; ++i) {
    const float gi = g[i];
    s.m[i] = (float)(h.b1 * s.m[i] + (1.0 - h.b1) * gi);
    s.v[i] = (float)(h.b2 * s.v[i] + (1.0 - h.b2) * gi * gi);
    p[i] -= (float)(lr_t * s.m[i] / (std::sqrt((double)s.v[i]) + h.eps));
  }
}

class TokenQueue {  // fifo_queue.h:34 — the sync_token_q
 public:
  void enqueue(int64_t v) {
    std::unique_lock<std::mutex> lk(mu_);
    q_.push_back(v);
    cv_.notify_one();
  }
  // Blocks until a token is available or the queue is closed (-1).
  int64_t dequeue() {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [&] { return closed_ || !q_.empty(); });
    if (q_.empty()) return -1;
    int64_t v = q_.front();
    q_.pop_front();
    return v;
  }
  void close() {
    std::unique_lock<std::mutex> lk(mu_);
    closed_ = true;
    cv_.notify_all();
  }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<int64_t> q_;
  bool closed_ = false;
};

class Accumulator {  // conditional_accumulator_base.h:30-46 semantics
 public:
  Accumulator(size_t size, int required)
      : sum_(size, 0.f), required_(required) {}

  // Returns 1 if accepted, 0 if dropped as stale (:34-37).
  int apply_grad(const float* g, int64_t local_step) {
    std::unique_lock<std::mutex> lk(mu_);
    if (local_step < step_) {
      ++dropped_;
      return 0;
    }
    for (size_t i = 0; i < sum_.size(); ++i) sum_[i] += g[i];
    ++count_;
    cv_.notify_all();
    return 1;
  }

  // Blocks until `required_` fresh grads arrived; averages into out,
  // resets, bumps the internal step (:39-46).
  bool take_grad(float* out) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [&] { return closed_ || count_ >= required_; });
    if (count_ < required_) return false;  // closed
    const float inv = 1.0f / (float)count_;
    for (size_t i = 0; i < sum_.size(); ++i) {
      out[i] = sum_[i] * inv;
      sum_[i] = 0.f;
    }
    count_ = 0;
    ++step_;
    return true;
  }

  void close() {
    std::unique_lock<std::mutex> lk(mu_);
    closed_ = true;
    cv_.notify_all();
  }
  int64_t dropped() const { return dropped_.load(std::memory_order_relaxed); }

 private:
  mutable std::mutex mu_;
  std::condition_variable cv_;
  std::vector<float> sum_;
  int count_ = 0;
  const int required_;
  int64_t step_ = 0;
  std::atomic<int64_t> dropped_{0};  // read by monitors without mu_
  bool closed_ = false;
};

class ParameterServer {
 public:
  ParameterServer(const int64_t* sizes, int n_params, AdamHyper hyper,
                  int replicas_to_aggregate, int64_t staleness_bound)
      : hyper_(hyper),
        staleness_bound_(staleness_bound),
        replicas_(replicas_to_aggregate) {
    offsets_.push_back(0);
    for (int i = 0; i < n_params; ++i)
      offsets_.push_back(offsets_.back() + (size_t)sizes[i]);
    params_.assign(offsets_.back(), 0.f);
    slots_ = std::make_unique<AdamSlots>(offsets_.back());
    if (replicas_ > 0)
      acc_ = std::make_unique<Accumulator>(offsets_.back(), replicas_);
  }

  size_t total() const { return params_.size(); }

  void init(const float* flat) {
    std::unique_lock<std::mutex> lk(mu_);
    std::memcpy(params_.data(), flat, params_.size() * sizeof(float));
  }

  // Weight pull — the RecvTensor read path (worker.h:85): every worker
  // step starts by pulling the current params.
  int64_t pull(float* out) {
    std::unique_lock<std::mutex> lk(mu_);
    std::memcpy(out, params_.data(), params_.size() * sizeof(float));
    return step_;
  }

  // ASYNC push: apply immediately under the lock; drop if the gradient is
  // older than the staleness bound (the unbounded-staleness reference
  // behavior, made bounded so demos can't diverge silently).
  int push_async(const float* flat_grads, int64_t local_step) {
    std::unique_lock<std::mutex> lk(mu_);
    if (staleness_bound_ >= 0 && local_step + staleness_bound_ < step_) {
      ++dropped_;
      return 0;
    }
    ++applies_;
    apply_adam(params_.data(), *slots_, flat_grads, params_.size(), hyper_,
               applies_);
    ++step_;
    return 1;
  }

  // SYNC push: feed the accumulator (worker side of §3.4).
  int push_sync(const float* flat_grads, int64_t local_step) {
    return acc_ ? acc_->apply_grad(flat_grads, local_step) : -1;
  }

  // Chief loop body (§3.4: take_grad -> apply -> bump step -> tokens):
  // returns the new global step, or -1 on shutdown.
  int64_t chief_sync_once(int tokens_per_step) {
    if (!acc_) return -1;
    std::vector<float> avg(params_.size());
    if (!acc_->take_grad(avg.data())) return -1;
    int64_t new_step;
    {
      std::unique_lock<std::mutex> lk(mu_);
      ++applies_;
      apply_adam(params_.data(), *slots_, avg.data(), params_.size(), hyper_,
                 applies_);
      new_step = ++step_;
    }
    for (int i = 0; i < tokens_per_step; ++i) tokens_.enqueue(new_step);
    return new_step;
  }

  int64_t dequeue_token() { return tokens_.dequeue(); }
  int64_t step() const {
    std::unique_lock<std::mutex> lk(mu_);
    return step_;
  }
  int64_t dropped() const {
    std::unique_lock<std::mutex> lk(mu_);
    return dropped_ + (acc_ ? acc_->dropped() : 0);
  }
  void close() {
    tokens_.close();
    if (acc_) acc_->close();
  }

 private:
  mutable std::mutex mu_;
  std::vector<float> params_;
  std::unique_ptr<AdamSlots> slots_;
  std::vector<size_t> offsets_;
  AdamHyper hyper_;
  int64_t step_ = 0;
  int64_t applies_ = 0;
  int64_t dropped_ = 0;
  const int64_t staleness_bound_;
  const int replicas_;
  std::unique_ptr<Accumulator> acc_;
  TokenQueue tokens_;
};

}  // namespace

extern "C" {

void* ps_create(const int64_t* sizes, int n_params, double lr, double b1,
                double b2, double eps, int replicas_to_aggregate,
                int64_t staleness_bound) {
  return new ParameterServer(sizes, n_params, AdamHyper{lr, b1, b2, eps},
                             replicas_to_aggregate, staleness_bound);
}
void ps_destroy(void* ps) { delete static_cast<ParameterServer*>(ps); }
int64_t ps_total_size(void* ps) {
  return (int64_t) static_cast<ParameterServer*>(ps)->total();
}
void ps_init(void* ps, const float* flat) {
  static_cast<ParameterServer*>(ps)->init(flat);
}
int64_t ps_pull(void* ps, float* out) {
  return static_cast<ParameterServer*>(ps)->pull(out);
}
int ps_push_async(void* ps, const float* grads, int64_t local_step) {
  return static_cast<ParameterServer*>(ps)->push_async(grads, local_step);
}
int ps_push_sync(void* ps, const float* grads, int64_t local_step) {
  return static_cast<ParameterServer*>(ps)->push_sync(grads, local_step);
}
int64_t ps_chief_sync_once(void* ps, int tokens_per_step) {
  return static_cast<ParameterServer*>(ps)->chief_sync_once(tokens_per_step);
}
int64_t ps_dequeue_token(void* ps) {
  return static_cast<ParameterServer*>(ps)->dequeue_token();
}
int64_t ps_step(void* ps) { return static_cast<ParameterServer*>(ps)->step(); }
int64_t ps_dropped(void* ps) {
  return static_cast<ParameterServer*>(ps)->dropped();
}
void ps_close(void* ps) { static_cast<ParameterServer*>(ps)->close(); }

}  // extern "C"
