"""Live reenactment of `dist_mnist.py --job_name={ps,worker}` on one host.

Topology parity with SURVEY.md §0.1 / §3.3 / §3.4, minus gRPC (the PS lives
in-process behind ctypes instead of behind a socket — the protocol and
blocking structure are identical):

- the C++ ParameterServer plays the `ps` job (variables + Adam slots +
  accumulators + token queue, all native — rows 8-12),
- each Python thread plays a `worker` job: pull params, compute gradients
  on its own batch stream (real JAX autodiff on CPU), push,

This is a PROTOCOL demo, not a concurrency-parity claim: workers are
threads, so Python-side gradient compute serializes under the GIL (the
reference's workers were processes). What it faithfully reproduces is the
blocking structure — stale-grad drop, take_grad(n) aggregation, the token
barrier — whose state machines live in the C++ server and release the GIL
while blocking. For real multi-process training use the SPMD path
(`cli/launch.py`).
- async mode: push applies immediately; staleness tolerated/bounded,
- sync mode (`--sync_replicas`): pushes feed the accumulator; worker 0
  doubles as chief running the aggregate->apply->token loop; workers block
  on the token queue (the §3.4 barrier).
"""

from __future__ import annotations

import threading
import time

import numpy as np


def run_demo(
    mode: str = "async",
    num_workers: int = 2,
    train_steps: int = 200,
    batch_size: int = 100,
    hidden_units: int = 100,
    lr: float = 0.01,
    dataset=None,
    seed: int = 0,
) -> dict:
    """Train the reference MLP through the native PS. Returns metrics."""
    import jax
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    from dist_mnist_tpu.data.datasets import load_dataset
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.ops import losses
    from dist_mnist_tpu.parallel.ps_demo.bindings import ParameterServer

    if mode not in ("async", "sync"):
        raise ValueError(f"mode must be async|sync, got {mode!r}")
    cpu = jax.devices("cpu")[0]
    dataset = dataset or load_dataset(
        "mnist", "/tmp/mnist-data", seed=seed, synthetic_sizes=(8192, 1024)
    )
    model = get_model("mlp", hidden_units=hidden_units)

    with jax.default_device(cpu):
        params0, _ = model.init(
            jax.random.PRNGKey(seed), dataset.train_images[:1]
        )
        flat0, unravel = ravel_pytree(params0)

        @jax.jit
        def grad_fn(flat_params, x, y):
            def loss_of(flat):
                logits, _ = model.apply(unravel(flat), {}, x, train=False)
                return losses.clipped_softmax_cross_entropy(logits, y)

            return jax.grad(loss_of)(flat_params)

        @jax.jit
        def acc_fn(flat_params, x, y):
            logits, _ = model.apply(unravel(flat_params), {}, x, train=False)
            return jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))

    sizes = [flat0.size]
    ps = ParameterServer(
        sizes,
        lr=lr,
        replicas_to_aggregate=num_workers if mode == "sync" else 0,
        staleness_bound=2 * num_workers if mode == "async" else -1,
    )
    ps.init(np.asarray(flat0))

    images = dataset.normalized(dataset.train_images)
    labels = dataset.train_labels
    n = images.shape[0]
    stop = threading.Event()
    applied_counts = [0] * num_workers

    def worker(widx: int):
        rng = np.random.default_rng(seed * 100 + widx)
        with jax.default_device(cpu):
            while not stop.is_set() and ps.step < train_steps:
                flat, pulled_step = ps.pull()  # weight pull (RecvTensor read)
                idx = rng.integers(0, n, batch_size)
                g = np.asarray(
                    grad_fn(jnp.asarray(flat), images[idx], labels[idx])
                )
                if mode == "async":
                    if ps.push_async(g, pulled_step):
                        applied_counts[widx] += 1
                else:
                    ps.push_sync(g, pulled_step)  # may be dropped as stale
                    token = ps.dequeue_token()  # §3.4 barrier
                    if token < 0:
                        break
                    applied_counts[widx] += 1

    def chief():
        # the chief-only QueueRunner thread (queue_runner_impl.py:236)
        while not stop.is_set() and ps.step < train_steps:
            if ps.chief_sync_once(tokens_per_step=num_workers) < 0:
                break

    threads = [
        # lint: ok[thread-lifecycle] demo-scoped workers, joined below in this function
        threading.Thread(target=worker, args=(w,), daemon=True)
        for w in range(num_workers)
    ]
    if mode == "sync":
        # lint: ok[thread-lifecycle] demo-scoped chief, joined below in this function
        threads.append(threading.Thread(target=chief, daemon=True))
    t0 = time.monotonic()
    for t in threads:
        t.start()
    while ps.step < train_steps and any(t.is_alive() for t in threads):
        time.sleep(0.01)
    stop.set()
    ps.close()
    for t in threads:
        t.join(timeout=5)
    elapsed = time.monotonic() - t0

    final_flat, final_step = ps.pull()
    with jax.default_device(cpu):
        test_acc = float(
            acc_fn(
                jnp.asarray(final_flat),
                jnp.asarray(dataset.normalized(dataset.test_images)),
                jnp.asarray(dataset.test_labels),
            )
        )
    return {
        "mode": mode,
        "global_step": final_step,
        "steps_per_sec": final_step / elapsed,
        "test_accuracy": test_acc,
        "dropped_stale_grads": ps.dropped,
        "per_worker_applies": applied_counts,
        "elapsed": elapsed,
    }


if __name__ == "__main__":
    import json
    import logging

    logging.basicConfig(level=logging.INFO)
    for mode in ("async", "sync"):
        print(json.dumps(run_demo(mode=mode), default=str))
