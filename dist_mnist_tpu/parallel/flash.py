"""Mesh-adaptive entry for the flash attention kernel.

A bare `pallas_call` cannot be partitioned by GSPMD: on a mesh with a >1
`model` axis it would force the sharded q/k/v to be gathered and the kernel
run replicated on every device — silently undoing exactly the tensor
parallelism TP_RULES set up (VERDICT r4 weak #3). So under a model axis the
kernel runs per-device over its LOCAL heads via shard_map (Megatron TP
attention: column-sharded qkv projections already make heads device-local,
so the reshard into P(data, None, model, None) is free). This is the same
head placement ring_self_attention uses for its hybrid DP x TP x SP spec.

Both flash consumers route here: ViT's `attention_impl="flash"` branch and
ring_attention's seq-absent fallback for `impl="flash"` — so the hazard is
closed at every dispatch point, not special-cased in one model.
"""

from __future__ import annotations

import functools
import logging

from jax.sharding import PartitionSpec as P

from dist_mnist_tpu.cluster.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    ambient_mesh,
    compat_shard_map,
)
from dist_mnist_tpu.ops.pallas.flash_attention import (
    flash_attention,
    masked_flash_attention,
)

log = logging.getLogger(__name__)


def flash_attention_tagged(q, k, v, block_k=None):
    """`flash_attention_sharded` + the `attn_out` remat tag — the shared
    seq-less fallback for ring_flash and ulysses_flash (keeps the
    save_attn policy surface uniform and in ONE place)."""
    from jax.ad_checkpoint import checkpoint_name

    return checkpoint_name(flash_attention_sharded(q, k, v, block_k=block_k),
                           "attn_out")


def flash_attention_sharded(q, k, v, block_k=None):
    """[B,S,H,D] flash attention on any ambient mesh.

    No/singleton model axis: the plain kernel. >1 model axis: shard_map
    over heads — refusing (at trace time, with a clear error instead of a
    deep XLA partitioning one) a head count the axis cannot divide.
    `block_k` selects the online-softmax streaming kernels (see
    flash_attention).
    """
    mesh = ambient_mesh()
    shape = getattr(mesh, "shape", {}) if mesh is not None else {}
    m = shape.get(MODEL_AXIS, 1)
    if m <= 1:
        return flash_attention(q, k, v, block_k=block_k)
    heads = q.shape[2]
    if heads % m:
        raise ValueError(
            f"flash attention on a {m}-way model axis shards the kernel "
            f"over heads (Megatron TP attention) and cannot split a head: "
            f"heads={heads} % model={m} != 0. Use a head count divisible "
            f"by {m}, or attention_impl='xla' (einsums partition without "
            "head granularity)."
        )
    # batch rides the data axis only when it divides (an eval batch or a
    # bare call may not) — an unmentioned axis just means the kernel sees
    # the full batch replicated, never an error. But it IS an O(data)x
    # compute/memory cliff: every device recomputes the whole batch, so say
    # so once per trace (mirroring moe.py's dense-fallback warning — a
    # jit-cached fallback is otherwise invisible; ADVICE r5)
    data = shape.get(DATA_AXIS, 1)
    batch_rides_data = data > 1 and q.shape[0] % data == 0
    if data > 1 and not batch_rides_data:
        log.warning(
            "flash attention: batch=%d %% data axis %d != 0 — the kernel "
            "drops the data axis and every device recomputes the FULL "
            "replicated batch (%dx redundant compute/memory); use a batch "
            "divisible by %d to ride the data axis",
            q.shape[0], data, data, data,
        )
    spec = P(DATA_AXIS if batch_rides_data else None,
             None, MODEL_AXIS, None)
    fn = compat_shard_map(
        functools.partial(flash_attention, block_k=block_k),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec)
    return fn(q, k, v)


def masked_flash_attention_sharded(q, k, v, lengths, block_k=None):
    """Variable-length twin of `flash_attention_sharded`: row b attends
    keys [0, lengths[b]) and the kernel grid skips fully-padded key blocks
    (ops/pallas/flash_attention.masked_flash_attention). Same mesh policy —
    plain kernel without a >1 model axis, shard_map over heads with one;
    `lengths` [B] follows the batch placement (sharded over data exactly
    when q/k/v batch rides the data axis, else replicated)."""
    kw = {} if block_k is None else {"block_k": block_k}
    mesh = ambient_mesh()
    shape = getattr(mesh, "shape", {}) if mesh is not None else {}
    m = shape.get(MODEL_AXIS, 1)
    if m <= 1:
        return masked_flash_attention(q, k, v, lengths, **kw)
    heads = q.shape[2]
    if heads % m:
        raise ValueError(
            f"flash attention on a {m}-way model axis shards the kernel "
            f"over heads (Megatron TP attention) and cannot split a head: "
            f"heads={heads} % model={m} != 0. Use a head count divisible "
            f"by {m}, or attention_impl='xla' (einsums partition without "
            "head granularity)."
        )
    data = shape.get(DATA_AXIS, 1)
    batch_rides_data = data > 1 and q.shape[0] % data == 0
    if data > 1 and not batch_rides_data:
        log.warning(
            "flash attention: batch=%d %% data axis %d != 0 — the kernel "
            "drops the data axis and every device recomputes the FULL "
            "replicated batch (%dx redundant compute/memory); use a batch "
            "divisible by %d to ride the data axis",
            q.shape[0], data, data, data,
        )
    batch_axis = DATA_AXIS if batch_rides_data else None
    spec = P(batch_axis, None, MODEL_AXIS, None)
    len_spec = P(batch_axis)
    fn = compat_shard_map(
        lambda q, k, v, lens: masked_flash_attention(q, k, v, lens, **kw),
        mesh=mesh, in_specs=(spec, spec, spec, len_spec), out_specs=spec)
    return fn(q, k, v, lengths)
