"""Ring attention — sequence/context parallelism over the `seq` mesh axis.

Absent from the reference (fixed 784-pixel inputs — SURVEY.md §5.7), but a
first-class capability here: long sequences are sharded over `seq`; each
device holds its local Q/K/V slice, K/V blocks rotate around the ICI ring
via `ppermute`, and softmax is accumulated blockwise in log-sum-exp form
(the numerically exact streaming softmax), so no device ever materializes
the full S x S score matrix — attention memory is O(S_local^2 * ring) time,
O(S_local) memory per device.

Two entry points:
- `ring_attention_inner(q, k, v, axis_name)` — call INSIDE shard_map.
- `ring_self_attention(q, k, v, mesh)` — wraps shard_map over `mesh`'s
  `seq` axis (composes under jit).
- `ring_attention(q, k, v)` — convenience used by models: rings over the
  ambient mesh when it has a seq axis > 1, else falls back to plain
  attention (so the same model code runs on any mesh).

Non-causal (bidirectional) attention, matching ops/nn.dot_product_attention;
inputs [B, S(, _local), H, D].
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P, get_abstract_mesh

from dist_mnist_tpu.cluster.mesh import SEQ_AXIS
from dist_mnist_tpu.parallel.collectives import ring_shift


def ring_attention_inner(q, k, v, axis_name: str = SEQ_AXIS):
    """Blockwise-LSE ring attention; q/k/v are this device's [B,Sl,H,D]."""
    n = lax.axis_size(axis_name)
    scale = q.shape[-1] ** -0.5
    qf = q.astype(jnp.float32)

    def block(qf, k_blk, v_blk):
        logits = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32))
        logits *= scale
        m = jnp.max(logits, axis=-1)  # [B,H,Sq]
        p = jnp.exp(logits - m[..., None])
        num = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32))
        den = jnp.sum(p, axis=-1)  # [B,H,Sq]
        return num, den, m

    def body(i, carry):
        acc_num, acc_den, acc_max, k_blk, v_blk = carry
        num, den, m = block(qf, k_blk, v_blk)
        new_max = jnp.maximum(acc_max, m)
        old_scale = jnp.exp(acc_max - new_max)
        blk_scale = jnp.exp(m - new_max)
        sc = lambda s: jnp.moveaxis(s, -1, 1)[..., None]  # [B,H,Sq]->[B,Sq,H,1]
        acc_num = acc_num * sc(old_scale) + num * sc(blk_scale)
        acc_den = acc_den * old_scale + den * blk_scale
        # rotate K/V to the next ring position (neighbour ICI hop); XLA
        # overlaps the ppermute with the current block's compute
        k_blk = ring_shift(k_blk, axis_name)
        v_blk = ring_shift(v_blk, axis_name)
        return acc_num, acc_den, new_max, k_blk, v_blk

    b, sl, h, d = q.shape
    init = (
        jnp.zeros((b, sl, h, d), jnp.float32),
        jnp.zeros((b, h, sl), jnp.float32),
        jnp.full((b, h, sl), -jnp.inf, jnp.float32),
        k,
        v,
    )
    acc_num, acc_den, _, _, _ = lax.fori_loop(0, n, body, init)
    out = acc_num / jnp.moveaxis(acc_den, -1, 1)[..., None]
    # save_attn remat tag (train/step.py REMAT_POLICIES): the seq-sharded
    # path must tag its own output — it never routes through
    # ops/nn.dot_product_attention, whose tag covers only the seq==1
    # fallback. Identity outside jax.checkpoint.
    from jax.ad_checkpoint import checkpoint_name

    return checkpoint_name(out.astype(q.dtype), "attn_out")


def ring_self_attention(q, k, v, mesh: Mesh, axis_name: str = SEQ_AXIS):
    """shard_map wrapper over [B,S,H,D]: batch stays sharded over `data`,
    heads over `model`, and the sequence dim rings over `axis_name` — the
    full hybrid DP x TP x SP layout in one spec. Requires B % data == 0,
    H % model == 0, S % seq == 0."""
    from dist_mnist_tpu.cluster.mesh import DATA_AXIS, MODEL_AXIS

    spec = P(DATA_AXIS, axis_name, MODEL_AXIS, None)
    fn = jax.shard_map(
        partial(ring_attention_inner, axis_name=axis_name),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def ring_attention(q, k, v):
    """Mesh-adaptive entry used by models: ring over the ambient mesh's
    `seq` axis when present (>1), else exact fallback."""
    mesh = get_abstract_mesh()
    if mesh is None or SEQ_AXIS not in mesh.shape or mesh.shape[SEQ_AXIS] == 1:
        from dist_mnist_tpu.ops.nn import dot_product_attention

        return dot_product_attention(q, k, v)
    return ring_self_attention(q, k, v, mesh)
