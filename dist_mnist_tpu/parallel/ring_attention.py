"""Ring attention — sequence/context parallelism over the `seq` mesh axis.

Absent from the reference (fixed 784-pixel inputs — SURVEY.md §5.7), but a
first-class capability here: long sequences are sharded over `seq`; each
device holds its local Q/K/V slice, K/V blocks rotate around the ICI ring
via `ppermute`, and softmax is accumulated blockwise in log-sum-exp form
(the numerically exact streaming softmax), so no device ever materializes
the full S x S score matrix — attention memory is O(S_local^2 * ring) time,
O(S_local) memory per device.

Entry points (each takes `impl="xla"|"flash"` to pick the local-block
engine — "flash" runs the Pallas kernel per block so score tiles stay in
VMEM even while the ring keeps HBM at O(S_local); see
ring_attention_inner):
- `ring_attention_inner(q, k, v, axis_name, impl)` — call INSIDE shard_map.
- `ring_self_attention(q, k, v, mesh, impl=...)` — wraps shard_map over
  `mesh`'s `seq` axis (composes under jit).
- `ring_attention(q, k, v, impl=...)` — convenience used by models: rings
  over the ambient mesh when it has a seq axis > 1, else falls back to the
  impl-matched dense path (so the same model code runs on any mesh).

Non-causal (bidirectional) attention, matching ops/nn.dot_product_attention;
inputs [B, S(, _local), H, D].
"""

from __future__ import annotations

from functools import partial

import jax

from dist_mnist_tpu.cluster.mesh import compat_axis_size
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dist_mnist_tpu.cluster.mesh import ambient_mesh as get_abstract_mesh

from dist_mnist_tpu.cluster.mesh import SEQ_AXIS
from dist_mnist_tpu.parallel.collectives import ring_shift


def ring_attention_inner(q, k, v, axis_name: str = SEQ_AXIS,
                         impl: str = "xla", block_k: int | None = None):
    """Blockwise-LSE ring attention; q/k/v are this device's [B,Sl,H,D].

    `impl` selects how each device computes its LOCAL q x k_block piece:
    - "xla": einsum — materializes the [B,H,Sl,Sl] score tile in HBM.
    - "flash": the Pallas kernel (ops/pallas/flash_attention_lse) — the
      score tile stays in VMEM, and the kernel's (out, lse) pair IS a
      merge-ready blockwise contribution: out is the block-normalized
      numerator, so (num=out, den=1, m=lse) drops into the same LSE
      accumulator (out * exp(lse - new_max) = exp(logits - new_max) @ V
      and 1 * exp(lse - new_max) = rowsum exp(logits - new_max)). This is
      the long-S configuration SP exists for: O(S_local) HBM from the ring
      AND VMEM-resident score tiles from the kernel.
    The merge itself is f32 in both paths, and at f32 inputs they agree to
    rounding. They differ ONLY in local-block precision: "xla" upcasts the
    whole block to f32 (HBM-expensive — part of why it needs the score
    tile); "flash" keeps the kernel's input-dtype output, so bf16 runs
    round each block's numerator to bf16 before the f32 merge (~1e-2
    relative — the standard flash-kernel contract; forcing f32 through the
    kernel would forfeit the MXU bf16 path it exists for). Both paths are
    differentiable — flash's lse cotangent is handled by its custom VJP."""
    if impl not in ("xla", "flash"):
        raise ValueError(
            f"ring attention impl {impl!r}: use 'xla' | 'flash'")
    n = compat_axis_size(axis_name)
    scale = q.shape[-1] ** -0.5
    qf = q.astype(jnp.float32)

    def block_xla(k_blk, v_blk):
        logits = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32))
        logits *= scale
        m = jnp.max(logits, axis=-1)  # [B,H,Sq]
        p = jnp.exp(logits - m[..., None])
        num = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32))
        den = jnp.sum(p, axis=-1)  # [B,H,Sq]
        return num, den, m

    def block_flash(k_blk, v_blk):
        from dist_mnist_tpu.ops.pallas.flash_attention import (
            flash_attention_lse,
        )

        # block_k streams K/V tiles through VMEM *within* the local
        # block too (online softmax) — ring bounds HBM, block_k bounds
        # VMEM residency
        out, lse = flash_attention_lse(q, k_blk, v_blk,
                                       block_k=block_k)  # [B,Sq,H,D],[B,H,Sq]
        return out.astype(jnp.float32), jnp.ones_like(lse), lse

    block = block_flash if impl == "flash" else block_xla

    def body(i, carry):
        acc_num, acc_den, acc_max, k_blk, v_blk = carry
        num, den, m = block(k_blk, v_blk)
        new_max = jnp.maximum(acc_max, m)
        old_scale = jnp.exp(acc_max - new_max)
        blk_scale = jnp.exp(m - new_max)
        sc = lambda s: jnp.moveaxis(s, -1, 1)[..., None]  # [B,H,Sq]->[B,Sq,H,1]
        acc_num = acc_num * sc(old_scale) + num * sc(blk_scale)
        acc_den = acc_den * old_scale + den * blk_scale
        # rotate K/V to the next ring position (neighbour ICI hop); XLA
        # overlaps the ppermute with the current block's compute
        k_blk = ring_shift(k_blk, axis_name)
        v_blk = ring_shift(v_blk, axis_name)
        return acc_num, acc_den, new_max, k_blk, v_blk

    b, sl, h, d = q.shape
    init = (
        jnp.zeros((b, sl, h, d), jnp.float32),
        jnp.zeros((b, h, sl), jnp.float32),
        jnp.full((b, h, sl), -jnp.inf, jnp.float32),
        k,
        v,
    )
    acc_num, acc_den, _, _, _ = lax.fori_loop(0, n, body, init)
    out = acc_num / jnp.moveaxis(acc_den, -1, 1)[..., None]
    # save_attn remat tag (train/step.py REMAT_POLICIES): the seq-sharded
    # path must tag its own output — it never routes through
    # ops/nn.dot_product_attention, whose tag covers only the seq==1
    # fallback. Identity outside jax.checkpoint.
    from jax.ad_checkpoint import checkpoint_name

    return checkpoint_name(out.astype(q.dtype), "attn_out")


def ring_self_attention(q, k, v, mesh: Mesh, axis_name: str = SEQ_AXIS,
                        impl: str = "xla", block_k: int | None = None):
    """shard_map wrapper over [B,S,H,D]: batch stays sharded over `data`,
    heads over `model`, and the sequence dim rings over `axis_name` — the
    full hybrid DP x TP x SP layout in one spec. Requires B % data == 0,
    H % model == 0, S % seq == 0. `impl` picks the local-block engine
    (see ring_attention_inner)."""
    from dist_mnist_tpu.cluster.mesh import DATA_AXIS, MODEL_AXIS

    spec = P(DATA_AXIS, axis_name, MODEL_AXIS, None)
    from dist_mnist_tpu.cluster.mesh import compat_shard_map

    fn = compat_shard_map(
        partial(ring_attention_inner, axis_name=axis_name, impl=impl,
                block_k=block_k),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


def ring_attention(q, k, v, impl: str = "xla",
                   block_k: int | None = None):
    """Mesh-adaptive entry used by models: ring over the ambient mesh's
    `seq` axis when present (>1), else exact fallback (flash kernel when
    impl="flash", plain attention otherwise — so the same model code runs
    on any mesh AND keeps its kernel choice when the mesh has no seq
    axis)."""
    mesh = get_abstract_mesh()
    if mesh is None or SEQ_AXIS not in mesh.shape or mesh.shape[SEQ_AXIS] == 1:
        if impl == "flash":
            from dist_mnist_tpu.parallel.flash import flash_attention_tagged

            # the shared seq-less kernel fallback: mesh-adaptive (a
            # seq-less mesh can still carry a model axis — ring_flash
            # under TP — where a bare pallas_call would silently
            # replicate) + the same attn_out tag every other attention
            # path carries (save_attn remat policy stays uniform)
            return flash_attention_tagged(q, k, v, block_k=block_k)
        from dist_mnist_tpu.ops.nn import dot_product_attention

        return dot_product_attention(q, k, v)
    return ring_self_attention(q, k, v, mesh, impl=impl, block_k=block_k)
