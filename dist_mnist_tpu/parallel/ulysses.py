"""Ulysses-style sequence parallelism: all-to-all head<->sequence reshard.

The alternative to ring attention (SURVEY.md §5.7 "Ulysses"): instead of
rotating K/V blocks, one `all_to_all` over ICI converts the sequence-sharded
layout [B, S/n, H, D] into a head-sharded layout [B, S, H/n, D]; attention
then runs fully local per device (exact, no streaming softmax needed), and a
second all_to_all restores the sequence sharding. Cheaper than ring when
H >= ring size and S_local is small; ring wins for very long S (its
memory stays O(S_local)).

`impl="flash"` swaps the local attention for the Pallas kernel — and
matters MORE here than in ring: after the reshard each device attends over
the FULL sequence, so the XLA path materializes a full [B, H/n, S, S]
score tensor in HBM; the kernel keeps score tiles in VMEM (plus `block_k`
streams K/V — ops/pallas/flash_attention). Mirrors ring_attention's
impl/block_k surface; ViT selects it via attention_impl="ulysses_flash".
"""

from __future__ import annotations

from functools import partial

import jax

from dist_mnist_tpu.cluster.mesh import compat_axis_size
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dist_mnist_tpu.cluster.mesh import ambient_mesh as get_abstract_mesh

from dist_mnist_tpu.cluster.mesh import SEQ_AXIS
from dist_mnist_tpu.ops.nn import dot_product_attention


def ulysses_attention_inner(q, k, v, axis_name: str = SEQ_AXIS,
                            impl: str = "xla",
                            block_k: int | None = None):
    """Inside shard_map: [B, S_local, H, D] per device; H % axis_size == 0.
    `impl` picks the local full-S attention engine: "xla" (HBM score
    tensor) or "flash" (VMEM score tiles; `block_k` streams K/V)."""
    if impl not in ("xla", "flash"):
        raise ValueError(
            f"ulysses attention impl {impl!r}: use 'xla' | 'flash'")
    n = compat_axis_size(axis_name)
    if q.shape[2] % n:
        raise ValueError(f"heads {q.shape[2]} not divisible by seq axis {n}")
    # scatter heads (axis 2), gather sequence (axis 1): -> [B, S, H/n, D]
    reshard = lambda x: lax.all_to_all(x, axis_name, split_axis=2,
                                       concat_axis=1, tiled=True)
    unshard = lambda x: lax.all_to_all(x, axis_name, split_axis=1,
                                       concat_axis=2, tiled=True)
    if impl == "flash":
        from jax.ad_checkpoint import checkpoint_name

        from dist_mnist_tpu.ops.pallas.flash_attention import flash_attention

        # same attn_out tag dot_product_attention applies on the xla path
        # (save_attn remat policy stays uniform across impls)
        out = checkpoint_name(
            flash_attention(reshard(q), reshard(k), reshard(v),
                            block_k=block_k),
            "attn_out")
    else:
        out = dot_product_attention(reshard(q), reshard(k), reshard(v))
    return unshard(out)


def ulysses_self_attention(q, k, v, mesh: Mesh, axis_name: str = SEQ_AXIS,
                           impl: str = "xla", block_k: int | None = None):
    from dist_mnist_tpu.cluster.mesh import DATA_AXIS

    spec = P(DATA_AXIS, axis_name, None, None)
    from dist_mnist_tpu.cluster.mesh import compat_shard_map

    fn = compat_shard_map(
        partial(ulysses_attention_inner, axis_name=axis_name, impl=impl,
                block_k=block_k),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


def ulysses_attention(q, k, v, impl: str = "xla",
                      block_k: int | None = None):
    """Mesh-adaptive entry used by models (mirrors ring_attention): the
    all-to-all reshard runs over the ambient mesh's `seq` axis when present
    (>1), else falls back to the impl-matched exact path — the same model
    code runs on any mesh AND keeps its kernel choice. Requires
    H % seq == 0 and S % seq == 0 on seq meshes."""
    mesh = get_abstract_mesh()
    if mesh is None or SEQ_AXIS not in mesh.shape or mesh.shape[SEQ_AXIS] == 1:
        if impl == "flash":
            from dist_mnist_tpu.parallel.flash import flash_attention_tagged

            # shared seq-less kernel fallback (see parallel/flash.py)
            return flash_attention_tagged(q, k, v, block_k=block_k)
        return dot_product_attention(q, k, v)
    return ulysses_self_attention(q, k, v, mesh, impl=impl, block_k=block_k)
