"""Ulysses-style sequence parallelism: all-to-all head<->sequence reshard.

The alternative to ring attention (SURVEY.md §5.7 "Ulysses"): instead of
rotating K/V blocks, one `all_to_all` over ICI converts the sequence-sharded
layout [B, S/n, H, D] into a head-sharded layout [B, S, H/n, D]; attention
then runs fully local per device (exact, no streaming softmax needed), and a
second all_to_all restores the sequence sharding. Cheaper than ring when
H >= ring size and S_local is small; ring wins for very long S (its
memory stays O(S_local)).
"""

from __future__ import annotations

from functools import partial

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P, get_abstract_mesh

from dist_mnist_tpu.cluster.mesh import SEQ_AXIS
from dist_mnist_tpu.ops.nn import dot_product_attention


def ulysses_attention_inner(q, k, v, axis_name: str = SEQ_AXIS):
    """Inside shard_map: [B, S_local, H, D] per device; H % axis_size == 0."""
    n = lax.axis_size(axis_name)
    if q.shape[2] % n:
        raise ValueError(f"heads {q.shape[2]} not divisible by seq axis {n}")
    # scatter heads (axis 2), gather sequence (axis 1): -> [B, S, H/n, D]
    reshard = lambda x: lax.all_to_all(x, axis_name, split_axis=2,
                                       concat_axis=1, tiled=True)
    unshard = lambda x: lax.all_to_all(x, axis_name, split_axis=1,
                                       concat_axis=2, tiled=True)
    out = dot_product_attention(reshard(q), reshard(k), reshard(v))
    return unshard(out)


def ulysses_self_attention(q, k, v, mesh: Mesh, axis_name: str = SEQ_AXIS):
    from dist_mnist_tpu.cluster.mesh import DATA_AXIS

    spec = P(DATA_AXIS, axis_name, None, None)
    fn = jax.shard_map(
        partial(ulysses_attention_inner, axis_name=axis_name),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def ulysses_attention(q, k, v):
    """Mesh-adaptive entry used by models (mirrors ring_attention): the
    all-to-all reshard runs over the ambient mesh's `seq` axis when present
    (>1), else falls back to exact local attention — the same model code
    runs on any mesh. Requires H % seq == 0 and S % seq == 0 on seq meshes."""
    mesh = get_abstract_mesh()
    if mesh is None or SEQ_AXIS not in mesh.shape or mesh.shape[SEQ_AXIS] == 1:
        return dot_product_attention(q, k, v)
    return ulysses_self_attention(q, k, v, mesh)
