"""Expert parallelism — switch-style MoE FFN with `all_to_all` dispatch.

Absent from the reference (SURVEY.md §2.6 lists EP as out of scope for
parity) but first-class here, because on TPU the dispatch primitive the
whole technique hangs on — `lax.all_to_all` over an ICI axis — is a single
compiled collective rather than the NCCL grouped send/recv a CUDA
implementation hand-rolls.

Design (top-1 "switch" routing by default, GShard-style top-k via `top_k`;
one expert per rank of the expert axis):
- gate: tokens [T, D] -> scores [T, E]; each token routes to its k best
  experts (k=1: raw softmax prob as combine weight; k>=2: the chosen
  probs renormalized to sum to 1).
- capacity: C = ceil(T/E * k * capacity_factor); assignments beyond an
  expert's capacity are dropped (contribute zero — standard switch
  behavior) — but never silently: every entry point also returns `stats`
  = {drop_fraction, expert_load[E]} so routing health is observable
  (the train step surfaces them as step metrics via the `_metric`
  model-state contract, train/step.py). `moe_ffn_adaptive` ADDS
  `ep_engaged` (1.0 = dispatched over the expert axis, 0.0 = dense
  fallback) — adaptive-only, so the dense/EP oracles keep one structure.
- dispatch: one-hot [T, E, C] mask -> [E, C, D] buffer -> tiled
  `all_to_all` so each rank receives the tokens bound for ITS expert from
  every rank -> expert FFN (dense relu dense) -> reverse `all_to_all` ->
  weighted combine back to [T, D].
- aux: load-balance loss (Shazeer/Switch form): E * sum_e f_e * p_e, where
  f_e = fraction of tokens routed to e, p_e = mean router prob for e.

`jax.grad` differentiates through both all_to_alls (they transpose to each
other), so expert-parallel backward needs no extra code.

Entry points:
- `init_moe(key, dim, hidden, n_experts)` — param pytree; expert weights
  have leading dim E for sharding over the expert axis.
- `moe_ffn_inner(params, x, axis_name)` — inside shard_map (params' expert
  leaves pre-sliced to this rank's experts).
- `moe_ffn(params, x, mesh, axis_name=MODEL_AXIS)` — jit-able wrapper;
  one expert per rank (E == axis size).
- `moe_ffn_dense(params, x)` — no-mesh reference implementation (all
  experts local); the numeric oracle for tests.
"""

from __future__ import annotations

from functools import partial

import jax

from dist_mnist_tpu.cluster.mesh import compat_axis_size
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dist_mnist_tpu.cluster.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    ambient_mesh as get_abstract_mesh,
    compat_shard_map,
)
from dist_mnist_tpu.ops.nn import fan_in_trunc_normal
from dist_mnist_tpu.ops.quant import q_dot


def init_moe(key, dim: int, hidden: int, n_experts: int):
    """Gate [D, E] + per-expert FFN stacks [E, ...]."""
    kg, k1, k2 = jax.random.split(key, 3)
    return {
        "gate": fan_in_trunc_normal(kg, (dim, n_experts)),
        "w1": fan_in_trunc_normal(k1, (n_experts, dim, hidden)),
        "b1": jnp.zeros((n_experts, hidden)),
        "w2": fan_in_trunc_normal(k2, (n_experts, hidden, dim)),
        "b2": jnp.zeros((n_experts, dim)),
    }


def _route(gate_w, x, n_experts: int, capacity: int, top_k: int = 1):
    """Top-k routing tensors: combine [T,E,C] (gate weight on the chosen
    slot), dispatch = combine != 0, the router statistics (f, p) the aux
    load-balance loss is built from, and routing-health stats. f/p are
    LOCAL means over the tokens seen here; the caller reduces them to
    global means before forming aux = E * Σ_e f_e p_e (the Switch form) —
    aux is linear in neither, so the reduction must happen on f/p, not on
    per-shard aux values.

    top_k=1 is the Switch rule (combine weight = raw softmax prob of the
    argmax expert); top_k>=2 is the GShard-style rule (a token rides to its
    k best experts, weights = their probs renormalized to sum to 1). A
    token's k experts are distinct, so the assignment matrix stays 0/1 and
    one queue-position cumsum covers every k.

    stats (health, not objective — VERDICT r3 weak 5: drops were silent):
    - drop_fraction: dropped (over-capacity) assignments / total assignments
    - expert_load:   [E] fraction of each expert's capacity C actually used
    """
    if not 1 <= top_k <= n_experts:
        raise ValueError(
            f"top_k={top_k} must be in [1, n_experts={n_experts}] "
            "(1 = Switch routing, >=2 = GShard-style top-k)"
        )
    scores = x @ gate_w  # [T, E]
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    _, top_idx = jax.lax.top_k(probs, top_k)  # [T, K]
    assigned = jnp.sum(  # [T, E] 0/1 — k distinct experts per token
        jax.nn.one_hot(top_idx, n_experts, dtype=jnp.float32), axis=1
    )
    weights = probs * assigned  # [T, E]
    if top_k > 1:
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
    # position of each (token, expert) assignment in the expert's queue
    pos = jnp.cumsum(assigned, axis=0) * assigned - assigned  # [T, E]
    in_cap = (pos < capacity).astype(jnp.float32) * assigned
    slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                          dtype=jnp.float32)  # [T, E, C]
    dispatch = in_cap[:, :, None] * slot  # [T, E, C] 0/1
    combine = dispatch * weights[:, :, None]
    # f normalized by k so Σ_e f_e = 1 and the aux scale is k-invariant.
    # DELIBERATE deviation from GShard's top-k aux (which uses the fraction
    # of tokens whose TOP-1 choice is e): counting all k assignments
    # balances the load the capacity queues actually see — every
    # assignment occupies a slot, not just the top-1 ones. Identical to
    # the canonical form at k=1 (advisor r4).
    f = jnp.mean(assigned, axis=0) / top_k  # [E]
    p = jnp.mean(probs, axis=0)  # [E] mean router prob per expert
    n_assigned = jnp.sum(assigned)
    stats = {
        "drop_fraction": 1.0 - jnp.sum(in_cap) / jnp.maximum(n_assigned, 1.0),
        "expert_load": jnp.sum(in_cap, axis=0) / capacity,
    }
    return dispatch, combine, f, p, stats


def _expert_ffn(w1, b1, w2, b2, tokens):
    # q_dot is a plain matmul on float weights (bit-identical baseline);
    # int8-served expert stacks take its fused-Pallas vs XLA-materialize
    # dispatch (ops/quant.py) — vmap over the stacked [E, D, H] leaves
    # batches the Pallas kernel, scan/all_to_all paths arrive pre-sliced
    h = jax.nn.relu(q_dot(tokens, w1) + b1)
    return q_dot(h, w2) + b2


def moe_ffn_dense(params, x, capacity_factor: float = 1.25, top_k: int = 1):
    """All experts local — the einsum-only oracle (also the fallback on a
    mesh without an expert axis). Returns (out, aux, stats)."""
    t, _ = x.shape
    e = params["gate"].shape[-1]
    capacity = max(1, int(-(-t // e) * top_k * capacity_factor))
    dispatch, combine, f, p, stats = _route(params["gate"], x, e, capacity,
                                            top_k)
    aux = e * jnp.sum(f * p)
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    expert_out = jax.vmap(_expert_ffn)(
        params["w1"], params["b1"], params["w2"], params["b2"], expert_in
    )
    out = jnp.einsum("tec,ecd->td", combine, expert_out)
    return out.astype(x.dtype), aux, stats


def moe_ffn_inner(params, x, axis_name: str = MODEL_AXIS,
                  capacity_factor: float = 1.25, aux_axes=None,
                  top_k: int = 1):
    """Inside shard_map: x [T_local, D] — tokens sharded over the expert
    axis too (canonical EP: the expert axis doubles as extra data sharding
    outside the MoE layer); params' expert leaves sliced to this rank
    (leading dim 1 — one expert per rank). `aux_axes`: every mesh axis the
    tokens are sharded over (default: just `axis_name`); router statistics
    are pmean'd over them so aux equals the dense oracle's global value.
    Health stats are likewise pmean'd: with equal-sized token shards that
    is the exact global drop fraction, and per-expert load averaged over
    the per-shard queues (each shard routes its own T_local tokens with
    capacity C — the EP capacity is per-shard by construction)."""
    n_experts = compat_axis_size(axis_name)
    t, _ = x.shape
    capacity = max(1, int(-(-t // n_experts) * top_k * capacity_factor))
    dispatch, combine, f, p, stats = _route(params["gate"], x, n_experts,
                                            capacity, top_k)
    aux_axes = (axis_name,) if aux_axes is None else tuple(aux_axes)
    f, p = lax.pmean(f, aux_axes), lax.pmean(p, aux_axes)
    stats = jax.tree.map(lambda a: lax.pmean(a, aux_axes), stats)
    aux = n_experts * jnp.sum(f * p)
    # [T,E,C] x [T,D] -> [E, C, D] send buffer (row e = tokens for expert e)
    send = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    # THE dispatch collective: rank r ends up with the C tokens every rank
    # routed to ITS expert, concatenated in rank order -> [1, E*C, D]
    recv = lax.all_to_all(send, axis_name, split_axis=0, concat_axis=1,
                          tiled=True)
    # tree.map so a QuantizedArray expert stack unstacks its q AND scale
    # (a plain array is a single leaf — identical to a direct squeeze)
    w1, b1, w2, b2 = (jax.tree.map(lambda a: jnp.squeeze(a, 0), params[k])
                      for k in ("w1", "b1", "w2", "b2"))
    out_tok = _expert_ffn(w1, b1, w2, b2, recv[0])  # [E*C, D]
    # reverse all_to_all: chunk s of out_tok goes back to rank s; what
    # arrives from rank e is expert e's outputs for OUR tokens -> [E, C, D]
    expert_out = lax.all_to_all(
        out_tok.reshape(n_experts, capacity, -1), axis_name,
        split_axis=0, concat_axis=0, tiled=True,
    )
    out = jnp.einsum("tec,ecd->td", combine, expert_out)
    return out.astype(x.dtype), aux, stats


def moe_ffn_adaptive(params, x, capacity_factor: float = 1.25,
                     top_k: int = 1):
    """Mesh-adaptive entry used by models (mirrors ring/ulysses attention):
    expert-parallel over the ambient mesh's `model` axis when it is >1 AND
    matches the expert count, else the dense-local oracle — the same model
    code runs on any mesh. x: [T, D] tokens. An expert-count/axis MISMATCH
    on a real model axis falls back dense too, but loudly: the user asked
    for expert parallelism and isn't getting it."""
    import logging

    mesh = get_abstract_mesh()
    e = params["gate"].shape[-1]
    axis = (getattr(mesh, "shape", {}) or {}).get(MODEL_AXIS, 1) if mesh else 1
    # `ep_engaged` rides the stats into the `_metric` step outputs: the
    # Python warning below fires once per trace, so a jit-cached dense
    # fallback would otherwise be invisible in logs/summaries while the
    # user believes the run is expert-parallel (VERDICT r4 weak #6)
    if axis != e:
        if axis > 1:
            logging.getLogger(__name__).warning(
                "moe_ffn_adaptive: n_experts=%d != model axis %d — running "
                "DENSE (all experts local, no all_to_all dispatch); size "
                "the model axis to the expert count for expert parallelism",
                e, axis,
            )
        out, aux, stats = moe_ffn_dense(params, x, capacity_factor, top_k)
        engaged = 0.0
    else:
        out, aux, stats = moe_ffn(params, x, mesh, MODEL_AXIS,
                                  capacity_factor, top_k)
        engaged = 1.0
    return out, aux, {**stats,
                      "ep_engaged": jnp.asarray(engaged, jnp.float32)}


def moe_ffn(params, x, mesh: Mesh, axis_name: str = MODEL_AXIS,
            capacity_factor: float = 1.25, top_k: int = 1):
    """Expert-parallel switch FFN over `mesh`'s `axis_name`; one expert per
    rank (E == axis size). x: [T, D] tokens, sharded jointly over
    `data` x the expert axis (T % (data*E) == 0); gate replicated; expert
    stacks sharded on their leading dim."""
    e = mesh.shape[axis_name]
    if params["gate"].shape[-1] != e:
        raise ValueError(
            f"n_experts {params['gate'].shape[-1]} != {axis_name} axis {e}"
        )
    p_spec = {
        "gate": P(),
        "w1": P(axis_name), "b1": P(axis_name),
        "w2": P(axis_name), "b2": P(axis_name),
    }
    tok_spec = P((DATA_AXIS, axis_name))
    run = compat_shard_map(
        partial(moe_ffn_inner, axis_name=axis_name,
                capacity_factor=capacity_factor,
                aux_axes=(DATA_AXIS, axis_name), top_k=top_k),
        mesh=mesh,
        in_specs=(p_spec, tok_spec),
        out_specs=(tok_spec, P(),
                   {"drop_fraction": P(), "expert_load": P()}),
    )
    return run(params, x)
