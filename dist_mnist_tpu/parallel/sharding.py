"""Placement rules: path-pattern -> PartitionSpec.

Replaces `replica_device_setter` (SURVEY.md §2.2 row 5): the reference
decided placement by *op type* (Variable-ish ops round-robin onto ps tasks,
device_setter.py:92-125); we decide by *param path* against mesh axes. Data
parallelism = params replicated, batch sharded on `data`; tensor parallelism
= matmul weights sharded on `model` (Megatron-style column/row pairs).
"""

from __future__ import annotations

import dataclasses
import re

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dist_mnist_tpu.cluster.mesh import DATA_AXIS, MODEL_AXIS


@dataclasses.dataclass(frozen=True)
class ShardingRules:
    """Ordered (regex, spec-maker) rules; first match wins, default
    replicated. The spec maker receives the array's ndim so a rule can place
    an axis relative to the end (e.g. "last dim over model").

    `fsdp_axis` adds a SHAPE-based rule on top of the regex rules (ZeRO-1 /
    FSDP, the TPU-native revival of the reference's `replica_device_setter`
    partitioning): every float leaf additionally shards its LARGEST axis
    that (a) is divisible by the mesh's `fsdp_axis` size and (b) the regex
    spec left free — so it composes with TP (a `qkv/w` already P(None,
    "model") becomes P("data", "model")). Leaves with no such axis (small
    biases, scalars) stay on their regex spec. GSPMD then inserts the
    all-gather on use and the reduce-scatter on the matching grads; the
    resident copy in HBM is 1/axis-size per device."""

    rules: tuple[tuple[str, tuple], ...] = ()
    fsdp_axis: str | None = None

    def spec_for(self, path: str, ndim: int) -> P:
        for pattern, axes in self.rules:
            if re.search(pattern, path):
                if len(axes) > ndim:  # rule doesn't fit (e.g. bias) -> last dims
                    axes = axes[-ndim:] if ndim else ()
                pad = (None,) * (ndim - len(axes))
                return P(*(pad + tuple(axes)))
        return P()  # replicated

    def leaf_spec(self, path: str, leaf, mesh: Mesh) -> P:
        """Full per-leaf placement: regex spec, then the FSDP shape rule."""
        spec = self.spec_for(path, getattr(leaf, "ndim", 0))
        if self.fsdp_axis:
            spec = _fsdp_compose(
                spec, leaf, mesh.shape[self.fsdp_axis], self.fsdp_axis
            )
        return spec

    def _fsdp_shards(self, leaf, mesh: Mesh | None, base: P) -> bool:
        """Would the FSDP shape rule shard `leaf` beyond its regex spec?"""
        if not self.fsdp_axis or mesh is None:
            return False
        return (
            _fsdp_compose(base, leaf, mesh.shape[self.fsdp_axis],
                          self.fsdp_axis)
            != base
        )

    def match_count(self, tree, mesh: Mesh | None = None) -> int:
        """How many leaves of `tree` this strategy actually places (0 on an
        empty rule set). A non-empty strategy matching NOTHING means it
        silently degrades to replication — callers should refuse. The FSDP
        shape rule needs the `mesh` to decide divisibility; without one only
        the regex rules are counted."""
        flat, _, paths = _paths(tree)
        n = 0
        for p, (_, v) in zip(paths, flat):
            if any(re.search(pattern, p) for pattern, _ in self.rules):
                n += 1
            elif self._fsdp_shards(v, mesh, self.spec_for(
                    p, getattr(v, "ndim", 0))):
                n += 1
        return n


def _fsdp_compose(spec: P, leaf, axis_size: int, axis_name: str) -> P:
    """`spec` with `axis_name` added on the largest free divisible dim of
    `leaf`, or `spec` unchanged when no dim qualifies. Float arrays only:
    params and optimizer slots are what ZeRO shards — uint8 batches, int
    counters, and PRNG keys must never be split by a shape heuristic."""
    shape = getattr(leaf, "shape", ())
    dtype = getattr(leaf, "dtype", None)
    if not shape or dtype is None or not jnp.issubdtype(dtype, jnp.floating):
        return spec
    entries = tuple(spec) + (None,) * (len(shape) - len(tuple(spec)))
    if axis_name in entries:  # already placed by a regex rule
        return spec
    best = -1
    for i, (dim, taken) in enumerate(zip(shape, entries)):
        if taken is None and dim % axis_size == 0 and dim > 1:
            if best < 0 or dim > shape[best]:
                best = i
    if best < 0:
        return spec
    entries = entries[:best] + (axis_name,) + entries[best + 1:]
    return P(*entries)


# Pure data parallelism: every param replicated.
DP_RULES = ShardingRules()

# Megatron-style TP for the transformer blocks + big fc layers:
#  - qkv / mlp_in: column-parallel (output dim over `model`)
#  - out / mlp_out: row-parallel  (input dim over `model`)
# Biases of row-parallel layers stay replicated (added after the reduce).
TP_RULES = ShardingRules(
    rules=(
        (r"(qkv|mlp_in|fc1)/w$", (None, MODEL_AXIS)),
        (r"(qkv|mlp_in|fc1)/b$", (MODEL_AXIS,)),
        (r"(attn/out|mlp_out|fc2)/w$", (MODEL_AXIS, None)),
    )
)

# ZeRO-1/FSDP: params + optimizer slots sharded over `data` (the shape rule
# — each leaf's largest divisible free axis), batch sharding unchanged. The
# SPMD revival of the reference's PS partitioning: `replica_device_setter`
# round-robined Variables AND their Adam slots across ps tasks
# (device_setter.py:92-125); here the same state is 1/data-th per chip and
# GSPMD inserts the gather/scatter the PS protocol did over gRPC.
FSDP_RULES = ShardingRules(fsdp_axis=DATA_AXIS)

# FSDP composed with Megatron TP: regex rules place the `model` axis first,
# the shape rule adds `data` on the largest remaining free dim.
FSDP_TP_RULES = ShardingRules(rules=TP_RULES.rules, fsdp_axis=DATA_AXIS)


def resolve_rules(name: str) -> ShardingRules:
    """Config-string -> rules (`Config.sharding_rules`). One definition so
    every driver (cli/train.py, bench.py) benchmarks/trains the SAME
    strategy a config names — a driver that forgot to thread this through
    would silently run DP under a TP config's name."""
    table = {"dp": DP_RULES, "tp": TP_RULES, "fsdp": FSDP_RULES,
             "fsdp_tp": FSDP_TP_RULES}
    if name not in table:
        raise ValueError(
            f"unknown sharding_rules {name!r}; use 'dp' | 'tp' | 'fsdp' | "
            "'fsdp_tp'"
        )
    return table[name]


def _key_seg(k) -> str:
    # DictKey -> "conv1", GetAttrKey -> "params" (str() would render
    # ".params" and break the "params/" prefix checks below),
    # SequenceKey -> "[0]" (chain optimizer states)
    if hasattr(k, "key"):
        return str(k.key)
    if hasattr(k, "name"):
        return str(k.name)
    if hasattr(k, "idx"):
        return f"[{k.idx}]"
    return str(k)


def _paths(tree):
    # tree_util spelling: `jax.tree.flatten_with_path` only exists on
    # jax>=0.5, and this is the same function there
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    paths = ["/".join(_key_seg(k) for k in path) for path, _ in flat]
    return flat, treedef, paths


def derive_state_specs(state, mesh: Mesh, rules: ShardingRules):
    """PartitionSpec pytree for a TrainState (anything with `.params` and
    `.opt_state`): params place by `rules` (regex + FSDP shape rule), and
    every OPTIMIZER-STATE leaf INHERITS the spec of the param it mirrors —
    matched by path suffix + shape — instead of defaulting to replicated.

    That inheritance is the derived-spec contract: Adam's m/v, AdamW's
    inner slots, the accumulation buffer (`acc/...`), and chained states
    (`[i]/m/...`) all structurally mirror the param tree, so the colocation
    the reference got from slot-colocated-with-variable on the PS
    (adam.py:189-203) holds under any rule set — including the shape-based
    FSDP rule, where a regex over slot paths could never see shapes.
    Non-mirroring opt leaves (step counters) and everything outside
    params/opt_state (model_state, step, rng) stay on the regex rules
    alone; the FSDP shape rule never touches them (BN statistics are
    updated by the forward pass — sharding them would change what a
    device computes, not just where bytes live)."""
    param_flat, _, param_paths = _paths(state.params)
    # longest-suffix match first: a bare "w" param must not shadow "x/w"
    by_len = sorted(
        zip(param_paths, param_flat), key=lambda kv: -len(kv[0])
    )
    param_specs = {
        p: rules.leaf_spec(p, v, mesh) for p, (_, v) in zip(
            param_paths, param_flat)
    }

    def inherited(path, leaf):
        shape = getattr(leaf, "shape", None)
        for ppath, (_, pleaf) in by_len:
            if (path.endswith("/" + ppath)
                    and getattr(pleaf, "shape", ()) == shape):
                return param_specs[ppath]
        return None

    flat, treedef, paths = _paths(state)
    specs = []
    for path, (_, leaf) in zip(paths, flat):
        if path.startswith("params/"):
            spec = rules.leaf_spec(path[len("params/"):], leaf, mesh)
        elif path.startswith("opt_state/"):
            spec = inherited(path, leaf)
            if spec is None:
                spec = rules.spec_for(path, getattr(leaf, "ndim", 0))
        else:
            spec = rules.spec_for(path, getattr(leaf, "ndim", 0))
        specs.append(spec)
    return jax.tree.unflatten(treedef, specs)


def tree_sharding(tree, mesh: Mesh, rules: ShardingRules):
    """Matching pytree of NamedShardings for `tree` under `rules`.

    A TrainState-shaped tree (has `.params`/`.opt_state`) goes through
    `derive_state_specs` so optimizer slots inherit their param's spec;
    any other pytree places each leaf independently by `leaf_spec`."""
    if hasattr(tree, "params") and hasattr(tree, "opt_state"):
        specs = derive_state_specs(tree, mesh, rules)
        return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                            is_leaf=lambda x: isinstance(x, P))
    flat, treedef, paths = _paths(tree)
    shardings = [
        NamedSharding(mesh, rules.leaf_spec(p, v, mesh))
        for p, (_, v) in zip(paths, flat)
    ]
    return jax.tree.unflatten(treedef, shardings)


def params_sharding(params, mesh: Mesh, rules: ShardingRules = DP_RULES):
    return tree_sharding(params, mesh, rules)


def _put_via_callback(leaf, sharding):
    """Place one (host-resident, process-identical) leaf under `sharding`
    without any cross-process collective.

    `jax.device_put` onto a sharding that is not fully addressable first
    broadcast-verifies the value across processes (multihost_utils.
    assert_equal) — one gloo broadcast PER LEAF, which flakes under
    concurrent launch (`op.preamble.length <= op.nbytes`). Initial state
    is computed identically on every process (same seed, same pure
    program), so the check is redundant: assemble the global array from
    local slices directly. Bitwise-equal to the device_put result.

    A leaf that is already a global (non-addressable) jax.Array cannot be
    read host-side; those fall back to device_put — by then they already
    carry a committed sharding, so no equality broadcast fires."""
    import numpy as np

    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        return jax.device_put(leaf, sharding)
    arr = np.asarray(leaf)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def shard_train_state(state, mesh: Mesh, rules: ShardingRules = DP_RULES):
    """Device_put a TrainState with params/opt-state placed by `rules`.

    Optimizer slots (Adam m/v — the reference's PS-resident slot variables,
    adam.py:189-203) inherit their param's spec: slot math is elementwise,
    so colocating slot shards with param shards makes the update fully
    local, exactly as slot-colocated-with-variable did on the PS.

    Refuses a non-trivial rule set that matches NO parameter: that is the
    silent-wrong-strategy failure `resolve_rules` exists to prevent (a
    `sharding_rules="tp"` config over a conv model — or `fsdp` over a model
    none of whose param dims divide the data axis — would otherwise train
    fully replicated under the strategy's name).
    """
    if (rules.rules or rules.fsdp_axis) and \
            rules.match_count(state.params, mesh) == 0:
        what = (tuple(p for p, _ in rules.rules)
                or f"fsdp over axis {rules.fsdp_axis!r}")
        raise ValueError(
            f"sharding rules {what} matched no "
            "parameter path — the model would silently train fully "
            "replicated (DP) under this strategy's name. Pick rules that "
            "match this model's params, or use DP_RULES explicitly."
        )
    sharded = tree_sharding(state, mesh, rules)
    if all(s.is_fully_addressable
           for s in jax.tree.leaves(sharded,
                                    is_leaf=lambda x: isinstance(
                                        x, NamedSharding))):
        return jax.device_put(state, sharded)
    # Multi-process: jax.device_put on a non-fully-addressable sharding
    # routes through multihost_utils.assert_equal — a per-leaf gloo
    # broadcast that races when many leaves go out back-to-back
    # (`op.preamble.length <= op.nbytes` SIGABRT). Every process computes
    # the SAME deterministic init here (same seed, same program), so the
    # cross-host equality check buys nothing: build each global array
    # directly from the local copy instead, no collective at all.
    return jax.tree.map(_put_via_callback, state, sharded)


def reshard_state(state, mesh: Mesh, rules: ShardingRules = DP_RULES):
    """Live spec migration: move an EXISTING (already-placed) state onto
    `mesh` under `rules`, re-deriving every leaf's spec for the new shape.

    This is the in-memory half of the elastic-resize story
    (docs/RESILIENCE.md "Elastic generations"): `derive_state_specs` is
    world-size-parameterized — the same rule set yields different
    PartitionSpecs on an 8- vs 4-device mesh (an fsdp dim that divides 8
    but not 4 falls back to replicated per leaf) — so resharding is just
    "derive specs against the NEW mesh, then move the bytes". Values are
    bitwise-preserved: the fast path lets XLA reshuffle device buffers
    (`device_put` handles cross-mesh moves when both sides are fully
    addressable), the general path round-trips through the host.

    Cross-PROCESS live migration is not attempted here: a shrunken world
    restores from the latest checkpoint instead (checkpoint/manager.py
    builds the abstract target with the new mesh's shardings, which is
    this same respec applied at restore time).
    """
    sharded = tree_sharding(state, mesh, rules)

    def _move(leaf, sharding):
        if not isinstance(leaf, jax.Array):
            return _put_via_callback(leaf, sharding)
        if leaf.is_fully_addressable and sharding.is_fully_addressable:
            return jax.device_put(leaf, sharding)
        arr = jax.device_get(leaf)  # raises on non-addressable source:
        # live cross-process migration goes via checkpoint, by design
        return jax.make_array_from_callback(arr.shape, sharding,
                                            lambda idx: arr[idx])

    return jax.tree.map(_move, state, sharded)
