"""Placement rules: path-pattern -> PartitionSpec.

Replaces `replica_device_setter` (SURVEY.md §2.2 row 5): the reference
decided placement by *op type* (Variable-ish ops round-robin onto ps tasks,
device_setter.py:92-125); we decide by *param path* against mesh axes. Data
parallelism = params replicated, batch sharded on `data`; tensor parallelism
= matmul weights sharded on `model` (Megatron-style column/row pairs).
"""

from __future__ import annotations

import dataclasses
import re

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dist_mnist_tpu.cluster.mesh import MODEL_AXIS


@dataclasses.dataclass(frozen=True)
class ShardingRules:
    """Ordered (regex, spec-maker) rules; first match wins, default
    replicated. The spec maker receives the array's ndim so a rule can place
    an axis relative to the end (e.g. "last dim over model")."""

    rules: tuple[tuple[str, tuple], ...] = ()

    def spec_for(self, path: str, ndim: int) -> P:
        for pattern, axes in self.rules:
            if re.search(pattern, path):
                if len(axes) > ndim:  # rule doesn't fit (e.g. bias) -> last dims
                    axes = axes[-ndim:] if ndim else ()
                pad = (None,) * (ndim - len(axes))
                return P(*(pad + tuple(axes)))
        return P()  # replicated

    def match_count(self, tree) -> int:
        """How many leaves of `tree` any rule matches (0 on an empty rule
        set). A non-empty rule set matching NOTHING means the named strategy
        silently degrades to replication — callers should refuse."""
        _, _, paths = _paths(tree)
        return sum(
            1 for p in paths
            if any(re.search(pattern, p) for pattern, _ in self.rules)
        )


# Pure data parallelism: every param replicated.
DP_RULES = ShardingRules()

# Megatron-style TP for the transformer blocks + big fc layers:
#  - qkv / mlp_in: column-parallel (output dim over `model`)
#  - out / mlp_out: row-parallel  (input dim over `model`)
# Biases of row-parallel layers stay replicated (added after the reduce).
TP_RULES = ShardingRules(
    rules=(
        (r"(qkv|mlp_in|fc1)/w$", (None, MODEL_AXIS)),
        (r"(qkv|mlp_in|fc1)/b$", (MODEL_AXIS,)),
        (r"(attn/out|mlp_out|fc2)/w$", (MODEL_AXIS, None)),
    )
)


def resolve_rules(name: str) -> ShardingRules:
    """Config-string -> rules (`Config.sharding_rules`). One definition so
    every driver (cli/train.py, bench.py) benchmarks/trains the SAME
    strategy a config names — a driver that forgot to thread this through
    would silently run DP under a TP config's name."""
    table = {"dp": DP_RULES, "tp": TP_RULES}
    if name not in table:
        raise ValueError(f"unknown sharding_rules {name!r}; use 'dp' | 'tp'")
    return table[name]


def _paths(tree):
    # tree_util spelling: `jax.tree.flatten_with_path` only exists on
    # jax>=0.5, and this is the same function there
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    paths = ["/".join(str(getattr(k, "key", k)) for k in path) for path, _ in flat]
    return flat, treedef, paths


def tree_sharding(tree, mesh: Mesh, rules: ShardingRules):
    """Matching pytree of NamedShardings for `tree` under `rules`."""
    flat, treedef, paths = _paths(tree)
    shardings = [
        NamedSharding(mesh, rules.spec_for(p, getattr(v, "ndim", 0)))
        for p, (_, v) in zip(paths, flat)
    ]
    return jax.tree.unflatten(treedef, shardings)


def params_sharding(params, mesh: Mesh, rules: ShardingRules = DP_RULES):
    return tree_sharding(params, mesh, rules)


def shard_train_state(state, mesh: Mesh, rules: ShardingRules = DP_RULES):
    """Device_put a TrainState with params/opt-state placed by `rules`.

    Optimizer slots (Adam m/v — the reference's PS-resident slot variables,
    adam.py:189-203) inherit their param's spec: slot math is elementwise,
    so colocating slot shards with param shards makes the update fully
    local, exactly as slot-colocated-with-variable did on the PS.

    Refuses a non-trivial rule set that matches NO parameter: that is the
    silent-wrong-strategy failure `resolve_rules` exists to prevent (a
    `sharding_rules="tp"` config over a conv model would otherwise train
    fully replicated under TP's name).
    """
    if rules.rules and rules.match_count(state.params) == 0:
        raise ValueError(
            f"sharding rules {tuple(p for p, _ in rules.rules)} matched no "
            "parameter path — the model would silently train fully "
            "replicated (DP) under this strategy's name. Pick rules that "
            "match this model's params, or use DP_RULES explicitly."
        )
    sharded = tree_sharding(state, mesh, rules)
    return jax.device_put(state, sharded)
