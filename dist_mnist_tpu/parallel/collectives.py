"""Named collective helpers + the explicit-SPMD (shard_map) step variant.

This is the manual-control counterpart of the GSPMD path in train/step.py:
there XLA *infers* the all-reduce from shardings; here the collectives are
written out. Each helper names the reference mechanism it replaces
(SURVEY.md §3.3/§3.4) — together they are the entire user-visible surface
of what was rows 21-27 of §2.5 (gRPC master/worker/rendezvous).
"""

from __future__ import annotations

from functools import partial

import jax

from dist_mnist_tpu.cluster.mesh import compat_axis_size
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dist_mnist_tpu.cluster.mesh import DATA_AXIS


def psum_mean(tree, axis_name: str = DATA_AXIS):
    """Average a gradient pytree across an axis — the one collective that
    replaces the whole PS push/pull + ConditionalAccumulator.take_grad
    average (sync_replicas_optimizer.py:295-300): one ICI all-reduce,
    in-program, overlapped by XLA with surrounding compute."""
    n = compat_axis_size(axis_name)
    return jax.tree.map(lambda g: lax.psum(g, axis_name) / n, tree)


def ring_shift(x, axis_name: str, *, reverse: bool = False):
    """Rotate x one step around the axis ring via ppermute (the building
    block of ring attention / ring all-reduce; rides neighbour ICI links)."""
    n = compat_axis_size(axis_name)
    if reverse:
        perm = [(i, (i - 1) % n) for i in range(n)]
    else:
        perm = [(i, (i + 1) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def all_to_all_heads(x, axis_name: str, *, split_axis: int, concat_axis: int):
    """Tiled all_to_all (Ulysses reshard: scatter one axis, gather another)."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def make_explicit_dp_step(model, optimizer, mesh: Mesh, *, loss_fn=None):
    """Data-parallel train step with hand-written collectives via shard_map.

    Semantically identical to train/step.make_train_step on a pure-DP mesh;
    exists (a) as executable documentation of where the all-reduce sits in
    the step, (b) as the template for hybrid strategies where manual
    placement beats GSPMD inference. Per-device closure: grads are psum-
    averaged BEFORE the optimizer update, so optimizer state stays bitwise
    identical across replicas — the invariant the PS enforced by having one
    copy of the slots (SURVEY.md §2.3 row 7).
    """
    from dist_mnist_tpu.ops import losses as losses_lib, metrics
    from dist_mnist_tpu.optim.base import apply_updates
    from dist_mnist_tpu.train.state import TrainState

    loss_fn = loss_fn or losses_lib.softmax_cross_entropy

    def per_device_step(state: TrainState, batch):
        # state replicated; batch holds this device's shard of the batch
        step_key = jax.random.fold_in(state.rng, state.step)
        x = batch["image"].astype(jnp.float32) / 255.0
        y = batch["label"]

        def loss_of(params):
            logits, new_ms = model.apply(
                params, state.model_state, x, train=True, rng=step_key
            )
            loss = loss_fn(logits, y)
            # same aux-objective CONTRACT as the GSPMD core (train/step.py
            # model_aux_loss). Note the semantics difference for
            # batch-statistic auxes like MoE's load balance: here the model
            # runs per-shard, so routing/capacity and aux are computed on
            # each shard's tokens and the pmean below averages the
            # per-shard estimates — the standard local-routing DP-MoE
            # choice. The GSPMD step routes over the GLOBAL batch. The two
            # agree when capacity is generous (no drops) and the router is
            # balanced; at tight capacity they are different (both valid)
            # estimators of the Switch objective.
            from dist_mnist_tpu.train.step import model_aux_loss

            aux = model_aux_loss(new_ms)
            if aux is not None:
                loss = loss + aux
            return loss, (logits, new_ms)

        (loss, (logits, new_ms)), grads = jax.value_and_grad(
            loss_of, has_aux=True
        )(state.params)
        # THE collective: replaces RecvTensor push/pull (§3.3)
        grads = psum_mean(grads, DATA_AXIS)
        # BN running stats were computed on local shards; average them so the
        # replicated-state invariant holds (GSPMD's sync-BN equivalent)
        new_ms = jax.tree.map(lambda a: lax.pmean(a, DATA_AXIS), new_ms)
        loss = lax.pmean(loss, DATA_AXIS)
        acc = lax.pmean(metrics.accuracy(logits, y), DATA_AXIS)
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_state = TrainState(
            step=state.step + 1,
            params=apply_updates(state.params, updates),
            model_state=new_ms,
            opt_state=new_opt,
            rng=state.rng,
        )
        return new_state, {"loss": loss, "accuracy": acc}

    state_spec = P()  # replicated
    batch_spec = {"image": P(DATA_AXIS), "label": P(DATA_AXIS)}

    from dist_mnist_tpu.cluster.mesh import compat_shard_map

    sharded = compat_shard_map(
        per_device_step,
        mesh=mesh,
        in_specs=(state_spec, batch_spec),
        out_specs=(state_spec, P()),
    )
    return jax.jit(sharded, donate_argnums=(0,))
