"""Fleet-router driver: N serving replicas behind one `serve/router.py`
Router, driven with two-class traffic.

`python -m dist_mnist_tpu.cli.router --config=mlp_mnist --replicas=3 \
    --platform=cpu --host_device_count=8 --checkpoint_dir=/tmp/ckpt`

Two fleet shapes:

- ``--inprocess`` (default): replicas are `InProcessReplica`s in this
  process, sharing one `CompiledModelCache` (AOT executables take the
  weights as runtime arguments, so the fleet compiles each bucket once).
  Fast to stand up; what tests and bench use.
- ``--noinprocess``: each replica is a spawned
  `cli/serve.py --serve_forever` subprocess on its own port, reached via
  `HttpReplica` (POST /predict, /swap; probed over /healthz). A
  `FleetScraper` (obs/fleet.py, PR 9's cross-host poller) scrapes every
  replica's /metrics and merges them onto THIS process's exporter — one
  scrape shows the whole serving fleet.

Either way the router gives SLO-tiered admission, health-probe routing,
retry/hedge/failover, and — with ``--watch`` — the zero-downtime weight
roll driven by the training run's commit markers (docs/SERVING.md
"Fleet router").
"""

from __future__ import annotations

import json
import logging

from absl import app, flags

log = logging.getLogger(__name__)

FLAGS = flags.FLAGS

flags.DEFINE_string("config", "mlp_mnist", "config name (see configs.py)")
flags.DEFINE_string("checkpoint_dir", None,
                    "checkpoint directory to serve from (and to watch for "
                    "commit markers with --watch); None = fresh init")
flags.DEFINE_integer("step", None, "initial checkpoint step (None = latest)")
flags.DEFINE_integer("replicas", 3, "fleet size")
flags.DEFINE_boolean("inprocess", True,
                     "in-process replicas (shared compile cache); "
                     "--noinprocess spawns cli/serve.py --serve_forever "
                     "subprocesses reached over HTTP")
flags.DEFINE_string("mesh", None, 'mesh override, e.g. "data=8"')
flags.DEFINE_string("platform", None, "pin the jax backend (e.g. cpu)")
flags.DEFINE_integer("host_device_count", None,
                     "with --platform=cpu: number of virtual host devices")
# -- per-replica serving policy ----------------------------------------------
flags.DEFINE_integer("max_batch", 64, "coalesce ceiling (requests per batch)")
flags.DEFINE_float("max_wait_ms", 2.0, "coalesce window after first request")
flags.DEFINE_integer("queue_depth", 256, "per-replica admission bound")
flags.DEFINE_string("compile_cache_dir", None,
                    "compilecache/ directory shared by the fleet; restarts "
                    "and subprocess replicas rewarm from its disk tier")
# -- model-zoo serving (serve/zoo.py; forwarded to every replica) -------------
flags.DEFINE_string("seq_buckets", None,
                    'variable-length serving: "auto", "h1,h2,...", or unset '
                    "for the native-only engine (see cli/serve.py)")
flags.DEFINE_float("moe_capacity_factor", 0,
                   "inference-time MoE expert capacity factor override; "
                   "0 = the checkpoint's train-time factor")
flags.DEFINE_float("serve_memory_budget_mb", 0,
                   "per-device weights+executables budget (MiB) per "
                   "replica engine; 0 = unbounded")
flags.DEFINE_string("serve_rules", None,
                    "serve-time sharding strategy override (cross-strategy "
                    "restore; see docs/SERVING.md)")
flags.DEFINE_string("quant", None,
                    'weight-only quantized serving ("int8"), forwarded to '
                    "every replica: ~4x smaller resident weights per "
                    "replica engine, so more replicas fit one host's "
                    "budget; hot-swap rolls re-quantize on the fly "
                    "(docs/SERVING.md)")
flags.DEFINE_string("fault_plan", None,
                    "faults/plan.py FaultPlan JSON (inline or path); "
                    "serve_replica_kill / serve_replica_stall target "
                    "replica ids, exercising failover and hedging")
# -- router policy ------------------------------------------------------------
flags.DEFINE_float("hedge_after_ms", 0,
                   "fixed hedge timeout for latency_sensitive requests; "
                   "0 = derive from the live p99")
flags.DEFINE_float("health_interval_s", 0.1, "replica probe cadence")
flags.DEFINE_boolean("watch", False,
                     "poll <checkpoint_dir>/commits and hot-swap the fleet "
                     "to each newly committed step (zero-downtime roll)")
flags.DEFINE_float("watch_interval_s", 2.0, "commit-marker poll cadence")
# -- autoscaling (serve/autoscale.py; requires --inprocess) --------------------
flags.DEFINE_boolean("autoscale", False,
                     "run an Autoscaler control loop over the fleet: "
                     "traffic-driven replica add/remove between "
                     "--min_replicas and --max_replicas (in-process fleets "
                     "only; new replicas warm-start from the shared compile "
                     "cache)")
flags.DEFINE_integer("min_replicas", 1, "autoscaler floor")
flags.DEFINE_integer("max_replicas", 8, "autoscaler ceiling")
flags.DEFINE_float("slo_p99_ms", 500.0,
                   "latency_sensitive p99 SLO the autoscaler defends")
flags.DEFINE_float("autoscale_interval_s", 0.25, "control-loop tick cadence")
# -- load generation ----------------------------------------------------------
flags.DEFINE_string("trace", None,
                    "trace-driven open-loop arrivals instead of the "
                    "closed-loop loadgen: diurnal | burst | flash_crowd")
flags.DEFINE_float("trace_duration_s", 20.0, "trace length (trace seconds)")
flags.DEFINE_float("trace_base_rps", 10.0, "trace baseline request rate")
flags.DEFINE_float("trace_peak_mult", 10.0,
                   "peak rate as a multiple of --trace_base_rps")
flags.DEFINE_integer("requests", 512, "loadgen request count")
flags.DEFINE_integer("concurrency", 64, "loadgen in-flight window")
flags.DEFINE_integer("seed", 0, "loadgen input/class seed")
flags.DEFINE_float("ls_fraction", 0.8, "latency_sensitive traffic fraction")
flags.DEFINE_float("ls_deadline_ms", 0,
                   "latency_sensitive per-request deadline; 0 = none")
flags.DEFINE_float("be_deadline_ms", 0,
                   "best_effort per-request deadline; 0 = none")
# -- observability ------------------------------------------------------------
flags.DEFINE_integer("metrics_port", 0,
                     "router-process /metrics (incl. fleet/ gauges and, in "
                     "subprocess mode, the FleetScraper's merged replica "
                     "series), /healthz and /events; 0 = disabled")
flags.DEFINE_string("journal", None,
                    "append-only JSONL run-journal path (obs/events.py); "
                    "replica_up/down, shed, weights_swap etc. land here")

# conftest leak registry: spawned replica subprocesses still alive after a
# test are leaks (mirrors cli/launch.py's _LIVE_CHILDREN)
_LIVE_REPLICA_PROCS: list = []


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_one_replica(i: int):
    """Spawn ONE `cli/serve.py --serve_forever` child (registered in
    `_LIVE_REPLICA_PROCS` immediately, before any wait, so a crash between
    spawn and admission still leaves the proc visible to the leak check).
    Returns (proc, url) without waiting for /healthz."""
    import os
    import subprocess
    import sys

    from dist_mnist_tpu.obs import events as events_mod

    port = _free_port()
    cmd = [
        sys.executable, "-m", "dist_mnist_tpu.cli.serve",
        "--serve_forever", f"--config={FLAGS.config}",
        f"--metrics_port={port}", f"--replica_id={i}",
        f"--max_batch={FLAGS.max_batch}",
        f"--max_wait_ms={FLAGS.max_wait_ms}",
        f"--queue_depth={FLAGS.queue_depth}",
    ]
    if FLAGS.checkpoint_dir:
        cmd.append(f"--checkpoint_dir={FLAGS.checkpoint_dir}")
    if FLAGS.step is not None:
        cmd.append(f"--step={FLAGS.step}")
    if FLAGS.platform:
        cmd.append(f"--platform={FLAGS.platform}")
    if FLAGS.host_device_count:
        cmd.append(f"--host_device_count={FLAGS.host_device_count}")
    if FLAGS.compile_cache_dir:
        cmd.append(f"--compile_cache_dir={FLAGS.compile_cache_dir}")
    if FLAGS.seq_buckets:
        cmd.append(f"--seq_buckets={FLAGS.seq_buckets}")
    if FLAGS.moe_capacity_factor:
        cmd.append(f"--moe_capacity_factor={FLAGS.moe_capacity_factor}")
    if FLAGS.serve_memory_budget_mb:
        cmd.append(
            f"--serve_memory_budget_mb={FLAGS.serve_memory_budget_mb}")
    if FLAGS.serve_rules:
        cmd.append(f"--serve_rules={FLAGS.serve_rules}")
    if FLAGS.quant:
        cmd.append(f"--quant={FLAGS.quant}")
    if FLAGS.fault_plan:
        cmd.append(f"--fault_plan={FLAGS.fault_plan}")
    if FLAGS.mesh:
        cmd.append(f"--mesh={FLAGS.mesh}")
    env = dict(os.environ)
    env[events_mod.ENV_HOST_ID] = str(i)
    if FLAGS.journal:
        env[events_mod.ENV_JOURNAL] = FLAGS.journal
    proc = subprocess.Popen(cmd, env=env)
    _LIVE_REPLICA_PROCS.append(proc)
    url = f"http://127.0.0.1:{port}"
    log.info("spawned replica %d (pid %d) on %s", i, proc.pid, url)
    return proc, url


def _reap_replica_proc(proc):
    """Terminate a spawned replica child and delist it from the leak
    registry — the single teardown path whether the replica retires at
    shutdown or mid-run (membership churn)."""
    import signal

    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=30)
    except Exception:  # noqa: BLE001
        proc.kill()
        proc.wait(timeout=5)
    if proc in _LIVE_REPLICA_PROCS:
        _LIVE_REPLICA_PROCS.remove(proc)


def _spawn_replicas(n: int):
    """Spawn n `cli/serve.py --serve_forever` children and wait until each
    /healthz reports serving. Returns (procs, HttpReplicas)."""
    import time
    import urllib.request

    from dist_mnist_tpu.serve import HttpReplica

    procs, urls = [], {}
    for i in range(n):
        proc, url = _spawn_one_replica(i)
        procs.append(proc)
        urls[i] = url

    deadline = time.monotonic() + 180.0  # cold jax import + prewarm compiles
    for i, proc in enumerate(procs):
        while True:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"replica {i} exited rc={proc.returncode} before serving")
            try:
                with urllib.request.urlopen(urls[i] + "/healthz",
                                            timeout=2.0) as r:
                    if json.loads(r.read()).get("state") == "serving":
                        break
            except OSError:
                pass
            if time.monotonic() > deadline:
                raise TimeoutError(f"replica {i} not serving within budget")
            time.sleep(0.25)
    replicas = [HttpReplica(i, urls[i], capacity_hint=FLAGS.queue_depth)
                for i in sorted(urls)]
    return procs, urls, replicas


def _build_inprocess_replicas(n: int):
    """N `InProcessReplica`s over one mesh + one shared compile cache."""
    from dist_mnist_tpu.cluster import initialize_distributed
    from dist_mnist_tpu.cluster.mesh import MeshSpec, make_mesh
    from dist_mnist_tpu.configs import get_config
    from dist_mnist_tpu.obs import HealthState
    from dist_mnist_tpu.serve import (
        CompiledModelCache,
        InferenceServer,
        InProcessReplica,
        ServeConfig,
        build_zoo_engine,
        load_for_serving,
    )

    initialize_distributed(
        None, 1, 0,
        platform=FLAGS.platform, host_device_count=FLAGS.host_device_count,
    )
    cfg = get_config(FLAGS.config)
    spec = cfg.mesh
    if FLAGS.mesh:
        kv = dict(part.split("=") for part in FLAGS.mesh.split(","))
        spec = MeshSpec(**{k: int(v) for k, v in kv.items()})
    mesh = make_mesh(spec)
    bundle = load_for_serving(
        cfg, mesh, checkpoint_dir=FLAGS.checkpoint_dir, step=FLAGS.step,
        sharding_rules=FLAGS.serve_rules, quant=FLAGS.quant or None)
    store = None
    if FLAGS.compile_cache_dir:
        from pathlib import Path

        from dist_mnist_tpu.compilecache import ExecutableStore

        store = ExecutableStore(Path(FLAGS.compile_cache_dir) / "exe")
    shared_cache = CompiledModelCache(store=store)
    plan = None
    if FLAGS.fault_plan:
        from dist_mnist_tpu.faults import FaultPlan

        plan = FaultPlan.from_spec(FLAGS.fault_plan)

    def make_server_factory(replica_id: int, startup=None):
        # `startup` is an optional StartupClock: when the autoscaler spawns
        # a replica it wants load-vs-compile attribution, so the engine/
        # weights build lands in the "restore" bucket and the prewarm (a
        # shared-cache rewarm — near-zero when warm) in "compile"
        from contextlib import nullcontext

        def make_server():
            with (startup.phase("restore") if startup else nullcontext()):
                engine = build_zoo_engine(
                    bundle, mesh, model_name=cfg.model,
                    max_bucket=max(FLAGS.max_batch, 1),
                    seq_buckets=FLAGS.seq_buckets or None,
                    moe_capacity_factor=FLAGS.moe_capacity_factor or None,
                    memory_budget_mb=FLAGS.serve_memory_budget_mb or None,
                    cache=shared_cache,
                )
                if plan is not None:
                    engine = plan.wrap_engine(engine, replica_id=replica_id)
                server = InferenceServer(
                    engine,
                    ServeConfig(max_batch=FLAGS.max_batch,
                                max_wait_ms=FLAGS.max_wait_ms,
                                queue_depth=FLAGS.queue_depth),
                    health=HealthState(),
                )
            with (startup.phase("compile") if startup else nullcontext()):
                return server.start()

        return make_server

    def load_weights(step: int):
        # quant rides the reload too: `roll_weights` hands each replica an
        # already-quantized tree (the engine would re-quantize a float one
        # anyway — this just pays the conversion once per roll, not per
        # replica)
        new = load_for_serving(
            cfg, mesh, checkpoint_dir=FLAGS.checkpoint_dir, step=step,
            sharding_rules=FLAGS.serve_rules, quant=FLAGS.quant or None)
        if not new.restored:
            raise FileNotFoundError(f"no committed checkpoint at step {step}")
        return new.params, new.model_state

    def make_replica(replica_id: int, startup=None):
        """Build-and-start one replica over the SAME bundle/mesh/shared
        cache — the autoscaler's spawn seam (cold replicas rewarm from the
        fleet's compile cache instead of compiling)."""
        return InProcessReplica(
            replica_id, make_server_factory(replica_id, startup),
            load_weights=load_weights if FLAGS.checkpoint_dir else None,
        ).start()

    replicas = [make_replica(i) for i in range(n)]
    return bundle, replicas, make_replica, shared_cache


def main(argv):
    del argv
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s",
    )
    logging.getLogger("absl").setLevel(logging.WARNING)

    import os

    from dist_mnist_tpu.obs import (
        FleetScraper,
        HealthState,
        MetricRegistry,
        MetricsExporter,
        RunJournal,
    )
    from dist_mnist_tpu.obs import events as events_mod
    from dist_mnist_tpu.serve import (
        CheckpointWatcher,
        Router,
        RouterConfig,
        run_fleet_loadgen,
    )

    registry = MetricRegistry()
    health = HealthState(
        generation=int(os.environ.get(events_mod.ENV_GENERATION, "0")))
    journal_path = (FLAGS.journal or os.environ.get(events_mod.ENV_JOURNAL))
    journal = (RunJournal(journal_path, generation=health.generation)
               if journal_path else None)
    if journal is not None:
        events_mod.set_journal(journal)

    if FLAGS.autoscale and not FLAGS.inprocess:
        raise app.UsageError("--autoscale requires --inprocess (the spawn "
                             "seam shares one compile cache and mesh)")

    procs: list = []
    scraper = None
    exporter = None
    watcher = None
    router = None
    autoscaler = None
    replicas: list = []
    make_replica = None
    shared_cache = None
    try:
        if FLAGS.inprocess:
            n0 = FLAGS.min_replicas if FLAGS.autoscale else FLAGS.replicas
            bundle, replicas, make_replica, shared_cache = (
                _build_inprocess_replicas(n0))
            image_shape = bundle.image_shape
            initial_step = bundle.step
        else:
            procs, urls, replicas = _spawn_replicas(FLAGS.replicas)
            from dist_mnist_tpu.configs import get_config
            from dist_mnist_tpu.data.datasets import DATASETS

            cfg = get_config(FLAGS.config)
            image_shape = tuple(DATASETS[cfg.dataset]["image_shape"])
            initial_step = FLAGS.step
            # PR 9's cross-host poller, retargeted at the serving fleet:
            # merged replica /metrics (incl. serve/ latency ladders) on
            # this process's exporter, plus /fleet JSON
            scraper = FleetScraper(journal=journal, interval_s=0.5)
            scraper.set_targets(urls)
            scraper.start()

        if FLAGS.metrics_port:
            try:
                exporter = MetricsExporter(
                    registry, health=health, journal_path=journal_path,
                    port=FLAGS.metrics_port,
                    info={"host_id": os.environ.get(events_mod.ENV_HOST_ID,
                                                    "0"),
                          "generation": str(health.generation),
                          "role": "router"},
                    fleet=scraper,
                ).start()
            except OSError as e:
                log.warning("metrics exporter: could not bind port %d (%s)",
                            FLAGS.metrics_port, e)

        router = Router(
            replicas,
            RouterConfig(
                hedge_after_ms=FLAGS.hedge_after_ms or None,
                health_interval_s=FLAGS.health_interval_s,
            ),
            registry=registry,
        ).start()
        health.set("serving")

        if FLAGS.autoscale:
            from dist_mnist_tpu.serve import (
                Autoscaler,
                FleetSignalSource,
                ScalePolicy,
            )

            def _spawn(replica_id, startup):
                # scaled-up replicas land in `replicas` so the finally
                # block below owns their teardown like the seed fleet's
                replica = make_replica(replica_id, startup)
                replicas.append(replica)
                return replica

            def _reap(replica):
                replica.close()
                if replica in replicas:
                    replicas.remove(replica)

            autoscaler = Autoscaler(
                router,
                FleetSignalSource(router, scraper=scraper),
                _spawn,
                reap=_reap,
                policy=ScalePolicy(min_replicas=FLAGS.min_replicas,
                                   max_replicas=FLAGS.max_replicas,
                                   slo_p99_ms=FLAGS.slo_p99_ms),
                interval_s=FLAGS.autoscale_interval_s,
                registry=registry,
                cache=shared_cache,
            ).start()

        if FLAGS.watch:
            if not FLAGS.checkpoint_dir:
                raise app.UsageError("--watch requires --checkpoint_dir")
            watcher = CheckpointWatcher(
                FLAGS.checkpoint_dir, router.roll_weights,
                poll_interval_s=FLAGS.watch_interval_s,
                initial_step=initial_step,
            ).start()

        if FLAGS.trace:
            from dist_mnist_tpu.serve import (
                burst_trace,
                diurnal_trace,
                flash_crowd_trace,
                run_trace_loadgen,
            )

            dur, base = FLAGS.trace_duration_s, FLAGS.trace_base_rps
            peak = base * FLAGS.trace_peak_mult
            if FLAGS.trace == "diurnal":
                arrivals = diurnal_trace(duration_s=dur, base_rps=base,
                                         peak_rps=peak, seed=FLAGS.seed)
            elif FLAGS.trace == "burst":
                arrivals = burst_trace(
                    duration_s=dur, base_rps=base, burst_rps=peak,
                    burst_every_s=dur / 4, burst_len_s=dur / 16,
                    seed=FLAGS.seed)
            elif FLAGS.trace == "flash_crowd":
                arrivals = flash_crowd_trace(
                    duration_s=dur, base_rps=base, spike_at_s=dur * 0.3,
                    spike_len_s=dur * 0.2, spike_mult=FLAGS.trace_peak_mult,
                    seed=FLAGS.seed)
            else:
                raise app.UsageError(f"unknown --trace {FLAGS.trace!r}")
            summary = run_trace_loadgen(
                router,
                arrivals=arrivals,
                image_shape=image_shape,
                seed=FLAGS.seed,
                ls_fraction=FLAGS.ls_fraction,
                ls_deadline_ms=FLAGS.ls_deadline_ms or None,
                be_deadline_ms=FLAGS.be_deadline_ms or None,
            )
            summary["trace"]["kind"] = FLAGS.trace
        else:
            summary = run_fleet_loadgen(
                router,
                n_requests=FLAGS.requests,
                concurrency=FLAGS.concurrency,
                image_shape=image_shape,
                seed=FLAGS.seed,
                ls_fraction=FLAGS.ls_fraction,
                ls_deadline_ms=FLAGS.ls_deadline_ms or None,
                be_deadline_ms=FLAGS.be_deadline_ms or None,
            )
        summary["replicas"] = FLAGS.replicas
        summary["inprocess"] = FLAGS.inprocess
        if FLAGS.quant:
            summary["quant"] = FLAGS.quant
        summary["serving_step"] = router.serving_step
        if watcher is not None:
            summary["watcher"] = {"polls": watcher.polls,
                                  "rolls": watcher.rolls}
        if autoscaler is not None:
            summary["autoscale"] = autoscaler.snapshot()
    finally:
        if autoscaler is not None:
            autoscaler.close()
        if watcher is not None:
            watcher.close()
        if router is not None:
            router.close()
        for r in replicas:
            try:
                r.close()
            except Exception:  # noqa: BLE001 - best-effort teardown
                log.warning("replica close failed", exc_info=True)
        for proc in list(procs):
            _reap_replica_proc(proc)
        if scraper is not None:
            scraper.close()
        if exporter is not None:
            exporter.close()
        if journal is not None:
            events_mod.set_journal(None)
            journal.close()
    print(json.dumps(summary, indent=2, sort_keys=True))


if __name__ == "__main__":
    app.run(main)
