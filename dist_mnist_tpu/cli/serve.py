"""Serving driver: load a checkpoint, start the inference server, drive it
with the deterministic load generator, print a latency/batching summary.

`python -m dist_mnist_tpu.cli.serve --config=mlp_mnist \
    --checkpoint_dir=/tmp/ckpt --platform=cpu --host_device_count=8`

There is deliberately no network listener here: the transport (gRPC/HTTP)
is deployment-specific and trivial next to the hard parts — batching,
compilation policy, admission — which this driver exercises end to end
and docs/SERVING.md specifies. `InferenceServer.submit` IS the serving
API; a transport shim maps one RPC to one submit().
"""

from __future__ import annotations

import json
import logging

from absl import app, flags

log = logging.getLogger(__name__)

FLAGS = flags.FLAGS

flags.DEFINE_string("config", "mlp_mnist", "config name (see configs.py)")
flags.DEFINE_string("checkpoint_dir", None,
                    "checkpoint directory to serve from (None = fresh init, "
                    "with a warning — useful for latency benchmarking)")
flags.DEFINE_integer("step", None, "checkpoint step (None = latest)")
flags.DEFINE_string("logdir", None, "serve-metrics output directory")
flags.DEFINE_string("mesh", None, 'mesh override, e.g. "data=8"')
flags.DEFINE_string("platform", None, "pin the jax backend (e.g. cpu)")
flags.DEFINE_integer("host_device_count", None,
                     "with --platform=cpu: number of virtual host devices")
# -- serving policy ----------------------------------------------------------
flags.DEFINE_integer("max_batch", 64, "coalesce ceiling (requests per batch)")
flags.DEFINE_float("max_wait_ms", 2.0, "coalesce window after first request")
flags.DEFINE_integer("queue_depth", 256, "admission queue bound")
flags.DEFINE_float("deadline_ms", 0, "per-request deadline; 0 = none")
flags.DEFINE_boolean("prewarm", True, "compile all buckets before serving")
flags.DEFINE_string("compile_cache_dir", None,
                    "warm-start cache directory (compilecache/): prewarm "
                    "deserializes the buckets a previous server process "
                    "compiled (<dir>/exe) instead of recompiling, and JAX's "
                    "persistent compilation cache runs under <dir>/xla; "
                    "None = cold start")
# -- load generation ---------------------------------------------------------
flags.DEFINE_integer("requests", 512, "loadgen request count")
flags.DEFINE_integer("concurrency", 64, "loadgen in-flight window")
flags.DEFINE_integer("seed", 0, "loadgen input seed")
flags.DEFINE_string("fault_plan", None,
                    "inline JSON or file path of a faults/plan.py FaultPlan; "
                    "serve_error faults wrap the engine so the batcher's "
                    "fail-one-batch-keep-serving isolation is drivable from "
                    "the CLI (docs/RESILIENCE.md)")
flags.DEFINE_integer("metrics_port", 0,
                     "serve /metrics (Prometheus text, incl. live latency/"
                     "batch histograms), /healthz (serving -> draining) and "
                     "/events on this port (obs/exporter.py); 0 = disabled")
flags.DEFINE_string("journal", None,
                    "append-only JSONL run-journal path (obs/events.py); "
                    "defaults to $DIST_MNIST_TPU_JOURNAL, else "
                    "<logdir>/events.jsonl when --logdir is set")


def main(argv):
    del argv
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s",
    )
    logging.getLogger("absl").setLevel(logging.WARNING)

    import os

    from dist_mnist_tpu.cluster import initialize_distributed
    from dist_mnist_tpu.cluster.mesh import MeshSpec, make_mesh
    from dist_mnist_tpu.configs import get_config
    from dist_mnist_tpu.obs import (
        HealthState,
        MetricRegistry,
        MetricsExporter,
        RunJournal,
    )
    from dist_mnist_tpu.obs import events as events_mod
    from dist_mnist_tpu.obs.writers import make_default_writer
    from dist_mnist_tpu.serve import (
        InferenceEngine,
        InferenceServer,
        ServeConfig,
        load_for_serving,
        run_loadgen,
    )

    registry = MetricRegistry()
    health = HealthState(
        generation=int(os.environ.get(events_mod.ENV_GENERATION, "0")))
    journal_path = (FLAGS.journal or os.environ.get(events_mod.ENV_JOURNAL)
                    or (FLAGS.logdir and f"{FLAGS.logdir}/events.jsonl"))
    journal = (RunJournal(journal_path, generation=health.generation)
               if journal_path else None)
    if journal is not None:
        events_mod.set_journal(journal)
    exporter = None
    if FLAGS.metrics_port:
        try:
            exporter = MetricsExporter(
                registry, health=health, journal_path=journal_path,
                port=FLAGS.metrics_port,
                info={
                    "host_id": os.environ.get(events_mod.ENV_HOST_ID, "0"),
                    "generation": str(health.generation),
                    "role": "serve",
                },
            ).start()
        except OSError as e:
            log.warning("metrics exporter: could not bind port %d (%s); "
                        "continuing without exposition", FLAGS.metrics_port, e)

    initialize_distributed(
        None, 1, 0,
        platform=FLAGS.platform, host_device_count=FLAGS.host_device_count,
    )
    cfg = get_config(FLAGS.config)
    spec = cfg.mesh
    if FLAGS.mesh:
        kv = dict(part.split("=") for part in FLAGS.mesh.split(","))
        spec = MeshSpec(**{k: int(v) for k, v in kv.items()})
    mesh = make_mesh(spec)

    bundle = load_for_serving(
        cfg, mesh, checkpoint_dir=FLAGS.checkpoint_dir, step=FLAGS.step
    )
    store = None
    if FLAGS.compile_cache_dir:
        from pathlib import Path

        from dist_mnist_tpu.compilecache import (
            ExecutableStore,
            enable_persistent_cache,
        )

        cache_root = Path(FLAGS.compile_cache_dir)
        enable_persistent_cache(cache_root / "xla")
        store = ExecutableStore(cache_root / "exe")
    engine = InferenceEngine(
        bundle.model, bundle.params, bundle.model_state, mesh,
        model_name=cfg.model, image_shape=bundle.image_shape,
        rules=bundle.rules, max_bucket=max(FLAGS.max_batch, 1),
        store=store,
    )
    if FLAGS.fault_plan:
        from dist_mnist_tpu.faults import FaultPlan

        engine = FaultPlan.from_spec(FLAGS.fault_plan).wrap_engine(engine)
    writer = make_default_writer(FLAGS.logdir, registry=registry)
    server = InferenceServer(
        engine,
        ServeConfig(
            max_batch=FLAGS.max_batch,
            max_wait_ms=FLAGS.max_wait_ms,
            queue_depth=FLAGS.queue_depth,
            default_deadline_ms=FLAGS.deadline_ms or None,
            prewarm=FLAGS.prewarm,
        ),
        writer=writer,
        health=health,
    )
    # live full-distribution exposition of the serve ladders (/metrics)
    server.metrics.attach_to(registry)
    try:
        with server:
            summary = run_loadgen(
                server,
                n_requests=FLAGS.requests,
                concurrency=FLAGS.concurrency,
                image_shape=bundle.image_shape,
                seed=FLAGS.seed,
            )
    finally:
        if exporter is not None:
            exporter.close()
        if journal is not None:
            events_mod.set_journal(None)
            journal.close()
    summary["checkpoint_step"] = bundle.step
    summary["restored"] = bundle.restored
    if store is not None:
        summary["compile_cache"] = store.stats()
    print(json.dumps(summary, indent=2, sort_keys=True))


if __name__ == "__main__":
    app.run(main)
