"""Serving driver: load a checkpoint, start the inference server, drive it
with the deterministic load generator, print a latency/batching summary.

`python -m dist_mnist_tpu.cli.serve --config=mlp_mnist \
    --checkpoint_dir=/tmp/ckpt --platform=cpu --host_device_count=8`

Three modes:

- default: drive the server with the deterministic load generator and
  exit — the transport-free latency/batching harness.
- ``--decode``: autoregressive decode serving (serve/decode.py) — a
  registry causal LM behind the prefill/decode split, continuous
  batching over the sharded KV cache, driven by the seeded decode
  loadgen; prints the TTFT/per-token-throughput summary.
- ``--serve_forever``: run as one FLEET REPLICA until SIGTERM/SIGINT.
  The metrics exporter doubles as the data plane (obs/exporter.py
  do_POST): POST /predict executes one inference, POST /swap quiesces
  and hot-swaps to a committed checkpoint step, and /healthz carries the
  serving -> draining state a `serve/router.py` Router probes. This is
  the process `cli/router.py` spawns N of.

`InferenceServer.submit` IS the serving API either way; the HTTP shim
maps one RPC to one submit().
"""

from __future__ import annotations

import json
import logging

from absl import app, flags

log = logging.getLogger(__name__)

FLAGS = flags.FLAGS

flags.DEFINE_string("config", "mlp_mnist", "config name (see configs.py)")
flags.DEFINE_string("checkpoint_dir", None,
                    "checkpoint directory to serve from (None = fresh init, "
                    "with a warning — useful for latency benchmarking)")
flags.DEFINE_integer("step", None, "checkpoint step (None = latest)")
flags.DEFINE_string("logdir", None, "serve-metrics output directory")
flags.DEFINE_string("mesh", None, 'mesh override, e.g. "data=8"')
flags.DEFINE_string("platform", None, "pin the jax backend (e.g. cpu)")
flags.DEFINE_integer("host_device_count", None,
                     "with --platform=cpu: number of virtual host devices")
# -- serving policy ----------------------------------------------------------
flags.DEFINE_integer("max_batch", 64, "coalesce ceiling (requests per batch)")
flags.DEFINE_float("max_wait_ms", 2.0, "coalesce window after first request")
flags.DEFINE_integer("queue_depth", 256, "admission queue bound")
flags.DEFINE_float("deadline_ms", 0, "per-request deadline; 0 = none")
flags.DEFINE_boolean("prewarm", True, "compile all buckets before serving")
flags.DEFINE_boolean("prewarm_async", False,
                     "warm the compile grid on a background ZooPrewarm "
                     "thread while already serving (first requests may pay "
                     "an on-demand compile; startup stays flat as the zoo "
                     "grid grows)")
# -- model-zoo serving (serve/zoo.py) ----------------------------------------
flags.DEFINE_string("seq_buckets", None,
                    'variable-length serving: "auto" for the power-of-two '
                    'height ladder, "h1,h2,..." for explicit bucket '
                    "ceilings (native appended), unset for the native-only "
                    "engine. Sub-native requests are right-padded and "
                    "masked; the native bucket keeps the maskless "
                    "bit-parity program")
flags.DEFINE_float("moe_capacity_factor", 0,
                   "inference-time MoE expert capacity factor override; "
                   "0 = the checkpoint's train-time factor. Overflow drops "
                   "surface as serve/moe_drop_fraction, never silently")
flags.DEFINE_float("serve_memory_budget_mb", 0,
                   "per-device budget (MiB) for weights + compiled "
                   "executables: prewarm REFUSES a grid that cannot fit; "
                   "live traffic evicts coldest grid cells LRU. 0 = "
                   "unbounded")
flags.DEFINE_string("serve_rules", None,
                    "serve-time sharding strategy override (none/dp/tp/"
                    "fsdp/fsdp_tp): restore a checkpoint trained under one "
                    "strategy directly into another's layout (cross-"
                    "strategy restore; see docs/SERVING.md)")
flags.DEFINE_string("quant", None,
                    'weight-only quantized serving: "int8" converts '
                    "matmul/conv kernels to (int8, f32 per-channel scale) "
                    "at load time — ~4x smaller resident weights under "
                    "--serve_memory_budget_mb; biases/norms/embeddings/"
                    "router gates stay float. Per-leaf quant error lands "
                    "on /metrics as serve/quant_error*; unset = full-width "
                    "float serving (docs/SERVING.md)")
flags.DEFINE_string("compile_cache_dir", None,
                    "warm-start cache directory (compilecache/): prewarm "
                    "deserializes the buckets a previous server process "
                    "compiled (<dir>/exe) instead of recompiling, and JAX's "
                    "persistent compilation cache runs under <dir>/xla; "
                    "None = cold start")
# -- autoregressive decode serving (serve/decode.py) -------------------------
flags.DEFINE_boolean("decode", False,
                     "autoregressive decode mode: serve a registry causal "
                     "LM through the prefill/decode split with continuous "
                     "batching over a sharded KV cache, drive it with the "
                     "seeded decode loadgen, print the TTFT/throughput "
                     "summary (docs/SERVING.md). --config is ignored; "
                     "--mesh/--platform/--metrics_port/--journal apply")
flags.DEFINE_string("decode_mode", "continuous",
                    'decode scheduling: "continuous" (admit between steps) '
                    'or "static" (the drain-the-whole-batch baseline)')
flags.DEFINE_integer("max_slots", 8,
                     "in-flight sequence capacity in --decode mode")
flags.DEFINE_string("decode_model", "causal_tiny",
                    "models/registry.py name of the causal LM to serve in "
                    "--decode mode")
# -- load generation ---------------------------------------------------------
flags.DEFINE_integer("requests", 512, "loadgen request count")
flags.DEFINE_integer("concurrency", 64, "loadgen in-flight window")
flags.DEFINE_integer("seed", 0, "loadgen input seed")
flags.DEFINE_string("fault_plan", None,
                    "inline JSON or file path of a faults/plan.py FaultPlan; "
                    "serve_error faults wrap the engine so the batcher's "
                    "fail-one-batch-keep-serving isolation is drivable from "
                    "the CLI (docs/RESILIENCE.md)")
flags.DEFINE_integer("metrics_port", 0,
                     "serve /metrics (Prometheus text, incl. live latency/"
                     "batch histograms), /healthz (serving -> draining) and "
                     "/events on this port (obs/exporter.py); 0 = disabled")
flags.DEFINE_string("journal", None,
                    "append-only JSONL run-journal path (obs/events.py); "
                    "defaults to $DIST_MNIST_TPU_JOURNAL, else "
                    "<logdir>/events.jsonl when --logdir is set")
# -- fleet-replica mode -------------------------------------------------------
flags.DEFINE_enum("tuned", "auto", ["auto", "off", "require"],
                  "persisted-autotuner serve knobs (dist_mnist_tpu/tune): "
                  "auto = apply the stored serve grid (max_batch / "
                  "seq_buckets winners) for this exact geometry when an "
                  "entry exists, defaults on a miss; require = fail fast "
                  "on a miss; off = never consult the store. Explicit "
                  "--max_batch/--seq_buckets always win. docs/TUNING.md")
flags.DEFINE_string("tuned_dir", None,
                    "TunedConfigStore directory; defaults to "
                    "$DIST_MNIST_TPU_TUNED_DIR")
flags.DEFINE_boolean("serve_forever", False,
                     "run as a fleet replica until SIGTERM/SIGINT: the "
                     "metrics exporter serves POST /predict and /swap next "
                     "to /healthz + /metrics (requires --metrics_port); no "
                     "loadgen runs (cli/router.py drives the traffic)")
flags.DEFINE_integer("replica_id", None,
                     "this replica's id in the fleet (scopes "
                     "serve_replica_* faults in --fault_plan); defaults to "
                     "$DIST_MNIST_TPU_HOST_ID, else 0")


def _serve_forever(server, exporter, cfg, mesh) -> dict:
    """Replica mode: wire the exporter's data plane to this server and
    block until SIGTERM/SIGINT. `predict_fn` maps one POST to one
    submit(); `swap_fn` quiesces the pipeline (the router already stopped
    routing here) and hot-swaps to the requested committed step via the
    same `load_for_serving` path the process booted through."""
    import signal
    import threading

    from dist_mnist_tpu.serve import load_for_serving

    def predict_fn(image, deadline_ms):
        fut = server.submit(image, deadline_ms=deadline_ms)
        # bound the HTTP worker's wait: the request's own deadline plus
        # slack for the batch in front of it, or a generous idle ceiling
        wait_s = (deadline_ms / 1e3 + 30.0) if deadline_ms else 60.0
        return fut.result(timeout=wait_s)

    swap_lock = threading.Lock()

    def swap_fn(step: int) -> dict:
        with swap_lock:
            if not server.quiesce(timeout=30.0):
                raise TimeoutError("pipeline did not quiesce for swap")
            new = load_for_serving(
                cfg, mesh, checkpoint_dir=FLAGS.checkpoint_dir, step=step,
                sharding_rules=FLAGS.serve_rules,
                quant=FLAGS.quant or None)
            if not new.restored:
                raise FileNotFoundError(
                    f"no committed checkpoint at step {step}")
            server.engine.swap_weights(new.params, new.model_state,
                                       version=step)
            if new.quant_report:
                # refresh the /metrics quant-error surface for the NEW
                # weights the replica now serves
                server.metrics.record_quant_report(new.quant_report)
            return {"swapped": True, "step": step, "quant": new.quant}

    exporter.predict_fn = predict_fn
    exporter.swap_fn = swap_fn

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    with server:
        log.info("replica serving on %s (SIGTERM to stop)",
                 exporter.url("/predict"))
        stop.wait()
        # stop accepting POSTs before the pipeline drains
        exporter.predict_fn = None
        exporter.swap_fn = None
    summary = server.stats()
    summary["weights_version"] = server.engine.weights_version
    return summary


def _run_decode(mesh, registry) -> dict:
    """Decode mode: build the LM engine + continuous-batching scheduler,
    prewarm the full prefill/decode grid, drive it with the seeded decode
    loadgen, and return the TTFT/throughput summary."""
    from dist_mnist_tpu.obs.writers import make_default_writer
    from dist_mnist_tpu.serve import (
        DecodeScheduler,
        build_decode_engine,
        run_decode_loadgen,
    )

    engine = build_decode_engine(
        mesh, model_name=FLAGS.decode_model, seed=FLAGS.seed,
        max_slots=FLAGS.max_slots)
    if FLAGS.prewarm:
        engine.prewarm()
    writer = make_default_writer(FLAGS.logdir, registry=registry)
    scheduler = DecodeScheduler(engine, mode=FLAGS.decode_mode,
                                max_queue=FLAGS.queue_depth, writer=writer)
    # live TTFT/throughput/occupancy ladders on /metrics
    scheduler.metrics.attach_to(registry)
    try:
        summary = run_decode_loadgen(
            scheduler,
            n_requests=FLAGS.requests,
            concurrency=FLAGS.concurrency,
            seed=FLAGS.seed,
        )
    finally:
        scheduler.close()
    summary.pop("token_times", None)
    summary["mode"] = FLAGS.decode_mode
    summary["max_slots"] = FLAGS.max_slots
    summary["model"] = FLAGS.decode_model
    return summary


def main(argv):
    del argv
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s",
    )
    logging.getLogger("absl").setLevel(logging.WARNING)

    import os

    from dist_mnist_tpu.cluster import initialize_distributed
    from dist_mnist_tpu.cluster.mesh import MeshSpec, make_mesh
    from dist_mnist_tpu.configs import get_config
    from dist_mnist_tpu.obs import (
        HealthState,
        MetricRegistry,
        MetricsExporter,
        RunJournal,
    )
    from dist_mnist_tpu.obs import events as events_mod
    from dist_mnist_tpu.obs.writers import make_default_writer
    from dist_mnist_tpu.serve import (
        InferenceServer,
        ServeConfig,
        build_zoo_engine,
        load_for_serving,
        run_loadgen,
    )

    registry = MetricRegistry()
    health = HealthState(
        generation=int(os.environ.get(events_mod.ENV_GENERATION, "0")))
    journal_path = (FLAGS.journal or os.environ.get(events_mod.ENV_JOURNAL)
                    or (FLAGS.logdir and f"{FLAGS.logdir}/events.jsonl"))
    journal = (RunJournal(journal_path, generation=health.generation)
               if journal_path else None)
    if journal is not None:
        events_mod.set_journal(journal)
    exporter = None
    if FLAGS.metrics_port:
        try:
            exporter = MetricsExporter(
                registry, health=health, journal_path=journal_path,
                port=FLAGS.metrics_port,
                info={
                    "host_id": os.environ.get(events_mod.ENV_HOST_ID, "0"),
                    "generation": str(health.generation),
                    "role": "serve",
                },
            ).start()
        except OSError as e:
            if FLAGS.serve_forever:
                raise  # the exporter IS the replica's data plane
            log.warning("metrics exporter: could not bind port %d (%s); "
                        "continuing without exposition", FLAGS.metrics_port, e)
    if FLAGS.serve_forever and exporter is None:
        raise app.UsageError("--serve_forever requires --metrics_port")

    initialize_distributed(
        None, 1, 0,
        platform=FLAGS.platform, host_device_count=FLAGS.host_device_count,
    )
    cfg = get_config(FLAGS.config)
    spec = cfg.mesh
    if FLAGS.mesh:
        kv = dict(part.split("=") for part in FLAGS.mesh.split(","))
        spec = MeshSpec(**{k: int(v) for k, v in kv.items()})
    if FLAGS.decode and not FLAGS.mesh:
        # decode serves a registry LM, not the config's classifier: the
        # config mesh is irrelevant, default to all devices on data
        spec = MeshSpec(data=-1)
    mesh = make_mesh(spec)

    if FLAGS.decode:
        try:
            summary = _run_decode(mesh, registry)
        finally:
            if exporter is not None:
                exporter.close()
            if journal is not None:
                events_mod.set_journal(None)
                journal.close()
        print(json.dumps(summary, indent=2, sort_keys=True))
        return

    max_batch, seq_buckets = FLAGS.max_batch, FLAGS.seq_buckets
    if FLAGS.tuned != "off":
        # tuned serve grid for this geometry (dist_mnist_tpu/tune):
        # applied before the engine/server are built so the winners
        # shape the zoo grid and the batcher ceiling; explicitly-set
        # flags stay pinned. The journal is installed above, so the
        # application lands as tuning/applied with its evidence.
        from dist_mnist_tpu.tune import apply_tuned

        protect = tuple(
            name for name, pinned in (
                ("serve_max_batch", FLAGS["max_batch"].present),
                ("serve_seq_buckets", FLAGS["seq_buckets"].present),
            ) if pinned)
        _, tuned_knobs = apply_tuned(
            cfg, mesh, mode=FLAGS.tuned, store_dir=FLAGS.tuned_dir,
            protect=protect, subsystem="serve")
        if "serve_max_batch" in tuned_knobs:
            max_batch = int(tuned_knobs["serve_max_batch"])
        if "serve_seq_buckets" in tuned_knobs:
            seq_buckets = str(tuned_knobs["serve_seq_buckets"])
    bundle = load_for_serving(
        cfg, mesh, checkpoint_dir=FLAGS.checkpoint_dir, step=FLAGS.step,
        sharding_rules=FLAGS.serve_rules, quant=FLAGS.quant or None,
    )
    store = None
    if FLAGS.compile_cache_dir:
        from pathlib import Path

        from dist_mnist_tpu.compilecache import (
            ExecutableStore,
            enable_persistent_cache,
        )

        cache_root = Path(FLAGS.compile_cache_dir)
        enable_persistent_cache(cache_root / "xla")
        store = ExecutableStore(cache_root / "exe")
    engine = build_zoo_engine(
        bundle, mesh, model_name=cfg.model,
        max_bucket=max(max_batch, 1),
        seq_buckets=seq_buckets or None,
        moe_capacity_factor=FLAGS.moe_capacity_factor or None,
        memory_budget_mb=FLAGS.serve_memory_budget_mb or None,
        store=store,
    )
    zoo_engine = engine  # pre-wrap handle for the zoo summary fields
    if FLAGS.fault_plan:
        from dist_mnist_tpu.faults import FaultPlan

        replica_id = (FLAGS.replica_id if FLAGS.replica_id is not None
                      else int(os.environ.get(events_mod.ENV_HOST_ID, "0")
                               or 0))
        engine = FaultPlan.from_spec(FLAGS.fault_plan).wrap_engine(
            engine, replica_id=replica_id)
    writer = make_default_writer(FLAGS.logdir, registry=registry)
    server = InferenceServer(
        engine,
        ServeConfig(
            max_batch=max_batch,
            max_wait_ms=FLAGS.max_wait_ms,
            queue_depth=FLAGS.queue_depth,
            default_deadline_ms=FLAGS.deadline_ms or None,
            prewarm=FLAGS.prewarm,
            prewarm_async=FLAGS.prewarm_async,
        ),
        writer=writer,
        health=health,
    )
    # live full-distribution exposition of the serve ladders (/metrics)
    server.metrics.attach_to(registry)
    try:
        if FLAGS.serve_forever:
            summary = _serve_forever(server, exporter, cfg, mesh)
        else:
            with server:
                summary = run_loadgen(
                    server,
                    n_requests=FLAGS.requests,
                    concurrency=FLAGS.concurrency,
                    image_shape=bundle.image_shape,
                    seed=FLAGS.seed,
                )
    finally:
        if exporter is not None:
            exporter.close()
        if journal is not None:
            events_mod.set_journal(None)
            journal.close()
    summary["checkpoint_step"] = bundle.step
    summary["restored"] = bundle.restored
    summary["serve_state_bytes_per_device"] = \
        zoo_engine.state_bytes_per_device()
    if bundle.quant:
        summary["quant"] = bundle.quant
        summary["quant_error_max"] = bundle.quant_report["max_abs_err"]
        summary["quant_rel_err_max"] = bundle.quant_report["max_rel_err"]
        summary["quant_leaves"] = bundle.quant_report["n_quantized"]
    if zoo_engine.seq_grid is not None:
        summary["seq_buckets"] = list(zoo_engine.seq_grid.heights)
        summary["seq_bucket_counts"] = {
            str(k): v for k, v in sorted(zoo_engine.seq_bucket_counts.items())
        }
    if store is not None:
        summary["compile_cache"] = store.stats()
    print(json.dumps(summary, indent=2, sort_keys=True))


if __name__ == "__main__":
    app.run(main)
