"""`cli/tune.py` — the offline knob search, as a cli/ entrypoint.

Thin delegation to `dist_mnist_tpu.tune.cli` (also reachable as
`python -m dist_mnist_tpu.tune`); both surfaces exist so the tuner sits
next to cli/train.py and cli/serve.py, whose `--tuned=auto` consumes
the store this writes. Usage and flags: tune/cli.py.
"""

from __future__ import annotations

import sys

from dist_mnist_tpu.tune.cli import main

if __name__ == "__main__":
    sys.exit(main())
