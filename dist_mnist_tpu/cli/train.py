"""Training driver — the `dist_mnist.py` replacement (SURVEY.md §0.1).

One SPMD entrypoint: every process runs this same program
(`python -m dist_mnist_tpu.cli.train --config=lenet5_mnist`). The
reference's cluster flags are accepted for familiarity but collapsed:
--job_name/--ps_hosts/--worker_hosts have no meaning without parameter
servers (a warning explains the mapping); --sync_replicas is the default
and only mode (SPMD is synchronous); --replicas_to_aggregate maps to
gradient accumulation (optim/sync.py).

Flag-name parity with the §0.1 table: data_dir, download_only, train_steps,
batch_size, learning_rate, hidden_units, sync_replicas,
replicas_to_aggregate, job_name, task_index, num_gpus, existing_servers,
ps_hosts, worker_hosts.
"""

from __future__ import annotations

import dataclasses
import logging
import time

from absl import app, flags

#: anchor for cold-start attribution (compilecache/startup.py): everything
#: before main() runs — absl + this module's imports — lands in the
#: ``import`` bucket; jax's import is deferred into ``init`` on purpose
_MODULE_T0 = time.monotonic()

log = logging.getLogger(__name__)

FLAGS = flags.FLAGS

# -- reference-parity flags (SURVEY.md §0.1 flag table) ----------------------
flags.DEFINE_string("data_dir", "/tmp/mnist-data", "dataset directory (IDX files)")
flags.DEFINE_boolean("download_only", False,
                     "materialize the dataset (synthetic twin) then exit")
flags.DEFINE_string("job_name", "", "IGNORED: no ps/worker jobs under SPMD")
flags.DEFINE_integer("task_index", 0, "IGNORED: use --process_id for multi-host")
flags.DEFINE_integer("num_gpus", 0, "IGNORED: TPU-native")
flags.DEFINE_integer("train_steps", None, "global steps (None = config value)")
flags.DEFINE_integer("batch_size", None, "GLOBAL batch size (None = config)")
flags.DEFINE_float("learning_rate", None, "LR (None = config value)")
flags.DEFINE_integer("hidden_units", None, "MLP hidden width (mlp model only)")
flags.DEFINE_boolean("sync_replicas", True,
                     "always True under SPMD; False warns (async PS is "
                     "out-of-model; see parallel/ps_demo)")
flags.DEFINE_integer("replicas_to_aggregate", None,
                     "minibatches aggregated per update, as a multiple of the "
                     "mesh: k means accumulate k steps (None = 1)")
flags.DEFINE_boolean("existing_servers", False, "IGNORED: no servers to reuse")
flags.DEFINE_string("ps_hosts", "", "IGNORED: no parameter servers")
flags.DEFINE_string("worker_hosts", "", "IGNORED: workers = mesh devices")

# -- framework flags ---------------------------------------------------------
flags.DEFINE_string("config", "mlp_mnist", "config name (see configs.py)")
flags.DEFINE_string("checkpoint_dir", None, "checkpoint directory (None = off)")
flags.DEFINE_string("logdir", None, "metrics/profile output directory")
flags.DEFINE_string("mesh", None, 'mesh override, e.g. "data=8,model=1"')
flags.DEFINE_string("coordinator_address", None, "host:port of process 0")
flags.DEFINE_string("platform", None,
                    "pin the jax backend (e.g. cpu for the simulated "
                    "cluster — see cli/launch.py); None = host default")
flags.DEFINE_integer("host_device_count", None,
                     "with --platform=cpu: number of virtual host devices "
                     "(multi-device configs without a pod)")
flags.DEFINE_integer("num_processes", 1, "total processes (multi-host)")
flags.DEFINE_integer("process_id", 0, "this process's index")
flags.DEFINE_boolean("profile", False, "trace a window of steps to logdir")
flags.DEFINE_string("sharding", None,
                    "sharding strategy override: dp | tp | fsdp (ZeRO-style "
                    "params+opt-state over the data axis) | fsdp_tp "
                    "(parallel/sharding.py resolve_rules; None = config)")
flags.DEFINE_string("prng_impl", None,
                    "PRNG impl override: threefry2x32 (default) | rbg "
                    "(faster dropout masks on TPU; see configs.py)")
flags.DEFINE_string("remat_policy", None,
                    "remat policy override when the config sets remat: "
                    "dots_no_batch (default) | save_attn | dots | nothing "
                    "(train/step.py REMAT_POLICIES)")
flags.DEFINE_integer("eval_every", None, "eval cadence in steps; 0 disables "
                     "(None = config value)")
flags.DEFINE_integer("log_every", None, "log/summary cadence in steps")
flags.DEFINE_enum("input_pipeline", "python",
                  ["python", "native", "device", "device_sharded"],
                  "input path: python (numpy host batcher) | native (C++ "
                  "prefetch ring) | device (dataset resident in HBM, "
                  "with-replacement sampling fused into the compiled step — "
                  "zero host work per step) | device_sharded (same, rows "
                  "sharded over the data axis for capacity)")
flags.DEFINE_integer("prefetch_depth", 2,
                     "device-prefetch ring depth for the host input paths "
                     "(python/native): a background worker issues sharded "
                     "H2D transfers this many batches ahead so the copy "
                     "overlaps the running step (data/prefetch.py); 0 = "
                     "synchronous feed")
flags.DEFINE_integer("runahead", 0,
                     "bound host dispatch runahead: wait on the k-th oldest "
                     "in-flight step before dispatching the next (caps HBM "
                     "held by undonated in-flight buffers without a "
                     "per-step sync); 0 = unbounded")
flags.DEFINE_integer("max_recoveries", 3,
                     "preemption restore attempts (needs checkpoint_dir)")
flags.DEFINE_integer("max_restore_fallbacks", 1,
                     "when the LATEST checkpoint is unreadable (truncated/"
                     "missing array files), fall back to up to this many "
                     "older steps, quarantining each bad step directory "
                     "(checkpoint/manager.py); 0 = strict, propagate the "
                     "read error")
flags.DEFINE_string("fault_plan", None,
                    "fault-injection plan: inline JSON or a path to a JSON "
                    "file (dist_mnist_tpu/faults/plan.py). Faults fire "
                    "deterministically at their configured steps; the same "
                    "plan drives launcher-level kills (cli/launch.py) and "
                    "in-process faults here")
flags.DEFINE_string("compile_cache_dir", None,
                    "warm-start cache directory (compilecache/): enables "
                    "JAX's persistent compilation cache under <dir>/xla and "
                    "an explicit serialized-AOT-executable store under "
                    "<dir>/exe, so a restarted process loads its step "
                    "programs in milliseconds instead of recompiling. "
                    "cli/launch.py --max_restarts injects a shared dir "
                    "automatically so generation N+1 warm-starts from "
                    "generation N's work; None = cold every process")
flags.DEFINE_integer("scan_chunk", 0,
                     "compile N steps into one lax.scan program (needs a "
                     "device input pipeline); hooks fire per chunk. The "
                     "bench-grade zero-dispatch path; 0 = one program per "
                     "step")
flags.DEFINE_integer("metrics_port", 0,
                     "serve /metrics (Prometheus text), /healthz (process "
                     "state machine) and /events (journal tail) on this "
                     "port from a background thread (obs/exporter.py). "
                     "Multi-process: each process binds port + process_id. "
                     "0 = disabled")
flags.DEFINE_string("journal", None,
                    "append-only JSONL run-journal path (obs/events.py) "
                    "recording run/preemption/restore/checkpoint/fault/"
                    "compile-cache lifecycle events. Defaults to "
                    "$DIST_MNIST_TPU_JOURNAL (the supervisor injects a "
                    "shared journal across restart generations), else "
                    "<logdir>/events.jsonl when --logdir is set")
flags.DEFINE_boolean("overlap", None,
                     "fsdp comm/compute overlap (parallel/overlap.py): "
                     "bucketed parameter all-gather prefetch + gradient "
                     "reduce-scatter flushed while the backward still runs. "
                     "Needs an fsdp sharding strategy; bit-identical to the "
                     "serial path (None = config value)")
flags.DEFINE_float("overlap_bucket_mb", None,
                   "overlap bucket granularity in MiB: smaller = more "
                   "chunks in flight, larger = fewer bigger transfers "
                   "(None = config value)")
flags.DEFINE_string("overlap_chunk", None,
                    "overlap chunking mode: all_gather (one collective per "
                    "bucket leaf) | ring (ppermute double-buffering, "
                    "collective_matmul-style); None = config value")
flags.DEFINE_integer("checkpoint_every_steps", 0,
                     "checkpoint cadence in STEPS (deterministic, for "
                     "fault/elastic runs where a wall-clock cadence would "
                     "make the pre-failure checkpoint timing racy); 0 = "
                     "use the config's checkpoint_every_secs")
flags.DEFINE_enum("elastic_batch_policy", None,
                  ["keep_global", "scale_lr"],
                  "global-batch policy under an elastic resize "
                  "(configs.apply_elastic_policy; None = config value)")
flags.DEFINE_integer("elastic_baseline_devices", 0,
                     "device count of the UNSHRUNKEN mesh (the elastic "
                     "supervisor injects this); with a resized mesh the "
                     "elastic_batch_policy is applied against it and the "
                     "decision is journaled. 0 = not elastic")
flags.DEFINE_integer("span_steps", 0,
                     "correlated step tracing: every N steps, journal one "
                     "`span` event per phase (input_wait / dispatch / h2d, "
                     "plus checkpoint saves) stamped with the (host, "
                     "generation, step) triple — scripts/fleet_trace.py "
                     "merges them into a chrome://tracing file with one "
                     "track per host. 0 = off")
flags.DEFINE_boolean("anomaly", False,
                     "in-loop anomaly detection (obs/anomaly.py): robust "
                     "median/MAD detectors over loss and step time journal "
                     "`anomaly` events and flip /healthz to the degraded "
                     "(200-but-flagged) state; never alters the trajectory")
flags.DEFINE_integer("anomaly_every", 25,
                     "anomaly-check cadence in steps (one loss fetch per "
                     "check — the NaNGuard sync budget)")
flags.DEFINE_boolean("async_snapshot", False,
                     "take checkpoints off the step critical path "
                     "(checkpoint/snapshot.py): the loop thread pays only a "
                     "device-side fork + queue handoff, a background writer "
                     "owns the orbax write, commit marker and peer "
                     "replication; drained durably at end/preemption")
flags.DEFINE_integer("snapshot_window", 1,
                     "bounded write-behind window for --async_snapshot: max "
                     "snapshots forked-but-not-durable at once")
flags.DEFINE_enum("snapshot_policy", "block", ["block", "drop_oldest"],
                  "what a save does when the snapshot window is full: block "
                  "(attributed save_stall) or drop the oldest queued "
                  "snapshot")
flags.DEFINE_enum("tuned", "auto", ["auto", "off", "require"],
                  "persisted-autotuner knobs (dist_mnist_tpu/tune): auto = "
                  "apply the TunedConfigStore winners for this exact "
                  "model/mesh/backend/jax-version geometry when an entry "
                  "exists (journaled as tuning/applied with the measured "
                  "evidence), fall back to defaults on a miss; require = "
                  "fail fast on a miss; off = never consult the store "
                  "(bit-identical to pre-tuner behavior). Explicit knob "
                  "flags (--overlap_bucket_mb etc.) always win over stored "
                  "values. See docs/TUNING.md")
flags.DEFINE_string("tuned_dir", None,
                    "TunedConfigStore directory (cli/tune.py writes it); "
                    "defaults to $DIST_MNIST_TPU_TUNED_DIR, and with "
                    "neither set --tuned=auto is a no-op")
flags.DEFINE_string("peer_dir", None,
                    "peer-ring shard redundancy root (checkpoint/peer.py): "
                    "each host serializes its shards to its own dir AND its "
                    "ring neighbor's, and restore assembles from surviving "
                    "peers before falling back to the checkpoint store. "
                    "Implies the async snapshot path. None = off")


def build_optimizer(cfg):
    from dist_mnist_tpu import optim

    aggregate = max(1, cfg.replicas_to_aggregate or 1)
    if cfg.lr_schedule == "cosine":
        # the schedule is driven by the inner optimizer's UPDATE count, which
        # advances once per `aggregate` loop steps — scale the horizon so the
        # decay completes over cfg.train_steps loop steps
        lr = optim.schedules.cosine_decay(
            cfg.learning_rate,
            max(1, cfg.train_steps // aggregate),
            max(0, cfg.warmup_steps // aggregate),
        )
    else:
        lr = cfg.learning_rate
    if cfg.optimizer == "adam" and cfg.weight_decay:
        base = optim.adamw(lr, weight_decay=cfg.weight_decay)
        wd_handled = True
    else:
        base = {
            "adam": lambda: optim.adam(lr),
            "sgd": lambda: optim.sgd(lr),
            "momentum": lambda: optim.momentum(lr, 0.9),
        }[cfg.optimizer]()
        wd_handled = False
    parts = []
    if cfg.grad_clip_norm:
        parts.append(optim.clip_by_global_norm(cfg.grad_clip_norm))
    if cfg.weight_decay and not wd_handled:
        parts.append(optim.add_decayed_weights(cfg.weight_decay))
    parts.append(base)
    opt = optim.chain(*parts) if len(parts) > 1 else base
    if aggregate > 1:
        opt = optim.gradient_accumulation(opt, aggregate)
    return opt


# compile_cache_key_fields moved to compilecache/key_fields.py (import-pure:
# serve and the tuner hash the same geometry fields, and importing this
# module from another absl CLI would re-run the flags.DEFINE_* block).
# Re-exported here so every existing `from ...cli.train import
# compile_cache_key_fields` keeps working.
from dist_mnist_tpu.compilecache.key_fields import (  # noqa: E402
    compile_cache_key_fields,
)


def run_config(cfg, **kwargs):
    """Public driver entrypoint (tests/bench call this; main() parses
    flags) — see `_run_config` for the full signature. This thin wrapper
    scopes `cfg.prng_impl` around the whole run; why and the checkpoint
    caveat live on utils/prng.prng_impl_scope."""
    from dist_mnist_tpu.utils.prng import prng_impl_scope

    with prng_impl_scope(cfg.prng_impl):
        return _run_config(cfg, **kwargs)


def _run_config(
    cfg,
    *,
    data_dir: str = "/tmp/mnist-data",
    checkpoint_dir: str | None = None,
    logdir: str | None = None,
    profile: bool = False,
    max_recoveries: int = 0,
    extra_hooks=(),
    mesh=None,
    input_pipeline: str = "python",
    scan_chunk: int = 0,
    prefetch_depth: int = 0,
    runahead: int = 0,
    fault_plan=None,
    preemption=None,
    max_restore_fallbacks: int = 1,
    compile_cache_dir: str | None = None,
    startup=None,
    metrics_port: int = 0,
    journal=None,
    generation: int = 0,
    checkpoint_every_steps: int = 0,
    elastic_baseline_devices: int = 0,
    span_steps: int = 0,
    anomaly: bool = False,
    anomaly_every: int = 25,
    async_snapshot: bool = False,
    snapshot_window: int = 1,
    snapshot_policy: str = "block",
    peer_dir: str | None = None,
    tuned: str = "auto",
    tuned_dir: str | None = None,
    tuned_protect=(),
):
    """Implementation behind `run_config` (the public wrapper adds the
    PRNG-impl scope — call THAT, not this).

    Sets up the observability spine around the run — metric registry,
    /metrics + /healthz exporter (`metrics_port`), and run journal
    (`journal` accepts a path or an obs.RunJournal; defaults to
    <logdir>/events.jsonl) — then delegates to `_run_train`.

    Returns (final_state, final_eval_dict, context) where context carries
    the mesh/model/registry/health/etc. for callers that keep going.
    """
    from pathlib import Path

    from dist_mnist_tpu.obs import (
        HealthState,
        MetricRegistry,
        MetricsExporter,
        RunJournal,
    )
    from dist_mnist_tpu.obs import events as events_mod

    registry = MetricRegistry()
    health = HealthState(generation=generation)
    journal_obj, journal_owned = None, False
    if isinstance(journal, RunJournal):
        journal_obj = journal
    elif journal:
        journal_obj, journal_owned = (
            RunJournal(journal, generation=generation), True)
    elif logdir:
        journal_obj, journal_owned = (
            RunJournal(Path(logdir) / "events.jsonl",
                       generation=generation), True)
    prev_journal = (events_mod.set_journal(journal_obj)
                    if journal_obj is not None else None)
    exporter = None
    if metrics_port:
        import os as _os

        # identity labels: merged fleet series stay attributable to this
        # process (host id is the supervisor-injected stable id; plain
        # single-host runs are host 0)
        proc_info = {
            "host_id": _os.environ.get(events_mod.ENV_HOST_ID, "0"),
            "generation": str(generation),
            "role": "train",
        }
        try:
            exporter = MetricsExporter(
                registry, health=health,
                journal_path=journal_obj.path if journal_obj else None,
                port=metrics_port,
                info=proc_info,
            ).start()
        except OSError as e:
            # exposition is an aid; a taken port must not kill training
            log.warning("metrics exporter: could not bind port %d (%s); "
                        "continuing without exposition", metrics_port, e)
    events_mod.emit("run_start", config=cfg.name,
                    train_steps=cfg.train_steps)
    try:
        state, final, ctx = _run_train(
            cfg, data_dir=data_dir, checkpoint_dir=checkpoint_dir,
            logdir=logdir, profile=profile, max_recoveries=max_recoveries,
            extra_hooks=extra_hooks, mesh=mesh,
            input_pipeline=input_pipeline, scan_chunk=scan_chunk,
            prefetch_depth=prefetch_depth, runahead=runahead,
            fault_plan=fault_plan, preemption=preemption,
            max_restore_fallbacks=max_restore_fallbacks,
            compile_cache_dir=compile_cache_dir, startup=startup,
            registry=registry, health=health,
            checkpoint_every_steps=checkpoint_every_steps,
            elastic_baseline_devices=elastic_baseline_devices,
            span_steps=span_steps, anomaly=anomaly,
            anomaly_every=anomaly_every,
            async_snapshot=async_snapshot,
            snapshot_window=snapshot_window,
            snapshot_policy=snapshot_policy,
            peer_dir=peer_dir,
            tuned=tuned, tuned_dir=tuned_dir, tuned_protect=tuned_protect,
        )
        import jax as _jax

        # process/world/goodput on the success record: the supervisor-level
        # elastic ledger (faults.goodput.elastic_summary) sums the CHIEF's
        # per-generation productive seconds from exactly these fields
        events_mod.emit("run_stop", ok=True, step=state.step_int,
                        preempted_at=ctx.get("preempted_at"),
                        reason=ctx["loop"].stop.reason,
                        process=_jax.process_index(),
                        world=_jax.process_count(),
                        devices=_jax.device_count(),
                        goodput={
                            k: (round(v, 6) if isinstance(v, float) else v)
                            for k, v in ctx["loop"].goodput.snapshot().items()
                        })
        ctx.update(
            registry=registry, health=health,
            journal=journal_obj.path if journal_obj else None,
            metrics_url=exporter.url() if exporter else None,
        )
        return state, final, ctx
    except BaseException as exc:
        events_mod.emit("run_stop", ok=False, error=type(exc).__name__)
        if health.state != "preempted":
            health.set("failed", type(exc).__name__)
        raise
    finally:
        if exporter is not None:
            exporter.close()
        if journal_obj is not None:
            events_mod.set_journal(prev_journal)
            if journal_owned:
                journal_obj.close()


def _run_train(
    cfg,
    *,
    data_dir: str = "/tmp/mnist-data",
    checkpoint_dir: str | None = None,
    logdir: str | None = None,
    profile: bool = False,
    max_recoveries: int = 0,
    extra_hooks=(),
    mesh=None,
    input_pipeline: str = "python",
    scan_chunk: int = 0,
    prefetch_depth: int = 0,
    runahead: int = 0,
    fault_plan=None,
    preemption=None,
    max_restore_fallbacks: int = 1,
    compile_cache_dir: str | None = None,
    startup=None,
    registry=None,
    health=None,
    checkpoint_every_steps: int = 0,
    elastic_baseline_devices: int = 0,
    span_steps: int = 0,
    anomaly: bool = False,
    anomaly_every: int = 25,
    async_snapshot: bool = False,
    snapshot_window: int = 1,
    snapshot_policy: str = "block",
    peer_dir: str | None = None,
    tuned: str = "auto",
    tuned_dir: str | None = None,
    tuned_protect=(),
):
    """The training run itself (see `_run_config`, which wraps it in the
    observability scope and owns the exporter/journal lifecycles)."""
    import jax

    from dist_mnist_tpu import hooks as hooks_lib
    from dist_mnist_tpu.checkpoint import CheckpointManager
    from dist_mnist_tpu.cluster import make_mesh, is_chief
    from dist_mnist_tpu.cluster.mesh import activate
    from dist_mnist_tpu.data import load_dataset, ShardedBatcher
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.obs import make_default_writer
    from dist_mnist_tpu.ops import losses
    from dist_mnist_tpu.parallel.sharding import (
        resolve_rules,
        shard_train_state,
    )
    from dist_mnist_tpu.train import (
        TrainLoop,
        create_train_state,
        evaluate,
        make_eval_step,
        make_train_step,
    )

    from dist_mnist_tpu.compilecache import (
        ExecutableStore,
        StartupClock,
        StartupHook,
        cache_key,
        enable_persistent_cache,
    )

    t0 = time.monotonic()
    startup = startup if startup is not None else StartupClock(t0=t0)
    # flag-combination errors fail BEFORE any expensive work (dataset load,
    # init, restore) — decidable from the arguments alone
    if scan_chunk and not input_pipeline.startswith("device"):
        raise ValueError(
            "--scan_chunk needs an in-program input path "
            "(--input_pipeline=device|device_sharded): a host batcher "
            "cannot feed a compiled multi-step scan"
        )
    rules = resolve_rules(cfg.sharding_rules)
    overlap_cfg = None
    if cfg.overlap:
        from dist_mnist_tpu.parallel.overlap import OverlapConfig

        if rules.fsdp_axis is None:
            raise ValueError(
                f"--overlap needs an fsdp sharding strategy (got "
                f"{cfg.sharding_rules!r}): there are no parameter shards "
                f"to gather — use --sharding=fsdp or fsdp_tp"
            )
        overlap_cfg = OverlapConfig(bucket_mb=cfg.overlap_bucket_mb,
                                    chunk=cfg.overlap_chunk)
    if scan_chunk and cfg.train_steps % scan_chunk:
        stop_at = -(-cfg.train_steps // scan_chunk) * scan_chunk
        log.warning(
            "train_steps=%d is not a multiple of scan_chunk=%d: the "
            "loop stops at the chunk boundary, step %d (%d extra "
            "steps, past the LR schedule horizon)",
            cfg.train_steps, scan_chunk, stop_at,
            stop_at - cfg.train_steps,
        )
    with startup.phase("init"):
        mesh = mesh if mesh is not None else make_mesh(cfg.mesh)
        if elastic_baseline_devices:
            # resized-mesh batch/LR policy, resolved BEFORE the optimizer
            # is built so the decision lives in the config this run logs
            from dist_mnist_tpu.configs import apply_elastic_policy
            from dist_mnist_tpu.obs import events as _events

            n_dev = int(mesh.devices.size)
            cfg = apply_elastic_policy(cfg, elastic_baseline_devices, n_dev)
            _events.emit(
                "elastic_policy", policy=cfg.elastic_batch_policy,
                baseline_devices=elastic_baseline_devices, devices=n_dev,
                batch_size=cfg.batch_size, learning_rate=cfg.learning_rate,
            )
            if n_dev != elastic_baseline_devices:
                log.info(
                    "elastic mesh: %d devices (baseline %d); policy=%s -> "
                    "global batch %d, lr %g",
                    n_dev, elastic_baseline_devices,
                    cfg.elastic_batch_policy, cfg.batch_size,
                    cfg.learning_rate,
                )
        if tuned != "off":
            # persisted-autotuner lookup (dist_mnist_tpu/tune): keyed over
            # the FINAL geometry (post-elastic-policy, live mesh), before
            # anything expensive — a --tuned=require miss fails here, and
            # an applied overlap knob lands before the key fields and the
            # overlap schedule below consume cfg. --tuned=off never
            # reaches this import: bit-identical to the pre-tuner path.
            from dist_mnist_tpu.tune import apply_tuned

            cfg, _tuned_runtime = apply_tuned(
                cfg, mesh, mode=tuned, store_dir=tuned_dir,
                protect=tuple(tuned_protect), subsystem="train")
            if overlap_cfg is not None:
                from dist_mnist_tpu.parallel.overlap import OverlapConfig

                overlap_cfg = OverlapConfig(bucket_mb=cfg.overlap_bucket_mb,
                                            chunk=cfg.overlap_chunk)
            if "prefetch_depth" in _tuned_runtime:
                prefetch_depth = int(_tuned_runtime["prefetch_depth"])
        dataset = load_dataset(cfg.dataset, data_dir, seed=cfg.seed)
        model = get_model(cfg.model, **cfg.model_kwargs)
        optimizer = build_optimizer(cfg)
    loss_fn = (
        losses.clipped_softmax_cross_entropy
        if cfg.loss == "clipped"
        else losses.softmax_cross_entropy
    )

    # warm-start tiers (compilecache/): the XLA persistent cache catches
    # every jit transparently; the ExecutableStore skips lowering too by
    # deserializing whole AOT step programs under an explicit key
    store = None
    step_key = lambda kind: None  # noqa: E731 — keyed only when caching
    if compile_cache_dir:
        from pathlib import Path

        if jax.process_count() > 1 and jax.default_backend() == "cpu":
            # a serialized multi-process CPU executable (either tier: the
            # ExecutableStore AOT blob or the XLA persistent cache entry)
            # embeds gloo communicator state from the incarnation that
            # compiled it; deserializing it under a re-formed coordination
            # service (restart/resize generation) corrupts the heap inside
            # the first steps. Degrade the whole warm-start tier to a
            # plain compile — correctness over cold-start here.
            log.info(
                "compile cache: disabled for multi-process cpu (serialized "
                "collective state does not survive a new distributed "
                "runtime incarnation)")
        else:
            cache_root = Path(compile_cache_dir)
            enable_persistent_cache(cache_root / "xla")
            store = ExecutableStore(cache_root / "exe")
            key_fields = compile_cache_key_fields(
                cfg, mesh, scan_chunk=scan_chunk,
                input_pipeline=input_pipeline)
            step_key = lambda kind: cache_key({"kind": kind, **key_fields})  # noqa: E731

    rng = jax.random.PRNGKey(cfg.seed)
    sample = dataset.train_images[:1]
    # activate (not plain `with mesh:`) so mesh-adaptive attention
    # (ring/ulysses discover the seq axis via the ABSTRACT mesh) engages
    with activate(mesh):
        with startup.phase("init"):
            state = create_train_state(model, optimizer, rng, sample)
            state = shard_train_state(state, mesh, rules)

        manager = None
        restored = False
        if checkpoint_dir:
            # --peer_dir implies the async snapshot path: peer replication
            # runs on the snapshot writer thread. The inner orbax manager
            # goes SYNC under the snapshotter — asyncness is owned by the
            # write-behind layer, and a sync inner write lets the commit
            # marker land in the same writer pass.
            wrap_async = bool(async_snapshot or peer_dir)
            manager = CheckpointManager(
                checkpoint_dir, async_save=not wrap_async,
                max_restore_fallbacks=max_restore_fallbacks,
            )
            if fault_plan is not None:
                # wrap BEFORE the startup restore so a corrupt fault
                # targeting a pre-existing step fires on restore_or_init too
                manager = fault_plan.wrap_checkpoint_manager(manager)
            if wrap_async:
                import os as _os

                from dist_mnist_tpu.checkpoint import (
                    AsyncSnapshotter,
                    PeerReplicator,
                )

                peer = None
                if peer_dir:
                    from dist_mnist_tpu.checkpoint.peer import (
                        alive_hosts_from_env,
                    )
                    from dist_mnist_tpu.cluster.membership import ENV_HOST_ID

                    host_id = int(_os.environ.get(
                        ENV_HOST_ID, jax.process_index()))
                    hosts = alive_hosts_from_env(
                        default=list(range(jax.process_count())))
                    peer = PeerReplicator(peer_dir, host_id, hosts)
                manager = AsyncSnapshotter(
                    manager, window=snapshot_window,
                    policy=snapshot_policy, peer=peer,
                )
            with startup.phase("restore"):
                state, restored = manager.restore_or_init(state)
        log.info(
            "config %s: model=%s params on %d devices, restored=%s",
            cfg.name, cfg.model, jax.device_count(), restored,
        )

        if input_pipeline.startswith("device"):
            # input fused into the program (train/step.py): the dataset
            # lives in HBM and each step samples on-device — no feed at
            # all. Resume-exact for free: sampling is a pure function of
            # (state.rng, state.step). Semantics: with-replacement draws
            # (vs the host paths' shuffled epochs) — documented trade.
            from dist_mnist_tpu.data import DeviceDataset
            from dist_mnist_tpu.train.step import (
                make_fused_train_step,
                make_scanned_train_fn,
            )

            dd = DeviceDataset(dataset, mesh,
                               shard=input_pipeline == "device_sharded",
                               seed=cfg.seed)
            if scan_chunk:
                run = make_scanned_train_fn(
                    model, optimizer, mesh, dd, cfg.batch_size, scan_chunk,
                    loss_fn=loss_fn, rules=rules, remat=cfg.remat,
                    augment=cfg.augment, remat_policy=cfg.remat_policy,
                    overlap=overlap_cfg,
                    store=store, cache_key=step_key("scan"),
                )
            else:
                run = make_fused_train_step(
                    model, optimizer, mesh, dd, cfg.batch_size,
                    loss_fn=loss_fn, rules=rules, remat=cfg.remat,
                    augment=cfg.augment, remat_policy=cfg.remat_policy,
                    overlap=overlap_cfg,
                    store=store, cache_key=step_key("fused"),
                )
            step_fn = lambda state, _batch: run(state)
            # surface the wrapper's compile/load attribution through the
            # adapter so the loop's goodput drain still sees it
            step_fn.consume_compile_s = run.consume_compile_s
        else:
            step_fn = make_train_step(model, optimizer, mesh, loss_fn=loss_fn,
                                      rules=rules, remat=cfg.remat,
                                      augment=cfg.augment,
                                      remat_policy=cfg.remat_policy,
                                      overlap=overlap_cfg,
                                      store=store, cache_key=step_key("train"))
        eval_step = make_eval_step(model, mesh, store=store,
                                   cache_key=step_key("eval"))
        eval_fn = lambda s: evaluate(
            eval_step, s, dataset.test_images, dataset.test_labels, mesh
        )

        writer = make_default_writer(logdir, chief=is_chief(),
                                     registry=registry)
        hooks = [
            hooks_lib.StopAtStepHook(last_step=cfg.train_steps),
            hooks_lib.StepCounterHook(
                every_steps=cfg.log_every, batch_size=cfg.batch_size, writer=writer
            ),
            hooks_lib.InputPipelineHook(writer, every_steps=cfg.log_every),
            hooks_lib.StepTimeHook(writer, every_steps=cfg.log_every),
            hooks_lib.LoggingHook(every_steps=cfg.log_every),
            hooks_lib.SummaryHook(writer, every_steps=cfg.log_every),
            hooks_lib.MemoryHook(writer, every_steps=cfg.log_every),
            hooks_lib.NaNGuardHook(),
        ]
        if anomaly:
            from dist_mnist_tpu.obs.anomaly import AnomalyHook

            # read-only by construction (docs/OBSERVABILITY.md "Fleet
            # view"): journals anomalies and shades /healthz to degraded,
            # trajectory stays bit-identical (bench.py --faults pins it)
            hooks.append(AnomalyHook(every_steps=anomaly_every,
                                     health=health))
        if overlap_cfg is not None:
            from dist_mnist_tpu.parallel.overlap import plan_stats

            hooks.append(hooks_lib.OverlapHook(
                writer,
                plan_stats(state.params, mesh, rules, overlap_cfg)))
        from dist_mnist_tpu.faults.goodput import GoodputHook

        goodput_hook = GoodputHook(writer, every_steps=cfg.log_every)
        hooks.append(goodput_hook)
        startup_hook = StartupHook(writer, startup, store=store)
        hooks.append(startup_hook)
        if fault_plan is not None:
            hooks.append(fault_plan.hook())
        eval_hook = None
        if cfg.eval_every:
            eval_hook = hooks_lib.EvalHook(eval_fn, every_steps=cfg.eval_every,
                                           writer=writer)
            hooks.append(eval_hook)
        if manager:
            hooks.append(
                hooks_lib.CheckpointHook(
                    manager, every_steps=checkpoint_every_steps
                )
                if checkpoint_every_steps
                else hooks_lib.CheckpointHook(
                    manager, every_secs=cfg.checkpoint_every_secs
                )
            )
        if profile and logdir:
            hooks.append(hooks_lib.ProfilerHook(logdir))
            hooks.append(hooks_lib.MemoryProfileHook(logdir))
        hooks.extend(extra_hooks)

        # resume-aware: start the stream at the restored step so the
        # post-restore trajectory equals the uninterrupted one (the
        # reference replayed the epoch from scratch — next_batch state died
        # with the process, SURVEY.md §3.5)
        if input_pipeline.startswith("device"):
            import itertools

            batches = itertools.repeat(None)  # sampling lives in the step
        elif input_pipeline == "native":
            from dist_mnist_tpu.data.native import NativeBatcher

            batches = NativeBatcher(dataset, cfg.batch_size, mesh,
                                    seed=cfg.seed,
                                    start_step=state.step_int)
        else:
            batches = ShardedBatcher(dataset, cfg.batch_size, mesh,
                                     seed=cfg.seed,
                                     start_step=state.step_int)
        if prefetch_depth and not input_pipeline.startswith("device"):
            # overlap H2D with the running step; the device pipelines have
            # no feed to overlap (sampling is inside the compiled step)
            from dist_mnist_tpu.data.prefetch import DevicePrefetcher

            batches = DevicePrefetcher(batches, depth=prefetch_depth)
        if fault_plan is not None:
            # outermost wrapper: an injected stall lands in the loop's feed
            # wait (goodput stall bucket), like any real input outage
            batches = fault_plan.wrap_batches(batches)
        loop = TrainLoop(
            step_fn,
            state,
            batches,
            hooks,
            checkpoint_manager=manager,
            max_recoveries=max_recoveries,
            steps_per_call=max(1, scan_chunk),
            runahead=runahead,
            preemption=preemption,
            health=health,
            span_steps=span_steps,
        )
        if registry is not None:
            # live full-distribution exposition of per-step wall time
            registry.attach_histogram("train/step_time_ms",
                                      loop.step_time_hist)
        state = loop.run()
        # EvalHook.end already evaluated the final state; don't pay for a
        # second full test-set pass
        final = eval_hook.last_result if eval_hook else eval_fn(state)
    elapsed = time.monotonic() - t0
    log.info(
        "done: step=%d test_acc=%.4f test_loss=%.4f wall=%.1fs",
        state.step_int, final["accuracy"], final["loss"], elapsed,
    )
    writer.flush()
    if manager:
        manager.close()
    return state, final, {"mesh": mesh, "model": model, "elapsed": elapsed,
                          "dataset": dataset, "loop": loop,
                          "goodput": goodput_hook.last,
                          "startup": startup_hook.last,
                          "compile_cache": store.stats() if store else None,
                          "preempted_at": loop.preempted_at}


def _apply_flag_overrides(cfg):
    over = {}
    if FLAGS.train_steps is not None:
        over["train_steps"] = FLAGS.train_steps
    if FLAGS.batch_size is not None:
        over["batch_size"] = FLAGS.batch_size
    if FLAGS.learning_rate is not None:
        over["learning_rate"] = FLAGS.learning_rate
    if FLAGS.replicas_to_aggregate is not None:
        over["replicas_to_aggregate"] = FLAGS.replicas_to_aggregate
    if FLAGS.eval_every is not None:
        over["eval_every"] = FLAGS.eval_every
    if FLAGS.log_every is not None:
        over["log_every"] = FLAGS.log_every
    if FLAGS.hidden_units is not None:
        over["model_kwargs"] = {**cfg.model_kwargs,
                                "hidden_units": FLAGS.hidden_units}
    if FLAGS.mesh:
        from dist_mnist_tpu.cluster.mesh import MeshSpec

        kv = dict(part.split("=") for part in FLAGS.mesh.split(","))
        over["mesh"] = MeshSpec(**{k: int(v) for k, v in kv.items()})
    if FLAGS.prng_impl:
        over["prng_impl"] = FLAGS.prng_impl
    if FLAGS.elastic_batch_policy:
        over["elastic_batch_policy"] = FLAGS.elastic_batch_policy
    if FLAGS.sharding:
        # validate EAGERLY (same rationale as remat_policy below): a typo'd
        # strategy must fail here, not silently train under the config's
        from dist_mnist_tpu.parallel.sharding import resolve_rules

        resolve_rules(FLAGS.sharding)
        over["sharding_rules"] = FLAGS.sharding
    if FLAGS.remat_policy:
        # validate EAGERLY: resolve_remat_policy otherwise only runs when
        # remat=True, so a typo'd policy on a non-remat config would pass
        # silently and the user would believe it was applied
        from dist_mnist_tpu.train.step import resolve_remat_policy

        resolve_remat_policy(FLAGS.remat_policy)
        over["remat_policy"] = FLAGS.remat_policy
    if FLAGS.overlap is not None:
        over["overlap"] = FLAGS.overlap
    if FLAGS.overlap_bucket_mb is not None:
        over["overlap_bucket_mb"] = FLAGS.overlap_bucket_mb
    if FLAGS.overlap_chunk is not None:
        over["overlap_chunk"] = FLAGS.overlap_chunk
    if over.get("overlap", cfg.overlap) or FLAGS.overlap_chunk \
            or FLAGS.overlap_bucket_mb is not None:
        # validate EAGERLY (same rationale as sharding/remat_policy): a
        # typo'd chunk mode or negative bucket must fail at flag-parse
        # depth, not deep inside step construction
        from dist_mnist_tpu.parallel.overlap import OverlapConfig

        OverlapConfig(
            bucket_mb=over.get("overlap_bucket_mb", cfg.overlap_bucket_mb),
            chunk=over.get("overlap_chunk", cfg.overlap_chunk))
    return dataclasses.replace(cfg, **over) if over else cfg


def main(argv):
    del argv
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s",
    )
    # orbax/absl INFO is extremely chatty (dozens of lines per save);
    # keep the console to this framework's own logs
    logging.getLogger("absl").setLevel(logging.WARNING)
    for name in ("job_name", "ps_hosts", "worker_hosts"):
        if getattr(FLAGS, name):
            log.warning(
                "--%s is a parameter-server-era flag; this framework runs one "
                "SPMD program over a device mesh (no ps/worker jobs). "
                "Multi-host: --coordinator_address/--num_processes/--process_id.",
                name,
            )
    if not FLAGS.sync_replicas:
        log.warning(
            "--nosync_replicas requested: async parameter-server training is "
            "architecturally out-of-model for SPMD (SURVEY.md §2.6); training "
            "proceeds synchronously. See parallel/ps_demo for the protocol demo."
        )

    from dist_mnist_tpu.cluster import initialize_distributed
    from dist_mnist_tpu.compilecache import StartupClock
    from dist_mnist_tpu.configs import get_config
    from dist_mnist_tpu.data import load_dataset
    from dist_mnist_tpu.faults import (
        FaultPlan,
        PreemptionNotice,
        install_preemption_handlers,
    )

    # cold-start attribution anchored at this module's import: everything
    # up to here is the ``import`` bucket, the distributed/backend bring-up
    # below is ``init``, and _run_config fills in the rest
    clock = StartupClock(t0=_MODULE_T0)
    clock.note("import", time.monotonic() - _MODULE_T0)

    # handshake installed BEFORE the expensive jax/distributed bring-up: a
    # SIGTERM that lands during init is honored at the first step boundary
    notice = PreemptionNotice()
    uninstall = install_preemption_handlers(notice)
    with clock.phase("init"):
        initialize_distributed(
            FLAGS.coordinator_address, FLAGS.num_processes, FLAGS.process_id,
            platform=FLAGS.platform, host_device_count=FLAGS.host_device_count,
        )
    cfg = _apply_flag_overrides(get_config(FLAGS.config))
    if FLAGS.download_only:
        ds = load_dataset(cfg.dataset, FLAGS.data_dir, seed=cfg.seed)
        log.info("dataset %s ready (%d train / %d test, synthetic=%s)",
                 ds.name, len(ds.train_labels), len(ds.test_labels), ds.synthetic)
        return
    plan = FaultPlan.from_spec(FLAGS.fault_plan) if FLAGS.fault_plan else None
    import os

    from dist_mnist_tpu.obs import events as events_mod

    # journal precedence: explicit flag > supervisor-injected env (one
    # journal shared across restart generations) > <logdir>/events.jsonl
    journal = FLAGS.journal or os.environ.get(events_mod.ENV_JOURNAL)
    generation = int(os.environ.get(events_mod.ENV_GENERATION, "0"))
    # one exporter per process: offset by process_id so a multi-process
    # host doesn't race for one port
    metrics_port = (FLAGS.metrics_port + FLAGS.process_id
                    if FLAGS.metrics_port else 0)
    try:
        _state, _final, ctx = run_config(
            cfg,
            data_dir=FLAGS.data_dir,
            checkpoint_dir=FLAGS.checkpoint_dir,
            logdir=FLAGS.logdir,
            profile=FLAGS.profile,
            max_recoveries=FLAGS.max_recoveries if FLAGS.checkpoint_dir else 0,
            input_pipeline=FLAGS.input_pipeline,
            scan_chunk=FLAGS.scan_chunk,
            prefetch_depth=FLAGS.prefetch_depth,
            runahead=FLAGS.runahead,
            fault_plan=plan,
            preemption=notice,
            max_restore_fallbacks=FLAGS.max_restore_fallbacks,
            compile_cache_dir=FLAGS.compile_cache_dir,
            startup=clock,
            metrics_port=metrics_port,
            journal=journal,
            generation=generation,
            checkpoint_every_steps=FLAGS.checkpoint_every_steps,
            elastic_baseline_devices=FLAGS.elastic_baseline_devices,
            span_steps=FLAGS.span_steps,
            anomaly=FLAGS.anomaly,
            anomaly_every=FLAGS.anomaly_every,
            async_snapshot=FLAGS.async_snapshot,
            snapshot_window=FLAGS.snapshot_window,
            snapshot_policy=FLAGS.snapshot_policy,
            peer_dir=FLAGS.peer_dir,
            tuned=FLAGS.tuned,
            tuned_dir=FLAGS.tuned_dir,
            # explicitly-flagged knobs outrank stored winners: the
            # operator pinned them, the tuner must not clobber them
            tuned_protect=tuple(
                name for name, pinned in (
                    ("overlap_bucket_mb", FLAGS.overlap_bucket_mb is not None),
                    ("overlap_chunk", FLAGS.overlap_chunk is not None),
                    ("prefetch_depth", FLAGS["prefetch_depth"].present),
                ) if pinned),
        )
    finally:
        uninstall()
    if ctx.get("preempted_at") is not None:
        # the marker line supervisors/tests key on; exit code stays 0 — a
        # preempted-but-checkpointed run is a SUCCESS to any scheduler
        log.warning("preempted@step=%d — checkpoint saved, clean shutdown",
                    ctx["preempted_at"])


if __name__ == "__main__":
    app.run(main)
