"""Local multi-process cluster launcher.

The reference was launched as K separate shell invocations of
`dist_mnist.py --job_name={ps,worker} --task_index=i` against hand-written
--ps_hosts/--worker_hosts lists (SURVEY.md §0.1; the repo's README/launch
helpers). This is the one-command replacement: it spawns N identical SPMD
processes of `cli.train`, wires them to one coordination service
(`jax.distributed`, the TSL descendant of the reference's GrpcServer —
grpc_server_lib.h:78-239), streams their interleaved logs with a `[pK]`
prefix, and propagates the first failure by tearing the rest down — the
job-level behavior the reference delegated to "run these commands in K
terminals".

There is no ps/worker asymmetry to configure: every process runs the same
program, and process 0 is chief by convention (cluster/coordination.py).

`--platform=cpu --devices_per_process=M` simulates an N-host, N*M-device
cluster on one machine with no accelerator (gloo collectives) — the
process-level analogue of the reference's `create_local_cluster` test
fixture (test_util.py:4029-4115), with real process isolation instead of
in-process servers.

Usage:
    python -m dist_mnist_tpu.cli.launch --num_processes=2 -- \
        --config=lenet5_mnist --train_steps=500
"""

from __future__ import annotations

import os
import random
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path

from absl import app, flags

# cli.train owns the shared flag namespace (--num_processes, --platform, …);
# importing it first makes flag definitions order-independent for every
# import order the package sees (its module top is cheap — stdlib + absl)
import dist_mnist_tpu.cli.train  # noqa: F401
# stdlib-only (cluster/__init__ resolves lazily, so no jax import here)
from dist_mnist_tpu.cluster.membership import (
    ENV_ALIVE_HOSTS,
    ENV_HOST_ID,
    Membership,
)

FLAGS = flags.FLAGS

flags.DEFINE_integer("port", 0, "coordinator port (0 = pick a free one)")
flags.DEFINE_integer("devices_per_process", 1,
                     "virtual devices per process (cpu platform only)")
flags.DEFINE_integer("max_restarts", 0,
                     "supervisor mode: on an abnormal NON-CHIEF process "
                     "death, restart the cluster (children resume from the "
                     "checkpoint) with exponential backoff + jitter, up to "
                     "this many times; 0 = fail fast (legacy behavior)")
flags.DEFINE_float("restart_backoff_s", 1.0,
                   "supervisor restart backoff base: attempt k sleeps "
                   "base * 2^k * (1 + jitter)")
flags.DEFINE_boolean("elastic", False,
                     "shrink-to-survive supervisor: an abnormal non-chief "
                     "death excludes that host and re-forms the cluster at "
                     "the surviving world size (no backoff) instead of "
                     "restarting the full world; recovered hosts grow the "
                     "mesh back at the next generation boundary "
                     "(docs/RESILIENCE.md 'Elastic generations')")
flags.DEFINE_integer("min_processes", 1,
                     "elastic mode: smallest world size worth forming; a "
                     "shrink below this is fatal")
flags.DEFINE_float("regrow_after_s", 0.0,
                   "elastic mode: re-admit an UNATTRIBUTED lost host this "
                   "many seconds after its failure (0 = only hosts with a "
                   "planned kill_host recovery ever come back)")
flags.DEFINE_integer("supervisor_port", None,
                     "elastic mode: serve the SUPERVISOR's own "
                     "/healthz+/metrics+/events on this port (0 = pick a "
                     "free one); reports `resizing` (503) during mesh "
                     "re-formation. Unset = no supervisor endpoint")

#: children of the CURRENT cluster generation — the conftest leak check
#: asserts this is empty of live processes after every test.
_LIVE_CHILDREN: list = []


_PORT_LOCK_DIR = Path(tempfile.gettempdir()) / "dist_mnist_tpu_ports"
_PORT_LOCK_STALE_SECS = 3600.0


def _reserve_port() -> tuple[int, socket.socket, Path]:
    """Pick a free port with a cross-process reservation.

    The gap between this pick and the child coordinator's bind is SECONDS
    wide (children pay the jax import first), so an OS-level free-port probe
    alone is a race. Two layers close it against the realistic contender —
    other launch() invocations on this machine (parallel pytest, CI shards):

    1. the probe socket stays open until the children are spawned, so
       concurrent pickers can't be handed the same port by the OS;
    2. an O_EXCL lockfile keyed by port number covers the
       probe-closed -> child-bound window; it is held until the cluster
       exits. Stale locks (launcher SIGKILLed) expire after an hour.

    Unrelated third-party processes binding random ports in that window
    remain theoretically possible — children then fail to handshake and the
    launcher reports it (no silent cross-wiring: the coordinator checks
    num_processes/process_id consistency).

    The bind itself retries: `bind(("localhost", 0))` can fail with
    EADDRINUSE/EADDRNOTAVAIL under ephemeral-port exhaustion (an elastic
    supervisor re-reserves a fresh port every generation, and parallel CI
    shards multiply that), and one transient bind failure must not kill a
    whole generation launch. Bounded so a genuinely exhausted/denied
    network namespace still surfaces as the OS error, not a hang.
    """
    _PORT_LOCK_DIR.mkdir(exist_ok=True)
    now = time.time()
    for stale in _PORT_LOCK_DIR.iterdir():
        try:
            if now - stale.stat().st_mtime > _PORT_LOCK_STALE_SECS:
                stale.unlink()
        except OSError:
            pass
    last_err: OSError | None = None
    for _ in range(32):
        s = socket.socket()
        try:
            s.bind(("localhost", 0))
        except OSError as e:
            s.close()
            last_err = e
            continue  # transient EADDRINUSE etc.: fresh socket, fresh pick
        port = s.getsockname()[1]
        lock = _PORT_LOCK_DIR / str(port)
        try:
            os.close(os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
            return port, s, lock
        except FileExistsError:
            s.close()  # reserved by a concurrent launcher; try another
    raise OSError(
        f"could not reserve a coordinator port after 32 attempts: {last_err}"
    )


def _pump(proc: subprocess.Popen, tag: str) -> None:
    """Prefix-and-forward one child's output (ps/worker logs used to live in
    K different terminals; here they interleave on one stream)."""
    for line in proc.stdout:  # type: ignore[union-attr]
        sys.stdout.write(f"[{tag}] {line.decode(errors='replace')}")
        sys.stdout.flush()


def _normalize_rc(code: int) -> int:
    """Deterministic positive exit status: a signal death (negative Popen
    returncode) maps to the shell convention 128+N, so launch()'s return
    value — and the supervisor's restart decision — never depends on how
    the platform spells "killed"."""
    return 128 - code if code < 0 else code


def _describe_exit(tag: str, code: int) -> str:
    """Human-readable failure cause, exit code and tag included — the
    string raised/logged so the death isn't lost in the pump output."""
    if code < 0:
        try:
            name = signal.Signals(-code).name
        except ValueError:
            name = f"signal {-code}"
        return f"{tag} exited rc={_normalize_rc(code)} (killed by {name})"
    return f"{tag} exited rc={code}"


def _say(msg: str) -> None:
    sys.stdout.write(msg + "\n")
    sys.stdout.flush()


def _launch_once(
    num_processes: int,
    train_args: list[str],
    *,
    port: int = 0,
    platform: str | None = None,
    devices_per_process: int = 1,
    env_extra: dict[str, str] | None = None,
    kill_spec: tuple[int, float] | None = None,
    child_command: list[str] | None = None,
    journal=None,
    generation: int = 0,
    hosts: list[int] | None = None,
    grow_after_s: float | None = None,
) -> tuple[int, str | None, int | None, bool]:
    """Spawn ONE cluster generation and wait it out.

    Returns ``(rc, failure, first_dead, grew)``: rc is 0 or the normalized
    exit status of the first abnormal death; `failure` describes that death
    (None on success and on operator interrupt — the supervisor must not
    "restart" a Ctrl-C); `first_dead` is the failing HOST id (the
    chief-death-is-fatal input); `grew` is True when the generation was
    deliberately drained because an excluded host's recovery came due.

    `hosts` maps per-generation process RANKS to stable host ids (elastic
    mode launches the surviving subset; rank i is host hosts[i], exported
    to the child as ``DIST_MNIST_TPU_HOST_ID``). Default: identity.
    `kill_spec` = (host id, delay seconds) injects a launcher-level chaos
    kill: SIGKILL that child `delay` seconds after spawn (faults/plan.py
    kill_process). `grow_after_s` arms the elastic regrow drain: after
    that many seconds, every child gets SIGTERM — the graceful-preemption
    handshake (checkpoint at a step boundary, exit 0) — so the supervisor
    can re-form a LARGER cluster at the next boundary. `child_command`
    replaces the ``python -m dist_mnist_tpu.cli.train`` prefix — the
    supervisor tests' seam for jax-free stub children."""
    if hosts is None:
        hosts = list(range(num_processes))
    assert len(hosts) == num_processes
    probe, lock = None, None
    if not port:
        port, probe, lock = _reserve_port()
    coord = f"localhost:{port}"
    env = dict(os.environ)
    if platform == "cpu" and devices_per_process > 1:
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={devices_per_process}"
        )
    if env_extra:
        env.update(env_extra)
    prefix = child_command or [sys.executable, "-m", "dist_mnist_tpu.cli.train"]

    procs: list[subprocess.Popen] = []
    pumps: list[threading.Thread] = []
    killer: threading.Thread | None = None
    grower: threading.Thread | None = None
    timer_stop = threading.Event()
    grew = threading.Event()
    rc, failure, first_dead = 0, None, None
    try:
        for i in range(num_processes):
            cmd = [
                *prefix,
                f"--coordinator_address={coord}",
                f"--num_processes={num_processes}",
                f"--process_id={i}",
                *([f"--platform={platform}"] if platform else []),
                *train_args,
            ]
            env_i = dict(env)
            env_i[ENV_HOST_ID] = str(hosts[i])
            p = subprocess.Popen(
                cmd, env=env_i,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT
            )
            procs.append(p)
            _LIVE_CHILDREN.append(p)
            t = threading.Thread(
                target=_pump, args=(p, f"p{hosts[i]}"),
                name=f"LaunchPump-p{hosts[i]}", daemon=True
            )
            t.start()
            pumps.append(t)
        if kill_spec is not None and kill_spec[0] in hosts:
            k, delay = kill_spec
            k_rank = hosts.index(k)

            def _chaos_kill():
                if timer_stop.wait(delay):
                    return  # cluster ended first
                victim = procs[k_rank]
                if victim.poll() is None:
                    _say(f"[launcher] fault injected: SIGKILL p{k} "
                         f"after {delay:.1f}s")
                    if journal is not None:
                        journal.emit("fault_injected", kind="kill_process",
                                     process=k, delay_s=delay,
                                     gen=generation)
                    victim.kill()

            killer = threading.Thread(
                target=_chaos_kill, name=f"FaultKillTimer-p{k}", daemon=True
            )
            killer.start()
        if grow_after_s is not None:
            g_delay = max(0.05, grow_after_s)

            def _grow_drain():
                if timer_stop.wait(g_delay):
                    return
                live = [p for p in procs if p.poll() is None]
                if not live:
                    return
                # graceful preemption handshake, not a kill: children
                # checkpoint at a step boundary and exit 0, so the grown
                # generation restores the freshest possible state
                grew.set()
                _say(f"[launcher] host recovery due: draining generation "
                     f"{generation} ({len(live)} children, SIGTERM) to "
                     f"grow the mesh")
                if journal is not None:
                    journal.emit("grow_drain", gen=generation,
                                 children=len(live))
                for p in live:
                    p.send_signal(signal.SIGTERM)

            grower = threading.Thread(
                target=_grow_drain, name="ElasticGrowTimer", daemon=True
            )
            grower.start()
        # all children exist; release the port for the child coordinator
        # (children spend seconds in jax import before binding it)
        if probe is not None:
            probe.close()
            probe = None
        # wait for all; on the first failure kill the survivors (a dead peer
        # would otherwise park them in collectives until the coordination
        # service's heartbeat timeout — fail fast instead)
        alive = set(range(num_processes))
        while alive:
            dead: list[tuple[int, int]] = []
            for i in sorted(alive):
                code = procs[i].poll()
                if code is None:
                    continue
                alive.discard(i)
                if code != 0:
                    dead.append((i, code))
            if dead and rc == 0:
                # attribution within one poll window: a dying WORKER takes
                # the chief down with it (coordination-service abort), so
                # when both land in the same tick the worker is the root
                # cause — blaming the chief would make a survivable worker
                # crash fatal to the supervisor. The chief is blamed only
                # when no worker died alongside it.
                i, code = next(((j, c) for j, c in dead if j != 0), dead[0])
                rc = _normalize_rc(code)
                failure = _describe_exit(f"p{hosts[i]}", code)
                first_dead = hosts[i]
                # SIGKILL, not SIGTERM: with a peer already dead the
                # survivors are parked in a collective they can never
                # finish, and every graceful-exit path (even a checkpoint
                # save) crosses another barrier with the same dead peer —
                # a SIGTERM would just stall here until the coordination
                # service's heartbeat timeout (~90s of pure downtime per
                # generation). The checkpoint frontier is whatever the
                # last cadence save already wrote.
                _say(f"[launcher] {failure}; killing {len(alive)} peer(s)")
                for j in sorted(alive):
                    procs[j].kill()
            if alive:
                try:
                    procs[min(alive)].wait(timeout=0.5)
                except subprocess.TimeoutExpired:
                    pass
    except KeyboardInterrupt:
        # forward the interrupt and give children a bounded window to
        # finish in-flight side effects (checkpoint save, log flush)
        # before the finally-kill
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        deadline = 10.0
        for p in procs:
            try:
                p.wait(timeout=deadline)
            except subprocess.TimeoutExpired:
                deadline = 0.1
        rc, failure, first_dead = 130, None, None
    finally:
        if probe is not None:
            probe.close()
        timer_stop.set()
        if killer is not None:
            killer.join(timeout=5)
        if grower is not None:
            grower.join(timeout=5)
        for p in procs:
            if p.poll() is None:
                p.kill()
        for t in pumps:
            t.join(timeout=5)
        for p in procs:
            p.wait()
            if p in _LIVE_CHILDREN:
                _LIVE_CHILDREN.remove(p)
        if lock is not None:
            try:
                lock.unlink()
            except OSError:
                pass
    return rc, failure, first_dead, grew.is_set()


def _metrics_port_base(train_args: list[str]) -> int | None:
    """The children's ``--metrics_port`` base from forwarded train args
    (rank i then listens on base + i — cli/train.py main), or None when
    the run exposes no per-process metrics. Last occurrence wins, like
    the child's absl parse."""
    base = None
    for i, a in enumerate(train_args):
        if a.startswith("--metrics_port="):
            val = a.split("=", 1)[1]
        elif a == "--metrics_port" and i + 1 < len(train_args):
            val = train_args[i + 1]
        else:
            continue
        try:
            base = int(val)
        except ValueError:
            continue
    return base if base else None


def launch(
    num_processes: int,
    train_args: list[str],
    *,
    port: int = 0,
    platform: str | None = None,
    devices_per_process: int = 1,
    env_extra: dict[str, str] | None = None,
    max_restarts: int = 0,
    restart_backoff_s: float = 1.0,
    kill_spec: tuple[int, float] | None = None,
    child_command: list[str] | None = None,
    compile_cache_dir: str | None = None,
    journal: str | None = None,
    elastic: bool = False,
    min_processes: int = 1,
    regrow_after_s: float = 0.0,
    host_kill: tuple[int, float | None] | None = None,
    health=None,
    supervisor_port: int | None = None,
    fleet_interval_s: float = 1.0,
) -> int:
    """Spawn the cluster; return 0 or a deterministic nonzero exit status
    (the first abnormal death's, signal deaths normalized to 128+N).
    Importable — tests and scripts call this directly.

    With ``max_restarts > 0`` this is a SUPERVISOR: an abnormal non-chief
    death tears the generation down (a dead peer would park the others in
    collectives) and relaunches the WHOLE cluster — single-process rejoin
    is not a thing under jax.distributed, but checkpoint resume makes a
    generation restart cheap, and the coordinator port is re-reserved
    fresh each time. Backoff is exponential with jitter. A chief (p0)
    death is fatal: the chief owns the coordination service, so its loss
    says the job itself — not one replica — is broken. An operator
    interrupt (Ctrl-C) is never "restarted".

    A supervised cluster also gets a WARM-START cache: every generation
    receives the same ``--compile_cache_dir`` (compilecache/), so
    generation N+1 deserializes the step programs generation N compiled
    instead of paying the cold compile again — the recurring compile cost
    the restart loop would otherwise multiply. If the caller didn't pick a
    directory, the supervisor creates a private one and removes it when
    the job ends; an explicit dir (flag or train_args) is left alone.

    A supervised cluster also gets ONE RUN JOURNAL (obs/events.py): the
    supervisor opens it, records its own lifecycle (``supervisor_start``,
    per-generation ``generation_start``/``generation_end``,
    ``supervisor_restart``, ``supervisor_stop``, launcher-level
    ``fault_injected`` kills), and injects the path plus the generation
    number into every child's environment (``DIST_MNIST_TPU_JOURNAL`` /
    ``DIST_MNIST_TPU_GENERATION``) — so a fault-plan run leaves a single
    machine-readable record of the whole restart sequence. An explicit
    ``journal`` path survives the run; otherwise the journal lives inside
    the supervisor-owned warm-start dir and is removed with it.

    ``elastic=True`` turns the restart-the-world supervisor into a
    membership state machine (docs/RESILIENCE.md "Elastic generations"):
    an abnormal non-chief death EXCLUDES that host (`cluster/membership`)
    and the next generation re-forms immediately — fresh coordinator port,
    surviving hosts only, smaller mesh, state restored (resharded) from
    the latest checkpoint — with NO backoff: the failing host is out of
    the new world, so there is nothing to back off from. Each shrink
    emits a ``generation_resize`` journal event (old/new world size) and
    consumes one of ``max_restarts``. A lost host re-joins when its
    recovery comes due — ``host_kill=(host, recover_after_s)`` from a
    seeded kill_host fault, or ``regrow_after_s`` for unattributed
    deaths — by gracefully draining the shrunken generation (SIGTERM →
    step-boundary checkpoint → exit 0) and growing the mesh back at the
    next boundary (a grow consumes no restart budget). A shrink below
    ``min_processes``, or any chief death, stays fatal. ``health`` (an
    obs.exporter.HealthState) tracks the supervisor itself — it reports
    ``resizing`` during mesh re-formation — and ``supervisor_port`` serves
    it over /healthz (503 while resizing, so routers hold traffic).

    When the forwarded train args include ``--metrics_port`` (so each
    child rank exposes its own /metrics on base+rank), the supervisor
    endpoint additionally runs a ``FleetScraper`` (obs/fleet.py):
    /metrics grows merged fleet-wide histograms plus ``fleet/*`` gauges
    (including straggler detection), and /fleet serves the per-host JSON
    view. Scrape targets are re-pointed at every generation start, so
    the fleet view follows resizes."""
    from dist_mnist_tpu.obs import events as events_mod

    if elastic and max_restarts <= 0:
        # elastic implies supervision; default budget = one resize per
        # host that could possibly be lost
        max_restarts = max(1, num_processes - 1)

    cache_dir_owned = False
    if max_restarts > 0 and compile_cache_dir is None and not any(
        a.startswith("--compile_cache_dir") for a in train_args
    ):
        compile_cache_dir = tempfile.mkdtemp(prefix="dist_mnist_warmstart_")
        cache_dir_owned = True
        _say(f"[supervisor] warm-start cache for restart generations: "
             f"{compile_cache_dir}")
    if compile_cache_dir is not None and not any(
        a.startswith("--compile_cache_dir") for a in train_args
    ):
        train_args = [*train_args, f"--compile_cache_dir={compile_cache_dir}"]
    if journal is None and max_restarts > 0 and compile_cache_dir is not None:
        journal = str(Path(compile_cache_dir) / "journal.jsonl")
    if elastic and platform == "cpu" and child_command is None and not any(
        a.startswith("--elastic_baseline_devices") for a in train_args
    ):
        # record the pre-shrink device count so every (possibly resized)
        # generation can resolve the global-batch policy against it
        # (configs.apply_elastic_policy); only the cpu simulator knows
        # devices-per-process here — real TPU topologies pass it in
        # train_args themselves
        train_args = [
            *train_args,
            f"--elastic_baseline_devices="
            f"{num_processes * devices_per_process}",
        ]
    jrnl = events_mod.RunJournal(journal) if journal else None
    if jrnl is not None:
        _say(f"[supervisor] run journal: {journal}")
        jrnl.emit("supervisor_start", num_processes=num_processes,
                  max_restarts=max_restarts, elastic=elastic)
    membership = Membership(num_processes) if elastic else None
    exporter = None
    scraper = None
    metrics_base = _metrics_port_base(train_args)
    if supervisor_port is not None and supervisor_port >= 0 and elastic:
        from dist_mnist_tpu.obs.exporter import HealthState, MetricsExporter

        if health is None:
            health = HealthState()
        if metrics_base:
            # children expose /metrics on metrics_base + rank: the fleet
            # scraper merges them and the supervisor endpoint serves the
            # fleet-wide view (/metrics merged series + /fleet JSON)
            from dist_mnist_tpu.obs.fleet import FleetScraper

            scraper = FleetScraper(journal=jrnl,
                                   interval_s=fleet_interval_s).start()
        exporter = MetricsExporter(
            registry=scraper.registry if scraper is not None else None,
            health=health, journal_path=journal, port=supervisor_port,
            info={"role": "supervisor", "generation": 0},
            fleet=scraper,
        ).start()
        _say(f"[supervisor] health endpoint: {exporter.url('/healthz')}"
             + (f" (fleet view: {exporter.url('/fleet')})"
                if scraper is not None else ""))
    rng = random.Random(0)  # deterministic jitter (tests time the backoff)
    attempt = 0  # failure restarts/resizes consumed (bounded)
    gen = 0  # journal generation number (grows also advance it)

    def _stop(rc: int) -> int:
        if jrnl is not None:
            jrnl.emit("supervisor_stop", rc=rc, restarts=attempt)
        if health is not None:
            health.set("stopped" if rc == 0 else "failed", f"rc={rc}")
        return rc

    try:
        while True:
            hosts = None
            grow_after = None
            if membership is not None:
                now = time.monotonic()
                recovered = membership.restore_due(now)
                if recovered:
                    # failure boundary doubled as the grow boundary (the
                    # generation died while a recovery was already due)
                    _say(f"[supervisor] host(s) {recovered} recovered; "
                         f"growing mesh to {membership.world_size}")
                hosts = membership.alive()
                grow_after = membership.next_recovery_in(now)
            world = len(hosts) if hosts is not None else num_processes
            env_gen = dict(env_extra or {})
            if journal:
                env_gen[events_mod.ENV_JOURNAL] = journal
                env_gen[events_mod.ENV_GENERATION] = str(gen)
            # membership snapshot for this generation: children use it to
            # decide which peer-ring replica dirs are still reachable after
            # a shrink (checkpoint/peer.py — a dead host's disk died with it)
            env_gen[ENV_ALIVE_HOSTS] = ",".join(
                str(h) for h in (hosts if hosts is not None
                                 else range(world)))
            if jrnl is not None:
                jrnl.emit("generation_start", gen=gen, world=world,
                          hosts=hosts)
            if health is not None:
                health.set("training", f"gen={gen} world={world}")
            if scraper is not None and metrics_base:
                # rank i listens on metrics_base + i and IS host hosts[i]
                gen_hosts = hosts if hosts is not None \
                    else list(range(world))
                scraper.set_targets({
                    h: f"http://127.0.0.1:{metrics_base + i}"
                    for i, h in enumerate(gen_hosts)
                })
            if exporter is not None and exporter.info is not None:
                exporter.info["generation"] = gen
            rc, failure, first_dead, grew = _launch_once(
                world, train_args, port=port, platform=platform,
                devices_per_process=devices_per_process,
                env_extra=env_gen or None,
                kill_spec=kill_spec if gen == 0 else None,
                child_command=child_command,
                journal=jrnl, generation=gen,
                hosts=hosts, grow_after_s=grow_after,
            )
            if jrnl is not None:
                jrnl.emit("generation_end", gen=gen, rc=rc,
                          failure=failure, first_dead=first_dead)
            if rc == 130 and failure is None:
                return _stop(rc)  # operator interrupt — never re-formed
            if grew and membership is not None:
                # planned drain for regrow: not a failure, no backoff, no
                # restart budget consumed
                now = time.monotonic()
                due = membership.restore_due(now)
                old_world, new_world = world, membership.world_size
                gen += 1
                _say(f"[supervisor] generation resized {old_world} -> "
                     f"{new_world} (grow: host(s) {due} back)")
                if jrnl is not None:
                    jrnl.emit("generation_resize", gen=gen, kind="grow",
                              old_world=old_world, new_world=new_world,
                              host=(due[0] if len(due) == 1 else due))
                if health is not None:
                    health.set("resizing",
                               f"grow {old_world}->{new_world}")
                continue
            if rc == 0 or failure is None or max_restarts <= 0:
                return _stop(rc)
            if first_dead == 0:
                _say(f"[supervisor] chief died ({failure}); fatal — "
                     f"not restarting, rc={rc}")
                return _stop(rc)
            if attempt >= max_restarts:
                _say(f"[supervisor] {failure}; giving up after {attempt} "
                     f"restart(s), rc={rc}")
                return _stop(rc)
            if membership is not None and first_dead is not None:
                # elastic shrink: exclude the lost host and re-form at the
                # surviving world size IMMEDIATELY — the failing host is
                # out of the next world, so crash-loop backoff would only
                # add downtime
                recover = None
                if host_kill is not None and first_dead == host_kill[0]:
                    recover = host_kill[1]
                elif regrow_after_s and regrow_after_s > 0:
                    recover = regrow_after_s
                membership.fail(
                    first_dead, now=time.monotonic(),
                    recover_after_s=recover,
                )
                old_world, new_world = world, membership.world_size
                if new_world < max(1, min_processes):
                    _say(f"[supervisor] {failure}; surviving world size "
                         f"{new_world} below min_processes="
                         f"{min_processes}; fatal, rc={rc}")
                    return _stop(rc)
                attempt += 1
                gen += 1
                _say(f"[supervisor] {failure}; generation resized "
                     f"{old_world} -> {new_world} (shrink: host "
                     f"{first_dead} out"
                     + (f", recovery in {recover:.1f}s" if recover
                        else "")
                     + f") — resize {attempt}/{max_restarts}, no backoff")
                if jrnl is not None:
                    jrnl.emit("generation_resize", gen=gen, kind="shrink",
                              old_world=old_world, new_world=new_world,
                              host=first_dead, recover_after_s=recover,
                              failure=failure)
                if health is not None:
                    health.set("resizing",
                               f"shrink {old_world}->{new_world}")
                continue
            delay = (restart_backoff_s * (2 ** attempt)
                     * (1.0 + 0.5 * rng.random()))
            attempt += 1
            gen += 1
            _say(f"[supervisor] {failure}; restarting cluster "
                 f"(attempt {attempt}/{max_restarts}) in {delay:.2f}s")
            if jrnl is not None:
                jrnl.emit("supervisor_restart", attempt=attempt,
                          delay_s=round(delay, 3), failure=failure)
            time.sleep(delay)
    finally:
        if scraper is not None:
            scraper.close()
        if exporter is not None:
            exporter.close()
        if jrnl is not None:
            jrnl.close()
        if cache_dir_owned:
            import shutil

            shutil.rmtree(compile_cache_dir, ignore_errors=True)


#: launcher-owned / per-child flags that must NOT be blanket-forwarded
_UNFORWARDED = {
    "port", "devices_per_process", "num_processes", "platform",
    "coordinator_address", "process_id",
}


def _forwarded_train_flags() -> list[str]:
    """Serialize train flags the user set on the LAUNCHER's command line.

    Because cli.train is imported here, absl parses its flags wherever they
    appear — `launch --num_processes=2 --train_steps=500` consumes
    --train_steps into this process's FLAGS instead of leaving it in argv.
    Forwarding every explicitly-set train-module flag keeps both styles
    working (before or after `--`)."""
    out = []
    for module, flag_list in FLAGS.flags_by_module_dict().items():
        if not module.endswith("cli.train"):
            continue
        for fl in flag_list:
            if fl.present and fl.name not in _UNFORWARDED:
                out.append(fl.serialize())
    return out


def main(argv):
    # explicitly-set train flags absl already consumed, then any literal
    # passthrough after `--` (duplicates are fine: the later, explicit
    # occurrence wins in the child's absl parse)
    train_args = _forwarded_train_flags() + [a for a in argv[1:] if a != "--"]
    # one plan, two layers: the launcher takes the kill_process fault;
    # --fault_plan is a cli.train flag, so the SAME plan is forwarded to
    # the children, which consume the in-process kinds
    kill_spec = None
    host_kill = None
    if FLAGS.fault_plan:
        from dist_mnist_tpu.faults import FaultPlan

        plan = FaultPlan.from_spec(FLAGS.fault_plan)
        kill_spec = plan.kill_spec()
        # kill_host faults fire IN the victim (faults/inject.py) at their
        # step; the supervisor only takes the attribution side — which
        # host is a planned permanent loss, and when it recovers
        host_kill = plan.host_kill_spec()
    rc = launch(
        FLAGS.num_processes,
        train_args,
        port=FLAGS.port,
        platform=FLAGS.platform,
        devices_per_process=FLAGS.devices_per_process,
        max_restarts=FLAGS.max_restarts,
        restart_backoff_s=FLAGS.restart_backoff_s,
        kill_spec=kill_spec,
        compile_cache_dir=FLAGS.compile_cache_dir,
        journal=FLAGS.journal,
        elastic=FLAGS.elastic,
        min_processes=FLAGS.min_processes,
        regrow_after_s=FLAGS.regrow_after_s,
        host_kill=host_kill,
        supervisor_port=FLAGS.supervisor_port,
    )
    if rc:
        sys.exit(rc)


if __name__ == "__main__":
    app.run(main)
