"""Command-line entrypoints (the reference repo's driver-script layer)."""
