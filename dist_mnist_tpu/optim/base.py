"""Gradient-transformation core.

An `Optimizer` is a pair of pure functions over pytrees:
``init(params) -> state`` and
``update(grads, state, params) -> (updates, new_state)``
where `updates` are deltas (`params + updates` applies them). Composable via
`chain`, mirroring how the reference composed SyncReplicasOptimizer around
AdamOptimizer (sync_replicas_optimizer.py:215: "opt = SyncReplicas(opt)").
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp

Params = Any
Grads = Any
State = Any


class Optimizer(NamedTuple):
    # State trees are built ONLY from dicts/tuples/namedtuples/lists of
    # arrays (adam's {"m","v","count"} dicts, chain's tuple-of-states):
    # checkpoint/manager.py::_flip_block_layouts recurses exactly those
    # container types when healing block-layout flips, so a custom
    # registered pytree node here would silently skip conversion of its
    # mirrored slots (advisor r4) — extend that walker if you add one.
    init: Callable[[Params], State]
    update: Callable[[Grads, State, Params], tuple[Grads, State]]


# kept as an alias for annotations in user code
OptimizerDef = Optimizer


def apply_updates(params: Params, updates: Grads) -> Params:
    """params + updates, preserving param dtype (master weights stay f32)."""
    return jax.tree.map(
        lambda p, u: (p + u.astype(p.dtype)) if u is not None else p, params, updates
    )


def chain(*optimizers: Optimizer) -> Optimizer:
    """Compose transformations left-to-right (grads flow through all)."""

    def init(params):
        return tuple(o.init(params) for o in optimizers)

    def update(grads, state, params):
        new_states = []
        for o, s in zip(optimizers, state):
            grads, ns = o.update(grads, s, params)
            new_states.append(ns)
        return grads, tuple(new_states)

    return Optimizer(init, update)


def scale(factor: float) -> Optimizer:
    return Optimizer(
        init=lambda params: (),
        update=lambda g, s, p: (jax.tree.map(lambda x: x * factor, g), s),
    )


def global_norm(tree) -> jax.Array:
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in jax.tree.leaves(tree))
    )


def clip_by_global_norm(max_norm: float) -> Optimizer:
    def update(grads, state, params):
        norm = global_norm(grads)
        factor = jnp.minimum(1.0, max_norm / (norm + 1e-12))
        return jax.tree.map(lambda g: g * factor, grads), state

    return Optimizer(init=lambda p: (), update=update)


def add_decayed_weights(weight_decay: float) -> Optimizer:
    """L2 regularization: adds wd*p INTO the gradient, so when chained
    before an adaptive optimizer the decay is scaled by its normalizer.
    For decoupled (AdamW-style) decay use `optim.adamw` instead."""

    def update(grads, state, params):
        return (
            jax.tree.map(lambda g, p: g + weight_decay * p.astype(g.dtype),
                         grads, params),
            state,
        )

    return Optimizer(init=lambda p: (), update=update)
