"""Learning-rate schedules (callables of the int32 update count)."""

from __future__ import annotations

import jax.numpy as jnp


def constant(value: float):
    return lambda count: jnp.float32(value)


def cosine_decay(peak: float, total_steps: int, warmup_steps: int = 0,
                 floor: float = 0.0):
    def schedule(count):
        t = count.astype(jnp.float32)
        warm = peak * t / jnp.maximum(1.0, warmup_steps)
        prog = jnp.clip(
            (t - warmup_steps) / jnp.maximum(1.0, total_steps - warmup_steps), 0, 1
        )
        cos = floor + (peak - floor) * 0.5 * (1 + jnp.cos(jnp.pi * prog))
        return jnp.where(t < warmup_steps, warm, cos)

    return schedule


def step_decay(base: float, boundaries: tuple[int, ...], factor: float = 0.1):
    def schedule(count):
        t = count.astype(jnp.float32)
        n_passed = sum((t >= b).astype(jnp.float32) for b in boundaries)
        return base * factor**n_passed

    return schedule
