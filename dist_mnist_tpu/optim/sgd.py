"""SGD and heavy-ball momentum (tf.train.GradientDescentOptimizer /
MomentumOptimizer analogues — same family as training_ops.h
ApplyGradientDescent / ApplyMomentum)."""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from dist_mnist_tpu.optim.base import Optimizer


def _lr_at(learning_rate, count):
    return learning_rate(count) if callable(learning_rate) else learning_rate


def sgd(learning_rate: float | Callable = 0.01) -> Optimizer:
    def init(params):
        return {"count": jnp.zeros((), jnp.int32)}

    def update(grads, state, params):
        count = state["count"] + 1
        lr = _lr_at(learning_rate, count)
        return (
            jax.tree.map(lambda g: -lr * g.astype(jnp.float32), grads),
            {"count": count},
        )

    return Optimizer(init, update)


def momentum(
    learning_rate: float | Callable = 0.01,
    decay: float = 0.9,
    nesterov: bool = False,
) -> Optimizer:
    def init(params):
        return {
            "velocity": jax.tree.map(
                lambda p: jnp.zeros_like(p, dtype=jnp.float32), params
            ),
            "count": jnp.zeros((), jnp.int32),
        }

    def update(grads, state, params):
        count = state["count"] + 1
        lr = _lr_at(learning_rate, count)
        g32 = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        vel = jax.tree.map(lambda v, g: decay * v + g, state["velocity"], g32)
        if nesterov:
            updates = jax.tree.map(lambda v, g: -lr * (decay * v + g), vel, g32)
        else:
            updates = jax.tree.map(lambda v: -lr * v, vel)
        return updates, {"velocity": vel, "count": count}

    return Optimizer(init, update)
