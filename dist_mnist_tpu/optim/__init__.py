"""Optimizers, built from scratch on the gradient-transformation pattern.

Replaces SURVEY.md §2.3 rows 6-8: `tf.train.Optimizer`'s
minimize = compute_gradients + apply_gradients split (optimizer.py:463-783),
Adam's m/v slots + beta-power non-slots (adam.py:189-231), and the fused
native ApplyAdam kernel (training_ops.h). Here the whole update is pure
array math inside the jit-compiled step — XLA fuses it into a handful of
elementwise kernels over each param, which *is* the training_ops.h fusion,
compiler-generated.

SyncReplicasOptimizer's `replicas_to_aggregate` semantics live in
`sync.py` as gradient accumulation (see that module for the exact mapping
and its documented divergence from the PS token-queue protocol).
"""

from dist_mnist_tpu.optim.base import (
    Optimizer,
    OptimizerDef,
    apply_updates,
    chain,
    clip_by_global_norm,
    scale,
    add_decayed_weights,
    global_norm,
)
from dist_mnist_tpu.optim.adam import adam, adamw, fused_adamw
from dist_mnist_tpu.optim.sgd import sgd, momentum
from dist_mnist_tpu.optim.sync import gradient_accumulation
from dist_mnist_tpu.optim import schedules

__all__ = [
    "Optimizer",
    "OptimizerDef",
    "apply_updates",
    "chain",
    "clip_by_global_norm",
    "scale",
    "add_decayed_weights",
    "global_norm",
    "adam",
    "adamw",
    "fused_adamw",
    "sgd",
    "momentum",
    "gradient_accumulation",
    "schedules",
]
