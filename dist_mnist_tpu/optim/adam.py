"""Adam with the reference's exact semantics.

Parity target: `tf.train.AdamOptimizer` (SURVEY.md §2.3 row 7) — slots m/v
per param plus shared beta1_power/beta2_power "non-slot" scalars
(adam.py:189-203), and the fused kernel's update rule (training_ops.h
ApplyAdam):

    lr_t   = lr * sqrt(1 - b2^t) / (1 - b1^t)
    m_t    = b1*m + (1-b1)*g
    v_t    = b2*v + (1-b2)*g^2
    param -= lr_t * m_t / (sqrt(v_t) + eps)      # eps OUTSIDE the sqrt,
                                                 # TF's convention

Defaults match tf.train.AdamOptimizer: b1=0.9, b2=0.999, eps=1e-8. We keep a
step counter instead of materialized beta-power variables (same numbers, one
scalar instead of two). All state is f32 regardless of compute dtype.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from dist_mnist_tpu.optim.base import Optimizer, global_norm


def adam(
    learning_rate: float | Callable[[jax.Array], jax.Array] = 0.01,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    *,
    fused: bool = False,
) -> Optimizer:
    """`fused=True` routes the per-tensor slot+delta update through the
    Pallas one-pass kernel (ops/pallas/fused_adam.py) instead of jnp ops;
    same math, one HBM pass."""

    def init(params):
        zeros = lambda: jax.tree.map(
            lambda p: jnp.zeros_like(p, dtype=jnp.float32), params
        )
        return {"m": zeros(), "v": zeros(), "count": jnp.zeros((), jnp.int32)}

    def update(grads, state, params):
        del params
        count = state["count"] + 1
        t = count.astype(jnp.float32)
        lr = learning_rate(count) if callable(learning_rate) else learning_rate
        lr_t = lr * jnp.sqrt(1.0 - b2**t) / (1.0 - b1**t)
        g32 = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        if fused:
            from dist_mnist_tpu.ops.pallas.fused_adam import fused_adam_update

            flat_g, treedef = jax.tree.flatten(g32)
            flat_m = treedef.flatten_up_to(state["m"])
            flat_v = treedef.flatten_up_to(state["v"])
            outs = [
                fused_adam_update(g_, m_, v_, lr_t, b1=b1, b2=b2, eps=eps)
                for g_, m_, v_ in zip(flat_g, flat_m, flat_v)
            ]
            updates = jax.tree.unflatten(treedef, [o[0] for o in outs])
            m = jax.tree.unflatten(treedef, [o[1] for o in outs])
            v = jax.tree.unflatten(treedef, [o[2] for o in outs])
            return updates, {"m": m, "v": v, "count": count}
        m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], g32)
        v = jax.tree.map(lambda v_, g: b2 * v_ + (1 - b2) * g * g, state["v"], g32)
        updates = jax.tree.map(lambda m_, v_: -lr_t * m_ / (jnp.sqrt(v_) + eps), m, v)
        return updates, {"m": m, "v": v, "count": count}

    return Optimizer(init, update)


def adamw(
    learning_rate: float | Callable[[jax.Array], jax.Array] = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.01,
) -> Optimizer:
    """Adam with DECOUPLED weight decay (Loshchilov & Hutter): the decay term
    bypasses the m/v normalization — update = adam_delta - lr*wd*param —
    unlike chaining add_decayed_weights before adam (which is plain L2)."""
    inner = adam(learning_rate, b1, b2, eps)

    def update(grads, state, params):
        count = state["count"] + 1
        lr = learning_rate(count) if callable(learning_rate) else learning_rate
        updates, new_state = inner.update(grads, state, params)
        updates = jax.tree.map(
            lambda u, p: u - lr * weight_decay * p.astype(u.dtype),
            updates, params,
        )
        return updates, new_state

    return Optimizer(inner.init, update)


def fused_adamw(
    learning_rate: float | Callable[[jax.Array], jax.Array] = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    clip_norm: float | None = None,
) -> Optimizer:
    """One-pass fused `clip_by_global_norm >> adamw`: the global-norm clip
    factor is a cross-tensor reduction computed ONCE in XLA, then each leaf
    runs a single Pallas kernel doing clip-scale, m/v slots, Adam delta,
    and the decoupled `-lr*wd*param` term in one HBM pass
    (ops/pallas/fused_adam.fused_adam_clip_wd_update) — vs three passes for
    the chained path (clip rewrite, adam, decay rewrite). Mathematically
    identical to `chain(clip_by_global_norm(clip_norm), adamw(...))`; with
    `weight_decay=0` and `clip_norm=None` it routes to the EXACT original
    `fused_adam_update` kernel, bit-identical to `adam(fused=True)`."""
    inner = adam(learning_rate, b1, b2, eps)  # reuse slot init/shape rules
    plain = weight_decay == 0.0 and clip_norm is None

    def update(grads, state, params):
        from dist_mnist_tpu.ops.pallas.fused_adam import (
            fused_adam_clip_wd_update,
            fused_adam_update,
        )

        count = state["count"] + 1
        t = count.astype(jnp.float32)
        lr = learning_rate(count) if callable(learning_rate) else learning_rate
        lr_t = lr * jnp.sqrt(1.0 - b2**t) / (1.0 - b1**t)
        g32 = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        flat_g, treedef = jax.tree.flatten(g32)
        flat_m = treedef.flatten_up_to(state["m"])
        flat_v = treedef.flatten_up_to(state["v"])
        if plain:
            outs = [
                fused_adam_update(g_, m_, v_, lr_t, b1=b1, b2=b2, eps=eps)
                for g_, m_, v_ in zip(flat_g, flat_m, flat_v)
            ]
        else:
            if clip_norm is None:
                clip_scale = jnp.float32(1.0)
            else:
                # same factor as optim.base.clip_by_global_norm
                norm = global_norm(g32)
                clip_scale = jnp.minimum(1.0, clip_norm / (norm + 1e-12))
            wd_step = lr * weight_decay
            flat_p = treedef.flatten_up_to(params)
            outs = [
                fused_adam_clip_wd_update(
                    g_, m_, v_, p_, lr_t, clip_scale, wd_step,
                    b1=b1, b2=b2, eps=eps)
                for g_, m_, v_, p_ in zip(flat_g, flat_m, flat_v, flat_p)
            ]
        updates = jax.tree.unflatten(treedef, [o[0] for o in outs])
        m = jax.tree.unflatten(treedef, [o[1] for o in outs])
        v = jax.tree.unflatten(treedef, [o[2] for o in outs])
        return updates, {"m": m, "v": v, "count": count}

    return Optimizer(inner.init, update)
