"""Sync-replica semantics: gradient accumulation over microbatches.

Reference protocol (SURVEY.md §2.3 row 9, §3.4): SyncReplicasOptimizer
parks per-variable ConditionalAccumulators on the PS
(sync_replicas_optimizer.py:274-293), each worker pushes a step-stamped
gradient, the chief's queue-runner takes `replicas_to_aggregate` fresh
gradients, averages, applies, and broadcasts tokens through a FIFOQueue
barrier (:312-322). Backup replicas (`total_num_replicas >
replicas_to_aggregate`) let the slowest K gradients be *dropped*.

SPMD mapping (documented divergence, per SURVEY.md §7 hard part (a)):
- The aggregate-then-apply barrier is exact: `psum` over the `data` axis is
  a synchronous average of all replicas' gradients inside the step.
- `replicas_to_aggregate = k * N` (aggregating MORE than one minibatch per
  update) maps exactly to this module: accumulate k microbatch gradients,
  apply on the k-th. Identical update math, k× the effective batch.
- Dropping the slowest K gradients is NOT expressible in a lockstep SPMD
  program (there is no "slowest" — all replicas finish the same compiled
  step together), and with ICI all-reduce there is no straggler problem for
  backup replicas to solve. We therefore do not emulate it; the async-PS
  demo (parallel/ps_demo) shows the original protocol for reference.

The accumulator's staleness guard (conditional_accumulator_base.h:34-37 —
drop grads whose local_step < global_step) is unnecessary here: a step's
gradients are, by construction, computed from the params of that same step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dist_mnist_tpu.optim.base import Optimizer


def gradient_accumulation(inner: Optimizer, every: int) -> Optimizer:
    """Apply `inner` once per `every` calls, averaging the buffered grads.

    Between boundaries the returned updates are zeros (params unchanged),
    matching the reference's worker view: non-aggregated steps leave
    variables untouched until the chief's take_grad fires (§3.4).
    Branchless (lax.cond-free): masks keep everything fusible and avoid
    divergent control flow in the compiled step.
    """
    if every < 1:
        raise ValueError("`every` must be >= 1")
    if every == 1:
        return inner

    def init(params):
        return {
            "acc": jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32), params),
            "calls": jnp.zeros((), jnp.int32),
            "inner": inner.init(params),
        }

    def update(grads, state, params):
        calls = state["calls"] + 1
        boundary = (calls % every) == 0
        acc = jax.tree.map(
            lambda a, g: a + g.astype(jnp.float32) / every, state["acc"], grads
        )
        # Run the inner update unconditionally on the accumulated average,
        # then mask: cheap relative to fwd/bwd and keeps one fused program.
        inner_updates, inner_state = inner.update(acc, state["inner"], params)
        updates = jax.tree.map(
            lambda u: jnp.where(boundary, u, jnp.zeros_like(u)), inner_updates
        )
        new_inner = jax.tree.map(
            lambda new, old: jnp.where(boundary, new, old), inner_state,
            state["inner"],
        )
        new_acc = jax.tree.map(
            lambda a: jnp.where(boundary, jnp.zeros_like(a), a), acc
        )
        return updates, {"acc": new_acc, "calls": calls, "inner": new_inner}

    return Optimizer(init, update)
