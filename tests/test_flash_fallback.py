"""The flash replicated-batch fallback must WARN, once per trace.

When `batch % data != 0` on a mesh with a real model axis, the flash
shard_map drops the data axis and every device recomputes the full
replicated batch — a silent O(data)x compute/memory cliff (ADVICE r5,
mirroring moe.py's dense-fallback warning). These tests pin the warning's
existence, its once-per-trace cadence (a jit-cached fallback would
otherwise be invisible after the first step), and its absence on the
well-shaped path. Kept separate from test_parallel_attention.py: this is
log-contract coverage, not numerics."""

from __future__ import annotations

import logging

import jax
import numpy as np
import pytest

from dist_mnist_tpu.cluster.mesh import activate
from dist_mnist_tpu.parallel.flash import flash_attention_sharded

_LOGGER = "dist_mnist_tpu.parallel.flash"


def _qkv(batch, seq=8, heads=2, dim=4, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jax.numpy.asarray(
        rng.normal(size=(batch, seq, heads, dim)), jax.numpy.float32)
    return mk(), mk(), mk()


def _warnings(caplog):
    return [r for r in caplog.records
            if r.name == _LOGGER and "drops the data axis" in r.message]


def test_replicated_batch_warns_once_per_trace(mesh_tp, caplog):
    q, k, v = _qkv(batch=3)  # 3 % data(4) != 0 -> replicated fallback
    fn = jax.jit(flash_attention_sharded)
    with activate(mesh_tp), caplog.at_level(logging.WARNING, logger=_LOGGER):
        out1 = fn(q, k, v)
        out2 = fn(q, k, v)  # cache hit: no retrace, no second warning
    assert out1.shape == q.shape
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2))
    assert len(_warnings(caplog)) == 1
    msg = _warnings(caplog)[0].getMessage()
    assert "batch=3" in msg and "4x redundant" in msg


def test_new_trace_warns_again(mesh_tp, caplog):
    # fresh lambda: jax's trace cache is keyed on the function object, and
    # this test must own its traces (batch sizes also unique to this test)
    fn = jax.jit(lambda a, b, c: flash_attention_sharded(a, b, c))
    with activate(mesh_tp), caplog.at_level(logging.WARNING, logger=_LOGGER):
        fn(*_qkv(batch=6, seed=1))
        fn(*_qkv(batch=7, seed=2))  # new shape -> new trace -> new warning
    assert len(_warnings(caplog)) == 2


def test_divisible_batch_does_not_warn(mesh_tp, caplog):
    q, k, v = _qkv(batch=4)  # 4 % data(4) == 0 -> rides the data axis
    with activate(mesh_tp), caplog.at_level(logging.WARNING, logger=_LOGGER):
        out = jax.jit(flash_attention_sharded)(q, k, v)
    assert out.shape == q.shape
    assert not _warnings(caplog)


def test_indivisible_heads_still_refused(mesh_tp):
    q, k, v = _qkv(batch=4, heads=3)  # 3 % model(2) != 0
    with activate(mesh_tp):
        with pytest.raises(ValueError, match="heads=3 % model=2"):
            flash_attention_sharded(q, k, v)
