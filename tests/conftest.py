"""Test fixtures.

The "cluster in one process" strategy (SURVEY.md §4): the reference tested
multi-task behavior with in-process gRPC servers (create_local_cluster,
test_util.py:4029); we fake an 8-device mesh on CPU with
--xla_force_host_platform_device_count so every pjit/collective path runs in
CI without a TPU. The axon sitecustomize in this image force-selects the TPU
platform, so the override must happen in-process before backend init.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

from dist_mnist_tpu.cluster.mesh import MeshSpec, make_mesh


@pytest.fixture(scope="session", autouse=True)
def _cpu_devices():
    assert jax.device_count() == 8, "tests expect the forced 8-device CPU mesh"


@pytest.fixture(scope="session")
def mesh8():
    """Pure-DP mesh over all 8 fake devices."""
    return make_mesh(MeshSpec(data=8))


@pytest.fixture(scope="session")
def mesh_tp():
    """Hybrid mesh: 4-way data x 2-way model."""
    return make_mesh(MeshSpec(data=4, model=2))


@pytest.fixture(scope="session")
def mesh1():
    """Single-device mesh (data=1) for reference results."""
    return make_mesh(MeshSpec(data=1), devices=jax.devices()[:1])


@pytest.fixture()
def rng():
    return jax.random.PRNGKey(0)


@pytest.fixture(autouse=True)
def _no_leaked_prefetch_workers():
    """Every background resource must be drained by test end: prefetch
    workers (a leak means some path — exception, early close, re-seek —
    skipped the stream drain), fault-injection timer threads (``Fault*``,
    cli/launch.py's chaos kill), elastic grow-drain timers
    (``ElasticGrowTimer``), supervisor child PROCESSES (a live
    child after launch() returned would outlive the test and poison the
    next one's port/coordinator), compile-cache atomic-write temp files
    (compilecache/store.py `_PENDING_TMP` — a pending entry means a save
    path skipped its finally), async snapshot writer threads
    (``SnapshotWriter`` — checkpoint/snapshot.py; alive after a test means
    a manager close/wait path was skipped) and peer-replica atomic-write
    temp files (checkpoint/peer.py `_PENDING_TMP`), tuned-config-store
    atomic-write temp files (tune/store.py `_PENDING_TMP`),
    metrics-exporter
    HTTP threads/sockets
    (``ObsExporter*`` serve threads and obs/exporter.py's
    ``_LIVE_EXPORTERS`` — an unclosed exporter holds a bound port for the
    rest of the session), fleet-router threads/registries (``Router*`` —
    RouterHealth/RouterTimer/RouterWatcher/RouterHttp pools,
    serve/router.py's ``_LIVE_ROUTERS``, and cli/router.py's
    ``_LIVE_REPLICA_PROCS`` subprocess replicas), background zoo-grid
    prewarm threads (``ZooPrewarm`` — serve/server.py's async prewarm must
    be joined by close()), decode-scheduler threads (``DecodeScheduler`` —
    serve/decode.py's continuous-batching loop must be joined by
    close()/drain()), and
    warm-start/coldstart/journal temp dirs
    created OUTSIDE pytest's tmp root (launch()'s supervisor mkdtemp and
    bench.py's coldstart pair dir must clean up after themselves). Polls
    briefly: a worker that JUST saw its stop flag may still be mid-exit
    when the test returns."""
    import sys
    import tempfile
    import threading
    import time
    from pathlib import Path

    from dist_mnist_tpu.data.prefetch import THREAD_NAME_PREFIX

    tmp_root = Path(tempfile.gettempdir())
    _stray_globs = ("dist_mnist_warmstart_*", "bench_coldstart_*",
                    "dist_mnist_journal_*")
    before = {p for g in _stray_globs for p in tmp_root.glob(g)}
    yield
    deadline = time.monotonic() + 2.0
    leaked: list = ["unchecked"]
    while time.monotonic() < deadline:
        leaked = [t.name for t in threading.enumerate()
                  if t.is_alive()
                  and (t.name.startswith(THREAD_NAME_PREFIX)
                       or t.name.startswith("Fault")
                       or t.name.startswith("Elastic")
                       or t.name.startswith("CompileCache")
                       or t.name.startswith("SnapshotWriter")
                       or t.name.startswith("ObsExporter")
                       or t.name.startswith("ZooPrewarm")
                       or t.name.startswith("ServeBatcher")
                       or t.name.startswith("DecodeScheduler")
                       or t.name.startswith("LaunchPump")
                       or t.name.startswith("Autoscaler")
                       or t.name.startswith("Router"))]
        exporter_mod = sys.modules.get("dist_mnist_tpu.obs.exporter")
        if exporter_mod is not None:
            leaked += [f"open exporter port={e.port}"
                       for e in exporter_mod._LIVE_EXPORTERS]
        router_mod = sys.modules.get("dist_mnist_tpu.serve.router")
        if router_mod is not None:
            leaked += [f"open router ({len(router_mod._LIVE_ROUTERS)})"
                       for _ in router_mod._LIVE_ROUTERS]
        cli_router_mod = sys.modules.get("dist_mnist_tpu.cli.router")
        if cli_router_mod is not None:
            leaked += [f"replica pid={p.pid}"
                       for p in cli_router_mod._LIVE_REPLICA_PROCS
                       if p.poll() is None]
        launch_mod = sys.modules.get("dist_mnist_tpu.cli.launch")
        if launch_mod is not None:
            leaked += [f"child pid={p.pid}" for p in launch_mod._LIVE_CHILDREN
                       if p.poll() is None]
        store_mod = sys.modules.get("dist_mnist_tpu.compilecache.store")
        if store_mod is not None:
            leaked += [f"pending cache tmp {p}"
                       for p in store_mod._PENDING_TMP]
        peer_mod = sys.modules.get("dist_mnist_tpu.checkpoint.peer")
        if peer_mod is not None:
            leaked += [f"pending peer tmp {p}"
                       for p in peer_mod._PENDING_TMP]
        tuned_mod = sys.modules.get("dist_mnist_tpu.tune.store")
        if tuned_mod is not None:
            leaked += [f"pending tuned tmp {p}"
                       for p in tuned_mod._PENDING_TMP]
        leaked += [f"stray tmp dir {p}" for g in _stray_globs
                   for p in tmp_root.glob(g) if p not in before]
        if not leaked:
            return
        time.sleep(0.02)
    raise AssertionError(f"leaked background workers/children: {leaked}")


@pytest.fixture(scope="session")
def small_mnist():
    """Small synthetic MNIST so tests stay fast."""
    from dist_mnist_tpu.data.datasets import load_dataset

    return load_dataset("mnist", "/definitely-not-a-dir", seed=0, cache_synthetic=False,
                        synthetic_sizes=(4096, 512))
