"""Tier-1 autoscaler tests: trace-generator determinism and rate-envelope
pins, the ScalePolicy unit matrix (up-triggers, down-hysteresis, cooldowns,
min/max clamps, no-flap), FleetSignalSource merging, the Autoscaler
actuation loop driven through tick() against stubbed routers/sources (no
real replicas, no compiles), and the Router's add/remove_replica membership
seam against a scripted fake replica."""

from __future__ import annotations

import contextlib
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from dist_mnist_tpu.obs import RunJournal
from dist_mnist_tpu.obs import events as events_mod
from dist_mnist_tpu.serve import (
    BEST_EFFORT,
    LATENCY_SENSITIVE,
    Autoscaler,
    FleetSignals,
    FleetSignalSource,
    PolicyState,
    Router,
    RouterConfig,
    ScalePolicy,
    ShuttingDownError,
    burst_trace,
    diurnal_trace,
    flash_crowd_trace,
)

FAST = RouterConfig(health_interval_s=0.02, retry_base_ms=1.0,
                    retry_max_ms=5.0)


@contextlib.contextmanager
def capture_journal(tmp_path):
    """Route ambient events.emit() into a JSONL file for the test."""
    path = tmp_path / "events.jsonl"
    journal = RunJournal(path)
    prev = events_mod.set_journal(journal)
    try:
        yield path
    finally:
        events_mod.set_journal(prev)
        journal.close()


def _kinds(path):
    return [e["event"] for e in events_mod.read_journal(path)]


# -- trace generators: determinism + rate-envelope pins -----------------------
#
# The generators place arrival k where the cumulative rate envelope crosses
# k + u_k (u_k a seeded uniform), so the arrival COUNT is floor(integral of
# the envelope) — a seed-independent closed form the tests pin exactly —
# while the exact offsets are seeded and byte-reproducible.


def test_trace_same_seed_is_byte_identical():
    a = flash_crowd_trace(duration_s=8.0, base_rps=5.0, spike_at_s=2.0,
                          spike_len_s=1.0, spike_mult=10.0, seed=7)
    b = flash_crowd_trace(duration_s=8.0, base_rps=5.0, spike_at_s=2.0,
                          spike_len_s=1.0, spike_mult=10.0, seed=7)
    assert a.tobytes() == b.tobytes()


def test_trace_seed_moves_offsets_not_count():
    a = diurnal_trace(duration_s=10.0, base_rps=5.0, peak_rps=15.0, seed=0)
    b = diurnal_trace(duration_s=10.0, base_rps=5.0, peak_rps=15.0, seed=1)
    assert len(a) == len(b)  # count is a pure function of the envelope
    assert a.tobytes() != b.tobytes()  # but the jitter really is seeded


def test_traces_sorted_and_bounded():
    for arr, dur in [
        (diurnal_trace(duration_s=10.0, base_rps=5.0, peak_rps=15.0), 10.0),
        (burst_trace(duration_s=40.0, base_rps=2.0, burst_rps=10.0,
                     burst_every_s=10.0, burst_len_s=1.0), 40.0),
        (flash_crowd_trace(duration_s=8.0, base_rps=5.0, spike_at_s=2.0,
                           spike_len_s=1.0), 8.0),
    ]:
        assert np.all(np.diff(arr) >= 0.0)
        assert arr[0] >= 0.0 and arr[-1] <= dur


def test_diurnal_rate_envelope_pin():
    # raised cosine, one period: integral = base*T + (peak-base)*T/2
    arr = diurnal_trace(duration_s=10.0, base_rps=5.0, peak_rps=15.0, seed=3)
    assert len(arr) == 100  # 5*10 + 10*10/2 = 100 exactly
    # crest half (middle) must carry more arrivals than the troughs
    mid = np.count_nonzero((arr >= 2.5) & (arr < 7.5))
    assert mid > len(arr) - mid


def test_burst_rate_envelope_pin():
    # 4 periods of (1s @ 10rps + 9s @ 2rps) = 4 * (10 + 18) = 112
    arr = burst_trace(duration_s=40.0, base_rps=2.0, burst_rps=10.0,
                      burst_every_s=10.0, burst_len_s=1.0, seed=0)
    assert abs(len(arr) - 112) <= 1  # trapezoid edges cost < 1 arrival
    in_burst = np.count_nonzero(np.mod(arr, 10.0) < 1.0)
    # 40 of ~112 arrivals land inside the 10% of time that is burst
    assert in_burst >= 35


def test_flash_crowd_rate_envelope_pin():
    # base 5rps * 8s = 40, spike (50-5)*1s = 45... total envelope:
    # 5*8 + 45*1 (plateau) + 45*2/2 (linear decay triangle) = 130
    arr = flash_crowd_trace(duration_s=8.0, base_rps=5.0, spike_at_s=2.0,
                            spike_len_s=1.0, spike_mult=10.0, decay_s=2.0,
                            seed=0)
    assert abs(len(arr) - 130) <= 1
    # the spike window itself runs at peak: ~50 arrivals in [2, 3)
    spike = np.count_nonzero((arr >= 2.0) & (arr < 3.0))
    assert abs(spike - 50) <= 2  # jitter can slide edge arrivals by < 1


# -- ScalePolicy unit matrix --------------------------------------------------


def sig(t, *, n=2, total=None, backlog=0.0, shed=0.0, p99=None):
    return FleetSignals(t=t, serving_replicas=n,
                        total_replicas=total if total is not None else n,
                        backlog_fraction=backlog, be_shed_rate=shed,
                        ls_p99_ms=p99)


def test_policy_up_triggers_and_priority():
    pol = ScalePolicy(min_replicas=1, max_replicas=8, slo_p99_ms=500.0)
    d = pol.decide(sig(0.0, shed=1.0), PolicyState())
    assert (d.action, d.reason, d.target_replicas) == ("up", "be_shedding", 3)
    d = pol.decide(sig(0.0, p99=350.0), PolicyState())
    assert (d.action, d.reason) == ("up", "ls_headroom_collapse")
    d = pol.decide(sig(0.0, backlog=0.5), PolicyState())
    assert (d.action, d.reason) == ("up", "backlog")
    # shedding outranks the other symptoms in the journaled reason
    d = pol.decide(sig(0.0, shed=1.0, p99=499.0, backlog=0.9), PolicyState())
    assert d.reason == "be_shedding"
    # a pre-traffic fleet has no LS p99 yet: None must not trigger
    d = pol.decide(sig(0.0, p99=None), PolicyState())
    assert d.action == "hold" and d.reason == "steady"


def test_policy_max_clamp_and_up_cooldown():
    pol = ScalePolicy(min_replicas=1, max_replicas=4, up_cooldown_s=2.0)
    assert pol.decide(sig(0.0, n=4, shed=5.0), PolicyState()).reason == "at_max"
    st = PolicyState(last_up_t=0.0)
    assert pol.decide(sig(1.9, shed=5.0), st).reason == "up_cooldown"
    assert pol.decide(sig(2.1, shed=5.0), st).action == "up"


def test_policy_down_needs_sustained_idle():
    pol = ScalePolicy(min_replicas=1, max_replicas=8, idle_window_s=5.0,
                      down_cooldown_s=0.0)
    st = PolicyState()
    assert pol.decide(sig(0.0, n=3), st).reason == "steady"  # idle starts
    assert pol.decide(sig(4.9, n=3), st).reason == "steady"  # not yet
    d = pol.decide(sig(5.0, n=3), st)
    assert (d.action, d.reason, d.target_replicas) == (
        "down", "sustained_idle", 2)


def test_policy_busy_sample_resets_idle_clock():
    pol = ScalePolicy(idle_window_s=5.0, down_cooldown_s=0.0)
    st = PolicyState()
    pol.decide(sig(0.0, n=3), st)
    # backlog 0.2: above idle_backlog but below backlog_up -> steady busy
    assert pol.decide(sig(3.0, n=3, backlog=0.2), st).reason == "steady"
    assert st.idle_since is None
    pol.decide(sig(4.0, n=3), st)  # idle clock restarts here
    assert pol.decide(sig(8.0, n=3), st).reason == "steady"
    assert pol.decide(sig(9.1, n=3), st).action == "down"


def test_policy_min_clamp_and_down_cooldowns():
    pol = ScalePolicy(min_replicas=2, idle_window_s=1.0, down_cooldown_s=10.0)
    st = PolicyState()
    pol.decide(sig(0.0, n=2), st)
    assert pol.decide(sig(2.0, n=2), st).reason == "at_min"
    # a recent down blocks the next one
    st = PolicyState(last_down_t=1.0)
    pol.decide(sig(0.0, n=4), st)
    assert pol.decide(sig(2.0, n=4), st).reason == "down_cooldown"
    # fresh capacity: a recent UP also blocks teardown
    st = PolicyState(last_up_t=1.0)
    pol.decide(sig(0.0, n=4), st)
    assert pol.decide(sig(2.0, n=4), st).reason == "down_cooldown"
    st = PolicyState(last_up_t=-100.0, last_down_t=-100.0)
    pol.decide(sig(0.0, n=4), st)
    assert pol.decide(sig(2.0, n=4), st).action == "down"


def test_policy_no_flap_under_oscillating_load():
    """Alternating busy/idle seconds: the idle window never fills, so the
    policy may grow the fleet (cooldown-paced) but NEVER tears it down."""
    pol = ScalePolicy(min_replicas=1, max_replicas=8, idle_window_s=5.0,
                      up_cooldown_s=2.0, down_cooldown_s=10.0)
    st = PolicyState()
    n = 2
    actions = []
    for t in range(60):
        busy = t % 2 == 0
        d = pol.decide(sig(float(t), n=n, shed=2.0 if busy else 0.0), st)
        actions.append((float(t), d.action))
        if d.action == "up":
            st.last_up_t = float(t)  # what the Autoscaler does on actuation
            n = min(n + 1, 8)
    assert all(a != "down" for _, a in actions)
    ups = [t for t, a in actions if a == "up"]
    assert ups, "oscillating shed should still grow the fleet"
    assert all(b - a >= pol.up_cooldown_s for a, b in zip(ups, ups[1:]))


def test_policy_validates_bounds():
    with pytest.raises(ValueError):
        ScalePolicy(min_replicas=0)
    with pytest.raises(ValueError):
        ScalePolicy(min_replicas=4, max_replicas=2)


# -- FleetSignalSource --------------------------------------------------------


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class SourceRouterStub:
    """Just enough Router surface for FleetSignalSource: metrics with a
    BE shed counter + LS p99, replica states, and a backlog fallback."""

    def __init__(self):
        self.shed = 0
        self.p99 = None
        self.states = {0: "serving", 1: "serving"}
        self.backlog = 0.0
        stub = self

        class _Metrics:
            def snapshot(self):
                return {"shed": {BEST_EFFORT: stub.shed,
                                 LATENCY_SENSITIVE: 0}}

            def latency_pct(self, cls, pct):
                assert (cls, pct) == (LATENCY_SENSITIVE, "p99")
                return stub.p99

        self.metrics = _Metrics()

    def replica_states(self):
        return dict(self.states)

    def backlog_fraction(self):
        return self.backlog


def test_signal_source_shed_rate_is_a_delta():
    router = SourceRouterStub()
    clock = FakeClock()
    src = FleetSignalSource(router, clock=clock)
    assert src.signals().be_shed_rate == 0.0  # no previous sample yet
    router.shed += 10
    clock.advance(2.0)
    s = src.signals()
    assert s.be_shed_rate == pytest.approx(5.0)  # 10 sheds / 2s
    clock.advance(1.0)
    assert src.signals().be_shed_rate == 0.0  # counter flat again
    assert s.serving_replicas == 2 and s.total_replicas == 2


def test_signal_source_prefers_scraped_queue_depth():
    router = SourceRouterStub()
    router.backlog = 0.9  # the in-process fallback would say "saturated"
    scraper = SimpleNamespace(
        _lock=threading.Lock(),
        _hosts={"h0": SimpleNamespace(
            reachable=True,
            scalars={"serve_queue_depth": 30.0,
                     "serve_queue_capacity": 100.0})},
        snapshot=lambda: {"hosts": 1})
    src = FleetSignalSource(router, scraper=scraper, clock=FakeClock())
    assert src.signals().backlog_fraction == pytest.approx(0.3)
    # a scraper with no serve gauges yet falls back to the router's view
    scraper._hosts["h0"].scalars = {}
    assert src.signals().backlog_fraction == pytest.approx(0.9)


# -- Autoscaler actuation via tick() -----------------------------------------


class StubReplica:
    def __init__(self, rid):
        self.id = rid
        self.closed = False

    def close(self, timeout=30.0):
        self.closed = True
        return True


class ActuationRouterStub:
    """Membership-seam double: add_replica admits (or refuses), states are
    a plain dict, remove_replica pops and returns the handle."""

    def __init__(self, states=None, admit=True):
        self.states = dict(states if states is not None else {0: "serving"})
        self.handles = {rid: StubReplica(rid) for rid in self.states}
        self.admit = admit
        self.added: list = []
        self.removed: list = []

    def replica_states(self):
        return dict(self.states)

    def add_replica(self, replica, *, wait_serving_s=30.0,
                    probe_interval_s=0.05):
        self.added.append(replica.id)
        if self.admit:
            self.states[replica.id] = "serving"
            self.handles[replica.id] = replica
        return self.admit

    def remove_replica(self, rid, *, quiesce_timeout_s=30.0):
        del self.states[rid]  # KeyError on unknown, matching Router
        return self.handles.pop(rid)


class ScriptedSource:
    """Pops one canned FleetSignals per tick; repeats the last forever."""

    def __init__(self, script):
        self.script = list(script)

    def signals(self):
        return self.script.pop(0) if len(self.script) > 1 else self.script[0]


class StubCache:
    def __init__(self):
        self.s = {"compile_secs": 0.0, "misses": 0,
                  "hits_memory": 0, "hits_disk": 0}

    def stats(self):
        return dict(self.s)


def _spawn_factory(router, cache=None, compile_on_spawn=False):
    """spawn closure exercising the StartupClock contract the CLI/bench
    factories follow: engine build under restore, prewarm under compile."""

    def spawn(rid, startup):
        with startup.phase("restore"):
            replica = StubReplica(rid)
        with startup.phase("compile"):
            if compile_on_spawn and cache is not None:  # a cold cache
                cache.s["misses"] += 1
                cache.s["compile_secs"] += 0.25
            elif cache is not None:
                cache.s["hits_memory"] += 1
        return replica

    return spawn


def test_scale_up_actuates_and_journals_warm_start(tmp_path):
    router = ActuationRouterStub()
    cache = StubCache()
    pol = ScalePolicy(min_replicas=1, max_replicas=4)
    scaler = Autoscaler(router, ScriptedSource([sig(0.0, n=1, shed=2.0)]),
                        _spawn_factory(router, cache), policy=pol,
                        cache=cache, clock=FakeClock())
    with capture_journal(tmp_path) as path:
        d = scaler.tick()
    assert d.action == "up" and router.added == [1]
    assert scaler.scale_ups == 1 and scaler.failed_scale_ups == 0
    assert router.replica_states() == {0: "serving", 1: "serving"}
    kinds = _kinds(path)
    assert "autoscale_decision" in kinds and "replica_scale_up" in kinds
    [receipt] = scaler.history
    assert receipt["replica"] == 1 and receipt["reason"] == "be_shedding"
    # the warm-start promise, as numbers: the spawn hit the shared cache
    assert receipt["cache_misses"] == 0
    assert receipt["cache_compile_ms"] == 0.0
    assert receipt["cache_hits_memory"] == 1
    assert receipt["restore_ms"] >= 0.0 and receipt["compile_ms"] >= 0.0
    # the cooldown stamp lands even on success (attempt-paced)
    assert scaler.state.last_up_t == 0.0


def test_scale_up_cold_cache_shows_in_receipt():
    router = ActuationRouterStub()
    cache = StubCache()
    scaler = Autoscaler(router, ScriptedSource([sig(0.0, n=1, shed=2.0)]),
                        _spawn_factory(router, cache, compile_on_spawn=True),
                        cache=cache, clock=FakeClock())
    scaler.tick()
    [receipt] = scaler.history
    assert receipt["cache_misses"] == 1  # a compiling scale-up is VISIBLE
    assert receipt["cache_compile_ms"] == pytest.approx(250.0)


def test_failed_admission_reaps_and_counts():
    router = ActuationRouterStub(admit=False)
    reaped: list = []
    scaler = Autoscaler(router, ScriptedSource([sig(0.0, n=1, shed=2.0)]),
                        _spawn_factory(router), reap=reaped.append,
                        clock=FakeClock())
    d = scaler.tick()
    assert d.action == "up"  # the decision fired; the actuation failed
    assert scaler.failed_scale_ups == 1 and scaler.scale_ups == 0
    assert [r.id for r in reaped] == [1]
    assert scaler.history == []
    # the cooldown still stamps: a failing spawn is not retried per-tick
    assert scaler.state.last_up_t == 0.0


def test_failed_spawn_survives_and_counts():
    def spawn(rid, startup):
        raise RuntimeError("no capacity at the provider")

    router = ActuationRouterStub()
    scaler = Autoscaler(router, ScriptedSource([sig(0.0, n=1, shed=2.0)]),
                        spawn, clock=FakeClock())
    scaler.tick()  # must not raise
    assert scaler.failed_scale_ups == 1 and router.added == []


def test_scale_down_drains_highest_id_and_reaps(tmp_path):
    router = ActuationRouterStub(
        states={0: "serving", 1: "serving", 2: "serving"})
    reaped: list = []
    pol = ScalePolicy(min_replicas=1, max_replicas=4, idle_window_s=5.0,
                      down_cooldown_s=0.0)
    scaler = Autoscaler(
        router, ScriptedSource([sig(0.0, n=3), sig(6.0, n=3)]),
        _spawn_factory(router), reap=reaped.append, policy=pol,
        clock=FakeClock())
    with capture_journal(tmp_path) as path:
        assert scaler.tick().action == "hold"  # idle clock starts
        d = scaler.tick()
    assert d.action == "down"
    assert router.removed == [] and 2 not in router.replica_states()
    assert [r.id for r in reaped] == [2]  # victim = max serving id
    assert scaler.scale_downs == 1
    assert "replica_scale_down" in _kinds(path)
    assert scaler.history[-1]["replica"] == 2


def test_replica_ids_are_monotonic_never_reused():
    router = ActuationRouterStub(states={0: "serving", 1: "serving"})
    pol = ScalePolicy(min_replicas=1, max_replicas=8, up_cooldown_s=1.0)
    scaler = Autoscaler(
        router,
        ScriptedSource([sig(0.0, n=2, shed=2.0), sig(5.0, n=2, shed=2.0)]),
        _spawn_factory(router), policy=pol, clock=FakeClock())
    scaler.tick()
    assert router.added == [2]
    # the new replica dies and is removed out-of-band...
    router.remove_replica(2)
    scaler.tick()
    # ...but its id is never handed out again (2's down-generation and
    # recovery bookkeeping in the real router must not alias)
    assert router.added == [2, 3]


def test_tick_holds_while_resize_in_flight():
    router = ActuationRouterStub()
    scaler = Autoscaler(router, ScriptedSource([sig(0.0, n=1, shed=2.0)]),
                        _spawn_factory(router), clock=FakeClock())
    assert scaler._resizing.acquire(blocking=False)
    try:
        d = scaler.tick()
    finally:
        scaler._resizing.release()
    assert (d.action, d.reason) == ("hold", "resize_in_flight")
    assert router.added == []  # nothing actuated under the in-flight guard


def test_replica_seconds_integrates_timeline_with_floor():
    clock = FakeClock(30.0)
    scaler = Autoscaler(ActuationRouterStub(), ScriptedSource([sig(0.0)]),
                        _spawn_factory(ActuationRouterStub()), clock=clock)
    scaler.timeline = [(0.0, 1), (10.0, 2), (20.0, 1)]
    assert scaler.replica_seconds(until=30.0) == pytest.approx(40.0)
    assert scaler.replica_seconds(until=30.0, floor=2) == pytest.approx(60.0)
    # until defaults to the live clock
    clock.t = 25.0
    assert scaler.replica_seconds() == pytest.approx(35.0)


def test_snapshot_shape():
    scaler = Autoscaler(ActuationRouterStub(),
                        ScriptedSource([sig(0.0, n=1, shed=2.0)]),
                        _spawn_factory(ActuationRouterStub()),
                        clock=FakeClock())
    snap = scaler.snapshot()
    assert set(snap) == {"ticks", "scale_ups", "scale_downs",
                         "failed_scale_ups", "timeline", "history"}


# -- Router membership seam (scripted replica, no compiles) ------------------


class SeamReplica:
    """Probe-only replica double for the add/remove lifecycle seam."""

    def __init__(self, rid, state="serving"):
        self.id = rid
        self.generation = 0
        self.state = state
        self.quiesced = False
        self.closed = False

    def probe(self):
        return {"state": self.state, "healthy": self.state == "serving",
                "generation": self.generation}

    def quiesce(self, timeout=30.0):
        self.quiesced = True
        return True

    def close(self, timeout=30.0):
        self.closed = True
        return True

    @property
    def queue_depth(self):
        return 0

    @property
    def capacity(self):
        return 10


def test_add_replica_admits_behind_warmup_gate(tmp_path):
    with Router([SeamReplica(0)], FAST) as router:
        with capture_journal(tmp_path) as path:
            assert router.add_replica(SeamReplica(1)) is True
        assert router.replica_states()[1] == "serving"
        assert router.metrics.snapshot()["replica_adds"] == 1
        assert "replica_up" in _kinds(path)


def test_add_replica_rejects_duplicates_and_closed_router():
    router = Router([SeamReplica(0)], FAST).start()
    try:
        with pytest.raises(ValueError):
            router.add_replica(SeamReplica(0))
    finally:
        router.close()
    with pytest.raises(ShuttingDownError):
        router.add_replica(SeamReplica(1))


def test_add_replica_warmup_timeout_withdraws_view():
    with Router([SeamReplica(0)], FAST) as router:
        cold = SeamReplica(1, state="starting")  # never reports healthy
        assert router.add_replica(cold, wait_serving_s=0.2) is False
        assert 1 not in router.replica_states()  # view withdrawn
        assert cold.closed is False  # the caller still owns the handle
        assert router.metrics.snapshot()["replica_ups"] == 0


def test_remove_replica_drains_and_returns_handle(tmp_path):
    r0, r1 = SeamReplica(0), SeamReplica(1)
    with Router([r0, r1], FAST) as router:
        with capture_journal(tmp_path) as path:
            handle = router.remove_replica(1, quiesce_timeout_s=1.0)
        assert handle is r1 and r1.quiesced is True
        assert r1.closed is False  # the router drains, the caller reaps
        assert list(router.replica_states()) == [0]
        snap = router.metrics.snapshot()
        assert snap["replica_removes"] == 1 and snap["replica_drains"] == 1
        assert "replica_drain" in _kinds(path)
        with pytest.raises(KeyError):
            router.remove_replica(7)
