"""Streaming histogram (obs/hist.py): bucket ladder, quantile error
bounds, merge algebra, thread safety, and the representative-values
bridge back to the raw-array writer protocol."""

import math
import threading

import numpy as np
import pytest

from dist_mnist_tpu.obs.hist import StreamingHistogram


def test_empty_snapshot_is_nan():
    h = StreamingHistogram()
    snap = h.snapshot()
    assert snap["count"] == 0
    assert snap["sum"] == 0.0
    for k in ("mean", "min", "max", "p50", "p95", "p99"):
        assert math.isnan(snap[k]), k
    assert math.isnan(h.quantile(0.5))


def test_exact_count_sum_min_max():
    h = StreamingHistogram()
    values = [0.5, 1.0, 2.5, 100.0, 3.7]
    h.observe_many(values)
    assert h.count == len(values)
    assert h.sum == pytest.approx(sum(values))
    snap = h.snapshot()
    assert snap["min"] == 0.5
    assert snap["max"] == 100.0
    assert snap["mean"] == pytest.approx(np.mean(values))


def test_quantiles_within_relative_error_bound():
    # default ladder: 10% bucket growth => <=10% relative quantile error
    rng = np.random.default_rng(0)
    values = rng.lognormal(mean=2.0, sigma=1.0, size=5000)
    h = StreamingHistogram()
    h.observe_many(values)
    for q in (0.5, 0.95, 0.99):
        exact = float(np.quantile(values, q))
        approx = h.quantile(q)
        assert abs(approx - exact) / exact < 0.11, (q, exact, approx)


def test_quantile_clamped_to_observed_range():
    h = StreamingHistogram()
    h.observe_many([5.0] * 100)
    # a single-value distribution: every quantile IS that value, not the
    # bucket's upper edge
    assert h.quantile(0.5) == 5.0
    assert h.quantile(0.99) == 5.0


def test_underflow_and_overflow_values_are_counted():
    h = StreamingHistogram(min_value=1.0, growth=2.0, n_buckets=8)
    h.observe(0.0)       # underflow bucket
    h.observe(-3.0)      # negative -> underflow bucket
    h.observe(1e12)      # overflow bucket
    assert h.count == 3
    snap = h.snapshot()
    assert snap["min"] == -3.0
    assert snap["max"] == 1e12


def test_nan_observations_are_skipped():
    h = StreamingHistogram()
    h.observe(float("nan"))
    h.observe(1.0)
    assert h.count == 1


def test_merge_equivalent_to_combined_stream():
    rng = np.random.default_rng(1)
    a_vals = rng.exponential(10.0, size=500)
    b_vals = rng.exponential(50.0, size=700)
    a, b, both = (StreamingHistogram() for _ in range(3))
    a.observe_many(a_vals)
    b.observe_many(b_vals)
    both.observe_many(np.concatenate([a_vals, b_vals]))
    a.merge(b)
    assert a.count == both.count
    assert a.sum == pytest.approx(both.sum)  # summation order differs
    sa, sb = a.snapshot(), both.snapshot()
    for k in ("count", "min", "max", "p50", "p95", "p99"):
        assert sa[k] == sb[k], k
    assert a.buckets() == both.buckets()


def test_merge_rejects_mismatched_ladder():
    a = StreamingHistogram()
    b = StreamingHistogram(growth=2.0)
    with pytest.raises(ValueError):
        a.merge(b)


def test_bad_ladder_rejected():
    with pytest.raises(ValueError):
        StreamingHistogram(growth=1.0)
    with pytest.raises(ValueError):
        StreamingHistogram(min_value=0.0)
    with pytest.raises(ValueError):
        StreamingHistogram(n_buckets=1)


def test_thread_safety_exact_count():
    h = StreamingHistogram()
    n_threads, per_thread = 8, 2000

    def work(seed):
        rng = np.random.default_rng(seed)
        for v in rng.uniform(0.1, 100.0, size=per_thread):
            h.observe(float(v))

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.count == n_threads * per_thread


def test_representative_values_bounded_and_in_range():
    h = StreamingHistogram()
    rng = np.random.default_rng(2)
    vals = rng.uniform(1.0, 1000.0, size=10_000)
    h.observe_many(vals)
    rep = h.representative_values(cap=512)
    assert 0 < len(rep) <= 512
    assert min(rep) >= h.snapshot()["min"]
    assert max(rep) <= h.snapshot()["max"]
    # the reconstructed sample preserves the distribution's location to
    # within the ladder's resolution
    assert np.median(rep) == pytest.approx(np.median(vals), rel=0.15)


def test_representative_values_empty():
    assert StreamingHistogram().representative_values() == []
