"""End-to-end: the config ladder's minimum slice trains to high accuracy on
the 8-device CPU mesh, checkpoint/resume works through the real driver path."""

import jax
import numpy as np
import pytest

from dist_mnist_tpu.cli.train import run_config
from dist_mnist_tpu.cluster.mesh import MeshSpec
from dist_mnist_tpu.configs import CONFIGS, get_config


def test_config_registry_covers_ladder():
    assert set(CONFIGS) == {
        "mlp_mnist", "lenet5_mnist", "lenet5_fashion",
        "resnet20_cifar", "vit_tiny_cifar", "vit_tiny_cifar_ulysses",
        "vit_tiny_cifar_moe", "vit_tiny_cifar_pp", "vit_tiny_cifar_tp",
        "vit_tiny_cifar_ring", "vit_tiny_cifar_flash",
        "vit_tiny_cifar_ring_flash", "vit_tiny_cifar_ulysses_flash",
        "resnet20_cifar_fsdp", "vit_tiny_cifar_fsdp_tp",
    }
    # every §2.6 strategy is CLI-selectable from the ladder: DP (all),
    # TP, SP-ring, SP-ulysses, EP-moe, PP, ZeRO-fsdp — one config each
    assert CONFIGS["vit_tiny_cifar_tp"].sharding_rules == "tp"
    assert CONFIGS["resnet20_cifar_fsdp"].sharding_rules == "fsdp"
    assert CONFIGS["vit_tiny_cifar_fsdp_tp"].sharding_rules == "fsdp_tp"


@pytest.mark.slow
def test_mlp_mnist_e2e(tmp_path):
    cfg = get_config("mlp_mnist", train_steps=250, eval_every=0)
    state, final, ctx = run_config(cfg, data_dir=str(tmp_path / "data"),
                                   logdir=str(tmp_path / "logs"))
    assert final["accuracy"] >= 0.95  # §7 step 5 bar is 0.97 @ 2000 steps
    assert state.step_int == 250
    assert (tmp_path / "logs" / "metrics.csv").exists()


@pytest.mark.slow
def test_checkpoint_resume_through_driver(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    cfg = get_config("mlp_mnist", train_steps=30, eval_every=0)
    data = str(tmp_path / "data")
    s1, _, _ = run_config(cfg, data_dir=data, checkpoint_dir=ckpt)
    assert s1.step_int == 30
    # "restart": same config, more steps — must resume from 30, not 0
    cfg2 = get_config("mlp_mnist", train_steps=60, eval_every=0)
    s2, _, _ = run_config(cfg2, data_dir=data, checkpoint_dir=ckpt)
    assert s2.step_int == 60


@pytest.mark.slow
@pytest.mark.parametrize("pipeline", ["device", "device_sharded"])
def test_device_input_pipeline_e2e(tmp_path, pipeline):
    """The fused on-device input path through the real driver: dataset in
    HBM (replicated or row-sharded), sampling compiled into the step, no
    host feed — and it still trains to high accuracy."""
    cfg = get_config("mlp_mnist", train_steps=150, eval_every=0)
    state, final, _ = run_config(cfg, data_dir=str(tmp_path / "data"),
                                 input_pipeline=pipeline)
    assert final["accuracy"] >= 0.90
    assert state.step_int == 150


@pytest.mark.slow
def test_scan_chunk_e2e(tmp_path):
    """Bench-grade zero-dispatch training through the real driver: 50-step
    lax.scan chunks, hooks per chunk."""
    cfg = get_config("mlp_mnist", train_steps=150, eval_every=0)
    state, final, _ = run_config(cfg, data_dir=str(tmp_path / "data"),
                                 input_pipeline="device", scan_chunk=50)
    assert final["accuracy"] >= 0.90
    assert state.step_int == 150
    # host batchers cannot feed a compiled multi-step scan
    with pytest.raises(ValueError, match="scan_chunk"):
        run_config(cfg, data_dir=str(tmp_path / "data"), scan_chunk=50)


@pytest.mark.slow
def test_resume_matches_uninterrupted_trajectory(tmp_path):
    """Save at 30, restart, run to 60 — params must equal a straight 60-step
    run. This is STRONGER than the reference could do: the batcher re-seeks
    to the restored step (data/pipeline.at_step), whereas next_batch state
    died with the process and the epoch replayed from scratch (§3.5)."""
    data = str(tmp_path / "data")
    cfg60 = get_config("mlp_mnist", train_steps=60, eval_every=0)
    s_full, _, _ = run_config(cfg60, data_dir=data)

    ckpt = str(tmp_path / "ckpt2")
    cfg30 = get_config("mlp_mnist", train_steps=30, eval_every=0)
    run_config(cfg30, data_dir=data, checkpoint_dir=ckpt)
    s_res, _, _ = run_config(cfg60, data_dir=data, checkpoint_dir=ckpt)

    for a, b in zip(
        jax.tree.leaves(s_full.params), jax.tree.leaves(s_res.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6
        )


@pytest.mark.slow
def test_lenet_fashion_dp4(tmp_path):
    cfg = get_config(
        "lenet5_fashion", train_steps=120, eval_every=0, batch_size=128,
        mesh=MeshSpec(data=4),
    )
    _, final, _ = run_config(cfg, data_dir=str(tmp_path / "data"))
    assert final["accuracy"] >= 0.9


@pytest.mark.slow
def test_resnet20_cifar_smoke(tmp_path):
    """Ladder config 4 builds, shards 8-way, and steps through the real
    driver (BN state threading + cosine/clip/8-way psum all exercised)."""
    cfg = get_config("resnet20_cifar", train_steps=3, batch_size=64,
                     eval_every=0, log_every=1)
    state, final, ctx = run_config(cfg, data_dir=str(tmp_path / "data"))
    assert state.step_int == 3
    assert np.isfinite(final["loss"])
    assert ctx["mesh"].shape["data"] == 8


@pytest.mark.slow
def test_tensor_parallel_config_e2e(tmp_path):
    """The TP ladder config through the real driver on a model=2 mesh:
    Megatron-sharded qkv/mlp weights actually materialize sharded, and the
    run trains."""
    import jax
    from jax.sharding import PartitionSpec as P

    cfg = get_config("vit_tiny_cifar_tp", train_steps=3, batch_size=16,
                     eval_every=0, mesh=MeshSpec(data=4, model=2))
    state, final, ctx = run_config(cfg, data_dir=str(tmp_path / "data"))
    assert state.step_int == 3
    assert np.isfinite(final["loss"])
    qkv = state.params["blocks"]["attn"]["qkv"]["w"]  # stacked [L, D, 3D]
    assert qkv.sharding.spec == P(None, None, "model")
    # materialization, not just the spec string: each device holds HALF the
    # last dim (a replicated array would also have 8 addressable shards,
    # so counting shards alone cannot catch a DP regression)
    assert qkv.addressable_shards[0].data.shape[-1] == qkv.shape[-1] // 2


@pytest.mark.slow
@pytest.mark.parametrize("name, mesh, small_kwargs", [
    # small geometries: the strategy plumbing is what's under test, not
    # the full depth-12 tower (that compile costs minutes on XLA-CPU)
    ("vit_tiny_cifar_ulysses", MeshSpec(data=4, seq=2),
     {"dim": 32, "depth": 2, "heads": 4, "patch": 8}),
    ("vit_tiny_cifar_ring", MeshSpec(data=4, seq=2),
     {"dim": 32, "depth": 2, "heads": 4, "patch": 8}),
    ("vit_tiny_cifar_moe", MeshSpec(data=2, model=4),
     {"dim": 32, "depth": 2, "heads": 4, "patch": 8}),
    ("vit_tiny_cifar_pp", MeshSpec(data=2, pipe=4),
     {"dim": 32, "depth": 4, "heads": 4, "patch": 8}),  # depth % pipe == 0
    # vit_tiny_cifar_flash / _ring_flash are deliberately NOT here: the
    # Pallas INTERPRETER (CPU) makes even the un-remat'd flash backward
    # pathologically slow at driver scale (measured >50 CPU-min at dim
    # 32/batch 16). Flash is covered at unit scale instead:
    # grads-vs-reference, through-ViT fwd/bwd, the flash+remat+scan
    # composition
    # (test_parallel_attention.py::test_flash_composes_with_remat_scan),
    # the ring composition (::test_ring_flash_matches_dense,
    # ::test_ring_flash_through_vit_fwd_bwd), and config plumbing
    # (::test_flash_config_selectable, ::test_ring_flash_config_selectable);
    # the driver paths differ from vit_tiny_cifar(_ring) only by
    # `attention_impl`.
])
def test_strategy_ladder_configs_through_driver(tmp_path, name, mesh,
                                                small_kwargs):
    """Every §2.6 strategy's LADDER CONFIG runs through the real driver
    (run_config), not just its module in isolation: mesh axes come from
    the config, the model kwargs select the strategy, and the run trains
    to a finite loss. (TP has its own sharding-materialization test.)"""
    base_kwargs = CONFIGS[name].model_kwargs
    cfg = get_config(name, train_steps=2, batch_size=16, eval_every=0,
                     mesh=mesh,
                     model_kwargs={**base_kwargs, **small_kwargs})
    state, final, ctx = run_config(cfg, data_dir=str(tmp_path / "data"))
    assert state.step_int == 2
    assert np.isfinite(final["loss"])
    # the strategy's mesh axis is real, not squeezed away
    axis = {"vit_tiny_cifar_ulysses": "seq", "vit_tiny_cifar_ring": "seq",
            "vit_tiny_cifar_moe": "model", "vit_tiny_cifar_pp": "pipe"}[name]
    assert ctx["mesh"].shape[axis] > 1


def test_prng_impl_rbg_trains_and_restores_default(tmp_path):
    """cfg.prng_impl="rbg" (the TPU-fast dropout PRNG) trains through the
    driver, and the process-global default impl is restored afterwards so
    co-resident runs keep threefry."""
    import jax

    prev = jax.config.jax_default_prng_impl
    cfg = get_config("mlp_mnist", train_steps=10, batch_size=32,
                     eval_every=0, prng_impl="rbg")
    state, final, _ = run_config(cfg, data_dir=str(tmp_path / "data"))
    assert state.step_int == 10
    assert np.isfinite(final["loss"])
    assert jax.config.jax_default_prng_impl == prev
