"""FLOPs accounting (utils/flops.py) — the MFU numerator/denominator."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dist_mnist_tpu.utils.flops import device_peak_flops, mfu, step_flops


def test_matmul_flops_exact():
    f = jax.jit(lambda a, b: a @ b)
    x = jnp.ones((64, 32), jnp.float32)
    y = jnp.ones((32, 16), jnp.float32)
    assert step_flops(f, x, y) == 2 * 64 * 32 * 16


def test_scan_body_counted_once():
    """Locks the semantics the bench relies on: a scan chunk's cost equals
    ONE body execution, independent of trip count."""

    def body(c, _):
        return jnp.tanh(c @ c), None

    x = jnp.ones((32, 32), jnp.float32)
    two = step_flops(jax.jit(lambda a: jax.lax.scan(body, a, None, length=2)[0]), x)
    hundred = step_flops(
        jax.jit(lambda a: jax.lax.scan(body, a, None, length=100)[0]), x
    )
    assert two is not None and two == hundred
    # and the loop body dominates: one matmul + tanh, not 100
    assert abs(two - 2 * 32**3) < 0.01 * 2 * 32**3


def test_train_step_wrapper_cost_analysis(mesh1):
    """The _lazy_jit wrapper exposes cost_analysis; the counted FLOPs cover
    at least the analytic matmul floor of the model (fwd+bwd ≈ 3x fwd)."""
    from dist_mnist_tpu import optim
    from dist_mnist_tpu.data.pipeline import shard_batch
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.train import create_train_state, make_train_step

    model = get_model("mlp", hidden_units=100)
    opt = optim.adam(0.01)
    rng = np.random.default_rng(0)
    batch_np = {
        "image": rng.integers(0, 255, (16, 28, 28, 1), dtype=np.uint8),
        "label": rng.integers(0, 10, (16,), dtype=np.int32),
    }
    with mesh1:
        state = create_train_state(model, opt, jax.random.PRNGKey(0),
                                   batch_np["image"][:1])
        step = make_train_step(model, opt, mesh1, donate=False)
        batch = shard_batch(batch_np, mesh1)
        new_state, _ = step(state, batch)
        flops = step_flops(step, new_state, batch)
    # fwd matmul floor: batch x (784x100 + 100x10) MACs x 2; bwd adds at
    # least the dW matmuls (input-layer dx is dead-code-eliminated)
    fwd_floor = 16 * 2 * (784 * 100 + 100 * 10)
    assert flops is not None and flops >= 2 * fwd_floor


def test_cost_analysis_never_executes_or_donates(mesh1):
    """Querying FLOPs on a donate=True step BEFORE its first call must not
    run the step (no donation, no step increment) — lower+compile only."""
    from dist_mnist_tpu import optim
    from dist_mnist_tpu.data.pipeline import shard_batch
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.parallel.sharding import shard_train_state
    from dist_mnist_tpu.train import create_train_state, make_train_step

    model = get_model("mlp", hidden_units=16)
    opt = optim.adam(0.01)
    rng = np.random.default_rng(0)
    batch_np = {
        "image": rng.integers(0, 255, (8, 28, 28, 1), dtype=np.uint8),
        "label": rng.integers(0, 10, (8,), dtype=np.int32),
    }
    with mesh1:
        state = create_train_state(model, opt, jax.random.PRNGKey(0),
                                   batch_np["image"][:1])
        state = shard_train_state(state, mesh1)
        step = make_train_step(model, opt, mesh1, donate=True)
        batch = shard_batch(batch_np, mesh1)
        flops = step_flops(step, state, batch)
        assert flops is not None and flops > 0
        assert not state.params["hid"]["w"].is_deleted()
        new_state, _ = step(state, batch)  # the real first call still works
    assert int(jax.device_get(new_state.step)) == 1


def test_peak_and_mfu():
    class FakeDev:
        device_kind = "TPU v5 lite"

    assert device_peak_flops(FakeDev()) == 197e12
    assert mfu(1.97e12, 0.01, FakeDev()) == 1.0
    assert mfu(None, 0.01, FakeDev()) is None
    assert mfu(1.0, 0.0, FakeDev()) is None

    class Unknown:
        device_kind = "AbacusAccelerator"

    assert mfu(1e9, 0.1, Unknown()) is None  # unknown chip -> null, not a guess


def test_analytic_flops_match_xla_count_for_unscanned_models():
    """The models' published analytic forward FLOPs must agree with XLA's
    compiled-program count (which is trustworthy when no layer-scan is
    involved) to within accounting slop — anchors the analytic numbers
    bench uses as the MFU numerator of record."""
    from dist_mnist_tpu.models import get_model

    for name, shape in (("mlp", (1, 28, 28, 1)), ("lenet5", (1, 28, 28, 1))):
        model = get_model(name, compute_dtype=jnp.float32)
        x = jnp.zeros(shape, jnp.float32)
        params, state = model.init(jax.random.PRNGKey(0), x)
        fwd = jax.jit(lambda p, xx: model.apply(p, state, xx, train=False)[0])
        counted = step_flops(fwd, params, x)
        analytic = model.flops_per_example(shape)
        assert counted is not None
        assert 0.5 < counted / analytic < 1.5, (name, counted, analytic)


def test_vit_scan_blocks_undercounts_but_analytic_does_not():
    """THE bug analytic FLOPs exist to fix: XLA's cost analysis counts the
    ViT layer-scan body once, so the compiled count of a scan_blocks model
    understates the stack by ~depth x, while the unrolled twin (identical
    numerics) matches the analytic figure."""
    from dist_mnist_tpu.models import get_model

    kw = dict(depth=4, dim=32, heads=2, patch=8, dropout_rate=0.0,
              compute_dtype=jnp.float32)
    shape = (1, 32, 32, 3)
    x = jnp.zeros(shape, jnp.float32)

    def counted(model):
        params, state = model.init(jax.random.PRNGKey(0), x)
        fwd = jax.jit(lambda p, xx: model.apply(p, state, xx, train=False)[0])
        return step_flops(fwd, params, x)

    scanned = get_model("vit_tiny", scan_blocks=True, **kw)
    unrolled = get_model("vit_tiny", scan_blocks=False, **kw)
    analytic = scanned.flops_per_example(shape)
    c_scan, c_unroll = counted(scanned), counted(unrolled)
    assert c_scan is not None and c_unroll is not None
    # unrolled agrees with analytic; scanned is short by ~depth x
    assert 0.5 < c_unroll / analytic < 1.5, (c_unroll, analytic)
    assert c_scan < 0.5 * analytic, (c_scan, analytic)


def test_analytic_step_flops_convention():
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.utils.flops import analytic_step_flops

    model = get_model("mlp", hidden_units=100)
    shape = (1, 28, 28, 1)
    per_ex = model.flops_per_example(shape)
    assert per_ex == 2 * (784 * 100 + 100 * 10)
    # step = batch x (fwd + 2x bwd)
    assert analytic_step_flops(model, shape, 64) == 64 * 3 * per_ex
    # models without a published count -> None (callers fall back to XLA)
    class Bare: ...
    assert analytic_step_flops(Bare(), shape, 64) is None


@pytest.mark.slow  # the CIFAR ResNet fwd compile costs ~10 s on XLA-CPU
def test_resnet_analytic_flops_match_xla_count():
    """ResNet-20 has the most error-prone analytic formula (strides,
    downsample projections) and feeds the published resnet20_cifar MFU —
    pin it to XLA's count like the other models."""
    from dist_mnist_tpu.models import get_model

    model = get_model("resnet20", compute_dtype=jnp.float32)
    shape = (1, 32, 32, 3)
    x = jnp.zeros(shape, jnp.float32)
    params, state = model.init(jax.random.PRNGKey(0), x)
    fwd = jax.jit(lambda p, xx: model.apply(p, state, xx, train=False)[0])
    counted = step_flops(fwd, params, x)
    analytic = model.flops_per_example(shape)
    assert counted is not None
    assert 0.5 < counted / analytic < 1.5, (counted, analytic)
