"""Tier-1 wiring for scripts/check_host_sync.py (ISSUE 3 satellite).

Running the lint as a test makes the hot-path sync surface a CI invariant:
a stray `float(device_scalar)` / `.item()` / per-key `device_get` in
train/, data/prefetch.py, or hooks/builtin.py fails the suite unless it
carries a reviewable `# host-sync-ok: <why>` annotation.
"""

import importlib.util
import sys
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
SCRIPT = REPO_ROOT / "scripts" / "check_host_sync.py"


@pytest.fixture(scope="module")
def lint():
    spec = importlib.util.spec_from_file_location("check_host_sync", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("check_host_sync", mod)
    spec.loader.exec_module(mod)
    return mod


def test_hot_paths_are_clean(lint, capsys):
    """THE gate: the shipped hot-path modules carry no unannotated syncs."""
    targets = lint.default_targets(REPO_ROOT)
    assert targets, "lint found no hot-path modules — wiring broke"
    names = {t.name for t in targets}
    assert {"step.py", "state.py", "prefetch.py", "builtin.py"} <= names
    rc = lint.main([])
    out = capsys.readouterr()
    assert rc == 0, f"host-sync violations in hot paths:\n{out.out}"


def test_detects_each_sync_construct(lint, tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n"
        "def f(x, arr):\n"
        "    a = float(x)\n"                      # bare float()
        "    b = jax.device_get(x)\n"             # attribute-qualified
        "    c = device_get(x)\n"                 # bare
        "    d = arr.item()\n"                    # method .item()
        "    return a, b, c, d\n"
    )
    violations = lint.scan_file(bad)
    assert [ln for ln, _ in violations] == [3, 4, 5, 6]
    assert all("host-sync-ok" in msg for _, msg in violations)


def test_allowlist_marker_blesses_line_and_next(lint, tmp_path):
    ok = tmp_path / "ok.py"
    ok.write_text(
        "import jax\n"
        "def f(x):\n"
        "    a = float(x)  # host-sync-ok: test fixture\n"
        "    # host-sync-ok: marker-above style\n"
        "    b = jax.device_get(x)\n"
        "    return a, b\n"
    )
    assert lint.scan_file(ok) == []


def test_marker_two_lines_above_does_not_bless(lint, tmp_path):
    far = tmp_path / "far.py"
    far.write_text(
        "def f(x):\n"
        "    # host-sync-ok: too far away\n"
        "    y = 1\n"
        "    return float(x)\n"
    )
    assert [ln for ln, _ in lint.scan_file(far)] == [4]


def test_comments_and_strings_do_not_count(lint, tmp_path):
    doc = tmp_path / "doc.py"
    doc.write_text(
        '"""This module once called float(x) and arr.item() per step."""\n'
        "def f():\n"
        "    # the old code did device_get(scalar) here\n"
        "    s = 'float(x)'\n"
        "    return s\n"
    )
    assert lint.scan_file(doc) == []


def test_non_sync_lookalikes_pass(lint, tmp_path):
    ok = tmp_path / "lookalike.py"
    ok.write_text(
        "def f(t, x):\n"
        "    a = t.float()\n"          # torch-style method, not builtin float(
        "    b = item(x)\n"            # bare item() is some other function
        "    c = x.astype(float)\n"    # float as a name, no call
        "    return a, b, c\n"
    )
    assert lint.scan_file(ok) == []


def test_main_reports_path_and_line(lint, tmp_path, capsys):
    bad = tmp_path / "bad2.py"
    bad.write_text("def f(x):\n    return x.item()\n")
    rc = lint.main([str(bad)])
    out = capsys.readouterr()
    assert rc == 1
    assert f"{bad}:2:" in out.out
