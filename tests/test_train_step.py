"""The compiled SPMD step: correctness of the implicit all-reduce
(DP result == single-device result), donation, metrics, eval."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dist_mnist_tpu import optim
from dist_mnist_tpu.data.pipeline import shard_batch
from dist_mnist_tpu.models import get_model
from dist_mnist_tpu.parallel.sharding import shard_train_state
from dist_mnist_tpu.train import (
    create_train_state,
    evaluate,
    make_eval_step,
    make_train_step,
)


def _setup(mesh, batch=32, seed=0):
    model = get_model("mlp", hidden_units=32)
    opt = optim.adam(0.01)
    rng = np.random.default_rng(seed)
    batch_np = {
        "image": rng.integers(0, 255, (batch, 28, 28, 1), dtype=np.uint8),
        "label": rng.integers(0, 10, (batch,), dtype=np.int32),
    }
    with mesh:
        state = create_train_state(model, opt, jax.random.PRNGKey(seed),
                                   batch_np["image"][:1])
        state = shard_train_state(state, mesh)
        step = make_train_step(model, opt, mesh, donate=False)
        dev_batch = shard_batch(batch_np, mesh)
    return model, opt, state, step, dev_batch, batch_np


def test_remat_policies_identical_numerics(mesh8):
    """Every named remat policy (and remat off) yields the same params
    after a step — policies trade recompute for memory, never numerics.
    Exercises the save_attn policy's checkpoint_name tag end-to-end."""
    from dist_mnist_tpu.cluster.mesh import activate
    from dist_mnist_tpu.train.step import REMAT_POLICIES

    model = get_model("vit_tiny", depth=2, dim=32, heads=4, patch=8,
                      pool="mean", dropout_rate=0.0,
                      compute_dtype=jnp.float32)
    opt = optim.adam(1e-3)
    rng = np.random.default_rng(9)
    batch_np = {
        "image": rng.integers(0, 255, (16, 32, 32, 3), dtype=np.uint8),
        "label": rng.integers(0, 10, (16,), dtype=np.int32),
    }
    results = {}
    for name in ("off", *REMAT_POLICIES):
        with activate(mesh8):
            state = create_train_state(model, opt, jax.random.PRNGKey(0),
                                       batch_np["image"][:1])
            state = shard_train_state(state, mesh8)
            step = make_train_step(model, opt, mesh8, donate=False,
                                   remat=name != "off",
                                   remat_policy=name if name != "off"
                                   else "dots_no_batch")
            new_state, out = step(state, shard_batch(batch_np, mesh8))
        results[name] = (float(out["loss"]),
                         np.asarray(new_state.params["head"]["w"]))
    base_loss, base_w = results["off"]
    for name, (loss, w) in results.items():
        np.testing.assert_allclose(loss, base_loss, rtol=1e-6, err_msg=name)
        np.testing.assert_allclose(w, base_w, rtol=1e-5, atol=1e-7,
                                   err_msg=name)
    with pytest.raises(ValueError, match="unknown remat_policy"):
        from dist_mnist_tpu.train.step import resolve_remat_policy

        resolve_remat_policy("bogus")


def test_save_attn_policy_saves_attention_residual():
    """Under save_attn the checkpoint_name("attn_out")-tagged attention
    output is an actually-SAVED residual (jax.ad_checkpoint.saved_residuals
    — the ground truth for what remat keeps), and under dots_no_batch it is
    not: the policy difference is real, not just named."""
    # not re-exported from jax.ad_checkpoint in this jax version (only
    # print_saved_residuals is); pinned-env test, private import ok
    from jax._src.ad_checkpoint import saved_residuals

    from dist_mnist_tpu.models import get_model as gm
    from dist_mnist_tpu.ops.losses import softmax_cross_entropy
    from dist_mnist_tpu.train.step import REMAT_POLICIES

    model = gm("vit_tiny", depth=1, dim=64, heads=4, patch=8, pool="mean",
               dropout_rate=0.0, compute_dtype=jnp.float32,
               scan_blocks=False)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(2, 32, 32, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, (2,)), jnp.int32)
    params, state = model.init(jax.random.PRNGKey(0), x)

    def fwd(p):
        logits, _ = model.apply(p, state, x, train=False)
        return softmax_cross_entropy(logits, y)

    def saved_from_attention(policy):
        res = saved_residuals(jax.checkpoint(fwd, policy=policy), params)
        return any("dot_product_attention" in str(src) for _, src in res)

    assert saved_from_attention(REMAT_POLICIES["save_attn"])
    assert not saved_from_attention(REMAT_POLICIES["dots_no_batch"])


def test_model_state_metric_contract(mesh8):
    """`_metric` entries of model_state surface as step outputs with the
    suffix stripped — the MoE routing-health channel (train/step.py)."""
    from dist_mnist_tpu.cluster.mesh import activate

    model = get_model("vit_tiny", depth=1, dim=32, heads=4, patch=8,
                      pool="mean", mlp_impl="moe", n_experts=2,
                      moe_capacity_factor=8.0, compute_dtype=jnp.float32)
    opt = optim.adam(1e-3)
    rng = np.random.default_rng(5)
    batch_np = {
        "image": rng.integers(0, 255, (16, 32, 32, 3), dtype=np.uint8),
        "label": rng.integers(0, 10, (16,), dtype=np.int32),
    }
    with activate(mesh8):
        state = create_train_state(model, opt, jax.random.PRNGKey(0),
                                   batch_np["image"][:1])
        state = shard_train_state(state, mesh8)
        step = make_train_step(model, opt, mesh8, donate=False)
        _, out = step(state, shard_batch(batch_np, mesh8))
    assert 0.0 <= float(out["moe_drop_fraction"]) <= 1.0
    assert out["moe_expert_load"].shape == (2,)
    # generous capacity -> nothing dropped, and the metric says so
    assert float(out["moe_drop_fraction"]) == 0.0


def test_loss_decreases(mesh8):
    _, _, state, step, batch, _ = _setup(mesh8)
    with mesh8:
        losses = []
        for _ in range(20):
            state, out = step(state, batch)
            losses.append(float(out["loss"]))
    assert losses[-1] < losses[0] * 0.5
    assert int(state.step_int) == 20


@pytest.mark.slow
def test_dp_matches_single_device(mesh8, mesh1):
    """8-way data-parallel must equal 1-device training on the same global
    batch — the correctness contract of replacing the PS push/pull with the
    in-step all-reduce (SURVEY.md §2.6 row 'DP sync')."""
    _, _, s8, step8, b8, batch_np = _setup(mesh8)
    _, _, s1, step1, _, _ = _setup(mesh1)
    with mesh1:
        b1 = shard_batch(batch_np, mesh1)
    for _ in range(5):
        with mesh8:
            s8, o8 = step8(s8, b8)
        with mesh1:
            s1, o1 = step1(s1, b1)
    np.testing.assert_allclose(float(o8["loss"]), float(o1["loss"]),
                               rtol=2e-5, atol=1e-6)
    w8 = np.asarray(s8.params["hid"]["w"])
    w1 = np.asarray(s1.params["hid"]["w"])
    np.testing.assert_allclose(w8, w1, rtol=2e-4, atol=2e-6)


def test_with_grad_norm_outputs(mesh8):
    """with_grad_norm emits the scalar global norm AND the per-leaf norm
    vector (SummaryHook histograms the latter)."""
    model = get_model("mlp", hidden_units=32)
    opt = optim.adam(0.01)
    rng = np.random.default_rng(0)
    batch_np = {
        "image": rng.integers(0, 255, (32, 28, 28, 1), dtype=np.uint8),
        "label": rng.integers(0, 10, (32,), dtype=np.int32),
    }
    with mesh8:
        state = create_train_state(model, opt, jax.random.PRNGKey(0),
                                   batch_np["image"][:1])
        state = shard_train_state(state, mesh8)
        step = make_train_step(model, opt, mesh8, donate=False,
                               with_grad_norm=True)
        _, out = step(state, shard_batch(batch_np, mesh8))
    n_leaves = len(jax.tree.leaves(state.params))
    assert out["grad_norm"].shape == ()
    assert out["grad_norms"].shape == (n_leaves,)
    # the vector and the scalar agree: ||g|| = sqrt(sum per-leaf ||g_i||^2)
    np.testing.assert_allclose(
        float(out["grad_norm"]),
        float(jnp.sqrt(jnp.sum(out["grad_norms"] ** 2))),
        rtol=1e-5,
    )


def test_metrics_replicated_scalars(mesh8):
    _, _, state, step, batch, _ = _setup(mesh8)
    with mesh8:
        _, out = step(state, batch)
    assert out["loss"].shape == ()
    assert out["accuracy"].shape == ()
    assert 0.0 <= float(out["accuracy"]) <= 1.0


def test_donation(mesh8):
    model = get_model("mlp", hidden_units=32)
    opt = optim.adam(0.01)
    rng = np.random.default_rng(0)
    batch_np = {
        "image": rng.integers(0, 255, (32, 28, 28, 1), dtype=np.uint8),
        "label": rng.integers(0, 10, (32,), dtype=np.int32),
    }
    with mesh8:
        state = create_train_state(model, opt, jax.random.PRNGKey(0),
                                   batch_np["image"][:1])
        state = shard_train_state(state, mesh8)
        step = make_train_step(model, opt, mesh8, donate=True)
        batch = shard_batch(batch_np, mesh8)
        new_state, _ = step(state, batch)
    # the old state's buffers were donated into the new state
    assert state.params["hid"]["w"].is_deleted()
    assert not new_state.params["hid"]["w"].is_deleted()


def test_evaluate_full_set_with_padding(mesh8, small_mnist):
    model = get_model("mlp", hidden_units=32)
    opt = optim.adam(0.01)
    with mesh8:
        state = create_train_state(
            model, opt, jax.random.PRNGKey(0), small_mnist.train_images[:1]
        )
        state = shard_train_state(state, mesh8)
        eval_step = make_eval_step(model, mesh8)
        # 512 test rows, batch 200 -> tail of 112 exercises the pad/mask path
        res = evaluate(eval_step, state, small_mnist.test_images,
                       small_mnist.test_labels, mesh8, batch_size=200)
    assert res["n"] == 512
    assert 0.0 <= res["accuracy"] <= 1.0
    # untrained model ≈ chance; padding bug would skew this wildly
    assert res["loss"] > 1.0


def test_evaluate_syncs_host_once(mesh8, small_mnist, monkeypatch):
    """evaluate() must sync the host exactly ONCE for the whole pass — the
    per-batch float() sync was an ~8 ms host round-trip per batch on the
    relay backend (VERDICT r3 weak 8). 512 rows / batch 128 = 4 batches,
    still one fetch. Guards BOTH channels: explicit jax.device_get calls
    (counted == 1) and implicit per-batch scalar conversions (ArrayImpl
    __float__/__int__/__bool__ — counted == 0: the final dict conversions
    act on the already-fetched numpy values, not device arrays)."""
    from jax._src.array import ArrayImpl  # pinned-env test: private ok

    model = get_model("mlp", hidden_units=32)
    opt = optim.adam(0.01)
    gets, converts = [], []
    real_get = jax.device_get
    monkeypatch.setattr(jax, "device_get",
                        lambda *a, **k: (gets.append(1), real_get(*a, **k))[1])
    for dunder in ("__float__", "__int__", "__bool__"):
        real = getattr(ArrayImpl, dunder)
        monkeypatch.setattr(
            ArrayImpl, dunder,
            (lambda real: lambda self: (converts.append(1), real(self))[1])(real),
        )
    with mesh8:
        state = create_train_state(
            model, opt, jax.random.PRNGKey(0), small_mnist.train_images[:1]
        )
        state = shard_train_state(state, mesh8)
        eval_step = make_eval_step(model, mesh8)
        gets.clear()
        converts.clear()
        evaluate(eval_step, state, small_mnist.test_images,
                 small_mnist.test_labels, mesh8, batch_size=128)
    assert len(gets) == 1, gets
    assert len(converts) == 0, f"{len(converts)} implicit device->host syncs"


def test_clipped_loss_parity_path(mesh8):
    """The reference loss (clipped CE) trains too (config 1 uses it)."""
    from dist_mnist_tpu.ops import losses

    model = get_model("mlp", hidden_units=32)
    opt = optim.adam(0.01)
    rng = np.random.default_rng(0)
    batch_np = {
        "image": rng.integers(0, 255, (64, 28, 28, 1), dtype=np.uint8),
        "label": rng.integers(0, 10, (64,), dtype=np.int32),
    }
    with mesh8:
        state = create_train_state(model, opt, jax.random.PRNGKey(0),
                                   batch_np["image"][:1])
        state = shard_train_state(state, mesh8)
        step = make_train_step(model, opt, mesh8,
                               loss_fn=losses.clipped_softmax_cross_entropy,
                               donate=False)
        batch = shard_batch(batch_np, mesh8)
        first = last = None
        for _ in range(10):
            state, out = step(state, batch)
            last = float(out["loss"])
            first = first if first is not None else last
    assert last < first


def test_fused_train_step(mesh8, small_mnist):
    """Input pipeline fused into the compiled step: loss decreases with
    zero host-side batching."""
    from dist_mnist_tpu.data.pipeline import DeviceDataset
    from dist_mnist_tpu.train.step import make_fused_train_step

    model = get_model("mlp", hidden_units=32)
    opt = optim.adam(0.01)
    with mesh8:
        state = create_train_state(model, opt, jax.random.PRNGKey(0),
                                   small_mnist.train_images[:1])
        state = shard_train_state(state, mesh8)
        dd = DeviceDataset(small_mnist, mesh8)
        step = make_fused_train_step(model, opt, mesh8, dd, 64)
        losses = []
        for _ in range(30):
            state, out = step(state)
            losses.append(float(out["loss"]))
    assert losses[-1] < losses[0] * 0.5
    assert state.step_int == 30


def test_malformed_batch_rejected_at_trace_time(mesh8, small_mnist):
    """§5.2 structural guards: a wrong-rank / wrong-dtype batch fails at
    trace time with a chex error, not with a silent broadcast."""
    from dist_mnist_tpu import optim
    from dist_mnist_tpu.train import create_train_state, make_train_step

    model = get_model("mlp")
    opt = optim.adam(1e-3)
    with mesh8:
        state = create_train_state(model, opt, jax.random.PRNGKey(0),
                                   small_mnist.train_images[:1])
        step = make_train_step(model, opt, mesh8, donate=False)
        imgs = small_mnist.train_images[:8]
        with pytest.raises(AssertionError):
            step(state, {"image": imgs.reshape(8, -1),  # rank 2, not NHWC
                         "label": small_mnist.train_labels[:8]})
        with pytest.raises(AssertionError):
            step(state, {"image": imgs,
                         "label": small_mnist.train_labels[:8].astype("float32")})


@pytest.mark.slow
def test_remat_matches_plain(mesh8, small_mnist):
    """jax.checkpoint must change memory, never math: one step with and
    without remat produces identical params (same rng paths)."""
    from dist_mnist_tpu import optim
    from dist_mnist_tpu.train import create_train_state, make_train_step

    model = get_model("lenet5")
    opt = optim.adam(1e-3)
    batch = shard_batch(
        {"image": small_mnist.train_images[:16],
         "label": small_mnist.train_labels[:16]}, mesh8,
    )
    outs = {}
    for name, remat in [("plain", False), ("remat", True)]:
        with mesh8:
            state = create_train_state(model, opt, jax.random.PRNGKey(0),
                                       small_mnist.train_images[:1])
            step = make_train_step(model, opt, mesh8, donate=False,
                                   remat=remat)
            new_state, out = step(state, batch)
            outs[name] = (new_state, out)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6),
        outs["plain"][0].params, outs["remat"][0].params,
    )
    np.testing.assert_allclose(float(outs["plain"][1]["loss"]),
                               float(outs["remat"][1]["loss"]), rtol=1e-6)
