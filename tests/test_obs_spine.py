"""Live telemetry spine: registry, Prometheus/healthz/events exposition,
health state machine, train-loop wiring, tag hygiene, and the
supervisor's shared run journal."""

import io
import itertools
import json
import re
import socket
import sys
import textwrap
import urllib.error
import urllib.request

import jax.numpy as jnp
import pytest

from dist_mnist_tpu.obs import events
from dist_mnist_tpu.obs.events import RunJournal, read_journal
from dist_mnist_tpu.obs.exporter import (
    HealthState,
    MetricsExporter,
    _prom_name,
    render_prometheus,
)
from dist_mnist_tpu.obs.hist import StreamingHistogram
from dist_mnist_tpu.obs.registry import MetricRegistry, RegistryWriter
from dist_mnist_tpu.obs.writers import make_default_writer
from dist_mnist_tpu.train.loop import PreemptionError, TrainLoop
from dist_mnist_tpu.train.state import TrainState

#: the repo-wide tag convention (docs/OBSERVABILITY.md): lowercase
#: namespaced paths, so Prometheus mangling is lossless modulo '/' and '.'
TAG_RE = re.compile(r"^[a-z0-9_/.]+$")


@pytest.fixture(autouse=True)
def _no_ambient_journal():
    prev = events.set_journal(None)
    yield
    events.set_journal(prev)


def _get(url, timeout=10):
    """(status, body) for a GET, without raising on 4xx/5xx."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _state(step=0):
    return TrainState(
        step=jnp.int32(step), params={}, model_state={}, opt_state={},
        rng=jnp.zeros((2,), jnp.uint32),
    )


def _fake_step(state, batch):
    return (
        TrainState(step=state.step + 1, params=state.params,
                   model_state=state.model_state, opt_state=state.opt_state,
                   rng=state.rng),
        {"loss": jnp.float32(batch)},
    )


# -- registry -----------------------------------------------------------------

def test_registry_writer_feeds_registry():
    reg = MetricRegistry()
    w = RegistryWriter(reg)
    w.scalar("goodput/fraction", 0.9, step=10)
    w.scalars({"input/stall_ms": 1.5, "steps_per_sec": 120.0}, step=20)
    w.histogram("serve/batch_size", [1, 2, 4, 8], step=20)
    w.flush()
    scalars = reg.scalars()
    assert scalars["goodput/fraction"] == (pytest.approx(0.9), 10,
                                           pytest.approx(scalars[
                                               "goodput/fraction"][2]))
    assert scalars["input/stall_ms"][0] == 1.5
    assert scalars["steps_per_sec"][1] == 20
    assert reg.histograms()["serve/batch_size"].count == 4
    snap = reg.snapshot()
    assert snap["scalars"]["steps_per_sec"] == 120.0
    assert snap["histograms"]["serve/batch_size"]["count"] == 4
    assert reg.tags() == sorted(
        ["goodput/fraction", "input/stall_ms", "steps_per_sec",
         "serve/batch_size"])


def test_registry_attach_histogram_live_reference():
    reg = MetricRegistry()
    h = StreamingHistogram()
    reg.attach_histogram("train/step_time_ms", h)
    h.observe(5.0)  # owner writes AFTER attach; registry sees it (by ref)
    assert reg.histograms()["train/step_time_ms"].count == 1


def test_make_default_writer_registry_every_process(tmp_path):
    # chief: registry rides alongside the disk sinks
    reg = MetricRegistry()
    w = make_default_writer(str(tmp_path), chief=True, registry=reg)
    w.scalar("loss", 1.25, step=1)
    w.flush()
    w.close()
    assert reg.scalars()["loss"][0] == 1.25
    assert (tmp_path / "metrics.csv").exists()
    # non-chief: NO files, but the local registry still fills (each
    # process's /metrics serves its own numbers)
    reg2 = MetricRegistry()
    out2 = tmp_path / "nonchief"
    out2.mkdir()
    w2 = make_default_writer(str(out2), chief=False, registry=reg2)
    w2.scalar("loss", 2.5, step=1)
    w2.flush()
    w2.close()
    assert reg2.scalars()["loss"][0] == 2.5
    assert not list(out2.iterdir())


# -- prometheus rendering -----------------------------------------------------

#: valid exposition lines: HELP/TYPE comments, or `name[{labels}] value`
_PROM_LINE = re.compile(
    r"^(# (TYPE|HELP) [a-zA-Z_:][a-zA-Z0-9_:]* .*"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? \S+)$")


def test_render_prometheus_is_valid_text():
    reg = MetricRegistry()
    reg.set_scalar("goodput/fraction", 0.875, step=5)
    reg.set_scalar("serve/queue_depth", 3, step=5)
    h = StreamingHistogram()
    h.observe_many([0.5, 1.0, 5.0, 1e12])  # incl. an overflow-bucket value
    reg.attach_histogram("train/step_time_ms", h)
    body = render_prometheus(reg, HealthState("training"))
    lines = body.strip().splitlines()
    for line in lines:
        assert _PROM_LINE.match(line), f"invalid exposition line: {line!r}"
    assert "goodput_fraction 0.875" in lines
    # histogram: cumulative buckets, exactly one +Inf, sum+count present
    bucket_lines = [l for l in lines
                    if l.startswith("train_step_time_ms_bucket")]
    cums = [int(l.rsplit(" ", 1)[1]) for l in bucket_lines]
    assert cums == sorted(cums), "bucket counts must be cumulative"
    assert cums[-1] == h.count
    assert sum('le="+Inf"' in l for l in bucket_lines) == 1
    assert any(l.startswith("train_step_time_ms_sum ") for l in lines)
    assert "train_step_time_ms_count 4" in lines
    # health gauges
    assert "process_healthy 1" in lines
    assert 'process_state{state="training"} 1' in lines
    assert 'process_state{state="failed"} 0' in lines


def test_prom_name_mangling_is_total():
    for ugly in ("serve/latency_ms", "a.b-c d", "9starts_with_digit", "", "é"):
        name = _prom_name(ugly)
        assert re.match(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$", name), (ugly, name)


# -- health state machine -----------------------------------------------------

def test_health_state_machine():
    h = HealthState()
    assert h.state == "starting" and h.healthy
    h.set("training")
    assert h.healthy
    h.set("draining", "shutdown requested")
    assert not h.healthy
    snap = h.snapshot()
    assert snap["state"] == "draining"
    assert snap["detail"] == "shutdown requested"
    assert snap["since_s"] >= 0
    with pytest.raises(ValueError):
        h.set("confused")


# -- exporter http endpoints --------------------------------------------------

def test_exporter_endpoints(tmp_path):
    reg = MetricRegistry()
    reg.set_scalar("goodput/fraction", 1.0, step=1)
    health = HealthState("training", generation=2)
    jpath = tmp_path / "j.jsonl"
    with RunJournal(jpath) as j:
        for i in range(5):
            j.emit("checkpoint_save", step=i)
    with MetricsExporter(reg, health=health, journal_path=str(jpath),
                         port=0) as exp:
        assert exp.port > 0  # ephemeral port was resolved
        code, body = _get(exp.url("/metrics"))
        assert code == 200
        assert "goodput_fraction 1.0" in body
        code, body = _get(exp.url("/healthz"))
        assert code == 200
        snap = json.loads(body)
        assert snap["state"] == "training" and snap["generation"] == 2
        # unhealthy states flip to 503 so a router can react
        health.set("draining")
        code, body = _get(exp.url("/healthz"))
        assert code == 503
        assert json.loads(body)["state"] == "draining"
        # journal tail as NDJSON, bounded by ?n=
        code, body = _get(exp.url("/events?n=2"))
        assert code == 200
        recs = [json.loads(l) for l in body.strip().splitlines()]
        assert [r["step"] for r in recs] == [3, 4]
        code, _ = _get(exp.url("/nope"))
        assert code == 404
    # context-manager close: thread + socket gone (conftest leak-check
    # double-covers this)
    from dist_mnist_tpu.obs.exporter import _LIVE_EXPORTERS
    assert exp not in _LIVE_EXPORTERS


def test_exporter_events_without_journal():
    with MetricsExporter(MetricRegistry(), port=0) as exp:
        code, body = _get(exp.url("/events"))
        assert code == 404
        assert "no journal" in body
        # /healthz without a HealthState: 200 "unknown" (exposition-only
        # processes still answer liveness probes)
        code, body = _get(exp.url("/healthz"))
        assert code == 200
        assert json.loads(body)["state"] == "unknown"


def test_exporter_bind_conflict_raises_oserror():
    with MetricsExporter(MetricRegistry(), port=0) as exp:
        with pytest.raises(OSError):
            MetricsExporter(MetricRegistry(), port=exp.port).start()


# -- train loop wiring --------------------------------------------------------

def test_loop_health_transitions_clean_run():
    health = HealthState()
    seen = []

    class Watch:
        def begin(self, loop):
            pass

        def before_step(self, step):
            pass

        def after_step(self, step, state, outputs):
            seen.append(health.state)

        def end(self, state):
            pass

    from dist_mnist_tpu.hooks import StopAtStepHook

    loop = TrainLoop(_fake_step, _state(), itertools.repeat(1.0),
                     [Watch(), StopAtStepHook(last_step=3)], health=health)
    loop.run()
    assert seen == ["training"] * 3
    assert health.state == "stopped"
    assert health.snapshot()["detail"] == "reached last step"


def test_loop_health_failed_on_error():
    def bad_step(state, batch):
        raise RuntimeError("boom")

    health = HealthState()
    loop = TrainLoop(bad_step, _state(), itertools.repeat(1.0), [],
                     health=health)
    with pytest.raises(RuntimeError):
        loop.run()
    assert health.state == "failed"


def test_loop_health_preempted_and_journal(tmp_path):
    class Notice:
        reason = "spot reclaim"
        _hits = 0

        def requested(self):
            Notice._hits += 1
            return Notice._hits > 3  # preempt before the 4th step

    class MemCkpt:
        saved = None

        def save(self, state):
            MemCkpt.saved = state

        def wait(self):
            pass

        def restore(self, target):
            return MemCkpt.saved

    from dist_mnist_tpu.hooks import StopAtStepHook

    health = HealthState()
    jpath = tmp_path / "j.jsonl"
    prev = events.set_journal(RunJournal(jpath))
    try:
        loop = TrainLoop(_fake_step, _state(), itertools.repeat(1.0),
                         [StopAtStepHook(last_step=100)],
                         checkpoint_manager=MemCkpt(), preemption=Notice(),
                         health=health)
        final = loop.run()
    finally:
        events.set_journal(prev).close()
    assert health.state == "preempted"
    assert loop.preempted_at == final.step_int == 3
    recs = read_journal(jpath)
    pre = [r for r in recs if r["event"] == "preemption"]
    assert len(pre) == 1
    assert pre[0]["step"] == 3
    assert pre[0]["reason"] == "spot reclaim"
    assert pre[0]["checkpoint_saved"] is True


def test_loop_journal_restore_events(tmp_path):
    """A recovered failure leaves a `restore` record matching goodput."""
    class Flaky:
        calls = 0

        def __call__(self, state, batch):
            Flaky.calls += 1
            if Flaky.calls == 3:
                raise PreemptionError("fake")
            return _fake_step(state, batch)

    class MemCkpt:
        saved = None

        def save(self, state):
            MemCkpt.saved = state

        def restore(self, target):
            return MemCkpt.saved

    from dist_mnist_tpu.hooks import StopAtStepHook

    mgr = MemCkpt()
    mgr.save(_state())
    jpath = tmp_path / "j.jsonl"
    prev = events.set_journal(RunJournal(jpath))
    try:
        loop = TrainLoop(Flaky(), _state(), itertools.repeat(1.0),
                         [StopAtStepHook(last_step=5)],
                         checkpoint_manager=mgr, max_recoveries=2)
        loop.run()
    finally:
        events.set_journal(prev).close()
    restores = [r for r in read_journal(jpath) if r["event"] == "restore"]
    assert len(restores) == loop.goodput.snapshot()["recoveries"] == 1
    assert restores[0]["failed_at_step"] == 2
    assert restores[0]["restored_step"] == 0


def test_loop_step_time_histogram_fills():
    from dist_mnist_tpu.hooks import StopAtStepHook

    loop = TrainLoop(_fake_step, _state(), itertools.repeat(1.0),
                     [StopAtStepHook(last_step=10)])
    loop.run()
    assert loop.step_time_hist.count == 10
    assert loop.step_time_hist.snapshot()["p50"] > 0


def test_step_time_hook_publishes_percentiles():
    from dist_mnist_tpu.hooks import StepTimeHook, StopAtStepHook

    reg = MetricRegistry()
    hook = StepTimeHook(RegistryWriter(reg), every_steps=4)
    loop = TrainLoop(_fake_step, _state(), itertools.repeat(1.0),
                     [hook, StopAtStepHook(last_step=8)])
    loop.run()
    scalars = reg.scalars()
    for tag in ("step_time/p50_ms", "step_time/p95_ms", "step_time/p99_ms",
                "step_time/mean_ms"):
        assert tag in scalars, sorted(scalars)
        assert scalars[tag][0] > 0


# -- live scrape during a (fake) run ------------------------------------------

def test_metrics_scrape_mid_run():
    """The acceptance shape, in miniature: /metrics serves the live
    step-time histogram and /healthz says `training` WHILE the loop runs."""
    from dist_mnist_tpu.hooks import StopAtStepHook

    reg = MetricRegistry()
    health = HealthState()
    scraped = {}

    with MetricsExporter(reg, health=health, port=0) as exp:
        class Scrape:
            def begin(self, loop):
                reg.attach_histogram("train/step_time_ms",
                                     loop.step_time_hist)

            def before_step(self, step):
                pass

            def after_step(self, step, state, outputs):
                if step == 5 and not scraped:
                    scraped["metrics"] = _get(exp.url("/metrics"))
                    scraped["healthz"] = _get(exp.url("/healthz"))

            def end(self, state):
                pass

        loop = TrainLoop(_fake_step, _state(), itertools.repeat(1.0),
                         [Scrape(), StopAtStepHook(last_step=8)],
                         health=health)
        loop.run()

    code, body = scraped["metrics"]
    assert code == 200
    assert "# TYPE train_step_time_ms histogram" in body
    count_line = [l for l in body.splitlines()
                  if l.startswith("train_step_time_ms_count")][0]
    # step 5's own timing lands AFTER the after_step hooks, so the live
    # scrape sees the 4 already-completed steps
    assert int(count_line.split()[1]) == 4
    code, body = scraped["healthz"]
    assert code == 200
    assert json.loads(body)["state"] == "training"
    assert health.state == "stopped"


# -- tag hygiene --------------------------------------------------------------

class _TagRecorder:
    """Writer that records every tag it is asked to publish."""

    def __init__(self):
        self.tags = set()

    def scalar(self, tag, value, step):
        self.tags.add(tag)

    def scalars(self, values, step):
        self.tags.update(values)

    def histogram(self, tag, values, step):
        self.tags.add(tag)

    def flush(self):
        pass


def test_serve_metrics_tags_are_hygienic():
    from dist_mnist_tpu.serve.metrics import ServeMetrics

    m = ServeMetrics()
    m.record_latency(3.0)
    m.record_batch(4, 8)
    rec = _TagRecorder()
    m.emit(rec, step=1, queue_depth=2,
           cache={"hits": 1, "misses": 0})
    assert rec.tags, "emit published nothing"
    for tag in rec.tags:
        assert TAG_RE.match(tag), f"non-hygienic serve tag {tag!r}"


def test_step_time_hook_tags_are_hygienic():
    from dist_mnist_tpu.hooks import StepTimeHook, StopAtStepHook

    rec = _TagRecorder()
    loop = TrainLoop(_fake_step, _state(), itertools.repeat(1.0),
                     [StepTimeHook(rec, every_steps=2),
                      StopAtStepHook(last_step=4)])
    loop.run()
    for tag in rec.tags:
        assert TAG_RE.match(tag), f"non-hygienic step-time tag {tag!r}"


# -- supervisor journal -------------------------------------------------------

_ENV_STUB = textwrap.dedent("""\
    import json, os, sys
    args = dict(a.split("=", 1) for a in sys.argv[1:]
                if a.startswith("--") and "=" in a)
    pid = int(args.get("--process_id", "0"))
    out = args["--envlog"] + f".p{pid}"
    with open(out, "a") as fh:
        fh.write(json.dumps({
            "journal": os.environ.get("DIST_MNIST_TPU_JOURNAL"),
            "generation": os.environ.get("DIST_MNIST_TPU_GENERATION"),
        }) + "\\n")
    if pid == 1 and args.get("--stub_mode") == "fail_once":
        marker = args["--stub_marker"]
        if not os.path.exists(marker):
            open(marker, "w").close()
            sys.exit(3)
    sys.exit(0)
""")


def _supervise(tmp_path, train_args, **kw):
    import contextlib

    from dist_mnist_tpu.cli.launch import launch

    stub = tmp_path / "env_stub.py"
    stub.write_text(_ENV_STUB)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = launch(2, train_args, platform="cpu", devices_per_process=1,
                    child_command=[sys.executable, str(stub)],
                    restart_backoff_s=0.05, **kw)
    return rc, buf.getvalue()


def test_supervisor_owns_one_journal_across_generations(tmp_path):
    jpath = tmp_path / "journal.jsonl"
    rc, log = _supervise(
        tmp_path,
        [f"--envlog={tmp_path / 'env'}", "--stub_mode=fail_once",
         f"--stub_marker={tmp_path / 'marker'}"],
        max_restarts=2, journal=str(jpath),
    )
    assert rc == 0, log
    recs = read_journal(jpath)
    evs = [r["event"] for r in recs]
    # the complete lifecycle, in order, in ONE file
    assert evs == [
        "supervisor_start",
        "generation_start", "generation_end",
        "supervisor_restart",
        "generation_start", "generation_end",
        "supervisor_stop",
    ], evs
    by_ev = {e: [r for r in recs if r["event"] == e] for e in set(evs)}
    assert by_ev["supervisor_start"][0]["max_restarts"] == 2
    assert [r["gen"] for r in by_ev["generation_start"]] == [0, 1]
    assert by_ev["generation_end"][0]["rc"] == 3
    assert by_ev["generation_end"][0]["first_dead"] == 1
    assert by_ev["generation_end"][1]["rc"] == 0
    assert by_ev["supervisor_restart"][0]["attempt"] == 1
    assert by_ev["supervisor_stop"][0] == {
        **by_ev["supervisor_stop"][0], "rc": 0, "restarts": 1}
    # children of BOTH generations were pointed at the same journal with
    # their generation number (the env injection contract)
    for pid in (0, 1):
        lines = (tmp_path / f"env.p{pid}").read_text().strip().splitlines()
        envs = [json.loads(l) for l in lines]
        assert [e["generation"] for e in envs] == ["0", "1"]
        assert all(e["journal"] == str(jpath) for e in envs)


_SLEEP_STUB = textwrap.dedent("""\
    import sys, time
    args = dict(a.split("=", 1) for a in sys.argv[1:]
                if a.startswith("--") and "=" in a)
    time.sleep(2.0 if int(args.get("--process_id", "0")) == 1 else 0.8)
    sys.exit(0)
""")


def test_supervisor_journals_chaos_kill(tmp_path):
    import contextlib

    from dist_mnist_tpu.cli.launch import launch

    jpath = tmp_path / "journal.jsonl"
    stub = tmp_path / "sleep_stub.py"
    stub.write_text(_SLEEP_STUB)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        launch(2, [], platform="cpu", devices_per_process=1,
               child_command=[sys.executable, str(stub)],
               restart_backoff_s=0.05, max_restarts=1,
               journal=str(jpath), kill_spec=(1, 0.2))
    log = buf.getvalue()
    assert "fault injected: SIGKILL p1" in log, log
    kills = [r for r in read_journal(jpath)
             if r["event"] == "fault_injected"]
    assert len(kills) == 1
    assert kills[0]["kind"] == "kill_process"
    assert kills[0]["process"] == 1
    assert kills[0]["gen"] == 0


# -- end to end through the driver -------------------------------------------

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_run_config_obs_spine_end_to_end(tmp_path):
    """run_config wires the whole spine: journal, registry in the default
    writer, /metrics + /healthz live during training, hygienic tags."""
    from dist_mnist_tpu.configs import get_config
    from dist_mnist_tpu.cli.train import run_config

    cfg = get_config("mlp_mnist", train_steps=6, eval_every=0, log_every=2)
    port = _free_port()
    scraped = {}

    class Scrape:
        def begin(self, loop):
            pass

        def before_step(self, step):
            pass

        def after_step(self, step, state, outputs):
            if step >= 2 and not scraped:
                scraped["metrics"] = _get(f"http://127.0.0.1:{port}/metrics")
                scraped["healthz"] = _get(f"http://127.0.0.1:{port}/healthz")

        def end(self, state):
            pass

    state, final, ctx = run_config(
        cfg, data_dir=str(tmp_path / "data"), logdir=str(tmp_path / "logs"),
        metrics_port=port, extra_hooks=[Scrape()],
    )
    assert state.step_int == 6
    # live scrape saw the training state and the step-time histogram
    code, body = scraped["metrics"]
    assert code == 200
    assert "# TYPE train_step_time_ms histogram" in body
    code, body = scraped["healthz"]
    assert code == 200 and json.loads(body)["state"] == "training"
    # the registry rides in ctx, fully hygienic
    assert ctx["health"].state == "stopped"
    tags = ctx["registry"].tags()
    assert "train/step_time_ms" in tags
    for tag in tags:
        assert TAG_RE.match(tag), f"non-hygienic tag {tag!r}"
    # the journal landed in the logdir with the run lifecycle
    recs = read_journal(tmp_path / "logs" / "events.jsonl")
    evs = [r["event"] for r in recs]
    assert evs[0] == "run_start" and evs[-1] == "run_stop"
    assert recs[-1]["ok"] is True
    assert ctx["journal"] == str(tmp_path / "logs" / "events.jsonl")
