"""IDX codec: round-trip (property-based, per SURVEY.md §4 mapping) and
error paths."""

import numpy as np
import pytest
from _hypothesis_stub import given, settings, st

from dist_mnist_tpu.data.idx import read_idx, write_idx

DTYPES = [np.uint8, np.int8, np.int16, np.int32, np.float32, np.float64]


@settings(max_examples=30, deadline=None)
@given(
    dtype=st.sampled_from(DTYPES),
    shape=st.lists(st.integers(1, 6), min_size=1, max_size=4),
    data=st.data(),
    gz=st.booleans(),
)
def test_roundtrip(tmp_path_factory, dtype, shape, data, gz):
    n = int(np.prod(shape))
    if np.issubdtype(dtype, np.integer):
        info = np.iinfo(dtype)
        vals = data.draw(
            st.lists(st.integers(info.min, info.max), min_size=n, max_size=n)
        )
    else:
        vals = data.draw(
            st.lists(
                st.floats(-1e6, 1e6, allow_nan=False, width=32),
                min_size=n,
                max_size=n,
            )
        )
    arr = np.array(vals, dtype=dtype).reshape(shape)
    path = tmp_path_factory.mktemp("idx") / ("x.idx.gz" if gz else "x.idx")
    write_idx(path, arr)
    out = read_idx(path)
    assert out.dtype == arr.dtype
    assert out.shape == arr.shape
    np.testing.assert_array_equal(out, arr)


def test_bad_magic(tmp_path):
    p = tmp_path / "bad.idx"
    p.write_bytes(b"\x01\x02\x08\x01\x00\x00\x00\x01\xff")
    with pytest.raises(ValueError, match="magic"):
        read_idx(p)


def test_truncated(tmp_path):
    p = tmp_path / "trunc.idx"
    p.write_bytes(b"\x00\x00\x08\x01\x00\x00\x00\x05\x01\x02")
    with pytest.raises(ValueError, match="truncated"):
        read_idx(p)


def test_unknown_dtype(tmp_path):
    p = tmp_path / "odd.idx"
    p.write_bytes(b"\x00\x00\x77\x01\x00\x00\x00\x01\x01")
    with pytest.raises(ValueError, match="dtype"):
        read_idx(p)
