"""Async snapshotting + peer-replicated restore (ISSUE 11).

The acceptance contract: checkpointing leaves the step critical path —
the loop pays a donation-safe device fork, a background writer owns
durability — without weakening any crash-consistency guarantee:

- commit markers: a step is restore-eligible only once its marker landed
  (kill-mid-write leaves a restorable-but-uncommitted directory that the
  quarantine ladder removes WITHOUT consuming a fallback);
- the write-behind window is bounded (block attributes the stall,
  drop_oldest never abandons the in-flight write);
- preemption drain: everything accepted is durable before the loop exits;
- ring peer redundancy restores a dead host's shards bit-identically to
  the store across an 8->4 shrink, store fallback when the peer died too.
"""

import dataclasses
import sys
import threading
import time
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dist_mnist_tpu import optim
from dist_mnist_tpu.checkpoint import (
    AsyncSnapshotter,
    CheckpointManager,
    PeerReplicator,
    fork_state,
    restore_from_peers,
)
from dist_mnist_tpu.cluster.membership import ring_peer
from dist_mnist_tpu.cluster.mesh import MeshSpec, activate, make_mesh
from dist_mnist_tpu.faults.goodput import GoodputClock
from dist_mnist_tpu.models import get_model
from dist_mnist_tpu.obs import events as events_mod
from dist_mnist_tpu.parallel.sharding import FSDP_RULES, shard_train_state
from dist_mnist_tpu.train import create_train_state


@pytest.fixture()
def state(mesh8):
    model = get_model("mlp", hidden_units=16)
    opt = optim.adam(0.01)
    with mesh8:
        s = create_train_state(
            model, opt, jax.random.PRNGKey(0),
            np.zeros((1, 28, 28, 1), np.uint8),
        )
        return shard_train_state(s, mesh8)


def _at_step(state, step):
    return dataclasses.replace(state, step=jnp.asarray(step, jnp.int32))


def _leaf_bytes(state):
    return [bytes(jax.device_get(x).tobytes())
            for x in jax.tree.leaves(state)]


# ------------------------------------------------------- commit markers --


def test_commit_marker_lands_with_sync_save(tmp_path, state):
    mgr = CheckpointManager(tmp_path, async_save=False)
    assert mgr.save(state)
    assert (tmp_path / "commits" / "0.committed").exists()
    assert mgr.latest_step() == 0
    mgr.close()


def test_uncommitted_step_is_not_restore_eligible(tmp_path, state):
    """Kill-mid-write simulation: a step directory present WITHOUT its
    commit marker (the marker only lands after durability) must never be
    reported by latest_step nor restored — it is quarantined up front
    without consuming a restore fallback (proved with the ladder budget
    at 0)."""
    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(_at_step(state, 3))
    mgr.save(_at_step(state, 7))
    mgr.close()
    # the writer died after the files hit disk but before the marker
    (tmp_path / "commits" / "7.committed").unlink()

    mgr2 = CheckpointManager(tmp_path, async_save=False,
                             max_restore_fallbacks=0)
    assert mgr2.latest_step() == 3
    restored = mgr2.restore(_at_step(state, 0))
    assert restored is not None and restored.step_int == 3
    # the torso went through quarantine, not retention GC
    assert (tmp_path / "quarantine" / "step_7").exists()
    assert not (tmp_path / "7").exists()
    mgr2.close()


def test_uncommitted_only_directory_restores_none(tmp_path, state):
    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(state)
    mgr.close()
    (tmp_path / "commits" / "0.committed").unlink()
    mgr2 = CheckpointManager(tmp_path, async_save=False)
    assert mgr2.latest_step() is None
    out, restored = mgr2.restore_or_init(state)
    assert not restored and out is state
    mgr2.close()


def test_flush_commits_lands_marker_without_next_save(tmp_path, state):
    """An orbax-async save's marker must land via the per-step
    flush_commits() poll (CheckpointHook.after_step calls it every step),
    not at the NEXT save()/wait(): a kill inside the cadence window must
    not quarantine a step whose write WAS durable — that would roll the
    restore back a whole cadence interval."""
    mgr = CheckpointManager(tmp_path, async_save=True)
    mgr.save(_at_step(state, 3))
    assert 3 in mgr._pending_commits
    marker = tmp_path / "commits" / "3.committed"
    deadline = time.monotonic() + 20.0
    while not marker.exists() and time.monotonic() < deadline:
        mgr.flush_commits()  # the after_step poll
        time.sleep(0.02)
    assert marker.exists(), "marker never landed via the poll"
    assert 3 not in mgr._pending_commits
    # a FRESH manager (the next generation after a kill: this one's
    # wait() never ran) sees step 3 as restore-eligible
    mgr2 = CheckpointManager(tmp_path, async_save=False,
                             max_restore_fallbacks=0)
    assert mgr2.latest_step() == 3
    restored = mgr2.restore(_at_step(state, 0))
    assert restored is not None and restored.step_int == 3
    mgr2.close()
    mgr.close()


def test_legacy_directory_adopted_on_open(tmp_path, state):
    """Pre-protocol checkpoint dirs (steps, no commits/) were written by
    managers that waited for durability before exit: adopt their steps as
    committed instead of quarantining a whole valid history."""
    import shutil

    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(_at_step(state, 4))
    mgr.close()
    shutil.rmtree(tmp_path / "commits")
    mgr2 = CheckpointManager(tmp_path, async_save=False)
    assert mgr2.latest_step() == 4
    assert (tmp_path / "commits" / "4.committed").exists()
    mgr2.close()


# ----------------------------------------------------- async snapshotter --


def test_fork_state_preserves_values_and_shardings(state):
    fork = fork_state(state)
    assert _leaf_bytes(fork) == _leaf_bytes(state)
    assert (fork.params["hid"]["w"].sharding
            == state.params["hid"]["w"].sharding)
    # fresh buffers: donation of the original cannot alias the fork
    assert fork.params["hid"]["w"] is not state.params["hid"]["w"]


def test_async_snapshotter_roundtrip_and_commit_events(tmp_path, state):
    journal = tmp_path / "journal.jsonl"
    prev = events_mod.set_journal(events_mod.RunJournal(journal))
    try:
        snap = AsyncSnapshotter(
            CheckpointManager(tmp_path / "ckpt", async_save=False))
        assert snap.save(state)
        assert not snap.save(state)  # deduped by step at the fork layer
        snap.wait()
        assert snap.latest_step() == 0
        snap.close()
    finally:
        j = events_mod.set_journal(prev)
        if j is not None:
            j.close()
    mgr = CheckpointManager(tmp_path / "ckpt", async_save=False)
    restored = mgr.restore(_at_step(state, 9))
    assert restored is not None
    assert _leaf_bytes(restored) == _leaf_bytes(state)
    mgr.close()
    records = events_mod.read_journal(journal)
    events = [r["event"] for r in records]
    assert "snapshot_fork" in events
    commits = [r for r in records if r["event"] == "checkpoint_commit"]
    assert len(commits) == 1 and commits[0]["step"] == 0
    # dispatch->durable span is back-dated to the fork
    assert commits[0]["dur_ms"] >= 0


class _SlowState:
    """Duck-typed state for writer-stub tests (fork_state passes non-array
    leaves through untouched)."""

    def __init__(self, step):
        self.step_int = step


class _SlowWriter:
    """CheckpointManager stub whose save blocks for `delay` seconds."""

    def __init__(self, delay=0.3, fail=False):
        self.delay = delay
        self.fail = fail
        self.saved = []
        self.started = threading.Event()
        self.closed = False

    def save(self, state, *, dispatch_ts=None):
        self.started.set()
        if self.fail:
            raise OSError("disk on fire")
        time.sleep(self.delay)
        self.saved.append(state.step_int)
        return True

    def wait(self):
        pass

    def close(self):
        self.closed = True

    def latest_step(self, *, refresh=False):
        return self.saved[-1] if self.saved else None


def test_write_behind_window_blocks_and_attributes_stall():
    writer = _SlowWriter(delay=0.3)
    snap = AsyncSnapshotter(writer, window=1, policy="block")
    t0 = time.monotonic()
    snap.save(_SlowState(1))
    assert writer.started.wait(2.0)
    # window full (one write in flight): this save must block and the
    # stall must be attributed, not silently swallowed
    snap.save(_SlowState(2))
    blocked = time.monotonic() - t0
    assert blocked >= 0.2
    assert snap.save_stall_s > 0.0
    snap.wait()
    assert writer.saved == [1, 2]
    assert snap.dropped == 0
    stall = snap.consume_save_stall_s()
    assert stall > 0.0 and snap.consume_save_stall_s() == 0.0
    snap.close()
    assert writer.closed


def test_write_behind_drop_oldest_never_abandons_inflight():
    writer = _SlowWriter(delay=0.4)
    snap = AsyncSnapshotter(writer, window=1, policy="drop_oldest")
    snap.save(_SlowState(1))
    assert writer.started.wait(2.0)
    t0 = time.monotonic()
    # in-flight write is never dropped: with an empty queue the new fork
    # is admitted as a transient overshoot instead
    snap.save(_SlowState(2))
    # now the queue holds 2 -> the next save drops it, not the in-flight 1
    snap.save(_SlowState(3))
    assert time.monotonic() - t0 < 0.3  # neither save blocked
    snap.wait()
    assert writer.saved == [1, 3]
    assert snap.dropped == 1
    assert snap.save_stall_s == 0.0
    snap.close()


def test_writer_error_surfaces_in_wait():
    writer = _SlowWriter(fail=True)
    snap = AsyncSnapshotter(writer, window=4)
    snap.save(_SlowState(1))
    with pytest.raises(RuntimeError, match="snapshot writer failed"):
        snap.wait()
    snap.close()  # close after a writer error must not hang
    assert writer.closed


def test_drain_on_preemption_durable_before_exit(mesh8, small_mnist,
                                                 tmp_path):
    """The preemption handshake through the async layer: notify mid-run ->
    the loop saves at the boundary via the snapshotter, and the drain in
    _honor_preemption/end() makes the step durable AND committed before
    the process exits — a fresh manager sees it."""
    from dist_mnist_tpu import hooks as hooks_lib
    from dist_mnist_tpu.data import ShardedBatcher
    from dist_mnist_tpu.faults.preemption import PreemptionNotice
    from dist_mnist_tpu.train import TrainLoop
    from dist_mnist_tpu.train.step import make_train_step

    notice = PreemptionNotice()

    class NotifyAt:
        def begin(self, loop):
            pass

        def before_step(self, step):
            pass

        def after_step(self, step, state, outputs):
            if step == 4:
                notice.notify("test preemption")

        def end(self, state):
            pass

    with activate(mesh8):
        model = get_model("mlp", hidden_units=16)
        optimizer = optim.adam(1e-3)
        s0 = create_train_state(model, optimizer, jax.random.PRNGKey(0),
                                small_mnist.train_images[:1])
        s0 = shard_train_state(s0, mesh8)
        step = make_train_step(model, optimizer, mesh8, donate=False)
        manager = AsyncSnapshotter(
            CheckpointManager(tmp_path, async_save=False))
        hooks = [hooks_lib.StopAtStepHook(last_step=12), NotifyAt(),
                 hooks_lib.CheckpointHook(manager, every_steps=3)]
        loop = TrainLoop(step, s0, ShardedBatcher(small_mnist, 64, mesh8,
                                                  seed=0),
                         hooks, checkpoint_manager=manager,
                         preemption=notice)
        loop.run()
        manager.close()
    assert loop.preempted_at == 4
    mgr = CheckpointManager(tmp_path, async_save=False)
    assert mgr.latest_step() == 4  # durable + committed before the stop
    assert (tmp_path / "commits" / "4.committed").exists()
    mgr.close()


def test_checkpoint_hook_begin_skips_existing_restore_point():
    from dist_mnist_tpu.hooks.builtin import CheckpointHook

    class _Mgr:
        def __init__(self, latest):
            self._latest = latest
            self.saves = []

        def latest_step(self):
            return self._latest

        def save(self, state):
            self.saves.append(state)
            return True

    loop = SimpleNamespace(initial_step=5, state="STATE")
    resumed = _Mgr(latest=5)
    CheckpointHook(resumed, every_steps=3).begin(loop)
    assert resumed.saves == []  # restore point exists: no save-on-create

    fresh = _Mgr(latest=None)
    CheckpointHook(fresh, every_steps=3).begin(loop)
    assert fresh.saves == ["STATE"]

    stale = _Mgr(latest=3)
    CheckpointHook(stale, every_steps=3).begin(loop)
    assert stale.saves == ["STATE"]


# ------------------------------------------------------ goodput save_s --


def test_goodput_save_bucket():
    g = GoodputClock()
    g.start()
    g.add_save(0.25)
    g.add_save(0.5)
    g.close()
    assert g.snapshot()["save_s"] == pytest.approx(0.75)


# -------------------------------------------------- peer ring redundancy --


def test_ring_peer():
    assert ring_peer(0, [0, 1, 2]) == 1
    assert ring_peer(2, [0, 1, 2]) == 0
    assert ring_peer(1, [2, 0, 1]) == 2  # order-insensitive
    assert ring_peer(0, [0]) is None  # alone: no redundancy possible
    assert ring_peer(5, [0, 1]) is None  # not a member


def _fake_fleet_write(root, state, *, hosts=(0, 1, 2, 3)):
    """Model a 4-host fleet over the 8-device mesh (2 devices per fake
    host) and have every host replicate its shards to its ring peer."""
    host_of = lambda d: d.id // 2  # noqa: E731
    for h in hosts:
        PeerReplicator(root, h, hosts, host_of=host_of).write(
            int(state.step_int), state)


def _mlp_state(mesh, seed=0, step=0):
    model = get_model("mlp", hidden_units=64)
    opt = optim.adam(1e-3)
    s = create_train_state(model, opt, jax.random.PRNGKey(seed),
                           jnp.zeros((1, 28, 28, 1), jnp.uint8))
    if step:
        s = _at_step(s, step)
    return shard_train_state(s, mesh, FSDP_RULES)


def test_peer_restore_bit_identical_to_store_across_shrink(tmp_path, mesh8):
    """The headline contract: an 8-device (4 fake hosts) fsdp state,
    peer-replicated around the ring, restores onto the 4-device surviving
    mesh bit-identically to the STORE restore of the same step — with
    host 1 dead, its shards coming off its ring peer's disk."""
    src = _mlp_state(mesh8, seed=0, step=7)
    _fake_fleet_write(tmp_path / "peer", src)
    mgr = CheckpointManager(tmp_path / "store", async_save=False)
    mgr.save(src)
    mgr.close()

    mesh4 = make_mesh(MeshSpec(data=4), devices=jax.devices()[:4])
    with activate(mesh4):
        target = _mlp_state(mesh4, seed=9, step=0)  # different init
        mgr2 = CheckpointManager(tmp_path / "store", async_save=False)
        store_restored = mgr2.restore(target)
        mgr2.close()
        got = restore_from_peers(tmp_path / "peer", target,
                                 alive={0, 2, 3}, min_step=7)
    assert got is not None
    peer_restored, step, sources = got
    assert step == 7
    # host 1 is dead: its shards must have come off a surviving HOLDER
    # (its own dir h1 is excluded), concretely its ring peer h2
    assert sources[1] == "h2"
    assert _leaf_bytes(peer_restored) == _leaf_bytes(store_restored)
    assert (peer_restored.params["hid"]["w"].sharding
            == target.params["hid"]["w"].sharding)


def test_peer_restore_none_when_peer_also_dead(tmp_path, mesh8):
    """Host 1's shards live on h1 (its own) and h2 (its ring peer); with
    both dead there is no full coverage and the caller must fall back to
    the store."""
    src = _mlp_state(mesh8, seed=0, step=7)
    _fake_fleet_write(tmp_path, src)
    target = _mlp_state(mesh8, seed=9)
    assert restore_from_peers(tmp_path, target, alive={0, 3}) is None


def test_peer_restore_min_step_and_tmp_files(tmp_path, mesh8):
    src = _mlp_state(mesh8, seed=0, step=7)
    _fake_fleet_write(tmp_path, src)
    target = _mlp_state(mesh8, seed=9)
    # staler than the store frontier: not worth assembling
    assert restore_from_peers(tmp_path, target, alive={0, 1, 2, 3},
                              min_step=8) is None
    # a kill mid-replication leaves only an atomic-write temp file, which
    # no restore ever considers
    stray = tmp_path / "h0" / "s0" / "step_99.npz.tmp-12345"
    stray.write_bytes(b"partial garbage")
    got = restore_from_peers(tmp_path, target, alive={0, 1, 2, 3})
    assert got is not None and got[1] == 7


def test_peer_restore_newest_covered_step_wins(tmp_path, mesh8):
    old = _mlp_state(mesh8, seed=0, step=3)
    new = _mlp_state(mesh8, seed=1, step=9)
    _fake_fleet_write(tmp_path, old)
    _fake_fleet_write(tmp_path, new)
    target = _mlp_state(mesh8, seed=9)
    got = restore_from_peers(tmp_path, target, alive={0, 1, 2, 3})
    assert got is not None
    restored, step, _ = got
    assert step == 9
    assert _leaf_bytes(restored) == _leaf_bytes(new)


def test_snapshotter_peer_first_restore_falls_back_to_store(tmp_path,
                                                            state):
    """Wired together: with a peer attached, restore() prefers the ring;
    with nothing usable there it falls through to the store ladder."""
    inner = CheckpointManager(tmp_path / "ckpt", async_save=False)
    peer = PeerReplicator(tmp_path / "peer", 0, [0],
                          host_of=lambda d: 0)
    snap = AsyncSnapshotter(inner, peer=peer)
    snap.save(state)
    snap.wait()
    # peer holds step 0 alongside the store
    journal = tmp_path / "journal.jsonl"
    prev = events_mod.set_journal(events_mod.RunJournal(journal))
    try:
        restored = snap.restore(_at_step(state, 9))
    finally:
        j = events_mod.set_journal(prev)
        if j is not None:
            j.close()
    assert restored is not None
    assert _leaf_bytes(restored) == _leaf_bytes(state)
    events = [r["event"] for r in events_mod.read_journal(journal)]
    assert "peer_restore" in events
    assert "checkpoint_restore" not in events  # the store was never read
    # wipe the ring -> the same call degrades to the store
    import shutil

    shutil.rmtree(tmp_path / "peer")
    restored2 = snap.restore(_at_step(state, 9))
    assert restored2 is not None
    assert _leaf_bytes(restored2) == _leaf_bytes(state)
    snap.close()


# -------------------------------------------------------- obs rendering --


def test_fleet_trace_renders_commit_as_span():
    sys.path.insert(0, "scripts")
    try:
        from fleet_trace import journal_events
    finally:
        sys.path.pop(0)
    recs = [
        {"ts": 100.0, "gen": 0, "host": 0, "event": "span",
         "name": "checkpoint", "dur_ms": 2.0},
        {"ts": 100.5, "gen": 0, "host": 0, "event": "checkpoint_commit",
         "step": 10, "dur_ms": 400.0},
        {"ts": 101.0, "gen": 1, "host": 0, "event": "peer_restore",
         "step": 10, "dur_ms": 3.0},
    ]
    evs = journal_events(recs)
    commit = next(e for e in evs if e["name"] == "checkpoint_commit")
    # a real bar (ph X) back-dated by its dispatch->durable duration
    assert commit["ph"] == "X"
    assert commit["dur"] == pytest.approx(400e3)
    assert commit["ts"] == pytest.approx((100.5 - 100.0) * 1e6 - 400e3)
    peer = next(e for e in evs if e["name"] == "peer_restore")
    assert peer["ph"] == "i"


def test_tail_run_renders_commit_and_peer_restore():
    sys.path.insert(0, "scripts")
    try:
        from tail_run import format_record
    finally:
        sys.path.pop(0)
    out = format_record({"seq": 1, "ts": 0.0, "pid": 9, "gen": 0,
                         "event": "checkpoint_commit", "step": 10,
                         "dur_ms": 412.5})
    assert "step=10" in out and "durable after 412.50ms" in out
    assert "dur_ms=" not in out  # head fields not repeated in the tail
    out2 = format_record({"seq": 2, "ts": 0.0, "pid": 9, "gen": 1,
                          "event": "peer_restore", "step": 10,
                          "dur_ms": 3.25, "sources": {"1": "h2"}})
    assert "step=10" in out2 and "3.25ms" in out2
