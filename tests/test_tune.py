"""Persistent autotuner: search determinism, the TunedConfigStore's key
semantics (geometry-keyed like the executable cache, own knobs excluded),
quarantine-not-crash failure handling, the `--tuned` apply paths, and the
compile-cache interlock (a tuner-applied compile-relevant knob must force
an executable-store miss)."""

import dataclasses
import json
import sys

import jax
import numpy as np
import pytest

from dist_mnist_tpu.cli.train import compile_cache_key_fields, run_config
from dist_mnist_tpu.compilecache.store import cache_key
from dist_mnist_tpu.configs import get_config
from dist_mnist_tpu.obs import events
from dist_mnist_tpu.tune import (
    KNOBS,
    TunableSpec,
    TunedConfigMissError,
    TunedConfigStore,
    apply_tuned,
    knob_names,
    make_entry,
    successive_halving,
    tuning_key,
)
from dist_mnist_tpu.tune.objectives import (
    moe_capacity_objective,
    overlap_cost_objective,
    serve_grid_objective,
)

TOY = TunableSpec(
    name="toy", subsystem="test", candidates=(1, 2, 3, 4), default=1,
    metric="toy_cost", bench_stage="none", target="train_runtime")


def toy_objective(cand, *, budget, seed):
    # deterministic, budget- and seed-sensitive: minimized at cand=2.5,
    # so 2 and 3 tie and the stable sort must resolve by ladder order
    return (cand - 2.5) ** 2 + 1.0 / budget + seed * 1e-9, {"cand": cand}


# -- search engine -------------------------------------------------------------


def test_successive_halving_winner_and_baseline_leg():
    res = successive_halving(TOY, toy_objective, seed=3, base_budget=8)
    assert res.winner == 2  # tie with 3 broken by ladder order
    assert res.strictly_beats_default
    assert res.vs_default_ratio < 1.0
    # the default was eliminated, so it must be re-scored at the FINAL
    # round's (budget, seed): same stream as the winner's final score
    baseline = [t for t in res.trials if t.extra.get("baseline_leg")]
    assert len(baseline) == 1
    assert baseline[0].budget == res.final_budget
    assert baseline[0].score == res.default_score


def test_successive_halving_deterministic_across_invocations():
    a = successive_halving(TOY, toy_objective, seed=0, base_budget=8)
    b = successive_halving(TOY, toy_objective, seed=0, base_budget=8)
    assert a.winner == b.winner
    assert a.winner_score == b.winner_score
    assert [(t.candidate, t.round, t.budget, t.score) for t in a.trials] \
        == [(t.candidate, t.round, t.budget, t.score) for t in b.trials]


def test_higher_is_better_direction():
    spec = dataclasses.replace(TOY, direction="higher_is_better", default=4)
    res = successive_halving(
        spec, lambda c, *, budget, seed: (float(c), {}), seed=0,
        base_budget=4)
    assert res.winner == 4  # the default IS the best: no strict beat
    assert not res.strictly_beats_default
    assert res.vs_default_ratio == 1.0


def test_search_journal_events(tmp_path):
    prev = events.set_journal(events.RunJournal(tmp_path / "j.jsonl"))
    try:
        successive_halving(TOY, toy_objective, seed=0, base_budget=8)
    finally:
        events.set_journal(prev).close()
    recs = [json.loads(line) for line in
            (tmp_path / "j.jsonl").read_text().splitlines()]
    kinds = [r["event"] for r in recs]
    assert kinds[0] == "tuning/search_start"
    assert kinds[-1] == "tuning/winner"
    assert kinds.count("tuning/trial") == len(
        [r for r in recs if "candidate" in r])
    winner = recs[-1]
    assert winner["strictly_beats_default"] is True
    assert winner["vs_default_ratio"] < 1.0


# -- objectives (the real machinery, deterministically) ------------------------


def test_overlap_objective_deterministic_and_beats_default(mesh8):
    objective = overlap_cost_objective(mesh8)
    s1, extra = objective(1.0, budget=32, seed=0)
    s2, _ = objective(1.0, budget=32, seed=0)
    assert s1 == s2  # structural cost model: no wall clock in the score
    assert extra["n_buckets"] >= 1 and extra["gathered_mbytes"] > 0
    res = successive_halving(KNOBS["overlap_bucket_mb"], objective,
                             seed=0, base_budget=32)
    assert res.strictly_beats_default  # the bench.py --tune gate


def test_serve_grid_objective_seeded_stream():
    objective = serve_grid_objective()
    s1, extra = objective((64, "auto"), budget=64, seed=0)
    s2, _ = objective((64, "auto"), budget=64, seed=0)
    s3, _ = objective((64, "auto"), budget=64, seed=1)
    assert s1 == s2
    assert s1 != s3  # the stream really is seed-driven
    assert extra["grid_cells"] > 0
    res = successive_halving(KNOBS["serve_grid"], objective,
                             seed=0, base_budget=32)
    assert res.strictly_beats_default
    assert res.winner != KNOBS["serve_grid"].default


def test_moe_capacity_objective_deterministic_and_monotone():
    objective = moe_capacity_objective()
    s1, extra = objective(1.25, budget=32, seed=0)
    s2, _ = objective(1.25, budget=32, seed=0)
    assert s1 == s2  # seeded Dirichlet/multinomial routing: no wall clock
    assert 0.0 <= extra["drop_fraction"] <= 1.0
    # a bigger buffer strictly drops fewer tokens (the toll prices it)
    drops = [objective(f, budget=32, seed=0)[1]["drop_fraction"]
             for f in KNOBS["moe_capacity_factor"].candidates]
    assert drops == sorted(drops, reverse=True)
    res = successive_halving(KNOBS["moe_capacity_factor"], objective,
                             seed=0, base_budget=32)
    assert res.strictly_beats_default


# -- key semantics -------------------------------------------------------------


def test_tuning_key_excludes_own_knobs(mesh8):
    """The lookup happens with the LAUNCH config, before the winner is
    applied — the tuned knobs' own values must not key the entry."""
    cfg = get_config("mlp_mnist")
    base = tuning_key(cfg, mesh8)
    assert tuning_key(
        dataclasses.replace(cfg, overlap_bucket_mb=0.5), mesh8) == base
    assert tuning_key(
        dataclasses.replace(cfg, overlap=True, overlap_chunk=4),
        mesh8) == base


def test_tuning_key_invalidation(mesh8, mesh_tp):
    cfg = get_config("mlp_mnist")
    base = tuning_key(cfg, mesh8)
    # geometry: mesh shape, model config, batch — all invalidate
    assert tuning_key(cfg, mesh_tp) != base
    assert tuning_key(get_config("lenet5_mnist"), mesh8) != base
    assert tuning_key(
        dataclasses.replace(cfg, batch_size=32), mesh8) != base
    # environment: backend / jax version (pinned via cache_key overrides,
    # the same auto-merged fields a real cross-version run would differ in)
    assert tuning_key(cfg, mesh8, backend="tpu") != base
    assert tuning_key(cfg, mesh8, jax_version="0.0.1") != base
    # and the namespace can never collide with the executable store's keys
    assert cache_key({"kind": "step",
                      **compile_cache_key_fields(cfg, mesh8)}) != base


def test_store_hit_requires_exact_geometry(tmp_path, mesh8, mesh_tp):
    cfg = get_config("mlp_mnist")
    store = TunedConfigStore(tmp_path)
    store.save(tuning_key(cfg, mesh8), {"knobs": {"overlap_bucket_mb": 0.5}})
    assert store.load(tuning_key(cfg, mesh8)) is not None
    assert store.load(tuning_key(cfg, mesh_tp)) is None
    assert store.load(tuning_key(cfg, mesh8, backend="tpu")) is None
    assert store.load(tuning_key(cfg, mesh8, jax_version="0.0.1")) is None


def test_tuned_compile_relevant_knob_forces_executable_cache_miss(mesh8):
    """The satellite-1 interlock: applying the tuner's overlap_bucket_mb
    winner changes compile_cache_key_fields' hash, so a cached serial
    executable can never serve the tuned schedule."""
    cfg = get_config("mlp_mnist")
    tuned_cfg, _ = _apply_poisoned(cfg, mesh8, bucket_mb=0.5)
    assert tuned_cfg.overlap_bucket_mb == 0.5
    assert cache_key(compile_cache_key_fields(tuned_cfg, mesh8)) \
        != cache_key(compile_cache_key_fields(cfg, mesh8))
    # ...while the TUNING key is unchanged — next launch still hits
    assert tuning_key(tuned_cfg, mesh8) == tuning_key(cfg, mesh8)


def test_every_catalog_knob_is_classified():
    """Each stored knob name must be either compile-relevant (keyed — the
    cache-key lint proves it) or runtime-only; and the spec plumbing
    (knob_values/knob_names) must agree on the flattened names."""
    flat = set(knob_names())
    assert {"overlap_bucket_mb", "serve_max_batch", "serve_seq_buckets",
            "prefetch_depth", "scan_chunk", "snapshot_window",
            "moe_capacity_factor", "kv_page_tokens",
            "decode_admit_buckets"} == flat
    for spec in KNOBS.values():
        assert set(spec.knob_values(spec.default)) == set(
            spec.fields if spec.fields else (spec.name,))


# -- store robustness ----------------------------------------------------------


def test_store_roundtrip_and_stats(tmp_path):
    store = TunedConfigStore(tmp_path)
    assert store.load("missing") is None
    n = store.save("k1", {"knobs": {"prefetch_depth": 4}, "evidence": {}})
    assert n > 0
    entry = store.load("k1")
    assert entry["knobs"] == {"prefetch_depth": 4}
    assert entry["key"] == "k1"
    stats = store.stats()
    assert stats["hits"] == 1 and stats["misses"] == 1
    assert stats["saves"] == 1 and stats["entries"] == 1


@pytest.mark.parametrize("blob", [
    "not json at all",
    '{"knobs": {"overlap_bucket_mb": 0.5}',   # truncated mid-write
    '["knobs"]',                              # json, wrong shape
    '{"winner": 0.5}',                        # dict, no knobs
])
def test_corrupt_entry_quarantined_not_crash(tmp_path, blob):
    store = TunedConfigStore(tmp_path)
    path = tmp_path / "bad.tuned.json"
    path.write_text(blob)
    assert store.load("bad") is None
    assert not path.exists()  # quarantined, not left to fail forever
    stats = store.stats()
    assert stats["corrupt"] == 1 and stats["misses"] == 1


def test_save_failure_degrades_to_warning(tmp_path):
    store = TunedConfigStore(tmp_path)
    # a key routing the tmp file into a nonexistent subdir makes the
    # atomic write's open() fail with an OSError (chmod tricks don't
    # work under root, which CI is)
    assert store.save("no-such-dir/k", {"knobs": {}}) == 0
    assert store.stats()["save_errors"] == 1
    from dist_mnist_tpu.tune.store import _PENDING_TMP

    assert not _PENDING_TMP  # the failure path still cleared its tmp


def test_evidence_readback(tmp_path, mesh8):
    cfg = get_config("mlp_mnist")
    res = successive_halving(TOY, toy_objective, seed=0, base_budget=8)
    store = TunedConfigStore(tmp_path)
    key = tuning_key(cfg, mesh8)
    store.save(key, make_entry(cfg, mesh8, [res]))
    entry = store.load(key)
    assert entry["knobs"] == {"toy": res.winner}
    ev = entry["evidence"]["toy"]
    assert ev["metric"] == "toy_cost"
    assert ev["value"] == res.winner_score
    assert ev["baseline"] == res.default_score
    assert ev["bench_stage"] == "none"
    assert ev["measured_at"] > 0
    assert entry["backend"] == jax.default_backend()
    assert entry["jax_version"] == jax.__version__
    # the key fields ride along, human-readable, for store forensics
    assert entry["fields"]["kind"] == "'tuned'"


# -- apply paths ---------------------------------------------------------------


def _apply_poisoned(cfg, mesh, *, bucket_mb=0.5, store_dir=None, mode="auto",
                    protect=(), subsystem="train", tmp_path=None,
                    extra_knobs=()):
    """Seed a store with a hand-written winner entry and apply it."""
    import tempfile

    root = store_dir or tempfile.mkdtemp(prefix="tuned-store-")
    store = TunedConfigStore(root)
    knobs = {"overlap_bucket_mb": bucket_mb, "prefetch_depth": 4,
             "serve_max_batch": 32, "serve_seq_buckets": "auto",
             "scan_chunk": 100, **dict(extra_knobs)}
    store.save(tuning_key(cfg, mesh), {
        "knobs": knobs,
        "evidence": {"overlap_bucket_mb": {
            "metric": "exposed_gather_cost_mb", "value": 1.28,
            "baseline": 1.80, "bench_stage": "overlap",
            "measured_at": 1700000000.0}},
    })
    return apply_tuned(cfg, mesh, mode=mode, store_dir=root,
                       protect=protect, subsystem=subsystem)


def test_apply_tuned_train_hit_applies_and_journals(tmp_path, mesh8):
    cfg = get_config("mlp_mnist")
    prev = events.set_journal(events.RunJournal(tmp_path / "j.jsonl"))
    try:
        tuned_cfg, runtime = _apply_poisoned(cfg, mesh8)
    finally:
        events.set_journal(prev).close()
    assert tuned_cfg.overlap_bucket_mb == 0.5
    assert runtime == {"prefetch_depth": 4}  # serve knobs: wrong subsystem
    # scan_chunk is auto_apply=False: stored but never applied
    recs = [json.loads(line) for line in
            (tmp_path / "j.jsonl").read_text().splitlines()
            if '"tuning/applied"' in line]
    by_knob = {r["knob"]: r for r in recs}
    assert set(by_knob) == {"overlap_bucket_mb", "prefetch_depth"}
    ev = by_knob["overlap_bucket_mb"]
    # the acceptance-criteria evidence fields, replayed from the store
    assert ev["value"] == 0.5
    assert ev["metric"] == "exposed_gather_cost_mb"
    assert ev["measured"] == 1.28 and ev["baseline"] == 1.80
    assert ev["bench_stage"] == "overlap"
    assert ev["measured_at"] == 1700000000.0


def test_apply_tuned_serve_subsystem(mesh8):
    cfg = get_config("mlp_mnist")
    tuned_cfg, runtime = _apply_poisoned(cfg, mesh8, subsystem="serve")
    assert tuned_cfg.overlap_bucket_mb == cfg.overlap_bucket_mb  # train knob
    assert runtime == {"serve_max_batch": 32, "serve_seq_buckets": "auto"}


def test_apply_tuned_protect_pins_explicit_flags(mesh8):
    cfg = get_config("mlp_mnist")
    tuned_cfg, runtime = _apply_poisoned(
        cfg, mesh8, protect=("overlap_bucket_mb", "prefetch_depth"))
    assert tuned_cfg.overlap_bucket_mb == cfg.overlap_bucket_mb
    assert runtime == {}


def test_apply_tuned_miss_emits_stale_key(tmp_path, mesh8):
    cfg = get_config("mlp_mnist")
    prev = events.set_journal(events.RunJournal(tmp_path / "j.jsonl"))
    try:
        out_cfg, runtime = apply_tuned(cfg, mesh8, mode="auto",
                                       store_dir=str(tmp_path / "empty"))
    finally:
        events.set_journal(prev).close()
    assert out_cfg is cfg and runtime == {}
    recs = [json.loads(line) for line in
            (tmp_path / "j.jsonl").read_text().splitlines()]
    assert [r["event"] for r in recs] == ["tuning/stale_key"]
    assert recs[0]["mode"] == "auto" and recs[0]["subsystem"] == "train"


def test_apply_tuned_require_miss_raises(tmp_path, mesh8):
    cfg = get_config("mlp_mnist")
    with pytest.raises(TunedConfigMissError, match="never tuned"):
        apply_tuned(cfg, mesh8, mode="require",
                    store_dir=str(tmp_path / "empty"))
    with pytest.raises(TunedConfigMissError, match="no tuned-config store"):
        apply_tuned(cfg, mesh8, mode="require", store_dir=None)


def test_run_config_tuned_require_refuses_on_miss(tmp_path):
    cfg = get_config("mlp_mnist", train_steps=10, eval_every=0)
    with pytest.raises(TunedConfigMissError):
        run_config(cfg, data_dir=str(tmp_path / "data"), tuned="require",
                   tuned_dir=str(tmp_path / "empty"))


def test_run_config_tuned_off_bit_identical(tmp_path, monkeypatch):
    """--tuned=off must be bit-identical to the pre-tuner driver even
    with a poisoned store injected via the environment: the off path
    never consults (or imports) the tuner."""
    cfg = get_config("mlp_mnist", train_steps=20, eval_every=0)
    data = str(tmp_path / "data")
    monkeypatch.delenv("DIST_MNIST_TPU_TUNED_DIR", raising=False)
    state_ref, final_ref, _ = run_config(cfg, data_dir=data)
    # seed a store entry FOR THIS GEOMETRY that would change the run
    from dist_mnist_tpu.cluster.mesh import make_mesh

    store = TunedConfigStore(tmp_path / "store")
    store.save(tuning_key(cfg, make_mesh(cfg.mesh)),
               {"knobs": {"overlap_bucket_mb": 0.5, "prefetch_depth": 8}})
    monkeypatch.setenv("DIST_MNIST_TPU_TUNED_DIR", str(tmp_path / "store"))
    state_off, final_off, _ = run_config(cfg, data_dir=data, tuned="off")
    assert final_off["loss"] == final_ref["loss"]
    for a, b in zip(jax.tree.leaves(state_ref.params),
                    jax.tree.leaves(state_off.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -- rendering -----------------------------------------------------------------


def test_tail_run_renders_tuning_applied():
    sys.path.insert(0, "scripts")
    try:
        from tail_run import format_record
    finally:
        sys.path.pop(0)
    line = format_record({
        "seq": 7, "ts": 1700000000.0, "pid": 1, "gen": 0,
        "event": "tuning/applied", "knob": "overlap_bucket_mb",
        "value": 0.5, "metric": "exposed_gather_cost_mb",
        "measured": 1.28, "baseline": 1.80, "bench_stage": "overlap",
    })
    assert "overlap_bucket_mb=0.5" in line
    assert "exposed_gather_cost_mb" in line
    assert "1.28" in line and "vs default 1.80" in line
