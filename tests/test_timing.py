"""utils/timing.timed_chunks — the shared benchmark stop-clock contract.

Every on-chip number in docs/PERF.md flows through this helper (bench.py,
scripts/perf_sweep.py, scripts/step_ablation.py, scripts/vit_probe.py), so
its contract is load-bearing: exactly one un-timed warmup call, n timed
calls chained through the state, and the returned loss fetched from the
final call's output.
"""

import jax.numpy as jnp

from dist_mnist_tpu.utils.timing import timed_chunks


def test_timed_chunks_contract():
    calls = []

    def run_fn(state):
        calls.append(state)
        return state + 1, {"loss": jnp.float32(100.0 - state)}

    dt, final_state, loss = timed_chunks(run_fn, 0, n_chunks=3)
    # warmup (state 0) + 3 timed calls, chained through the state
    assert calls == [0, 1, 2, 3]
    assert final_state == 4
    # loss comes from the FINAL call's output (state 3 -> 97)
    assert loss == 97.0
    assert dt >= 0.0


def test_timed_chunks_zero_chunks_still_warms_up():
    calls = []

    def run_fn(state):
        calls.append(state)
        return state + 1, {"loss": jnp.float32(state)}

    dt, final_state, loss = timed_chunks(run_fn, 5, n_chunks=0)
    assert calls == [5]  # warmup only
    assert final_state == 6
    assert loss == 5.0  # the warmup output is what the clock fetched
