"""Collective matmul (parallel/collective_matmul.py): the overlapped
all-gather->matmul and matmul->reduce-scatter rings must match the dense
product exactly, shard correctly, and differentiate."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dist_mnist_tpu.cluster.mesh import MeshSpec, make_mesh
from dist_mnist_tpu.parallel.collective_matmul import (
    allgather_matmul,
    matmul_reducescatter,
)


@pytest.fixture(scope="module")
def mesh8m():
    return make_mesh(MeshSpec(data=1, model=8))


def _rand(shape, seed):
    return jnp.asarray(
        np.random.default_rng(seed).normal(size=shape), jnp.float32
    )


def test_allgather_matmul_matches_dense(mesh8m):
    x = jax.device_put(_rand((16, 12), 0),
                       NamedSharding(mesh8m, P("model", None)))
    w = jax.device_put(_rand((12, 24), 1),
                       NamedSharding(mesh8m, P(None, "model")))
    out = allgather_matmul(x, w, mesh8m)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) @ np.asarray(w),
                               rtol=1e-5, atol=1e-5)
    assert out.sharding.spec == P(None, "model")


def test_matmul_reducescatter_matches_dense(mesh8m):
    x = jax.device_put(_rand((16, 32), 2),
                       NamedSharding(mesh8m, P(None, "model")))
    w = jax.device_put(_rand((32, 8), 3),
                       NamedSharding(mesh8m, P("model", None)))
    out = matmul_reducescatter(x, w, mesh8m)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) @ np.asarray(w),
                               rtol=1e-5, atol=1e-5)
    # older jax canonicalizes away the trailing None in the spec
    assert out.sharding.spec in (P("model", None), P("model"))


def test_collective_matmul_differentiates(mesh8m):
    """Usable inside a training step: grads flow through the ppermute
    rings and match the dense matmul's grads."""
    x = _rand((8, 12), 4)
    w = _rand((12, 16), 5)

    def loss_ring(w_):
        return jnp.sum(allgather_matmul(x, w_, mesh8m) ** 2)

    def loss_dense(w_):
        return jnp.sum((x @ w_) ** 2)

    g_ring = jax.grad(loss_ring)(w)
    g_dense = jax.grad(loss_dense)(w)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense),
                               rtol=1e-5, atol=1e-5)


def test_allgather_matmul_under_jit_two_axes():
    """Composes with a data axis present (the realistic hybrid mesh) and
    under jit."""
    mesh = make_mesh(MeshSpec(data=2, model=4))
    x = jax.device_put(_rand((8, 12), 6),
                       NamedSharding(mesh, P("model", None)))
    w = jax.device_put(_rand((12, 8), 7),
                       NamedSharding(mesh, P(None, "model")))
    out = jax.jit(lambda a, b: allgather_matmul(a, b, mesh))(x, w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) @ np.asarray(w),
                               rtol=1e-5, atol=1e-5)
