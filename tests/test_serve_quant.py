"""Tier-1 quantized-serving tests (ops/quant.py + the serve stack's
--quant=int8 path): per-channel scale layout (incl. stacked scan/vmap
leaves and the per-tensor degenerate fallback), the leaf-selection rule,
float-vs-int8 parity + exact top-1 agreement across the ladder (dense,
ViT, MoE, sharded restore), quant-aware cache keys at both tiers, the
budget-admits-int8 pin, and the compile-free hot-swap re-quantize pin.
All CPU-mesh; models tiny for the tier-1 time budget."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dist_mnist_tpu.models.registry import get_model
from dist_mnist_tpu.ops.quant import (
    QuantizedArray,
    default_leaf_rule,
    dequantize,
    error_report,
    is_quantized,
    materialize,
    quantize,
    quantize_tree,
)
from dist_mnist_tpu.parallel.sharding import resolve_rules
from dist_mnist_tpu.serve import (
    ServeMemoryBudgetError,
    build_zoo_engine,
    load_for_serving,
    quantize_for_serving,
)
from dist_mnist_tpu.serve.engine import InferenceEngine

IMAGE_SHAPE = (16, 16, 3)


def _tiny_vit(**kw):
    kwargs = dict(depth=1, dim=16, heads=2, patch=4, pool="mean")
    kwargs.update(kw)
    return get_model("vit_tiny", **kwargs)


def _images(n, shape=(28, 28, 1), seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=(n, *shape), dtype=np.uint8)


# -- quantize/dequantize unit behavior ----------------------------------------

def test_per_channel_scale_layout_2d_and_stacked():
    w = jax.random.normal(jax.random.PRNGKey(0), (12, 32))
    qa = quantize(w)
    # amax reduces the CONTRACTION axis (ndim-2) only: one scale per
    # output channel, broadcastable against the int8 payload
    assert qa.q.shape == (12, 32) and qa.q.dtype == jnp.int8
    assert qa.scale.shape == (1, 32) and qa.mode == "channel"
    err = np.abs(np.asarray(dequantize(qa) - w))
    # symmetric int8: error bounded by scale/2 per channel
    assert (err <= np.asarray(qa.scale) / 2 + 1e-7).all()
    # stacked (scan/vmap) leaf keeps its leading dims in the scale, so
    # lax.scan slices the QuantizedArray exactly like the float original
    ws = jax.random.normal(jax.random.PRNGKey(1), (4, 12, 48))
    qs = quantize(ws)
    assert qs.scale.shape == (4, 1, 48)
    sliced = jax.tree.map(lambda a: a[2], qs)
    np.testing.assert_allclose(np.asarray(dequantize(sliced)),
                               np.asarray(dequantize(qs)[2]), rtol=1e-6)


def test_per_tensor_fallback_on_degenerate_channel():
    w = jnp.zeros((8, 4)).at[:, 0].set(jnp.linspace(-1.0, 1.0, 8))
    qa = quantize(w)  # columns 1..3 are all-zero -> per-channel degenerate
    assert qa.mode == "tensor"
    assert qa.scale.shape == (1, 4)  # broadcast to the keepdims layout
    np.testing.assert_allclose(np.asarray(dequantize(qa)), np.asarray(w),
                               atol=float(qa.scale.max()) / 2 + 1e-7)
    with pytest.raises(ValueError):
        quantize(jnp.ones((4,)))  # rank < 2 is a caller error


def test_leaf_rule_and_quantize_tree_idempotent():
    tree = {
        "hid": {"w": jnp.ones((4, 8)), "b": jnp.zeros((8,))},
        # "gate" is the MoE router's leaf name (parallel/moe.py init):
        # precision-critical, rank 2, and deliberately NOT in the rule
        "moe": {"gate": jnp.ones((4, 2)),
                "w1": jnp.ones((2, 4, 8)), "w2": jnp.ones((2, 8, 4))},
        "pos": jnp.ones((1, 9, 16)),  # embedding-like: not w/w1/w2
    }
    q = quantize_tree(tree)
    assert isinstance(q["hid"]["w"], QuantizedArray)
    assert isinstance(q["moe"]["w1"], QuantizedArray)
    assert isinstance(q["moe"]["w2"], QuantizedArray)
    # biases, the gate, and embeddings stay float
    assert not isinstance(q["hid"]["b"], QuantizedArray)
    assert not isinstance(q["moe"]["gate"], QuantizedArray)
    assert not isinstance(q["pos"], QuantizedArray)
    assert is_quantized(q) and not is_quantized(tree)
    # idempotent: re-running never double-quantizes
    q2 = quantize_tree(q)
    assert q2["hid"]["w"] is q["hid"]["w"]
    # materialize: identity on floats, dequant on QA — the one helper
    # compute code calls so float baselines stay bit-identical
    assert materialize(tree["hid"]["w"], jnp.float32) is tree["hid"]["w"]
    assert materialize(q["hid"]["w"], jnp.float32).dtype == jnp.float32
    report = error_report(tree, q)
    assert report["n_quantized"] == 3
    assert set(report["leaves"]) == {"hid/w", "moe/w1", "moe/w2"}
    for leaf in report["leaves"].values():
        assert leaf["max_abs_err"] >= 0.0 and leaf["mode"] == "channel"


# -- float-vs-int8 parity across the ladder -----------------------------------

def _agreement(eng_f, eng_q, images, atol):
    lf, lq = eng_f.predict(images), eng_q.predict(images)
    np.testing.assert_allclose(lf, lq, atol=atol)
    return float(np.mean(np.argmax(lf, -1) == np.argmax(lq, -1)))


def test_dense_mlp_parity_and_top1_agreement(mesh8):
    bundle_f = load_for_serving("mlp_mnist", mesh8)
    bundle_q = load_for_serving("mlp_mnist", mesh8, quant="int8")
    assert bundle_q.quant == "int8" and bundle_f.quant is None
    assert bundle_q.quant_report["n_quantized"] == 2
    eng_f = build_zoo_engine(bundle_f, mesh8, model_name="mlp_f",
                             max_bucket=8)
    eng_q = build_zoo_engine(bundle_q, mesh8, model_name="mlp_q",
                             max_bucket=8)
    assert eng_q.quant == "int8"
    # per-channel int8 on an MLP: logits move by well under a decision
    # boundary on this pool — exact top-1 agreement
    assert _agreement(eng_f, eng_q, _images(8), atol=0.05) == 1.0
    ratio = (eng_q.state_bytes_per_device()["param_bytes"]
             / eng_f.state_bytes_per_device()["param_bytes"])
    assert ratio < 0.30, f"int8 resident ratio {ratio:.3f}"


def test_vit_scan_and_moe_parity(mesh_tp):
    for kw, name in [({"depth": 2, "scan_blocks": True}, "vq_scan"),
                     ({"mlp_impl": "moe", "n_experts": 2}, "vq_moe")]:
        model = _tiny_vit(**kw)
        params, ms = model.init(jax.random.PRNGKey(0),
                                jnp.zeros((1, *IMAGE_SHAPE), jnp.float32))
        eng_f = InferenceEngine(
            model, params, ms, mesh_tp, model_name=name + "_f",
            image_shape=IMAGE_SHAPE, rules=resolve_rules("tp"), max_bucket=8)
        eng_q = InferenceEngine(
            model, quantize_tree(params), ms, mesh_tp,
            model_name=name + "_q", image_shape=IMAGE_SHAPE,
            rules=resolve_rules("tp"), max_bucket=8)
        assert eng_q.quant == "int8"  # auto-detected from the tree
        images = _images(8, shape=IMAGE_SHAPE, seed=3)
        # attention + (for moe) routing downstream of quantized matmuls:
        # wider tolerance than the MLP, agreement still exact on this pool
        assert _agreement(eng_f, eng_q, images, atol=0.2) == 1.0


def test_sharded_restore_serves_quantized(mesh_tp, tmp_path):
    """fsdp-trained -> TP-served -> int8: quantization happens AFTER the
    cross-strategy restore and preserves the live placements."""
    import dataclasses

    from dist_mnist_tpu.checkpoint.manager import CheckpointManager
    from dist_mnist_tpu.configs import get_config
    from dist_mnist_tpu.optim import adam
    from dist_mnist_tpu.train.state import create_train_state

    cfg = get_config("vit_tiny_cifar")
    cfg = dataclasses.replace(
        cfg, model_kwargs={"depth": 1, "dim": 16, "heads": 2,
                           "pool": "mean"},
        sharding_rules="fsdp")
    model = get_model(cfg.model, **cfg.model_kwargs)
    state = create_train_state(model, adam(1e-3),
                               jax.random.PRNGKey(cfg.seed),
                               jnp.zeros((1, 32, 32, 3), jnp.float32))
    mgr = CheckpointManager(tmp_path / "ckpt", async_save=False)
    assert mgr.save(state)
    mgr.wait()
    mgr.close()

    served_f = load_for_serving(cfg, mesh_tp,
                                checkpoint_dir=tmp_path / "ckpt",
                                sharding_rules="tp")
    served_q = load_for_serving(cfg, mesh_tp,
                                checkpoint_dir=tmp_path / "ckpt",
                                sharding_rules="tp", quant="int8")
    assert served_q.restored and served_q.quant == "int8"
    # the int8 payload kept the restore's NamedSharding (model-axis TP),
    # not a replicated fallback
    qkv = [leaf for leaf in jax.tree.leaves(served_q.params,
                                            is_leaf=lambda x: isinstance(
                                                x, QuantizedArray))
           if isinstance(leaf, QuantizedArray)]
    assert qkv and any(
        not leaf.q.sharding.is_fully_replicated for leaf in qkv)
    eng_f = build_zoo_engine(served_f, mesh_tp, model_name="vtp_f",
                             max_bucket=8)
    eng_q = build_zoo_engine(served_q, mesh_tp, model_name="vtp_q",
                             max_bucket=8)
    images = _images(8, shape=(32, 32, 3), seed=5)
    assert _agreement(eng_f, eng_q, images, atol=0.2) == 1.0
    assert eng_q.state_bytes_per_device()["param_bytes"] < \
        eng_f.state_bytes_per_device()["param_bytes"]


# -- memory budget: int8 fits where float refuses -----------------------------

def test_budget_admits_int8_where_float_refuses(mesh8):
    """The memory-budget pin: a budget sized between the int8 and float
    weight footprints refuses the float engine at construction and admits
    the quantized one. Needs a model whose weights dwarf the compiled
    code (the wide MLP) — on the ladder's tiny models the executables
    dominate and the comparison would be about XLA code size, not
    quantization."""
    model = get_model("mlp", hidden_units=2048)
    params, ms = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 28, 28, 1), jnp.float32))
    kw = dict(mesh=mesh8, image_shape=(28, 28, 1),
              rules=resolve_rules("dp"), max_bucket=8)
    qparams = quantize_tree(params)
    q_total = InferenceEngine(model, qparams, ms, model_name="mlpw_q",
                              **kw).state_bytes_per_device()["total_bytes"]
    f_total = InferenceEngine(model, params, ms, model_name="mlpw_f",
                              **kw).state_bytes_per_device()["total_bytes"]
    assert q_total < 0.30 * f_total
    budget = (q_total + f_total) // 2
    with pytest.raises(ServeMemoryBudgetError, match="weights alone"):
        InferenceEngine(model, params, ms, model_name="mlpw_refuse",
                        memory_budget_bytes=budget, **kw)
    eng = InferenceEngine(model, qparams, ms, model_name="mlpw_admit",
                          memory_budget_bytes=budget, **kw)
    # prewarm under the armed budget: CPU XLA materializes the
    # dequantized f32 weights as an executable TEMP buffer (TPU fuses the
    # dequant into the matmul's operand read), so the compiled cell's
    # XLA-attributed bytes are model-sized here. Re-arm with that
    # measured headroom — the budget machinery itself (weights floor +
    # per-cell accounting) is what this pins, not CPU fusion behavior.
    probe = InferenceEngine(model, qparams, ms, model_name="mlpw_probe",
                            **kw)
    probe.prewarm()
    exec_bytes = probe.cache.stats()["resident_bytes_executables"]
    eng.cache.set_budget(budget + exec_bytes + 64 * 1024,
                         base_bytes=q_total)
    assert eng.prewarm() > 0  # the int8 grid sits resident under budget
    assert eng.predict(_images(4)).shape == (4, 10)


# -- hot swap re-quantizes without recompiling --------------------------------

def test_hot_swap_requantizes_float_tree_compile_free(mesh8):
    bundle = load_for_serving("mlp_mnist", mesh8, quant="int8")
    eng = build_zoo_engine(bundle, mesh8, model_name="mlp_swap",
                           max_bucket=8)
    eng.prewarm()
    misses0 = eng.cache.misses
    # the rollout path hands full-width float checkpoints to a quantized
    # replica: swap must quantize on the fly, not recompile or refuse
    float_bundle = load_for_serving("mlp_mnist", mesh8)
    new_params = jax.tree.map(lambda p: p + 0.5, float_bundle.params)
    eng.swap_weights(new_params, float_bundle.model_state, version=2)
    assert is_quantized(eng.params) and eng.weights_version == 2
    eng.predict(_images(8, seed=7))
    assert eng.cache.misses == misses0, "hot-swap caused a recompile"


# -- quant-aware cache keys ---------------------------------------------------

def test_engine_cache_keys_fold_quant_in(mesh8):
    bundle_f = load_for_serving("mlp_mnist", mesh8)
    bundle_q = load_for_serving("mlp_mnist", mesh8, quant="int8")
    eng_f = build_zoo_engine(bundle_f, mesh8, model_name="mlp",
                             max_bucket=8)
    eng_q = build_zoo_engine(bundle_q, mesh8, model_name="mlp",
                             max_bucket=8)
    # same model name, same bucket: quant must split BOTH cache tiers —
    # an int8 engine must never execute (or disk-load) a float program
    assert eng_f._key(8) != eng_q._key(8)
    assert eng_f._store_key(8) != eng_q._store_key(8)
    # and the float keys are byte-identical to the pre-quant format, so
    # existing warm disk caches survive the feature landing
    assert "quant" not in eng_f._store_key(8)
    assert "wint8" in eng_q._key(8)[3]


def test_train_compile_cache_key_fields_fold_quant_in(mesh8):
    from dist_mnist_tpu.cli.train import compile_cache_key_fields
    from dist_mnist_tpu.compilecache.store import cache_key
    from dist_mnist_tpu.configs import get_config

    cfg = get_config("mlp_mnist")
    base = compile_cache_key_fields(cfg, mesh8)
    quant = compile_cache_key_fields(cfg, mesh8, quant="int8")
    none = compile_cache_key_fields(cfg, mesh8, quant="none")
    assert cache_key({"kind": "serve", **base}) != \
        cache_key({"kind": "serve", **quant})
    # "none" is the no-op spelling: identical fields -> identical key,
    # keeping every historical cache entry warm
    assert base == none and "quant" not in base
