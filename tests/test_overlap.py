"""Communication/compute overlap for the fsdp hot path (ISSUE 7).

Acceptance contract: the overlapped bucket schedule (parallel/overlap.py)
is a value-level IDENTITY — its loss trajectory over >= 2 epochs on the
8-device CPU mesh is BIT-identical to the barriered serial twin, to plain
GSPMD fsdp, and (ring mode) to the ppermute decomposition; the compiled
program emits its collectives in chunked (per-bucket) form; the schedule
composes with TP (`fsdp_tp`); plan/bucket metadata is exact; and the
driver refuses overlap under a rule set with nothing to gather.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dist_mnist_tpu import optim
from dist_mnist_tpu.cluster.mesh import DATA_AXIS
from dist_mnist_tpu.data.pipeline import ShardedBatcher, shard_batch
from dist_mnist_tpu.models import get_model
from dist_mnist_tpu.parallel.overlap import (
    OverlapConfig,
    build_param_gather,
    plan_stats,
    prefetched_layer_matmul,
)
from dist_mnist_tpu.parallel.sharding import (
    DP_RULES,
    FSDP_RULES,
    FSDP_TP_RULES,
    shard_train_state,
)
from dist_mnist_tpu.train import create_train_state
from dist_mnist_tpu.train.step import make_train_step

#: tiny bucket -> every sharded leaf closes its own bucket; the MLP has two
#: fsdp-sharded matrices, so chunked structure is visible with 2+ buckets
TINY_BUCKET = 1e-6

VARIANTS = {
    "gspmd": None,  # implicit gather-on-use — the PR 3 baseline
    "serial": OverlapConfig(bucket_mb=TINY_BUCKET, serial=True),
    "overlap": OverlapConfig(bucket_mb=TINY_BUCKET),
    "ring": OverlapConfig(bucket_mb=TINY_BUCKET, chunk="ring"),
}


def _mlp_state(mesh, rules, hidden=64):
    model = get_model("mlp", hidden_units=hidden)
    opt = optim.adam(1e-3)
    state = create_train_state(model, opt, jax.random.PRNGKey(0),
                               jnp.zeros((1, 28, 28, 1), jnp.uint8))
    return model, opt, shard_train_state(state, mesh, rules)


# ------------------------------------------------------------- trajectory --


def test_overlap_trajectories_bit_identical_two_epochs(mesh8, small_mnist):
    """Same seed, same stream, two full epochs: gathers are copies and
    optimization_barrier is a bit-exact identity, so ALL schedules —
    GSPMD, barriered serial, overlapped buckets, ppermute ring — must
    produce the SAME bits, not just close floats. An overlap 'win' that
    perturbed the math would be a different optimizer."""
    batch_size = 512
    n_steps = 2 * (len(small_mnist.train_labels) // batch_size)
    assert n_steps >= 8
    traj = {}
    for name, overlap in VARIANTS.items():
        model, opt, state = _mlp_state(mesh8, FSDP_RULES)
        step = make_train_step(model, opt, mesh8, rules=FSDP_RULES,
                               overlap=overlap)
        batches = iter(ShardedBatcher(small_mnist, batch_size, mesh8, seed=0))
        losses = []
        for _ in range(n_steps):
            state, out = step(state, next(batches))
            losses.append(out["loss"])
        traj[name] = np.asarray(jax.device_get(losses), np.float64)
    for name in ("serial", "overlap", "ring"):
        np.testing.assert_array_equal(
            traj[name], traj["gspmd"],
            err_msg=f"{name} diverged from gspmd fsdp")
    assert traj["gspmd"][-1] < traj["gspmd"][0]  # it actually trained


def test_overlap_composes_with_tp(mesh_tp, small_mnist):
    """fsdp_tp: the gather plan must leave TP-sharded dims alone (only the
    fsdp axis is removed from the output layout), and the overlapped
    trajectory must stay bit-identical to GSPMD under the composed rules."""
    batch_size = 512
    traj = {}
    for name, overlap in (("gspmd", None),
                          ("overlap", OverlapConfig(bucket_mb=TINY_BUCKET))):
        model, opt, state = _mlp_state(mesh_tp, FSDP_TP_RULES)
        step = make_train_step(model, opt, mesh_tp, rules=FSDP_TP_RULES,
                               overlap=overlap)
        batches = iter(ShardedBatcher(small_mnist, batch_size, mesh_tp,
                                      seed=0))
        losses = []
        for _ in range(6):
            state, out = step(state, next(batches))
            losses.append(out["loss"])
        traj[name] = np.asarray(jax.device_get(losses), np.float64)
    np.testing.assert_array_equal(traj["overlap"], traj["gspmd"])


# ------------------------------------------------------------ collectives --


def _compiled_text(mesh, overlap, batch=64):
    model, opt, state = _mlp_state(mesh, FSDP_RULES)
    step = make_train_step(model, opt, mesh, rules=FSDP_RULES, donate=False,
                           overlap=overlap)
    img = np.zeros((batch, 28, 28, 1), np.uint8)
    lab = np.zeros((batch,), np.int32)
    return step.compiled_text(state, shard_batch(
        {"image": img, "label": lab}, mesh))


def test_overlap_hlo_emits_chunked_collectives(mesh8):
    """The overlapped program must keep its collectives in CHUNKED form:
    at least one gather collective per bucket (the bucket boundary is a
    shard_map region GSPMD cannot merge away) plus a collective gradient
    reduction. Per-bucket granularity is what the scheduler overlaps."""
    cfg = OverlapConfig(bucket_mb=TINY_BUCKET)
    text = _compiled_text(mesh8, cfg)
    if text is None:
        pytest.skip("backend cannot render compiled HLO text")
    model, opt, state = _mlp_state(mesh8, FSDP_RULES)
    stats = plan_stats(state.params, mesh8, FSDP_RULES, cfg)
    assert stats["buckets"] >= 2  # tiny bucket => one bucket per matrix
    assert text.count("all-gather(") >= stats["buckets"]
    assert ("all-reduce(" in text) or ("reduce-scatter(" in text)


def test_ring_hlo_uses_collective_permute(mesh8):
    """chunk='ring' decomposes every gather into ppermute hops — the
    compiled program must carry collective-permutes and NO all-gather
    (n-1 hops per leaf, like parallel/collective_matmul.py's rings)."""
    text = _compiled_text(mesh8, OverlapConfig(bucket_mb=TINY_BUCKET,
                                               chunk="ring"))
    if text is None:
        pytest.skip("backend cannot render compiled HLO text")
    assert text.count("collective-permute(") > 0
    assert "all-gather(" not in text


# ------------------------------------------------------------------- plan --


def test_plan_stats_bucket_grouping(mesh8):
    _, _, state = _mlp_state(mesh8, FSDP_RULES)
    tiny = plan_stats(state.params, mesh8, FSDP_RULES,
                      OverlapConfig(bucket_mb=TINY_BUCKET))
    huge = plan_stats(state.params, mesh8, FSDP_RULES,
                      OverlapConfig(bucket_mb=1e3))
    # mlp-64: hid/w (784,64), hid/b (64,), and sm/w (64,10) all have a dim
    # divisible by 8, so the shape rule shards them; sm/b (10,) does not
    assert tiny["sharded_leaves"] == 3
    assert tiny["total_leaves"] == 4
    assert tiny["buckets"] == 3       # every sharded leaf closes a bucket
    assert huge["buckets"] == 1       # nothing reaches the threshold
    assert huge["sharded_leaves"] == 3
    gathered = sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in (state.params["hid"]["w"], state.params["hid"]["b"],
                     state.params["sm"]["w"]))
    assert tiny["gathered_bytes"] == huge["gathered_bytes"] == gathered


def test_gather_is_identity_with_gathered_layout(mesh8):
    """build_param_gather under jit: values unchanged bitwise, fsdp leaves
    come out with the data axis REMOVED from their spec, non-sharded
    leaves pass through."""
    _, _, state = _mlp_state(mesh8, FSDP_RULES)
    gather = build_param_gather(mesh8, FSDP_RULES, OverlapConfig())

    out = jax.jit(gather)(state.params)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(state.params),
            jax.tree_util.tree_leaves_with_path(out)):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # sharded matrix now replicated over data; bias spec untouched
    assert out["hid"]["w"].sharding.is_equivalent_to(
        NamedSharding(mesh8, P(None, None)), 2)


def test_overlap_requires_fsdp_rules(mesh8):
    with pytest.raises(ValueError, match="fsdp"):
        build_param_gather(mesh8, DP_RULES, OverlapConfig())


@pytest.mark.parametrize("bad", [
    {"chunk": "rinng"},
    {"bucket_mb": 0.0},
    {"bucket_mb": -1.0},
])
def test_overlap_config_validates(bad):
    with pytest.raises(ValueError):
        OverlapConfig(**bad)


def test_cli_rejects_overlap_without_fsdp(mesh8):
    """--overlap on a dp config must fail eagerly with a pointed message,
    not silently train unoverlapped (the resolve_rules precedent)."""
    from dist_mnist_tpu.cli.train import run_config
    from dist_mnist_tpu.configs import get_config

    cfg = dataclasses.replace(get_config("lenet5_fashion"), overlap=True,
                              train_steps=1, eval_every=0)
    assert cfg.sharding_rules == "dp"
    with pytest.raises(ValueError, match="fsdp"):
        run_config(cfg, data_dir="/definitely-not-a-dir", mesh=mesh8)


# -------------------------------------------------------------- primitive --


def test_prefetched_layer_matmul_matches_serial(mesh8):
    """The lax.scan double-buffered layer stack equals the plain serial
    gather-then-matmul loop bitwise (gathers are copies)."""
    L, B, D = 4, 16, 32
    key = jax.random.PRNGKey(3)
    x = jax.random.normal(key, (B, D), jnp.float32)
    ws = jax.random.normal(jax.random.fold_in(key, 1), (L, D, D),
                           jnp.float32) / np.sqrt(D)
    x_s = jax.device_put(x, NamedSharding(mesh8, P(DATA_AXIS, None)))
    ws_s = jax.device_put(ws, NamedSharding(mesh8, P(None, DATA_AXIS, None)))

    got = prefetched_layer_matmul(x_s, ws_s, mesh8)
    want = x
    for l in range(L):
        want = jnp.tanh(want @ ws[l])
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert got.sharding.is_equivalent_to(
        NamedSharding(mesh8, P(DATA_AXIS, None)), 2)


@pytest.mark.parametrize("bad_shape", [(32, 32), (2, 30, 30)])
def test_prefetched_layer_matmul_validates(mesh8, bad_shape):
    x = jnp.zeros((16, bad_shape[-1]), jnp.float32)
    with pytest.raises(ValueError):
        prefetched_layer_matmul(x, jnp.zeros(bad_shape, jnp.float32), mesh8)


# ------------------------------------------------------------------- hook --


def test_overlap_hook_publishes_numeric_plan(mesh8):
    from dist_mnist_tpu.hooks import OverlapHook

    class _Writer:
        def __init__(self):
            self.rows = []

        def scalars(self, vals, step):
            self.rows.append((dict(vals), step))

    class _Loop:
        initial_step = 0

    _, _, state = _mlp_state(mesh8, FSDP_RULES)
    stats = plan_stats(state.params, mesh8, FSDP_RULES,
                       OverlapConfig(bucket_mb=TINY_BUCKET, serial=True))
    writer = _Writer()
    hook = OverlapHook(writer, stats)
    hook.begin(_Loop())
    (vals, step), = writer.rows
    assert step == 0
    assert vals["overlap/buckets"] == stats["buckets"]
    assert vals["overlap/gathered_bytes"] == stats["gathered_bytes"]
    assert vals["overlap/serial"] == 1.0
    assert "overlap/chunk" not in vals  # strings never become scalars
    assert all(isinstance(v, (int, float)) for v in vals.values())
    assert hook.last == vals
