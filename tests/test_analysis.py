"""graftlint (dist_mnist_tpu.analysis) — ISSUE 15 tentpole wiring.

Three layers, all jax-free (the analysis package is stdlib-only by
design, so this file keeps tier-1's no-accelerator property):

1. per-rule regression pairs — for every rule, a violating fixture that
   MUST produce its finding (the true-positive regression test) and a
   clean twin that must not;
2. the engine contracts — suppression grammar (unified + legacy forms,
   own-line + line-above, multi-rule, reasonless = finding), baseline
   round-trip (match, partition, stale, empty-reason hard error), JSON
   schema;
3. the meta-test: `python -m dist_mnist_tpu.analysis` on THIS tree exits
   0 — the lint suite is a tier-1 invariant from here on.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from dist_mnist_tpu.analysis import baseline as baseline_mod
from dist_mnist_tpu.analysis import rules as rules_mod
from dist_mnist_tpu.analysis.core import Context, Finding, SourceFile, run
from dist_mnist_tpu.analysis.rules import (
    bench_stages, host_sync, registry_drift, spmd_divergence,
    thread_lifecycle)

REPO_ROOT = Path(__file__).resolve().parent.parent


def sf_of(tmp_path: Path, text: str, name: str = "mod.py") -> SourceFile:
    p = tmp_path / name
    p.write_text(textwrap.dedent(text))
    return SourceFile(p, name)


def repo_of(tmp_path: Path, files: dict[str, str]) -> Context:
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return Context(tmp_path)


# -- registry -----------------------------------------------------------------

def test_at_least_six_rules_registered():
    assert len(rules_mod.ALL_RULES) >= 6
    assert len(set(rules_mod.RULE_IDS)) == len(rules_mod.ALL_RULES)


def test_select_unknown_rule_raises():
    with pytest.raises(KeyError):
        rules_mod.select(["no-such-rule"])


# -- host-sync ----------------------------------------------------------------

def test_host_sync_flags_in_function_syncs(tmp_path):
    sf = sf_of(tmp_path, """\
        import jax
        def step(x, arr):
            a = float(x)
            b = jax.device_get(x)
            c = arr.item()
            return a, b, c
        """)
    lines = [f.line for f in host_sync.scan_source(sf)]
    assert lines == [3, 4, 5]


def test_host_sync_module_level_is_import_time_not_hot_path(tmp_path):
    # the AST rule's improvement over the tokenize lint: module-level
    # calls run once at import, never per step
    sf = sf_of(tmp_path, """\
        import jax
        EPS = float("1e-8")
        def step(x):
            return x
        """)
    assert host_sync.scan_source(sf) == []


def test_host_sync_hot_path_set_is_nonempty_and_curated():
    files = host_sync.hot_path_files(REPO_ROOT)
    names = {p.name for p in files}
    assert {"step.py", "state.py", "prefetch.py", "builtin.py"} <= names


# -- spmd-divergence ----------------------------------------------------------

def test_spmd_flags_collective_under_rank_branch(tmp_path):
    sf = sf_of(tmp_path, """\
        import jax
        def sync(state):
            if jax.process_index() == 0:
                state = broadcast_one_to_all(state)
            return state
        """)
    finds = spmd_divergence.scan_source(sf)
    assert len(finds) == 1 and finds[0].line == 4
    assert "deadlock" in finds[0].message


def test_spmd_flags_ckpt_save_under_rank_branch_but_not_writer(tmp_path):
    sf = sf_of(tmp_path, """\
        def save(ckpt_manager, writer, step, state):
            if jax.process_index() == 0:
                writer.save(step)            # chief-writes-summaries: legal
                ckpt_manager.save(step, state)   # orbax barrier: deadlock
        """)
    finds = spmd_divergence.scan_source(sf)
    assert [f.line for f in finds] == [4]


def test_spmd_early_return_guard_is_clean(tmp_path):
    # the guard puts the collective OUTSIDE the if body — every rank
    # that reaches it participates
    sf = sf_of(tmp_path, """\
        def sync(state):
            if jax.process_index() != 0:
                return state
            return broadcast_one_to_all(state)
        """)
    assert spmd_divergence.scan_source(sf) == []


def test_spmd_else_arm_of_rank_branch_is_flagged(tmp_path):
    sf = sf_of(tmp_path, """\
        def sync(x):
            if jax.process_index() == 0:
                pass
            else:
                x = psum(x, "i")
            return x
        """)
    assert [f.line for f in spmd_divergence.scan_source(sf)] == [5]


# -- cache-key ----------------------------------------------------------------

def _cache_key_repo(tmp_path, keyed: str) -> Context:
    from dist_mnist_tpu.analysis.rules.cache_key import RUNTIME_ONLY
    configs = "\n".join(
        ["import dataclasses",
         "@dataclasses.dataclass(frozen=True)",
         "class Config:",
         '    model: str = "mlp"',
         "    lr_gamma: float = 0.9"]
        + [f"    {name}: int = 0" for name in sorted(RUNTIME_ONLY)]) + "\n"
    return repo_of(tmp_path, {
        "dist_mnist_tpu/configs.py": configs,
        "dist_mnist_tpu/compilecache/key_fields.py": (
            "def compile_cache_key_fields(cfg, mesh):\n"
            f"    return {keyed}\n"),
    })


def test_cache_key_flags_unkeyed_unallowlisted_field(tmp_path):
    ctx = _cache_key_repo(tmp_path, '{"model": cfg.model}')
    finds = rules_mod.select(["cache-key"])[0].check(ctx)
    assert any("Config.lr_gamma" in f.message for f in finds)
    assert not any("Config.model" in f.message for f in finds)


def test_cache_key_clean_when_all_fields_keyed_or_allowlisted(tmp_path):
    ctx = _cache_key_repo(
        tmp_path, '{"model": cfg.model, "lr_gamma": cfg.lr_gamma}')
    assert rules_mod.select(["cache-key"])[0].check(ctx) == []


def test_cache_key_reports_stale_allowlist_entry(tmp_path):
    # a repo whose Config lost a field the allowlist still names
    ctx = repo_of(tmp_path, {
        "dist_mnist_tpu/configs.py": """\
            import dataclasses
            @dataclasses.dataclass(frozen=True)
            class Config:
                model: str = "mlp"
            """,
        "dist_mnist_tpu/compilecache/key_fields.py": """\
            def compile_cache_key_fields(cfg, mesh):
                return {"model": cfg.model}
            """,
    })
    finds = rules_mod.select(["cache-key"])[0].check(ctx)
    assert any("no longer a Config field" in f.message for f in finds)


# -- thread-lifecycle ---------------------------------------------------------

def test_thread_lifecycle_flags_unnamed_and_unregistered(tmp_path):
    sf = sf_of(tmp_path, """\
        import threading
        def spawn():
            t = threading.Thread(target=print, daemon=True)
            u = threading.Thread(target=print, name="Mystery-1")
            t.start(); u.start()
        """)
    finds = thread_lifecycle.scan_source(sf, prefixes={"Worker"})
    msgs = [f.message for f in finds]
    assert any("no resolvable literal" in m for m in msgs)
    assert any("'Mystery-1'" in m and "no prefix" in m for m in msgs)
    # neither thread has a join in the enclosing function
    assert any("no shutdown path" in m for m in msgs)


def test_thread_lifecycle_clean_class_with_close(tmp_path):
    sf = sf_of(tmp_path, """\
        import threading
        class Pump:
            def __init__(self):
                self._t = threading.Thread(
                    target=self._loop, name="Worker-pump", daemon=True)
            def _loop(self): pass
            def close(self):
                self._t.join()
        """)
    assert thread_lifecycle.scan_source(sf, prefixes={"Worker"}) == []


def test_thread_lifecycle_function_local_join_is_a_shutdown_path(tmp_path):
    sf = sf_of(tmp_path, """\
        import threading
        def run():
            t = threading.Thread(target=print, name="Worker-tmp")
            t.start()
            t.join()
        """)
    assert thread_lifecycle.scan_source(sf, prefixes={"Worker"}) == []


def test_thread_lifecycle_flags_subclass_without_shutdown(tmp_path):
    sf = sf_of(tmp_path, """\
        import threading
        class Looper(threading.Thread):
            def run(self): pass
        class Good(threading.Thread):
            def run(self): pass
            def stop(self): pass
        """)
    finds = thread_lifecycle.scan_source(sf, prefixes={"Worker"})
    assert len(finds) == 1 and "Looper" in finds[0].message


def test_thread_lifecycle_conftest_registry_parses():
    prefixes = thread_lifecycle.conftest_prefixes(Context(REPO_ROOT))
    # the live registry: the rule reads tests/conftest.py, so a prefix
    # removed there fails HERE, not silently in the leak-check
    assert {"DevicePrefetcher", "SnapshotWriter", "ServeBatcher",
            "LaunchPump", "Router"} <= prefixes


# -- journal-drift / metric-drift ---------------------------------------------

_DOC = """\
    ## Metrics

    | namespace | source | highlights |
    |---|---|---|
    | `train/*` | loop | step timings |
    | `dead/metric` | nobody | stale row |

    ## Events

    | event | emitter | payload |
    |---|---|---|
    | `good_event` | mod.py | step |
    | `dead_event` | nobody | stale row |
    """


def _drift_repo(tmp_path, body: str) -> Context:
    return repo_of(tmp_path, {
        "docs/OBSERVABILITY.md": _DOC,
        "dist_mnist_tpu/mod.py": body,
    })


def test_journal_drift_both_directions_and_hygiene(tmp_path):
    ctx = _drift_repo(tmp_path, """\
        def f(events, step):
            events.emit("good_event", step=step)
            events.emit("rogue_event", step=step)
            events.emit("Bad-Charset")
        """)
    finds = registry_drift.RULE.check(ctx)
    msgs = "\n".join(f.message for f in finds)
    assert "'rogue_event' is emitted here but missing" in msgs
    assert "'dead_event' is emitted nowhere" in msgs
    assert "'Bad-Charset' violates the hygiene charset" in msgs
    assert "good_event" not in msgs


def test_metric_drift_wildcard_match_and_rogue_tag(tmp_path):
    ctx = _drift_repo(tmp_path, """\
        def f(writer, v):
            writer.scalar("train/loss", v)        # matches train/*
            writer.scalar("mystery/thing", v)     # undocumented
        """)
    finds = registry_drift.METRIC_RULE.check(ctx)
    msgs = "\n".join(f.message for f in finds)
    assert "'mystery/thing' matches no namespace" in msgs
    assert "train/loss" not in msgs
    assert "'dead/metric' has no trace" in msgs


def test_metric_drift_fstring_prefix_checks_namespace(tmp_path):
    ctx = _drift_repo(tmp_path, """\
        def f(writer, k, v):
            writer.scalar(f"train/{k}", v)     # prefix under train/*
            writer.scalar(f"rogue/{k}", v)     # prefix matches nothing
        """)
    finds = registry_drift.METRIC_RULE.check(ctx)
    msgs = "\n".join(f.message for f in finds)
    assert "'rogue/'" in msgs and "'train/'" not in msgs


def test_live_doc_tables_parse():
    text = (REPO_ROOT / "docs/OBSERVABILITY.md").read_text()
    events = registry_drift._doc_names(
        text, registry_drift.EVENT_TABLE_HEADER)
    metrics = registry_drift._doc_names(
        text, registry_drift.METRIC_TABLE_HEADER)
    assert {"checkpoint_commit", "snapshot_fork", "peer_restore",
            "save_stall", "snapshot_drop"} <= set(events)
    assert "fleet/straggler_ratio" in metrics


# -- bench-stages -------------------------------------------------------------

_BENCH = """\
    import argparse
    p = argparse.ArgumentParser()
    p.add_argument("--serve", action="store_true")
    p.add_argument("--input", action="store_true")
    p.add_argument("--steps", type=int, default=10)
    """


def _bench_repo(tmp_path, measure: str, retry: str) -> Context:
    return repo_of(tmp_path, {
        "bench.py": _BENCH,
        "scripts/measure_all.sh": measure,
        "scripts/retry_missed_stages.sh": retry,
    })


def test_bench_stage_missing_from_one_script_is_flagged(tmp_path):
    ctx = _bench_repo(
        tmp_path,
        "python bench.py --serve\npython bench.py --input\n",
        "python bench.py --serve\n")  # retry forgot --input
    finds = bench_stages.RULE.check(ctx)
    assert len(finds) == 1
    assert "--input" in finds[0].message
    assert "retry_missed_stages.sh" in finds[0].message


def test_bench_reverse_catches_undefined_flag(tmp_path):
    ctx = _bench_repo(
        tmp_path,
        "python bench.py --serve --typo-stage\npython bench.py --input\n",
        "python bench.py --serve\npython bench.py --input\n")
    finds = bench_stages.RULE.check(ctx)
    assert any("--typo-stage" in f.message and "no such flag" in f.message
               for f in finds)


def test_bench_clean_when_both_scripts_cover_all_modes(tmp_path):
    ctx = _bench_repo(
        tmp_path,
        "python bench.py --serve\npython bench.py --input --steps 5\n",
        "python bench.py --serve\npython bench.py --input\n")
    assert bench_stages.RULE.check(ctx) == []


# -- suppressions -------------------------------------------------------------

def test_suppression_own_line_line_above_and_multi_rule(tmp_path):
    sf = sf_of(tmp_path, """\
        def step(x, arr):
            a = float(x)  # lint: ok[host-sync] fixture same-line
            # lint: ok[host-sync] fixture marker-above
            b = jax.device_get(x)
            # lint: ok[host-sync, spmd-divergence] fixture multi-rule
            c = arr.item()
            return a, b, c
        """)
    assert sf.is_suppressed("host-sync", 2)
    assert sf.is_suppressed("host-sync", 4)
    assert sf.is_suppressed("host-sync", 6)
    assert sf.is_suppressed("spmd-divergence", 6)
    assert not sf.is_suppressed("host-sync", 7)


def test_legacy_host_sync_marker_still_honored(tmp_path):
    sf = sf_of(tmp_path, """\
        def step(x):
            return float(x)  # host-sync-ok: legacy form
        """)
    assert sf.is_suppressed("host-sync", 2)
    assert sf.suppressions[0].legacy


def test_reasonless_suppression_is_itself_a_finding(tmp_path):
    ctx = repo_of(tmp_path, {
        "dist_mnist_tpu/mod.py": """\
            def step(x):
                a = float(x)  # lint: ok[host-sync]
                return a
            """,
    })
    ctx.source("dist_mnist_tpu/mod.py")  # pull into the parse cache
    result = run(ctx, [])
    assert [f.rule for f in result["findings"]] == ["suppression-hygiene"]


def test_engine_applies_suppressions_to_rule_findings(tmp_path):
    class Fires:
        rule_id = "host-sync"
        doc = ""

        def check(self, ctx):
            sf = ctx.source("dist_mnist_tpu/mod.py")
            return [Finding("host-sync", sf.rel, 2, "fixture finding")]

    ctx = repo_of(tmp_path, {
        "dist_mnist_tpu/mod.py": """\
            def step(x):
                return float(x)  # lint: ok[host-sync] fixture reason
            """,
    })
    result = run(ctx, [Fires()])
    assert result["findings"] == [] and result["suppressed"] == 1


# -- baseline -----------------------------------------------------------------

def test_baseline_round_trip_partition_and_stale(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps({"entries": [
        {"rule": "r", "path": "a.py", "match": "known debt",
         "reason": "fixture"},
        {"rule": "r", "path": "gone.py", "match": "paid off",
         "reason": "fixture"},
    ]}))
    bl = baseline_mod.Baseline.load(path)
    new, old = bl.partition([
        Finding("r", "a.py", 3, "this is known debt, grandfathered"),
        Finding("r", "a.py", 9, "a fresh regression"),
    ])
    assert [f.line for f in old] == [3]
    assert [f.line for f in new] == [9]
    assert [e["match"] for e in bl.stale_entries()] == ["paid off"]


def test_baseline_rejects_empty_reason_and_missing_keys():
    with pytest.raises(baseline_mod.BaselineError, match="empty reason"):
        baseline_mod.Baseline([{"rule": "r", "path": "p", "match": "m",
                                "reason": "   "}])
    with pytest.raises(baseline_mod.BaselineError, match="missing"):
        baseline_mod.Baseline([{"rule": "r", "path": "p"}])


def test_live_baseline_entries_all_carry_reasons():
    bl = baseline_mod.Baseline.load(
        REPO_ROOT / baseline_mod.DEFAULT_NAME)  # raises on empty reasons
    for e in bl.entries:
        assert e["reason"].strip()


# -- the meta-test: this tree is clean ----------------------------------------

def _run_cli(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "dist_mnist_tpu.analysis", *args],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)


def test_live_tree_is_clean_and_json_schema_is_stable():
    proc = _run_cli("--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    assert data["version"] == 1
    assert set(data) == {"version", "rules", "findings", "baselined",
                         "suppressed", "stale_baseline"}
    assert data["findings"] == []
    assert len(data["rules"]) >= 6
    assert data["suppressed"] > 0      # the ported hot-path annotations
    assert data["stale_baseline"] == []  # no paid-off debt left behind


def test_cli_rule_selection_and_unknown_rule_exit_codes():
    assert _run_cli("--rules", "bench-stages").returncode == 0
    proc = _run_cli("--rules", "no-such-rule")
    assert proc.returncode == 2
    assert "no-such-rule" in proc.stderr


def test_cli_reports_violations_with_exit_1(tmp_path):
    # a copy of the minimal drift repo, driven through the real CLI
    for rel, text in {
        "docs/OBSERVABILITY.md": _DOC,
        "dist_mnist_tpu/__init__.py": "",
        "dist_mnist_tpu/mod.py": (
            "def f(events):\n"
            "    events.emit('rogue_event')\n"),
        "scripts/measure_all.sh": "",
    }.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    proc = _run_cli("--repo-root", str(tmp_path), "--rules",
                    "journal-drift")
    assert proc.returncode == 1
    assert "dist_mnist_tpu/mod.py:2: journal-drift" in proc.stdout
