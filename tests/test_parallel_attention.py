"""Sequence parallelism (ring + Ulysses) and the explicit-collectives step:
every variant must match the plain XLA attention / GSPMD step numerically."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from _hypothesis_stub import given, settings, st

from dist_mnist_tpu.cluster.mesh import MeshSpec, make_mesh
from dist_mnist_tpu.ops.nn import dot_product_attention
from dist_mnist_tpu.parallel.ring_attention import (
    ring_attention,
    ring_self_attention,
)
from dist_mnist_tpu.parallel.ulysses import ulysses_self_attention


@pytest.fixture(scope="module")
def mesh_seq():
    """4-way sequence-parallel mesh (x2 data)."""
    return make_mesh(MeshSpec(data=2, model=1, seq=4))


def _qkv(b=2, s=32, h=4, d=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    return mk(), mk(), mk()


def test_ring_matches_reference(mesh_seq):
    q, k, v = _qkv()
    expected = dot_product_attention(q, k, v)
    with mesh_seq:
        out = ring_self_attention(q, k, v, mesh_seq)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


def test_ring_under_jit(mesh_seq):
    q, k, v = _qkv(seed=1)
    expected = dot_product_attention(q, k, v)
    with mesh_seq:
        out = jax.jit(lambda a, b, c: ring_self_attention(a, b, c, mesh_seq))(
            q, k, v
        )
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


def test_ring_adaptive_fallback_no_mesh():
    """Outside any seq mesh, ring_attention degrades to exact attention."""
    q, k, v = _qkv(seed=2)
    out = ring_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dot_product_attention(q, k, v)),
        rtol=1e-5, atol=1e-6,
    )


def test_ring_attention_memory_advantage_long_seq():
    """At LONG sequence length the ring path never materializes the S x S
    score matrix: per-device temp memory is an order of magnitude below
    dense attention's (SURVEY.md §5.7 — the reason SP exists). Uses XLA's
    compile-time memory accounting (memory_analysis) so the check runs in
    seconds on the CPU mesh with S in the thousands, no execution."""
    b, s, h, d = 1, 4096, 4, 64
    ring_ways = 4
    mesh = make_mesh(MeshSpec(data=1, seq=ring_ways))
    shape = jax.ShapeDtypeStruct((b, s, h, d), jnp.float32)

    dense_mem = (
        jax.jit(dot_product_attention)
        .lower(shape, shape, shape).compile().memory_analysis()
    )
    with mesh:
        ring_mem = (
            jax.jit(lambda q, k, v: ring_self_attention(q, k, v, mesh))
            .lower(shape, shape, shape).compile().memory_analysis()
        )

    scores_bytes = b * h * s * s * 4  # the f32 S x S logits dense holds
    assert dense_mem.temp_size_in_bytes >= scores_bytes  # claim is meaningful
    # ring per-device peak: blockwise S_local x S_local pieces -> at least
    # a ring_ways x reduction vs dense (measured: ~16x = ring_ways^2)
    assert ring_mem.temp_size_in_bytes * ring_ways < dense_mem.temp_size_in_bytes


def test_ulysses_matches_reference(mesh_seq):
    q, k, v = _qkv(seed=3)
    expected = dot_product_attention(q, k, v)
    with mesh_seq:
        out = ulysses_self_attention(q, k, v, mesh_seq)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_rejects_bad_head_count(mesh_seq):
    q, k, v = _qkv(h=6)  # 6 % 4 != 0
    with pytest.raises(ValueError, match="divisible"):
        with mesh_seq:
            ulysses_self_attention(q, k, v, mesh_seq)


def test_ulysses_through_vit_fwd_bwd():
    """Ulysses selected FROM THE MODEL (`attention_impl="ulysses"`) on a
    seq mesh: forward logits and parameter grads must match the xla path
    bit-for-bit up to collective reassociation (VERDICT r2 missing item 5)."""
    from dist_mnist_tpu.cluster.mesh import activate
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.ops.losses import softmax_cross_entropy

    mesh = make_mesh(MeshSpec(data=2, seq=2))  # heads 4 % seq 2 == 0
    kwargs = dict(depth=2, dim=64, heads=4, patch=8, pool="mean",
                  compute_dtype=jnp.float32)
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(4, 32, 32, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, (4,)), jnp.int32)

    results = {}
    for impl in ("xla", "ulysses"):
        model = get_model("vit_tiny", attention_impl=impl, **kwargs)
        params, state = model.init(jax.random.PRNGKey(0), x)

        def loss_fn(p):
            logits, _ = model.apply(p, state, x, train=False)
            return softmax_cross_entropy(logits, y), logits

        with activate(mesh):
            (loss, logits), grads = jax.jit(
                jax.value_and_grad(loss_fn, has_aux=True)
            )(params)
            jax.block_until_ready(loss)
        results[impl] = (float(loss), np.asarray(logits), grads)

    np.testing.assert_allclose(results["xla"][1], results["ulysses"][1],
                               rtol=2e-4, atol=2e-5)
    for (ka, a), (kb, b) in zip(
        jax.tree_util.tree_flatten_with_path(results["xla"][2])[0][:10],
        jax.tree_util.tree_flatten_with_path(results["ulysses"][2])[0][:10],
    ):
        assert ka == kb
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5, err_msg=str(ka))


def test_ulysses_config_selectable():
    """The ladder config wires Ulysses end-to-end (mesh has a seq axis,
    model kwargs select the impl, head count divides the seq axis)."""
    from dist_mnist_tpu.configs import get_config
    from dist_mnist_tpu.models import get_model

    cfg = get_config("vit_tiny_cifar_ulysses")
    assert cfg.mesh.seq == 2
    model = get_model(cfg.model, **cfg.model_kwargs)
    assert model.attention_impl == "ulysses"
    assert model.heads % cfg.mesh.seq == 0


def test_flash_attention_lse_merge_pair():
    """flash_attention_lse's (out, lse) is the exact merge-ready pair:
    out == dense attention and lse == logsumexp of the scaled logits (the
    LSE identity the ring composition relies on). Odd S covers the
    key-padding mask + query-pad slice-off."""
    from dist_mnist_tpu.ops.pallas import flash_attention_lse

    q, k, v = _qkv(b=2, s=65, h=3, d=32, seed=6)
    out, lse = flash_attention_lse(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dot_product_attention(q, k, v)),
        rtol=2e-4, atol=2e-5,
    )
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (q.shape[-1] ** -0.5)
    lse_ref = jax.scipy.special.logsumexp(logits, axis=-1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_attention_lse_grads_both_outputs():
    """The lse cotangent folds into the backward kernels as delta - dlse
    (flash_attention._flash_bwd_impl): grads of a function of BOTH outputs
    must match XLA autodiff through the dense (out, lse) pair — this is
    what makes ring(flash-local) train-grade. Odd S exercises the zero
    dlse padding tail."""
    from dist_mnist_tpu.ops.pallas import flash_attention_lse

    q, k, v = _qkv(b=2, s=33, h=2, d=16, seed=7)
    scale = q.shape[-1] ** -0.5

    def f_ref(q, k, v):
        o = dot_product_attention(q, k, v)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        l = jax.scipy.special.logsumexp(logits, axis=-1)
        return jnp.sum(o * jnp.cos(o)) + jnp.sum(jnp.sin(l))

    def f_flash(q, k, v):
        o, l = flash_attention_lse(q, k, v)
        return jnp.sum(o * jnp.cos(o)) + jnp.sum(jnp.sin(l))

    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_ref, g_fl):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5, err_msg=f"d{name}")


def test_ring_flash_matches_dense(mesh_seq):
    """The flash x ring composition (VERDICT r4 missing #3 / next #2):
    ring with flash LOCAL blocks == ring with XLA local blocks == dense.
    The kernel's (out, lse) enters the blockwise accumulator as
    (num=out, den=1, m=lse)."""
    q, k, v = _qkv(seed=8)
    expected = dot_product_attention(q, k, v)
    with mesh_seq:
        out_xla = ring_self_attention(q, k, v, mesh_seq, impl="xla")
        out_fl = ring_self_attention(q, k, v, mesh_seq, impl="flash")
    np.testing.assert_allclose(np.asarray(out_fl), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(out_fl), np.asarray(out_xla),
                               rtol=2e-4, atol=2e-5)


def test_ring_flash_grads_match_dense(mesh_seq):
    """d(q,k,v) through jit(shard_map(ring(flash_local))) — the flash
    custom VJP's lse cotangent path under the ring accumulator — matches
    autodiff through dense attention."""
    q, k, v = _qkv(seed=9)

    def loss_ref(q, k, v):
        return jnp.sum(jnp.tanh(dot_product_attention(q, k, v)))

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    with mesh_seq:
        g = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(jnp.tanh(
                ring_self_attention(q, k, v, mesh_seq, impl="flash"))),
            argnums=(0, 1, 2),
        ))(q, k, v)
    for name, a, b in zip("qkv", g_ref, g):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5, err_msg=f"d{name}")


def test_ring_flash_rejects_unknown_impl(mesh_seq):
    from dist_mnist_tpu.parallel.ring_attention import ring_attention_inner

    from dist_mnist_tpu.cluster.mesh import compat_shard_map

    with pytest.raises(ValueError, match="ring attention impl 'einsum'"):
        compat_shard_map(
            lambda q, k, v: ring_attention_inner(q, k, v, impl="einsum"),
            mesh=mesh_seq,
            in_specs=(None, None, None),
            out_specs=None,
        )(*_qkv(seed=10))


def test_ring_flash_fallback_no_seq_mesh_keeps_kernel():
    """Outside a seq mesh, ring_attention(impl="flash") degrades to the
    flash kernel (not the HBM einsum) and stays exact — the model's
    attention_impl="ring_flash" keeps its kernel choice on any mesh."""
    from dist_mnist_tpu.parallel.ring_attention import ring_attention

    q, k, v = _qkv(seed=11)
    out = ring_attention(q, k, v, impl="flash")
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dot_product_attention(q, k, v)),
        rtol=2e-4, atol=2e-5,
    )


def test_ring_flash_through_vit_fwd_bwd():
    """ring_flash selected FROM THE MODEL on a seq mesh: logits and the
    leading parameter grads match the xla attention path."""
    from dist_mnist_tpu.cluster.mesh import activate
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.ops.losses import softmax_cross_entropy

    mesh = make_mesh(MeshSpec(data=2, seq=2))
    kwargs = dict(depth=2, dim=64, heads=4, patch=8, pool="mean",
                  compute_dtype=jnp.float32)
    rng = np.random.default_rng(12)
    x = jnp.asarray(rng.normal(size=(4, 32, 32, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, (4,)), jnp.int32)

    results = {}
    for impl in ("xla", "ring_flash"):
        model = get_model("vit_tiny", attention_impl=impl, **kwargs)
        params, state = model.init(jax.random.PRNGKey(0), x)

        def loss_fn(p):
            logits, _ = model.apply(p, state, x, train=False)
            return softmax_cross_entropy(logits, y), logits

        with activate(mesh):
            (loss, logits), grads = jax.jit(
                jax.value_and_grad(loss_fn, has_aux=True)
            )(params)
            jax.block_until_ready(loss)
        results[impl] = (float(loss), np.asarray(logits), grads)

    np.testing.assert_allclose(results["xla"][1], results["ring_flash"][1],
                               rtol=2e-4, atol=2e-5)
    for (ka, a), (kb, b) in zip(
        jax.tree_util.tree_flatten_with_path(results["xla"][2])[0][:10],
        jax.tree_util.tree_flatten_with_path(results["ring_flash"][2])[0][:10],
    ):
        assert ka == kb
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5, err_msg=str(ka))


def test_ring_flash_bf16_tracks_dense(mesh_seq):
    """At bf16 inputs (the ViT default compute_dtype) the flash local
    block rounds each numerator to bf16 before the f32 merge — the
    documented flash-kernel contract (ring_attention_inner docstring).
    Pin that it still tracks the f32 dense reference at bf16-scale
    tolerance, so the precision difference stays bounded, not silent."""
    q, k, v = _qkv(seed=14)
    expected = dot_product_attention(q, k, v)  # f32 reference
    qb, kb, vb = (t.astype(jnp.bfloat16) for t in (q, k, v))
    with mesh_seq:
        out = ring_self_attention(qb, kb, vb, mesh_seq, impl="flash")
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(expected),
        rtol=5e-2, atol=5e-2,
    )


def test_ring_flash_composes_with_remat():
    """ring(flash_local) under jax.checkpoint — the composition the
    vit_tiny_cifar_ring_flash ladder config (remat=True) compiles on chip:
    the flash custom VJP (with its lse cotangent) must survive shard_map +
    rematerialization. Tiny shapes: interpreter backward runs per ring
    step."""
    q, k, v = _qkv(b=2, s=16, h=2, d=8, seed=13)
    mesh = make_mesh(MeshSpec(data=2, model=1, seq=2))

    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(jnp.tanh(dot_product_attention(q, k, v))),
        argnums=(0, 1, 2))(q, k, v)
    with mesh:
        f = jax.checkpoint(
            lambda q, k, v: ring_self_attention(q, k, v, mesh, impl="flash"))
        g = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(jnp.tanh(f(q, k, v))),
            argnums=(0, 1, 2)))(q, k, v)
    for name, a, b in zip("qkv", g_ref, g):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5, err_msg=f"d{name}")


def test_ring_flash_config_selectable():
    """The composed ladder config wires ring+flash end-to-end (seq mesh
    axis from the config, model kwargs select the composition)."""
    from dist_mnist_tpu.configs import get_config
    from dist_mnist_tpu.models import get_model

    cfg = get_config("vit_tiny_cifar_ring_flash")
    assert cfg.mesh.seq == 2
    model = get_model(cfg.model, **cfg.model_kwargs)
    assert model.attention_impl == "ring_flash"


def test_flash_attention_matches_reference():
    from dist_mnist_tpu.ops.pallas import flash_attention

    q, k, v = _qkv(b=2, s=65, h=3, d=32, seed=4)  # odd S: pad/mask path
    out = flash_attention(q, k, v)  # interpret mode on CPU
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dot_product_attention(q, k, v)),
        rtol=2e-4, atol=2e-5,
    )


def test_flash_attention_grads_match_reference():
    """The recompute-based custom VJP: dQ/dK/dV == XLA autodiff through the
    dense reference, including the odd-S key-padding mask replay in the dQ
    kernel (VERDICT r3 missing 2 / weak 1: flash was forward-only)."""
    from dist_mnist_tpu.ops.pallas import flash_attention

    q, k, v = _qkv(b=2, s=65, h=3, d=32, seed=6)
    do = jnp.asarray(np.random.default_rng(7).normal(size=q.shape), jnp.float32)
    _, vjp_ref = jax.vjp(dot_product_attention, q, k, v)
    _, vjp_flash = jax.vjp(flash_attention, q, k, v)
    for name, ref, got in zip("qkv", vjp_ref(do), vjp_flash(do)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-3, atol=2e-4, err_msg=f"d{name}")


@pytest.mark.slow
def test_flash_through_vit_fwd_bwd():
    """Flash selected FROM THE MODEL (`attention_impl="flash"`) in a real
    training position: forward logits and parameter grads match the xla
    path (mirror of test_ulysses_through_vit_fwd_bwd)."""
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.ops.losses import softmax_cross_entropy

    kwargs = dict(depth=2, dim=64, heads=4, patch=8, pool="mean",
                  compute_dtype=jnp.float32)
    rng = np.random.default_rng(8)
    x = jnp.asarray(rng.normal(size=(4, 32, 32, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, (4,)), jnp.int32)

    results = {}
    for impl in ("xla", "flash"):
        model = get_model("vit_tiny", attention_impl=impl, **kwargs)
        params, state = model.init(jax.random.PRNGKey(0), x)

        def loss_fn(p):
            logits, _ = model.apply(p, state, x, train=False)
            return softmax_cross_entropy(logits, y), logits

        (loss, logits), grads = jax.jit(
            jax.value_and_grad(loss_fn, has_aux=True)
        )(params)
        jax.device_get(loss)
        results[impl] = (float(loss), np.asarray(logits), grads)

    np.testing.assert_allclose(results["xla"][1], results["flash"][1],
                               rtol=2e-4, atol=2e-5)
    for (ka, a), (kb, b) in zip(
        jax.tree_util.tree_flatten_with_path(results["xla"][2])[0][:10],
        jax.tree_util.tree_flatten_with_path(results["flash"][2])[0][:10],
    ):
        assert ka == kb
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5, err_msg=str(ka))


def test_flash_composes_with_remat_scan():
    """flash's custom VJP under jax.checkpoint over a lax.scan of blocks —
    the exact composition the vit_tiny_cifar_flash ladder config compiles
    (remat=True, scan_blocks) — at unit scale: grads must be finite and
    match the no-remat flash path. Kept tiny: each backward recompute runs
    the kernel under the Pallas INTERPRETER on CPU."""
    from dist_mnist_tpu.ops.pallas import flash_attention

    rng = np.random.default_rng(13)
    b, s, h, d = 2, 16, 2, 8
    x = jnp.asarray(rng.normal(size=(b, s, h * d)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(2, h * d, h * d)) * 0.1, jnp.float32)

    from jax.ad_checkpoint import checkpoint_name

    def block(xx, wi):
        qkv = xx @ wi
        q = k = v = qkv.reshape(b, s, h, d)
        # the same attn_out tag the real flash path applies (models/vit.py)
        # — without it save_attn degenerates to dots_no_batch and the
        # name-filter x custom-VJP interaction goes untested
        out = checkpoint_name(flash_attention(q, k, v), "attn_out")
        return out.reshape(b, s, h * d), None

    def loss(w, policy):
        def fwd(xx):
            out, _ = jax.lax.scan(lambda c, wi: block(c, wi), xx, w)
            return out

        if policy is not None:
            fwd = jax.checkpoint(fwd, policy=policy)
        return jnp.sum(fwd(x) ** 2)

    from dist_mnist_tpu.train.step import REMAT_POLICIES

    g_plain = jax.grad(lambda w: loss(w, None))(w)
    assert np.isfinite(np.asarray(g_plain)).all()  # allclose treats NaN==NaN
    for name in ("dots_no_batch", "save_attn"):
        g_remat = jax.grad(lambda w: loss(w, REMAT_POLICIES[name]))(w)
        np.testing.assert_allclose(np.asarray(g_remat), np.asarray(g_plain),
                                   rtol=1e-4, atol=1e-5, err_msg=name)


def test_flash_config_selectable():
    """The flash ladder config wires the kernel end-to-end."""
    from dist_mnist_tpu.configs import get_config
    from dist_mnist_tpu.models import get_model

    cfg = get_config("vit_tiny_cifar_flash")
    model = get_model(cfg.model, **cfg.model_kwargs)
    assert model.attention_impl == "flash"


def test_fused_adam_matches_plain():
    from dist_mnist_tpu import optim

    rng = np.random.default_rng(0)
    params = {"w": jnp.asarray(rng.normal(size=(130, 7)), jnp.float32),
              "b": jnp.asarray(rng.normal(size=(3,)), jnp.float32)}
    grads = jax.tree.map(
        lambda p: jnp.asarray(rng.normal(size=p.shape), jnp.float32), params
    )
    plain, fused = optim.adam(0.01), optim.adam(0.01, fused=True)
    sp, sf = plain.init(params), fused.init(params)
    pp = pf = params
    for _ in range(3):
        up, sp = plain.update(grads, sp, pp)
        pp = optim.apply_updates(pp, up)
        uf, sf = fused.update(grads, sf, pf)
        pf = optim.apply_updates(pf, uf)
    for kk in params:
        np.testing.assert_allclose(np.asarray(pp[kk]), np.asarray(pf[kk]),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(sp["m"][kk]),
                                   np.asarray(sf["m"][kk]), rtol=1e-5)


@pytest.mark.slow
def test_explicit_dp_step_matches_gspmd(mesh8):
    """shard_map explicit-collectives step == GSPMD inferred step."""
    from dist_mnist_tpu import optim
    from dist_mnist_tpu.data.pipeline import shard_batch
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.parallel.collectives import make_explicit_dp_step
    from dist_mnist_tpu.parallel.sharding import shard_train_state
    from dist_mnist_tpu.train import create_train_state, make_train_step

    model = get_model("mlp", hidden_units=16)
    rng = np.random.default_rng(0)
    batch_np = {
        "image": rng.integers(0, 255, (32, 28, 28, 1), dtype=np.uint8),
        "label": rng.integers(0, 10, (32,), dtype=np.int32),
    }
    results = {}
    for name, maker in (
        ("gspmd", lambda m, o: make_train_step(model, o, m, donate=False)),
        ("explicit", lambda m, o: make_explicit_dp_step(model, o, m)),
    ):
        opt = optim.adam(0.01)
        with mesh8:
            state = create_train_state(model, opt, jax.random.PRNGKey(0),
                                       batch_np["image"][:1])
            state = shard_train_state(state, mesh8)
            step = maker(mesh8, opt)
            batch = shard_batch(batch_np, mesh8)
            for _ in range(3):
                state, out = step(state, batch)
        results[name] = (np.asarray(state.params["hid"]["w"]),
                         float(out["loss"]))
    np.testing.assert_allclose(results["gspmd"][0], results["explicit"][0],
                               rtol=2e-4, atol=2e-6)
    np.testing.assert_allclose(results["gspmd"][1], results["explicit"][1],
                               rtol=2e-4)


def test_explicit_dp_step_matches_gspmd_with_aux(mesh8):
    """Both step implementations consume the model_aux_loss contract (the
    bug class guarded: one silently DROPPING the aux term). capacity_factor
    is pinned generous deliberately: with no token drops, per-shard routing
    (explicit step) and global routing (GSPMD) coincide; at tight capacity
    they are different-but-valid estimators of the Switch objective — see
    parallel/collectives.py's loss_of comment."""
    from dist_mnist_tpu import optim
    from dist_mnist_tpu.data.pipeline import shard_batch
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.parallel.collectives import make_explicit_dp_step
    from dist_mnist_tpu.parallel.sharding import shard_train_state
    from dist_mnist_tpu.train import create_train_state, make_train_step

    model = get_model("vit_tiny", depth=1, dim=32, heads=4, patch=8,
                      pool="mean", mlp_impl="moe", n_experts=2,
                      moe_capacity_factor=8.0, dropout_rate=0.0,
                      compute_dtype=jnp.float32)
    rng = np.random.default_rng(21)
    batch_np = {
        "image": rng.integers(0, 255, (16, 32, 32, 3), dtype=np.uint8),
        "label": rng.integers(0, 10, (16,), dtype=np.int32),
    }
    results = {}
    for name, maker in (
        ("gspmd", lambda m, o: make_train_step(model, o, m, donate=False)),
        ("explicit", lambda m, o: make_explicit_dp_step(model, o, m)),
    ):
        opt = optim.adam(0.01)
        with mesh8:
            state = create_train_state(model, opt, jax.random.PRNGKey(0),
                                       batch_np["image"][:1])
            state = shard_train_state(state, mesh8)
            step = maker(mesh8, opt)
            state, out = step(state, shard_batch(batch_np, mesh8))
        results[name] = (float(out["loss"]),
                         np.asarray(state.params["block0"]["moe"]["gate"]))
    np.testing.assert_allclose(results["gspmd"][0], results["explicit"][0],
                               rtol=2e-5)
    np.testing.assert_allclose(results["gspmd"][1], results["explicit"][1],
                               rtol=2e-4, atol=2e-6)


def _vit_flash(heads):
    """Tiny float32 ViT on the flash path (exactness vs unsharded)."""
    from dist_mnist_tpu.models.vit import ViTTiny

    return ViTTiny(depth=2, dim=48, heads=heads, dropout_rate=0.0,
                   compute_dtype=jnp.float32, attention_impl="flash",
                   scan_blocks=True)


def test_flash_tp_matches_unsharded(mesh_tp):
    """flash x TP composition (VERDICT r4 weak #3): a bare pallas_call
    cannot be GSPMD-partitioned, so under a model axis the model runs the
    kernel per-device over LOCAL heads via shard_map (Megatron TP
    attention). Logits and param grads must match the unsharded kernel."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dist_mnist_tpu.cluster.mesh import DATA_AXIS, activate
    from dist_mnist_tpu.parallel.sharding import TP_RULES, tree_sharding

    model = _vit_flash(heads=2)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 32, 32, 3))
    params, _ = model.init(jax.random.PRNGKey(0), x)

    def loss(p, xx):
        logits, _ = model.apply(p, {}, xx)
        return jnp.sum(logits ** 2)

    expected = loss(params, x)
    g_expected = jax.grad(loss)(params, x)
    with activate(mesh_tp):
        p_sh = jax.device_put(params, tree_sharding(params, mesh_tp,
                                                    TP_RULES))
        x_sh = jax.device_put(x, NamedSharding(mesh_tp, P(DATA_AXIS)))
        got = jax.jit(loss)(p_sh, x_sh)
        g_got = jax.jit(jax.grad(loss))(p_sh, x_sh)
    np.testing.assert_allclose(float(got), float(expected), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5),
        g_expected, g_got,
    )


def test_flash_tp_indivisible_heads_raises(mesh_tp):
    """heads % model != 0 must refuse at trace time with a clear error,
    not die deep inside XLA partitioning (the same loud-refusal standard
    shard_train_state applies to no-match rules)."""
    from dist_mnist_tpu.cluster.mesh import activate

    model = _vit_flash(heads=3)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 32, 32, 3))
    params, _ = model.init(jax.random.PRNGKey(0), x)
    with activate(mesh_tp):
        with pytest.raises(ValueError, match="heads"):
            jax.jit(lambda p, xx: model.apply(p, {}, xx)[0])(params, x)


def test_ring_flash_fallback_tp_mesh_local_heads(mesh_tp):
    """ring_flash's seq-absent fallback on a mesh that still carries a
    model axis must route through flash_attention_sharded (local heads),
    not the bare kernel — the same silent-replication hazard as flash+TP,
    one dispatch layer down (code-review r5)."""
    from dist_mnist_tpu.cluster.mesh import activate

    q, k, v = _qkv(h=4, seed=7)
    expected = dot_product_attention(q, k, v)
    with activate(mesh_tp):
        out = jax.jit(lambda a, b, c: ring_attention(a, b, c,
                                                     impl="flash"))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


class TestFlashBlockK:
    """Online-softmax (block_k) kernel path vs the full-K-resident path
    and the dense reference: forward, grads of both outputs, and the LSE
    pair — the streaming kernels must be drop-in numerics (r5; lifts the
    single-device resident-K VMEM ceiling)."""

    def _qkv(self, s, seed=0, dtype=jnp.float32):
        rng = np.random.default_rng(seed)
        mk = lambda: jnp.asarray(rng.normal(size=(2, s, 3, 16)), dtype)
        return mk(), mk(), mk()

    @pytest.mark.parametrize("s", [200, 256, 300])
    def test_fwd_matches_dense_and_fullk(self, s):
        from dist_mnist_tpu.ops.pallas import flash_attention

        q, k, v = self._qkv(s, seed=s)
        ref = dot_product_attention(q, k, v)
        bk = flash_attention(q, k, v, block_k=128)
        full = flash_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(bk), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(np.asarray(bk), np.asarray(full),
                                   rtol=1e-5, atol=1e-6)

    def test_grads_match_dense(self):
        from dist_mnist_tpu.ops.pallas import flash_attention

        q, k, v = self._qkv(300, seed=7)  # odd S: padding masks in play

        def f(fn):
            return jax.grad(
                lambda a, b, c: jnp.sum(fn(a, b, c) ** 2),
                argnums=(0, 1, 2))(q, k, v)

        g_ref = f(dot_product_attention)
        g_bk = f(lambda a, b, c: flash_attention(a, b, c, block_k=128))
        for r, got in zip(g_ref, g_bk):
            np.testing.assert_allclose(np.asarray(got), np.asarray(r),
                                       rtol=3e-4, atol=3e-5)

    def test_lse_pair_and_dlse_cotangent(self):
        """Both outputs differentiable on the streaming path — the ring
        composition's requirement (lse cotangent folds into delta)."""
        from dist_mnist_tpu.ops.pallas import flash_attention_lse

        q, k, v = self._qkv(260, seed=9)
        o_full, l_full = flash_attention_lse(q, k, v)
        o_bk, l_bk = flash_attention_lse(q, k, v, block_k=128)
        np.testing.assert_allclose(np.asarray(o_bk), np.asarray(o_full),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(l_bk), np.asarray(l_full),
                                   rtol=1e-5, atol=1e-5)

        def f(bk):
            def inner(a, b, c):
                o, l = flash_attention_lse(a, b, c, block_k=bk)
                return jnp.sum(o ** 2) + jnp.sum(jnp.sin(l))
            return jax.grad(inner, argnums=(0, 1, 2))(q, k, v)

        for r, got in zip(f(None), f(128)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(r),
                                       rtol=3e-4, atol=3e-5)

    def test_single_tile_falls_back_to_fullk(self):
        """S <= one tile: streaming degenerates to the proven full-K
        kernel (the quantizer returns None) — same result either way."""
        from dist_mnist_tpu.ops.pallas import flash_attention
        from dist_mnist_tpu.ops.pallas.flash_attention import (
            _quantize_block_k,
        )

        assert _quantize_block_k(128, 65) is None
        assert _quantize_block_k(128, 256) == 128
        assert _quantize_block_k(100, 300) == 128  # rounded up
        q, k, v = self._qkv(65, seed=11)
        np.testing.assert_allclose(
            np.asarray(flash_attention(q, k, v, block_k=128)),
            np.asarray(flash_attention(q, k, v)), rtol=1e-6, atol=1e-7)

    def test_ring_flash_blockk_local_blocks(self, mesh_seq):
        """block_k composes under the ring: sequence-sharded long S whose
        LOCAL blocks also stream K/V tiles (online softmax) still equals
        dense — ring bounds HBM, block_k bounds VMEM residency."""
        from dist_mnist_tpu.parallel.ring_attention import (
            ring_self_attention,
        )

        rng = np.random.default_rng(13)
        mk = lambda: jnp.asarray(rng.normal(size=(2, 1024, 4, 16)),
                                 jnp.float32)
        q, k, v = mk(), mk(), mk()
        expected = dot_product_attention(q, k, v)
        with mesh_seq:
            out = ring_self_attention(q, k, v, mesh_seq, impl="flash",
                                      block_k=128)  # 1024/4 = 256 local
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   rtol=2e-4, atol=2e-5)


def test_flash_sharded_tp_threads_block_k(mesh_tp):
    """block_k must survive the TP shard_map branch (code review r5: it
    was silently dropped, reinstating the full-K VMEM ceiling exactly
    where streaming matters). Exactness vs dense pins the plumbing."""
    from dist_mnist_tpu.cluster.mesh import activate
    from dist_mnist_tpu.parallel.flash import flash_attention_sharded

    rng = np.random.default_rng(17)
    mk = lambda: jnp.asarray(rng.normal(size=(2, 300, 4, 16)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    expected = dot_product_attention(q, k, v)
    with activate(mesh_tp):
        out = jax.jit(lambda a, b, c: flash_attention_sharded(
            a, b, c, block_k=128))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


class TestUlyssesFlash:
    """Ulysses with the flash local engine (r5): after the head reshard
    each device attends over the FULL sequence — where VMEM score tiles
    matter most. Must equal the xla local engine and dense."""

    def test_matches_xla_and_dense(self, mesh_seq):
        # S=512: after the reshard each device attends over the FULL 512
        # tokens, so block_k=128 genuinely streams 4 K tiles (S=32 would
        # quantize block_k away to the single-tile full-K path)
        q, k, v = _qkv(s=512, h=4, seed=31)
        expected = dot_product_attention(q, k, v)
        with mesh_seq:
            out_fl = ulysses_self_attention(q, k, v, mesh_seq,
                                            impl="flash")
            out_bk = ulysses_self_attention(q, k, v, mesh_seq,
                                            impl="flash", block_k=128)
        np.testing.assert_allclose(np.asarray(out_fl),
                                   np.asarray(expected),
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(np.asarray(out_bk),
                                   np.asarray(expected),
                                   rtol=2e-4, atol=2e-5)

    def test_grads_match_dense(self, mesh_seq):
        q, k, v = _qkv(h=4, seed=32)

        def g(fn):
            return jax.grad(
                lambda a, b, c: jnp.sum(fn(a, b, c) ** 2),
                argnums=(0, 1, 2))(q, k, v)

        g_ref = g(dot_product_attention)
        with mesh_seq:
            g_fl = g(jax.jit(lambda a, b, c: ulysses_self_attention(
                a, b, c, mesh_seq, impl="flash")))
        for r, got in zip(g_ref, g_fl):
            np.testing.assert_allclose(np.asarray(got), np.asarray(r),
                                       rtol=3e-4, atol=3e-5)

    def test_fallback_no_seq_mesh_keeps_kernel(self):
        """Off any seq mesh, impl='flash' degrades to the (mesh-adaptive)
        kernel, not the einsum — same contract as ring_flash."""
        from dist_mnist_tpu.parallel.ulysses import ulysses_attention

        q, k, v = _qkv(seed=33)
        out = ulysses_attention(q, k, v, impl="flash")
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(dot_product_attention(q, k, v)),
            rtol=2e-4, atol=2e-5)

    def test_rejects_unknown_impl(self, mesh_seq):
        q, k, v = _qkv(h=4, seed=34)
        with pytest.raises(ValueError, match="impl"):
            with mesh_seq:
                ulysses_self_attention(q, k, v, mesh_seq, impl="cuda")

    def test_through_vit_fwd_bwd(self):
        """ulysses_flash selected FROM THE MODEL on a seq mesh: logits
        and grads match the xla impl (same standard as ring_flash)."""
        from dist_mnist_tpu.cluster.mesh import activate
        from dist_mnist_tpu.models import get_model
        from dist_mnist_tpu.ops.losses import softmax_cross_entropy

        mesh = make_mesh(MeshSpec(data=2, seq=2))
        kwargs = dict(depth=2, dim=64, heads=4, patch=8, pool="mean",
                      compute_dtype=jnp.float32)
        rng = np.random.default_rng(35)
        x = jnp.asarray(rng.normal(size=(4, 32, 32, 3)), jnp.float32)
        y = jnp.asarray(rng.integers(0, 10, (4,)), jnp.int32)
        results = {}
        for impl in ("xla", "ulysses_flash"):
            model = get_model("vit_tiny", attention_impl=impl, **kwargs)
            params, state = model.init(jax.random.PRNGKey(0), x)

            def loss_fn(p):
                logits, _ = model.apply(p, state, x, train=False)
                return softmax_cross_entropy(logits, y), logits

            with activate(mesh):
                (loss, logits), grads = jax.jit(
                    jax.value_and_grad(loss_fn, has_aux=True))(params)
                jax.block_until_ready(loss)
            results[impl] = (float(loss), np.asarray(logits), grads)
        np.testing.assert_allclose(results["xla"][1],
                                   results["ulysses_flash"][1],
                                   rtol=2e-4, atol=2e-5)
        for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_flatten_with_path(results["xla"][2])[0][:8],
            jax.tree_util.tree_flatten_with_path(
                results["ulysses_flash"][2])[0][:8],
        ):
            assert ka == kb
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-5,
                                       err_msg=str(ka))

    def test_config_selectable(self):
        from dist_mnist_tpu.configs import get_config
        from dist_mnist_tpu.models import get_model

        cfg = get_config("vit_tiny_cifar_ulysses_flash")
        model = get_model(cfg.model, **cfg.model_kwargs)
        assert model.attention_impl == "ulysses_flash"
        assert model.heads % cfg.mesh.seq == 0


def test_flash_memory_advantage_long_seq():
    """Compile-time memory accounting (same method as the ring memory
    test): at S=4096 the dense path's temp memory carries the [B,H,S,S]
    score tensor; the flash kernel's stays an order of magnitude below —
    the single-device half of the long-context story, measured."""
    from dist_mnist_tpu.ops.pallas import flash_attention

    b, s, h, d = 1, 4096, 4, 64
    shape = jax.ShapeDtypeStruct((b, s, h, d), jnp.float32)
    dense_mem = (jax.jit(dot_product_attention)
                 .lower(shape, shape, shape).compile().memory_analysis())
    flash_mem = (jax.jit(lambda q, k, v: flash_attention(q, k, v))
                 .lower(shape, shape, shape).compile().memory_analysis())
    scores_bytes = b * h * s * s * 4
    assert dense_mem.temp_size_in_bytes >= scores_bytes
    assert flash_mem.temp_size_in_bytes * 8 < dense_mem.temp_size_in_bytes


@settings(max_examples=12, deadline=None)
@given(
    b=st.integers(1, 3),
    s=st.integers(1, 300),
    h=st.integers(1, 4),
    d=st.sampled_from([8, 16, 32]),
    block_q=st.sampled_from([64, 128, 256]),
    use_bk=st.booleans(),
    seed=st.integers(0, 2**31 - 1),
)
def test_flash_property_matches_dense(b, s, h, d, block_q, use_bk, seed):
    """Property: for ANY geometry (odd S, S smaller than a tile, tiny
    heads, every block_q/block_k quantization path) the kernel family
    equals dense attention. Catches padding-mask and tiling edge cases a
    hand-picked grid misses."""
    from dist_mnist_tpu.ops.pallas import flash_attention

    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    out = flash_attention(q, k, v, block_q=block_q,
                          block_k=128 if use_bk else None)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dot_product_attention(q, k, v)),
        rtol=3e-4, atol=3e-5)
