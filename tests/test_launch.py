"""Multi-process cluster launch (cli/launch.py).

The reference's distributed story was K shell commands
(`dist_mnist.py --job_name=... --task_index=...`) against real gRPC
servers; upstream tested it with in-process servers
(create_local_cluster, test_util.py:4029-4115). Here the launcher spawns
REAL OS processes wired by `jax.distributed` (gloo collectives on CPU),
so this test exercises the actual multi-host control plane: coordination
handshake, cross-process device mesh, per-process data sharding, psum over
process boundaries, chief-only side effects.

Slow (each child pays jax import + CPU compile) — keep step counts tiny.
"""

from __future__ import annotations

import re
import subprocess
import sys

import pytest

from dist_mnist_tpu.cli.launch import launch


@pytest.mark.slow
def test_two_process_training(tmp_path):
    data_dir = str(tmp_path / "data")
    # pre-materialize the dataset once so the children don't race the
    # synthetic-twin cache write (--download_only parity path, §0.1 flag 2)
    r = subprocess.run(
        [sys.executable, "-m", "dist_mnist_tpu.cli.train",
         "--download_only", f"--data_dir={data_dir}",
         "--config=mlp_mnist", "--platform=cpu"],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr

    out = tmp_path / "launch.log"
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = launch(
            2,
            [
                "--config=mlp_mnist",
                f"--data_dir={data_dir}",
                "--train_steps=6",
                "--batch_size=32",
                "--eval_every=0",
                "--log_every=2",
            ],
            platform="cpu",
            devices_per_process=2,
        )
    log = buf.getvalue()
    out.write_text(log)
    assert rc == 0, log

    # both processes joined one 4-device cluster...
    assert re.search(r"\[p0\].*process 0/2, 2 local / 4 global", log), log
    assert re.search(r"\[p1\].*process 1/2, 2 local / 4 global", log), log
    # ...and both finished all 6 steps with the SAME final test accuracy
    # (state is replicated; divergence would mean the psum didn't span
    # processes)
    finals = re.findall(r"\[p(\d)\].*done: step=(\d+) test_acc=([0-9.]+)", log)
    assert sorted(f[0] for f in finals) == ["0", "1"], log
    assert all(f[1] == "6" for f in finals), finals
    assert finals[0][2] == finals[1][2], finals


@pytest.mark.slow
def test_two_process_device_pipeline(tmp_path):
    """The fused device input path on a REAL 2-process × 4-device cluster
    (8 global devices): dataset rows sharded across BOTH processes' devices
    (make_array_from_callback — device_put can't reach non-addressable
    devices), sampling in-program, scan-chunked loop. Both processes must
    converge identically (VERDICT r3 next-9: sharded residency + bound-data
    jit args across processes at the widest per-process device count)."""
    import contextlib
    import io

    data_dir = str(tmp_path / "data")
    r = subprocess.run(
        [sys.executable, "-m", "dist_mnist_tpu.cli.train",
         "--download_only", f"--data_dir={data_dir}",
         "--config=mlp_mnist", "--platform=cpu"],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = launch(
            2,
            [
                "--config=mlp_mnist",
                f"--data_dir={data_dir}",
                "--train_steps=6",
                "--batch_size=32",
                "--eval_every=0",
                "--input_pipeline=device_sharded",
                "--scan_chunk=3",
            ],
            platform="cpu",
            devices_per_process=4,
        )
    log = buf.getvalue()
    assert rc == 0, log
    finals = re.findall(r"\[p(\d)\].*done: step=(\d+) test_acc=([0-9.]+)", log)
    assert sorted(f[0] for f in finals) == ["0", "1"], log
    assert all(f[1] == "6" for f in finals), finals
    assert finals[0][2] == finals[1][2], finals


@pytest.mark.slow
def test_launch_propagates_child_failure(tmp_path):
    rc = launch(
        2,
        ["--config=does_not_exist"],
        platform="cpu",
        devices_per_process=1,
    )
    assert rc != 0


@pytest.mark.slow
def test_two_process_checkpoint_resume(tmp_path):
    """Collective checkpoint restore across a REAL 2-process cluster: run 1
    saves, run 2 must log restored=True on BOTH processes and continue to
    the extended step count (the multi-host analogue of
    SessionManager.prepare_session auto-restore, SURVEY.md §3.5)."""
    import contextlib
    import io

    data_dir = str(tmp_path / "data")
    ckpt_dir = str(tmp_path / "ckpt")
    subprocess.run(
        [sys.executable, "-m", "dist_mnist_tpu.cli.train",
         "--download_only", f"--data_dir={data_dir}",
         "--config=mlp_mnist", "--platform=cpu"],
        capture_output=True, text=True, timeout=300, check=True,
    )
    common = [
        "--config=mlp_mnist", f"--data_dir={data_dir}",
        f"--checkpoint_dir={ckpt_dir}", "--batch_size=32",
        "--eval_every=0", "--log_every=2",
    ]
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc1 = launch(2, common + ["--train_steps=4"], platform="cpu",
                     devices_per_process=2)
    log1 = buf.getvalue()
    assert rc1 == 0, log1
    assert re.search(r"\[p0\].*restored=False", log1), log1

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc2 = launch(2, common + ["--train_steps=8"], platform="cpu",
                     devices_per_process=2)
    log2 = buf.getvalue()
    assert rc2 == 0, log2
    for p in ("p0", "p1"):
        assert re.search(rf"\[{p}\].*restored=True", log2), log2
        assert re.search(rf"\[{p}\].*done: step=8", log2), log2
