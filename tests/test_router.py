"""Tier-1 fleet-router tests: the error taxonomy pins, tiered shedding,
retry/failover/hedging, drain integration, zero-downtime weight rolls, the
commit-marker watcher, the fleet load generator, and the HTTP replica
transport. Policy tests run against a scripted fake replica (deterministic,
no compiles); lifecycle and swap tests run a real 2-replica fleet over the
8-device CPU mesh with one shared compile cache."""

from __future__ import annotations

import contextlib
import threading
import time
from concurrent.futures import CancelledError, Future

import jax
import numpy as np
import pytest

from dist_mnist_tpu.faults import Fault, FaultPlan
from dist_mnist_tpu.obs import HealthState, MetricRegistry, RunJournal
from dist_mnist_tpu.obs import events as events_mod
from dist_mnist_tpu.serve import (
    BEST_EFFORT,
    LATENCY_SENSITIVE,
    AllReplicasDownError,
    CheckpointWatcher,
    CompiledModelCache,
    DeadlineExceededError,
    InferenceEngine,
    InferenceServer,
    InProcessReplica,
    QueueFullError,
    ReplicaKilledError,
    Router,
    RouterConfig,
    ServeConfig,
    ShedError,
    ShuttingDownError,
    classify_failure,
    load_for_serving,
    run_fleet_loadgen,
)
from dist_mnist_tpu.serve.admission import InferenceResult
from dist_mnist_tpu.serve.errors import REPLICA_FATAL, RETRYABLE, TERMINAL

IMAGE_SHAPE = (28, 28, 1)


# -- shared real-fleet plumbing (one compile per module via shared cache) ----

@pytest.fixture(scope="module")
def bundle(mesh8):
    return load_for_serving("mlp_mnist", mesh8)


@pytest.fixture(scope="module")
def shared_cache():
    return CompiledModelCache()


@pytest.fixture()
def make_fleet(mesh8, bundle, shared_cache):
    """Factory for N started InProcessReplicas sharing one compile cache;
    everything it makes is closed at test end."""
    made: list = []

    def _make(n, *, plan=None, load_weights=None, queue_depth=64):
        def factory(rid):
            def make_server():
                eng = InferenceEngine(
                    bundle.model, bundle.params, bundle.model_state, mesh8,
                    model_name="mlp", image_shape=bundle.image_shape,
                    rules=bundle.rules, max_bucket=8, cache=shared_cache)
                if plan is not None:
                    eng = plan.wrap_engine(eng, replica_id=rid)
                return InferenceServer(
                    eng,
                    ServeConfig(max_batch=8, max_wait_ms=1.0,
                                queue_depth=queue_depth),
                    health=HealthState()).start()
            return make_server

        fleet = [InProcessReplica(i, factory(i), load_weights=load_weights)
                 .start() for i in range(n)]
        made.extend(fleet)
        return fleet

    yield _make
    for r in made:
        r.close()


def _image(seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=IMAGE_SHAPE, dtype=np.uint8)


def wait_for(pred, timeout=5.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


@contextlib.contextmanager
def capture_journal(tmp_path):
    """Route ambient events.emit() into a JSONL file for the test."""
    path = tmp_path / "events.jsonl"
    journal = RunJournal(path)
    prev = events_mod.set_journal(journal)
    try:
        yield path
    finally:
        events_mod.set_journal(prev)
        journal.close()


def _kinds(path):
    return [e["event"] for e in events_mod.read_journal(path)]


# -- scripted replica for policy tests ---------------------------------------

class FakeReplica:
    """Deterministic replica double: completes submits immediately with a
    canned result, or with the next scripted exception; backlog inputs
    (queue_depth/capacity) are plain attributes the test sets."""

    def __init__(self, rid, *, depth=0, cap=10):
        self.id = rid
        self.generation = 0
        self.depth = depth
        self.cap = cap
        self.state = "serving"
        self.fail_with: list = []  # popped per submit; empty = succeed
        self.hang = False  # leave the attempt future unresolved
        self.submits = 0

    def submit(self, image, *, deadline_ms=None, cancel_event=None):
        self.submits += 1
        fut: Future = Future()
        if self.hang:
            return fut
        if self.fail_with:
            fut.set_exception(self.fail_with.pop(0))
        else:
            fut.set_result(InferenceResult(
                logits=np.zeros(10, np.float32), label=0, latency_ms=0.1))
        return fut

    @property
    def queue_depth(self):
        return self.depth

    @property
    def capacity(self):
        return self.cap

    def probe(self):
        return {"state": self.state, "healthy": self.state == "serving",
                "generation": self.generation}

    def quiesce(self, timeout=30.0):
        return True

    def swap_to(self, step):
        pass

    def rewarm(self):
        return 0.0

    def close(self, timeout=30.0):
        return True


FAST = RouterConfig(health_interval_s=0.02, retry_base_ms=1.0,
                    retry_max_ms=5.0)


# -- error taxonomy pins ------------------------------------------------------

def test_classify_failure_is_type_first():
    # the message says "queue full" but the TYPE is unrecognized -> the
    # generic transient clause, never the admission-pushback one (no
    # string matching anywhere in the classifier)
    assert classify_failure(ValueError("queue full at capacity")) == RETRYABLE
    # a TimeoutError IS an OSError since 3.10: the deadline must classify
    # as a dead REQUEST before the connection clause calls it a dead REPLICA
    assert isinstance(DeadlineExceededError("x"), OSError)
    assert classify_failure(DeadlineExceededError("x")) == TERMINAL
    assert classify_failure(CancelledError()) == TERMINAL
    assert classify_failure(ShedError("x")) == TERMINAL
    assert classify_failure(AllReplicasDownError("x")) == TERMINAL
    assert classify_failure(QueueFullError("x")) == RETRYABLE
    assert classify_failure(ShuttingDownError("x")) == RETRYABLE
    assert classify_failure(RuntimeError("injected")) == RETRYABLE
    assert classify_failure(ReplicaKilledError("x")) == REPLICA_FATAL
    assert classify_failure(ConnectionRefusedError("x")) == REPLICA_FATAL
    assert classify_failure(BrokenPipeError("x")) == REPLICA_FATAL


# -- tiered shedding (scripted backlog) ---------------------------------------

def test_best_effort_sheds_before_latency_sensitive(tmp_path):
    with capture_journal(tmp_path) as jpath:
        fake = FakeReplica(0, depth=6, cap=10)  # backlog 0.6
        with Router([fake], FAST) as router:
            with pytest.raises(ShedError, match="backlog"):
                router.submit(_image(), request_class=BEST_EFFORT)
            # same backlog, the expensive tier still gets through
            res = router.submit(
                _image(), request_class=LATENCY_SENSITIVE).result(timeout=5)
            assert res.label == 0
            snap = router.metrics.snapshot()
            assert snap["shed"] == {BEST_EFFORT: 1, LATENCY_SENSITIVE: 0}
    assert "shed" in _kinds(jpath)


def test_latency_sensitive_sheds_only_when_full():
    fake = FakeReplica(0, depth=10, cap=10)  # backlog 1.0: every queue full
    with Router([fake], FAST) as router:
        with pytest.raises(ShedError):
            router.submit(_image(), request_class=LATENCY_SENSITIVE)
        assert fake.submits == 0  # shed at the router, not a replica queue


def test_hopeless_best_effort_deadline_sheds_under_pressure():
    fake = FakeReplica(0, depth=3, cap=10)  # 0.3 >= deadline_guard_at
    with Router([fake], FAST) as router:
        for _ in range(20):  # observed latency ~100ms
            router.metrics.latency_ms[LATENCY_SENSITIVE].observe(100.0)
        with pytest.raises(ShedError, match="deadline_hopeless"):
            router.submit(_image(), request_class=BEST_EFFORT, deadline_ms=5)
        # a generous deadline at the same backlog is fine
        router.submit(_image(), request_class=BEST_EFFORT,
                      deadline_ms=5000).result(timeout=5)
        # and the guard never applies to latency_sensitive
        router.submit(_image(), request_class=LATENCY_SENSITIVE,
                      deadline_ms=5).result(timeout=5)


def test_submit_validates_class_and_shutdown():
    fake = FakeReplica(0)
    router = Router([fake], FAST).start()
    try:
        with pytest.raises(ValueError, match="request class"):
            router.submit(_image(), request_class="bulk")
    finally:
        router.close()
    with pytest.raises(ShuttingDownError):
        router.submit(_image())


# -- retry / failover / hedging (scripted) ------------------------------------

def test_transient_errors_retry_with_backoff():
    fake = FakeReplica(0)
    fake.fail_with = [RuntimeError("flaky"), RuntimeError("flaky")]
    with Router([fake], FAST) as router:
        res = router.submit(_image()).result(timeout=5)
        assert res.label == 0
        snap = router.metrics.snapshot()
        assert snap["retries"] == 2
        assert fake.submits == 3


def test_replica_fatal_requeues_then_all_down(tmp_path):
    with capture_journal(tmp_path) as jpath:
        fakes = [FakeReplica(0), FakeReplica(1)]
        for f in fakes:
            f.fail_with = [ReplicaKilledError("boom")] * 8
        with Router(fakes, FAST) as router:
            fut = router.submit(_image())
            with pytest.raises(AllReplicasDownError):
                fut.result(timeout=5)
            snap = router.metrics.snapshot()
            assert snap["replica_downs"] == 2
            assert snap["requeues"] == 2  # one failover hop per replica
            assert router.replica_states() == {0: "down", 1: "down"}
            # probes say "serving" but the generation never moved: the
            # router must NOT re-admit a dead engine behind a live probe
            time.sleep(0.1)
            assert router.replica_states() == {0: "down", 1: "down"}
            # a restart (generation bump) is what clears the mark
            fakes[0].fail_with = []
            fakes[0].generation = 1
            assert wait_for(lambda: router.replica_states()[0] == "serving")
            assert router.metrics.snapshot()["replica_ups"] == 1
    assert "replica_down" in _kinds(jpath)
    assert "replica_up" in _kinds(jpath)


def test_router_close_fails_outstanding_flights():
    fake = FakeReplica(0)
    fake.hang = True
    router = Router([fake], FAST).start()
    fut = router.submit(_image())
    router.close()
    with pytest.raises(ShuttingDownError):
        fut.result(timeout=1)


def test_hedge_timeout_derivation():
    fake = FakeReplica(0)
    with Router([fake], RouterConfig(health_interval_s=0.02,
                                     hedge_after_ms=40.0)) as router:
        assert router._hedge_after_ms() == 40.0
    with Router([fake], FAST) as router:
        assert router._hedge_after_ms() is None  # no samples yet
        for _ in range(FAST.hedge_min_samples):
            router.metrics.latency_ms[LATENCY_SENSITIVE].observe(1.0)
        # derived from the live p99, never below the floor
        assert router._hedge_after_ms() == FAST.hedge_floor_ms


# -- real fleet: failover, hedging, drain -------------------------------------

def test_replica_kill_failover_completes_every_request(make_fleet, tmp_path):
    plan = FaultPlan([Fault.serve_replica_kill(replica=0, request=0)])
    with capture_journal(tmp_path) as jpath:
        fleet = make_fleet(2, plan=plan)
        with Router(fleet, FAST) as router:
            futs = [router.submit(_image(i)) for i in range(12)]
            results = [f.result(timeout=30) for f in futs]
            assert all(r.logits.shape == (10,) for r in results)
            snap = router.metrics.snapshot()
            assert snap["replica_downs"] == 1
            assert snap["requeues"] >= 1
            assert snap["failed"] == {LATENCY_SENSITIVE: 0, BEST_EFFORT: 0}
            assert len(snap["recovery_ms"]) == 1  # down -> first reroute
            assert router.replica_states()[0] == "down"
            # restart rebuilds the whole replica; the shared cache keeps it
            # in load-not-compile time and the health loop re-admits it
            fleet[0].restart()
            assert wait_for(
                lambda: router.replica_states()[0] == "serving", timeout=10)
            router.submit(_image()).result(timeout=30)
    kinds = _kinds(jpath)
    for expected in ("replica_down", "request_requeued",
                     "failover_first_response", "replica_up"):
        assert expected in kinds, kinds


def test_stalled_replica_is_hedged_around(make_fleet, tmp_path):
    plan = FaultPlan([Fault.serve_replica_stall(replica=0, seconds=0.5,
                                                request=0)])
    with capture_journal(tmp_path) as jpath:
        fleet = make_fleet(2, plan=plan)
        cfg = RouterConfig(health_interval_s=0.02, hedge_after_ms=30.0)
        with Router(fleet, cfg) as router:
            res = router.submit(
                _image(), request_class=LATENCY_SENSITIVE).result(timeout=30)
            # the hedge (fires at 30ms) beats the 500ms stall
            assert res.latency_ms < 450
            assert router.metrics.snapshot()["hedges"] == 1
            # let the stalled loser finish so close() isn't racing it
            assert wait_for(
                lambda: fleet[0].server.queue_depth == 0
                and fleet[0].server.metrics.inflight == 0, timeout=5)
    assert "request_hedged" in _kinds(jpath)


def test_draining_replica_stops_receiving_new_work(make_fleet, tmp_path):
    with capture_journal(tmp_path) as jpath:
        fleet = make_fleet(2)
        with Router(fleet, FAST) as router:
            fleet[0].server.health.set("draining")
            assert wait_for(
                lambda: router.replica_states()[0] == "draining")
            admitted_before = fleet[0].server.metrics.snapshot()["admitted"]
            for i in range(6):
                router.submit(_image(i)).result(timeout=30)
            assert (fleet[0].server.metrics.snapshot()["admitted"]
                    == admitted_before)
            fleet[0].server.health.set("serving")
            assert wait_for(
                lambda: router.replica_states()[0] == "serving")
            snap = router.metrics.snapshot()
            assert snap["replica_drains"] == 1
            assert snap["replica_ups"] == 1
    assert "replica_drain" in _kinds(jpath)


# -- zero-downtime weight hot-swap --------------------------------------------

def test_weight_roll_is_zero_downtime_and_reversible(
        make_fleet, bundle, tmp_path):
    orig = bundle.params
    shifted = jax.tree_util.tree_map(lambda a: a + 0.5, orig)

    def load_weights(step):
        return (shifted if step == 7 else orig), bundle.model_state

    probe = _image(42)
    with capture_journal(tmp_path) as jpath:
        fleet = make_fleet(2, load_weights=load_weights)
        with Router(fleet, FAST) as router:
            logits_old = router.submit(probe).result(timeout=30).logits

            # requests in flight THROUGH the roll: none may drop, and each
            # must see a coherent weight set (pre- or post-swap, never torn)
            inflight_results: list = []
            stop = threading.Event()

            def pump():
                while not stop.is_set():
                    inflight_results.append(
                        router.submit(probe).result(timeout=30).logits)

            t = threading.Thread(target=pump, name="swap-pump")
            t.start()
            try:
                roll = router.roll_weights(7)
            finally:
                stop.set()
                t.join(timeout=60)
            assert not t.is_alive()
            assert roll == {"step": 7, "swapped": [0, 1], "failed": []}
            assert router.serving_step == 7
            assert all(r.server.engine.weights_version == 7 for r in fleet)

            logits_new = router.submit(probe).result(timeout=30).logits
            assert not np.allclose(logits_old, logits_new, atol=1e-3)
            assert inflight_results  # the pump made progress during the roll
            for got in inflight_results:
                assert (np.allclose(got, logits_old, atol=1e-4)
                        or np.allclose(got, logits_new, atol=1e-4)), \
                    "a request observed torn weights"

            # roll back to the original weights: same executable, same
            # batch composition -> bit-exact with the pre-swap answer
            assert router.roll_weights(8)["swapped"] == [0, 1]
            logits_back = router.submit(probe).result(timeout=30).logits
            np.testing.assert_array_equal(logits_back, logits_old)
    swaps = [e for e in events_mod.read_journal(jpath)
             if e["event"] == "weights_swap"]
    assert len(swaps) == 4 and all(e["ok"] for e in swaps)


def test_failed_swap_keeps_replica_on_old_weights(make_fleet, tmp_path):
    def load_weights(step):
        raise FileNotFoundError(f"no committed checkpoint at step {step}")

    probe = _image(43)
    with capture_journal(tmp_path) as jpath:
        fleet = make_fleet(1, load_weights=load_weights)
        with Router(fleet, FAST) as router:
            before = router.submit(probe).result(timeout=30).logits
            roll = router.roll_weights(9)
            assert roll["swapped"] == []
            assert roll["failed"][0]["replica"] == 0
            assert "FileNotFoundError" in roll["failed"][0]["reason"]
            assert router.serving_step is None
            # the replica is still serving its old weights, not wedged
            assert router.replica_states()[0] == "serving"
            assert fleet[0].server.engine.weights_version == 0
            after = router.submit(probe).result(timeout=30).logits
            np.testing.assert_array_equal(before, after)
            assert router.metrics.snapshot()["swap_failures"] == 1
    bad = [e for e in events_mod.read_journal(jpath)
           if e["event"] == "weights_swap"]
    assert bad and not bad[0]["ok"]


# -- commit-marker watcher ----------------------------------------------------

def test_checkpoint_watcher_follows_commit_markers(tmp_path):
    rolled: list = []
    w = CheckpointWatcher(tmp_path, rolled.append, initial_step=None)
    assert w.latest_committed() is None  # no commits dir yet
    commits = tmp_path / "commits"
    commits.mkdir()
    (commits / "not-a-step.committed").touch()  # strays are skipped
    assert w.poll_once() is None
    (commits / "5.committed").touch()
    assert w.poll_once() == 5
    (commits / "3.committed").touch()  # older than what we serve: ignored
    assert w.poll_once() is None
    (commits / "10.committed").touch()
    assert w.poll_once() == 10
    assert rolled == [5, 10]
    assert w.polls == 4 and w.rolls == 2


def test_checkpoint_watcher_consumes_a_failed_roll(tmp_path):
    calls: list = []

    def on_new_step(step):
        calls.append(step)
        if step == 20:
            raise RuntimeError("bad checkpoint")

    commits = tmp_path / "commits"
    commits.mkdir()
    w = CheckpointWatcher(tmp_path, on_new_step, initial_step=10)
    (commits / "10.committed").touch()
    assert w.poll_once() is None  # initial_step already served
    (commits / "20.committed").touch()
    assert w.poll_once() is None  # roll failed...
    assert w.poll_once() is None  # ...and is NOT retried every poll
    (commits / "30.committed").touch()
    assert w.poll_once() == 30  # the next commit retriggers naturally
    assert calls == [20, 30]


def test_watcher_drives_router_roll(make_fleet, bundle, tmp_path):
    shifted = jax.tree_util.tree_map(lambda a: a + 0.25, bundle.params)
    fleet = make_fleet(1, load_weights=lambda step: (shifted,
                                                    bundle.model_state))
    with Router(fleet, FAST) as router:
        w = CheckpointWatcher(tmp_path, router.roll_weights, initial_step=0)
        commits = tmp_path / "commits"
        commits.mkdir()
        (commits / "7.committed").touch()
        assert w.poll_once() == 7
        assert router.serving_step == 7
        assert fleet[0].server.engine.weights_version == 7


# -- fleet load generator -----------------------------------------------------

def test_fleet_loadgen_accounting_is_deterministic(make_fleet):
    fleet = make_fleet(2)
    with Router(fleet, FAST) as router:
        summary = run_fleet_loadgen(
            router, n_requests=40, concurrency=8,
            image_shape=IMAGE_SHAPE, seed=7, ls_fraction=0.5)
    n_ls = int((np.random.default_rng(7).random(40) < 0.5).sum())
    assert summary["offered"] == {LATENCY_SENSITIVE: n_ls,
                                  BEST_EFFORT: 40 - n_ls}
    assert summary["ok"] == summary["offered"]  # healthy fleet: all served
    assert summary["total_ok"] == 40
    for cls in (LATENCY_SENSITIVE, BEST_EFFORT):
        assert summary[f"latency_{cls}"]["p99_ms"] > 0
        assert summary["errors"][cls] == 0
        assert summary["dropped"][cls] == 0
    assert summary["router"]["completed"] == summary["ok"]
    # both replicas carried traffic (least-loaded spreading)
    assert all(r.server.metrics.snapshot()["admitted"] > 0 for r in fleet)


# -- HTTP replica transport ---------------------------------------------------

def test_http_replica_roundtrip_and_error_mapping():
    from dist_mnist_tpu.obs import MetricsExporter
    from dist_mnist_tpu.serve.router import HttpReplica

    seen: dict = {}
    fail: list = []

    def predict_fn(image, deadline_ms):
        seen["shape"] = image.shape
        seen["deadline_ms"] = deadline_ms
        if fail:
            raise fail.pop(0)
        return InferenceResult(logits=np.arange(10, dtype=np.float32),
                               label=3, latency_ms=1.0)

    def swap_fn(step):
        seen["swap"] = step
        return {"swapped": True, "step": step}

    exporter = MetricsExporter(
        MetricRegistry(), health=HealthState("serving"),
        predict_fn=predict_fn, swap_fn=swap_fn).start()
    replica = HttpReplica(0, f"http://127.0.0.1:{exporter.port}")
    try:
        res = replica.submit(_image(), deadline_ms=250.0).result(timeout=10)
        assert res.label == 3
        np.testing.assert_array_equal(
            res.logits, np.arange(10, dtype=np.float32))
        assert seen["shape"] == IMAGE_SHAPE
        assert seen["deadline_ms"] == 250.0

        snap = replica.probe()
        assert snap == {"state": "serving", "healthy": True, "generation": 0}

        # the typed statuses come back as the SAME exception types, so
        # classify_failure treats a remote replica exactly like a local one
        for sent, expect in ((QueueFullError("full"), QueueFullError),
                             (ShuttingDownError("bye"), ShuttingDownError),
                             (DeadlineExceededError("late"),
                              DeadlineExceededError)):
            fail.append(sent)
            with pytest.raises(expect):
                replica.submit(_image()).result(timeout=10)
        fail.append(ReplicaKilledError("dead engine"))
        with pytest.raises(ReplicaKilledError):
            replica.submit(_image()).result(timeout=10)

        replica.swap_to(12)
        assert seen["swap"] == 12
    finally:
        replica.close()
        exporter.close()
    # a closed exporter reads as a stopped replica, not an exception
    assert replica.probe()["state"] == "stopped"
