"""bench.py ladder-mode batch scaling (pure logic, no backend).

A ladder config's global batch is sized for its `ladder_devices` chip count
(BASELINE.md configs 3-5); bench preserves the per-chip batch on smaller
boxes so (a) steps/sec/chip stays comparable to the intended topology and
(b) a pod-slice batch cannot OOM a single chip (the measured failure that
motivated this: vit_tiny_cifar's batch-1024 step needs 19.4G HBM vs the
v5e's 15.75G).
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench
from dist_mnist_tpu.configs import get_config


def test_full_ladder_runs_config_batch():
    cfg = get_config("resnet20_cifar")  # ladder_devices=8, batch 1024
    batch, note = bench.ladder_batch(cfg, 8)
    assert batch == 1024
    assert note == "config global batch"
    # more chips than the ladder sized for: per-chip batch is PRESERVED in
    # this direction too (128/chip x 16), so per-chip anchors stay
    # comparable instead of reading as a fake regression (ADVICE r3 #4)
    assert bench.ladder_batch(cfg, 16)[0] == 2048


def test_small_box_preserves_per_chip_batch():
    cfg = get_config("vit_tiny_cifar")  # ladder_devices=16, batch 1024
    batch, note = bench.ladder_batch(cfg, 1)
    assert batch == 1024 // 16  # 64/chip
    assert "per-chip geometry" in note and "16-chip" in note
    # 4 of 16 chips -> 4x the per-chip batch
    assert bench.ladder_batch(cfg, 4)[0] == 4 * 64


def test_single_chip_configs_never_scale():
    for name in ("mlp_mnist", "lenet5_mnist"):  # ladder_devices=1
        cfg = get_config(name)
        assert bench.ladder_batch(cfg, 1)[0] == cfg.batch_size


def test_every_ladder_config_declares_a_consistent_ladder():
    from dist_mnist_tpu.configs import CONFIGS

    for cfg in CONFIGS.values():
        assert cfg.ladder_devices >= 1
        # per-chip batch must stay integral on the declared ladder
        assert cfg.batch_size % cfg.ladder_devices == 0, cfg.name


def test_probe_or_exit_failure_emits_script_schema(monkeypatch, capsys):
    """Script-mode probe failures must NOT reuse bench's steps/sec-shaped
    error line (a consumer would read a fake 0.0 measurement)."""
    import json

    import pytest

    monkeypatch.setattr(bench, "_probe", lambda r, t: ["probe timed out"])
    with pytest.raises(SystemExit) as exc:
        bench.probe_or_exit("my_script")
    assert exc.value.code == 1
    out = json.loads(capsys.readouterr().out.strip())
    assert out["script"] == "my_script"
    assert "probe timed out" in out["error"]
    assert "value" not in out and "unit" not in out  # not bench's schema


def test_probe_or_exit_success_applies_platform_override(monkeypatch):
    calls = []
    monkeypatch.setattr(bench, "_probe", lambda r, t: [])
    monkeypatch.setattr(bench, "apply_platform_override",
                        lambda: calls.append("override"))
    bench.probe_or_exit("my_script")
    assert calls == ["override"]  # the probed backend is the one pinned


def test_probe_backend_failure_carries_committed_anchor(monkeypatch, capsys):
    """An outage line must surface the last committed on-chip number as
    labeled context — value stays 0.0 (an outage is not a measurement)."""
    import json

    monkeypatch.setattr(bench, "_probe", lambda r, t: ["probe timed out"])
    assert bench.probe_backend(bench.HEADLINE_METRIC, retries=1) is False
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] == 0.0 and "error" in out
    anchor = out["extra"]["last_committed_anchor"]
    assert anchor["value"] > 0 and "NOT produced by this run" in anchor["note"]
