"""bench.py ladder-mode batch scaling (pure logic, no backend).

A ladder config's global batch is sized for its `ladder_devices` chip count
(BASELINE.md configs 3-5); bench preserves the per-chip batch on smaller
boxes so (a) steps/sec/chip stays comparable to the intended topology and
(b) a pod-slice batch cannot OOM a single chip (the measured failure that
motivated this: vit_tiny_cifar's batch-1024 step needs 19.4G HBM vs the
v5e's 15.75G).
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench
from dist_mnist_tpu.configs import get_config


def test_full_ladder_runs_config_batch():
    cfg = get_config("resnet20_cifar")  # ladder_devices=8, batch 1024
    batch, note = bench.ladder_batch(cfg, 8)
    assert batch == 1024
    assert note == "config global batch"
    # more chips than the ladder sized for: per-chip batch is PRESERVED in
    # this direction too (128/chip x 16), so per-chip anchors stay
    # comparable instead of reading as a fake regression (ADVICE r3 #4)
    assert bench.ladder_batch(cfg, 16)[0] == 2048


def test_small_box_preserves_per_chip_batch():
    cfg = get_config("vit_tiny_cifar")  # ladder_devices=16, batch 1024
    batch, note = bench.ladder_batch(cfg, 1)
    assert batch == 1024 // 16  # 64/chip
    assert "per-chip geometry" in note and "16-chip" in note
    # 4 of 16 chips -> 4x the per-chip batch
    assert bench.ladder_batch(cfg, 4)[0] == 4 * 64


def test_single_chip_configs_never_scale():
    for name in ("mlp_mnist", "lenet5_mnist"):  # ladder_devices=1
        cfg = get_config(name)
        assert bench.ladder_batch(cfg, 1)[0] == cfg.batch_size


def test_every_ladder_config_declares_a_consistent_ladder():
    from dist_mnist_tpu.configs import CONFIGS

    for cfg in CONFIGS.values():
        assert cfg.ladder_devices >= 1
        # per-chip batch must stay integral on the declared ladder
        assert cfg.batch_size % cfg.ladder_devices == 0, cfg.name


def test_probe_or_exit_failure_emits_script_schema(monkeypatch, capsys):
    """Script-mode probe failures must NOT reuse bench's steps/sec-shaped
    error line (a consumer would read a fake 0.0 measurement)."""
    import json

    import pytest

    monkeypatch.setattr(bench, "_probe", lambda r, t: ["probe timed out"])
    with pytest.raises(SystemExit) as exc:
        bench.probe_or_exit("my_script")
    assert exc.value.code == 1
    out = json.loads(capsys.readouterr().out.strip())
    assert out["script"] == "my_script"
    assert "probe timed out" in out["error"]
    assert "value" not in out and "unit" not in out  # not bench's schema


def test_probe_or_exit_success_applies_platform_override(monkeypatch):
    calls = []
    monkeypatch.setattr(bench, "_probe", lambda r, t: [])
    monkeypatch.setattr(bench, "apply_platform_override",
                        lambda: calls.append("override"))
    bench.probe_or_exit("my_script")
    assert calls == ["override"]  # the probed backend is the one pinned


def test_probe_backend_failure_carries_committed_anchor(monkeypatch, capsys):
    """An outage line must surface the last committed on-chip number as
    labeled context — value stays 0.0 (an outage is not a measurement)."""
    import json

    monkeypatch.setattr(bench, "_probe", lambda r, t: ["probe timed out"])
    assert bench.probe_backend(bench.HEADLINE_METRIC, retries=1) is False
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] == 0.0 and "error" in out
    anchor = out["extra"]["last_committed_anchor"]
    assert anchor["value"] > 0 and "NOT produced by this run" in anchor["note"]


class _FakeProc:
    def __init__(self, returncode, stdout="", stderr=""):
        self.returncode = returncode
        self.stdout = stdout
        self.stderr = stderr


def test_probe_timeout_env_override(monkeypatch):
    """BENCH_PROBE_TIMEOUT_S must override the per-attempt subprocess
    deadline (CI smoke lanes shrink a 150 s probe to seconds)."""
    import subprocess

    seen = []

    def fake_run(cmd, capture_output, text, timeout):
        seen.append(timeout)
        return _FakeProc(0, stdout="DEVCOUNT 8")

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setenv("BENCH_PROBE_TIMEOUT_S", "7")
    assert bench._probe(retries=3, timeout_s=150) == []
    assert seen == [7]

    monkeypatch.setenv("BENCH_PROBE_TIMEOUT_S", "not-a-number")
    assert bench._probe(retries=1, timeout_s=150) == []
    assert seen[-1] == 150  # junk override falls back to the default


def test_probe_short_circuits_on_connection_refused(monkeypatch):
    """A connection-refused-class failure means the relay is DOWN, not
    flaky: remaining attempts (and their backoff sleeps) must be skipped."""
    import subprocess

    attempts = []

    def fake_run(cmd, capture_output, text, timeout):
        attempts.append(1)
        return _FakeProc(1, stderr="RPC failed: Connection refused (ECONNREFUSED)")

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep",
                        lambda s: (_ for _ in ()).throw(
                            AssertionError("backoff sleep after a fatal error")))
    errs = bench._probe(retries=3, timeout_s=1)
    assert len(attempts) == 1  # short-circuited after the first attempt
    assert len(errs) == 1 and "short-circuited" in errs[0]


def test_probe_still_retries_transient_errors(monkeypatch):
    import subprocess

    attempts = []

    def fake_run(cmd, capture_output, text, timeout):
        attempts.append(1)
        return _FakeProc(1, stderr="transient tunnel hiccup")

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    errs = bench._probe(retries=3, timeout_s=1)
    assert len(attempts) == 3 and len(errs) == 3


# ------------------------------------------------- cpu fallback (ISSUE 3) --


def test_probe_falls_back_to_cpu_and_tags_records(monkeypatch, capsys):
    """TPU probe down, CPU probe up: the run proceeds and EVERY emitted
    record carries `backend: cpu-fallback` — a labeled CPU number instead
    of no number (and never a number masquerading as on-chip)."""
    import json
    import os

    def fake_probe(retries, timeout_s):
        if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
            return []  # CPU probe succeeds
        return ["RPC failed: Connection refused (ECONNREFUSED)"]

    monkeypatch.setattr(bench, "_probe", fake_probe)
    monkeypatch.setattr(bench, "_RECORD_TAGS", {})
    monkeypatch.setenv("JAX_PLATFORMS", "")  # pretend the relay was selected

    assert bench.probe_backend_with_fallback("steps_per_sec_per_chip",
                                             retries=1) is True
    assert os.environ["JAX_PLATFORMS"] == "cpu"
    assert bench._RECORD_TAGS == {"backend": "cpu-fallback"}

    bench.emit({"metric": "steps_per_sec_per_chip", "value": 123.0})
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["backend"] == "cpu-fallback"
    assert rec["value"] == 123.0


def test_probe_fallback_both_down_emits_error(monkeypatch, capsys):
    """TPU AND CPU probes down: structured error line with both probes'
    errors, rc path returns False, and no fallback tag leaks."""
    import json

    monkeypatch.setattr(bench, "_probe",
                        lambda r, t: ["RPC failed: Connection refused"])
    monkeypatch.setattr(bench, "_RECORD_TAGS", {})
    monkeypatch.setenv("JAX_PLATFORMS", "")

    assert bench.probe_backend_with_fallback("steps_per_sec_per_chip",
                                             retries=2) is False
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["value"] == 0.0
    assert "cpu fallback failed" in rec["error"]
    assert rec["extra"]["probe_errors"]
    assert "backend" not in rec  # no fallback tag on a failed run
    assert bench._RECORD_TAGS == {}
