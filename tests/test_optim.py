"""Optimizer math: TF-Adam parity, transforms, sync semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dist_mnist_tpu import optim


def _numpy_tf_adam(params, grads_seq, lr=0.01, b1=0.9, b2=0.999, eps=1e-8):
    """Reference loop implementing training_ops.h ApplyAdam exactly."""
    p = params.copy()
    m = np.zeros_like(p)
    v = np.zeros_like(p)
    for t, g in enumerate(grads_seq, start=1):
        lr_t = lr * np.sqrt(1 - b2**t) / (1 - b1**t)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        p = p - lr_t * m / (np.sqrt(v) + eps)
    return p


def test_adam_matches_tf_semantics():
    rng = np.random.default_rng(0)
    p0 = rng.normal(size=(7,)).astype(np.float32)
    grads = [rng.normal(size=(7,)).astype(np.float32) for _ in range(5)]
    expected = _numpy_tf_adam(p0, grads)

    opt = optim.adam(0.01)
    params = {"w": jnp.asarray(p0)}
    state = opt.init(params)
    for g in grads:
        updates, state = opt.update({"w": jnp.asarray(g)}, state, params)
        params = optim.apply_updates(params, updates)
    np.testing.assert_allclose(np.asarray(params["w"]), expected, rtol=1e-5)


def test_adam_state_is_f32_even_for_bf16_grads():
    opt = optim.adam(0.01)
    params = {"w": jnp.ones((3,), jnp.float32)}
    state = opt.init(params)
    updates, state = opt.update({"w": jnp.ones((3,), jnp.bfloat16)}, state, params)
    assert state["m"]["w"].dtype == jnp.float32
    assert updates["w"].dtype == jnp.float32


def test_momentum_and_sgd_shapes():
    for opt in (optim.sgd(0.1), optim.momentum(0.1, 0.9),
                optim.momentum(0.1, 0.9, nesterov=True)):
        params = {"a": jnp.ones((2, 2))}
        state = opt.init(params)
        updates, state = opt.update({"a": jnp.ones((2, 2))}, state, params)
        new = optim.apply_updates(params, updates)
        assert new["a"].shape == (2, 2)
        assert float(jnp.abs(new["a"] - params["a"]).max()) > 0


def test_clip_by_global_norm():
    opt = optim.clip_by_global_norm(1.0)
    g = {"a": jnp.full((4,), 10.0)}
    updates, _ = opt.update(g, opt.init(g), g)
    assert float(optim.global_norm(updates)) == pytest.approx(1.0, rel=1e-5)
    small = {"a": jnp.full((4,), 0.01)}
    updates, _ = opt.update(small, opt.init(small), small)
    np.testing.assert_allclose(np.asarray(updates["a"]), 0.01, rtol=1e-5)


def test_chain_order():
    opt = optim.chain(optim.scale(2.0), optim.sgd(1.0))
    params = {"a": jnp.zeros(())}
    updates, _ = opt.update({"a": jnp.ones(())}, opt.init(params), params)
    assert float(updates["a"]) == pytest.approx(-2.0)


def test_gradient_accumulation_matches_large_batch():
    """k accumulated microbatches == one update on the averaged gradient
    (the replicas_to_aggregate mapping, optim/sync.py)."""
    k = 4
    rng = np.random.default_rng(1)
    grads = [rng.normal(size=(5,)).astype(np.float32) for _ in range(k)]
    mean_grad = np.mean(grads, axis=0)

    base = optim.adam(0.01)
    accum = optim.gradient_accumulation(optim.adam(0.01), every=k)

    params = {"w": jnp.zeros((5,))}
    # path A: k microbatch calls through the accumulator
    sa = accum.init(params)
    pa = params
    intermediate = []
    for g in grads:
        updates, sa = accum.update({"w": jnp.asarray(g)}, sa, pa)
        pa = optim.apply_updates(pa, updates)
        intermediate.append(np.asarray(pa["w"]).copy())
    # params must not move before the boundary (§3.4 worker view)
    for snap in intermediate[:-1]:
        np.testing.assert_array_equal(snap, 0.0)
    # path B: one update with the averaged gradient
    sb = base.init(params)
    updates, sb = base.update({"w": jnp.asarray(mean_grad)}, sb, params)
    pb = optim.apply_updates(params, updates)
    np.testing.assert_allclose(np.asarray(pa["w"]), np.asarray(pb["w"]), rtol=1e-5)
    # and the inner count advanced exactly once
    assert int(sa["inner"]["count"]) == 1


def test_gradient_accumulation_every_one_is_identity():
    inner = optim.adam(0.01)
    assert optim.gradient_accumulation(inner, 1) is inner


def test_schedules():
    from dist_mnist_tpu.optim import schedules

    cos = schedules.cosine_decay(1.0, 100, warmup_steps=10)
    assert float(cos(jnp.int32(0))) == pytest.approx(0.0)
    assert float(cos(jnp.int32(10))) == pytest.approx(1.0, abs=1e-6)
    assert float(cos(jnp.int32(100))) == pytest.approx(0.0, abs=1e-6)
    step = schedules.step_decay(1.0, (10, 20), 0.1)
    assert float(step(jnp.int32(5))) == pytest.approx(1.0)
    assert float(step(jnp.int32(15))) == pytest.approx(0.1)
    assert float(step(jnp.int32(25))) == pytest.approx(0.01, rel=1e-4)


def test_adamw_decoupled_decay():
    """adamw decay bypasses m/v normalization: for equal params and zero
    grads, the update is exactly -lr*wd*p."""
    opt = optim.adamw(0.1, weight_decay=0.5)
    params = {"w": jnp.full((3,), 2.0)}
    state = opt.init(params)
    updates, _ = opt.update({"w": jnp.zeros((3,))}, state, params)
    np.testing.assert_allclose(np.asarray(updates["w"]), -0.1 * 0.5 * 2.0,
                               rtol=1e-6)
