"""`hypothesis` import shim for the property tests.

The image this repo targets does not always ship `hypothesis` (and the
no-new-deps rule forbids installing it). Importing it at module top level
made three whole test modules ERROR at collection, losing every
non-property test in them. This shim re-exports the real library when
present; otherwise the property tests skip individually and the rest of
each module still runs.
"""

from __future__ import annotations

try:
    from hypothesis import given, settings, strategies as st  # noqa: F401

    HAVE_HYPOTHESIS = True
except ModuleNotFoundError:
    import pytest

    HAVE_HYPOTHESIS = False

    def given(*_args, **_kwargs):
        return lambda fn: pytest.mark.skip(reason="hypothesis not installed")(fn)

    def settings(*_args, **_kwargs):
        return lambda fn: fn

    class _AnyStrategy:
        """Stands in for `strategies`: every attribute is a callable
        returning None — the stub `given` never evaluates strategies."""

        def __getattr__(self, name):
            return lambda *a, **k: None

    st = _AnyStrategy()
