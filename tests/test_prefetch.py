"""DevicePrefetcher: stream equivalence, trajectory identity, recovery
re-seek, worker lifecycle, and the TrainLoop runahead bound.

The determinism contract under test: a prefetched feed is an OVERLAP
optimization only — it must never reorder, drop, or duplicate batches, so
everything downstream (loss trajectories, recovery replay) is bit-identical
to the synchronous feed.
"""

import collections
import itertools
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dist_mnist_tpu import optim
from dist_mnist_tpu.cluster.mesh import activate
from dist_mnist_tpu.data.pipeline import ShardedBatcher
from dist_mnist_tpu.data.prefetch import (
    THREAD_NAME_PREFIX,
    DevicePrefetcher,
    PrefetchStats,
)
from dist_mnist_tpu.hooks import InputPipelineHook, StopAtStepHook
from dist_mnist_tpu.models import get_model
from dist_mnist_tpu.train import create_train_state
from dist_mnist_tpu.train.loop import PreemptionError, TrainLoop
from dist_mnist_tpu.train.state import TrainState
from dist_mnist_tpu.train.step import make_train_step


def _live_workers():
    return [t for t in threading.enumerate()
            if t.name.startswith(THREAD_NAME_PREFIX) and t.is_alive()]


def _wait_drained(timeout=2.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if not _live_workers():
            return True
        time.sleep(0.01)
    return False


def _take(iterable, n):
    """First n items, CLOSING the iterator (islice would leave a prefetch
    worker running behind a suspended generator)."""
    it = iter(iterable)
    try:
        return [next(it) for _ in range(n)]
    finally:
        if hasattr(it, "close"):
            it.close()


def _host(batch):
    return {k: np.asarray(v) for k, v in batch.items()}


# ---------------------------------------------------------------- stream


def test_prefetched_stream_identical_to_sync(small_mnist, mesh8):
    """≥2 epochs (8 steps/epoch at batch 512 on the 4096-row set): the
    prefetched stream is the sync stream, batch for batch, bit for bit."""
    sync = _take(ShardedBatcher(small_mnist, 512, mesh8, seed=0), 20)
    pre = _take(
        DevicePrefetcher(ShardedBatcher(small_mnist, 512, mesh8, seed=0),
                         depth=3), 20)
    for s, p in zip(sync, pre):
        hs, hp = _host(s), _host(p)
        np.testing.assert_array_equal(hs["image"], hp["image"])
        np.testing.assert_array_equal(hs["label"], hp["label"])


def test_prefetched_batches_are_device_resident(small_mnist, mesh8):
    (batch,) = _take(
        DevicePrefetcher(ShardedBatcher(small_mnist, 512, mesh8)), 1)
    assert isinstance(batch["image"], jax.Array)
    assert batch["image"].sharding.mesh.shape == mesh8.shape


def test_at_step_reseek_matches_inner(small_mnist, mesh8):
    inner = ShardedBatcher(small_mnist, 512, mesh8, seed=0)
    want = _take(inner.at_step(5), 4)
    got = _take(DevicePrefetcher(inner, depth=2).at_step(5), 4)
    for w, g in zip(want, got):
        np.testing.assert_array_equal(_host(w)["label"], _host(g)["label"])


def test_at_step_requires_seekable_inner():
    with pytest.raises(TypeError, match="at_step"):
        DevicePrefetcher(itertools.repeat({"x": np.zeros(1)})).at_step(3)


def test_depth_must_be_positive(small_mnist, mesh8):
    with pytest.raises(ValueError, match="depth"):
        DevicePrefetcher(ShardedBatcher(small_mnist, 512, mesh8), depth=0)


# ------------------------------------------------------ worker lifecycle


def test_worker_drains_on_exhaustion():
    items = [{"x": np.ones(4)} for _ in range(5)]
    got = list(DevicePrefetcher(items, depth=2))
    assert len(got) == 5
    assert _wait_drained()


def test_inner_exception_propagates_and_drains():
    def bad():
        yield {"x": np.ones(4)}
        yield {"x": np.ones(4)}
        raise ValueError("corrupt shard")

    class _Seekless:
        def __init__(self, gen):
            self._gen = gen

        def __iter__(self):
            return self._gen()

    pf = DevicePrefetcher(_Seekless(bad), depth=2)
    with pytest.raises(ValueError, match="corrupt shard"):
        list(pf)
    assert _wait_drained()


def test_early_close_drains_worker(small_mnist, mesh8):
    """Closing mid-stream (what TrainLoop's finally does) must reap the
    worker even while it is blocked on a full ring."""
    pf = DevicePrefetcher(ShardedBatcher(small_mnist, 512, mesh8), depth=2)
    it = iter(pf)
    next(it)
    assert _live_workers()  # worker is up and filling the ring
    it.close()
    assert _wait_drained()


def test_prefetcher_close_reaps_all_streams(small_mnist, mesh8):
    pf = DevicePrefetcher(ShardedBatcher(small_mnist, 512, mesh8), depth=2)
    it = iter(pf)
    next(it)
    pf.close()
    assert _wait_drained()
    it.close()


def test_stats_accumulate(small_mnist, mesh8):
    pf = DevicePrefetcher(ShardedBatcher(small_mnist, 512, mesh8), depth=2)
    _take(pf, 6)
    s = pf.stats()
    assert s["batches"] == 6
    assert s["h2d_bytes"] > 0
    assert s["depth"] == 2
    assert 0.0 <= s["mean_occupancy"] <= 2.0


# ------------------------------------------------- training equivalence


def _mlp_setup(small_mnist, mesh):
    model = get_model("mlp")
    optimizer = optim.adam(1e-3)
    state = create_train_state(
        model, optimizer, jax.random.PRNGKey(0), small_mnist.train_images[:1]
    )
    # donate=False: the SAME initial state feeds both trajectories
    step = make_train_step(model, optimizer, mesh, donate=False)
    return state, step


def _loss_trajectory(step, state, batches, n_steps):
    losses = []
    it = iter(batches)
    try:
        for _ in range(n_steps):
            state, out = step(state, next(it))
            losses.append(float(jax.device_get(out["loss"])))
    finally:
        if hasattr(it, "close"):
            it.close()
    return losses


def test_loss_trajectory_bit_identical(small_mnist, mesh8):
    """Two full epochs of real MLP training: prefetched feed reproduces the
    sync feed's loss trajectory EXACTLY (not approximately)."""
    with activate(mesh8):
        state, step = _mlp_setup(small_mnist, mesh8)
        n = 16  # 2 epochs at 8 steps/epoch
        sync = _loss_trajectory(
            step, state, ShardedBatcher(small_mnist, 512, mesh8, seed=0), n)
        pre = _loss_trajectory(
            step, state,
            DevicePrefetcher(ShardedBatcher(small_mnist, 512, mesh8, seed=0),
                             depth=3), n)
    assert sync == pre  # bit-identical, no tolerance


# ------------------------------------------------------ loop integration


def _loop_state(step=0):
    return TrainState(
        step=jnp.int32(step), params={}, model_state={}, opt_state={},
        rng=jnp.zeros((2,), jnp.uint32),
    )


class _MemoryCkpt:
    def __init__(self):
        self.saved = None

    def save(self, state):
        self.saved = state

    def restore(self, target):
        return self.saved


class _RecordingFlakyStep:
    """Records each consumed batch's label checksum; raises PreemptionError
    on the call indices in `fail_at` (batch consumed but NOT recorded —
    exactly the consumed-then-lost case replay must cover)."""

    def __init__(self, fail_at=()):
        self.fail_at = set(fail_at)
        self.calls = 0
        self.seen = []

    def __call__(self, state, batch):
        n = self.calls
        self.calls += 1
        if n in self.fail_at:
            raise PreemptionError("injected preemption")
        self.seen.append(int(np.asarray(batch["label"]).sum()))
        return (
            TrainState(step=state.step + 1, params=state.params,
                       model_state=state.model_state,
                       opt_state=state.opt_state, rng=state.rng),
            {"loss": jnp.float32(0.0)},
        )


def test_recovery_replays_through_prefetcher(small_mnist, mesh8):
    """Preemption mid-stream with a prefetched feed: restore + at_step
    re-seek must REPLAY the batches consumed since the checkpoint (the ring
    had already pulled ahead), not skip past them."""
    expected = [int(_host(b)["label"].sum()) for b in
                _take(ShardedBatcher(small_mnist, 512, mesh8, seed=0), 6)]

    step = _RecordingFlakyStep(fail_at={3})
    mgr = _MemoryCkpt()
    state = _loop_state()
    mgr.save(state)  # checkpoint at step 0

    batches = DevicePrefetcher(
        ShardedBatcher(small_mnist, 512, mesh8, seed=0), depth=2)
    loop = TrainLoop(step, state, batches, [StopAtStepHook(last_step=6)],
                     checkpoint_manager=mgr, max_recoveries=1)
    final = loop.run()

    assert final.step_int == 6
    # calls 0-2 trained on b0..b2; call 3 lost b3 to the preemption; the
    # recovered run replays b0..b5 from the restored step — nothing skipped
    assert step.seen == expected[:3] + expected[:6]
    assert _wait_drained()
    # the re-seeked prefetcher shares the stats object: counts accumulate
    assert loop.batches.stats()["batches"] >= 9


def test_runahead_bounds_inflight_outputs():
    observed = []

    def fake_step(state, batch):
        return (
            TrainState(step=state.step + 1, params=state.params,
                       model_state=state.model_state,
                       opt_state=state.opt_state, rng=state.rng),
            {"loss": jnp.float32(1.0)},
        )

    loop = TrainLoop(fake_step, _loop_state(), itertools.repeat(1.0),
                     [StopAtStepHook(last_step=12)], runahead=2)

    class _WatchedDeque(collections.deque):
        def append(self, x):
            super().append(x)
            observed.append(len(self))

    loop._inflight = _WatchedDeque()
    final = loop.run()
    assert final.step_int == 12  # bound changes scheduling, not results
    assert observed and max(observed) <= 2
    assert loop.runahead_wait_s >= 0.0
    assert not loop._inflight  # drained in finally


def test_input_pipeline_hook_reports(small_mnist, mesh8):
    class _BatchRecWriter:
        def __init__(self):
            self.rows = []

        def scalar(self, tag, value, step):
            self.rows.append((step, {tag: value}))

        def scalars(self, values, step):
            self.rows.append((step, dict(values)))

    writer = _BatchRecWriter()
    step = _RecordingFlakyStep()
    batches = DevicePrefetcher(
        ShardedBatcher(small_mnist, 512, mesh8, seed=0), depth=2)
    loop = TrainLoop(step, _loop_state(), batches,
                     [InputPipelineHook(writer, every_steps=4),
                      StopAtStepHook(last_step=8)],
                     runahead=1)
    loop.run()

    assert writer.rows, "hook wrote nothing at its cadence"
    steps = [s for s, _ in writer.rows]
    assert steps == [4, 8]
    for _, vals in writer.rows:
        assert "input/feed_stall_ms_per_step" in vals
        assert "input/runahead_wait_ms_per_step" in vals
        assert "input/prefetch_occupancy" in vals
        assert "input/h2d_mbytes_per_step" in vals
        assert vals["input/h2d_mbytes_per_step"] > 0
    assert loop.hooks[0].last  # bench harness handle
    assert _wait_drained()


def test_shared_stats_object_survives_reseek(small_mnist, mesh8):
    stats = PrefetchStats(depth=2)
    pf = DevicePrefetcher(ShardedBatcher(small_mnist, 512, mesh8),
                          depth=2, stats=stats)
    _take(pf, 3)
    _take(pf.at_step(4), 2)
    assert pf.stats()["batches"] == 5
