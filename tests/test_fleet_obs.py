"""Fleet observability: Prometheus text round-trip, cross-host scraping
and merging, straggler and anomaly detection, correlated step tracing,
and the supervisor-side fleet ladder under elastic resizes."""

import contextlib
import io
import itertools
import json
import re
import socket
import sys
import textwrap
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

from dist_mnist_tpu.hooks import StopAtStepHook
from dist_mnist_tpu.obs import events
from dist_mnist_tpu.obs.anomaly import AnomalyHook, RobustDetector
from dist_mnist_tpu.obs.events import RunJournal, read_journal
from dist_mnist_tpu.obs.exporter import (
    HealthState,
    MetricsExporter,
    render_prometheus,
)
from dist_mnist_tpu.obs.fleet import FleetScraper, parse_prometheus
from dist_mnist_tpu.obs.hist import StreamingHistogram
from dist_mnist_tpu.obs.registry import MetricRegistry
from dist_mnist_tpu.train.loop import TrainLoop
from dist_mnist_tpu.train.state import TrainState

REPO = Path(__file__).resolve().parents[1]


@pytest.fixture(autouse=True)
def _no_ambient_journal():
    prev = events.set_journal(None)
    yield
    events.set_journal(prev)


def _get(url, timeout=10):
    """(status, body) for a GET, without raising on 4xx/5xx."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _state(step=0):
    return TrainState(
        step=jnp.int32(step), params={}, model_state={}, opt_state={},
        rng=jnp.zeros((2,), jnp.uint32),
    )


def _fake_step(state, batch):
    return (
        TrainState(step=state.step + 1, params=state.params,
                   model_state=state.model_state, opt_state=state.opt_state,
                   rng=state.rng),
        {"loss": jnp.float32(batch)},
    )


# -- Prometheus text round-trip ------------------------------------------------

def test_histogram_prometheus_round_trip_is_exact():
    """render_prometheus -> parse_prometheus reconstructs the histogram
    bucket-for-bucket: the fleet merge path loses nothing."""
    h = StreamingHistogram()
    for v in [0.5, 1.0, 2.5, 2.5, 40.0, 900.0, 1e9]:
        h.observe(v)
    reg = MetricRegistry()
    reg.attach_histogram("train/step_time_ms", h)
    text = render_prometheus(reg)
    _, hists, _ = parse_prometheus(text)
    back = hists["train_step_time_ms"]
    assert back._counts == h._counts
    assert back.count == h.count
    assert back.sum == pytest.approx(h.sum)
    assert back.percentiles()["p50"] == h.percentiles()["p50"]
    # merging two parsed copies doubles every bucket
    back.merge(hists["train_step_time_ms"])
    assert back.count == 2 * h.count


def test_parse_prometheus_scalars_info_and_state():
    reg = MetricRegistry()
    reg.set_scalar("goodput/fraction", 0.875, 7)
    health = HealthState()
    health.set("degraded", "anomaly: loss")
    text = render_prometheus(
        reg, health, info={"host_id": "3", "role": "train"})
    scalars, _, info = parse_prometheus(text)
    assert scalars["goodput_fraction"] == pytest.approx(0.875)
    assert info["host_id"] == "3" and info["role"] == "train"
    assert info["state"] == "degraded"


def test_healthz_degraded_is_200_but_flagged():
    health = HealthState()
    health.set("training")
    health.set("degraded", "anomaly: loss")
    assert health.healthy  # degraded serves 200: still doing useful work
    snap = health.snapshot()
    assert snap["state"] == "degraded" and snap["detail"] == "anomaly: loss"
    text = render_prometheus(None, health)
    assert 'process_state{state="degraded"} 1' in text
    assert "process_healthy 1" in text


# -- exporter under concurrent scrape ------------------------------------------

def test_concurrent_scrapes_against_live_exporter():
    """N scrape threads against one exporter while the owner keeps
    writing: every response parses, no tearing, no 500s."""
    reg = MetricRegistry()
    hist = StreamingHistogram()
    reg.attach_histogram("train/step_time_ms", hist)
    health = HealthState()
    health.set("training")
    with MetricsExporter(reg, health=health, port=0,
                         info={"host_id": "0", "role": "train"}) as exp:
        stop = threading.Event()

        def writer():
            i = 0
            while not stop.is_set():
                hist.observe(1.0 + (i % 50))
                reg.set_scalar("train/loss", 1.0 / (i + 1), i)
                i += 1

        wt = threading.Thread(target=writer, daemon=True)
        wt.start()
        errors = []

        def scrape():
            for _ in range(20):
                code, body = _get(exp.url("/metrics"))
                if code != 200:
                    errors.append(code)
                    continue
                _, hists, info = parse_prometheus(body)
                if info.get("host_id") != "0":
                    errors.append("info lost")
                h = hists.get("train_step_time_ms")
                # cumulative buckets must reconstruct self-consistently
                if h is not None and h.count != sum(h._counts):
                    errors.append("torn histogram")

        threads = [threading.Thread(target=scrape) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        wt.join()
    assert not errors


# -- fleet scraper -------------------------------------------------------------

def _child_exporter(host_id, mean_ms, n=20):
    reg = MetricRegistry()
    hist = StreamingHistogram()
    for _ in range(n):
        hist.observe(mean_ms)
    reg.attach_histogram("train/step_time_ms", hist)
    health = HealthState()
    health.set("training")
    exp = MetricsExporter(
        reg, health=health, port=0,
        info={"host_id": str(host_id), "generation": "0", "role": "train"},
    ).start()
    return exp, hist


def test_fleet_scraper_merges_two_children(tmp_path):
    exp0, hist0 = _child_exporter(0, 5.0)
    exp1, hist1 = _child_exporter(1, 8.0)
    scraper = FleetScraper(interval_s=60)
    sup = None
    try:
        scraper.set_targets({0: f"http://127.0.0.1:{exp0.port}",
                             1: f"http://127.0.0.1:{exp1.port}"})
        snap = scraper.scrape_once()
        assert [h["reachable"] for h in snap["hosts"]] == [True, True]
        assert snap["hosts"][0]["info"]["host_id"] == "0"
        merged = scraper.merged_histograms()["train_step_time_ms"]
        assert merged.count == hist0.count + hist1.count
        scalars = scraper.registry.scalars()
        assert scalars["fleet/hosts"][0] == 2
        assert scalars["fleet/reachable_hosts"][0] == 2
        assert scalars["fleet/healthy_hosts"][0] == 2
        # supervisor exporter serves the merged fleet view + /fleet JSON
        sup = MetricsExporter(
            registry=scraper.registry, port=0,
            info={"role": "supervisor", "generation": 0},
            fleet=scraper,
        ).start()
        code, body = _get(sup.url("/metrics"))
        assert code == 200
        assert "# TYPE fleet_train_step_time_ms histogram" in body
        assert 'fleet_host_up{host="0"} 1' in body
        assert 'fleet_host_up{host="1"} 1' in body
        assert 'process_info{generation="0",role="supervisor"} 1' in body
        _, hists, _ = parse_prometheus(body)
        assert hists["fleet_train_step_time_ms"].count == merged.count
        code, body = _get(sup.url("/fleet"))
        assert code == 200
        fleet = json.loads(body)
        assert len(fleet["hosts"]) == 2 and fleet["scrapes"] == 1
        # a vanished child is data, not an error: scrape keeps going
        exp1.close()
        snap = scraper.scrape_once()
        assert [h["reachable"] for h in snap["hosts"]] == [True, False]
        assert scraper.registry.scalars()["fleet/reachable_hosts"][0] == 1
    finally:
        if sup is not None:
            sup.close()
        scraper.close()
        exp0.close()
        exp1.close()


def test_straggler_detection_names_the_host(tmp_path):
    exp0, hist0 = _child_exporter(0, 5.0)
    exp1, hist1 = _child_exporter(1, 50.0)
    jrnl = RunJournal(tmp_path / "j.jsonl")
    scraper = FleetScraper(journal=jrnl, interval_s=60,
                           straggler_ratio=2.0, straggler_window=3)
    try:
        scraper.set_targets({0: f"http://127.0.0.1:{exp0.port}",
                             1: f"http://127.0.0.1:{exp1.port}"})
        for _ in range(3):
            # both hosts keep stepping at their characteristic speed
            hist0.observe(5.0)
            hist1.observe(50.0)
            snap = scraper.scrape_once()
        assert snap["straggler"]["host"] == 1
        assert snap["straggler"]["ratio"] == pytest.approx(10.0)
        assert snap["straggler"]["detected"] == 1
        scalars = scraper.registry.scalars()
        assert scalars["fleet/straggler_host"][0] == 1
        assert scalars["fleet/straggler_ratio"][0] == pytest.approx(10.0)
        assert scalars["fleet/stragglers_detected"][0] == 1
    finally:
        scraper.close()
        exp0.close()
        exp1.close()
        jrnl.close()
    recs = [r for r in read_journal(tmp_path / "j.jsonl")
            if r["event"] == "straggler_detected"]
    assert len(recs) == 1  # sustained skew fires ONCE, not per scrape
    assert recs[0]["host"] == 1
    assert recs[0]["ratio"] == pytest.approx(10.0)
    assert recs[0]["window"] == 3
    # tail_run renders it with the host in the head
    sys.path.insert(0, str(REPO / "scripts"))
    try:
        from tail_run import format_record
    finally:
        sys.path.pop(0)
    line = format_record(recs[0])
    assert "straggler_detected" in line and "host=1" in line
    assert "10.00x median" in line


def test_fleet_tags_are_hygienic():
    """The fleet/* namespace follows the repo tag convention
    (docs/OBSERVABILITY.md, enforced for the other namespaces in
    test_obs_spine.py)."""
    tag_re = re.compile(r"^[a-z0-9_/.]+$")
    scraper = FleetScraper(interval_s=60)
    try:
        scraper.scrape_once()  # zero targets still publishes the gauges
        tags = scraper.registry.tags()
        assert "fleet/hosts" in tags and "fleet/straggler_ratio" in tags
        for tag in tags:
            assert tag.startswith("fleet/"), tag
            assert tag_re.match(tag), f"non-hygienic fleet tag {tag!r}"
    finally:
        scraper.close()


# -- anomaly detection ---------------------------------------------------------

def test_robust_detector_flags_spike_not_drift():
    det = RobustDetector(window=16, threshold=6.0, warmup=4)
    verdicts = [det.check(1.0 + 0.01 * (i % 3)) for i in range(10)]
    assert all(v is None or not v["anomaly"] for v in verdicts)
    v = det.check(50.0)
    assert v is not None and v["anomaly"] and v["z"] >= 6.0
    # the spike entered the window but cannot poison the median
    v = det.check(1.0)
    assert not v["anomaly"]


def test_robust_detector_flat_window_still_fires():
    det = RobustDetector(window=8, threshold=6.0, warmup=4)
    for _ in range(6):
        det.check(2.0)  # MAD == 0: the relative-change fallback engages
    v = det.check(3.0)
    assert v is not None and v["anomaly"]


def test_anomaly_hook_degraded_flip_and_recovery(tmp_path):
    jrnl = RunJournal(tmp_path / "j.jsonl")
    events.set_journal(jrnl)
    health = HealthState()
    health.set("training")
    hook = AnomalyHook(every_steps=1, health=health, threshold=5.0,
                       window=8, warmup=3, recovery_cadences=2)

    class _Loop:
        initial_step = 0
        step_time_hist = StreamingHistogram()

    hook.begin(_Loop())
    step = 0
    for _ in range(6):
        step += 1
        hook.after_step(step, None, {"loss": jnp.float32(1.0)})
    assert health.state == "training" and not hook.anomalies
    step += 1
    hook.after_step(step, None, {"loss": jnp.float32(500.0)})
    assert hook.anomalies and hook.anomalies[0]["kind"] == "loss"
    assert health.state == "degraded"
    assert health.healthy  # degraded is 200-but-flagged, not an outage
    for _ in range(2):
        step += 1
        hook.after_step(step, None, {"loss": jnp.float32(1.0)})
    assert health.state == "training"  # recovery_cadences clean -> restored
    jrnl.close()
    evs = [r["event"] for r in read_journal(tmp_path / "j.jsonl")]
    assert "anomaly" in evs and "anomaly_cleared" in evs


def test_anomaly_hook_never_perturbs_the_trajectory(tmp_path):
    """The bit-identical pin: the same loop with and without the hook
    (plus a spiky loss that FIRES it) produces the same trajectory."""
    batches = [1.0, 1.0, 1.0, 1.0, 1.0, 400.0, 1.0, 1.0, 1.0, 1.0]

    def run(with_hook):
        seen = []

        class _Watch:
            def begin(self, loop):
                pass

            def before_step(self, step):
                pass

            def after_step(self, step, state, outputs):
                seen.append(
                    np.asarray(outputs["loss"], np.float32).tobytes())

            def end(self, state):
                pass

        hooks = [_Watch(), StopAtStepHook(last_step=len(batches))]
        anomaly = None
        if with_hook:
            anomaly = AnomalyHook(every_steps=1, threshold=5.0,
                                  window=8, warmup=3)
            hooks.append(anomaly)
        loop = TrainLoop(_fake_step, _state(), iter(batches), hooks)
        loop.run()
        return seen, anomaly

    clean, _ = run(False)
    instrumented, anomaly = run(True)
    assert anomaly.anomalies, "the seeded spike must actually fire"
    assert clean == instrumented


# -- correlated step tracing ---------------------------------------------------

def test_loop_emits_spans_and_journal_host_stamp(tmp_path, monkeypatch):
    monkeypatch.setenv(events.ENV_HOST_ID, "3")
    jrnl = RunJournal(tmp_path / "j.jsonl", generation=2)
    events.set_journal(jrnl)
    loop = TrainLoop(_fake_step, _state(), itertools.repeat(1.0),
                     [StopAtStepHook(last_step=6)], span_steps=2)
    loop.run()
    jrnl.close()
    recs = read_journal(tmp_path / "j.jsonl")
    spans = [r for r in recs if r["event"] == "span"]
    assert spans, "span cadence never fired"
    names = {r["name"] for r in spans}
    assert {"input_wait", "dispatch"} <= names
    for r in spans:
        # the correlated-tracing triple rides on every record
        assert (r["host"], r["gen"]) == (3, 2)
        assert isinstance(r["step"], int)
        if r["name"] in ("input_wait", "dispatch"):
            assert r["dur_ms"] >= 0


def test_fleet_trace_builds_per_host_tracks(tmp_path):
    jpath = tmp_path / "j.jsonl"
    with RunJournal(jpath, generation=0, host_id=0) as j:
        j.emit("span", name="dispatch", step=10, dur_ms=4.0)
    with RunJournal(jpath, generation=0, host_id=1) as j:
        j.emit("span", name="dispatch", step=10, dur_ms=5.0)
        j.emit("span", name="h2d", step=10, bytes=4096)
    with RunJournal(jpath, generation=0) as j:
        j.host_id = None  # supervisor-side record
        j.emit("generation_resize", kind="shrink", old_world=2,
               new_world=1, host=1)
    with RunJournal(jpath, generation=1, host_id=0) as j:
        j.emit("span", name="dispatch", step=20, dur_ms=4.5)

    sys.path.insert(0, str(REPO / "scripts"))
    try:
        from fleet_trace import build_fleet_trace, main
    finally:
        sys.path.pop(0)
    doc = build_fleet_trace(jpath)
    evs = doc["traceEvents"]
    names = {(e["pid"], e["args"]["name"]) for e in evs
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert {(1, "host 0"), (2, "host 1")} <= names
    complete = [e for e in evs if e["ph"] == "X"]
    assert {(e["pid"], e["tid"]) for e in complete} == {(1, 0), (2, 0),
                                                        (1, 1)}
    assert all(e["dur"] > 0 and e["ts"] >= 0 for e in complete)
    # h2d has no duration -> instant, not a zero-width bar
    h2d = [e for e in evs if e.get("name") == "h2d"]
    assert h2d and h2d[0]["ph"] == "i"
    resize = [e for e in evs if e.get("name") == "generation_resize"]
    assert resize and resize[0]["ph"] == "i"
    # the CLI writes the same document
    out = tmp_path / "trace.json"
    assert main([str(jpath), "-o", str(out)]) == 0
    assert json.loads(out.read_text())["traceEvents"]


# -- the fleet ladder under an elastic supervisor ------------------------------

# Jax-free stub child that behaves like an instrumented trainer: serves
# /metrics (a growing train_step_time_ms histogram at a per-host mean) and
# /healthz on metrics_port+rank, traps SIGTERM, sleeps per-generation.
FLEET_STUB = textwrap.dedent("""\
    import json, os, signal, sys, threading, time
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))
    args = dict(a.split("=", 1) for a in sys.argv[1:]
                if a.startswith("--") and "=" in a)
    gen = os.environ.get("DIST_MNIST_TPU_GENERATION", "0")
    host = os.environ.get("DIST_MNIST_TPU_HOST_ID", "?")
    rank = int(args["--process_id"])
    port = int(args["--metrics_port"]) + rank
    mean_ms = 50.0 if host == args.get("--stub_straggler") else 5.0
    state = {"count": 0}

    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path == "/healthz":
                body = json.dumps({"state": "training", "healthy": True,
                                   "generation": int(gen)})
            else:
                state["count"] += 10
                c = state["count"]
                body = (
                    "# TYPE train_step_time_ms histogram\\n"
                    f'train_step_time_ms_bucket{{le="+Inf"}} {c}\\n'
                    f"train_step_time_ms_sum {mean_ms * c}\\n"
                    f"train_step_time_ms_count {c}\\n"
                    f'process_info{{generation="{gen}",host_id="{host}",'
                    'role="train"} 1\\n'
                )
            data = body.encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", port), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    time.sleep(float(args.get(f"--stub_sleep_g{gen}", "0")))
    srv.shutdown()
    sys.exit(0)
""")


def _free_port_block(n):
    for _ in range(20):
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        base = probe.getsockname()[1]
        probe.close()
        if base + n >= 65535:
            continue
        held = []
        try:
            for i in range(n):
                s = socket.socket()
                s.bind(("127.0.0.1", base + i))
                held.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in held:
                s.close()
    pytest.skip("no contiguous port block available")


def test_elastic_fleet_ladder_straggler_and_shrink(tmp_path):
    """The acceptance ladder: an elastic supervisor over 3 stub children
    serving /metrics. The supervisor's FleetScraper merges them
    (fleet histograms + per-host gauges on the supervisor /metrics, JSON
    on /fleet), names the seeded straggler in the journal, and survives
    a mid-scrape shrink without wedging."""
    from dist_mnist_tpu.cli.launch import launch

    stub = tmp_path / "fleet_stub.py"
    stub.write_text(FLEET_STUB)
    jpath = tmp_path / "journal.jsonl"
    metrics_base = _free_port_block(3)
    sup_port = _free_port()

    result = {}

    def supervise():
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            result["rc"] = launch(
                3,
                [f"--metrics_port={metrics_base}", "--stub_straggler=2",
                 "--stub_sleep_g0=20", "--stub_sleep_g1=4"],
                platform="cpu", devices_per_process=1,
                child_command=[sys.executable, str(stub)],
                restart_backoff_s=0.05, elastic=True, journal=str(jpath),
                kill_spec=(1, 2.0), supervisor_port=sup_port,
                fleet_interval_s=0.1,
            )
        result["log"] = buf.getvalue()

    t = threading.Thread(target=supervise)
    t.start()
    try:
        sup = f"http://127.0.0.1:{sup_port}"

        def wait_for(pred, timeout=15.0, what=""):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                try:
                    code, body = _get(f"{sup}/fleet", timeout=2)
                    if code == 200 and pred(json.loads(body)):
                        return json.loads(body)
                except OSError:
                    pass
                time.sleep(0.1)
            pytest.fail(f"fleet never reached: {what}")

        # generation 0: all three hosts scraped and merged
        wait_for(lambda f: len(f["hosts"]) == 3
                 and all(h["reachable"] for h in f["hosts"]),
                 what="3 reachable hosts")
        # the seeded straggler (host 2, 10x the median) gets named
        fleet = wait_for(lambda f: f["straggler"]["detected"] >= 1,
                         what="straggler detection")
        assert fleet["straggler"]["host"] == 2
        assert fleet["straggler"]["ratio"] >= 2.0
        # supervisor /metrics serves the merged fleet view live
        code, body = _get(f"{sup}/metrics")
        assert code == 200
        assert "# TYPE fleet_train_step_time_ms histogram" in body
        assert "fleet_straggler_ratio" in body
        assert 'fleet_host_step_time_mean_ms{host="2"}' in body
        assert 'process_info{generation="0",role="supervisor"} 1' in body
        _, hists, _ = parse_prometheus(body)
        assert hists["fleet_train_step_time_ms"].count > 0
        # the kill at t=2s shrinks 3 -> 2 mid-scrape: the scraper must
        # re-point at the survivors (host 1 stays listed as "gone") and
        # keep serving, not wedge
        fleet = wait_for(
            lambda f: len(f["targets"]) == 2
            and sorted(h["host"] for h in f["hosts"]
                       if h["reachable"]) == [0, 2],
            timeout=25.0, what="post-shrink fleet of 2")
        gone = [h for h in fleet["hosts"] if h["host"] == 1]
        assert gone and gone[0]["state"] == "gone"
        code, body = _get(f"{sup}/metrics")
        assert 'process_info{generation="1",role="supervisor"} 1' in body
    finally:
        t.join(timeout=60)
    assert not t.is_alive(), "supervised run wedged"
    assert result["rc"] == 0, result["log"]

    recs = read_journal(jpath)
    straggler = [r for r in recs if r["event"] == "straggler_detected"]
    assert straggler and straggler[0]["host"] == 2
    resize = [r for r in recs if r["event"] == "generation_resize"]
    assert [(r["kind"], r["old_world"], r["new_world"], r["host"])
            for r in resize] == [("shrink", 3, 2, 1)]
