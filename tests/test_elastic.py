"""Elastic training: membership ledger, kill_host faults, the
shrink/grow supervisor ladder, cross-world-size checkpoint restore,
post-shrink trajectory determinism, the coordinator-port bind retry,
the `resizing` health state, and elastic goodput accounting.

The acceptance contract of elastic mode (ISSUE 8): a non-chief host loss
re-forms the cluster at the surviving world size (shrink, no backoff, no
full-world restart) with state resharded from the latest checkpoint; a
recovered host grows the mesh back at the next generation boundary; an
8->4->2->8 restore chain is bit-identical; and the whole story is
journaled (`generation_resize`) and summarizable
(`faults.goodput.elastic_summary`).
"""

import contextlib
import dataclasses
import errno
import io
import json
import socket
import sys
import textwrap

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from dist_mnist_tpu import optim
from dist_mnist_tpu.cluster.membership import ENV_HOST_ID, Membership
from dist_mnist_tpu.cluster.mesh import MeshSpec, activate, make_mesh
from dist_mnist_tpu.faults import Fault, FaultPlan
from dist_mnist_tpu.faults.goodput import elastic_summary
from dist_mnist_tpu.models import get_model
from dist_mnist_tpu.parallel.sharding import (
    DP_RULES,
    FSDP_RULES,
    reshard_state,
    shard_train_state,
)
from dist_mnist_tpu.train import create_train_state
from dist_mnist_tpu.train.state import state_memory_bytes


# ------------------------------------------------------------ membership --


def test_membership_basic_accounting():
    m = Membership(4)
    assert m.alive() == [0, 1, 2, 3]
    assert m.world_size == 4
    m.fail(2, now=100.0)
    assert m.alive() == [0, 1, 3]
    assert m.world_size == 3
    assert not m.is_alive(2) and m.is_alive(3)
    # ranks are positional in the SURVIVING list; host ids are stable
    assert m.rank_of(0) == 0 and m.rank_of(1) == 1 and m.rank_of(3) == 2
    assert m.rank_of(2) is None
    m.restore(2)
    assert m.alive() == [0, 1, 2, 3]


def test_membership_chief_and_range_guards():
    m = Membership(2)
    with pytest.raises(ValueError, match="chief"):
        m.fail(0, now=0.0)
    with pytest.raises(ValueError, match="out of range"):
        m.fail(2, now=0.0)
    with pytest.raises(ValueError):
        Membership(0)


def test_membership_recovery_deadlines():
    m = Membership(3)
    m.fail(1, now=10.0, recover_after_s=5.0)
    m.fail(2, now=10.0)  # permanent: no deadline
    assert m.due(14.9) == []
    assert m.due(15.0) == [1]
    assert m.next_recovery_in(12.0) == pytest.approx(3.0)
    assert m.next_recovery_in(20.0) == 0.0  # clamped, already due
    assert m.restore_due(15.0) == [1]
    assert m.alive() == [0, 1]
    # host 2 never auto-recovers
    assert m.due(1e9) == []
    assert m.next_recovery_in(0.0) is None


# ----------------------------------------------------------- fault plan --


def test_kill_host_plan_roundtrip_and_specs():
    plan = FaultPlan([Fault.kill_host(1, step=35, recover_after_s=2.5)])
    again = FaultPlan.from_spec(plan.to_json())
    f = again.faults[0]
    assert (f.kind, f.process, f.step, f.recover_after_s) == (
        "kill_host", 1, 35, 2.5)
    assert again.host_kill_spec() == (1, 2.5)
    # distinct from the launcher-timer kind on both query paths
    assert again.kill_spec() is None
    timer = FaultPlan([Fault.kill_process(1, after_s=5.0)])
    assert timer.kill_spec() == (1, 5.0)
    assert timer.host_kill_spec() is None


def test_kill_host_without_recovery_is_permanent():
    plan = FaultPlan([Fault.kill_host(2, step=10)])
    assert plan.host_kill_spec() == (2, None)


def test_kill_host_latches_without_killing_in_later_generations(monkeypatch):
    from dist_mnist_tpu.obs import events

    monkeypatch.setenv(events.ENV_GENERATION, "1")
    plan = FaultPlan([Fault.kill_host(0, step=3)])
    hook = plan.hook()
    hook.before_step(5)  # the victim IS this process, but gen != 0
    assert plan.faults[0].fired  # latched: replay can't re-lose the host
    # (still alive to assert — the point of the test)


def test_kill_host_ignores_non_victim_process():
    # this test process is jax process_index() == 0; victim is process 1
    plan = FaultPlan([Fault.kill_host(1, step=3)])
    hook = plan.hook()
    hook.before_step(5)
    assert not plan.faults[0].fired  # not ours: stays pending, no kill


# ---------------------------------------------------------- batch policy --


def test_apply_elastic_policy():
    from dist_mnist_tpu.configs import apply_elastic_policy, get_config

    cfg = get_config("mlp_mnist")
    # keep_global (default): nothing changes — surviving devices take
    # bigger slices of the SAME global batch
    out = apply_elastic_policy(cfg, 8, 4)
    assert out.batch_size == cfg.batch_size
    assert out.learning_rate == cfg.learning_rate
    # scale_lr: linear-scaling rule against the pre-shrink device count
    cfg2 = dataclasses.replace(cfg, elastic_batch_policy="scale_lr")
    out2 = apply_elastic_policy(cfg2, 8, 4)
    assert out2.learning_rate == pytest.approx(cfg.learning_rate * 0.5)
    # equal world or unknown baseline: identity
    assert apply_elastic_policy(cfg2, 8, 8) is cfg2
    assert apply_elastic_policy(cfg2, 0, 4) is cfg2
    bad = dataclasses.replace(cfg, elastic_batch_policy="yolo")
    with pytest.raises(ValueError, match="elastic_batch_policy"):
        apply_elastic_policy(bad, 8, 4)


# ------------------------------------------------------- port bind retry --


def test_reserve_port_retries_transient_bind_failures(monkeypatch):
    from dist_mnist_tpu.cli import launch as launch_mod

    real_socket = socket.socket
    fails = {"n": 3}

    class FlakySocket(real_socket):
        def bind(self, addr):
            if fails["n"] > 0:
                fails["n"] -= 1
                raise OSError(errno.EADDRINUSE, "Address already in use")
            return super().bind(addr)

    monkeypatch.setattr(launch_mod.socket, "socket", FlakySocket)
    port, probe, lock = launch_mod._reserve_port()
    try:
        assert port > 0
        assert fails["n"] == 0  # all three transient failures were retried
    finally:
        probe.close()
        lock.unlink()


def test_reserve_port_exhaustion_raises_os_error(monkeypatch):
    from dist_mnist_tpu.cli import launch as launch_mod

    real_socket = socket.socket

    class DeadSocket(real_socket):
        def bind(self, addr):
            raise OSError(errno.EADDRNOTAVAIL, "Cannot assign")

    monkeypatch.setattr(launch_mod.socket, "socket", DeadSocket)
    with pytest.raises(OSError, match="could not reserve a coordinator "
                                      "port after 32 attempts"):
        launch_mod._reserve_port()


# ------------------------------------------- supervisor: stub-child ladder --

# Jax-free elastic child: logs its generation/host/rank/world to a shared
# file, traps SIGTERM as the graceful-preemption handshake (exit 0), and
# sleeps per-generation (`--stub_sleep_g<N>`, default: exit immediately).
ELASTIC_STUB = textwrap.dedent("""\
    import os, signal, sys, time

    signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))
    args = dict(a.split("=", 1) for a in sys.argv[1:]
                if a.startswith("--") and "=" in a)
    gen = os.environ.get("DIST_MNIST_TPU_GENERATION", "0")
    host = os.environ.get("DIST_MNIST_TPU_HOST_ID", "?")
    with open(args["--stub_log"], "a") as f:
        f.write(f"gen={gen} host={host} rank={args['--process_id']} "
                f"world={args['--num_processes']}\\n")
    time.sleep(float(args.get(f"--stub_sleep_g{gen}", "0")))
    sys.exit(0)
""")


@pytest.fixture()
def elastic_stub(tmp_path):
    path = tmp_path / "elastic_stub.py"
    path.write_text(ELASTIC_STUB)
    return [sys.executable, str(path)]


def _supervise_elastic(n, elastic_stub, train_args, **kw):
    from dist_mnist_tpu.cli.launch import launch

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = launch(n, train_args, platform="cpu", devices_per_process=1,
                    child_command=elastic_stub, restart_backoff_s=0.05,
                    elastic=True, **kw)
    return rc, buf.getvalue()


def _stub_lines(log_path):
    return [dict(kv.split("=") for kv in line.split())
            for line in log_path.read_text().splitlines()]


def test_elastic_shrink_reforms_at_surviving_world(elastic_stub, tmp_path):
    """Kill host 1 of 3 -> the next generation launches 2 processes with
    stable host ids {0, 2} mapped to ranks {0, 1}, with no backoff sleep
    and no full-world restart."""
    stub_log = tmp_path / "stub.log"
    jpath = tmp_path / "journal.jsonl"
    rc, log = _supervise_elastic(
        3, elastic_stub,
        [f"--stub_log={stub_log}", "--stub_sleep_g0=5.0"],
        kill_spec=(1, 0.3), journal=str(jpath),
    )
    assert rc == 0, log
    assert "p1 exited rc=137 (killed by SIGKILL)" in log
    assert "generation resized 3 -> 2 (shrink: host 1 out)" in log
    assert "no backoff" in log
    assert "restarting cluster" not in log  # the restart path never ran

    gen1 = [l for l in _stub_lines(stub_log) if l["gen"] == "1"]
    assert sorted((l["host"], l["rank"], l["world"]) for l in gen1) == [
        ("0", "0", "2"), ("2", "1", "2")]

    records = [json.loads(l) for l in jpath.read_text().splitlines()]
    resize = [r for r in records if r["event"] == "generation_resize"]
    assert len(resize) == 1
    assert (resize[0]["kind"], resize[0]["old_world"],
            resize[0]["new_world"], resize[0]["host"]) == ("shrink", 3, 2, 1)
    gen1_start = [r for r in records if r["event"] == "generation_start"
                  and r["gen"] == 1]
    assert gen1_start and gen1_start[0]["world"] == 2
    assert gen1_start[0]["hosts"] == [0, 2]


def test_elastic_grow_back_after_recovery(elastic_stub, tmp_path):
    """A kill_host with a recovery deadline: shrink 2->1, then the grow
    timer drains the shrunken generation (SIGTERM -> exit 0) and the mesh
    grows back to 2 — rc 0, no restart budget consumed by the grow."""
    stub_log = tmp_path / "stub.log"
    jpath = tmp_path / "journal.jsonl"
    rc, log = _supervise_elastic(
        2, elastic_stub,
        [f"--stub_log={stub_log}", "--stub_sleep_g0=5.0",
         "--stub_sleep_g1=10.0"],
        kill_spec=(1, 0.3), host_kill=(1, 0.9), journal=str(jpath),
    )
    assert rc == 0, log
    assert "generation resized 2 -> 1 (shrink: host 1 out, recovery in 0.9s)" in log
    assert "host recovery due: draining generation 1" in log
    assert "generation resized 1 -> 2 (grow: host(s) [1] back)" in log

    records = [json.loads(l) for l in jpath.read_text().splitlines()]
    kinds = [(r["kind"], r["old_world"], r["new_world"])
             for r in records if r["event"] == "generation_resize"]
    assert kinds == [("shrink", 2, 1), ("grow", 1, 2)]
    assert any(r["event"] == "grow_drain" for r in records)
    # the final (grown) generation ran the full world again
    gen2 = [l for l in _stub_lines(stub_log) if l["gen"] == "2"]
    assert sorted(l["host"] for l in gen2) == ["0", "1"]
    stop = [r for r in records if r["event"] == "supervisor_stop"]
    assert stop and stop[0]["rc"] == 0
    # one shrink consumed one restart; the grow consumed none
    assert stop[0]["restarts"] == 1


def test_elastic_chief_death_still_fatal(elastic_stub, tmp_path):
    stub_log = tmp_path / "stub.log"
    rc, log = _supervise_elastic(
        2, elastic_stub,
        [f"--stub_log={stub_log}", "--stub_sleep_g0=5.0"],
        kill_spec=(0, 0.3),
    )
    assert rc == 137, log
    assert "chief died" in log
    assert "generation resized" not in log


def test_elastic_min_processes_floor_is_fatal(elastic_stub, tmp_path):
    stub_log = tmp_path / "stub.log"
    rc, log = _supervise_elastic(
        2, elastic_stub,
        [f"--stub_log={stub_log}", "--stub_sleep_g0=5.0"],
        kill_spec=(1, 0.3), min_processes=2,
    )
    assert rc == 137, log
    assert "below min_processes=2" in log
    assert "generation resized" not in log


# ----------------------------------------- cross-world-size resharding --


def _subset_mesh(k):
    """A data=k mesh over the first k of the 8 fake devices — the
    in-process analogue of a generation formed at world size k."""
    return make_mesh(MeshSpec(data=k), devices=jax.devices()[:k])


def _mlp_state(mesh, rules, seed=0, step=0):
    model = get_model("mlp", hidden_units=64)
    opt = optim.adam(1e-3)
    state = create_train_state(model, opt, jax.random.PRNGKey(seed),
                               jnp.zeros((1, 28, 28, 1), jnp.uint8))
    if step:
        state = dataclasses.replace(state, step=jnp.asarray(step, jnp.int32))
    return model, opt, shard_train_state(state, mesh, rules)


def _leaf_bytes(state):
    return [bytes(jax.device_get(x).tobytes())
            for x in jax.tree.leaves(state)]


def test_checkpoint_restore_across_world_sizes_8_4_2_8(tmp_path, mesh8):
    """The elastic acceptance chain: a checkpoint written at world 8
    restores onto 4, that onto 2, that back onto 8 — every hop through
    the resharding-by-construction restore path, values bit-identical at
    the end, and the per-device fsdp shard bytes growing exactly 2x per
    halving (the devices that remain absorb the lost shards)."""
    from dist_mnist_tpu.checkpoint import CheckpointManager

    def _hid_w_shard_bytes(s):
        # one device's share of the fsdp-sharded (784, 64) kernel
        return s.params["hid"]["w"].addressable_shards[0].data.nbytes

    model, opt, src = _mlp_state(mesh8, FSDP_RULES, seed=0, step=7)
    src_bytes = _leaf_bytes(src)
    bytes_at = {8: state_memory_bytes(src)}
    shard_at = {8: _hid_w_shard_bytes(src)}

    prev_dir, prev_world = None, 8
    state = src
    for world in (4, 2, 8):
        d = tmp_path / f"from_{prev_world}"
        mgr = CheckpointManager(d, async_save=False)
        try:
            assert mgr.save(state)
            mgr.wait()
            mesh = _subset_mesh(world) if world != 8 else mesh8
            with activate(mesh):
                # a DIFFERENT init as the target proves values came from
                # disk, not from the source pytree
                _, _, target = _mlp_state(mesh, FSDP_RULES, seed=9, step=0)
                state = mgr.restore(target)
        finally:
            mgr.close()
        assert state.step_int == 7
        if world != 8:
            bytes_at[world] = state_memory_bytes(state)
            shard_at[world] = _hid_w_shard_bytes(state)
        prev_dir, prev_world = d, world

    # full circle: bit-identical to the world-8 original, leaf for leaf
    assert _leaf_bytes(state) == src_bytes
    # halving the mesh EXACTLY doubles each device's share of a sharded
    # leaf (the survivors absorb the lost shards)...
    assert shard_at[4] == 2 * shard_at[8]
    assert shard_at[2] == 4 * shard_at[8]
    # ...while the per-device total grows by slightly less than 2x per hop
    # (tiny non-divisible leaves like the (10,) output bias stay replicated)
    assert (2 * bytes_at[8]["param_bytes"] > bytes_at[4]["param_bytes"]
            > bytes_at[8]["param_bytes"])
    assert bytes_at[4]["opt_state_bytes"] > bytes_at[8]["opt_state_bytes"]


def test_reshard_state_preserves_values_and_respecs(mesh8):
    """`parallel.reshard_state` re-derives specs from the TARGET mesh:
    same values bit for bit, shardings owned by the new mesh."""
    _, _, state = _mlp_state(mesh8, DP_RULES, seed=0, step=3)
    before = _leaf_bytes(state)
    mesh4 = _subset_mesh(4)
    out = reshard_state(state, mesh4, FSDP_RULES)
    assert _leaf_bytes(out) == before
    w = out.params["hid"]["w"]
    assert w.sharding.mesh.devices.size == 4
    assert w.sharding.spec == P("data", None)
    # and back up to the full mesh under dp
    out8 = reshard_state(out, mesh8, DP_RULES)
    assert _leaf_bytes(out8) == before
    assert out8.params["hid"]["w"].sharding.spec == P()


def test_post_shrink_trajectory_is_deterministic(tmp_path, mesh8,
                                                 small_mnist):
    """Restore a world-8 checkpoint onto a world-4 mesh and continue
    training twice: the two continuations must be bit-identical — the
    pinned form of the 'post-recovery trajectory deterministic'
    acceptance criterion."""
    from dist_mnist_tpu.checkpoint import CheckpointManager
    from dist_mnist_tpu.data.pipeline import ShardedBatcher
    from dist_mnist_tpu.train.step import make_train_step

    model, opt, src = _mlp_state(mesh8, DP_RULES, seed=0, step=0)
    mgr = CheckpointManager(tmp_path / "ckpt", async_save=False)
    try:
        assert mgr.save(dataclasses.replace(
            src, step=jnp.asarray(5, jnp.int32)))
        mgr.wait()
        mesh4 = _subset_mesh(4)
        with activate(mesh4):
            _, _, target = _mlp_state(mesh4, DP_RULES, seed=9)
            restored = mgr.restore(target)
    finally:
        mgr.close()
    assert restored.step_int == 5

    def continue_run(n=3):
        with activate(mesh4):
            step = make_train_step(model, opt, mesh4, donate=False)
            batches = ShardedBatcher(small_mnist, 32, mesh4, seed=0)
            it = iter(batches.at_step(restored.step_int))
            state, losses = restored, []
            for _ in range(n):
                state, out = step(state, next(it))
                losses.append(jax.device_get(out["loss"]).tobytes())
            if hasattr(it, "close"):
                it.close()
        return losses

    assert continue_run() == continue_run()


# ------------------------------------------------- health + observability --


def test_healthz_resizing_state_is_unhealthy():
    from dist_mnist_tpu.obs.exporter import HealthState, render_prometheus

    h = HealthState()
    h.set("training")
    assert h.healthy
    h.set("resizing", "shrink 2->1")
    assert not h.healthy  # 503: routers hold traffic across the boundary
    snap = h.snapshot()
    assert snap["state"] == "resizing" and snap["detail"] == "shrink 2->1"
    text = render_prometheus(None, h)
    assert 'process_state{state="resizing"} 1' in text
    assert 'process_state{state="training"} 0' in text
    assert "process_healthy 0" in text
    h.set("training")  # re-formation done: back to useful work
    assert h.healthy


def test_tail_run_renders_generation_resize():
    sys.path.insert(0, "scripts")
    try:
        from tail_run import format_record
    finally:
        sys.path.pop(0)
    rec = {"seq": 9, "ts": 0.0, "pid": 1, "gen": 2,
           "event": "generation_resize", "kind": "shrink",
           "old_world": 2, "new_world": 1, "host": 1,
           "recover_after_s": 0.9}
    out = format_record(rec)
    assert "shrink 2->1 host=1" in out
    assert "recover_after_s=0.9" in out
    # the fields in the head are not repeated in the extras tail
    assert "old_world=" not in out


# ------------------------------------------------------- elastic goodput --


def test_elastic_summary_from_synthetic_journal():
    recs = [
        {"event": "supervisor_start", "ts": 0.0},
        {"event": "generation_start", "gen": 0, "ts": 1.0},
        {"event": "first_step", "process": 0, "step": 1, "ts": 5.0},
        {"event": "first_step", "process": 1, "step": 1, "ts": 5.5},
        {"event": "generation_end", "gen": 0, "ts": 20.0},
        {"event": "generation_resize", "kind": "shrink", "old_world": 2,
         "new_world": 1, "host": 1, "ts": 20.1},
        {"event": "generation_start", "gen": 1, "ts": 21.0},
        {"event": "first_step", "process": 0, "step": 36, "ts": 25.0},
        {"event": "run_stop", "process": 0, "step": 60, "ts": 50.0,
         "goodput": {"productive_s": 30.0}},
        {"event": "generation_end", "gen": 1, "ts": 50.5},
        {"event": "supervisor_stop", "ts": 60.0},
        "not-a-dict",  # malformed lines must not break the ledger
    ]
    s = elastic_summary(recs)
    assert s["total_wall_s"] == pytest.approx(60.0)
    assert s["productive_s"] == pytest.approx(30.0)
    assert s["goodput_fraction"] == pytest.approx(0.5)
    # recovery window: failed gen's end (20.0) -> next CHIEF first_step
    # (25.0) — process 1's first_step never terminates a window
    assert s["recoveries"] == 1
    assert s["recovery_latency_s"] == pytest.approx(5.0)
    assert s["resize_s"] == pytest.approx(5.0)
    assert s["generations"] == 2
    assert s["resizes"] == [{"kind": "shrink", "old_world": 2,
                             "new_world": 1, "host": 1}]
    assert s["final_step"] == 60


def test_elastic_summary_normalizes_by_healthy_rate():
    """With gen-0 rate evidence (first_step -> cadence checkpoint_save),
    productive seconds are FULL-MESH-EQUIVALENT: frontier / healthy_rate.
    The degraded generation's own stepping speed must not change the
    number — raw busy-seconds would reward a slower (shrunken) world."""
    recs = [
        {"event": "supervisor_start", "ts": 0.0},
        {"event": "generation_start", "gen": 0, "ts": 1.0},
        {"event": "first_step", "process": 0, "gen": 0, "step": 1,
         "ts": 5.0},
        {"event": "checkpoint_save", "gen": 0, "step": 21, "ts": 7.0},
        {"event": "generation_end", "gen": 0, "ts": 20.0},
        {"event": "generation_start", "gen": 1, "ts": 21.0},
        {"event": "first_step", "process": 0, "gen": 1, "step": 22,
         "ts": 25.0},
        {"event": "run_stop", "process": 0, "step": 60, "ts": 50.0,
         "goodput": {"productive_s": 30.0}},
        {"event": "supervisor_stop", "ts": 60.0},
    ]
    s = elastic_summary(recs)
    # rate = (21 - 1) steps / (7.0 - 5.0) s = 10 steps/s
    assert s["healthy_steps_per_s"] == pytest.approx(10.0)
    # 60 frontier steps at full-mesh rate = 6.0 equivalent seconds,
    # regardless of the 30 busy-seconds gen 1 actually spent
    assert s["productive_s"] == pytest.approx(6.0)
    assert s["busy_s"] == pytest.approx(30.0)
    assert s["goodput_fraction"] == pytest.approx(0.1)


def test_elastic_summary_empty_and_no_resize():
    s = elastic_summary([])
    assert s["goodput_fraction"] == 0.0 and s["recoveries"] == 0
    # a clean single-generation run: fraction is productive/wall, no windows
    s2 = elastic_summary([
        {"event": "supervisor_start", "ts": 0.0},
        {"event": "generation_start", "gen": 0, "ts": 1.0},
        {"event": "run_stop", "process": 0, "step": 10, "ts": 9.0,
         "goodput": {"productive_s": 8.0}},
        {"event": "supervisor_stop", "ts": 10.0},
    ])
    assert s2["goodput_fraction"] == pytest.approx(0.8)
    assert s2["recoveries"] == 0 and s2["resizes"] == []


def test_goodput_clock_resize_bucket():
    from dist_mnist_tpu.faults.goodput import GoodputClock

    clock = GoodputClock()
    clock.add_resize(1.5)
    clock.add_resize(0.5)
    snap = clock.snapshot()
    assert snap["resize_s"] == pytest.approx(2.0)
