"""Warm-start engine tests (compilecache/): executable-store round trips,
cache-key invalidation, corrupt-entry tolerance, startup/goodput compile
attribution, supervisor cache-dir injection, the serve disk tier, and
bench's probe-verdict cache."""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import jax
import numpy as np
import pytest

from dist_mnist_tpu import optim
from dist_mnist_tpu.cluster.mesh import activate
from dist_mnist_tpu.compilecache import (
    ExecutableStore,
    StartupClock,
    StartupHook,
    cache_key,
)
from dist_mnist_tpu.compilecache.store import ENTRY_SUFFIX
from dist_mnist_tpu.data.pipeline import shard_batch
from dist_mnist_tpu.models import get_model
from dist_mnist_tpu.parallel.sharding import shard_train_state
from dist_mnist_tpu.train import create_train_state, make_eval_step
from dist_mnist_tpu.train.step import make_train_step

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench  # noqa: E402


# -- cache_key ----------------------------------------------------------------

BASE_FIELDS = {
    "kind": "train", "model": "mlp", "batch_size": 64,
    "mesh": (("data", 8),), "sharding": "dp", "dtype": "float32",
    "donate": True, "scan_chunk": 0,
}


def test_cache_key_stable():
    assert cache_key(dict(BASE_FIELDS)) == cache_key(dict(BASE_FIELDS))
    assert len(cache_key(BASE_FIELDS)) == 32


@pytest.mark.parametrize("change", [
    {"mesh": (("data", 4), ("model", 2))},   # mesh shape
    {"sharding": "fsdp"},                    # sharding strategy
    {"dtype": "bfloat16"},                   # dtype
    {"donate": False},                       # donation
    {"scan_chunk": 100},                     # scan chunk
    {"overlap": True},                       # comm/compute overlap schedule
    {"overlap_bucket_mb": 8.0},              # overlap bucket granularity
    {"overlap_chunk": "ring"},               # overlap gather decomposition
    {"jax_version": "0.0.0-stale"},          # runtime version (implicit field)
    {"backend": "tpu"},                      # backend (implicit field)
])
def test_cache_key_invalidates(change):
    assert cache_key({**BASE_FIELDS, **change}) != cache_key(BASE_FIELDS)


@pytest.mark.parametrize("override", [
    {"overlap": True},
    {"overlap_bucket_mb": 0.5},
    {"overlap_chunk": "ring"},
])
def test_compile_cache_key_fields_cover_overlap_knobs(mesh8, override):
    """The driver's key-field builder must fold every overlap knob in, so
    toggling --overlap (or its sub-knobs) forces a store MISS instead of
    loading a stale serial executable — the schedules lower to different
    HLO even though they are value-identical."""
    import dataclasses

    from dist_mnist_tpu.cli.train import compile_cache_key_fields
    from dist_mnist_tpu.configs import get_config

    cfg = get_config("lenet5_fashion")
    base = compile_cache_key_fields(cfg, mesh8)
    changed = compile_cache_key_fields(
        dataclasses.replace(cfg, **override), mesh8)
    assert cache_key({"kind": "train", **base}) != \
        cache_key({"kind": "train", **changed})
    # and the store behaves accordingly: a key derived from the overlapped
    # config cannot hit an entry saved under the serial config's key
    assert base != changed


# -- ExecutableStore round trip ----------------------------------------------

def _mlp_fixture(mesh, small_mnist, batch=64):
    model = get_model("mlp")
    opt = optim.adam(1e-3)
    state = create_train_state(model, opt, jax.random.PRNGKey(0),
                               small_mnist.train_images[:1])
    state = shard_train_state(state, mesh)
    batch_np = {"image": small_mnist.train_images[:batch],
                "label": small_mnist.train_labels[:batch].astype(np.int32)}
    return model, opt, state, shard_batch(batch_np, mesh)


def _losses(step, state, batch, n=3):
    out_losses = []
    for _ in range(n):
        state, out = step(state, batch)
        out_losses.append(np.asarray(jax.device_get(out["loss"])).tobytes())
    return out_losses


def test_store_round_trip_bit_identical(mesh8, small_mnist, tmp_path):
    """save -> load in a fresh wrapper (the new-process path) -> the loaded
    executable produces a bit-identical trajectory to the compiling one."""
    model, opt, state, batch = _mlp_fixture(mesh8, small_mnist)
    key = cache_key(BASE_FIELDS)
    with activate(mesh8):
        store1 = ExecutableStore(tmp_path / "exe")
        step1 = make_train_step(model, opt, mesh8, donate=False,
                                store=store1, cache_key=key)
        cold = _losses(step1, state, batch)
        assert step1.cache_stats["tier"] == "fresh"
        assert step1.cache_stats["compile_ms"] > 0
        assert store1.stats() == {**store1.stats(), "misses": 1, "entries": 1}
        # drained once by the caller; second drain must be zero
        assert step1.consume_compile_s() > 0
        assert step1.consume_compile_s() == 0.0

        # fresh store object + fresh wrapper over the same directory — the
        # same isolation a restarted process has
        store2 = ExecutableStore(tmp_path / "exe")
        step2 = make_train_step(model, opt, mesh8, donate=False,
                                store=store2, cache_key=key)
        warm = _losses(step2, state, batch)
        assert step2.cache_stats["tier"] == "disk"
        s2 = store2.stats()
        assert (s2["hits"], s2["misses"], s2["corrupt"]) == (1, 0, 0)
        assert s2["compile_ms_saved"] > 0
    assert warm == cold


def test_eval_step_round_trips_store(mesh8, small_mnist, tmp_path):
    model, opt, state, batch = _mlp_fixture(mesh8, small_mnist)
    key = cache_key({**BASE_FIELDS, "kind": "eval"})
    with activate(mesh8):
        store = ExecutableStore(tmp_path / "exe")
        ev1 = make_eval_step(model, mesh8, store=store, cache_key=key)
        r1 = jax.device_get(ev1(state, batch))
        assert store.stats()["misses"] == 1

        store2 = ExecutableStore(tmp_path / "exe")
        ev2 = make_eval_step(model, mesh8, store=store2, cache_key=key)
        r2 = jax.device_get(ev2(state, batch))
        assert store2.stats()["hits"] == 1
    assert all(np.asarray(a).tobytes() == np.asarray(b).tobytes()
               for a, b in zip(r1, r2))


def test_corrupt_entry_is_quarantined_and_overwritten(mesh8, small_mnist,
                                                      tmp_path):
    """Garbage entry -> miss + unlink (never a crash); the subsequent save
    overwrites it and the NEXT load hits."""
    model, opt, state, batch = _mlp_fixture(mesh8, small_mnist)
    key = cache_key(BASE_FIELDS)
    store = ExecutableStore(tmp_path / "exe")
    entry = tmp_path / "exe" / f"{key}{ENTRY_SUFFIX}"
    entry.write_bytes(b"not a pickled executable")
    assert store.load(key) is None
    assert not entry.exists()  # quarantined
    s = store.stats()
    assert (s["corrupt"], s["misses"], s["hits"]) == (1, 1, 0)

    with activate(mesh8):
        step = make_train_step(model, opt, mesh8, donate=False,
                               store=store, cache_key=key)
        _losses(step, state, batch, n=1)
    assert step.cache_stats["tier"] == "fresh"
    assert entry.exists()  # recompile overwrote the quarantined slot
    assert ExecutableStore(tmp_path / "exe").load(key) is not None


def test_truncated_entry_falls_back(mesh8, small_mnist, tmp_path):
    model, opt, state, batch = _mlp_fixture(mesh8, small_mnist)
    key = cache_key(BASE_FIELDS)
    store = ExecutableStore(tmp_path / "exe")
    with activate(mesh8):
        step = make_train_step(model, opt, mesh8, donate=False,
                               store=store, cache_key=key)
        _losses(step, state, batch, n=1)
    entry = tmp_path / "exe" / f"{key}{ENTRY_SUFFIX}"
    blob = entry.read_bytes()
    entry.write_bytes(blob[: len(blob) // 2])  # torn write / partial copy
    store2 = ExecutableStore(tmp_path / "exe")
    assert store2.load(key) is None
    assert store2.stats()["corrupt"] == 1
    assert not entry.exists()


def test_save_is_failure_soft(tmp_path):
    store = ExecutableStore(tmp_path / "exe")
    # an unserializable object must log-and-return-0, never raise: a full
    # disk or an odd executable must not kill a run that was going to
    # compile anyway
    assert store.save("somekey", object()) == 0
    assert store.stats()["bytes_written"] == 0


# -- startup clock / goodput attribution -------------------------------------

def test_startup_clock_buckets_and_residual():
    clock = StartupClock()
    clock.note("import", 1.0)
    with clock.phase("init"):
        pass
    clock.note("compile", 0.5)
    assert clock.snapshot()["compile_ms"] == 500.0
    assert "time_to_first_step_ms" not in clock.snapshot()  # not frozen yet
    clock.first_step_done()
    assert clock.time_to_first_step_s is not None
    # pin the frozen headline so the residual arithmetic is deterministic
    # (first_step_done is first-call-wins, so a direct set is the test's
    # stand-in for "the first step landed 3s after t0")
    clock.time_to_first_step_s = 3.0
    init_s = clock.buckets["init"]
    first = clock.snapshot()
    assert first["time_to_first_step_ms"] == 3000.0
    # residual: ttfs minus everything attributed, floored at zero
    assert first["first_step_ms"] == pytest.approx(
        max(0.0, (3.0 - 1.5 - init_s) * 1e3))
    # compile noted AFTER the freeze shrinks the residual, not the headline
    clock.note("compile", 10.0)
    again = clock.snapshot()
    assert again["time_to_first_step_ms"] == 3000.0
    assert again["first_step_ms"] == 0.0
    # freeze is first-call-wins
    clock.first_step_done()
    assert clock.time_to_first_step_s == 3.0


def test_goodput_clock_compile_bucket():
    from dist_mnist_tpu.faults.goodput import GoodputClock

    g = GoodputClock()
    g.add_compile(1.25)
    g.add_compile(0.25)
    assert g.snapshot()["compile_s"] == 1.5


class _CaptureWriter:
    def __init__(self):
        self.scalar_calls: list = []

    def scalars(self, d, step):
        self.scalar_calls.append((dict(d), step))

    def flush(self):
        pass


def test_loop_drains_compile_into_goodput_and_startup_hook_publishes(
        mesh8, small_mnist, tmp_path):
    """End to end through TrainLoop: the wrapper's compile time lands in
    the goodput `compile` bucket BEFORE after_step hooks fire, and the
    StartupHook publishes `startup/*` + `compile_cache/*` once."""
    from dist_mnist_tpu import hooks as hooks_lib
    from dist_mnist_tpu.train import TrainLoop

    model, opt, state, batch = _mlp_fixture(mesh8, small_mnist)
    store = ExecutableStore(tmp_path / "exe")
    writer = _CaptureWriter()
    clock = StartupClock()
    hook = StartupHook(writer, clock, store=store)
    with activate(mesh8):
        step = make_train_step(model, opt, mesh8, donate=False,
                               store=store, cache_key=cache_key(BASE_FIELDS))
        loop = TrainLoop(step, state, iter([batch] * 4),
                         [hooks_lib.StopAtStepHook(last_step=3), hook])
        loop.run()
    assert loop.goodput.compile_s > 0
    assert loop.goodput.snapshot()["compile_s"] == loop.goodput.compile_s
    # published exactly once, at the first step
    assert len(writer.scalar_calls) == 1
    tags, at_step = writer.scalar_calls[0]
    assert at_step == 1
    assert tags["startup/compile_ms"] == pytest.approx(
        loop.goodput.compile_s * 1e3)
    assert tags["startup/time_to_first_step_ms"] > 0
    assert tags["compile_cache/misses"] == 1.0
    assert tags["compile_cache/entries"] == 1.0
    assert hook.last["cache_misses"] == 1


# -- supervisor cache-dir injection (jax-free stub children) ------------------

ARGV_STUB = textwrap.dedent("""\
    import os, sys, time

    args = dict(a.split("=", 1) for a in sys.argv[1:]
                if a.startswith("--") and "=" in a)
    with open(args["--argv_log"], "a") as fh:
        fh.write(" ".join(sys.argv[1:]) + "\\n")
    if int(args.get("--process_id", "0")) == 0:
        time.sleep(0.5)
        sys.exit(0)
    marker = args.get("--marker")
    if marker and not os.path.exists(marker):
        open(marker, "w").close()
        sys.exit(3)
    sys.exit(0)
""")


@pytest.fixture()
def argv_stub(tmp_path):
    path = tmp_path / "argv_stub.py"
    path.write_text(ARGV_STUB)
    return [sys.executable, str(path)]


def _cache_dirs_per_line(argv_log: Path) -> list[list[str]]:
    return [[a.split("=", 1)[1] for a in line.split()
             if a.startswith("--compile_cache_dir=")]
            for line in argv_log.read_text().splitlines()]


def test_supervisor_injects_shared_cache_dir_across_generations(
        argv_stub, tmp_path):
    """Every generation of a supervised cluster gets the SAME injected
    --compile_cache_dir, and the supervisor-owned dir is removed when the
    job ends."""
    from dist_mnist_tpu.cli.launch import launch

    argv_log = tmp_path / "argv.log"
    rc = launch(
        2,
        [f"--argv_log={argv_log}", f"--marker={tmp_path / 'marker'}"],
        child_command=argv_stub, max_restarts=2, restart_backoff_s=0.05,
    )
    assert rc == 0
    per_line = _cache_dirs_per_line(argv_log)
    assert len(per_line) == 4  # 2 processes x 2 generations
    assert all(len(dirs) == 1 for dirs in per_line)  # injected exactly once
    dirs = {d for line in per_line for d in line}
    assert len(dirs) == 1  # one shared dir across ALL generations
    injected = dirs.pop()
    assert "dist_mnist_warmstart_" in injected
    assert not Path(injected).exists()  # supervisor cleaned its own dir


def test_supervisor_respects_explicit_cache_dir(argv_stub, tmp_path):
    from dist_mnist_tpu.cli.launch import launch

    argv_log = tmp_path / "argv.log"
    explicit = tmp_path / "cc"
    explicit.mkdir()
    rc = launch(
        2,
        [f"--argv_log={argv_log}", f"--compile_cache_dir={explicit}"],
        child_command=argv_stub, max_restarts=1, restart_backoff_s=0.05,
    )
    assert rc == 0
    per_line = _cache_dirs_per_line(argv_log)
    assert per_line and all(line == [str(explicit)] for line in per_line)
    assert explicit.exists()  # an explicit dir is never deleted


def test_unsupervised_launch_injects_nothing(argv_stub, tmp_path):
    from dist_mnist_tpu.cli.launch import launch

    argv_log = tmp_path / "argv.log"
    rc = launch(2, [f"--argv_log={argv_log}"], child_command=argv_stub,
                max_restarts=0)
    assert rc == 0
    assert all(not dirs for dirs in _cache_dirs_per_line(argv_log))


# -- serve disk tier ----------------------------------------------------------

def test_serve_cache_disk_tier_and_per_key_stats(mesh8, tmp_path):
    from dist_mnist_tpu.serve import InferenceEngine, load_for_serving

    bundle = load_for_serving("mlp_mnist", mesh8)
    store = ExecutableStore(tmp_path / "exe")

    def make_engine(st):
        return InferenceEngine(
            bundle.model, bundle.params, bundle.model_state, mesh8,
            model_name="mlp-cc", image_shape=bundle.image_shape,
            rules=bundle.rules, max_bucket=8, store=st,
        )

    e1 = make_engine(store)
    e1.prewarm([8])
    s1 = e1.cache.stats()
    assert (s1["misses"], s1["hits_disk"], s1["hits_memory"]) == (1, 0, 0)
    (pk1,) = s1["per_key"].values()
    assert pk1["tier"] == "fresh" and pk1["compile_ms"] > 0

    # memory tier on a repeat hit
    e1.compiled_for(8)
    s1b = e1.cache.stats()
    assert (s1b["hits"], s1b["hits_memory"]) == (1, 1)

    rng = np.random.default_rng(0)
    img = rng.integers(0, 256, size=(3, *bundle.image_shape), dtype=np.uint8)
    ref = e1.predict(img)

    # a "restarted server": fresh engine, fresh store object, same dir
    e2 = make_engine(ExecutableStore(tmp_path / "exe"))
    e2.prewarm([8])
    s2 = e2.cache.stats()
    assert (s2["misses"], s2["hits_disk"]) == (0, 1)
    (pk2,) = s2["per_key"].values()
    assert pk2["tier"] == "disk" and pk2["load_ms"] > 0
    # existing stat keys are preserved for the metrics/server plumbing
    for k in ("hits", "misses", "entries", "compile_secs", "execute_secs",
              "execute_count"):
        assert k in s2
    # the deserialized executable computes the same program
    np.testing.assert_array_equal(e2.predict(img), ref)


def test_serve_cache_without_store_unchanged(mesh8):
    """No store wired -> exact legacy behavior and stat values."""
    from dist_mnist_tpu.serve.engine import CompiledModelCache

    cache = CompiledModelCache()
    built = []
    cache.get("k", lambda: built.append(1) or "exe")
    assert cache.get("k", lambda: built.append(1) or "exe2") == "exe"
    assert len(built) == 1
    s = cache.stats()
    assert (s["hits"], s["misses"], s["entries"]) == (1, 1, 1)
    assert (s["hits_memory"], s["hits_disk"]) == (1, 0)


# -- bench probe-verdict cache ------------------------------------------------

@pytest.fixture()
def probe_cache(tmp_path, monkeypatch):
    path = tmp_path / "probe_cache.json"
    monkeypatch.setenv("BENCH_PROBE_CACHE", str(path))
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    return path


def _forbid_subprocess(monkeypatch):
    def boom(*a, **k):
        raise AssertionError("probe subprocess ran despite cached verdict")
    monkeypatch.setattr(bench.subprocess, "run", boom)


def test_probe_cache_hit_up_verdict(probe_cache, monkeypatch):
    probe_cache.write_text(json.dumps({"cpu": []}))
    _forbid_subprocess(monkeypatch)
    assert bench._probe(3, 150) == []


def test_probe_cache_hit_down_verdict(probe_cache, monkeypatch):
    probe_cache.write_text(json.dumps({"cpu": ["probe timed out after 5s"]}))
    _forbid_subprocess(monkeypatch)
    errs = bench._probe(3, 150)
    assert len(errs) == 1
    assert "probe timed out after 5s" in errs[0]
    assert "cached verdict" in errs[0]  # labeled as replayed, not fresh


def test_probe_cache_keyed_by_platform(probe_cache, monkeypatch):
    # a verdict for the default (tpu) probe must not satisfy the cpu probe
    probe_cache.write_text(json.dumps({"default": []}))
    calls = []

    def fake_run(*a, **k):
        calls.append(a)
        raise subprocess.TimeoutExpired(cmd="probe", timeout=1)

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setenv("BENCH_PROBE_TIMEOUT_S", "1")
    errs = bench._probe(1, 150)
    assert calls and "timed out" in errs[0]
    # ... and the real probe's verdict was recorded under the cpu key
    verdicts = json.loads(probe_cache.read_text())
    assert verdicts["default"] == []
    assert "timed out" in verdicts["cpu"][0]
    # second probe replays the cached failure without a subprocess
    _forbid_subprocess(monkeypatch)
    assert "cached verdict" in bench._probe(1, 150)[-1]


def test_probe_cache_unset_probes_normally(monkeypatch, tmp_path):
    monkeypatch.delenv("BENCH_PROBE_CACHE", raising=False)
    calls = []

    def fake_run(*a, **k):
        calls.append(a)

        class Out:
            returncode = 0
            stdout = "DEVCOUNT 8"
            stderr = ""
        return Out()

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    assert bench._probe(1, 150) == []
    assert len(calls) == 1
