"""Fault-injection and resilience subsystem (dist_mnist_tpu/faults/).

The reference validated preemption recovery by hand-raising AbortedError
into _RecoverableSession in unit tests (SURVEY.md §4) and tested nothing
at the launch or checkpoint layers. Here every recovery path is reachable
on purpose through a seeded `FaultPlan`, and the headline invariant is
BIT-IDENTICAL trajectories: a recovered run must produce exactly the
per-step losses of the fault-free run (restore + re-seek + replay, never
skip), so resilience cannot silently perturb the math.

Fast tests (tier-1): classifier pins, plan serialization, goodput clock,
the in-process preemption handshake, recovery trajectory identity, the
serve-engine fault, and the supervisor restart ladder driven by a jax-free
stub child (launch/backoff/exit-code semantics in ~a second). Slow tests:
SIGTERM against a real `cli.train` process and the 2-process kill-injection
integration (each child pays the jax import + compile).
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import re
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from dist_mnist_tpu.faults import (
    Fault,
    FaultPlan,
    FaultyBatches,
    GoodputClock,
    PreemptionNotice,
    install_preemption_handlers,
)
from dist_mnist_tpu.train.loop import PreemptionError, TrainLoop, _is_preemption


# -- satellite: _is_preemption classification pins ---------------------------

def test_preemption_error_classifies():
    assert _is_preemption(PreemptionError("injected"))


def test_value_error_mentioning_preempt_is_NOT_preemption():
    # the exact bug the tightened classifier defends: an application
    # ValueError whose MESSAGE contains "preempt" must not buy a silent
    # checkpoint restore (type is checked before status substrings)
    assert not _is_preemption(ValueError("user config: preempt_margin=3"))
    assert not _is_preemption(RuntimeError("UNAVAILABLE: socket closed"))


def test_xla_runtime_error_status_substrings():
    XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})
    assert _is_preemption(XlaRuntimeError("UNAVAILABLE: socket closed"))
    assert _is_preemption(XlaRuntimeError("ABORTED: coordination service"))
    assert _is_preemption(XlaRuntimeError("slice preempted by scheduler"))
    # right type, unrelated status: not a preemption
    assert not _is_preemption(XlaRuntimeError("INVALID_ARGUMENT: shape"))


# -- FaultPlan: construction + (de)serialization -----------------------------

def test_plan_json_roundtrip(tmp_path):
    plan = FaultPlan(
        [
            Fault.preempt(8),
            Fault.corrupt_checkpoint(6, mode="delete"),
            Fault.stall_input(2, 0.25),
            Fault.kill_process(1, after_s=3.0),
            Fault.serve_error(request=4),
        ],
        seed=7,
    )
    back = FaultPlan.from_json(plan.to_json())
    assert back.seed == 7
    assert [f.to_dict() for f in back.faults] == [f.to_dict() for f in plan.faults]
    assert back.kill_spec() == (1, 3.0)

    # --fault_plan accepts inline JSON or a file path
    inline = FaultPlan.from_spec(plan.to_json())
    assert inline.kill_spec() == (1, 3.0)
    p = tmp_path / "plan.json"
    p.write_text(plan.to_json())
    assert FaultPlan.from_spec(str(p)).kill_spec() == (1, 3.0)


def test_unknown_fault_kind_rejected():
    with pytest.raises(ValueError, match="unknown fault kind"):
        Fault("meteor_strike")


def test_fired_latch_consumes_fault():
    plan = FaultPlan([Fault.preempt(3)])
    (f,) = plan.pending("preempt")
    f.fired = True
    assert plan.pending("preempt") == []
    assert plan.fired() == [f]


def test_wiring_helpers_are_noops_without_matching_faults():
    plan = FaultPlan([Fault.preempt(3)])
    sentinel = object()
    assert plan.wrap_batches(sentinel) is sentinel
    assert plan.wrap_checkpoint_manager(sentinel) is sentinel
    assert plan.wrap_engine(sentinel) is sentinel
    assert plan.wrap_checkpoint_manager(None) is None


# -- GoodputClock ------------------------------------------------------------

def test_goodput_clock_buckets_and_events():
    g = GoodputClock()
    g.start()
    g.add_productive(2.0)
    g.add_stall(0.5)
    g.begin_recovery(failed_at_step=10, restored_step=6, restore_s=1.0)
    assert g.in_replay
    g.note_replay(0.3, 2, at_step=8)
    assert g.in_replay
    g.note_replay(0.3, 2, at_step=10)  # frontier regained -> event closes
    assert not g.in_replay
    g.close()
    snap = g.snapshot()
    assert snap["recoveries"] == 1
    assert snap["replayed_steps"] == 4
    assert snap["restore_s"] == pytest.approx(1.0)
    assert snap["replay_s"] == pytest.approx(0.6)
    assert snap["recovery_latency_ms"] == pytest.approx(1600.0)
    (ev,) = g.events
    assert ev["complete"] and ev["failed_at_step"] == 10 and ev["restored_step"] == 6


def test_goodput_close_freezes_incomplete_recovery():
    g = GoodputClock()
    g.start()
    g.begin_recovery(failed_at_step=5, restored_step=2, restore_s=0.1)
    g.close()
    (ev,) = g.events
    assert not ev["complete"]  # run ended mid-replay; reported honestly
    assert g.snapshot()["goodput_fraction"] >= 0.0


# -- preemption handshake ----------------------------------------------------

def test_signal_sets_notice_and_second_signal_escalates():
    notice = PreemptionNotice()
    escalated = []
    prev = signal.signal(signal.SIGUSR1, lambda s, f: escalated.append(s))
    try:
        uninstall = install_preemption_handlers(notice, signals=(signal.SIGUSR1,))
        signal.raise_signal(signal.SIGUSR1)
        assert notice.requested()
        assert notice.reason == "signal SIGUSR1"
        assert not escalated
        # second signal: previous disposition restored and re-raised
        signal.raise_signal(signal.SIGUSR1)
        assert escalated == [signal.SIGUSR1]
        uninstall()  # idempotent even after the handler un-installed itself
    finally:
        signal.signal(signal.SIGUSR1, prev)


def test_uninstall_restores_previous_handler():
    notice = PreemptionNotice()
    prev = signal.getsignal(signal.SIGUSR2)
    uninstall = install_preemption_handlers(notice, signals=(signal.SIGUSR2,))
    assert signal.getsignal(signal.SIGUSR2) is not prev
    uninstall()
    assert signal.getsignal(signal.SIGUSR2) is prev


# -- in-process training harness ---------------------------------------------

class _Trajectory:
    """Per-step loss recorder; device scalars fetched once at `end`."""

    def __init__(self):
        self.loss = {}

    def begin(self, loop):
        pass

    def before_step(self, step):
        pass

    def after_step(self, step, state, outputs):
        self.loss[step] = outputs["loss"]

    def end(self, state):
        import jax

        self.loss = {k: np.asarray(jax.device_get(v))
                     for k, v in self.loss.items()}


def _run_training(mesh, dataset, *, n_steps=12, ckpt_dir=None, ckpt_every=3,
                  plan=None, preemption=None, extra_hooks=(),
                  max_restore_fallbacks=1):
    """One short mlp training run; returns (trajectory dict, loop)."""
    import jax

    from dist_mnist_tpu import hooks as hooks_lib, optim
    from dist_mnist_tpu.checkpoint import CheckpointManager
    from dist_mnist_tpu.cluster.mesh import activate
    from dist_mnist_tpu.data import ShardedBatcher
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.parallel.sharding import shard_train_state
    from dist_mnist_tpu.train import create_train_state
    from dist_mnist_tpu.train.step import make_train_step

    with activate(mesh):
        model = get_model("mlp", hidden_units=16)
        optimizer = optim.adam(1e-3)
        state = create_train_state(
            model, optimizer, jax.random.PRNGKey(0), dataset.train_images[:1])
        state = shard_train_state(state, mesh)
        step = make_train_step(model, optimizer, mesh, donate=False)

        traj = _Trajectory()
        hooks = [hooks_lib.StopAtStepHook(last_step=n_steps), traj,
                 *extra_hooks]
        manager = None
        if ckpt_dir is not None:
            manager = CheckpointManager(
                ckpt_dir, async_save=False,
                max_restore_fallbacks=max_restore_fallbacks)
            if plan is not None:
                manager = plan.wrap_checkpoint_manager(manager)
            hooks.append(hooks_lib.CheckpointHook(manager, every_steps=ckpt_every))
        batches = ShardedBatcher(dataset, 64, mesh, seed=0)
        if plan is not None:
            hooks.append(plan.hook())
            batches = plan.wrap_batches(batches)
        loop = TrainLoop(step, state, batches, hooks,
                         checkpoint_manager=manager, max_recoveries=3,
                         preemption=preemption)
        loop.run()
        if manager is not None:
            manager.close()
    return traj.loss, loop


def _assert_identical(clean: dict, faulted: dict):
    assert set(clean) == set(faulted)
    for s in clean:
        assert clean[s].tobytes() == faulted[s].tobytes(), (
            f"loss diverged at step {s}: {clean[s]!r} != {faulted[s]!r}")


def test_notice_stops_loop_at_boundary_with_checkpoint(mesh8, small_mnist,
                                                       tmp_path):
    """The in-process handshake: notify mid-run -> the loop checkpoints at
    the next step boundary, records `preempted_at`, and stops cleanly."""
    notice = PreemptionNotice()

    class NotifyAt:
        def begin(self, loop):
            pass

        def before_step(self, step):
            pass

        def after_step(self, step, state, outputs):
            if step == 4:
                notice.notify("test preemption")

        def end(self, state):
            pass

    traj, loop = _run_training(
        mesh8, small_mnist, n_steps=12, ckpt_dir=tmp_path / "ckpt",
        preemption=notice, extra_hooks=(NotifyAt(),))
    assert loop.preempted_at == 4
    assert loop.stop.reason == "preempted@step=4"
    assert max(traj) == 4  # no step ran past the boundary
    from dist_mnist_tpu.checkpoint import CheckpointManager

    mgr = CheckpointManager(tmp_path / "ckpt")
    assert mgr.latest_step() == 4  # durable before the stop
    mgr.close()


def test_preempt_recovery_trajectory_bit_identical(mesh8, small_mnist,
                                                   tmp_path):
    """Injected preemption at step 8 -> restore latest (6), replay 2 steps;
    the recovered trajectory is bit-identical to the fault-free run."""
    clean, _ = _run_training(mesh8, small_mnist, n_steps=12)

    plan = FaultPlan([Fault.preempt(8)])
    faulted, loop = _run_training(
        mesh8, small_mnist, n_steps=12, ckpt_dir=tmp_path / "ckpt", plan=plan)

    _assert_identical(clean, faulted)
    assert all(f.fired for f in plan.faults)
    snap = loop.goodput.snapshot()
    assert snap["recoveries"] == 1
    assert snap["replayed_steps"] == 2  # restored@6, frontier was 8
    (ev,) = loop.goodput.events
    assert ev["complete"] and ev["failed_at_step"] == 8 and ev["restored_step"] == 6
    assert snap["recovery_latency_ms"] > 0
    assert 0.0 < snap["goodput_fraction"] <= 1.0


def test_combined_preempt_corrupt_stall_chain(mesh8, small_mnist, tmp_path):
    """The acceptance chain: preemption at 8 AND the checkpoint it wants
    (6) corrupted AND an input stall — restore quarantines step 6, falls
    back to 3, replays 5 steps, and the trajectory is still bit-identical."""
    clean, _ = _run_training(mesh8, small_mnist, n_steps=12)

    ckpt = tmp_path / "ckpt"
    plan = FaultPlan([
        Fault.preempt(8),
        Fault.corrupt_checkpoint(6),
        Fault.stall_input(2, 0.05),
    ])
    faulted, loop = _run_training(
        mesh8, small_mnist, n_steps=12, ckpt_dir=ckpt, plan=plan,
        max_restore_fallbacks=2)

    _assert_identical(clean, faulted)
    assert sorted(f.kind for f in plan.fired()) == [
        "corrupt_checkpoint", "preempt", "stall_input"]
    assert (ckpt / "quarantine" / "step_6").exists()
    # step 6 exists again on disk: the REPLAY re-saved it (healthy — the
    # manager stayed writable after the quarantine)
    assert (ckpt / "6").exists()
    snap = loop.goodput.snapshot()
    assert snap["recoveries"] == 1
    assert snap["replayed_steps"] == 5  # restored@3 after the fallback
    assert snap["stall_s"] >= 0.05
    (ev,) = loop.goodput.events
    assert ev["restored_step"] == 3 and ev["failed_at_step"] == 8


# -- FaultyBatches (jax-free) ------------------------------------------------

class _ListBatches:
    def __init__(self, items, start=0):
        self.items = items
        self.start = start

    def at_step(self, step):
        return _ListBatches(self.items, start=step)

    def __iter__(self):
        return iter(self.items[self.start:])


def test_faulty_batches_stalls_then_delegates():
    plan = FaultPlan([Fault.stall_input(1, 0.05)])
    fb = FaultyBatches(_ListBatches([10, 11, 12]), plan)
    t0 = time.monotonic()
    assert list(fb) == [10, 11, 12]
    assert time.monotonic() - t0 >= 0.05
    assert plan.faults[0].fired  # at-most-once: a re-iteration won't stall
    t0 = time.monotonic()
    assert list(fb) == [10, 11, 12]
    assert time.monotonic() - t0 < 0.05


def test_faulty_batches_reseek_preserves_wrapper():
    plan = FaultPlan([Fault.stall_input(99, 0.01)])
    fb = FaultyBatches(_ListBatches([10, 11, 12]), plan)
    fb2 = fb.at_step(2)
    assert isinstance(fb2, FaultyBatches)
    assert list(fb2) == [12]
    assert fb2._plan is plan  # same latches across the re-seek


# -- serve-engine fault ------------------------------------------------------

def test_serve_error_fails_one_batch_keeps_serving(mesh8):
    from dist_mnist_tpu.serve import (
        InferenceEngine, InferenceServer, ServeConfig, load_for_serving)

    bundle = load_for_serving("mlp_mnist", mesh8)
    engine = InferenceEngine(
        bundle.model, bundle.params, bundle.model_state, mesh8,
        model_name="mlp-faults", image_shape=bundle.image_shape,
        rules=bundle.rules, max_bucket=16,
    )
    plan = FaultPlan([Fault.serve_error(request=0)])
    faulty = plan.wrap_engine(engine)
    assert faulty is not engine  # wired (pending serve_error present)

    rng = np.random.default_rng(0)
    img = rng.integers(0, 256, size=bundle.image_shape, dtype=np.uint8)
    server = InferenceServer(faulty, ServeConfig(
        max_batch=8, max_wait_ms=2.0, queue_depth=16, prewarm=False))
    with server:
        f1 = server.submit(img)
        with pytest.raises(RuntimeError, match="injected serve engine error"):
            f1.result(timeout=30)
        # the batcher failed ONLY that batch's futures; the next request
        # must be served (fired latch: the fault does not re-raise)
        f2 = server.submit(img)
        assert f2.result(timeout=30).logits.shape == (10,)
    assert plan.faults[0].fired


# -- supervisor: stub-child restart ladder -----------------------------------

STUB_CHILD = textwrap.dedent("""\
    import os, sys, time

    args = dict(a.split("=", 1) for a in sys.argv[1:]
                if a.startswith("--") and "=" in a)
    pid = int(args.get("--process_id", "0"))
    mode = args.get("--stub_mode", "ok")
    if pid == 0:
        chief_rc = int(args.get("--stub_chief_rc", "0"))
        time.sleep(float(args.get("--stub_chief_sleep", "0.5")))
        sys.exit(chief_rc)
    if mode == "fail_once":
        marker = args["--stub_marker"]
        if not os.path.exists(marker):
            open(marker, "w").close()
            sys.exit(3)
        sys.exit(0)
    if mode == "fail_always":
        sys.exit(7)
    time.sleep(float(args.get("--stub_sleep", "0")))
    sys.exit(0)
""")


@pytest.fixture()
def stub_child(tmp_path):
    """A jax-free child program so supervisor semantics (restart, backoff,
    exit codes, kill injection) are testable in ~a second."""
    path = tmp_path / "stub_child.py"
    path.write_text(STUB_CHILD)
    return [sys.executable, str(path)]


def _supervise(stub_child, train_args, **kw):
    from dist_mnist_tpu.cli.launch import launch

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = launch(2, train_args, platform="cpu", devices_per_process=1,
                    child_command=stub_child, restart_backoff_s=0.05, **kw)
    return rc, buf.getvalue()


def test_supervisor_restarts_failed_worker(stub_child, tmp_path):
    rc, log = _supervise(
        stub_child,
        ["--stub_mode=fail_once", f"--stub_marker={tmp_path / 'marker'}"],
        max_restarts=2,
    )
    assert rc == 0, log
    # satellite: the error names the dead worker's tag AND exit code
    assert "p1 exited rc=3" in log
    assert "restarting cluster (attempt 1/2)" in log


def test_supervisor_gives_up_with_childs_rc(stub_child):
    rc, log = _supervise(stub_child, ["--stub_mode=fail_always"],
                         max_restarts=1)
    assert rc == 7, log  # deterministic: the dead child's own exit code
    assert "p1 exited rc=7" in log
    assert "giving up after 1 restart(s)" in log


def test_supervisor_fail_fast_without_restarts(stub_child):
    rc, log = _supervise(stub_child, ["--stub_mode=fail_always"],
                         max_restarts=0)
    assert rc == 7, log
    assert "restarting" not in log


def test_supervisor_kill_injection_then_clean_restart(stub_child):
    rc, log = _supervise(
        stub_child,
        ["--stub_mode=sleep", "--stub_sleep=2.0", "--stub_chief_sleep=2.0"],
        max_restarts=1, kill_spec=(1, 0.3),
    )
    assert rc == 0, log
    assert "fault injected: SIGKILL p1" in log
    assert "p1 exited rc=137 (killed by SIGKILL)" in log
    # the kill fires only in generation 0; the restarted cluster completes
    assert "restarting cluster (attempt 1/1)" in log


def test_supervisor_chief_death_is_fatal(stub_child):
    rc, log = _supervise(
        stub_child,
        ["--stub_chief_rc=5", "--stub_chief_sleep=0.1",
         "--stub_mode=sleep", "--stub_sleep=2.0"],
        max_restarts=3,
    )
    assert rc == 5, log
    assert "chief died" in log
    assert "restarting cluster" not in log  # chief state is unrecoverable


# -- slow: real-process integration ------------------------------------------

@pytest.mark.slow
def test_sigterm_checkpoints_and_exits_zero(tmp_path):
    """SIGTERM to a real training process -> checkpoint at the boundary
    step, `preempted@step=N` marker, exit code 0 (the acceptance handshake)."""
    data_dir = str(tmp_path / "data")
    ckpt_dir = tmp_path / "ckpt"
    r = subprocess.run(
        [sys.executable, "-m", "dist_mnist_tpu.cli.train",
         "--download_only", f"--data_dir={data_dir}",
         "--config=mlp_mnist", "--platform=cpu"],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr

    proc = subprocess.Popen(
        [sys.executable, "-m", "dist_mnist_tpu.cli.train",
         "--config=mlp_mnist", f"--data_dir={data_dir}",
         f"--checkpoint_dir={ckpt_dir}", "--platform=cpu",
         "--train_steps=100000", "--batch_size=32", "--eval_every=0",
         "--log_every=5"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    lines = []
    try:
        deadline = time.monotonic() + 240
        # wait until training demonstrably progresses...
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            lines.append(line)
            if re.search(r"step \d+: ", line):
                break
        else:
            pytest.fail("no training progress before deadline")
        # ...then preempt it
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=240)
        lines.append(out)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    log = "".join(lines)
    assert proc.returncode == 0, log
    m = re.search(r"preempted@step=(\d+)", log)
    assert m, log
    step = int(m.group(1))
    from dist_mnist_tpu.checkpoint import CheckpointManager

    mgr = CheckpointManager(ckpt_dir)
    assert mgr.latest_step() == step  # durable at the boundary step
    mgr.close()


@pytest.mark.slow
def test_launch_kill_injection_training_completes(tmp_path):
    """Acceptance: one killed non-chief process under the supervisor ->
    cluster restarts and training still completes all steps, with both
    processes agreeing on the final accuracy."""
    from dist_mnist_tpu.cli.launch import launch

    data_dir = str(tmp_path / "data")
    r = subprocess.run(
        [sys.executable, "-m", "dist_mnist_tpu.cli.train",
         "--download_only", f"--data_dir={data_dir}",
         "--config=mlp_mnist", "--platform=cpu"],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = launch(
            2,
            ["--config=mlp_mnist", f"--data_dir={data_dir}",
             f"--checkpoint_dir={tmp_path / 'ckpt'}",
             "--train_steps=6", "--batch_size=32", "--eval_every=0",
             "--log_every=2"],
            platform="cpu", devices_per_process=1,
            max_restarts=2, restart_backoff_s=0.2, kill_spec=(1, 5.0),
        )
    log = buf.getvalue()
    assert rc == 0, log
    assert "fault injected: SIGKILL p1" in log
    assert "p1 exited rc=137" in log
    assert "restarting cluster" in log
    finals = re.findall(r"\[p(\d)\].*done: step=(\d+) test_acc=([0-9.]+)", log)
    assert sorted(f[0] for f in finals) == ["0", "1"], log
    assert all(f[1] == "6" for f in finals), finals
    assert finals[0][2] == finals[1][2], finals
