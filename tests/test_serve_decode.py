"""Tier-1 decode-serving tests (serve/decode.py + models/causal_lm.py).

The subsystem's contracts, in dependency order: (1) the model's
incremental decode is BITWISE the full-sequence forward at every
position — including across the prefill/decode boundary and under a
TP-sharded KV cache; (2) the engine's prefill result depends only on the
request, never on the admission batch around it; (3) the scheduler's
continuous batching changes WHEN a request runs, never WHAT it computes
(identical token streams vs the static baseline), admits
latency_sensitive ahead of queued best_effort, respects slot capacity,
and never recompiles after prewarm. All CPU-mesh.
"""

from __future__ import annotations

import threading
import time

import jax
import numpy as np
import pytest

from dist_mnist_tpu.cluster.mesh import activate
from dist_mnist_tpu.models.causal_lm import CausalLMTiny
from dist_mnist_tpu.serve import (
    BEST_EFFORT,
    DECODE_SLO_TARGETS,
    LATENCY_SENSITIVE,
    CompiledModelCache,
    DecodeMetrics,
    DecodeScheduler,
    QueueFullError,
    ShuttingDownError,
    build_decode_engine,
    init_lm_for_serving,
    make_prompts,
    run_decode_loadgen,
)
from dist_mnist_tpu.serve.zoo import DecodeGrid, default_decode_grid

# small geometry keeps the (admit x prompt) grid's CPU compiles fast
LM_KW = dict(vocab_size=64, dim=32, depth=2, heads=4, max_seq=32)
MAX_SLOTS = 4


@pytest.fixture(scope="module")
def lm():
    model = CausalLMTiny(**LM_KW)
    params, _ = model.init(jax.random.PRNGKey(0))
    return model, params


@pytest.fixture(scope="module")
def shared_cache():
    """One compiled set for every engine in this module: engines differ
    only in mesh/KV state, so cross-engine reuse is both a speedup and
    itself a correctness claim (executables close over no weights)."""
    return CompiledModelCache()


@pytest.fixture(scope="module")
def engine(mesh8, shared_cache):
    eng = build_decode_engine(mesh8, max_slots=MAX_SLOTS,
                              cache=shared_cache, **LM_KW)
    eng.prewarm()
    return eng


def _prompts(n, seed=0, max_seq=LM_KW["max_seq"]):
    return [p for p, _ in make_prompts(n, max_seq=max_seq, seed=seed,
                                       max_new=1)]


# -- model: bitwise decode==forward ------------------------------------------

def test_incremental_decode_bit_matches_full_forward(lm):
    model, params = lm
    rng = np.random.default_rng(1)
    s = 12
    tokens = rng.integers(0, model.vocab_size, size=(2, s), dtype=np.int32)
    full, _ = model.apply(params, {}, tokens)
    full = np.asarray(full)
    cache = model.init_cache(2)
    for pos in range(s):
        logits, cache = model.decode_step(
            params, cache, tokens[:, pos], np.full(2, pos, np.int32))
        np.testing.assert_array_equal(
            np.asarray(logits), full[:, pos],
            err_msg=f"decode step at position {pos} is not bitwise the "
                    f"full forward")


def test_prefill_then_decode_boundary_bitwise(lm):
    model, params = lm
    rng = np.random.default_rng(2)
    plen = 9
    prompt = rng.integers(0, model.vocab_size, size=(1, plen),
                          dtype=np.int32)
    full, _ = model.apply(params, {}, prompt)
    cache = model.init_cache(1)
    last, cache = model.prefill(params, cache, prompt,
                                np.zeros(1, np.int32),
                                np.full(1, plen, np.int32))
    np.testing.assert_array_equal(np.asarray(last), np.asarray(full)[:, -1])
    # first decode step == full forward over (prompt + that token)
    nxt = np.argmax(np.asarray(last), axis=-1).astype(np.int32)
    step, cache = model.decode_step(params, cache, nxt,
                                    np.full(1, plen, np.int32))
    extended = np.concatenate([prompt, nxt[:, None]], axis=1)
    full2, _ = model.apply(params, {}, extended)
    np.testing.assert_array_equal(np.asarray(step),
                                  np.asarray(full2)[:, plen])


def test_prefill_padding_rows_do_not_perturb_real_rows(lm):
    """A request's cache rows and logits are identical whether it
    prefilled solo or padded into a batch with other prompts — the
    model-level half of stream independence from scheduling."""
    model, params = lm
    rng = np.random.default_rng(3)
    plen, bucket = 6, 8
    prompt = np.zeros((1, bucket), np.int32)
    prompt[0, :plen] = rng.integers(0, model.vocab_size, size=plen)
    solo_last, solo_cache = model.prefill(
        params, model.init_cache(3), prompt, np.asarray([1], np.int32),
        np.asarray([plen], np.int32))
    other = rng.integers(0, model.vocab_size, size=(1, bucket),
                         dtype=np.int32)
    batch = np.concatenate([other, prompt], axis=0)
    both_last, both_cache = model.prefill(
        params, model.init_cache(3), batch, np.asarray([0, 1], np.int32),
        np.asarray([bucket, plen], np.int32))
    np.testing.assert_array_equal(np.asarray(solo_last)[0],
                                  np.asarray(both_last)[1])
    np.testing.assert_array_equal(np.asarray(solo_cache["k"])[:, 1],
                                  np.asarray(both_cache["k"])[:, 1])


def test_tp_sharded_cache_bitwise_vs_unsharded(lm, mesh_tp):
    """Full forward + an incremental decode under the TP mesh (heads
    sharded over model=2) are bitwise the unsharded results."""
    model, params = lm
    rng = np.random.default_rng(4)
    s = 8
    tokens = rng.integers(0, model.vocab_size, size=(2, s), dtype=np.int32)
    ref, _ = model.apply(params, {}, tokens)
    ref_cache = model.init_cache(2)
    ref_step, ref_cache = model.decode_step(
        params, ref_cache, tokens[:, 0], np.zeros(2, np.int32))
    with activate(mesh_tp):
        tp_full, _ = model.apply(params, {}, tokens)
        tp_cache = model.init_cache(2)
        tp_step, tp_cache = model.decode_step(
            params, tp_cache, tokens[:, 0], np.zeros(2, np.int32))
    np.testing.assert_array_equal(np.asarray(tp_full), np.asarray(ref))
    np.testing.assert_array_equal(np.asarray(tp_step), np.asarray(ref_step))
    np.testing.assert_array_equal(np.asarray(tp_cache["k"]),
                                  np.asarray(ref_cache["k"]))


def test_tp_engine_streams_match_dp_engine(mesh8, mesh_tp, shared_cache):
    """Whole-stack TP parity: the same traffic through a data=4 x model=2
    engine (heads-sharded KV cache) and the pure-DP engine yields
    identical token streams."""
    def streams(mesh, cache):
        eng = build_decode_engine(mesh, max_slots=MAX_SLOTS, cache=cache,
                                  **LM_KW)
        eng.prewarm()
        sched = DecodeScheduler(eng)
        try:
            return run_decode_loadgen(sched, n_requests=6, concurrency=4,
                                      seed=5, keep_streams=True)["streams"]
        finally:
            sched.close()

    # TP mesh compiles its own programs: a separate cache keeps this
    # module's shared DP cache key-space clean
    assert streams(mesh8, shared_cache) == streams(
        mesh_tp, CompiledModelCache())


# -- engine: grid + zero recompiles ------------------------------------------

def test_decode_grid_bucketing_and_cells():
    grid = default_decode_grid(CausalLMTiny(**LM_KW), max_slots=MAX_SLOTS)
    assert grid.rows == MAX_SLOTS + 1
    assert grid.prompt_bucket_for(1) == grid.prompt_buckets[0]
    assert grid.prompt_bucket_for(5) == 8
    assert grid.prompt_bucket_for(32) == 32
    with pytest.raises(ValueError):
        grid.prompt_bucket_for(33)
    assert grid.admit_bucket_for(3) == 4
    cells = grid.cells()
    assert cells[-1] == ("decode",)
    assert len(cells) == (len(grid.admit_buckets)
                          * len(grid.prompt_buckets) + 1)
    with pytest.raises(ValueError):
        DecodeGrid(max_slots=0, max_seq=32, prompt_buckets=(4,),
                   admit_buckets=(1,))


def test_prewarm_then_zero_hot_path_recompiles(engine, shared_cache):
    assert engine.prewarm() == 0  # module fixture already compiled all
    before = shared_cache.misses
    sched = DecodeScheduler(engine)
    try:
        summary = run_decode_loadgen(sched, n_requests=12, concurrency=6,
                                     seed=0)
    finally:
        sched.close()
    assert summary["ok"] == 12
    assert summary["recompiles_during_traffic"] == 0
    assert shared_cache.misses == before


def test_engine_prefill_groups_by_request_own_bucket(engine):
    """Mixed prompt lengths in one admission still prefill through each
    request's OWN prompt bucket (multiple executables), and the first
    generated token matches a solo prefill of the same prompt."""
    prompts = [np.arange(3, dtype=np.int32) % engine.model.vocab_size,
               np.arange(14, dtype=np.int32) % engine.model.vocab_size]
    together = engine.prefill(prompts, [0, 1])
    solo = [engine.prefill([p], [i])[0] for i, p in enumerate(prompts)]
    np.testing.assert_array_equal(together, np.asarray(solo))


def test_init_lm_for_serving_rejects_non_lm():
    with pytest.raises(ValueError, match="decode surface"):
        init_lm_for_serving("mlp")


# -- scheduler: continuous batching ------------------------------------------

def test_continuous_and_static_streams_identical(mesh8, shared_cache):
    def run(mode):
        eng = build_decode_engine(mesh8, max_slots=MAX_SLOTS,
                                  cache=shared_cache, **LM_KW)
        eng.prewarm()
        sched = DecodeScheduler(eng, mode=mode)
        try:
            return run_decode_loadgen(sched, n_requests=10, concurrency=6,
                                      seed=7, keep_streams=True)
        finally:
            sched.close()

    cont, stat = run("continuous"), run("static")
    assert cont["streams"] == stat["streams"]
    assert cont["ok"] == stat["ok"] == 10
    assert cont["recompiles_during_traffic"] == 0
    assert stat["recompiles_during_traffic"] == 0


def test_slot_admit_evict_invariants(engine):
    """More requests than slots: every admission gets a real slot, live
    occupancy never exceeds capacity, every eviction returns its slot,
    and the scheduler ends empty with all slots free."""
    sched = DecodeScheduler(engine)
    n = 3 * MAX_SLOTS
    try:
        futs = [sched.submit(p, 4) for p in _prompts(n, seed=8)]
        results = [f.result(timeout=60) for f in futs]
        assert sched.drain(timeout=30)
    finally:
        sched.close()
    assert all(len(r.tokens) == 4 for r in results)
    assert len(sched.admit_log) == n
    # submissions were all enqueued before any admission cycle ran more
    # than once, so admission order == submission order for one class
    assert [seq for seq, _ in sched.admit_log] == sorted(
        seq for seq, _ in sched.admit_log)
    assert sched.active_count == 0
    assert sched.free_slots == MAX_SLOTS
    assert sched.queue_depth == 0
    snap = sched.metrics.snapshot()
    assert snap["completed"] == n
    assert snap["mean_active_slots"] <= MAX_SLOTS


def test_latency_sensitive_jumps_the_queue(engine):
    """With every slot occupied and best_effort requests queued, a newly
    submitted latency_sensitive request is admitted before ALL of them
    (DECODE_SLO_TARGETS maps it to the TTFT target)."""
    assert DECODE_SLO_TARGETS[LATENCY_SENSITIVE] == "ttft_ms"
    assert DECODE_SLO_TARGETS[BEST_EFFORT] == "tokens_per_s"
    sched = DecodeScheduler(engine)
    try:
        occupants = [sched.submit(p, 16) for p in _prompts(MAX_SLOTS,
                                                           seed=9)]
        # wait until every slot is genuinely occupied so the queue forms
        deadline = time.monotonic() + 30
        while sched.free_slots and time.monotonic() < deadline:
            time.sleep(0.002)
        assert sched.free_slots == 0
        queued_be = [sched.submit(p, 2) for p in _prompts(3, seed=10)]
        ls = sched.submit(_prompts(1, seed=11)[0], 2,
                          request_class=LATENCY_SENSITIVE)
        ls.result(timeout=60)
        for f in occupants + queued_be:
            f.result(timeout=60)
        assert sched.drain(timeout=30)
    finally:
        sched.close()
    post_occupancy = sched.admit_log[MAX_SLOTS:]
    assert post_occupancy[0][1] == LATENCY_SENSITIVE
    assert [cls for _, cls in post_occupancy[1:]] == [BEST_EFFORT] * 3


def test_submit_validation_and_backpressure(engine):
    sched = DecodeScheduler(engine, max_queue=2)
    try:
        with pytest.raises(ValueError, match="empty prompt"):
            sched.submit(np.zeros(0, np.int32), 4)
        with pytest.raises(ValueError, match="max_seq"):
            sched.submit(np.zeros(30, np.int32), 8)
        with pytest.raises(ValueError, match="request class"):
            sched.submit(np.zeros(4, np.int32), 2, request_class="vip")
        # saturate the slots (one at a time: max_queue=2 also caps how
        # many un-admitted submissions may be pending), then the queue
        blockers = []
        deadline = time.monotonic() + 30
        for p in _prompts(MAX_SLOTS, seed=12):
            blockers.append(sched.submit(p, 16))
            while sched.queue_depth and time.monotonic() < deadline:
                time.sleep(0.002)
        while sched.free_slots and time.monotonic() < deadline:
            time.sleep(0.002)
        queued = []
        with pytest.raises(QueueFullError):
            for p in _prompts(8, seed=13):
                queued.append(sched.submit(p, 2))
        assert sched.metrics.rejected_queue_full == 1
        for f in blockers + queued:
            f.result(timeout=60)
    finally:
        sched.close()


def test_close_fails_pending_and_joins_thread(engine):
    sched = DecodeScheduler(engine)
    futs = [sched.submit(p, 16) for p in _prompts(2 * MAX_SLOTS, seed=14)]
    sched.close()
    with pytest.raises(ShuttingDownError):
        sched.submit(np.zeros(4, np.int32), 2)
    # every future settled: a result (finished before close) or the
    # shutdown error (queued/in-flight at close) — never dropped
    for f in futs:
        assert f.done()
        if f.exception() is not None:
            assert isinstance(f.exception(), ShuttingDownError)
    assert not any(t.name.startswith("DecodeScheduler")
                   for t in threading.enumerate() if t.is_alive())
    sched.close()  # idempotent


# -- loadgen + metrics --------------------------------------------------------

def test_decode_loadgen_deterministic(mesh8, shared_cache):
    reqs_a = make_prompts(16, max_seq=32, seed=3)
    reqs_b = make_prompts(16, max_seq=32, seed=3)
    assert all((a == b).all() and na == nb
               for (a, na), (b, nb) in zip(reqs_a, reqs_b))
    assert all(p.size + n <= 32 for p, n in reqs_a)

    def run():
        eng = build_decode_engine(mesh8, max_slots=MAX_SLOTS,
                                  cache=shared_cache, **LM_KW)
        eng.prewarm()
        sched = DecodeScheduler(eng)
        try:
            return run_decode_loadgen(sched, n_requests=8, concurrency=4,
                                      seed=15, keep_streams=True)
        finally:
            sched.close()

    a, b = run(), run()
    assert a["streams"] == b["streams"]
    assert a["tokens_out"] == b["tokens_out"] > 0
    assert np.isfinite(a["ttft_p99_ms"]) and np.isfinite(
        a["tokens_per_s_mean"])
    # one token-timestamp list per completed request, one stamp per token
    assert [len(t) for t in a["token_times"]] == [len(s)
                                                  for s in a["streams"]]


def test_decode_metrics_emit_batched_and_attached():
    class Writer:
        def __init__(self):
            self.scalar_batches = []
            self.hists = []

        def scalars(self, vals, step):
            self.scalar_batches.append((dict(vals), step))

        def histogram(self, tag, values, step):
            self.hists.append(tag)

        def flush(self):
            pass

    class Registry:
        def __init__(self):
            self.attached = {}

        def attach_histogram(self, tag, hist):
            self.attached[tag] = hist

    m = DecodeMetrics()
    m.record_submitted(LATENCY_SENSITIVE)
    m.record_admitted(12.5, LATENCY_SENSITIVE)
    m.record_step(3)
    m.record_completed(80.0, 8, 100.0)
    m.record_rejected("queue_full")
    reg = Registry()
    m.attach_to(reg)
    assert set(reg.attached) == {"serve/decode_ttft_ms",
                                 "serve/decode_tokens_per_s",
                                 "serve/decode_active_slots"}
    w = Writer()
    m.emit(w, 1, queue_depth=2, cache={"hits": 5, "misses": 1})
    (vals, step), = w.scalar_batches
    assert step == 1
    assert vals["serve/decode_submitted"] == 1
    assert vals["serve/decode_completed"] == 1
    assert vals["serve/decode_rejected_queue_full"] == 1
    assert vals["serve/decode_queue_depth"] == 2
    assert vals["serve/decode_ttft_p99_ms"] == pytest.approx(12.5, rel=0.2)
    assert vals["serve/decode_tokens_per_s"] == pytest.approx(100.0,
                                                              rel=0.2)
    assert "serve/decode_ttft_ms" in w.hists
    with pytest.raises(ValueError):
        m.record_rejected("bad_reason")
