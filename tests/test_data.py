"""Synthetic datasets + pipeline determinism and sharding."""

import jax
import numpy as np

from dist_mnist_tpu.data import synthetic
from dist_mnist_tpu.data.datasets import load_dataset
from dist_mnist_tpu.data.pipeline import (
    DeviceDataset,
    ShardedBatcher,
    epoch_batches,
    shard_batch,
)


def test_synthetic_mnist_shapes_and_determinism():
    x1, y1 = synthetic.synthetic_mnist(256, seed=3)
    x2, y2 = synthetic.synthetic_mnist(256, seed=3)
    assert x1.shape == (256, 28, 28, 1) and x1.dtype == np.uint8
    assert y1.shape == (256,) and y1.dtype == np.int32
    np.testing.assert_array_equal(x1, x2)  # bitwise reproducible (multi-host)
    np.testing.assert_array_equal(y1, y2)
    assert set(np.unique(y1)) <= set(range(10))
    x3, _ = synthetic.synthetic_mnist(256, seed=4)
    assert (x1 != x3).any()


def test_synthetic_cifar_shapes():
    x, y = synthetic.synthetic_cifar10(64, seed=0)
    assert x.shape == (64, 32, 32, 3) and x.dtype == np.uint8
    assert y.min() >= 0 and y.max() <= 9


def test_synthetic_classes_are_distinguishable():
    """Mean images per class should differ clearly (sanity of class signal)."""
    x, y = synthetic.synthetic_mnist(2000, seed=0)
    means = np.stack([x[y == c].mean(0) for c in range(10)])
    dists = np.linalg.norm(
        (means[:, None] - means[None, :]).reshape(10, 10, -1), axis=-1
    )
    off_diag = dists[~np.eye(10, dtype=bool)]
    assert off_diag.min() > 1.0


def test_load_dataset_fallback_and_idx_loading(tmp_path):
    ds = load_dataset("mnist", tmp_path, synthetic_sizes=(512, 128))
    assert ds.synthetic
    # write the canonical 4-file layout, reload from disk
    from dist_mnist_tpu.data.idx import write_idx

    write_idx(tmp_path / "train-images-idx3-ubyte", ds.train_images[..., 0])
    write_idx(tmp_path / "train-labels-idx1-ubyte",
              ds.train_labels.astype(np.uint8))
    write_idx(tmp_path / "t10k-images-idx3-ubyte.gz", ds.test_images[..., 0])
    write_idx(tmp_path / "t10k-labels-idx1-ubyte.gz",
              ds.test_labels.astype(np.uint8))
    ds2 = load_dataset("mnist", tmp_path)
    assert not ds2.synthetic
    np.testing.assert_array_equal(ds2.train_images, ds.train_images)
    np.testing.assert_array_equal(ds2.test_labels, ds.test_labels)


def test_epoch_batches_partition_and_determinism():
    a = [b.copy() for b in epoch_batches(103, 10, seed=1, epoch=2)]
    b = [b.copy() for b in epoch_batches(103, 10, seed=1, epoch=2)]
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    flat = np.concatenate(a)
    assert len(flat) == 100  # drop remainder
    assert len(np.unique(flat)) == 100  # without replacement
    c = np.concatenate(list(epoch_batches(103, 10, seed=1, epoch=3)))
    assert (flat != c).any()  # reshuffled across epochs


def test_sharded_batcher_shapes(mesh8, small_mnist):
    it = iter(ShardedBatcher(small_mnist, 64, mesh8, seed=0))
    batch = next(it)
    assert batch["image"].shape == (64, 28, 28, 1)
    assert batch["label"].shape == (64,)
    # sharded over the data axis: each device holds 8 rows
    db = batch["image"].sharding.shard_shape(batch["image"].shape)
    assert db[0] == 8


def test_device_dataset_sample_inside_jit(mesh8, small_mnist):
    dd = DeviceDataset(small_mnist, mesh8)

    @jax.jit
    def draw(key):
        b = dd.sample(key, 32)
        return b["image"].sum(), b["label"]

    s, lab = draw(jax.random.PRNGKey(0))
    assert lab.shape == (32,)
    s2, lab2 = draw(jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(lab), np.asarray(lab2))


def test_sharded_batcher_rejects_oversized_batch(mesh8, small_mnist):
    import pytest

    with pytest.raises(ValueError, match="exceeds dataset size"):
        next(iter(ShardedBatcher(small_mnist, 1 << 20, mesh8)))


def test_synthetic_cache_roundtrip(tmp_path):
    """Full-size synthetic twins cache to disk atomically, reload fast, and
    KEEP synthetic=True (the marker file); corrupt files fall back."""
    ds1 = load_dataset("mnist", tmp_path, synthetic_sizes=(60_000, 10_000))
    assert ds1.synthetic
    ds2 = load_dataset("mnist", tmp_path)
    assert ds2.synthetic  # cached twin must not masquerade as real data
    np.testing.assert_array_equal(ds1.train_images, ds2.train_images)
    # corrupt a cached file: loader must fall back to synthesis, not crash
    (tmp_path / "train-images-idx3-ubyte").write_bytes(b"\x00\x00\x08\x03trunc")
    ds3 = load_dataset("mnist", tmp_path, synthetic_sizes=(512, 128))
    assert ds3.synthetic and len(ds3.train_labels) == 512


def test_sharded_batcher_start_step_seeks(mesh8, small_mnist):
    """A batcher started at step K yields exactly the stream the fresh
    batcher yields after K batches — across epoch boundaries too."""
    b = ShardedBatcher(small_mnist, 512, mesh8, seed=7)  # 8 steps/epoch
    k = 10  # crosses into epoch 1
    fresh = iter(b)
    for _ in range(k):
        next(fresh)
    seeked = iter(b.at_step(k))
    for _ in range(3):
        want, got = next(fresh), next(seeked)
        np.testing.assert_array_equal(
            np.asarray(want["image"]), np.asarray(got["image"])
        )
        np.testing.assert_array_equal(
            np.asarray(want["label"]), np.asarray(got["label"])
        )


def test_native_batcher_start_step_seeks(mesh8, small_mnist):
    from dist_mnist_tpu.data.native import NativeBatcher

    b = NativeBatcher(small_mnist, 512, mesh8, seed=7)
    k = 10
    imgs = []
    for _ in range(k + 2):
        img, lab, step = b.next_local()
        imgs.append((img, lab, step))
    b2 = b.at_step(k)
    for i in range(2):
        img, lab, step = b2.next_local()
        assert step == k + i == imgs[k + i][2]
        np.testing.assert_array_equal(img, imgs[k + i][0])
        np.testing.assert_array_equal(lab, imgs[k + i][1])
    b2.close()
