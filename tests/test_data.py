"""Synthetic datasets + pipeline determinism and sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dist_mnist_tpu.data import synthetic
from dist_mnist_tpu.data.datasets import load_dataset
from dist_mnist_tpu.data.pipeline import (
    DeviceDataset,
    ShardedBatcher,
    epoch_batches,
    shard_batch,
)


def test_synthetic_mnist_shapes_and_determinism():
    x1, y1 = synthetic.synthetic_mnist(256, seed=3)
    x2, y2 = synthetic.synthetic_mnist(256, seed=3)
    assert x1.shape == (256, 28, 28, 1) and x1.dtype == np.uint8
    assert y1.shape == (256,) and y1.dtype == np.int32
    np.testing.assert_array_equal(x1, x2)  # bitwise reproducible (multi-host)
    np.testing.assert_array_equal(y1, y2)
    assert set(np.unique(y1)) <= set(range(10))
    x3, _ = synthetic.synthetic_mnist(256, seed=4)
    assert (x1 != x3).any()


def test_synthetic_cifar_shapes():
    x, y = synthetic.synthetic_cifar10(64, seed=0)
    assert x.shape == (64, 32, 32, 3) and x.dtype == np.uint8
    assert y.min() >= 0 and y.max() <= 9


def test_synthetic_classes_are_distinguishable():
    """Mean images per class should differ clearly (sanity of class signal)."""
    x, y = synthetic.synthetic_mnist(2000, seed=0)
    means = np.stack([x[y == c].mean(0) for c in range(10)])
    dists = np.linalg.norm(
        (means[:, None] - means[None, :]).reshape(10, 10, -1), axis=-1
    )
    off_diag = dists[~np.eye(10, dtype=bool)]
    assert off_diag.min() > 1.0


def test_load_dataset_fallback_and_idx_loading(tmp_path):
    ds = load_dataset("mnist", tmp_path, synthetic_sizes=(512, 128))
    assert ds.synthetic
    # write the canonical 4-file layout, reload from disk
    from dist_mnist_tpu.data.idx import write_idx

    write_idx(tmp_path / "train-images-idx3-ubyte", ds.train_images[..., 0])
    write_idx(tmp_path / "train-labels-idx1-ubyte",
              ds.train_labels.astype(np.uint8))
    write_idx(tmp_path / "t10k-images-idx3-ubyte.gz", ds.test_images[..., 0])
    write_idx(tmp_path / "t10k-labels-idx1-ubyte.gz",
              ds.test_labels.astype(np.uint8))
    ds2 = load_dataset("mnist", tmp_path)
    assert not ds2.synthetic
    np.testing.assert_array_equal(ds2.train_images, ds.train_images)
    np.testing.assert_array_equal(ds2.test_labels, ds.test_labels)


def test_epoch_batches_partition_and_determinism():
    a = [b.copy() for b in epoch_batches(103, 10, seed=1, epoch=2)]
    b = [b.copy() for b in epoch_batches(103, 10, seed=1, epoch=2)]
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    flat = np.concatenate(a)
    assert len(flat) == 100  # drop remainder
    assert len(np.unique(flat)) == 100  # without replacement
    c = np.concatenate(list(epoch_batches(103, 10, seed=1, epoch=3)))
    assert (flat != c).any()  # reshuffled across epochs


def test_sharded_batcher_shapes(mesh8, small_mnist):
    it = iter(ShardedBatcher(small_mnist, 64, mesh8, seed=0))
    batch = next(it)
    assert batch["image"].shape == (64, 28, 28, 1)
    assert batch["label"].shape == (64,)
    # sharded over the data axis: each device holds 8 rows
    db = batch["image"].sharding.shard_shape(batch["image"].shape)
    assert db[0] == 8


def test_device_dataset_sample_inside_jit(mesh8, small_mnist):
    dd = DeviceDataset(small_mnist, mesh8)

    @jax.jit
    def draw(key):
        b = dd.sample(key, 32)
        return b["image"].sum(), b["label"]

    s, lab = draw(jax.random.PRNGKey(0))
    assert lab.shape == (32,)
    s2, lab2 = draw(jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(lab), np.asarray(lab2))


def test_sharded_batcher_rejects_oversized_batch(mesh8, small_mnist):
    import pytest

    with pytest.raises(ValueError, match="exceeds dataset size"):
        next(iter(ShardedBatcher(small_mnist, 1 << 20, mesh8)))


@pytest.mark.slow
def test_synthetic_cache_roundtrip(tmp_path):
    """Full-size synthetic twins cache to disk atomically, reload fast, and
    KEEP synthetic=True (the marker file); corrupt files fall back."""
    ds1 = load_dataset("mnist", tmp_path, synthetic_sizes=(60_000, 10_000))
    assert ds1.synthetic
    ds2 = load_dataset("mnist", tmp_path)
    assert ds2.synthetic  # cached twin must not masquerade as real data
    np.testing.assert_array_equal(ds1.train_images, ds2.train_images)
    # corrupt a cached file: loader must fall back to synthesis, not crash
    (tmp_path / "train-images-idx3-ubyte").write_bytes(b"\x00\x00\x08\x03trunc")
    ds3 = load_dataset("mnist", tmp_path, synthetic_sizes=(512, 128))
    assert ds3.synthetic and len(ds3.train_labels) == 512


def test_sharded_batcher_start_step_seeks(mesh8, small_mnist):
    """A batcher started at step K yields exactly the stream the fresh
    batcher yields after K batches — across epoch boundaries too."""
    b = ShardedBatcher(small_mnist, 512, mesh8, seed=7)  # 8 steps/epoch
    k = 10  # crosses into epoch 1
    fresh = iter(b)
    for _ in range(k):
        next(fresh)
    seeked = iter(b.at_step(k))
    for _ in range(3):
        want, got = next(fresh), next(seeked)
        np.testing.assert_array_equal(
            np.asarray(want["image"]), np.asarray(got["image"])
        )
        np.testing.assert_array_equal(
            np.asarray(want["label"]), np.asarray(got["label"])
        )


def test_native_batcher_start_step_seeks(mesh8, small_mnist):
    from dist_mnist_tpu.data.native import NativeBatcher

    b = NativeBatcher(small_mnist, 512, mesh8, seed=7)
    k = 10
    imgs = []
    for _ in range(k + 2):
        img, lab, step = b.next_local()
        imgs.append((img, lab, step))
    b2 = b.at_step(k)
    for i in range(2):
        img, lab, step = b2.next_local()
        assert step == k + i == imgs[k + i][2]
        np.testing.assert_array_equal(img, imgs[k + i][0])
        np.testing.assert_array_equal(lab, imgs[k + i][1])
    b2.close()


# ---- property tests (SURVEY.md §4: hypothesis for the sharding math) -------

from _hypothesis_stub import given, settings, st


@settings(max_examples=50, deadline=None)
@given(
    n=st.integers(min_value=1, max_value=2000),
    batch=st.integers(min_value=1, max_value=128),
    seed=st.integers(min_value=0, max_value=2**31 - 1),
    epoch=st.integers(min_value=0, max_value=100),
)
def test_epoch_batches_cover_without_repeat(n, batch, seed, epoch):
    """Every epoch is a permutation prefix: batches are disjoint, sizes
    exact, indices in range, and the same (seed, epoch) is bitwise stable
    across calls (the cross-host agreement contract)."""
    from dist_mnist_tpu.data.pipeline import epoch_batches

    batches = list(epoch_batches(n, batch, seed=seed, epoch=epoch))
    assert len(batches) == n // batch
    seen = np.concatenate(batches) if batches else np.array([], np.int64)
    assert len(set(seen.tolist())) == len(seen)  # no repeats
    assert all(b.shape == (batch,) for b in batches)
    if len(seen):
        assert seen.min() >= 0 and seen.max() < n
    again = list(epoch_batches(n, batch, seed=seed, epoch=epoch))
    assert all((a == b).all() for a, b in zip(batches, again))


@settings(max_examples=50, deadline=None)
@given(
    per_dev=st.integers(min_value=1, max_value=64),
    data_axis=st.sampled_from([1, 2, 4, 8]),
)
def test_local_batch_slice_partitions(per_dev, data_axis):
    """process slice x process count == global == device slice x axis size."""
    from dist_mnist_tpu.cluster.mesh import MeshSpec, local_batch_slice, make_mesh

    mesh = make_mesh(MeshSpec(data=data_axis),
                     devices=jax.devices()[:data_axis])
    global_batch = per_dev * data_axis
    per_proc, per_device = local_batch_slice(global_batch, mesh)
    assert per_device == per_dev
    assert per_proc * jax.process_count() == global_batch
    assert per_device * data_axis == global_batch


@settings(max_examples=30, deadline=None)
@given(
    n=st.integers(min_value=8, max_value=512),
    batch=st.integers(min_value=1, max_value=64),
    ckpt_step=st.integers(min_value=0, max_value=300),
)
def test_batcher_seek_is_pure_function_of_step(n, batch, ckpt_step):
    """at_step(k) must reproduce the exact index sequence an uninterrupted
    run sees from step k (the checkpoint-resume data-stream contract;
    pipeline.py 'resume exactly where a restored step left off')."""
    from dist_mnist_tpu.data.pipeline import epoch_batches

    steps_per_epoch = n // batch
    if steps_per_epoch == 0:
        return

    def stream_from(step, count=4):
        epoch, skip = divmod(step, steps_per_epoch)
        out = []
        while len(out) < count:
            for b, idx in enumerate(epoch_batches(n, batch, seed=7, epoch=epoch)):
                if b < skip:
                    continue
                out.append(idx)
                if len(out) == count:
                    break
            skip = 0
            epoch += 1
        return out

    uninterrupted = stream_from(0, count=min(ckpt_step, 50) + 4)
    resumed = stream_from(min(ckpt_step, 50), count=4)
    tail = uninterrupted[min(ckpt_step, 50):]
    assert all((a == b).all() for a, b in zip(tail, resumed))


def test_random_crop_flip_properties():
    """Shape/dtype preserved; deterministic per key; identity-free changes;
    values drawn only from the source image neighbourhood."""
    from dist_mnist_tpu.data.augment import random_crop_flip

    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 255, (16, 32, 32, 3), dtype=np.uint8)
    key = jax.random.PRNGKey(1)
    out1 = np.asarray(random_crop_flip(key, jnp.asarray(imgs)))
    out2 = np.asarray(random_crop_flip(key, jnp.asarray(imgs)))
    assert out1.shape == imgs.shape and out1.dtype == imgs.dtype
    np.testing.assert_array_equal(out1, out2)  # same key -> same batch
    other = np.asarray(random_crop_flip(jax.random.PRNGKey(2), jnp.asarray(imgs)))
    assert (other != out1).any()  # different key -> different crops
    # per-image histograms can only contain source-image values (crop+flip
    # of a reflect-pad rearranges pixels, never invents them)
    for i in range(4):
        assert set(np.unique(out1[i])) <= set(np.unique(imgs[i]))


def test_device_dataset_sharded_residency_and_sampling(mesh8, small_mnist):
    """shard=True: rows live 1/data_axis per device, sampling stays local
    (no collectives) and feeds a training step that learns."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from dist_mnist_tpu import optim
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.parallel.sharding import shard_train_state
    from dist_mnist_tpu.train import create_train_state
    from dist_mnist_tpu.train.step import make_fused_train_step

    with mesh8:
        dd = DeviceDataset(small_mnist, mesh8, shard=True)
        # rows sharded over `data`, not replicated
        assert dd.images.sharding.spec == P("data")
        assert dd.n % 8 == 0
        # sampling inside jit yields a data-sharded batch
        batch = jax.jit(lambda k: dd.sample(k, 64))(jax.random.PRNGKey(0))
        assert batch["image"].shape == (64, 28, 28, 1)
        assert batch["image"].sharding.spec == P("data")
        # each device's slice drew from its own shard -> slices differ
        slices = [np.asarray(s.data) for s in batch["label"].addressable_shards]
        assert len({tuple(s.tolist()) for s in slices}) > 1

        # end-to-end: the fused step trains off the sharded residency
        model = get_model("mlp", hidden_units=32)
        opt = optim.adam(0.01)
        state = create_train_state(model, opt, jax.random.PRNGKey(0),
                                   small_mnist.train_images[:1])
        state = shard_train_state(state, mesh8)
        step = make_fused_train_step(model, opt, mesh8, dd, 64)
        losses = []
        for _ in range(30):
            state, out = step(state)
            losses.append(float(out["loss"]))
    assert losses[-1] < losses[0] * 0.5


@pytest.mark.slow
def test_augmented_step_trains(mesh8, small_mnist):
    """augment=True composes with the jitted step (static shapes, grads)."""
    from dist_mnist_tpu import optim
    from dist_mnist_tpu.data.pipeline import shard_batch
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.train import create_train_state, make_train_step

    model = get_model("mlp", hidden_units=32)
    opt = optim.adam(0.01)
    with mesh8:
        state = create_train_state(model, opt, jax.random.PRNGKey(0),
                                   small_mnist.train_images[:1])
        step = make_train_step(model, opt, mesh8, donate=False, augment=True)
        batch = shard_batch({"image": small_mnist.train_images[:32],
                             "label": small_mnist.train_labels[:32]}, mesh8)
        losses = []
        for _ in range(10):
            state, out = step(state, batch)
            losses.append(float(out["loss"]))
    assert losses[-1] < losses[0]
