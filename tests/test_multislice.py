"""Multislice (hybrid ICI x DCN) mesh layout — EXECUTED, not just shape math.

Real multislice hardware is unavailable in CI, so `with_fake_slices` tags
the CPU devices with synthetic `slice_index` values; `make_mesh` then takes
the genuine `mesh_utils.create_hybrid_device_mesh` branch (SURVEY.md §5.8 —
the DCN tier of reference rows 21-27), and each placement runs a REAL
train/pipeline step on the unwrapped devices.
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dist_mnist_tpu.cluster.mesh import (
    MeshSpec,
    _SliceFacade,
    make_mesh,
    slice_count,
    with_fake_slices,
)


@pytest.fixture()
def hybrid_spy(monkeypatch):
    """Spy on the hybrid-layout call so tests can assert the DCN branch
    actually executed (enumeration-order fallback would be layout-identical
    on CPU, so device order alone can't distinguish them)."""
    from jax.experimental import mesh_utils

    calls = []
    real = mesh_utils.create_hybrid_device_mesh

    def spy(ici_shape, dcn_shape, **kw):
        calls.append((tuple(ici_shape), tuple(dcn_shape)))
        return real(ici_shape, dcn_shape, **kw)

    monkeypatch.setattr(mesh_utils, "create_hybrid_device_mesh", spy)
    return calls


def test_fake_slices_detected():
    devs = with_fake_slices(jax.devices(), 2)
    assert slice_count(devs) == 2
    assert [d.slice_index for d in devs] == [0, 0, 0, 0, 1, 1, 1, 1]
    # facades forward everything else to the real device
    assert devs[0].platform == jax.devices()[0].platform
    with pytest.raises(ValueError):
        with_fake_slices(jax.devices(), 3)


def test_dcn_on_data_placement_steps(hybrid_spy):
    """2 slices x 4 devices, pure DP: the DCN factor lands on `data`
    (hierarchical gradient all-reduce), and one real train step runs."""
    from dist_mnist_tpu import optim
    from dist_mnist_tpu.data.pipeline import shard_batch
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.parallel.sharding import shard_train_state
    from dist_mnist_tpu.train import create_train_state, make_train_step

    mesh = make_mesh(MeshSpec(data=-1),
                     devices=with_fake_slices(jax.devices(), 2))
    assert hybrid_spy == [((4, 1, 1, 1), (2, 1, 1, 1))]
    # the mesh itself holds REAL devices (facades unwrapped) so it executes
    assert not any(isinstance(d, _SliceFacade) for d in mesh.devices.flat)
    assert len({d.id for d in mesh.devices.flat}) == 8

    model = get_model("mlp", hidden_units=16)
    optimizer = optim.adam(1e-3)
    rng = np.random.default_rng(0)
    batch_np = {
        "image": rng.integers(0, 255, (16, 28, 28, 1), dtype=np.uint8),
        "label": rng.integers(0, 10, (16,), dtype=np.int32),
    }
    with mesh:
        state = create_train_state(model, optimizer, jax.random.PRNGKey(0),
                                   batch_np["image"][:1])
        state = shard_train_state(state, mesh)
        step = make_train_step(model, optimizer, mesh, donate=False)
        new_state, out = step(state, shard_batch(batch_np, mesh))
    assert np.isfinite(float(out["loss"]))
    assert int(jax.device_get(new_state.step)) == 1


def test_dcn_on_pipe_placement_steps(hybrid_spy):
    """data axis can't absorb the slice count -> DCN lands on `pipe`
    (GPipe point-to-point tolerates DCN latency), and a real pipelined
    fwd+bwd runs over that mesh."""
    from dist_mnist_tpu.parallel.pipeline import (
        pipeline_apply,
        stack_stage_params,
    )

    devs = with_fake_slices(jax.devices()[:2], 2)
    mesh = make_mesh(MeshSpec(data=1, pipe=2), devices=devs)
    assert hybrid_spy == [((1, 1, 1, 1), (1, 1, 1, 2))]

    dim = 8
    stages = [
        {"w": jnp.eye(dim) * (1.0 + 0.1 * i), "b": jnp.zeros((dim,))}
        for i in range(2)
    ]
    fn = lambda p, x: jax.nn.relu(x @ p["w"] + p["b"])

    def pp_loss(stacked, x):
        return jnp.sum(
            pipeline_apply(fn, stacked, x, num_microbatches=2, mesh=mesh)
        )

    g = jax.jit(jax.grad(pp_loss))(stack_stage_params(stages),
                                   jnp.ones((4, dim)))
    assert np.isfinite(float(jnp.sum(g["w"])))


def test_layout_fallback_always_warns(monkeypatch, caplog):
    """Topology-aware layout failure must NEVER be silent (VERDICT r2 weak
    item 4): the enumeration-order fallback logs a warning even on a
    single-slice topology."""
    from jax.experimental import mesh_utils

    def boom(*a, **kw):
        raise ValueError("synthetic layout failure")

    monkeypatch.setattr(mesh_utils, "create_device_mesh", boom)
    with caplog.at_level(logging.WARNING, logger="dist_mnist_tpu.cluster.mesh"):
        mesh = make_mesh(MeshSpec(data=-1))
    assert mesh.shape["data"] == 8  # fallback still yields a working mesh
    assert any("falling back" in r.message for r in caplog.records)
    # multislice flavor carries the louder DCN warning
    caplog.clear()
    monkeypatch.setattr(mesh_utils, "create_hybrid_device_mesh", boom)
    with caplog.at_level(logging.WARNING, logger="dist_mnist_tpu.cluster.mesh"):
        make_mesh(MeshSpec(data=-1), devices=with_fake_slices(jax.devices(), 2))
    assert any("MULTISLICE" in r.message for r in caplog.records)


def test_unplaceable_slice_factor_warns(caplog):
    """Neither data nor pipe divisible by the slice count: mesh still
    builds, with the loud latency warning."""
    devs = with_fake_slices(jax.devices()[:6], 2)
    with caplog.at_level(logging.WARNING, logger="dist_mnist_tpu.cluster.mesh"):
        mesh = make_mesh(MeshSpec(data=3, model=2), devices=devs)
    assert mesh.shape == {"data": 3, "model": 2, "seq": 1, "pipe": 1}
    assert any("cannot place" in r.message for r in caplog.records)
