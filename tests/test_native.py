"""Native C++ components: PS demo (async/sync protocol) + prefetch loader."""

import numpy as np
import pytest

pytest.importorskip("ctypes")


# ---------------------------------------------------------------------------
# parameter server


@pytest.fixture(scope="module")
def ps_lib():
    from dist_mnist_tpu.parallel.ps_demo.bindings import build_library

    try:
        build_library()
    except Exception as e:  # pragma: no cover
        pytest.skip(f"toolchain unavailable: {e}")
    return True


def test_ps_pull_push_adam_matches_reference(ps_lib):
    """Native ApplyAdam == the framework's Python/XLA Adam (same rule)."""
    from dist_mnist_tpu.parallel.ps_demo.bindings import ParameterServer

    rng = np.random.default_rng(0)
    p0 = rng.normal(size=(37,)).astype(np.float32)
    grads = [rng.normal(size=(37,)).astype(np.float32) for _ in range(4)]

    ps = ParameterServer([37], lr=0.01)
    ps.init(p0)
    for i, g in enumerate(grads):
        assert ps.push_async(g, local_step=i)
    native, step = ps.pull()
    assert step == 4

    import jax.numpy as jnp

    from dist_mnist_tpu import optim

    opt = optim.adam(0.01)
    params = {"w": jnp.asarray(p0)}
    state = opt.init(params)
    for g in grads:
        updates, state = opt.update({"w": jnp.asarray(g)}, state, params)
        params = optim.apply_updates(params, updates)
    np.testing.assert_allclose(native, np.asarray(params["w"]), rtol=1e-5,
                               atol=1e-6)


def test_ps_async_staleness_drop(ps_lib):
    from dist_mnist_tpu.parallel.ps_demo.bindings import ParameterServer

    ps = ParameterServer([4], lr=0.0, staleness_bound=1)
    ps.init(np.zeros(4, np.float32))
    g = np.ones(4, np.float32)
    assert ps.push_async(g, 0)  # step 0 -> 1
    assert ps.push_async(g, 1)  # step 1 -> 2
    assert not ps.push_async(g, 0)  # 0 + bound(1) < 2 -> dropped
    assert ps.dropped == 1


def test_ps_sync_aggregation_and_tokens(ps_lib):
    """Accumulator averages exactly N fresh grads; tokens broadcast the new
    step; stale grads are dropped (conditional_accumulator_base.h:34-46)."""
    from dist_mnist_tpu.parallel.ps_demo.bindings import ParameterServer

    ps = ParameterServer([2], lr=1.0, b1=0.0, b2=0.0, eps=0.0,
                         replicas_to_aggregate=2)
    ps.init(np.zeros(2, np.float32))
    assert ps.push_sync(np.array([1.0, 3.0], np.float32), 0)
    assert ps.push_sync(np.array([3.0, 1.0], np.float32), 0)
    new_step = ps.chief_sync_once(tokens_per_step=2)
    assert new_step == 1
    assert ps.dequeue_token() == 1
    assert ps.dequeue_token() == 1
    # b1=b2=0, eps=0, lr=1: update = -sqrt(1-0)/1 * g/|g| = -sign... with
    # m=g, v=g^2: delta = -1 * g/sqrt(g^2) = -sign(g); avg grad = (2,2).
    params, _ = ps.pull()
    np.testing.assert_allclose(params, [-1.0, -1.0], rtol=1e-6)
    # a gradient stamped before the take is now stale
    assert not ps.push_sync(np.array([1.0, 1.0], np.float32), 0)
    assert ps.push_sync(np.array([1.0, 1.0], np.float32), 1)


@pytest.mark.slow
def test_ps_demo_end_to_end_both_modes(ps_lib, small_mnist):
    from dist_mnist_tpu.parallel.ps_demo import run_demo

    sync = run_demo(mode="sync", num_workers=2, train_steps=120,
                    dataset=small_mnist)
    assert sync["global_step"] >= 120
    assert sync["test_accuracy"] > 0.8
    async_ = run_demo(mode="async", num_workers=2, train_steps=120,
                      dataset=small_mnist)
    assert async_["global_step"] >= 120
    assert async_["test_accuracy"] > 0.6  # staleness costs some accuracy
    assert sum(async_["per_worker_applies"]) > 0


# ---------------------------------------------------------------------------
# native loader


@pytest.fixture(scope="module")
def loader_lib():
    from dist_mnist_tpu.data.native.batcher import build_library

    try:
        build_library()
    except Exception as e:  # pragma: no cover
        pytest.skip(f"toolchain unavailable: {e}")
    return True


def test_native_loader_deterministic_epochs(loader_lib, mesh8, small_mnist):
    from dist_mnist_tpu.data.native import NativeBatcher

    a = NativeBatcher(small_mnist, 64, mesh8, seed=7)
    b = NativeBatcher(small_mnist, 64, mesh8, seed=7)
    seen = []
    for _ in range(10):
        ia, la, sa = a.next_local()
        ib, lb, sb = b.next_local()
        assert sa == sb
        np.testing.assert_array_equal(ia, ib)
        np.testing.assert_array_equal(la, lb)
        seen.append((ia, la))
    # batches really are gathered rows of the dataset
    img, lab = seen[0]
    n = small_mnist.train_images.shape[0]
    # find the first row in the dataset (exact match must exist)
    row = img[0]
    matches = np.where(
        (small_mnist.train_images.reshape(n, -1) == row.reshape(-1)).all(1)
    )[0]
    assert len(matches) >= 1
    assert small_mnist.train_labels[matches[0]] == lab[0] or len(matches) > 1
    a.close()
    b.close()


def test_native_loader_epoch_coverage(loader_lib, mesh8, small_mnist):
    """One epoch = each index used exactly once (shuffled without
    replacement), matching the Python pipeline's contract."""
    from dist_mnist_tpu.data.native import NativeBatcher

    n = small_mnist.train_images.shape[0]
    batch = 512
    per_epoch = n // batch
    nb = NativeBatcher(small_mnist, batch, mesh8, seed=3)
    label_counts = np.zeros(10, np.int64)
    for _ in range(per_epoch):
        _, lab, _ = nb.next_local()
        label_counts += np.bincount(lab, minlength=10)
    expected = np.bincount(small_mnist.train_labels[: per_epoch * batch],
                           minlength=10)
    # same multiset of labels per epoch (indices are a permutation)
    assert label_counts.sum() == per_epoch * batch
    full = np.bincount(small_mnist.train_labels, minlength=10)
    assert (label_counts <= full).all()
    nb.close()


def test_native_loader_rejects_bad_batch(loader_lib, mesh8, small_mnist):
    from dist_mnist_tpu.data.native import NativeBatcher

    with pytest.raises(ValueError):
        NativeBatcher(small_mnist, 1 << 20, mesh8)


def test_native_loader_yields_sharded_batches(loader_lib, mesh8, small_mnist):
    from dist_mnist_tpu.data.native import NativeBatcher

    nb = NativeBatcher(small_mnist, 64, mesh8, seed=0)
    batch = next(iter(nb))
    assert batch["image"].shape == (64, 28, 28, 1)
    shard = batch["image"].sharding.shard_shape(batch["image"].shape)
    assert shard[0] == 8
    nb.close()
