"""Orbax checkpoint manager: round-trip, retention, restore-or-init,
kill/resume (SURVEY.md §3.5 / §5.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dist_mnist_tpu import optim
from dist_mnist_tpu.checkpoint import CheckpointManager
from dist_mnist_tpu.models import get_model
from dist_mnist_tpu.parallel.sharding import shard_train_state
from dist_mnist_tpu.train import create_train_state


@pytest.fixture()
def state(mesh8):
    model = get_model("mlp", hidden_units=16)
    opt = optim.adam(0.01)
    with mesh8:
        s = create_train_state(
            model, opt, jax.random.PRNGKey(0), np.zeros((1, 28, 28, 1), np.uint8)
        )
        return shard_train_state(s, mesh8)


def test_save_restore_roundtrip(tmp_path, state):
    mgr = CheckpointManager(tmp_path, async_save=False)
    assert mgr.latest_step() is None
    assert mgr.save(state)
    mgr.wait()
    assert mgr.latest_step() == 0
    import dataclasses

    zeroed = dataclasses.replace(
        state,
        params=jax.tree.map(jnp.zeros_like, state.params),
        step=jnp.int32(99),
    )
    restored = mgr.restore(zeroed)
    assert restored is not None
    assert restored.step_int == 0
    np.testing.assert_array_equal(
        np.asarray(restored.params["hid"]["w"]),
        np.asarray(state.params["hid"]["w"]),
    )
    # shardings survive restore (collective restore on multi-host)
    assert restored.params["hid"]["w"].sharding == state.params["hid"]["w"].sharding
    mgr.close()


def test_restore_or_init(tmp_path, state):
    mgr = CheckpointManager(tmp_path, async_save=False)
    out, restored = mgr.restore_or_init(state)
    assert not restored and out is state
    mgr.save(state)
    mgr.wait()
    out, restored = mgr.restore_or_init(state)
    assert restored
    mgr.close()


def test_dedupe_same_step(tmp_path, state):
    mgr = CheckpointManager(tmp_path, async_save=False)
    assert mgr.save(state)
    mgr.wait()
    assert not mgr.save(state)  # same step: deduped
    mgr.close()


def test_kill_resume_cycle(tmp_path, mesh8, state):
    """Simulated preemption: save at step N in one manager, 'restart' with a
    fresh manager + fresh init, resume from N (the SessionManager
    prepare_session flow, §3.2)."""
    import dataclasses

    mgr1 = CheckpointManager(tmp_path, async_save=False)
    advanced = dataclasses.replace(state, step=jnp.int32(123))
    mgr1.save(advanced)
    mgr1.wait()
    mgr1.close()
    # "process restart": new manager, newly-initialized state
    mgr2 = CheckpointManager(tmp_path, async_save=False)
    fresh = dataclasses.replace(
        state, params=jax.tree.map(jnp.zeros_like, state.params)
    )
    resumed, was_restored = mgr2.restore_or_init(fresh)
    assert was_restored
    assert resumed.step_int == 123
    np.testing.assert_array_equal(
        np.asarray(resumed.params["hid"]["w"]),
        np.asarray(state.params["hid"]["w"]),
    )
    mgr2.close()


def test_max_to_keep(tmp_path, state):
    import dataclasses

    mgr = CheckpointManager(tmp_path, max_to_keep=2, async_save=False)
    for s in (1, 2, 3, 4):
        mgr.save(dataclasses.replace(state, step=jnp.int32(s)))
        mgr.wait()
    steps = mgr._mgr.all_steps()
    assert mgr.latest_step() == 4
    assert len(steps) <= 2
    mgr.close()
