"""Orbax checkpoint manager: round-trip, retention, restore-or-init,
kill/resume (SURVEY.md §3.5 / §5.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dist_mnist_tpu import optim
from dist_mnist_tpu.checkpoint import CheckpointManager
from dist_mnist_tpu.models import get_model
from dist_mnist_tpu.parallel.sharding import shard_train_state
from dist_mnist_tpu.train import create_train_state


@pytest.fixture()
def state(mesh8):
    model = get_model("mlp", hidden_units=16)
    opt = optim.adam(0.01)
    with mesh8:
        s = create_train_state(
            model, opt, jax.random.PRNGKey(0), np.zeros((1, 28, 28, 1), np.uint8)
        )
        return shard_train_state(s, mesh8)


def test_save_restore_roundtrip(tmp_path, state):
    mgr = CheckpointManager(tmp_path, async_save=False)
    assert mgr.latest_step() is None
    assert mgr.save(state)
    mgr.wait()
    assert mgr.latest_step() == 0
    import dataclasses

    zeroed = dataclasses.replace(
        state,
        params=jax.tree.map(jnp.zeros_like, state.params),
        step=jnp.int32(99),
    )
    restored = mgr.restore(zeroed)
    assert restored is not None
    assert restored.step_int == 0
    np.testing.assert_array_equal(
        np.asarray(restored.params["hid"]["w"]),
        np.asarray(state.params["hid"]["w"]),
    )
    # shardings survive restore (collective restore on multi-host)
    assert restored.params["hid"]["w"].sharding == state.params["hid"]["w"].sharding
    mgr.close()


def test_restore_or_init(tmp_path, state):
    mgr = CheckpointManager(tmp_path, async_save=False)
    out, restored = mgr.restore_or_init(state)
    assert not restored and out is state
    mgr.save(state)
    mgr.wait()
    out, restored = mgr.restore_or_init(state)
    assert restored
    mgr.close()


def test_dedupe_same_step(tmp_path, state):
    mgr = CheckpointManager(tmp_path, async_save=False)
    assert mgr.save(state)
    mgr.wait()
    assert not mgr.save(state)  # same step: deduped
    mgr.close()


def test_kill_resume_cycle(tmp_path, mesh8, state):
    """Simulated preemption: save at step N in one manager, 'restart' with a
    fresh manager + fresh init, resume from N (the SessionManager
    prepare_session flow, §3.2)."""
    import dataclasses

    mgr1 = CheckpointManager(tmp_path, async_save=False)
    advanced = dataclasses.replace(state, step=jnp.int32(123))
    mgr1.save(advanced)
    mgr1.wait()
    mgr1.close()
    # "process restart": new manager, newly-initialized state
    mgr2 = CheckpointManager(tmp_path, async_save=False)
    fresh = dataclasses.replace(
        state, params=jax.tree.map(jnp.zeros_like, state.params)
    )
    resumed, was_restored = mgr2.restore_or_init(fresh)
    assert was_restored
    assert resumed.step_int == 123
    np.testing.assert_array_equal(
        np.asarray(resumed.params["hid"]["w"]),
        np.asarray(state.params["hid"]["w"]),
    )
    mgr2.close()


def test_block_layout_flip_on_restore(tmp_path, mesh8):
    """Save a ViT trained with scan_blocks=False (unrolled block0..N),
    restore into a scan_blocks=True (stacked `blocks`) target: the manager
    detects the structure mismatch and converts — params AND Adam slots —
    instead of dying with an orbax tree error (VERDICT r3 weak 7)."""
    opt = optim.adam(0.01)
    sample = np.zeros((1, 32, 32, 3), np.uint8)
    kw = dict(depth=2, dim=32, heads=4, patch=8, pool="mean",
              compute_dtype=jnp.float32)
    unrolled = get_model("vit_tiny", scan_blocks=False, **kw)
    scanned = get_model("vit_tiny", scan_blocks=True, **kw)
    with mesh8:
        u_state = shard_train_state(
            create_train_state(unrolled, opt, jax.random.PRNGKey(0), sample),
            mesh8)
        s_state = shard_train_state(
            create_train_state(scanned, opt, jax.random.PRNGKey(1), sample),
            mesh8)
    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(u_state)
    mgr.wait()
    restored = mgr.restore(s_state)
    assert restored is not None
    assert "blocks" in restored.params and "block0" not in restored.params
    # same-seed init means restored stacked row i == unrolled block i
    for i in range(2):
        np.testing.assert_array_equal(
            np.asarray(restored.params["blocks"]["attn"]["qkv"]["w"][i]),
            np.asarray(u_state.params["block{}".format(i)]["attn"]["qkv"]["w"]),
        )
    # optimizer slots converted too (Adam m mirrors params structurally)
    m = restored.opt_state["m"] if isinstance(restored.opt_state, dict) \
        else next(s for s in restored.opt_state
                  if isinstance(s, dict) and "m" in s)["m"]
    assert "blocks" in m
    # shardings re-placed to the target's
    assert (restored.params["blocks"]["attn"]["qkv"]["w"].sharding
            == s_state.params["blocks"]["attn"]["qkv"]["w"].sharding)
    mgr.close()
    # and the reverse direction: scanned checkpoint -> unrolled target
    mgr2 = CheckpointManager(tmp_path / "rev", async_save=False)
    mgr2.save(s_state)
    mgr2.wait()
    rev = mgr2.restore(u_state)
    assert rev is not None and "block0" in rev.params
    np.testing.assert_array_equal(
        np.asarray(rev.params["block1"]["attn"]["qkv"]["w"]),
        np.asarray(s_state.params["blocks"]["attn"]["qkv"]["w"][1]),
    )
    mgr2.close()


def test_pre_metric_checkpoint_restores(tmp_path, mesh8):
    """A checkpoint written before the model grew `_metric` model-state
    entries (the MoE health stats) must still restore: the manager retries
    without them and refills from the target's initial values (additive
    metadata must never orphan a checkpoint)."""
    import dataclasses

    opt = optim.adam(0.01)
    sample = np.zeros((1, 32, 32, 3), np.uint8)
    model = get_model("vit_tiny", depth=1, dim=32, heads=4, patch=8,
                      pool="mean", mlp_impl="moe", n_experts=2,
                      compute_dtype=jnp.float32)
    with mesh8:
        full = shard_train_state(
            create_train_state(model, opt, jax.random.PRNGKey(0), sample),
            mesh8)
    # simulate the old on-disk format: model_state without metric keys
    old_format = dataclasses.replace(
        full,
        model_state={k: v for k, v in full.model_state.items()
                     if not k.endswith("_metric")},
        step=jnp.int32(7),
    )
    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(old_format)
    mgr.wait()
    restored = mgr.restore(full)
    assert restored is not None and restored.step_int == 7
    assert set(restored.model_state) == set(full.model_state)
    np.testing.assert_array_equal(
        np.asarray(restored.params["block0"]["moe"]["gate"]),
        np.asarray(full.params["block0"]["moe"]["gate"]),
    )
    mgr.close()


def test_corrupt_restore_raises_original_error(tmp_path, mesh8, state):
    """A genuinely incompatible checkpoint (different model entirely) must
    surface the ORIGINAL structure error, not a layout-flip retry's."""
    import dataclasses

    opt = optim.adam(0.01)
    sample = np.zeros((1, 32, 32, 3), np.uint8)
    vit = get_model("vit_tiny", depth=2, dim=32, heads=4, patch=8,
                    pool="mean", compute_dtype=jnp.float32)
    with mesh8:
        vit_state = shard_train_state(
            create_train_state(vit, opt, jax.random.PRNGKey(0), sample),
            mesh8)
    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(state)  # the MLP state from the fixture
    mgr.wait()
    with pytest.raises(Exception) as ei:
        mgr.restore(vit_state)  # vit target vs mlp checkpoint: hopeless
    # the surfaced error is the ORIGINAL mismatch (mentions the real
    # checkpoint/target trees), not a layout-flip retry artifact
    assert "hid" in str(ei.value) or "patch" in str(ei.value) or \
        "structure" in str(ei.value).lower(), str(ei.value)[:300]
    mgr.close()


def test_max_to_keep(tmp_path, state):
    import dataclasses

    mgr = CheckpointManager(tmp_path, max_to_keep=2, async_save=False)
    for s in (1, 2, 3, 4):
        mgr.save(dataclasses.replace(state, step=jnp.int32(s)))
        mgr.wait()
    steps = mgr._mgr.all_steps()
    assert mgr.latest_step() == 4
    assert len(steps) <= 2
    mgr.close()


def test_io_error_skips_healing_ladder(tmp_path, state, monkeypatch):
    """A non-structure failure (I/O, corruption) must propagate
    immediately — the healing ladder used to run up to 3 extra full
    restore attempts first (advisor r4)."""
    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(state)
    mgr.wait()
    calls = []
    monkeypatch.setattr(
        mgr, "_restore_with_structure_healing",
        lambda *a, **k: calls.append(1))
    monkeypatch.setattr(
        mgr, "_restore_into",
        lambda *a, **k: (_ for _ in ()).throw(OSError("disk on fire")))
    with pytest.raises(OSError, match="disk on fire"):
        mgr.restore(state)
    assert not calls  # ladder never consulted
    mgr.close()


def test_partial_metric_checkpoint_restores(tmp_path, mesh8):
    """A checkpoint whose model_state carries an OLDER `_metric` set (some
    but not all of the target's) heals: the ladder trims the target to
    the on-disk metric keys (read from checkpoint metadata) and refills
    the rest from the target's initial values (code review r5 — stripping
    ALL metrics mismatched in the other direction and orphaned every MoE
    checkpoint saved before a new metric was added)."""
    import dataclasses

    opt = optim.adam(0.01)
    sample = np.zeros((1, 32, 32, 3), np.uint8)
    moe = get_model("vit_tiny", depth=2, dim=32, heads=4, patch=8,
                    pool="mean", compute_dtype=jnp.float32,
                    mlp_impl="moe", n_experts=2)
    with mesh8:
        st = shard_train_state(
            create_train_state(moe, opt, jax.random.PRNGKey(0), sample),
            mesh8)
    # simulate the pre-ep_engaged checkpoint: drop one metric entry
    old = dataclasses.replace(st, model_state={
        k: v for k, v in st.model_state.items()
        if k != "moe_ep_engaged_metric"})
    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(old)
    mgr.wait()
    restored = mgr.restore(st)
    assert sorted(restored.model_state) == sorted(st.model_state)
    # the refilled entry carries the target's initial value
    assert float(restored.model_state["moe_ep_engaged_metric"]) == 0.0
    mgr.close()


def test_flipped_layout_plus_partial_metrics_heals(tmp_path, mesh8):
    """The deepest healing rung: a checkpoint saved in the UNROLLED block
    layout with an OLDER metric set restores into a scanned-layout target
    carrying a newer metric — exercising the 'flipped layout + on-disk
    _metric entries only' rung added in r5."""
    import dataclasses

    opt = optim.adam(0.01)
    sample = np.zeros((1, 32, 32, 3), np.uint8)
    kw = dict(depth=2, dim=32, heads=4, patch=8, pool="mean",
              compute_dtype=jnp.float32, mlp_impl="moe", n_experts=2)
    unrolled = get_model("vit_tiny", scan_blocks=False, **kw)
    scanned = get_model("vit_tiny", scan_blocks=True, **kw)
    with mesh8:
        st_unrolled = shard_train_state(
            create_train_state(unrolled, opt, jax.random.PRNGKey(0),
                               sample), mesh8)
        st_scanned = shard_train_state(
            create_train_state(scanned, opt, jax.random.PRNGKey(0),
                               sample), mesh8)
    old = dataclasses.replace(st_unrolled, model_state={
        k: v for k, v in st_unrolled.model_state.items()
        if k != "moe_ep_engaged_metric"})
    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(old)
    mgr.wait()
    restored = mgr.restore(st_scanned)
    assert sorted(restored.model_state) == sorted(st_scanned.model_state)
    # layout actually converted: stacked blocks, matching init values
    assert "blocks" in restored.params and "block0" not in restored.params
    np.testing.assert_allclose(
        np.asarray(restored.params["blocks"]["attn"]["qkv"]["w"][0]),
        np.asarray(st_unrolled.params["block0"]["attn"]["qkv"]["w"]),
        rtol=1e-6)
    mgr.close()


def test_healing_classifier_ignores_error_wording(tmp_path, state,
                                                  monkeypatch):
    """A structure mismatch must enter the healing ladder regardless of how
    the underlying Orbax version WORDS its ValueError (ADVICE r5): the
    classifier probes the on-disk tree metadata, not the message. Simulated
    by re-raising the first restore failure with nonsense wording."""
    import dataclasses

    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(state)
    mgr.wait()
    # target with an extra _metric entry: genuinely mismatched vs on-disk
    target = dataclasses.replace(
        state, model_state={**state.model_state,
                            "bogus_health_metric": jnp.zeros(())})
    orig = mgr._restore_into
    fired = []

    def reworded(step, tgt):
        try:
            return orig(step, tgt)
        except Exception:
            if not fired:  # only the FIRST failure gets reworded
                fired.append(1)
                raise ValueError("kaboom: completely novel phrasing 0x7f")
            raise

    monkeypatch.setattr(mgr, "_restore_into", reworded)
    restored = mgr.restore(target)  # heals despite the unknown wording
    assert restored is not None
    assert "bogus_health_metric" in restored.model_state
    mgr.close()


def test_non_structural_keyerror_skips_healing(tmp_path, state, monkeypatch):
    """A KeyError naming a key that exists in NEITHER the target tree nor
    the on-disk metadata is not structural — it must propagate immediately
    instead of buying extra full restore attempts."""
    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(state)
    mgr.wait()
    calls = []
    monkeypatch.setattr(
        mgr, "_restore_with_structure_healing",
        lambda *a, **k: calls.append(1))
    monkeypatch.setattr(
        mgr, "_restore_into",
        lambda *a, **k: (_ for _ in ()).throw(
            KeyError("definitely_not_a_tree_key")))
    with pytest.raises(KeyError, match="definitely_not_a_tree_key"):
        mgr.restore(state)
    assert not calls
    mgr.close()


def test_structural_keyerror_enters_healing(tmp_path, state, monkeypatch):
    """A KeyError naming an actual tree key (here a model_state/params-tree
    name) IS structural evidence and must reach the ladder."""
    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(state)
    mgr.wait()
    calls = []
    monkeypatch.setattr(
        mgr, "_restore_with_structure_healing",
        lambda step, tgt, err: calls.append(1) or state)
    key = next(iter(state.params))  # a real params tree key
    monkeypatch.setattr(
        mgr, "_restore_into",
        lambda *a, **k: (_ for _ in ()).throw(KeyError(key)))
    assert mgr.restore(state) is state
    assert calls == [1]
    mgr.close()


def test_restore_weights_no_optimizer(tmp_path, state):
    """serve-side weights-only restore: params/model_state come back (with
    the requested shardings), the optimizer slots never enter the target."""
    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(state)
    mgr.wait()
    absify = lambda t: jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
        t)
    out = mgr.restore_weights(absify(state.params),
                              absify(state.model_state))
    assert out is not None
    step, params, model_state = out
    assert step == state.step_int
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    mgr.close()


def test_restore_weights_empty_dir(tmp_path):
    mgr = CheckpointManager(tmp_path, async_save=False)
    assert mgr.restore_weights({}, {}) is None
    mgr.close()


# -- corruption fallback ladder (faults PR) ----------------------------------

def _save_steps(mgr, state, steps):
    """Save `state` at each step number (orbax keys saves on state.step)."""
    import dataclasses

    for s in steps:
        mgr.save(dataclasses.replace(state, step=jnp.int32(s)))
    mgr.wait()


def test_corrupt_latest_falls_back_and_quarantines(tmp_path, state):
    """An unreadable latest checkpoint (truncated payload — the partial
    write a preempted saver leaves behind) must not brick the restart:
    restore quarantines it and falls back to the previous step."""
    from dist_mnist_tpu.faults.inject import _corrupt_step_dir

    mgr = CheckpointManager(tmp_path, async_save=False)
    _save_steps(mgr, state, [0, 1])
    assert _corrupt_step_dir(tmp_path / "1") is not None
    restored = mgr.restore(state)
    assert restored is not None and restored.step_int == 0
    assert (tmp_path / "quarantine" / "step_1").exists()
    assert not (tmp_path / "1").exists()
    # the manager stays usable: save after quarantine, restore the new latest
    _save_steps(mgr, state, [2])
    assert mgr.latest_step(refresh=True) == 2
    assert mgr.restore(state).step_int == 2
    mgr.close()


def test_corrupt_only_checkpoint_raises_original_error(tmp_path, state):
    """No older step to fall back to: the ORIGINAL read error propagates
    (truly-unrecoverable must stay loud, not return None as cold-start)."""
    from dist_mnist_tpu.faults.inject import _corrupt_step_dir

    mgr = CheckpointManager(tmp_path, async_save=False)
    _save_steps(mgr, state, [0])
    _corrupt_step_dir(tmp_path / "0")
    with pytest.raises(ValueError, match="(?i)out_of_range|error reading"):
        mgr.restore(state)
    mgr.close()


def test_max_restore_fallbacks_zero_disables_ladder(tmp_path, state):
    from dist_mnist_tpu.faults.inject import _corrupt_step_dir

    mgr = CheckpointManager(tmp_path, async_save=False,
                            max_restore_fallbacks=0)
    _save_steps(mgr, state, [0, 1])
    _corrupt_step_dir(tmp_path / "1")
    with pytest.raises(ValueError):
        mgr.restore(state)
    assert (tmp_path / "1").exists()  # nothing quarantined
    mgr.close()


def test_structural_mismatch_never_quarantines(tmp_path, state, monkeypatch):
    """The fallback ladder is for READ corruption only: a structural
    KeyError (healing ladder territory) must not eat checkpoints."""
    mgr = CheckpointManager(tmp_path, async_save=False)
    _save_steps(mgr, state, [0, 1])
    monkeypatch.setattr(
        mgr, "_restore_step",
        lambda *a, **k: (_ for _ in ()).throw(KeyError("params.missing")))
    with pytest.raises(KeyError):
        mgr.restore(state)
    assert (tmp_path / "1").exists() and not (tmp_path / "quarantine").exists()
