"""Tier-1 model-zoo serving tests (serve/zoo.py + the engine's 2-D grid):
variable-length masked serving, MoE capacity at inference, cross-strategy
(fsdp-trained -> TP-served) restore, the per-device memory budget, and the
batcher's oversized-window split. All CPU-mesh; models are kept tiny
(depth 1, dim 16-32) so every compile stays in the tier-1 time budget."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dist_mnist_tpu.models.registry import get_model
from dist_mnist_tpu.parallel.sharding import resolve_rules
from dist_mnist_tpu.serve import (
    InferenceServer,
    SeqGrid,
    ServeConfig,
    ServeMemoryBudgetError,
    build_zoo_engine,
    default_seq_grid,
    load_for_serving,
    parse_seq_buckets,
    supports_mask,
)
from dist_mnist_tpu.serve.engine import CompiledModelCache, InferenceEngine

IMAGE_SHAPE = (16, 16, 3)  # native height 16, patch 4 -> ladder 4, 8, 16


def _tiny_vit(**kw):
    kwargs = dict(depth=1, dim=16, heads=2, patch=4, pool="mean")
    kwargs.update(kw)
    return get_model("vit_tiny", **kwargs)


def _images(n, h=16, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=(n, h, *IMAGE_SHAPE[1:]),
                        dtype=np.uint8)


def _reference_logits(model, params, ms, images):
    """The engine's normalization contract (x/255) applied directly."""
    x = jnp.asarray(images, jnp.float32) / 255.0
    logits, _ = model.apply(params, ms, x, train=False)
    return np.asarray(logits)


@pytest.fixture(scope="module")
def zoo_engine(mesh8):
    """Maskable tiny ViT behind the auto height ladder on the 8-way mesh."""
    model = _tiny_vit()
    params, ms = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, *IMAGE_SHAPE), jnp.float32))
    return InferenceEngine(
        model, params, ms, mesh8, model_name="vit_zoo",
        image_shape=IMAGE_SHAPE, rules=resolve_rules("dp"), max_bucket=16,
        seq_grid=default_seq_grid(IMAGE_SHAPE, 4),
    )


# -- SeqGrid planning layer ---------------------------------------------------

def test_seq_grid_buckets_and_tokens():
    grid = default_seq_grid(IMAGE_SHAPE, 4)
    assert grid.heights == (4, 8, 16)
    assert [grid.bucket_for(h) for h in (1, 4, 5, 8, 9, 16)] == \
        [4, 4, 8, 8, 16, 16]
    with pytest.raises(ValueError, match="native"):
        grid.bucket_for(17)
    # 4 tokens per patch-row of width 16 / patch 4
    assert grid.n_tokens(4) == 4 and grid.n_tokens(16) == 16
    mask = grid.mask([4, 8], bucket_h=8)
    assert mask.shape == (2, 8)
    assert mask[0].tolist() == [True] * 4 + [False] * 4
    assert mask[1].all()


def test_seq_grid_validation_and_parse():
    with pytest.raises(ValueError, match="patch"):
        SeqGrid(native_height=16, width=16, channels=3, patch=4,
                heights=(6, 16))
    assert parse_seq_buckets(None, IMAGE_SHAPE, 4) is None
    assert parse_seq_buckets("auto", IMAGE_SHAPE, 4).heights == (4, 8, 16)
    # native appended when the explicit spec leaves it out
    assert parse_seq_buckets("8", IMAGE_SHAPE, 4).heights == (8, 16)


def test_supports_mask_gates_kernel_attention():
    assert supports_mask(_tiny_vit())
    # flash is maskable since the variable-length kernel landed: zoo
    # prefix masks become per-row lengths (ops/pallas/flash_attention)
    assert supports_mask(_tiny_vit(attention_impl="flash"))
    assert not supports_mask(_tiny_vit(attention_impl="ring"))
    assert not supports_mask(get_model("mlp"))


# -- 2-D grid: keys, prewarm, no-recompile hot path ---------------------------

def test_grid_cache_keys_distinguish_batch_seq_and_variant(zoo_engine):
    e = zoo_engine
    assert e.grid() == [(8, 4), (8, 8), (8, 16), (16, 4), (16, 8), (16, 16)]
    # dense native, masked native, and masked sub-native are DIFFERENT
    # programs — one key each, per batch bucket
    keys = {e._key(8), e._key(8, 16), e._key(8, 8), e._key(16, 8)}
    assert len(keys) == 4


def test_prewarm_compiles_grid_then_zero_recompiles(zoo_engine):
    e = zoo_engine
    n = e.prewarm()
    # per batch bucket: 1 dense native + one masked program per height
    assert n == len(e.buckets()) * (1 + len(e.seq_grid.heights))
    misses0 = e.cache.stats()["misses"]
    # arbitrary (batch, height) traffic over the warmed grid: heights that
    # round up into every bucket, including the masked-native cell (h=9..16
    # rounds into 16 but still needs its padding masked when short)
    for n_req, h in [(1, 3), (5, 8), (2, 12), (16, 16), (3, 5)]:
        out = e.predict(_images(n_req, h=h, seed=h))
        assert out.shape == (n_req, 10)
    assert e.cache.stats()["misses"] == misses0, "hot-path recompile"
    assert e.prewarm() == 0  # idempotent: everything already resident
    assert sum(e.seq_bucket_counts.values()) >= 5


def test_masked_short_request_matches_unpadded_forward(zoo_engine):
    e = zoo_engine
    model, params, ms = e.model, e.params, e.model_state
    # bf16 compute: batch padding + the masked program shift reduction
    # order by 1-2 ulp; a WRONG mask moves logits by whole units
    for h in (4, 8, 12):
        images = _images(3, h=h, seed=h)
        got = e.predict(images)
        want = _reference_logits(model, params, ms, images)
        np.testing.assert_allclose(got, want, atol=0.04, rtol=0.04)


def test_native_dense_path_is_maskless(zoo_engine):
    e = zoo_engine
    images = _images(4, h=16, seed=1)
    got = e.predict(images)
    want = _reference_logits(e.model, e.params, e.model_state, images)
    np.testing.assert_allclose(got, want, atol=0.04, rtol=0.04)
    # full-height traffic routed through the DENSE (maskless) program
    assert e.cache.per_key[e._key(8)]["hits"] >= 1


# -- MoE serving --------------------------------------------------------------

def test_moe_serve_matches_train_forward_and_reports_drops(mesh_tp):
    # n_experts == model-axis size -> the expert-parallel moe_ffn path
    model = _tiny_vit(mlp_impl="moe", n_experts=2)
    params, ms = model.init(jax.random.PRNGKey(1),
                            jnp.zeros((1, *IMAGE_SHAPE), jnp.float32))
    assert "moe_drop_fraction_metric" in ms
    engine = InferenceEngine(
        model, params, ms, mesh_tp, model_name="vit_moe",
        image_shape=IMAGE_SHAPE, rules=resolve_rules("tp"), max_bucket=8,
    )
    images = _images(8, h=16, seed=2)
    got = engine.predict(images)
    want = _reference_logits(model, params, ms, images)
    # bf16 + expert-parallel dispatch vs the unsharded reference: ulp-level
    np.testing.assert_allclose(got, want, atol=0.06, rtol=0.06)
    drop = engine.last_moe_drop_fraction
    assert drop is not None and 0.0 <= drop <= 1.0


def test_moe_capacity_factor_override_via_zoo_factory(mesh_tp):
    import dataclasses as _dc

    model = _tiny_vit(mlp_impl="moe", n_experts=2)
    params, ms = model.init(jax.random.PRNGKey(1),
                            jnp.zeros((1, *IMAGE_SHAPE), jnp.float32))
    bundle = _dc.make_dataclass(
        "B", ["model", "params", "model_state", "image_shape", "rules"])(
        model, params, ms, IMAGE_SHAPE, resolve_rules("tp"))
    engine = build_zoo_engine(bundle, mesh_tp, model_name="vit_moe",
                              max_bucket=8, moe_capacity_factor=0.25)
    assert engine.model.moe_capacity_factor == 0.25
    engine.predict(_images(8, h=16, seed=3))
    # a starved capacity factor must SURFACE drops, not silently truncate
    assert engine.last_moe_drop_fraction is not None
    # a dense model refuses the knob instead of ignoring it
    dense = _dc.make_dataclass(
        "D", ["model", "params", "model_state", "image_shape", "rules"])(
        get_model("mlp"), None, None, (28, 28, 1), resolve_rules("dp"))
    with pytest.raises(ValueError, match="moe_capacity_factor"):
        build_zoo_engine(dense, mesh_tp, model_name="mlp",
                         moe_capacity_factor=2.0)


# -- sharded serving + cross-strategy restore ---------------------------------

def test_cross_strategy_restore_fsdp_to_tp_bit_parity(mesh_tp, tmp_path):
    """A checkpoint written under one strategy restores bit-identically
    under another: the serve rules only change PLACEMENT."""
    import dataclasses

    from dist_mnist_tpu.checkpoint.manager import CheckpointManager
    from dist_mnist_tpu.configs import get_config
    from dist_mnist_tpu.optim import adam
    from dist_mnist_tpu.train.state import create_train_state

    cfg = get_config("vit_tiny_cifar")
    cfg = dataclasses.replace(
        cfg, model_kwargs={"depth": 1, "dim": 16, "heads": 2,
                           "pool": "mean"},
        sharding_rules="fsdp")
    model = get_model(cfg.model, **cfg.model_kwargs)
    sample = jnp.zeros((1, 32, 32, 3), jnp.float32)
    state = create_train_state(model, adam(1e-3),
                               jax.random.PRNGKey(cfg.seed), sample)
    state = dataclasses.replace(state, step=jnp.asarray(7, jnp.int32))
    mgr = CheckpointManager(tmp_path / "ckpt", async_save=False)
    assert mgr.save(state)
    mgr.wait()
    mgr.close()

    served_tp = load_for_serving(cfg, mesh_tp, checkpoint_dir=tmp_path / "ckpt",
                                 sharding_rules="tp")
    served_dp = load_for_serving(cfg, mesh_tp, checkpoint_dir=tmp_path / "ckpt",
                                 sharding_rules="dp")
    assert served_tp.restored and served_dp.restored
    for a, b in zip(jax.tree.leaves(served_tp.params),
                    jax.tree.leaves(served_dp.params)):
        assert np.array_equal(np.asarray(a), np.asarray(b))

    eng_tp = build_zoo_engine(served_tp, mesh_tp, model_name="vit_tp",
                              max_bucket=8)
    eng_dp = build_zoo_engine(served_dp, mesh_tp, model_name="vit_dp",
                              max_bucket=8)
    images = np.random.default_rng(5).integers(
        0, 256, size=(8, 32, 32, 3), dtype=np.uint8)
    # same VALUES, different placements: logits agree to bf16 reduction-
    # order noise (TP partial-sums across the model axis)
    np.testing.assert_allclose(eng_tp.predict(images),
                               eng_dp.predict(images),
                               atol=0.04, rtol=0.04)
    # TP weights serve resident-sharded: strictly fewer bytes per device
    assert eng_tp.state_bytes_per_device()["param_bytes"] < \
        eng_dp.state_bytes_per_device()["param_bytes"]


def test_fsdp_restore_serves_at_a_fraction_of_dense_bytes(mesh8, tmp_path):
    """The acceptance shape: an fsdp-placed restore holds ~1/data-axis of
    the replicated dense per-device bytes (big matmul params dominate)."""
    fsdp = load_for_serving("mlp_mnist", mesh8, sharding_rules="fsdp")
    dense = load_for_serving("mlp_mnist", mesh8)
    eng_f = build_zoo_engine(fsdp, mesh8, model_name="mlp_f", max_bucket=8)
    eng_d = build_zoo_engine(dense, mesh8, model_name="mlp_d", max_bucket=8)
    f = eng_f.state_bytes_per_device()["param_bytes"]
    d = eng_d.state_bytes_per_device()["param_bytes"]
    assert f < 0.25 * d, f"fsdp {f} B/device vs dense {d} B/device"
    images = np.random.default_rng(0).integers(
        0, 256, size=(4, 28, 28, 1), dtype=np.uint8)
    np.testing.assert_allclose(eng_f.predict(images), eng_d.predict(images),
                               atol=1e-5, rtol=1e-5)


# -- memory budget ------------------------------------------------------------

class _FakeExe:
    def __init__(self, nbytes):
        self._n = nbytes

    def memory_analysis(self):
        import types

        return types.SimpleNamespace(generated_code_size_in_bytes=self._n,
                                     temp_size_in_bytes=0)


def test_budget_lru_evicts_coldest_and_counts():
    cache = CompiledModelCache()
    cache.set_budget(1000, base_bytes=400)
    cache.get("a", lambda: _FakeExe(300))
    cache.get("b", lambda: _FakeExe(300))  # resident 1000 == budget: fits
    cache.get("a", lambda: _FakeExe(300))  # touch a -> b is now coldest
    cache.get("c", lambda: _FakeExe(300))  # must evict b, never c
    assert cache.evictions == 1
    stats = cache.stats()
    assert stats["entries"] == 2
    misses0 = stats["misses"]
    cache.get("a", lambda: _FakeExe(300))  # still resident
    assert cache.stats()["misses"] == misses0
    cache.get("b", lambda: _FakeExe(300))  # evicted -> rebuilds
    assert cache.stats()["misses"] == misses0 + 1


def test_budget_refusals():
    cache = CompiledModelCache()
    with pytest.raises(ServeMemoryBudgetError, match="weights alone"):
        cache.set_budget(300, base_bytes=400)
    cache.set_budget(1000, base_bytes=400)
    with pytest.raises(ServeMemoryBudgetError, match="even alone"):
        cache.get("big", lambda: _FakeExe(700))
    assert cache.stats()["entries"] == 0  # the unfittable entry was popped


def test_engine_prewarm_refuses_impossible_budget(mesh8):
    model = _tiny_vit()
    params, ms = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, *IMAGE_SHAPE), jnp.float32))
    engine = InferenceEngine(
        model, params, ms, mesh8, model_name="vit_tight",
        image_shape=IMAGE_SHAPE, rules=resolve_rules("dp"), max_bucket=8,
        seq_grid=default_seq_grid(IMAGE_SHAPE, 4),
        # one byte of executable headroom beyond the weights: the first
        # compiled cell cannot fit beside them
        memory_budget_bytes=(
            sum(int(np.prod(p.shape)) * 4 for p in jax.tree.leaves(params))
            + 1),
    )
    with pytest.raises(ServeMemoryBudgetError):
        engine.prewarm()


def test_weights_over_budget_refused_at_construction(mesh8):
    model = _tiny_vit()
    params, ms = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, *IMAGE_SHAPE), jnp.float32))
    with pytest.raises(ServeMemoryBudgetError, match="weights alone"):
        InferenceEngine(
            model, params, ms, mesh8, model_name="vit_nofit",
            image_shape=IMAGE_SHAPE, rules=resolve_rules("dp"),
            max_bucket=8, memory_budget_bytes=16,
        )


# -- batcher: oversized-window split ------------------------------------------

def test_batcher_splits_oversized_window_across_executions(mesh8):
    bundle = load_for_serving("mlp_mnist", mesh8)
    engine = InferenceEngine(
        bundle.model, bundle.params, bundle.model_state, mesh8,
        model_name="mlp_split", image_shape=bundle.image_shape,
        rules=bundle.rules, max_bucket=16,
    )
    # max_batch 40 > max_bucket 16: the window must split, not raise
    server = InferenceServer(engine, ServeConfig(
        max_batch=40, max_wait_ms=25.0, queue_depth=64))
    images = np.random.default_rng(0).integers(
        0, 256, size=(40, 28, 28, 1), dtype=np.uint8)
    with server:
        futs = [server.submit(img) for img in images]
        results = [f.result(timeout=60.0) for f in futs]
    assert len(results) == 40
    assert server.metrics.completed == 40
    assert server.metrics.batch_size.snapshot()["max"] <= 16
    # a single DIRECT predict beyond the ceiling still raises
    with pytest.raises(ValueError, match="max_bucket"):
        engine.bucket_for(17)


def test_async_prewarm_warms_grid_in_background_and_joins(mesh8):
    import time

    model = _tiny_vit()
    params, ms = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, *IMAGE_SHAPE), jnp.float32))
    engine = InferenceEngine(
        model, params, ms, mesh8, model_name="vit_async",
        image_shape=IMAGE_SHAPE, rules=resolve_rules("dp"), max_bucket=8,
        seq_grid=default_seq_grid(IMAGE_SHAPE, 4),
    )
    server = InferenceServer(engine, ServeConfig(
        max_batch=8, max_wait_ms=1.0, queue_depth=32, prewarm_async=True))
    with server:
        # serving is live immediately; a request may pay its own compile
        fut = server.submit(_images(1, h=16)[0])
        assert fut.result(timeout=60.0).logits.shape == (10,)
        deadline = time.monotonic() + 60.0
        want = len(engine.buckets()) * (1 + len(engine.seq_grid.heights))
        while engine.cache.stats()["entries"] < want:
            assert time.monotonic() < deadline, "background prewarm stalled"
            time.sleep(0.05)
    # close() joined the ZooPrewarm thread (conftest's leak check would
    # fail this test otherwise); no refusal was recorded
    assert "prewarm_error" not in server.stats()


# -- hot swap on a sharded zoo replica ----------------------------------------

def test_roll_weights_rewarm_retouches_grid_without_recompiling(mesh_tp):
    from dist_mnist_tpu.serve import InProcessReplica, Router, RouterConfig

    model = _tiny_vit()
    params, ms = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, *IMAGE_SHAPE), jnp.float32))
    cache = CompiledModelCache()

    def make_server():
        engine = InferenceEngine(
            model, params, ms, mesh_tp, model_name="vit_roll",
            image_shape=IMAGE_SHAPE, rules=resolve_rules("tp"),
            max_bucket=4, cache=cache,
            seq_grid=default_seq_grid(IMAGE_SHAPE, 4),
        )
        return InferenceServer(engine, ServeConfig(
            max_batch=4, max_wait_ms=1.0, queue_depth=32)).start()

    def load_weights(step):
        return jax.tree.map(lambda p: p + 1.0, params), ms

    replica = InProcessReplica(0, make_server,
                               load_weights=load_weights).start()
    router = Router([replica], RouterConfig(health_interval_s=0.05)).start()
    try:
        misses_warm = cache.stats()["misses"]
        res = router.roll_weights(9)
        assert not res["failed"]
        eng = replica.server.engine
        assert eng.weights_version == 9
        # the post-swap rewarm walked the whole 2-D grid as memory hits
        assert cache.stats()["misses"] == misses_warm
        # short and native requests both serve on the NEW weights
        fut = router.submit(_images(1, h=8, seed=4)[0])
        assert fut.result(timeout=30.0).logits.shape == (10,)
    finally:
        router.close()
        replica.close()
