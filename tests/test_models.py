"""Model init/apply contracts for the whole zoo."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dist_mnist_tpu.models import get_model
from dist_mnist_tpu.utils import param_count

CASES = [
    ("mlp", (4, 28, 28, 1), {}),
    ("lenet5", (4, 28, 28, 1), {}),
    ("resnet20", (4, 32, 32, 3), {}),
    ("vit_tiny", (4, 32, 32, 3), {"depth": 2}),  # shallow for test speed
]


@pytest.mark.parametrize("name,shape,kwargs", CASES)
def test_init_apply_shapes(name, shape, kwargs, rng):
    model = get_model(name, **kwargs)
    x = jnp.zeros(shape, jnp.float32)
    params, state = model.init(rng, x)
    logits, new_state = model.apply(params, state, x, train=True, rng=rng)
    assert logits.shape == (shape[0], 10)
    assert logits.dtype == jnp.float32  # logits always f32 for the loss
    logits_eval, _ = model.apply(params, state, x, train=False)
    assert logits_eval.shape == (shape[0], 10)
    assert jax.tree.structure(new_state) == jax.tree.structure(state)


def test_mlp_reference_geometry(rng):
    """Exact §0.1 shapes: hid_w [784,100], sm_w [100,10]."""
    model = get_model("mlp", hidden_units=100)
    params, _ = model.init(rng, jnp.zeros((1, 28, 28, 1)))
    assert params["hid"]["w"].shape == (784, 100)
    assert params["hid"]["b"].shape == (100,)
    assert params["sm"]["w"].shape == (100, 10)
    assert param_count(params) == 784 * 100 + 100 + 100 * 10 + 10
    # truncated-normal stddev 1/sqrt(fan_in): bounded by 2*stddev
    w = np.asarray(params["hid"]["w"])
    assert np.abs(w).max() <= 2.0 / np.sqrt(784) + 1e-6
    assert 0.5 / np.sqrt(784) < w.std() < 1.5 / np.sqrt(784)


def test_lenet_param_count(rng):
    """conv5x5x32 + conv5x5x64 + fc512 + fc10 (the classic tower)."""
    model = get_model("lenet5")
    params, _ = model.init(rng, jnp.zeros((1, 28, 28, 1)))
    expected = (
        (5 * 5 * 1 * 32 + 32)
        + (5 * 5 * 32 * 64 + 64)
        + (7 * 7 * 64 * 512 + 512)
        + (512 * 10 + 10)
    )
    assert param_count(params) == expected


@pytest.mark.slow
def test_resnet_batchnorm_state_updates(rng):
    model = get_model("resnet20")
    x = jnp.ones((8, 32, 32, 3))
    params, state = model.init(rng, x)
    _, new_state = model.apply(params, state, x, train=True)
    # running stats must move in train mode...
    diff = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()), state, new_state)
    assert max(jax.tree.leaves(diff)) > 0
    # ...and stay frozen in eval mode
    _, eval_state = model.apply(params, state, x, train=False)
    same = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()), state, eval_state)
    assert max(jax.tree.leaves(same)) == 0


@pytest.mark.slow
def test_vit_token_count(rng):
    model = get_model("vit_tiny", depth=1)
    params, _ = model.init(rng, jnp.zeros((1, 32, 32, 3)))
    assert params["pos"].shape == (1, 65, 192)  # 64 patches + CLS


@pytest.mark.slow
def test_dropout_only_in_train(rng):
    model = get_model("lenet5")
    x = jnp.array(np.random.default_rng(0).normal(size=(4, 28, 28, 1)),
                  jnp.float32)
    params, state = model.init(rng, x)
    a, _ = model.apply(params, state, x, train=False)
    b, _ = model.apply(params, state, x, train=False)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c, _ = model.apply(params, state, x, train=True, rng=jax.random.PRNGKey(1))
    d, _ = model.apply(params, state, x, train=True, rng=jax.random.PRNGKey(2))
    assert (np.asarray(c) != np.asarray(d)).any()


def test_vit_scan_blocks_matches_unrolled(rng):
    """scan-over-layers (one compiled block) must be numerically identical
    to the unrolled python loop — same init, same forward, same grads."""
    kwargs = dict(depth=3, dim=64, heads=4, patch=8,
                  compute_dtype=jnp.float32)
    loop_model = get_model("vit_tiny", **kwargs)
    scan_model = get_model("vit_tiny", scan_blocks=True, **kwargs)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 32, 32, 3)),
                    jnp.float32)
    lp, ls = loop_model.init(rng, x)
    sp, ss = scan_model.init(rng, x)
    # identical per-block init, just stacked
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs),
                           *[lp[f"block{i}"] for i in range(3)])
    assert all(
        np.allclose(a, b) for a, b in
        zip(jax.tree.leaves(stacked), jax.tree.leaves(sp["blocks"]))
    )

    def loss_l(p):
        return jnp.sum(loop_model.apply(p, ls, x, train=False)[0] ** 2)

    def loss_s(p):
        return jnp.sum(scan_model.apply(p, ss, x, train=False)[0] ** 2)

    vl, gl = jax.value_and_grad(loss_l)(lp)
    vs, gs = jax.value_and_grad(loss_s)(sp)
    np.testing.assert_allclose(float(vl), float(vs), rtol=1e-5)
    g_stacked = jax.tree.map(lambda *xs: jnp.stack(xs),
                             *[gl[f"block{i}"] for i in range(3)])
    for a, b in zip(jax.tree.leaves(g_stacked), jax.tree.leaves(gs["blocks"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gl["head"]["w"]),
                               np.asarray(gs["head"]["w"]),
                               rtol=2e-4, atol=1e-5)


def test_vit_block_layout_converter(rng):
    """convert_block_layout round-trips and moves a pre-scan_blocks
    checkpoint tree into the stacked layout (and back)."""
    from dist_mnist_tpu.models.vit import convert_block_layout

    kwargs = dict(depth=3, dim=32, heads=4, patch=8, compute_dtype=jnp.float32)
    loop_model = get_model("vit_tiny", **kwargs)
    scan_model = get_model("vit_tiny", scan_blocks=True, **kwargs)
    x = jnp.zeros((1, 32, 32, 3), jnp.float32)
    lp, ls = loop_model.init(rng, x)
    sp, _ = scan_model.init(rng, x)

    converted = convert_block_layout(lp)  # unrolled -> stacked
    assert jax.tree.structure(converted) == jax.tree.structure(sp)
    for a, b in zip(jax.tree.leaves(converted), jax.tree.leaves(sp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    # and the converted tree actually runs in the scan model
    out_scan, _ = scan_model.apply(converted, ls, x, train=False)
    out_loop, _ = loop_model.apply(lp, ls, x, train=False)
    np.testing.assert_allclose(np.asarray(out_scan), np.asarray(out_loop),
                               rtol=1e-5, atol=1e-6)
    # round-trip back to unrolled
    back = convert_block_layout(converted)
    assert jax.tree.structure(back) == jax.tree.structure(lp)
