"""Hook lifecycle + TrainLoop semantics (MonitoredTrainingSession parity)."""

import itertools

import jax.numpy as jnp
import pytest

from dist_mnist_tpu.hooks import (
    EvalHook,
    LoggingHook,
    NaNGuardHook,
    NanLossError,
    StepCounterHook,
    StopAtStepHook,
)
from dist_mnist_tpu.hooks.base import EverySteps, Hook
from dist_mnist_tpu.train.loop import PreemptionError, StopSignal, TrainLoop
from dist_mnist_tpu.train.state import TrainState


def _state(step=0):
    return TrainState(
        step=jnp.int32(step), params={}, model_state={}, opt_state={},
        rng=jnp.zeros((2,), jnp.uint32),
    )


def _fake_step(state, batch):
    return (
        TrainState(
            step=state.step + 1, params=state.params,
            model_state=state.model_state, opt_state=state.opt_state,
            rng=state.rng,
        ),
        {"loss": jnp.float32(batch)},
    )


def test_stop_at_step():
    loop = TrainLoop(_fake_step, _state(), itertools.repeat(1.0),
                     [StopAtStepHook(last_step=7)])
    final = loop.run()
    assert final.step_int == 7
    assert loop.stop.reason == "reached last step"


def test_stop_num_steps_from_restore():
    """num_steps counts from the restored step (≙ StopAtStepHook:441-447)."""
    loop = TrainLoop(_fake_step, _state(step=10), itertools.repeat(1.0),
                     [StopAtStepHook(num_steps=5)])
    assert loop.run().step_int == 15


def test_steps_per_call_chunked_loop():
    """steps_per_call=K (compiled scan chunks): hooks fire once per chunk
    at the post-chunk step; stop rounds up to the chunk boundary."""
    def chunk_step(state, batch):  # pretends to run 10 steps in one call
        return (
            TrainState(step=state.step + 10, params=state.params,
                       model_state=state.model_state,
                       opt_state=state.opt_state, rng=state.rng),
            {"loss": jnp.float32(1.0)},
        )

    seen = []

    class Rec(Hook):
        def after_step(self, step, state, outputs):
            seen.append(step)

    loop = TrainLoop(chunk_step, _state(), itertools.repeat(None),
                     [Rec(), StopAtStepHook(last_step=25)],
                     steps_per_call=10)
    final = loop.run()
    assert seen == [10, 20, 30]  # stop rounds up to the chunk boundary
    assert final.step_int == 30


def test_data_exhaustion_stops():
    loop = TrainLoop(_fake_step, _state(), iter([1.0, 1.0, 1.0]), [])
    assert loop.run().step_int == 3
    assert loop.stop.reason == "data exhausted"


def test_hook_order_and_lifecycle():
    calls = []

    class Recorder(Hook):
        def begin(self, loop):
            calls.append("begin")

        def before_step(self, step):
            calls.append(f"before{step}")

        def after_step(self, step, state, outputs):
            calls.append(f"after{step}")

        def end(self, state):
            calls.append("end")

    loop = TrainLoop(_fake_step, _state(), iter([1.0, 2.0]), [Recorder()])
    loop.run()
    assert calls == ["begin", "before0", "after1", "before1", "after2", "end"]


def test_nan_guard_raises():
    hook = NaNGuardHook(every_steps=1)
    loop = TrainLoop(_fake_step, _state(), itertools.repeat(float("nan")),
                     [hook, StopAtStepHook(last_step=10)])
    with pytest.raises(NanLossError):
        loop.run()


def test_nan_guard_stop_mode():
    hook = NaNGuardHook(every_steps=1, fail_on_nan=False)
    loop = TrainLoop(_fake_step, _state(), itertools.repeat(float("nan")),
                     [hook, StopAtStepHook(last_step=10)])
    final = loop.run()
    assert final.step_int == 1
    assert loop.stop.reason == "non-finite loss"


def test_logging_hook_single_sync_per_cadence(monkeypatch):
    """Every logged key rides ONE jax.device_get per cadence — per-key
    float() was one blocking host sync per metric, serializing dispatch."""
    import jax

    from dist_mnist_tpu.hooks import builtin

    def multi_metric_step(state, batch):
        state, _ = _fake_step(state, batch)
        return state, {"loss": jnp.float32(0.5), "accuracy": jnp.float32(0.9),
                       "grad_norm": jnp.float32(1.2)}

    loop = TrainLoop(multi_metric_step, _state(), itertools.repeat(1.0),
                     [LoggingHook(every_steps=2), StopAtStepHook(last_step=4)])

    # patch AFTER loop construction (builtin.jax IS the jax module, and
    # TrainLoop.__init__'s state.step_int would otherwise count as a sync)
    gets = []
    real_get = jax.device_get
    monkeypatch.setattr(builtin.jax, "device_get",
                        lambda tree: gets.append(1) or real_get(tree))
    loop.run()
    assert len(gets) == 2  # cadences at steps 2 and 4: one sync each


def test_step_counter_rate():
    hook = StepCounterHook(every_steps=5, batch_size=32)
    loop = TrainLoop(_fake_step, _state(), itertools.repeat(1.0),
                     [hook, StopAtStepHook(last_step=10)])
    loop.run()
    assert hook.last_rate is not None and hook.last_rate > 0


def test_eval_hook_cadence_and_end():
    evals = []
    hook = EvalHook(lambda s: evals.append(s.step_int) or
                    {"loss": 0.0, "accuracy": 1.0}, every_steps=4)
    loop = TrainLoop(_fake_step, _state(), itertools.repeat(1.0),
                     [hook, StopAtStepHook(last_step=10)])
    loop.run()
    assert evals == [4, 8, 10]  # cadence + final


def test_every_steps_requires_config():
    with pytest.raises(ValueError):
        EverySteps()


def test_every_steps_crossing_not_aliasing():
    """Chunk-strided step numbers (scan_chunk) must trigger whenever a
    cadence multiple is crossed — bare `step % every == 0` would alias to
    the LCM (e.g. every 1600 steps for chunk=64, every=100)."""
    t = EverySteps(every_steps=100)
    t.prime(0)
    fired = [s for s in range(64, 1700, 64) if t.should_trigger(s)]
    # one firing per crossed multiple of 100 (100..1600 = 16 of them)
    assert len(fired) == 16
    assert fired[:3] == [128, 256, 320]
    # per-step striding keeps the exact-multiple behavior
    t2 = EverySteps(every_steps=4)
    t2.prime(0)
    assert [s for s in range(1, 11) if t2.should_trigger(s)] == [4, 8]
    # the FIRST observation can itself be a crossing (chunk 150, every 100)
    t3 = EverySteps(every_steps=100)
    t3.prime(0)
    assert t3.should_trigger(150)
    # a primed timer at a restored step doesn't fire spuriously
    t4 = EverySteps(every_steps=100)
    t4.prime(5000)
    assert not t4.should_trigger(5001)
    assert t4.should_trigger(5100)


def test_stop_signal_exception_channel():
    sig = StopSignal()
    exc = RuntimeError("boom")
    sig.request_stop("bad", exc)
    assert sig.should_stop()
    with pytest.raises(RuntimeError, match="boom"):
        sig.raise_requested_exception()


class _FlakyStep:
    """Fails with a preemption error on chosen calls (§4 injection pattern)."""

    def __init__(self, fail_at: set[int]):
        self.calls = 0
        self.fail_at = fail_at

    def __call__(self, state, batch):
        self.calls += 1
        if self.calls in self.fail_at:
            raise PreemptionError("fake preemption")
        return _fake_step(state, batch)


class _MemoryCkpt:
    """In-memory checkpoint manager double."""

    def __init__(self):
        self.saved = None

    def save(self, state):
        self.saved = state

    def restore(self, target):
        return self.saved


def test_recoverable_loop_restores_and_continues():
    mgr = _MemoryCkpt()
    step = _FlakyStep(fail_at={4})
    state = _state()
    mgr.save(state)  # initial checkpoint at step 0

    loop = TrainLoop(step, state, itertools.repeat(1.0),
                     [StopAtStepHook(last_step=6)],
                     checkpoint_manager=mgr, max_recoveries=2)
    final = loop.run()
    assert final.step_int == 6  # recovered from step 0 and finished


def test_unrecoverable_without_manager():
    step = _FlakyStep(fail_at={2})
    loop = TrainLoop(step, _state(), itertools.repeat(1.0),
                     [StopAtStepHook(last_step=6)])
    with pytest.raises(PreemptionError):
        loop.run()


def test_non_preemption_errors_propagate():
    def bad_step(state, batch):
        raise ValueError("logic bug")

    loop = TrainLoop(bad_step, _state(), itertools.repeat(1.0),
                     [StopAtStepHook(last_step=6)],
                     checkpoint_manager=_MemoryCkpt(), max_recoveries=5)
    with pytest.raises(ValueError, match="logic bug"):
        loop.run()


def test_stop_hook_no_extra_step_after_restore():
    """Restored at/past last_step: exit immediately, don't train one more."""
    loop = TrainLoop(_fake_step, _state(step=2000), itertools.repeat(1.0),
                     [StopAtStepHook(last_step=2000)])
    assert loop.run().step_int == 2000
    assert loop.stop.reason == "already at last step"


def test_eval_hook_no_double_eval_when_final_on_cadence():
    evals = []
    hook = EvalHook(lambda s: evals.append(s.step_int) or
                    {"loss": 0.0, "accuracy": 1.0}, every_steps=4)
    loop = TrainLoop(_fake_step, _state(), itertools.repeat(1.0),
                     [hook, StopAtStepHook(last_step=8)])
    loop.run()
    assert evals == [4, 8]  # end() skipped: step 8 already evaluated


class _FakeMgr:
    """latest_step advances each poll — a trainer job making progress."""

    def __init__(self, steps):
        self._steps = iter(steps)
        self.polls = 0

    def latest_step(self):
        self.polls += 1
        return next(self._steps)


def test_global_step_waiter_blocks_until_step():
    from dist_mnist_tpu.hooks import GlobalStepWaiterHook

    mgr = _FakeMgr([None, 2, 4, 5, 99])
    hook = GlobalStepWaiterHook(5, checkpoint_manager=mgr, poll_secs=0.0)
    loop = TrainLoop(_fake_step, _state(), iter([1.0]), [hook])
    loop.run()
    assert mgr.polls == 4  # stopped polling the moment 5 was reached


def test_global_step_waiter_passes_if_restored_past():
    from dist_mnist_tpu.hooks import GlobalStepWaiterHook

    mgr = _FakeMgr([])
    hook = GlobalStepWaiterHook(5, checkpoint_manager=mgr, poll_secs=0.0)
    loop = TrainLoop(_fake_step, _state(step=9), iter([1.0]), [hook])
    loop.run()
    assert mgr.polls == 0


def test_global_step_waiter_timeout():
    from dist_mnist_tpu.hooks import GlobalStepWaiterHook

    mgr = _FakeMgr(itertools.repeat(1))
    hook = GlobalStepWaiterHook(5, checkpoint_manager=mgr, poll_secs=0.0,
                                timeout_secs=0.05)
    loop = TrainLoop(_fake_step, _state(), iter([1.0]), [hook])
    with pytest.raises(TimeoutError):
        loop.run()


def test_final_ops_hook():
    from dist_mnist_tpu.hooks import FinalOpsHook

    hook = FinalOpsHook(lambda state: state.step_int * 10)
    loop = TrainLoop(_fake_step, _state(), iter([1.0, 1.0]), [hook])
    loop.run()
    assert hook.final_result == 20


def test_global_step_waiter_reloads_bare_managers():
    """A manager without latest_step(refresh=) but with reload() (bare orbax)
    must be rescanned each poll — a cached step list would spin forever."""
    from dist_mnist_tpu.hooks import GlobalStepWaiterHook

    class _BareMgr:
        def __init__(self):
            self._on_disk = None
            self.reloads = 0

        def reload(self):
            self.reloads += 1
            if self.reloads >= 3:  # a foreign trainer reaches step 7
                self._on_disk = 7

        def latest_step(self):
            return self._on_disk

    mgr = _BareMgr()
    hook = GlobalStepWaiterHook(5, checkpoint_manager=mgr, poll_secs=0.0,
                                timeout_secs=5.0)
    loop = TrainLoop(_fake_step, _state(), iter([1.0]), [hook])
    loop.run()
    assert mgr.reloads == 3


class _RecWriter:
    def __init__(self):
        self.scalars = []
        self.hists = []

    def scalar(self, tag, value, step):
        self.scalars.append((step, tag, value))

    def histogram(self, tag, values, step):
        import numpy as np

        self.hists.append((step, tag, int(np.asarray(values).size)))

    def flush(self):
        pass


def test_summary_hook_histograms_array_outputs():
    """Array-valued step outputs (e.g. per-leaf grad_norms) become
    histograms; scalars stay scalars."""
    from dist_mnist_tpu.hooks import SummaryHook

    def step_with_vec(state, batch):
        new, out = _fake_step(state, batch)
        out["grad_norms"] = jnp.arange(5.0)
        return new, out

    w = _RecWriter()
    loop = TrainLoop(step_with_vec, _state(), itertools.repeat(1.0),
                     [SummaryHook(w, every_steps=2),
                      StopAtStepHook(last_step=4)])
    loop.run()
    assert [(s, t) for s, t, _ in w.scalars] == [(2, "loss"), (4, "loss")]
    assert w.hists == [(2, "grad_norms", 5), (4, "grad_norms", 5)]


def test_summary_hook_degrades_for_scalar_only_writer():
    """A pre-histogram custom writer (scalar/flush only) must not crash:
    array outputs degrade to summary-stat scalars."""
    from dist_mnist_tpu.hooks import SummaryHook

    class OldWriter:
        def __init__(self):
            self.scalars = []

        def scalar(self, tag, value, step):
            self.scalars.append((step, tag, value))

        def flush(self):
            pass

    def step_with_vec(state, batch):
        new, out = _fake_step(state, batch)
        out["grad_norms"] = jnp.arange(4.0)
        return new, out

    w = OldWriter()
    loop = TrainLoop(step_with_vec, _state(), itertools.repeat(1.0),
                     [SummaryHook(w, every_steps=2),
                      StopAtStepHook(last_step=2)])
    loop.run()
    tags = {t for _, t, _ in w.scalars}
    assert "grad_norms/mean" in tags and "grad_norms/max" in tags
    assert "loss" in tags


def test_summary_hook_param_histograms_cadence():
    from dist_mnist_tpu.hooks import SummaryHook

    state = TrainState(
        step=jnp.int32(0),
        params={"hid": {"w": jnp.ones((3, 2)), "b": jnp.zeros((2,))}},
        model_state={}, opt_state={}, rng=jnp.zeros((2,), jnp.uint32),
    )

    def step_keep_params(s, batch):
        new, out = _fake_step(s, batch)
        return TrainState(step=new.step, params=s.params, model_state={},
                          opt_state={}, rng=s.rng), out

    w = _RecWriter()
    hook = SummaryHook(w, every_steps=100, param_histograms_every=3)
    loop = TrainLoop(step_keep_params, state, itertools.repeat(1.0),
                     [hook, StopAtStepHook(last_step=6)])
    loop.run()
    assert (3, "params/hid/w", 6) in w.hists
    assert (3, "params/hid/b", 2) in w.hists
    assert (6, "params/hid/w", 6) in w.hists


def test_memory_profile_hook(tmp_path):
    from dist_mnist_tpu.hooks import MemoryProfileHook

    hook = MemoryProfileHook(str(tmp_path), after_steps=2)
    loop = TrainLoop(_fake_step, _state(), iter([1.0] * 3), [hook])
    loop.run()
    prof = tmp_path / "memory-step2.prof"
    assert prof.exists() and prof.stat().st_size > 0


def test_memory_profile_hook_resumed_and_short_runs(tmp_path):
    """Anchors to the RESTORED step (fires) and still captures when the run
    is shorter than after_steps (memory-final.prof at end)."""
    from dist_mnist_tpu.hooks import MemoryProfileHook

    hook = MemoryProfileHook(str(tmp_path), after_steps=2)
    loop = TrainLoop(_fake_step, _state(step=100), iter([1.0] * 3), [hook])
    loop.run()
    assert (tmp_path / "memory-step102.prof").exists()

    short = tmp_path / "short"
    short.mkdir()
    hook = MemoryProfileHook(str(short), after_steps=50)
    loop = TrainLoop(_fake_step, _state(), iter([1.0] * 3), [hook])
    loop.run()
    assert (short / "memory-final.prof").exists()
